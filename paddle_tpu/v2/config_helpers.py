"""trainer_config_helpers — the legacy v2 layer-config DSL.

Reference: /root/reference/python/paddle/trainer_config_helpers/layers.py
(7,531 LoC layer DSL), networks.py (img_conv_group, simple_lstm),
config_parser.py (the Python->ModelConfig compiler, 4,399 LoC — shape
inference incl. square-image sqrt rule and caffe/ceil output-size modes),
python/paddle/trainer_config_helpers/{activations.py, poolings.py,
attrs.py, optimizers.py}.

TPU-native redesign: the reference compiles this DSL to a ModelConfig proto
interpreted by the C++ GradientMachine; here every ``*_layer`` call lowers
EAGERLY onto the fluid Program builder (paddle_tpu.fluid.layers), so a v2
config script *is* a fluid topology — one IR, one executor, one compiled
XLA step for both generations. Sequence layers carry LoD metadata; image
layers carry (C, H, W) metadata with the reference's shape rules
(config_parser.py cnn_output_size: caffe mode for conv, ceil mode for
pooling; height = width = sqrt(size / channels) when unspecified).

Data layers are LAZY: the reference's data_layer declares only a size —
whether it is a float image, an integer label, or a token sequence is
decided by the data provider. Here the first consumer materializes the
variable with the right dtype/lod (conv -> float image, cost label ->
int64, embedding -> int64 sequence), preserving the reference's config
scripts verbatim.

Run a reference config with ``parse_config(source)`` (the ``paddle train
--config=`` analog) and feed the result to ``paddle_tpu.v2.SGD``.
"""

from __future__ import annotations

import math

import numpy as np

__all__ = [
    # plumbing
    "settings", "get_config_arg", "set_config_args", "outputs",
    "define_py_data_sources2", "get_topology", "parse_config", "Topology",
    # activations
    "ReluActivation", "LinearActivation", "SoftmaxActivation",
    "SigmoidActivation", "TanhActivation", "IdentityActivation",
    # poolings
    "MaxPooling", "AvgPooling", "SumPooling",
    # attrs
    "ExtraAttr", "ExtraLayerAttribute", "ParamAttr", "ParameterAttribute",
    # optimizers / regularizers
    "MomentumOptimizer", "AdamOptimizer", "AdamaxOptimizer",
    "RMSPropOptimizer", "AdaGradOptimizer", "DecayedAdaGradOptimizer",
    "AdaDeltaOptimizer", "L2Regularization", "L1Regularization",
    # layers
    "data_layer", "fc_layer", "img_conv_layer", "img_pool_layer",
    "img_cmrnorm_layer", "batch_norm_layer", "addto_layer", "concat_layer",
    "dropout_layer", "embedding_layer", "lstmemory", "simple_lstm",
    "grumemory", "simple_gru", "last_seq", "first_seq", "pooling_layer",
    "cross_entropy", "classification_cost", "regression_cost",
    "img_conv_group", "conv_projection", "LayerOutput",
]


# ---------------------------------------------------------------------------
# global config state (the reference keeps this in config_parser globals)
# ---------------------------------------------------------------------------

_SETTINGS: dict = {}
_CONFIG_ARGS: dict = {}
_OUTPUTS: list = []
_DATA_LAYERS: list = []
_DATA_SOURCES: dict = {}
_SEQUENCE_HINTS: set = set()


def _reset_config():
    _SETTINGS.clear()
    _CONFIG_ARGS.clear()
    del _OUTPUTS[:]
    del _DATA_LAYERS[:]
    _DATA_SOURCES.clear()
    _SEQUENCE_HINTS.clear()


def parse_config_args(s):
    """'k1=v1,k2=v2' -> dict, whitespace-tolerant (the --config_args CLI
    format shared by the trainer CLI and the utils tools)."""
    out = {}
    for kv in (s or "").split(","):
        if "=" in kv:
            k, _, v = kv.partition("=")
            out[k.strip()] = v.strip()
    return out


def set_config_args(**kwargs):
    """Provide the values get_config_arg reads (the reference passes them on
    the paddle_trainer command line: --config_args=batch_size=64,...)."""
    _CONFIG_ARGS.update(kwargs)


def get_config_arg(name, type_, default=None):
    v = _CONFIG_ARGS.get(name, default)
    if v is None:
        return None
    if type_ is bool and isinstance(v, str):
        return v.lower() in ("1", "true", "yes")
    return type_(v)


def settings(batch_size=None, learning_rate=1e-3, learning_method=None,
             regularization=None, gradient_clipping_threshold=None, **kw):
    _SETTINGS.update(dict(
        batch_size=batch_size, learning_rate=learning_rate,
        learning_method=learning_method, regularization=regularization,
        gradient_clipping_threshold=gradient_clipping_threshold, **kw))


def define_py_data_sources2(train_list, test_list, module=None, obj=None,
                            args=None):
    """Recorded for introspection only: the v2 trainer contract feeds
    readers directly (reference PyDataProvider2 pulled batches through an
    embedded interpreter; here the reader decorators own that job)."""
    _DATA_SOURCES.update(dict(train_list=train_list, test_list=test_list,
                              module=module, obj=obj, args=args or {}))


def outputs(*layers):
    del _OUTPUTS[:]
    _OUTPUTS.extend(layers)


# ---------------------------------------------------------------------------
# activations / poolings / attrs / optimizers
# ---------------------------------------------------------------------------

class _Activation:
    act = None

    def __repr__(self):
        return f"{type(self).__name__}()"


class ReluActivation(_Activation):
    act = "relu"


class LinearActivation(_Activation):
    act = None


IdentityActivation = LinearActivation


class SoftmaxActivation(_Activation):
    act = "softmax"


class SigmoidActivation(_Activation):
    act = "sigmoid"


class TanhActivation(_Activation):
    act = "tanh"


def _act_str(act):
    if act is None:
        return None
    if isinstance(act, str):
        return act
    return act.act


class _Pooling:
    pool_type = "max"


class MaxPooling(_Pooling):
    pool_type = "max"

    def __init__(self, output_max_index=False):
        # output_max_index is accepted for config parity (reference
        # poolings.py); index emission is served by max_pool*_with_index
        self.output_max_index = output_max_index


class AvgPooling(_Pooling):
    pool_type = "avg"


class SumPooling(_Pooling):
    pool_type = "sum"


# cudnn pooling spellings (reference poolings.py CudnnMaxPooling /
# CudnnAvgPooling — kernel-choice hints; one XLA lowering here)
class CudnnMaxPooling(MaxPooling):
    pass


class CudnnAvgPooling(AvgPooling):
    pass


class ExtraLayerAttribute:
    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None):
        self.drop_rate = drop_rate
        self.error_clipping_threshold = error_clipping_threshold
        self.device = device


ExtraAttr = ExtraLayerAttribute


class ParameterAttribute:
    """Maps the commonly used subset onto fluid.ParamAttr (reference
    attrs.py ParameterAttribute has ~15 knobs tied to the legacy updater)."""

    def __init__(self, name=None, initial_std=None, initial_mean=None,
                 learning_rate=None, l1_rate=None, l2_rate=None,
                 is_static=False, **kw):
        self.name = name
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.learning_rate = learning_rate
        self.l2_rate = l2_rate
        self.is_static = is_static

    def to_fluid(self):
        from ..fluid.param_attr import ParamAttr as FluidParamAttr
        from ..fluid.initializer import Normal
        init = None
        if self.initial_std is not None or self.initial_mean is not None:
            init = Normal(loc=self.initial_mean or 0.0,
                          scale=self.initial_std
                          if self.initial_std is not None else 0.01)
        return FluidParamAttr(name=self.name, initializer=init,
                              learning_rate=self.learning_rate
                              if self.learning_rate is not None else 1.0,
                              trainable=not self.is_static)


ParamAttr = ParameterAttribute


def _fluid_param_attr(attr):
    if attr is None or attr is True:
        return None
    if isinstance(attr, ParameterAttribute):
        return attr.to_fluid()
    return attr


class _OptimizerSpec:
    fluid_cls = None
    kwargs: dict = {}

    def create(self, learning_rate, regularization=None):
        import paddle_tpu.fluid as fluid
        cls = getattr(fluid.optimizer, self.fluid_cls)
        return cls(learning_rate=learning_rate,
                   regularization=regularization, **self.kwargs)


class MomentumOptimizer(_OptimizerSpec):
    fluid_cls = "Momentum"

    def __init__(self, momentum=0.9, sparse=False):
        self.kwargs = {"momentum": momentum}


class AdamOptimizer(_OptimizerSpec):
    fluid_cls = "Adam"

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.kwargs = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon}


class AdamaxOptimizer(_OptimizerSpec):
    fluid_cls = "Adamax"

    def __init__(self, beta1=0.9, beta2=0.999):
        self.kwargs = {"beta1": beta1, "beta2": beta2}


class RMSPropOptimizer(_OptimizerSpec):
    fluid_cls = "RMSProp"

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.kwargs = {"rho": rho, "epsilon": epsilon}


class AdaGradOptimizer(_OptimizerSpec):
    fluid_cls = "Adagrad"

    def __init__(self, epsilon=1e-6):
        self.kwargs = {"epsilon": epsilon}


class DecayedAdaGradOptimizer(_OptimizerSpec):
    fluid_cls = "DecayedAdagrad"

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.kwargs = {"decay": rho, "epsilon": epsilon}


class AdaDeltaOptimizer(_OptimizerSpec):
    fluid_cls = "Adadelta"

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.kwargs = {"rho": rho, "epsilon": epsilon}


class L2Regularization:
    def __init__(self, rate):
        self.rate = rate

    def to_fluid(self):
        from ..fluid.regularizer import L2Decay
        return L2Decay(self.rate)


class L1Regularization:
    def __init__(self, rate):
        self.rate = rate

    def to_fluid(self):
        from ..fluid.regularizer import L1Decay
        return L1Decay(self.rate)


# ---------------------------------------------------------------------------
# LayerOutput
# ---------------------------------------------------------------------------

class LayerOutput:
    """A DSL node: the lowered fluid Variable plus v2 metadata. Data layers
    defer materialization to their first consumer (see module docstring)."""

    def __init__(self, var=None, size=None, hwc=None, is_seq=False,
                 name=None, data_size=None):
        self._var = var
        self.size = size
        self.hwc = hwc            # (channels, height, width) when image-like
        self.is_seq = is_seq
        self.name = name
        self._data_size = data_size   # pending data layer: declared size

    # ---- lazy data-layer materialization ----
    @property
    def is_pending(self):
        return self._var is None

    def materialize(self, kind="dense"):
        """kind: dense [-1, size] float | label [-1, 1] int64 |
        seq_ids [-1, 1] int64 lod 1 | seq_dense [-1, size] float lod 1.
        A sequence hint (parse_config(sequence_inputs=...)) upgrades the
        dense/label guesses — the reference learns sequence-ness from the
        data provider at runtime, which an eager lowering cannot see."""
        if self._var is not None:
            return self._var
        if self.name in _SEQUENCE_HINTS:
            kind = {"dense": "seq_dense", "label": "seq_ids"}.get(kind, kind)
        import paddle_tpu.fluid as fluid
        if kind == "label":
            self._var = fluid.layers.data(self.name, shape=[1],
                                          dtype="int64")
        elif kind == "seq_ids":
            self._var = fluid.layers.data(self.name, shape=[1],
                                          dtype="int64", lod_level=1)
            self.is_seq = True
        elif kind == "seq_dense":
            self._var = fluid.layers.data(self.name, shape=[self._data_size],
                                          lod_level=1)
            self.is_seq = True
        else:
            self._var = fluid.layers.data(self.name,
                                          shape=[self._data_size])
        self.size = self._data_size
        return self._var

    @property
    def var(self):
        return self.materialize()

    def __repr__(self):
        return (f"LayerOutput(name={self.name!r}, size={self.size}, "
                f"hwc={self.hwc}, seq={self.is_seq}, "
                f"pending={self.is_pending})")


def _unwrap(v, kind="dense"):
    if isinstance(v, LayerOutput):
        return v.materialize(kind) if v.is_pending else v.var
    return v


def _img_meta(input, num_channels=None):
    """(C, H, W) of a layer input, inferring square images from flat sizes
    (config_parser.py: img_size = sqrt(size / channels) when not given)."""
    if isinstance(input, LayerOutput) and input.hwc is not None:
        return input.hwc
    size = (input.size or input._data_size) \
        if isinstance(input, LayerOutput) else None
    if num_channels is None:
        raise ValueError(
            "img layer needs num_channels when its input carries no image "
            "metadata (reference config_parser infers only from a prior "
            "image layer)")
    if size is None:
        raise ValueError("cannot infer image height/width: input size "
                         "unknown")
    pixels = size // num_channels
    # the reference's get_img_size rule (config_parser.py:1210-1215):
    # width = floor(sqrt(pixels)), height = pixels // width, ASSERTING
    # width * height == pixels — squares pass, 12 -> 4x3 passes, a typo'd
    # size like 783 still errors at config time
    w = int(math.isqrt(pixels))
    h = pixels // max(w, 1)
    if w <= 0 or w * h != pixels or pixels * num_channels != size:
        raise ValueError(
            f"input size {size} does not factor into H x W x "
            f"{num_channels} channels (reference get_img_size rule)")
    return (num_channels, h, w)


def _as_image_var(input, num_channels=None):
    """Fluid var reshaped to [-1, C, H, W] + its (C,H,W)."""
    import paddle_tpu.fluid as fluid
    c, h, w = _img_meta(input, num_channels)
    var = _unwrap(input)
    if var.shape is not None and len(var.shape) == 2:
        var = fluid.layers.reshape(var, [-1, c, h, w])
    return var, (c, h, w)


def _conv_out(sz, f, p, s, caffe_mode=True):
    """config_parser.py cnn_output_size: caffe mode floors, legacy pooling
    mode ceils."""
    if caffe_mode:
        return (sz - f + 2 * p) // s + 1
    return int(math.ceil((sz - f + 2 * p) / s)) + 1


def _apply_drop(out_var, layer_attr):
    import paddle_tpu.fluid as fluid
    if isinstance(layer_attr, ExtraLayerAttribute) and layer_attr.drop_rate:
        return fluid.layers.dropout(out_var, layer_attr.drop_rate)
    return out_var


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------

def data_layer(name, size, height=None, width=None, **kw):
    out = LayerOutput(name=name, data_size=size)
    if height and width:
        c = size // (height * width)
        out.hwc = (c, height, width)
    _DATA_LAYERS.append(out)
    return out


def fc_layer(input, size, act=None, param_attr=None, bias_attr=True,
             layer_attr=None, name=None):
    import paddle_tpu.fluid as fluid
    inputs = input if isinstance(input, (list, tuple)) else [input]
    vars_ = [_unwrap(i) for i in inputs]
    out = fluid.layers.fc(vars_ if len(vars_) > 1 else vars_[0], size,
                          act=_act_str(act),
                          param_attr=_fluid_param_attr(param_attr),
                          bias_attr=None if bias_attr is True else bias_attr,
                          name=name)
    out = _apply_drop(out, layer_attr)
    is_seq = any(isinstance(i, LayerOutput) and i.is_seq for i in inputs)
    return LayerOutput(out, size=size, name=name, is_seq=is_seq)


def img_conv_layer(input, filter_size, num_filters, name=None,
                   num_channels=None, stride=1, padding=0, groups=1,
                   act=None, bias_attr=True, param_attr=None,
                   layer_attr=None, **kw):
    import paddle_tpu.fluid as fluid
    var, (c, h, w) = _as_image_var(input, num_channels)
    out = fluid.layers.conv2d(
        var, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=padding, groups=groups, act=_act_str(act),
        bias_attr=None if bias_attr is True else bias_attr,
        param_attr=_fluid_param_attr(param_attr), name=name)
    oh = _conv_out(h, filter_size, padding, stride)
    ow = _conv_out(w, filter_size, padding, stride)
    out = _apply_drop(out, layer_attr)
    return LayerOutput(out, size=num_filters * oh * ow,
                       hwc=(num_filters, oh, ow), name=name)


def img_pool_layer(input, pool_size, name=None, num_channels=None, stride=1,
                   padding=0, pool_type=None, layer_attr=None,
                   pool_size_y=None, stride_y=None, padding_y=None, **kw):
    """_y variants give asymmetric windows (reference img_pool_layer:
    pool_size is the x/width extent, *_y the height)."""
    import paddle_tpu.fluid as fluid
    var, (c, h, w) = _as_image_var(input, num_channels)
    ptype = (pool_type or MaxPooling()).pool_type
    ph, pw = (pool_size_y or pool_size), pool_size
    sh, sw = (stride_y or stride), stride
    pdh, pdw = (padding if padding_y is None else padding_y), padding
    out = fluid.layers.pool2d(var, pool_size=[ph, pw], pool_type=ptype,
                              pool_stride=[sh, sw], pool_padding=[pdh, pdw],
                              ceil_mode=True)
    # legacy pooling uses the ceil output size (config_parser.py
    # cnn_output_size with caffe_mode=False)
    oh = _conv_out(h, ph, pdh, sh, caffe_mode=False)
    ow = _conv_out(w, pw, pdw, sw, caffe_mode=False)
    return LayerOutput(out, size=c * oh * ow, hwc=(c, oh, ow), name=name)


def img_cmrnorm_layer(input, size=5, scale=0.0001, power=0.75, name=None,
                      num_channels=None, **kw):
    """Cross-map response normalization (reference layers.py
    img_cmrnorm_layer -> config_parser divides scale by size before the
    kernel, gserver NormProjectionLayer)."""
    import paddle_tpu.fluid as fluid
    var, hwc = _as_image_var(input, num_channels)
    out = fluid.layers.lrn(var, n=size, k=1.0, alpha=scale / size,
                           beta=power)
    lo = LayerOutput(out, size=hwc[0] * hwc[1] * hwc[2], hwc=hwc, name=name)
    return lo


def batch_norm_layer(input, act=None, name=None, num_channels=None,
                     use_global_stats=None, moving_average_fraction=0.9,
                     bias_attr=True, param_attr=None, layer_attr=None, **kw):
    import paddle_tpu.fluid as fluid
    var, hwc = _as_image_var(input, num_channels)
    out = fluid.layers.batch_norm(
        var, act=_act_str(act), is_test=bool(use_global_stats),
        momentum=moving_average_fraction,
        param_attr=_fluid_param_attr(param_attr))
    return LayerOutput(out, size=hwc[0] * hwc[1] * hwc[2], hwc=hwc,
                       name=name)


def addto_layer(input, act=None, name=None, bias_attr=False, **kw):
    import paddle_tpu.fluid as fluid
    inputs = input if isinstance(input, (list, tuple)) else [input]
    acc = _unwrap(inputs[0])
    for other in inputs[1:]:
        acc = fluid.layers.elementwise_add(acc, _unwrap(other))
    if _act_str(act):
        acc = getattr(fluid.layers, _act_str(act))(acc)
    first = inputs[0]
    return LayerOutput(acc, size=getattr(first, "size", None),
                       hwc=getattr(first, "hwc", None), name=name,
                       is_seq=getattr(first, "is_seq", False))


def concat_layer(input, act=None, name=None, **kw):
    import paddle_tpu.fluid as fluid
    inputs = list(input)
    imgs = [i for i in inputs if isinstance(i, LayerOutput)
            and i.hwc is not None]
    if len(imgs) == len(inputs):
        vars_ = [_as_image_var(i)[0] for i in inputs]
        out = fluid.layers.concat(vars_, axis=1)   # channel concat
        c = sum(i.hwc[0] for i in inputs)
        h, w = inputs[0].hwc[1], inputs[0].hwc[2]
        if _act_str(act):
            out = getattr(fluid.layers, _act_str(act))(out)
        return LayerOutput(out, size=c * h * w, hwc=(c, h, w), name=name)
    vars_ = [_unwrap(i) for i in inputs]
    out = fluid.layers.concat(vars_, axis=1)
    if _act_str(act):
        out = getattr(fluid.layers, _act_str(act))(out)
    size = sum(i.size for i in inputs if isinstance(i, LayerOutput))
    return LayerOutput(out, size=size or None, name=name,
                       is_seq=any(getattr(i, "is_seq", False)
                                  for i in inputs))


def dropout_layer(input, dropout_rate, name=None):
    import paddle_tpu.fluid as fluid
    out = fluid.layers.dropout(_unwrap(input), dropout_rate)
    return LayerOutput(out, size=getattr(input, "size", None),
                       hwc=getattr(input, "hwc", None), name=name,
                       is_seq=getattr(input, "is_seq", False))


def embedding_layer(input, size, param_attr=None, name=None, **kw):
    import paddle_tpu.fluid as fluid
    var = _unwrap(input, kind="seq_ids")
    vocab = input.size if isinstance(input, LayerOutput) and input.size \
        else input._data_size
    out = fluid.layers.embedding(var, size=(vocab, size),
                                 param_attr=_fluid_param_attr(param_attr))
    return LayerOutput(out, size=size, is_seq=True, name=name)


def lstmemory(input, size=None, reverse=False, act=None,
              gate_act=None, state_act=None, name=None, param_attr=None,
              bias_attr=True, **kw):
    """input must be width 4*size (the reference requires the projection done
    by a preceding mixed/fc layer, layers.py lstmemory docs)."""
    import paddle_tpu.fluid as fluid
    var = _unwrap(input)
    in_size = input.size if isinstance(input, LayerOutput) else None
    size = size or (in_size // 4 if in_size else None)
    hidden, _ = fluid.layers.dynamic_lstm(
        var, size=size * 4, is_reverse=reverse,
        gate_activation=_act_str(gate_act) or "sigmoid",
        cell_activation=_act_str(state_act) or "tanh",
        candidate_activation=_act_str(act) or "tanh",
        param_attr=_fluid_param_attr(param_attr))
    return LayerOutput(hidden, size=size, is_seq=True, name=name)


def simple_lstm(input, size, reverse=False, mat_param_attr=None,
                bias_param_attr=True, inner_param_attr=None, act=None,
                gate_act=None, state_act=None, name=None, **kw):
    """networks.py simple_lstm: mixed(4*size, linear) + lstmemory."""
    proj = fc_layer(input, size * 4, act=LinearActivation(),
                    param_attr=mat_param_attr, bias_attr=bias_param_attr)
    return lstmemory(proj, size=size, reverse=reverse, act=act,
                     gate_act=gate_act, state_act=state_act,
                     param_attr=inner_param_attr, name=name)


def grumemory(input, size=None, reverse=False, act=None, gate_act=None,
              name=None, param_attr=None, **kw):
    import paddle_tpu.fluid as fluid
    var = _unwrap(input)
    in_size = input.size if isinstance(input, LayerOutput) else None
    size = size or (in_size // 3 if in_size else None)
    hidden = fluid.layers.dynamic_gru(
        var, size=size, is_reverse=reverse,
        candidate_activation=_act_str(act) or "tanh",
        gate_activation=_act_str(gate_act) or "sigmoid",
        param_attr=_fluid_param_attr(param_attr))
    return LayerOutput(hidden, size=size, is_seq=True, name=name)


def simple_gru(input, size, reverse=False, act=None, gate_act=None,
               name=None, **kw):
    proj = fc_layer(input, size * 3, act=LinearActivation())
    return grumemory(proj, size=size, reverse=reverse, act=act,
                     gate_act=gate_act, name=name)


def _seq_select(input, which, agg_level=None, stride=-1, name=None):
    """first_seq/last_seq with the reference's agg_level/stride axes
    (layers.py first_seq:1395/last_seq:1353: stride>0 emits one result per
    stride-window — a sequence; TO_SEQUENCE pools inner sequences of a
    nested input)."""
    from ..fluid.layer_helper import LayerHelper
    var = _unwrap(input, kind="seq_dense")
    helper = LayerHelper(f"{which.lower()}_seq", name=name)
    attrs = {"pooltype": which}
    is_seq_out = False
    if stride and stride > 0:
        attrs["stride"] = int(stride)
        is_seq_out = True
    if agg_level == AggregateLevel.TO_SEQUENCE:
        attrs["agg_level"] = "seq"
        is_seq_out = True
    out = helper.create_tmp_variable(var.dtype,
                                     lod_level=1 if is_seq_out else 0)
    helper.append_op("sequence_pool", inputs={"X": [var.name]},
                     outputs={"Out": [out.name]}, attrs=attrs)
    return LayerOutput(out, size=getattr(input, "size", None), name=name,
                       is_seq=is_seq_out)


def last_seq(input, agg_level=None, stride=-1, name=None, **kw):
    return _seq_select(input, "LAST", agg_level, stride, name)


def first_seq(input, agg_level=None, stride=-1, name=None, **kw):
    return _seq_select(input, "FIRST", agg_level, stride, name)


def pooling_layer(input, pooling_type=None, agg_level=None, stride=-1,
                  name=None, **kw):
    ptype = {"max": "MAX", "avg": "AVERAGE",
             "sum": "SUM"}[(pooling_type or MaxPooling()).pool_type]
    return _seq_select(input, ptype, agg_level, stride, name)


def cross_entropy(input, label, name=None, coeff=1.0, weight=None, **kw):
    """Cost over an already-softmaxed input (the reference image configs
    apply SoftmaxActivation on the last fc, then cross_entropy); ``weight``
    scales each sample's cost (the reference's weight data layer)."""
    import paddle_tpu.fluid as fluid
    lab = _unwrap(label, kind="label")
    ce = fluid.layers.cross_entropy(_unwrap(input), lab)
    if weight is not None:
        ce = fluid.layers.elementwise_mul(ce, _unwrap(weight))
    cost = fluid.layers.mean(ce)
    if coeff != 1.0:
        cost = fluid.layers.scale(cost, scale=float(coeff))
    return LayerOutput(cost, size=1, name=name)


def classification_cost(input, label, name=None, weight=None, **kw):
    return cross_entropy(input, label, name=name, weight=weight)


def regression_cost(input, label, name=None, **kw):
    import paddle_tpu.fluid as fluid
    lab = _unwrap(label)
    cost = fluid.layers.mean(fluid.layers.square_error_cost(_unwrap(input),
                                                            lab))
    return LayerOutput(cost, size=1, name=name)


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, param_attr=None, name=None, **kw):
    """Reference conv_projection (layers.py) is a projection for
    concat/mixed layers; under eager fluid lowering a projection IS a conv
    output, so this is img_conv_layer without activation."""
    return img_conv_layer(input, filter_size=filter_size,
                          num_filters=num_filters,
                          num_channels=num_channels, stride=stride,
                          padding=padding, param_attr=param_attr,
                          act=LinearActivation(), name=name)


def img_conv_group(input, conv_num_filter, num_channels=None,
                   pool_size=None, pool_stride=1, pool_type=None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_batchnorm_drop_rate=None, conv_with_batchnorm=False,
                   pool_padding=0, **kw):
    """networks.py img_conv_group: conv (+optional BN) stack then one pool."""
    tmp = input
    n = len(conv_num_filter)

    def per(v, i):
        return v[i] if isinstance(v, (list, tuple)) else v

    for i, nf in enumerate(conv_num_filter):
        tmp = img_conv_layer(
            tmp, filter_size=per(conv_filter_size, i), num_filters=nf,
            num_channels=num_channels if i == 0 else None,
            padding=per(conv_padding, i),
            act=None if per(conv_with_batchnorm, i) else conv_act)
        if per(conv_with_batchnorm, i):
            tmp = batch_norm_layer(tmp, act=conv_act)
            dr = per(conv_batchnorm_drop_rate, i) \
                if conv_batchnorm_drop_rate else None
            if dr:
                tmp = dropout_layer(tmp, dr)
    return img_pool_layer(tmp, pool_size=pool_size, stride=pool_stride,
                          padding=pool_padding, pool_type=pool_type)


# ---------------------------------------------------------------------------
# topology extraction
# ---------------------------------------------------------------------------

class Topology:
    """What a parsed config yields: the cost var (fluid), data layers in
    declaration order, and an optimizer built from settings() — everything
    paddle_tpu.v2.SGD needs."""

    def __init__(self, cost, outputs, data_layers, settings_dict,
                 data_sources):
        self.cost = cost
        self.outputs = outputs
        self.data_layers = list(data_layers)
        self.settings = dict(settings_dict)
        self.data_sources = dict(data_sources)

    @property
    def feed_order(self):
        return [d.name for d in self.data_layers if not d.is_pending]

    def create_optimizer(self):
        import paddle_tpu.fluid as fluid
        lr = self.settings.get("learning_rate", 1e-3)
        method = self.settings.get("learning_method")
        reg = self.settings.get("regularization")
        reg = reg.to_fluid() if reg is not None else None
        if method is None:
            return fluid.optimizer.SGD(learning_rate=lr, regularization=reg)
        return method.create(lr, regularization=reg)


def get_topology():
    if not _OUTPUTS:
        raise RuntimeError("config declared no outputs(...)")
    cost_node = _OUTPUTS[-1]
    cost = cost_node.var if isinstance(cost_node, LayerOutput) else cost_node
    return Topology(cost, list(_OUTPUTS), _DATA_LAYERS, _SETTINGS,
                    _DATA_SOURCES)


def parse_config(source, config_args=None, main_program=None,
                 startup_program=None, sequence_inputs=()):
    """Run a v2 config script (source text or file path) against fresh (or
    given) fluid programs — the ``paddle train --config=X.py
    --config_args=...`` entry point. Returns (topology, main, startup).

    ``sequence_inputs``: data-layer names whose feeds are token/feature
    SEQUENCES (the information the reference's data provider supplies at
    runtime)."""
    import paddle_tpu.fluid as fluid
    import os

    _reset_config()
    _SEQUENCE_HINTS.update(sequence_inputs)
    if config_args:
        set_config_args(**config_args)
    if os.path.exists(source):
        with open(source) as f:
            source = f.read()
    # py2-era compatibility shim so reference configs run unedited: the
    # benchmark configs are python2 (xrange) and import the reference
    # package name
    source = source.replace("paddle.trainer_config_helpers",
                            "paddle_tpu.trainer_config_helpers")
    source = source.replace("xrange", "range")

    main = main_program or fluid.Program()
    startup = startup_program or fluid.Program()
    glb = {"__name__": "__paddle_tpu_config__"}
    exec("from paddle_tpu.trainer_config_helpers import *", glb)
    with fluid.program_guard(main, startup):
        exec(compile(source, "<v2-config>", "exec"), glb)
        topo = get_topology()
    return topo, main, startup


# ---------------------------------------------------------------------------
# round-4 DSL breadth: the layers that map 1:1 onto registered ops
# (reference trainer_config_helpers/layers.py; validated by running the
# reference's own tests/configs through parse_config)
# ---------------------------------------------------------------------------

class ExpActivation(_Activation):
    act = "exp"


class AbsActivation(_Activation):
    act = "abs"


class SquareActivation(_Activation):
    act = "square"


class BReluActivation(_Activation):
    act = "brelu"


class SoftReluActivation(_Activation):
    act = "soft_relu"


class STanhActivation(_Activation):
    act = "stanh"


class AggregateLevel:
    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"


class ExpandLevel:
    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE


def _unary_layer(op_type, input, name=None, attrs=None, **meta):
    helper_var = _unwrap(input)
    from ..fluid.layer_helper import LayerHelper
    helper = LayerHelper(op_type, name=name)
    out = helper.create_tmp_variable(
        helper_var.dtype, shape=helper_var.shape,
        lod_level=helper_var.lod_level)
    helper.append_op(op_type, inputs={"X": [helper_var.name]},
                     outputs={"Out": [out.name]}, attrs=attrs or {})
    return LayerOutput(out, size=getattr(input, "size", None),
                       hwc=getattr(input, "hwc", None),
                       is_seq=getattr(input, "is_seq", False), name=name)


def clip_layer(input, min, max, name=None, **kw):
    return _unary_layer("clip", input, name=name,
                        attrs={"min": float(min), "max": float(max)})


def scaling_layer(input, weight, name=None, **kw):
    """Row-wise scale by a [N, 1] weight layer (layers.py scaling_layer)."""
    import paddle_tpu.fluid as fluid
    out = fluid.layers.elementwise_mul(_unwrap(input), _unwrap(weight),
                                       axis=0)
    return LayerOutput(out, size=getattr(input, "size", None), name=name,
                       is_seq=getattr(input, "is_seq", False))


def slope_intercept_layer(input, slope=1.0, intercept=0.0, name=None, **kw):
    return _unary_layer("scale", input, name=name,
                        attrs={"scale": float(slope),
                               "bias": float(intercept)})


def power_layer(input, power, name=None, **kw):
    return _unary_layer("pow", input, name=name,
                        attrs={"factor": float(power)})


def trans_layer(input, name=None, **kw):
    """2-D transpose (layers.py trans_layer over TransLayer)."""
    import paddle_tpu.fluid as fluid
    out = fluid.layers.transpose(_unwrap(input), perm=[1, 0])
    return LayerOutput(out, size=getattr(input, "size", None), name=name)


def interpolation_layer(input, weight, name=None, **kw):
    """w * in0 + (1-w) * in1 with a [N, 1] weight (layers.py
    interpolation_layer)."""
    import paddle_tpu.fluid as fluid
    a, b = input
    w = _unwrap(weight)
    av = fluid.layers.elementwise_mul(_unwrap(a), w, axis=0)
    one_minus = fluid.layers.scale(w, scale=-1.0, bias=1.0)
    bv = fluid.layers.elementwise_mul(_unwrap(b), one_minus, axis=0)
    out = fluid.layers.elementwise_add(av, bv)
    return LayerOutput(out, size=getattr(a, "size", None), name=name)


def dotmul_operator(a, b, scale=1.0, **kw):
    import paddle_tpu.fluid as fluid
    out = fluid.layers.elementwise_mul(_unwrap(a), _unwrap(b))
    if scale != 1.0:
        out = fluid.layers.scale(out, scale=float(scale))
    return LayerOutput(out, size=getattr(a, "size", None))


def cos_sim(a, b, scale=1.0, name=None, **kw):
    import paddle_tpu.fluid as fluid
    out = fluid.layers.cos_sim(_unwrap(a), _unwrap(b))
    if scale != 1.0:
        out = fluid.layers.scale(out, scale=float(scale))
    return LayerOutput(out, size=1, name=name)


def maxout_layer(input, groups, num_channels=None, name=None, **kw):
    import paddle_tpu.fluid as fluid
    var, (c, h, w) = _as_image_var(input, num_channels)
    out = fluid.layers.maxout(var, groups=groups)
    oc = c // groups
    return LayerOutput(out, size=oc * h * w, hwc=(oc, h, w), name=name)


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, name=None, **kw):
    """Zero-pad the C/H/W dims of an image layer (layers.py pad_layer)."""
    import paddle_tpu.fluid as fluid
    var, (c, h, w) = _as_image_var(input, None)
    pc = list(pad_c or [0, 0])
    ph = list(pad_h or [0, 0])
    pw = list(pad_w or [0, 0])
    out = fluid.layers.pad(var, [0, 0] + pc + ph + pw)
    nc, nh, nw = c + sum(pc), h + sum(ph), w + sum(pw)
    return LayerOutput(out, size=nc * nh * nw, hwc=(nc, nh, nw), name=name)


def expand_layer(input, expand_as, expand_level=None, name=None, **kw):
    """Tile each row of ``input`` along the matching sequence of
    ``expand_as`` (layers.py expand_layer -> sequence_expand)."""
    import paddle_tpu.fluid as fluid
    if expand_level == ExpandLevel.FROM_SEQUENCE:
        raise NotImplementedError(
            "expand_layer FROM_SEQUENCE (sub-sequence granularity) is not "
            "supported; FROM_NO_SEQUENCE covers the dense->sequence case")
    out = fluid.layers.sequence_expand(_unwrap(input), _unwrap(expand_as))
    return LayerOutput(out, size=getattr(input, "size", None), is_seq=True,
                       name=name)


def ctc_layer(input, label, size=None, blank=None, norm_by_times=False,
              name=None, **kw):
    """Mean CTC cost (layers.py ctc_layer; the fluid warpctc op implements
    both the legacy ctc and warp-ctc contracts — delegate)."""
    return warp_ctc_layer(input, label, blank=blank if blank is not None
                          else (size - 1 if size else 0),
                          norm_by_times=norm_by_times, name=name)


def warp_ctc_layer(input, label, size=None, blank=0, norm_by_times=False,
                   name=None, **kw):
    import paddle_tpu.fluid as fluid
    out = fluid.layers.mean(fluid.layers.warpctc(
        _unwrap(input), _unwrap(label, kind="seq_ids"), blank=blank,
        norm_by_times=norm_by_times))
    return LayerOutput(out, size=1, name=name)


def crf_layer(input, label, size=None, param_attr=None, name=None, **kw):
    import paddle_tpu.fluid as fluid
    out = fluid.layers.mean(fluid.layers.linear_chain_crf(
        _unwrap(input), _unwrap(label, kind="seq_ids"),
        param_attr=_fluid_param_attr(param_attr)))
    return LayerOutput(out, size=1, name=name)


def rank_cost(left, right, label, name=None, **kw):
    import paddle_tpu.fluid as fluid
    out = fluid.layers.mean(fluid.layers.rank_loss(
        _unwrap(label), _unwrap(left), _unwrap(right)))
    return LayerOutput(out, size=1, name=name)


def huber_regression_cost(input, label, delta=1.0, name=None, **kw):
    import paddle_tpu.fluid as fluid
    from ..fluid.layer_helper import LayerHelper
    helper = LayerHelper("huber_loss", name=name)
    residual = helper.create_tmp_variable("float32")
    out = helper.create_tmp_variable("float32")
    helper.append_op("huber_loss",
                     inputs={"X": [_unwrap(input).name],
                             "Y": [_unwrap(label).name]},
                     outputs={"Out": [out.name],
                              "Residual": [residual.name]},
                     attrs={"delta": float(delta)})
    import paddle_tpu.fluid as fluid
    return LayerOutput(fluid.layers.mean(out), size=1, name=name)


def multi_binary_label_cross_entropy(input, label, name=None, **kw):
    import paddle_tpu.fluid as fluid
    out = fluid.layers.mean(fluid.layers.sigmoid_cross_entropy_with_logits(
        _unwrap(input), _unwrap(label)))
    return LayerOutput(out, size=1, name=name)


def sum_cost(input, name=None, **kw):
    import paddle_tpu.fluid as fluid
    from ..fluid.layer_helper import LayerHelper
    helper = LayerHelper("reduce_sum", name=name)
    out = helper.create_tmp_variable("float32", shape=())
    helper.append_op("reduce_sum", inputs={"X": [_unwrap(input).name]},
                     outputs={"Out": [out.name]},
                     attrs={"reduce_all": True, "dim": 0, "keep_dim": False})
    return LayerOutput(out, size=1, name=name)


def mse_cost(input, label, name=None, **kw):
    return regression_cost(input, label, name=name)


def bidirectional_gru(input, size, return_seq=True, name=None, **kw):
    """fwd + reverse grumemory concatenated (networks.py
    bidirectional_gru)."""
    fwd = simple_gru(input, size)
    bwd = simple_gru(input, size, reverse=True)
    if return_seq:
        return concat_layer([fwd, bwd])
    return concat_layer([last_seq(fwd), first_seq(bwd)])


def bidirectional_lstm(input, size, return_seq=True, name=None, **kw):
    fwd = simple_lstm(input, size)
    bwd = simple_lstm(input, size, reverse=True)
    if return_seq:
        return concat_layer([fwd, bwd])
    return concat_layer([last_seq(fwd), first_seq(bwd)])


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride=1, num_channel=None, act=None,
                         pool_type=None, name=None, **kw):
    conv = img_conv_layer(input, filter_size=filter_size,
                          num_filters=num_filters,
                          num_channels=num_channel, padding=0, act=act)
    return img_pool_layer(conv, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type)


# LayerOutput arithmetic + layer_math (reference trainer_config_helpers/
# math.py: `1 + x`, `x * y`, elementwise chains in config scripts)
def _lo_binary(self, other, op_type, reverse=False):
    import paddle_tpu.fluid as fluid
    if isinstance(other, LayerOutput):
        fn = getattr(fluid.layers, op_type)
        a, b = (other, self) if reverse else (self, other)
        # the fluid out var inherits X's static shape, so the LARGER
        # operand must be X (the reference math.py special-cases the
        # size-1 operand the same way); a - b with a smaller becomes
        # -(b - a)
        # pending data layers carry their size in _data_size
        sa = a.size or a._data_size or 0
        sb = b.size or b._data_size or 0
        negate = False
        if sb > sa:
            if op_type == "elementwise_sub":
                negate = True
            a, b = b, a
        out = fn(_unwrap(a), _unwrap(b))
        if negate:
            out = fluid.layers.scale(out, scale=-1.0)
        return LayerOutput(out, size=a.size, is_seq=a.is_seq or b.is_seq,
                           hwc=a.hwc)
    scalar = float(other)
    if op_type == "elementwise_add":
        return slope_intercept_layer(self, 1.0, scalar)
    if op_type == "elementwise_sub":
        return slope_intercept_layer(self, -1.0 if reverse else 1.0,
                                     scalar if reverse else -scalar)
    if op_type == "elementwise_mul":
        return slope_intercept_layer(self, scalar, 0.0)
    raise TypeError(op_type)


LayerOutput.__add__ = lambda s, o: _lo_binary(s, o, "elementwise_add")
LayerOutput.__radd__ = LayerOutput.__add__
LayerOutput.__sub__ = lambda s, o: _lo_binary(s, o, "elementwise_sub")
LayerOutput.__rsub__ = lambda s, o: _lo_binary(s, o, "elementwise_sub",
                                               reverse=True)
LayerOutput.__mul__ = lambda s, o: _lo_binary(s, o, "elementwise_mul")
LayerOutput.__rmul__ = LayerOutput.__mul__


__all__ += [
    "ExpActivation", "AbsActivation", "SquareActivation", "BReluActivation",
    "SoftReluActivation", "STanhActivation", "AggregateLevel", "ExpandLevel",
    "clip_layer", "scaling_layer", "slope_intercept_layer", "power_layer",
    "trans_layer", "interpolation_layer", "dotmul_operator", "cos_sim",
    "maxout_layer", "pad_layer", "expand_layer", "ctc_layer",
    "warp_ctc_layer", "crf_layer", "rank_cost", "huber_regression_cost",
    "multi_binary_label_cross_entropy", "sum_cost", "mse_cost",
    "bidirectional_gru", "bidirectional_lstm", "simple_img_conv_pool",
]


class _LayerMath:
    """The config-script math namespace (reference trainer_config_helpers/
    math.py, exported as ``layer_math``): elementwise functions over
    LayerOutput."""

    @staticmethod
    def _u(op, x, attrs=None):
        return _unary_layer(op, x, attrs=attrs)

    def exp(self, x):
        return self._u("exp", x)

    def sqrt(self, x):
        return self._u("sqrt", x)

    def reciprocal(self, x):
        return self._u("reciprocal", x)

    def log(self, x):
        return self._u("log", x)

    def abs(self, x):
        return self._u("abs", x)

    def sigmoid(self, x):
        return self._u("sigmoid", x)

    def tanh(self, x):
        return self._u("tanh", x)

    def square(self, x):
        return self._u("square", x)

    def relu(self, x):
        return self._u("relu", x)


layer_math = _LayerMath()

__all__ += ["layer_math"]


def recurrent_layer(input, act=None, reverse=False, bias_attr=True,
                    param_attr=None, name=None, **kw):
    """Vanilla full-matrix recurrence over the input sequence (reference
    layers.py recurrent_layer -> gserver RecurrentLayer; size equals the
    input size)."""
    import paddle_tpu.fluid as fluid
    out = fluid.layers.dynamic_vanilla_rnn(
        _unwrap(input, kind="seq_dense"),
        size=(input.size or input._data_size),
        act=_act_str(act) or "tanh", is_reverse=reverse,
        param_attr=_fluid_param_attr(param_attr),
        bias_attr=False if bias_attr is False
        else (None if bias_attr is True else _fluid_param_attr(bias_attr)))
    return LayerOutput(out, size=(input.size or input._data_size),
                       is_seq=True, name=name)


def block_expand_layer(input, num_channels=None, block_x=1, block_y=1,
                       stride_x=1, stride_y=1, padding_x=0, padding_y=0,
                       name=None, **kw):
    """Image -> sequence of flattened blocks (layers.py block_expand_layer;
    the fluid im2sequence op owns the patch walk)."""
    import paddle_tpu.fluid as fluid
    var, (c, h, w) = _as_image_var(input, num_channels)
    out = fluid.layers.im2sequence(var, filter_size=[block_y, block_x],
                                   stride=[stride_y, stride_x],
                                   padding=[padding_y, padding_x])
    return LayerOutput(out, size=c * block_x * block_y, is_seq=True,
                       name=name)


__all__ += ["block_expand_layer", "recurrent_layer"]


# ---------------------------------------------------------------------------
# mixed_layer + projections (reference trainer_config_helpers/layers.py:867
# mixed_layer, :405+ projections) — the legacy DSL's composition primitive:
# ``with mixed_layer(size=n, act=a) as m: m += projection(...)`` sums the
# lowered projections, adds the optional bias, applies the activation.
# ---------------------------------------------------------------------------

class _Projection:
    def __init__(self, kind, input, param_attr=None, size=None, offset=None):
        self.kind = kind
        self.input = input
        self.param_attr = param_attr
        self.size = size
        self.offset = offset

    def lower(self, out_size):
        import paddle_tpu.fluid as fluid

        x = _unwrap(self.input)
        in_size = getattr(self.input, "size", None) or \
            (x.shape[-1] if x.shape else None)
        if self.kind == "full":
            return fluid.layers.fc(input=x, size=out_size, act=None,
                                   bias_attr=False,
                                   param_attr=_fluid_param_attr(
                                       self.param_attr))
        if self.kind == "trans_full":
            # out.row = in.row @ W^T with W [out_size, in_size] — shared
            # against an fc whose weight is [in', out'] = [out_size, in_size]
            # (layers.py:468 trans_full_matrix_projection, the sharew case)
            from paddle_tpu.fluid.layer_helper import LayerHelper
            helper = LayerHelper("trans_full_matrix_projection")
            w = helper.create_parameter(
                _fluid_param_attr(self.param_attr) or
                fluid.ParamAttr(), shape=(out_size, in_size),
                dtype="float32")
            return fluid.layers.matmul(x, w, transpose_y=True)
        if self.kind == "identity":
            if self.offset is None:
                # reference layers.py identity_projection config_assert:
                # without an offset the sizes must agree — silently cropping
                # to the first out_size columns would hide a wiring bug
                if in_size not in (None, out_size):
                    raise ValueError(
                        f"identity_projection: input size {in_size} != "
                        f"mixed_layer size {out_size} (pass offset= to "
                        "select a column window)")
                return x
            if self.offset == 0 and in_size in (None, out_size):
                return x
            # layers.py:548 identity_projection with offset: columns
            # [offset, offset+out_size)
            return fluid.layers.crop(
                x, shape=[-1, out_size], offsets=[0, int(self.offset)])
        if self.kind == "table":
            ids = _unwrap(self.input, "seq_ids")   # int64 id sequence
            return fluid.layers.embedding(
                input=ids, size=[in_size, out_size],
                param_attr=_fluid_param_attr(self.param_attr))
        if self.kind == "dotmul":
            from paddle_tpu.fluid.layer_helper import LayerHelper
            helper = LayerHelper("dotmul_projection")
            w = helper.create_parameter(
                _fluid_param_attr(self.param_attr) or fluid.ParamAttr(),
                shape=(1, out_size), dtype="float32")
            return fluid.layers.elementwise_mul(x, w)
        raise NotImplementedError(
            f"projection kind {self.kind!r} inside mixed_layer (the "
            "context/conv projections lower through sequence_conv_pool / "
            "img_conv_layer instead)")


def full_matrix_projection(input, size=0, param_attr=None):
    return _Projection("full", input, param_attr, size)


def trans_full_matrix_projection(input, size=0, param_attr=None):
    return _Projection("trans_full", input, param_attr, size)


def identity_projection(input, offset=None, size=None):
    return _Projection("identity", input, None, size, offset)


def table_projection(input, size=0, param_attr=None):
    return _Projection("table", input, param_attr, size)


def dotmul_projection(input, param_attr=None):
    return _Projection("dotmul", input, param_attr)


class MixedLayer(LayerOutput):
    """The ``with mixed_layer(...) as m`` object: LayerOutput whose var is
    produced at context exit from the accumulated projections."""

    def __init__(self, size, act=None, bias_attr=False, name=None):
        super().__init__(var=None, size=size, name=name)
        self._mixed_act = act
        self._mixed_bias = bias_attr
        self._projs = []

    def __iadd__(self, proj):
        if not isinstance(proj, _Projection):
            raise TypeError(f"mixed_layer += expects a projection, got "
                            f"{type(proj).__name__}")
        self._projs.append(proj)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        self._lower()
        return False

    def _lower(self):
        import paddle_tpu.fluid as fluid

        if self._var is not None:   # already materialized (a consumer
            return                  # inside the with-block forced it)
        if not self._projs:
            raise ValueError("mixed_layer exited with no projections")
        terms = [p.lower(self.size) for p in self._projs]
        total = terms[0]
        for t in terms[1:]:
            total = fluid.layers.elementwise_add(total, t)
        if self._mixed_bias not in (False, None):
            from paddle_tpu.fluid.layer_helper import LayerHelper
            helper = LayerHelper("mixed_bias")
            battr = None if self._mixed_bias is True else self._mixed_bias
            b = helper.create_parameter(
                _fluid_param_attr(battr) or fluid.ParamAttr(),
                shape=(self.size,), dtype="float32", is_bias=True)
            total = fluid.layers.elementwise_add(total, b)
        act = _act_str(self._mixed_act)
        if act and act != "linear":
            total = getattr(fluid.layers, act)(total)
        self._var = total

    # pending-materialization guard: using the mixed layer before the with-
    # block ends (or calling it bare) lowers on demand
    def materialize(self, kind="dense"):
        if self._var is None:
            self._lower()
        return self._var


def mixed_layer(size=0, input=None, act=None, bias_attr=False, name=None,
                **kw):
    ml = MixedLayer(size=size, act=act, bias_attr=bias_attr, name=name)
    if input:
        for proj in (input if isinstance(input, (list, tuple)) else [input]):
            ml += proj
    return ml


def TrainData(spec=None, **kw):
    """Legacy proto data-source declaration (config_parser TrainData):
    recorded for introspection; the trainer contract feeds readers."""
    _DATA_SOURCES.update(train_data=spec)


def TestData(spec=None, **kw):
    _DATA_SOURCES.update(test_data=spec)


def SimpleData(files=None, feat_dim=0, context_len=0, buffer_capacity=0,
               **kw):
    return dict(kind="simple", files=files, feat_dim=feat_dim,
                context_len=context_len, buffer_capacity=buffer_capacity)


__all__ += ["mixed_layer", "full_matrix_projection",
            "trans_full_matrix_projection", "identity_projection",
            "table_projection", "dotmul_projection", "TrainData",
            "TestData", "SimpleData"]


def nce_layer(input, label, num_classes=None, weight=None,
              num_neg_samples=10, neg_distribution=None, param_attr=None,
              bias_attr=None, name=None, **kw):
    """NCE cost (reference layers.py nce_layer over NCELayer); the sampled
    negative distribution is uniform here — ``neg_distribution`` is
    accepted for config parity (the fluid nce op samples uniformly, like
    the reference's default when no distribution is given)."""
    import paddle_tpu.fluid as fluid
    xs = input if isinstance(input, (list, tuple)) else [input]
    x = _unwrap(xs[0])
    if len(xs) > 1:
        x = fluid.layers.concat([_unwrap(v) for v in xs], axis=1)
    cost = fluid.layers.nce(
        input=x, label=_unwrap(label, "label"),
        num_total_classes=int(num_classes),
        num_neg_samples=int(num_neg_samples),
        sample_weight=None if weight is None else _unwrap(weight),
        param_attr=_fluid_param_attr(param_attr),
        bias_attr=_fluid_param_attr(bias_attr))
    out = fluid.layers.mean(cost)
    return LayerOutput(out, size=1, name=name)


__all__ += ["nce_layer", "CudnnAvgPooling", "CudnnMaxPooling"]


def hsigmoid(input, label, num_classes=None, name=None, param_attr=None,
             bias_attr=True, **kw):
    """Hierarchical sigmoid cost layer (reference layers.py hsigmoid over
    gserver HierarchicalSigmoidLayer): inputs are concatenated (the
    reference keeps one weight block per input; a single [num_classes-1,
    sum(sizes)] block is the same linear map), cost averaged over the
    batch. ``num_classes=None`` falls back to the label layer's size;
    ``bias_attr=None`` means default bias (the reference's
    wrap_bias_attr_default(has_bias=True) rule), False disables it."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.layer_helper import LayerHelper

    xs = input if isinstance(input, (list, tuple)) else [input]
    x = _unwrap(xs[0])
    if len(xs) > 1:
        x = fluid.layers.concat([_unwrap(v) for v in xs], axis=1)
    dim = 0
    for v in xs:
        d = getattr(v, "size", None)
        if not d:
            uv = _unwrap(v)
            d = uv.shape[-1] if uv.shape else None
        if not d or d < 0:
            raise ValueError(
                "hsigmoid: cannot infer an input's feature size (declare "
                "the layer size)")
        dim += int(d)
    if num_classes is None:
        num_classes = getattr(label, "size", None) or             getattr(label, "_data_size", None)
    if not num_classes or int(num_classes) <= 2:
        raise ValueError(
            "hsigmoid requires num_classes > 2 (reference layers.py "
            "hsigmoid config_assert)")
    helper = LayerHelper("hsigmoid", name=name)
    w = helper.create_parameter(
        _fluid_param_attr(param_attr) or fluid.ParamAttr(),
        shape=(int(num_classes) - 1, dim), dtype="float32")
    inputs = {"X": [x.name], "W": [w.name],
              "Label": [_unwrap(label, "label").name]}
    if bias_attr is not False:   # None == default bias, like the reference
        battr = None if bias_attr in (True, None) else bias_attr
        b = helper.create_parameter(
            _fluid_param_attr(battr) or fluid.ParamAttr(),
            shape=(1, int(num_classes) - 1), dtype="float32", is_bias=True)
        inputs["Bias"] = [b.name]
    cost = helper.create_tmp_variable("float32")
    helper.append_op("hsigmoid", inputs=inputs,
                     outputs={"Out": [cost.name]},
                     attrs={"num_classes": int(num_classes)})
    out = fluid.layers.mean(cost)
    return LayerOutput(out, size=1, name=name)


__all__ += ["hsigmoid"]
