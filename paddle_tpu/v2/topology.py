"""v2 Topology: the serializable (network, data-types) bundle behind
paddle.infer.

Reference: python/paddle/v2/topology.py — wraps the output layers' model
proto, exposes ``data_type()`` (the typed data layers the network reads)
and ``serialize_for_inference(stream)`` (the {protobin, data_type} pickle
the reference Inference(fileobj=...) loads). Here the fluid Program IS the
topology format: the bundle is the pruned for-test Program's JSON plus the
reconstructed InputTypes, so a trained v2 model round-trips through a
stream into a fresh process.
"""

from __future__ import annotations

import json

from . import data_type as v2_data_type


def _to_vars(layers):
    from .config_helpers import LayerOutput

    if not isinstance(layers, (list, tuple)):
        layers = [layers]
    out = []
    for l in layers:
        out.append(l.var if isinstance(l, LayerOutput) else l)
    return out


def _input_type_from_var(var):
    """Reconstruct the declaration-time InputType from the fluid data var
    (data_type.py maps InputType -> (dtype, shape, lod_level) exactly)."""
    shape = [int(s) for s in (var.shape or [1]) if s not in (None, -1)]
    dim = shape[-1] if shape else 1
    return v2_data_type.InputType(dim=dim, seq_type=1 if var.lod_level else 0,
                                  dtype=str(var.dtype or "float32"),
                                  shape=shape or [1],
                                  lod_level=int(var.lod_level or 0))


class Topology:
    """Topology(output_layer or [output_layers]) over the current program."""

    def __init__(self, layers, extra_layers=None):
        from ..fluid.io import _prune_program
        from .config_helpers import _DATA_LAYERS

        vars_ = _to_vars(layers) + _to_vars(extra_layers or [])
        self.fetch_names = [v.name for v in _to_vars(layers)]
        program = vars_[0].block.program
        self.program = _prune_program(program, [], self.fetch_names)
        block = self.program.global_block()

        # the data layers this pruned network actually reads, in declaration
        # order (reference Topology.data_type walks the proto's data layers)
        produced = set()
        read = set()
        for op in block.ops:
            for n in op.input_arg_names():
                if n not in produced:
                    read.add(n)
            produced.update(op.output_arg_names())
        self.feed_names = list(dict.fromkeys(
            d.name for d in _DATA_LAYERS
            if not d.is_pending and d.name in read and block.has_var(d.name)))
        # fluid-built programs have no v2 data-layer records; fall back to
        # free is_data vars
        if not self.feed_names:
            self.feed_names = [n for n in read
                               if block.has_var(n) and block.var(n).is_data]

    def data_type(self):
        """[(name, InputType)] for every data layer the network reads."""
        block = self.program.global_block()
        return [(n, _input_type_from_var(block.var(n)))
                for n in self.feed_names]

    def proto(self):
        """The serialized network (reference returns the ModelConfig proto;
        here the Program JSON — the framework's model wire format)."""
        return self.program.to_json()

    def serialize_for_inference(self, stream):
        """Write the inference bundle (reference topology.py
        serialize_for_inference: {protobin, data_type} via pickle; here a
        JSON document — no pickle, loadable anywhere)."""
        meta = self.program.to_dict()
        meta["feed_var_names"] = list(self.feed_names)
        meta["fetch_var_names"] = list(self.fetch_names)
        meta["data_types"] = [
            {"name": n, "dim": t.dim, "seq_type": t.seq_type,
             "dtype": t.dtype, "shape": t.shape, "lod_level": t.lod_level}
            for n, t in self.data_type()]
        data = json.dumps(meta).encode("utf-8")
        stream.write(data)


def load_serialized(fileobj):
    """Inverse of serialize_for_inference -> (program, feed_names,
    fetch_names)."""
    from ..fluid.framework import Program

    meta = json.loads(fileobj.read().decode("utf-8"))
    program = Program.from_dict(meta)
    return program, meta["feed_var_names"], meta["fetch_var_names"]


__all__ = ["Topology", "load_serialized"]
