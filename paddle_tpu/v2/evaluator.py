"""``paddle.v2.evaluator`` — evaluator spellings of the v2 generation.

Reference: python/paddle/v2/evaluator.py (auto-converts every
``*_evaluator`` of python/paddle/trainer_config_helpers/evaluators.py:18-35
to a v2 name with the suffix dropped). In the reference these attach
evaluator configs to the topology and the GradientMachine accumulates them;
here each evaluator appends the corresponding metric ops to the program
being built and returns a LayerOutput, so callers fetch it per batch
(``SGD.train`` feeds fetched metrics into the event stream) or wrap it with
``fluid.evaluator`` for cross-batch accumulation.
"""

from __future__ import annotations

from .config_helpers import LayerOutput, _unwrap

__all__ = ["classification_error", "auc", "pnpair", "precision_recall",
           "ctc_error", "chunk", "sum", "column_sum", "value_printer",
           "maxid_printer", "detection_map"]


def classification_error(input, label, name=None, top_k=1, **kw):
    """evaluators.py classification_error_evaluator: error rate = 1 - top-k
    accuracy (reference computes error; fluid's accuracy op computes the
    complement)."""
    import paddle_tpu.fluid as fluid
    acc = fluid.layers.accuracy(input=_unwrap(input),
                                label=_unwrap(label, "label"), k=top_k)
    one = fluid.layers.fill_constant(shape=[1], dtype="float32", value=1.0)
    err = fluid.layers.elementwise_sub(one, acc)
    return LayerOutput(err, size=1, name=name)


def auc(input, label, name=None, **kw):
    import paddle_tpu.fluid as fluid
    out = fluid.layers.auc(input=_unwrap(input),
                           label=_unwrap(label, "label"))
    var = out[0] if isinstance(out, (tuple, list)) else out
    return LayerOutput(var, size=1, name=name)


def pnpair(input, label, query_id, weight=None, name=None, **kw):
    """positive_negative_pair over (score, label, query) triples."""
    from ..fluid.layer_helper import LayerHelper
    helper = LayerHelper("positive_negative_pair", name=name)
    outs = {s: helper.create_tmp_variable("float32")
            for s in ("PositivePair", "NegativePair", "NeutralPair")}
    inputs = {"Score": [_unwrap(input).name],
              "Label": [_unwrap(label, "label").name],
              "QueryID": [_unwrap(query_id, "label").name]}
    if weight is not None:
        inputs["Weight"] = [_unwrap(weight).name]
    helper.append_op("positive_negative_pair", inputs=inputs,
                     outputs={k: [v.name] for k, v in outs.items()})
    return LayerOutput(outs["PositivePair"], size=1, name=name)


def precision_recall(input, label, positive_label=None, weight=None,
                     name=None, **kw):
    import paddle_tpu.fluid as fluid
    inp = _unwrap(input)
    maxids = fluid.layers.topk(inp, k=1)[1]
    out = fluid.layers.precision_recall(
        indices=maxids, labels=_unwrap(label, "label"),
        class_number=input.size)
    var = out[0] if isinstance(out, (tuple, list)) else out
    return LayerOutput(var, size=None, name=name)


def ctc_error(input, label, name=None, **kw):
    """evaluators.py ctc_error_evaluator: normalized edit distance between
    the decoded prediction and the label sequence."""
    import paddle_tpu.fluid as fluid
    out = fluid.layers.edit_distance(input=_unwrap(input, "seq_ids"),
                                     label=_unwrap(label, "seq_ids"),
                                     normalized=True)
    var = out[0] if isinstance(out, (tuple, list)) else out
    return LayerOutput(var, size=1, name=name)


def chunk(input, label, chunk_scheme, num_chunk_types, name=None, **kw):
    import paddle_tpu.fluid as fluid
    out = fluid.layers.chunk_eval(input=_unwrap(input, "seq_ids"),
                                  label=_unwrap(label, "seq_ids"),
                                  chunk_scheme=chunk_scheme,
                                  num_chunk_types=num_chunk_types)
    var = out[0] if isinstance(out, (tuple, list)) else out
    return LayerOutput(var, size=1, name=name)


def sum(input, name=None, **kw):  # noqa: A001 (reference name)
    import paddle_tpu.fluid as fluid
    out = fluid.layers.reduce_sum(_unwrap(input))
    return LayerOutput(out, size=1, name=name)


def column_sum(input, name=None, **kw):
    import paddle_tpu.fluid as fluid
    out = fluid.layers.reduce_sum(_unwrap(input), dim=0)
    return LayerOutput(out, size=getattr(input, "size", None), name=name)


def value_printer(input, name=None, **kw):
    """evaluators.py value_printer_evaluator -> the Print debug op."""
    import paddle_tpu.fluid as fluid
    out = fluid.layers.Print(_unwrap(input),
                             message=name or "value_printer")
    return LayerOutput(out, size=getattr(input, "size", None), name=name)


def maxid_printer(input, name=None, **kw):
    import paddle_tpu.fluid as fluid
    maxids = fluid.layers.topk(_unwrap(input), k=1)[1]
    out = fluid.layers.Print(maxids, message=name or "maxid_printer")
    return LayerOutput(out, size=1, name=name)


def detection_map(overlap_threshold=0.5, name=None, **kw):
    """detection_map_evaluator — served by the stateful fluid DetectionMAP
    evaluator (fluid/evaluator.py): host-side accumulation over
    multiclass_nms outputs, ``update()`` per batch + ``eval()``."""
    from ..fluid import evaluator as fe
    return fe.DetectionMAP(overlap_threshold=overlap_threshold, name=name)
