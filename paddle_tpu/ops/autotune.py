"""Kernel autotuner plane: measured per-shape variant selection.

The ``kernel_tier`` routing layer (ops/pallas/__init__.py) decides
pallas-vs-jnp per kernel FAMILY from the hand-edited ``AUTO_PALLAS``
frozenset — a guess encoded in source. This module makes that decision
DATA: every tunable kernel registers its named variants here (``jnp``,
``pallas``, and the conv_bn-only ``pallas_db`` double-buffered /
``pallas_bf16`` reduced-precision variants), a :class:`Tuner` times the
variants that support a concrete shape key — interleaved best-of-N
windows, the bench.py discipline — and the winners land in a persistent
:class:`TuneTable`. Dispatch sites consult the attached table through
:func:`dispatch_variant` under ``kernel_tier=auto`` BEFORE falling back
to the static ``AUTO_PALLAS`` routing, so a tuned table *is* the new
routing and an untuned process behaves bitwise as before.

The *Tensor Processing Primitives* design (PAPERS.md): a small set of
tuned primitives selected by measurement, not one-off hand-tuning — and
the lever that makes a TPU window cheap: every shape the fleet serves is
measured once at publish time and cached, instead of hand-tuned.

Persistence follows the execcache artifact contract exactly:

* **content-addressed artifact** — ``MAGIC + sha256hex(blob) + "\\n" +
  blob`` (blob is canonical JSON, no pickle), written tmp +
  ``os.replace``;
* **full identity fingerprint in the filename** — a table is only valid
  for the toolchain + backend + device kind that measured it
  (``table-<fingerprint_key[:40]>.jtune``), so a foreign table is a
  silent filename miss, never a wrong selection;
* **typed bounded rejects** — :data:`REJECT_REASONS`; every refusal is
  a ``paddle_tpu_kernel_autotune_rejects`` bump plus a
  ``kernel_autotune_reject`` flight event followed by static-routing
  fallback, never an engine failure;
* **manifest pinning** — a published ``<version>/tune/`` dir loads
  read-only with the RAW bytes checked against the manifest's
  ``tune_files`` digests before parsing (``registry.verify`` re-hashes
  the same digests offline, ``gc`` deletes them with the version).

Retrace discipline: the attached table's digest lives in the
``kernel_autotune_digest`` flag, which is in the executor's
``_JIT_KEY_FLAGS`` — attaching/detaching a table bumps the flags
version, so every jitted program retraces onto the new routing and
every execcache fingerprint keys on the digest (a warm artifact
compiled against table X never loads into a process routing by table Y).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from contextlib import contextmanager

from ..core.flags import get_flag, set_flags
from ..obs.metrics import REGISTRY as _METRICS
from .pallas import record_fallback, use_pallas

TUNE_DIRNAME = "tune"
ARTIFACT_SUFFIX = ".jtune"
_MAGIC = b"PDTPUTUNE1\n"

# typed bounded reject vocabulary (the execcache shape, minus run_failed
# — a tuning table is never executed, only read):
#   format       — bad magic / truncated / bit-flipped payload
#   manifest     — raw bytes not certified by the version manifest
#   fingerprint  — embedded identity != this process's identity
#   deserialize  — JSON/schema violations inside a well-formed envelope
REJECT_REASONS = ("format", "manifest", "fingerprint", "deserialize")

_M_SELECTIONS = _METRICS.counter(
    "paddle_tpu_kernel_autotune_selections",
    "dispatches routed by a tuned-table entry (counted at trace time, "
    "once per retrace — steady state adds zero), per kernel family",
    labels=("kernel",))
_M_TUNES = _METRICS.counter(
    "paddle_tpu_kernel_autotune_tunes",
    "tuner measurements recorded into a tuning table (one per (kernel, "
    "shape key) tuned), per kernel family",
    labels=("kernel",))
_M_REJECTS = _METRICS.counter(
    "paddle_tpu_kernel_autotune_rejects",
    "tuning-table artifacts refused, by typed reason "
    "(ops.autotune.REJECT_REASONS); every reject falls back to static "
    "AUTO_PALLAS routing, never an engine failure",
    labels=("reason",))
_M_SELECTED = _METRICS.gauge(
    "paddle_tpu_kernel_variant_selected",
    "entries in the ATTACHED tuning table per (kernel, winning variant) "
    "— zero everywhere when no table is attached",
    labels=("kernel", "variant"))

_LOCK = threading.RLock()
_ACTIVE = None              # the attached TuneTable (process-wide)
_FORCED = {}                # kernel -> forced variant (tuner/tests)
_CAPTURE = None             # active capture list, or None


# ---------------------------------------------------------------------------
# shape keys
# ---------------------------------------------------------------------------

def make_key(**fields):
    """Canonical shape key for one dispatch: a sorted tuple of
    (name, value) pairs with shapes as int tuples and dtypes as strings
    — hashable, and JSON-stable via :func:`key_str`."""
    def canon(v):
        if isinstance(v, (list, tuple)):
            return tuple(canon(x) for x in v)
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        return str(v)                       # np/jnp dtypes and friends
    return tuple(sorted((str(k), canon(v)) for k, v in fields.items()))

def key_str(key):
    """The table's storage key: compact JSON of the key tuple (tuples
    encode as lists, deterministically)."""
    return json.dumps(key, separators=(",", ":"), default=list)


# ---------------------------------------------------------------------------
# the variant registry
# ---------------------------------------------------------------------------

class _VariantSpec:
    __slots__ = ("name", "build", "bf16")

    def __init__(self, name, build, bf16=False):
        self.name = name
        self.build = build          # build(key) -> zero-arg runner | None
        self.bf16 = bool(bf16)


class VariantRegistry:
    """Named variants per tunable kernel family. ``build(key)`` returns
    a zero-arg timed callable that runs ONE step of the variant on
    inputs synthesized from the shape key (or None when the key cannot
    be synthesized standalone — the tuner then records the routing
    winner without timings)."""

    def __init__(self):
        self._kernels = {}

    def register(self, kernel, name, build, bf16=False):
        self._kernels.setdefault(kernel, {})[name] = \
            _VariantSpec(name, build, bf16=bf16)

    def variants(self, kernel):
        return dict(self._kernels.get(kernel, {}))

    def kernels(self):
        return sorted(self._kernels)


VARIANTS = VariantRegistry()


def variant_allowed(kernel, name, registry=None):
    """May the table route this kernel to this variant HERE? Unknown
    names (a table from a newer build) and bf16-flagged variants without
    the ``kernel_autotune_bf16`` opt-in are refused — the dispatch falls
    through to static routing instead. ``registry`` defaults to the
    process-wide :data:`VARIANTS` (the Tuner passes its own)."""
    spec = (registry or VARIANTS).variants(kernel).get(name)
    if spec is None:
        return False
    return not spec.bf16 or bool(get_flag("kernel_autotune_bf16"))


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

@contextmanager
def force_variant(kernel, name):
    """Pin one kernel family to one variant for the duration (tuner
    runners and parity tests; trace-time effect — re-trace inside the
    context for jitted callers)."""
    with _LOCK:
        prev = _FORCED.get(kernel)
        _FORCED[kernel] = name
    try:
        yield
    finally:
        with _LOCK:
            if prev is None:
                _FORCED.pop(kernel, None)
            else:
                _FORCED[kernel] = prev


@contextmanager
def capture():
    """Record every (kernel, key, supported-variants) a traced region
    dispatches — what ``registry.warm(tune=True)`` runs around the
    engine's real warmup to learn which shapes to tune."""
    global _CAPTURE
    with _LOCK:
        prev, _CAPTURE = _CAPTURE, []
        keys = _CAPTURE
    try:
        yield keys
    finally:
        with _LOCK:
            _CAPTURE = prev


def dispatch_variant(kernel, key, supported, tier_kernel=None):
    """The ONE routing decision for a tunable dispatch site: which named
    variant executes this call. Host-side and trace-time (under jit it
    runs once per retrace), so steady state costs nothing.

    ``supported`` maps variant name -> this call's shape/config
    predicate. Order: a :func:`force_variant` pin wins; else under
    ``kernel_tier=auto`` with autotuning on, the attached table's entry
    for ``key`` (if its variant is supported and allowed); else the
    static pre-autotune routing via ``use_pallas(tier_kernel or
    kernel)`` — bitwise the old behavior. ``tier_kernel`` names the
    ``AUTO_PALLAS``/fallback-counter family when it differs from the
    table's kernel name (e.g. table kernel "rnn", tier family "lstm")."""
    tier = tier_kernel or kernel
    if _CAPTURE is not None:
        _CAPTURE.append((kernel, key,
                         tuple(sorted(n for n, ok in supported.items()
                                      if ok))))
    forced = _FORCED.get(kernel)
    if forced is not None:
        if supported.get(forced, False):
            return forced
        if forced != "jnp":
            record_fallback(tier)
        return "jnp"
    if (get_flag("kernel_tier") == "auto" and get_flag("kernel_autotune")
            and _ACTIVE is not None):
        choice = _ACTIVE.lookup(kernel, key)
        if (choice is not None and supported.get(choice, False)
                and variant_allowed(kernel, choice)):
            _M_SELECTIONS.labels(kernel=kernel).inc()
            return choice
    return "pallas" if use_pallas(tier, supported.get("pallas", False)) \
        else "jnp"


# ---------------------------------------------------------------------------
# measurement core — THE interleaved best-of-N implementation
# ---------------------------------------------------------------------------

def measure(runners, repeats=3, inner=2):
    """Time each runner: ``repeats`` interleaved windows of ``inner``
    calls each, best window kept — the bench.py best-of-N discipline,
    interleaved across variants so drift (thermal, a noisy neighbor)
    hits every variant equally instead of biasing whichever ran last.
    One untimed warmup call per runner absorbs trace+compile. Returns
    ``{name: best ms/call}``; a runner that raises during warmup is
    dropped (a variant that cannot run cannot win)."""
    import jax

    def block(out):
        for leaf in jax.tree_util.tree_leaves(out):
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()

    order = []
    for name in sorted(runners):
        try:
            block(runners[name]())
        except Exception:
            continue
        order.append(name)
    best = {}
    for _ in range(max(1, int(repeats))):
        for name in order:
            fn = runners[name]
            t0 = time.perf_counter()
            out = None
            for _i in range(max(1, int(inner))):
                out = fn()
            block(out)
            ms = (time.perf_counter() - t0) * 1e3 / max(1, int(inner))
            if name not in best or ms < best[name]:
                best[name] = ms
    return best


# ---------------------------------------------------------------------------
# the tuning table + store (execcache fingerprint contract)
# ---------------------------------------------------------------------------

def table_fingerprint():
    """Identity a table's measurements are valid for: format/schema +
    toolchain + backend + device kind. Shapes and dtypes live in the
    per-entry keys; everything environmental lives here, so a table
    measured on another backend/toolchain is a filename miss (and a
    doctored one a typed ``fingerprint`` reject)."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    return {
        "format": 1,
        "kind": "kernel_tune_table",
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": str(dev.platform),
        "device_kind": str(getattr(dev, "device_kind", dev.platform)),
    }


def fingerprint_key(fp):
    """Stable digest of a fingerprint dict (the artifact filename key)."""
    return hashlib.sha256(
        json.dumps(fp, sort_keys=True, default=str).encode()).hexdigest()


class TuneTable:
    """{(kernel, key) -> winning variant (+ the timings that decided
    it)} under one :func:`table_fingerprint` identity."""

    def __init__(self, fingerprint=None, entries=None):
        self.fingerprint = dict(fingerprint) if fingerprint is not None \
            else table_fingerprint()
        # (kernel, key_str) -> {"variant": str, "timings_ms": {...}}
        self.entries = dict(entries or {})

    def set(self, kernel, key, variant, timings_ms=None):
        self.entries[(str(kernel), key_str(key))] = {
            "variant": str(variant),
            "timings_ms": {k: float(v)
                           for k, v in (timings_ms or {}).items()},
        }

    def lookup(self, kernel, key):
        e = self.entries.get((str(kernel), key_str(key)))
        return None if e is None else e["variant"]

    def merge(self, other):
        """Fold another table's entries in (same-key entries from
        ``other`` win — it is the newer measurement)."""
        self.entries.update(other.entries)
        return self

    def to_doc(self):
        return {
            "schema": "pdtpu-tune-table-v1",
            "fingerprint": dict(self.fingerprint),
            "entries": [
                {"kernel": k, "key": json.loads(ks),
                 "variant": e["variant"],
                 "timings_ms": dict(e["timings_ms"])}
                for (k, ks), e in sorted(self.entries.items())],
        }

    @classmethod
    def from_doc(cls, doc):
        """Strict schema validation — any violation raises ValueError
        (the store's ``deserialize`` reject)."""
        if not isinstance(doc, dict) \
                or doc.get("schema") != "pdtpu-tune-table-v1":
            raise ValueError("not a pdtpu-tune-table-v1 document")
        fp = doc.get("fingerprint")
        entries_doc = doc.get("entries")
        if not isinstance(fp, dict) or not isinstance(entries_doc, list):
            raise ValueError("malformed tuning-table document")
        table = cls(fingerprint=fp)
        for e in entries_doc:
            if not isinstance(e, dict) \
                    or not isinstance(e.get("kernel"), str) \
                    or not isinstance(e.get("variant"), str) \
                    or not isinstance(e.get("key"), list):
                raise ValueError("malformed tuning-table entry")
            timings = e.get("timings_ms", {})
            if not isinstance(timings, dict):
                raise ValueError("malformed tuning-table timings")
            table.entries[(e["kernel"],
                           json.dumps(e["key"], separators=(",", ":")))] \
                = {"variant": e["variant"],
                   "timings_ms": {str(k): float(v)
                                  for k, v in timings.items()}}
        return table

    def digest(self):
        """Content identity of the whole table (the
        ``kernel_autotune_digest`` flag value while attached)."""
        return hashlib.sha256(
            json.dumps(self.to_doc(), sort_keys=True).encode()).hexdigest()


class TuneStore:
    """One directory of tuning-table artifacts, execcache-disciplined:
    content-addressed envelope, identity in the filename, typed bounded
    rejects, optional manifest pinning, tmp+replace writes. ``load``
    and ``save`` never raise — a broken table must only ever cost the
    static routing it failed to replace."""

    def __init__(self, path, readonly=False, expected_digests=None):
        self.path = str(path)
        self.readonly = bool(readonly)
        self._expected = None if expected_digests is None \
            else dict(expected_digests)
        if not self.readonly:
            os.makedirs(self.path, exist_ok=True)
        self._touched = set()

    def artifact_path(self, fp=None):
        fp = fp if fp is not None else table_fingerprint()
        return os.path.join(
            self.path, f"table-{fingerprint_key(fp)[:40]}{ARTIFACT_SUFFIX}")

    def note_reject(self, reason, error=None):
        from ..obs.recorder import record as _flight_record

        if reason not in REJECT_REASONS:
            reason = "deserialize"
        _M_REJECTS.labels(reason=reason).inc()
        _flight_record("kernel_autotune_reject", component="ops.autotune",
                       dir=self.path, reason=reason,
                       error=None if error is None
                       else f"{type(error).__name__}: {error}")

    def load(self, fp=None):
        """The table for this process's identity, or None (miss or
        typed reject — the caller keeps static routing). A missing file
        is a silent miss; everything else wrong is a counted reject."""
        fp = fp if fp is not None else table_fingerprint()
        path = self.artifact_path(fp)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        stage = "format"
        try:
            if self._expected is not None:
                # manifest pinning: raw bytes must be exactly what the
                # version manifest certifies, BEFORE any parsing
                stage = "manifest"
                want = self._expected.get(os.path.basename(path))
                if want is None:
                    raise ValueError("artifact is not listed in the "
                                     "version manifest's tune_files")
                if hashlib.sha256(raw).hexdigest() != want:
                    raise ValueError("artifact bytes do not match the "
                                     "manifest's tune_files digest")
                stage = "format"
            if not raw.startswith(_MAGIC):
                raise ValueError("bad magic (not a tuning-table artifact)")
            header_end = raw.index(b"\n", len(_MAGIC))
            digest = raw[len(_MAGIC):header_end].decode("ascii")
            blob = raw[header_end + 1:]
            if hashlib.sha256(blob).hexdigest() != digest:
                raise ValueError("payload digest mismatch (truncated or "
                                 "bit-flipped artifact)")
            stage = "deserialize"
            table = TuneTable.from_doc(json.loads(blob.decode("utf-8")))
            stage = "fingerprint"
            if table.fingerprint != fp:
                raise ValueError("table fingerprint does not match this "
                                 "process's identity")
        except Exception as e:
            self.note_reject(stage, error=e)
            return None
        self._touched.add(os.path.basename(path))
        return table

    def save(self, table):
        """Persist one table (tmp + ``os.replace``); returns the
        artifact path, or None when read-only / unwritable."""
        if self.readonly:
            return None
        from ..obs.recorder import record as _flight_record

        try:
            blob = json.dumps(table.to_doc(), sort_keys=True).encode()
            data = (_MAGIC + hashlib.sha256(blob).hexdigest().encode()
                    + b"\n" + blob)
            path = self.artifact_path(table.fingerprint)
            tmp = path + f".{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except Exception as e:
            _flight_record("kernel_autotune_save_failed",
                           component="ops.autotune", dir=self.path,
                           error=f"{type(e).__name__}: {e}")
            return None
        self._touched.add(os.path.basename(path))
        return path

    def touched(self):
        return sorted(self._touched)


# ---------------------------------------------------------------------------
# attach / resolve (the active-table plumbing engines use)
# ---------------------------------------------------------------------------

def _refresh_selected_gauge():
    _M_SELECTED.reset()
    if _ACTIVE is None:
        return
    counts = {}
    for (kernel, _ks), e in _ACTIVE.entries.items():
        pair = (kernel, e["variant"])
        counts[pair] = counts.get(pair, 0) + 1
    for (kernel, variant), n in counts.items():
        _M_SELECTED.labels(kernel=kernel, variant=variant).set(n)


def attach_table(table, merge=True):
    """Make ``table`` the process-wide routing table and key every
    retrace + execcache fingerprint on its digest (the
    ``kernel_autotune_digest`` flag). ``merge=True`` folds it into an
    already-attached table (entries are shape-keyed and
    model-independent, so two bundles' tables coexist). Returns the
    active digest."""
    global _ACTIVE
    with _LOCK:
        if merge and _ACTIVE is not None:
            table = TuneTable(fingerprint=table.fingerprint,
                              entries=_ACTIVE.entries).merge(table)
        _ACTIVE = table
        digest = table.digest()
        _refresh_selected_gauge()
    set_flags({"kernel_autotune_digest": digest})
    return digest


def detach_table():
    """Drop the active table: routing reverts to static AUTO_PALLAS and
    the digest flag clears (flags-version bump -> retrace)."""
    global _ACTIVE
    with _LOCK:
        _ACTIVE = None
        _refresh_selected_gauge()
    set_flags({"kernel_autotune_digest": ""})


def active_table():
    return _ACTIVE


def active_digest():
    """Digest of the attached table, or None — what bench records stamp
    as ``tune_digest`` and engine stats surface."""
    with _LOCK:
        return None if _ACTIVE is None else _ACTIVE.digest()


def manifest_tune_digests(model_dir):
    """basename -> sha256 pin set from the version manifest's
    ``tune_files``. Manifest without the field pins the empty set (an
    uncertified tune dir next to a manifest loads nothing); no readable
    manifest returns None (not a registry version — the artifact
    self-digest is the only integrity layer)."""
    try:
        with open(os.path.join(model_dir, "VERSION.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    return {os.path.basename(rel): digest
            for rel, digest in manifest.get("tune_files", {}).items()}


def resolve_store(model_dir=None):
    """The store an engine should read its table from: the bundle's
    published ``tune/`` dir (read-only, manifest-pinned) when it
    exists, else the ``kernel_autotune_dir`` flag's local dir, else
    None — the execcache ``resolve_cache`` precedence."""
    if model_dir:
        tdir = os.path.join(str(model_dir), TUNE_DIRNAME)
        if os.path.isdir(tdir):
            return TuneStore(tdir, readonly=True,
                             expected_digests=manifest_tune_digests(
                                 str(model_dir)))
    local = get_flag("kernel_autotune_dir")
    if local and os.path.isdir(local):
        return TuneStore(local, readonly=True)
    return None


def attach_for_bundle(model_dir=None):
    """Engine-warmup hook: resolve + load + attach the bundle's table
    BEFORE any executable is compiled or acquired, so the digest flag
    is already in the jit key and every execcache fingerprint. No-op
    (returns None) unless ``kernel_tier=auto`` with ``kernel_autotune``
    on and a loadable table exists; corruption downgrades to static
    routing via the store's typed rejects — never a raise."""
    if not get_flag("kernel_autotune") or get_flag("kernel_tier") != "auto":
        return None
    store = resolve_store(model_dir)
    if store is None:
        return None
    table = store.load()
    if table is None:
        return None
    return attach_table(table)


# ---------------------------------------------------------------------------
# the tuner
# ---------------------------------------------------------------------------

class Tuner:
    """Measure captured dispatch keys and record the winners.

    ``repeats``/``inner`` are the interleaved best-of-N window shape
    (see :func:`measure`). bf16-flagged variants join the candidate set
    only under the ``kernel_autotune_bf16`` opt-in — a value-changing
    variant must be chosen, never stumbled into."""

    def __init__(self, repeats=3, inner=2, registry=None):
        self.repeats = int(repeats)
        self.inner = int(inner)
        self.registry = registry or VARIANTS

    def tune(self, captured, table=None):
        """-> :class:`TuneTable` with one entry per distinct
        (kernel, key) in ``captured`` (the :func:`capture` output).
        Single-candidate keys record their only routing without
        timings; multi-candidate keys are measured."""
        table = table if table is not None else TuneTable()
        seen = set()
        for kernel, key, supported_names in captured:
            ks = (kernel, key_str(key))
            if ks in seen:
                continue
            seen.add(ks)
            specs = self.registry.variants(kernel)
            cands = [n for n in supported_names
                     if n in specs
                     and variant_allowed(kernel, n, self.registry)]
            if not cands:
                continue
            winner, timings = cands[0], {}
            if len(cands) > 1:
                runners = {}
                for n in cands:
                    try:
                        r = specs[n].build(key)
                    except Exception:
                        r = None
                    if r is not None:
                        runners[n] = r
                if len(runners) > 1:
                    timings = measure(runners, repeats=self.repeats,
                                      inner=self.inner)
                if timings:
                    winner = min(timings, key=timings.get)
                elif "jnp" in cands:
                    winner = "jnp"
            table.set(kernel, key, winner, timings)
            _M_TUNES.labels(kernel=kernel).inc()
        return table


# ---------------------------------------------------------------------------
# variant registrations — runner builders synthesize inputs from keys
# ---------------------------------------------------------------------------

def _fields(key):
    return dict(key)


def _rng_fill(shape, dtype, seed):
    import numpy as np

    rng = np.random.RandomState(seed)
    return rng.standard_normal(size=shape).astype(dtype)


def _conv_bn_build(variant):
    def build(key):
        import jax
        import jax.numpy as jnp

        k = _fields(key)
        dtype = k["dtype"]
        x = jnp.asarray(_rng_fill(k["x"], dtype, 11))
        w = jnp.asarray(_rng_fill(k["w"], dtype, 13))
        cout = int(k["w"][0])
        scale = jnp.ones((cout,), jnp.float32)
        bias = jnp.zeros((cout,), jnp.float32)
        rm = jnp.zeros((cout,), jnp.float32)
        rv = jnp.ones((cout,), jnp.float32)
        strides, paddings = k["strides"], k["paddings"]
        act, is_test = k["act"], bool(k["is_test"])
        eps = 1e-5
        if variant == "jnp":
            from .conv_ops import _conv2d_compute
            from .norm_ops import bn_forward_math

            def f(x, w, scale, bias, rm, rv):
                z = _conv2d_compute(x, w, strides, paddings,
                                    k["dilations"], k["groups"], k["df"])
                y = bn_forward_math(z, scale, bias, rm, rv, eps, 0.9,
                                    k["df"], is_test)[0]
                return jnp.maximum(y, 0) if act == "relu" else y
            fn = jax.jit(f)
            return lambda: fn(x, w, scale, bias, rm, rv)
        from .pallas import conv_bn as cbk
        block_n = 2 if variant == "pallas_db" else 1
        if variant == "pallas_bf16":
            x = x.astype(jnp.bfloat16)
            w = w.astype(jnp.bfloat16)
        if is_test:
            def f(x, w, a, b):
                return cbk.conv_affine_pallas(x, w, a, b, strides,
                                              paddings, act,
                                              block_n=block_n)
            fn = jax.jit(f)
            return lambda: fn(x, w, scale, bias)
        def f(x, w, scale, bias):
            return cbk.conv_bn_train_pallas(x, w, scale, bias, eps,
                                            strides, paddings, act,
                                            block_n=block_n)
        fn = jax.jit(f)
        return lambda: fn(x, w, scale, bias)
    return build


def _paged_attention_build(variant):
    def build(key):
        import jax
        import jax.numpy as jnp
        import numpy as np

        k = _fields(key)
        s, h, d = (int(v) for v in k["q"])
        nb, bs = int(k["kc"][0]), int(k["kc"][1])
        p = int(k["tables"])
        qh = jnp.asarray(_rng_fill((s, h, d), k["dtype"], 17))
        kc = jnp.asarray(_rng_fill((nb, bs, h, d), k["dtype"], 19))
        vc = jnp.asarray(_rng_fill((nb, bs, h, d), k["dtype"], 23))
        bt = jnp.asarray((np.arange(s * p) % nb).reshape(s, p)
                         .astype(np.int32))
        ctx = jnp.full((s,), min(p * bs, nb * bs), jnp.int32)
        from .pallas import paged_attention as pa
        fn = jax.jit(pa.paged_attention_pallas if variant == "pallas"
                     else pa.paged_attention_jnp)
        return lambda: fn(qh, kc, vc, bt, ctx)
    return build


def _rnn_build(variant):
    def build(key):
        import jax
        import jax.numpy as jnp

        k = _fields(key)
        cell = k["cell"]
        b, L, hx = (int(v) for v in k["x"])
        H = hx // (4 if cell == "lstm" else 3)
        dtype = k["dtype"]
        x = jnp.asarray(_rng_fill((b, L, hx), dtype, 29)) * 0.1
        w = jnp.asarray(_rng_fill((H, hx), dtype, 31)) * 0.1
        lens = jnp.full((b,), L, jnp.int32)
        from . import rnn_ops
        if cell == "lstm":
            h0 = jnp.zeros((b, H), x.dtype)
            c0 = jnp.zeros((b, H), x.dtype)
            fn = jax.jit(lambda x, lens, w, h0, c0: rnn_ops._lstm_scan(
                x, lens, w, h0, c0, "sigmoid", "tanh", "tanh"))
            args = (x, lens, w, h0, c0)
        else:
            fn = jax.jit(lambda x, lens, w: rnn_ops._gru_compute(
                x, lens, w, None, None, {}))
            args = (x, lens, w)

        def run():
            # re-enter the force context every call: the first call
            # traces INSIDE it (pinning the variant into the jaxpr),
            # later calls are cache hits
            with force_variant("rnn", variant):
                return fn(*args)
        return run
    return build


def _embedding_build(variant):
    def build(key):
        import jax
        import jax.numpy as jnp
        import numpy as np

        k = _fields(key)
        rows, dim, nnz = int(k["rows"]), int(k["dim"]), int(k["nnz"])
        p = jnp.asarray(_rng_fill((rows, dim), k["dtype"], 37))
        vals = jnp.asarray(_rng_fill((nnz, dim), k["dtype"], 41))
        # Knuth-hash row ids: spread like real minibatch ids
        idx = jnp.asarray(((np.arange(nnz) * 2654435761) % rows)
                          .astype(np.int32))
        lr = jnp.asarray(0.01, p.dtype)
        if variant == "pallas":
            from .pallas.embedding import embedding_sgd_pallas
            fn = jax.jit(embedding_sgd_pallas)
            return lambda: fn(p, idx, vals, lr)
        fn = jax.jit(lambda p, r, v, lr: p.at[r].add(-lr * v, mode="drop"))
        return lambda: fn(p, idx, vals, lr)
    return build


def _optimizer_build(variant):
    def build(key):
        import jax
        import jax.numpy as jnp

        k = _fields(key)
        kind, tensors, elems = k["kind"], int(k["tensors"]), int(k["elems"])
        per = max(1, elems // max(1, tensors))
        from .optimizer_ops import (_adam_dense, _momentum_dense,
                                    _sgd_dense)
        ps = [jnp.asarray(_rng_fill((per,), "float32", 43 + i))
              for i in range(tensors)]
        gs = [jnp.asarray(_rng_fill((per,), "float32", 53 + i))
              for i in range(tensors)]
        ss = [jnp.asarray(_rng_fill((per,), "float32", 67 + i))
              for i in range(tensors)]
        s2 = [jnp.abs(jnp.asarray(_rng_fill((per,), "float32", 79 + i)))
              for i in range(tensors)]
        lr, mu = 0.01, 0.9
        if variant == "pallas":
            from .pallas import optimizer as opk

            def f(ps, gs, ss, s2):
                shapes = [p.shape for p in ps]
                if kind == "sgd":
                    arenas = [opk.flatten_arena(t)[0] for t in (ps, gs)]
                    results = (opk.sgd_arena_pallas(*arenas, lr),)
                elif kind == "momentum":
                    arenas = [opk.flatten_arena(t)[0]
                              for t in (ps, gs, ss)]
                    results = opk.momentum_arena_pallas(*arenas, lr, mu)
                else:
                    arenas = [opk.flatten_arena(t)[0]
                              for t in (ps, gs, ss, s2)]
                    results = opk.adam_arena_pallas(*arenas, lr, 0.9,
                                                    0.999, 1e-8)
                return [opk.split_arena(r, shapes) for r in results]
        else:
            def f(ps, gs, ss, s2):
                out = []
                for i in range(tensors):
                    if kind == "sgd":
                        out.append(_sgd_dense(ps[i], gs[i], lr))
                    elif kind == "momentum":
                        out.append(_momentum_dense(ps[i], gs[i], ss[i],
                                                   lr, mu, False))
                    else:
                        out.append(_adam_dense(ps[i], gs[i], ss[i],
                                               s2[i], lr, 0.9, 0.999,
                                               1e-8))
                return out
        fn = jax.jit(f)
        return lambda: fn(ps, gs, ss, s2)
    return build


VARIANTS.register("conv_bn", "jnp", _conv_bn_build("jnp"))
VARIANTS.register("conv_bn", "pallas", _conv_bn_build("pallas"))
VARIANTS.register("conv_bn", "pallas_db", _conv_bn_build("pallas_db"))
VARIANTS.register("conv_bn", "pallas_bf16", _conv_bn_build("pallas_bf16"),
                  bf16=True)
VARIANTS.register("paged_attention", "jnp", _paged_attention_build("jnp"))
VARIANTS.register("paged_attention", "pallas",
                  _paged_attention_build("pallas"))
# chunked prefill has one lowering today; registering it keeps its
# warmup shapes in tuned tables so a future pallas variant tunes in
# with zero dispatch-site changes
VARIANTS.register("chunked_prefill_attention", "jnp", lambda key: None)
VARIANTS.register("rnn", "jnp", _rnn_build("jnp"))
VARIANTS.register("rnn", "pallas", _rnn_build("pallas"))
VARIANTS.register("embedding", "jnp", _embedding_build("jnp"))
VARIANTS.register("embedding", "pallas", _embedding_build("pallas"))
VARIANTS.register("optimizer", "jnp", _optimizer_build("jnp"))
VARIANTS.register("optimizer", "pallas", _optimizer_build("pallas"))


__all__ = [
    "ARTIFACT_SUFFIX", "REJECT_REASONS", "TUNE_DIRNAME", "TuneStore",
    "TuneTable", "Tuner", "VARIANTS", "VariantRegistry", "active_digest",
    "active_table", "attach_for_bundle", "attach_table", "capture",
    "detach_table", "dispatch_variant", "fingerprint_key",
    "force_variant", "key_str", "make_key", "manifest_tune_digests",
    "measure", "resolve_store", "table_fingerprint", "variant_allowed",
]
