"""Loss & classification ops.

Reference: softmax_op.cc, cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, sigmoid_cross_entropy_with_logits_op.cc,
smooth_l1_loss_op.cc, squared_l2_distance_op.cc, hinge_loss_op.cc,
log_loss_op.cc, huber_loss_op.cc (/root/reference/paddle/fluid/operators/).

cross_entropy semantics follow the reference: labels are either int64 class
ids of shape [N, 1] (hard) or a float distribution [N, D] (soft_label attr).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.amp import upcast_f32
from ..core.registry import register_op, same_shape, OpSpec
from .common import G, data_of, like


@register_op("softmax", infer_shape=same_shape("X", "Out"), grad=lambda op: [OpSpec(
    "softmax_grad", {"Out": op.output("Out"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))})])
def softmax(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", like(x, jax.nn.softmax(data_of(x), axis=-1)))


@register_op("softmax_grad")
def softmax_grad(ctx):
    out = data_of(ctx.input("Out"))
    d = data_of(ctx.input("Out@GRAD"))
    dx = out * (d - jnp.sum(d * out, axis=-1, keepdims=True))
    ctx.set_output("X@GRAD", like(ctx.input("Out@GRAD"), dx))


def _take_label(x, label):
    """Pick per-row probability at int label; works for any leading rank
    (dense [N, V] and padded-LoD [b, L, V] layouts alike)."""
    lab = label.reshape(x.shape[:-1]).astype(jnp.int32)
    return jnp.take_along_axis(x, lab[..., None], axis=-1)


@register_op("cross_entropy", grad=lambda op: [OpSpec(
    "cross_entropy_grad",
    {"X": op.input("X"), "Label": op.input("Label"),
     "Y@GRAD": G(op.output("Y"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def cross_entropy(ctx):
    xv = ctx.input("X")
    x = upcast_f32(data_of(xv))
    label = data_of(ctx.input("Label"))
    eps = 1e-8
    if ctx.attr("soft_label", False):
        y = -jnp.sum(label * jnp.log(jnp.maximum(x, eps)), axis=-1, keepdims=True)
    else:
        y = -jnp.log(jnp.maximum(_take_label(x, label), eps))
    ctx.set_output("Y", like(xv, y))


@register_op("cross_entropy_grad")
def cross_entropy_grad(ctx):
    xv = ctx.input("X")
    x = data_of(xv)
    label = data_of(ctx.input("Label"))
    d = data_of(ctx.input("Y@GRAD"))
    eps = 1e-8
    if ctx.attr("soft_label", False):
        dx = -d * label / jnp.maximum(x, eps)
    else:
        onehot = jax.nn.one_hot(label.reshape(x.shape[:-1]).astype(jnp.int32),
                                x.shape[-1], dtype=x.dtype)
        dx = -d * onehot / jnp.maximum(x, eps)
    ctx.set_output("X@GRAD", like(xv, dx))


@register_op("softmax_with_cross_entropy", grad=lambda op: [OpSpec(
    "softmax_with_cross_entropy_grad",
    {"Softmax": op.output("Softmax"), "Label": op.input("Label"),
     "Loss@GRAD": G(op.output("Loss"))},
    {"Logits@GRAD": G(op.input("Logits"))}, dict(op.attrs))])
def softmax_with_cross_entropy(ctx):
    """Fused, numerically-stable version (reference
    softmax_with_cross_entropy_op.cc) — on TPU the fusion happens in XLA, but
    we keep the stable log-sum-exp formulation."""
    # float32 stability island: bf16 logits (AMP) are upcast before the LSE
    logits = upcast_f32(data_of(ctx.input("Logits")))
    label = data_of(ctx.input("Label"))
    lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
    log_probs = logits - lse
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(label * log_probs, axis=-1, keepdims=True)
    else:
        loss = -_take_label(log_probs, label)
    ctx.set_output("Softmax", jnp.exp(log_probs))
    ctx.set_output("Loss", loss)


@register_op("softmax_with_cross_entropy_grad")
def softmax_with_cross_entropy_grad(ctx):
    sm = data_of(ctx.input("Softmax"))
    label = data_of(ctx.input("Label"))
    d = data_of(ctx.input("Loss@GRAD"))
    if ctx.attr("soft_label", False):
        dlogits = d * (sm - label)
    else:
        onehot = jax.nn.one_hot(label.reshape(-1).astype(jnp.int32),
                                sm.shape[-1], dtype=sm.dtype)
        dlogits = d * (sm - onehot)
    ctx.set_output("Logits@GRAD", dlogits)


@register_op("sigmoid_cross_entropy_with_logits",
             infer_shape=same_shape("X", "Out"),
             grad=lambda op: [OpSpec(
                 "sigmoid_cross_entropy_with_logits_grad",
                 {"X": op.input("X"), "Label": op.input("Label"),
                  "Out@GRAD": G(op.output("Out"))},
                 {"X@GRAD": G(op.input("X"))})])
def sigmoid_cross_entropy_with_logits(ctx):
    x = data_of(ctx.input("X"))
    label = data_of(ctx.input("Label"))
    out = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ctx.set_output("Out", out)


@register_op("sigmoid_cross_entropy_with_logits_grad")
def sigmoid_cross_entropy_with_logits_grad(ctx):
    x = data_of(ctx.input("X"))
    label = data_of(ctx.input("Label"))
    d = data_of(ctx.input("Out@GRAD"))
    ctx.set_output("X@GRAD", d * (jax.nn.sigmoid(x) - label))


@register_op("squared_l2_distance", grad=lambda op: [OpSpec(
    "squared_l2_distance_grad",
    {"sub_result": op.output("sub_result"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X")), "Y@GRAD": G(op.input("Y"))})])
def squared_l2_distance(ctx):
    x = data_of(ctx.input("X"))
    y = data_of(ctx.input("Y"))
    sub = x - y
    ctx.set_output("sub_result", sub)
    ctx.set_output("Out", jnp.sum(jnp.square(sub), axis=-1, keepdims=True))


@register_op("squared_l2_distance_grad")
def squared_l2_distance_grad(ctx):
    sub = data_of(ctx.input("sub_result"))
    d = data_of(ctx.input("Out@GRAD"))
    g = 2.0 * d * sub
    ctx.set_output("X@GRAD", g)
    # Y may be a broadcast single row (reference squared_l2_distance_op.h)
    dy = -g
    ynames = ctx.op.output("Y@GRAD")
    if ynames and ctx.block.has_var(ynames[0]):
        yshape = ctx.block.var(ynames[0]).shape
        if yshape and yshape[0] == 1 and g.shape[0] != 1:
            dy = -jnp.sum(g, axis=0, keepdims=True)
    ctx.set_output("Y@GRAD", dy)


@register_op("smooth_l1_loss", grad=lambda op: [OpSpec(
    "smooth_l1_loss_grad",
    {"Diff": op.output("Diff"), "Out@GRAD": G(op.output("Out")),
     **({"InsideWeight": op.input("InsideWeight")}
        if op.input("InsideWeight") else {}),
     **({"OutsideWeight": op.input("OutsideWeight")}
        if op.input("OutsideWeight") else {})},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def smooth_l1_loss(ctx):
    """smooth_l1_loss_op.h: InsideWeight gates the diff, OutsideWeight
    scales the per-element loss before the row sum (the SSD positive
    mask)."""
    x = data_of(ctx.input("X"))
    y = data_of(ctx.input("Y"))
    sigma2 = ctx.attr("sigma", 1.0) ** 2
    diff = x - y
    if ctx.has_input("InsideWeight"):
        diff = diff * data_of(ctx.input("InsideWeight"))
    ad = jnp.abs(diff)
    val = jnp.where(ad < 1.0 / sigma2, 0.5 * sigma2 * diff * diff,
                    ad - 0.5 / sigma2)
    if ctx.has_input("OutsideWeight"):
        val = val * data_of(ctx.input("OutsideWeight"))
    ctx.set_output("Diff", diff)
    ctx.set_output("Out", jnp.sum(val, axis=tuple(range(1, x.ndim)),
                                  keepdims=False).reshape(-1, 1))


@register_op("smooth_l1_loss_grad")
def smooth_l1_loss_grad(ctx):
    diff = data_of(ctx.input("Diff"))
    d = data_of(ctx.input("Out@GRAD")).reshape((-1,) + (1,) * (diff.ndim - 1))
    sigma2 = ctx.attr("sigma", 1.0) ** 2
    g = jnp.where(jnp.abs(diff) < 1.0 / sigma2, sigma2 * diff, jnp.sign(diff))
    if ctx.has_input("OutsideWeight"):
        g = g * data_of(ctx.input("OutsideWeight"))
    if ctx.has_input("InsideWeight"):
        g = g * data_of(ctx.input("InsideWeight"))
    ctx.set_output("X@GRAD", d * g)


@register_op("log_loss", infer_shape=same_shape("Predicted", "Loss"),
             grad=lambda op: [OpSpec(
                 "log_loss_grad",
                 {"Predicted": op.input("Predicted"), "Labels": op.input("Labels"),
                  "Loss@GRAD": G(op.output("Loss"))},
                 {"Predicted@GRAD": G(op.input("Predicted"))}, dict(op.attrs))])
def log_loss(ctx):
    p = data_of(ctx.input("Predicted"))
    y = data_of(ctx.input("Labels"))
    eps = ctx.attr("epsilon", 1e-4)
    ctx.set_output("Loss", -y * jnp.log(p + eps) - (1 - y) * jnp.log(1 - p + eps))


@register_op("log_loss_grad")
def log_loss_grad(ctx):
    p = data_of(ctx.input("Predicted"))
    y = data_of(ctx.input("Labels"))
    d = data_of(ctx.input("Loss@GRAD"))
    eps = ctx.attr("epsilon", 1e-4)
    ctx.set_output("Predicted@GRAD", d * (-y / (p + eps) + (1 - y) / (1 - p + eps)))


@register_op("hinge_loss", infer_shape=same_shape("Logits", "Loss"),
             grad=lambda op: [OpSpec(
                 "hinge_loss_grad",
                 {"Logits": op.input("Logits"), "Labels": op.input("Labels"),
                  "Loss@GRAD": G(op.output("Loss"))},
                 {"Logits@GRAD": G(op.input("Logits"))})])
def hinge_loss(ctx):
    x = data_of(ctx.input("Logits"))
    y = data_of(ctx.input("Labels"))
    ctx.set_output("Loss", jnp.maximum(1.0 - (2.0 * y - 1.0) * x, 0.0))


@register_op("hinge_loss_grad")
def hinge_loss_grad(ctx):
    x = data_of(ctx.input("Logits"))
    y = data_of(ctx.input("Labels"))
    d = data_of(ctx.input("Loss@GRAD"))
    alt = 2.0 * y - 1.0
    ctx.set_output("Logits@GRAD", d * jnp.where(1.0 - alt * x > 0, -alt, 0.0))


@register_op("huber_loss", grad=lambda op: [OpSpec(
    "huber_loss_grad",
    {"Residual": op.output("Residual"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X")), "Y@GRAD": G(op.input("Y"))}, dict(op.attrs))])
def huber_loss(ctx):
    x = data_of(ctx.input("X"))
    y = data_of(ctx.input("Y"))
    delta = ctx.attr("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    out = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    ctx.set_output("Residual", r)
    ctx.set_output("Out", out)


@register_op("huber_loss_grad")
def huber_loss_grad(ctx):
    r = data_of(ctx.input("Residual"))
    d = data_of(ctx.input("Out@GRAD"))
    delta = ctx.attr("delta", 1.0)
    g = jnp.where(jnp.abs(r) <= delta, r, delta * jnp.sign(r))
    ctx.set_output("X@GRAD", -d * g)
    ctx.set_output("Y@GRAD", d * g)


# ---------------------------------------------------------------------------
# hsigmoid (legacy gserver HierarchicalSigmoidLayer; math/MatrixBitCode.cpp
# SimpleCode: c = label + num_classes, node(b) = (c >> (b+1)) - 1,
# bit(b) = (c >> b) & 1, cost = sum_b softplus(z_b) - bit_b * z_b)
# ---------------------------------------------------------------------------

def _hsigmoid_compute(x, w, bias, label, num_classes):
    c = label.reshape(-1).astype(jnp.int32) + num_classes
    max_len = int(num_classes - 1).bit_length()
    cost = jnp.zeros((x.shape[0],), jnp.float32)
    xf = x.astype(jnp.float32)
    for b in range(max_len):
        parent = (c >> (b + 1))
        valid = (parent >= 1).astype(jnp.float32)
        idx = jnp.maximum(parent - 1, 0)
        bit = ((c >> b) & 1).astype(jnp.float32)
        z = jnp.sum(xf * w[idx].astype(jnp.float32), axis=-1)
        if bias is not None:
            z = z + bias.reshape(-1)[idx].astype(jnp.float32)
        cost = cost + valid * (jax.nn.softplus(z) - bit * z)
    return cost.reshape(-1, 1)


@register_op("hsigmoid", grad=lambda op: [OpSpec(
    "hsigmoid_grad",
    {"X": op.input("X"), "W": op.input("W"), "Label": op.input("Label"),
     **({"Bias": op.input("Bias")} if op.input("Bias") else {}),
     "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X")), "W@GRAD": G(op.input("W")),
     **({"Bias@GRAD": G(op.input("Bias"))} if op.input("Bias") else {})},
    dict(op.attrs))])
def hsigmoid(ctx):
    """Hierarchical sigmoid cost over the complete-binary-tree SimpleCode
    (reference HierarchicalSigmoidLayer.cpp:127 sumByBitCode +
    MatrixBitCode.cpp)."""
    x = data_of(ctx.input("X"))
    w = data_of(ctx.input("W"))
    label = data_of(ctx.input("Label"))
    bias = data_of(ctx.input("Bias")) if ctx.has_input("Bias") else None
    ctx.set_output("Out", _hsigmoid_compute(
        x, w, bias, label, int(ctx.attr("num_classes"))))


@register_op("hsigmoid_grad")
def hsigmoid_grad(ctx):
    x = data_of(ctx.input("X"))
    w = data_of(ctx.input("W"))
    label = data_of(ctx.input("Label"))
    has_bias = ctx.has_input("Bias")
    bias = data_of(ctx.input("Bias")) if has_bias else None
    d = data_of(ctx.input("Out@GRAD"))
    n = int(ctx.attr("num_classes"))
    args = (x, w) + ((bias,) if has_bias else ())

    def f(*a):
        xx, ww = a[0], a[1]
        bb = a[2] if has_bias else None
        return _hsigmoid_compute(xx, ww, bb, label, n)

    out, vjp = jax.vjp(f, *args)
    grads = vjp(d.astype(out.dtype).reshape(out.shape))
    ctx.set_output("X@GRAD", grads[0])
    ctx.set_output("W@GRAD", grads[1])
    if has_bias:
        ctx.set_output("Bias@GRAD", grads[2])
