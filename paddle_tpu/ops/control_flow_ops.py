"""Control-flow ops: while, recurrent (StaticRNN/DynamicRNN), TensorArray,
conditional_block, beam_search, beam_search_decode.

Reference: /root/reference/paddle/fluid/operators/while_op.cc (scope-mutating
loop over a sub-block), recurrent_op.cc:39-103 (StepScopes per timestep),
tensor_array_read_write ops, conditional_block_op.cc, beam_search_op.h:96-193,
beam_search_decode_op.cc, and the lod_rank_table/shrink_rnn_memory DynamicRNN
machinery (lod_rank_table_op.cc, shrink_rnn_memory_op.cc).

TPU-native re-design (SURVEY.md §7 hard part b): the reference mutates step
scopes imperatively; under XLA everything must functionalize:

* TensorArray (the reference's LoDTensorArray) becomes ``TensorArrayVal`` — a
  PRE-ALLOCATED [cap, ...] device buffer plus a length counter, a pytree that
  crosses jit/scan/while_loop. Writes are dynamic_update_slice at a traced
  index. Arrays carried through a while loop must receive one write before
  the loop so their shape is known (the reference's decoders all do this).
* ``while`` lowers to ONE ``lax.while_loop`` whose carry is exactly the set
  of block-written variables that pre-exist outside, plus the condition.
* ``recurrent``/``dynamic_recurrent`` (StaticRNN/DynamicRNN) lower to ONE
  ``lax.scan`` over the time axis. DynamicRNN replaces the reference's
  lod_rank_table + shrink_rnn_memory batch-shrinking (a GPU-efficiency
  reordering) with per-row aliveness masking over the padded LoD batch — the
  TPU equivalent with identical semantics on the valid region.
* ``conditional_block`` runs its block and select()s outputs against the
  previous bindings — XLA computes both sides, cond picks (scalar guards
  like LR schedules and Switch cases).
* ``beam_search`` works on DENSE [batch, beam] state (scores accumulated in
  log space, finished beams frozen at end_id) instead of the reference's
  2-level-LoD layout; ``beam_search_decode`` backtracks stored parent
  pointers into a LoDArray of [batch*beam] ragged token sequences.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op, OpSpec
from .common import G, data_of


@jax.tree_util.register_pytree_node_class
class TensorArrayVal:
    """Pre-allocated tensor array: data [cap, ...], length scalar int32."""

    __slots__ = ("data", "length")

    def __init__(self, data, length):
        self.data = data
        self.length = length

    def tree_flatten(self):
        return (self.data, self.length), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def cap(self):
        return self.data.shape[0]

    def __repr__(self):
        return (f"TensorArrayVal(cap={getattr(self.data, 'shape', None)}, "
                f"length={self.length})")


class EmptyTensorArray:
    """Build-time placeholder until the first write fixes the element shape."""

    def __init__(self, cap):
        self.cap = cap


def _as_scalar_i32(v):
    return data_of(v).reshape(()).astype(jnp.int32)


def _write_to_array_grad_maker(op):
    """Backward of the in-place array write: the element grad is the array
    grad's slot i; the array grad loses slot i (overwrite — the array name
    is rebound in place, like while's carried state). This is what lets
    parameters STAGED through tensor arrays into a While loop train
    (reference write_to_array's grad in backward.py sub-block handling)."""
    return [OpSpec(
        "write_to_array_grad",
        {"I": op.input("I"), "Out@GRAD": G(op.output("Out"))},
        {"X@GRAD": G(op.input("X")),
         "Array@GRAD": G(op.input("Array")) if op.input("Array") else []},
        dict(op.attrs),
        overwrite_slots=frozenset({"Array@GRAD"}))]


@register_op("write_to_array", grad=_write_to_array_grad_maker)
def write_to_array(ctx):
    x = ctx.input("X")
    xd = x.data if isinstance(x, LoDArray) else data_of(x)
    i = _as_scalar_i32(ctx.input("I"))
    # read-modify-write: the array var is both input "Array" and output "Out"
    # (the reference write_to_array aliases them); first write allocates the
    # [cap, ...] buffer from the element's shape
    arr = ctx.input("Array") if ctx.has_input("Array") else None
    if arr is None or isinstance(arr, EmptyTensorArray):
        cap = arr.cap if arr is not None else ctx.attr("cap", 64)
        data = jnp.zeros((cap,) + xd.shape, xd.dtype)
        length = jnp.zeros((), jnp.int32)
        arr = TensorArrayVal(data, length)
    new_data = jax.lax.dynamic_update_index_in_dim(arr.data, xd.astype(
        arr.data.dtype), i, axis=0)
    new_len = jnp.maximum(arr.length, i + 1)
    ctx.set_output("Out", TensorArrayVal(new_data, new_len))


@register_op("write_to_array_grad")
def write_to_array_grad(ctx):
    g = ctx.input("Out@GRAD")          # TensorArrayVal-shaped grad
    i = _as_scalar_i32(ctx.input("I"))
    ctx.set_output("X@GRAD", jax.lax.dynamic_index_in_dim(
        g.data, i, axis=0, keepdims=False))
    if ctx.op.output("Array@GRAD"):
        zero_slot = jnp.zeros(g.data.shape[1:], g.data.dtype)
        ctx.set_output("Array@GRAD", TensorArrayVal(
            jax.lax.dynamic_update_index_in_dim(g.data, zero_slot, i,
                                                axis=0), g.length))


@register_op("read_from_array", grad=lambda op: [OpSpec(
    "read_from_array_grad",
    {"X": op.input("X"), "I": op.input("I"),
     "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))})])
def read_from_array(ctx):
    arr = ctx.input("X")
    i = _as_scalar_i32(ctx.input("I"))
    ctx.set_output("Out", jax.lax.dynamic_index_in_dim(arr.data, i, axis=0,
                                                       keepdims=False))


@register_op("read_from_array_grad")
def read_from_array_grad(ctx):
    arr = ctx.input("X")
    i = _as_scalar_i32(ctx.input("I"))
    dy = data_of(ctx.input("Out@GRAD"))
    zeros = jnp.zeros_like(arr.data)
    ctx.set_output("X@GRAD", TensorArrayVal(
        jax.lax.dynamic_update_index_in_dim(zeros, dy.astype(zeros.dtype),
                                            i, axis=0), arr.length))


@register_op("array_length")
def array_length(ctx):
    arr = ctx.input("X")
    ctx.set_output("Out", arr.length.reshape(1).astype(jnp.int64)
                   if hasattr(arr.length, "reshape")
                   else jnp.asarray([arr.length], jnp.int64))


@register_op("max_sequence_len")
def max_sequence_len(ctx):
    """Max length of a LoD input (max_sequence_len over the rank table in the
    reference; here directly over lens)."""
    x = ctx.input("RankTable")
    lens = x.lens if isinstance(x, LoDArray) else data_of(x)
    ctx.set_output("Out", jnp.max(lens).reshape(1).astype(jnp.int64))


# ---------------------------------------------------------------------------
# while
# ---------------------------------------------------------------------------

def _block_written(block):
    """Names written by the block, recursing into nested control-flow
    sub-blocks (a nested While/Switch writing an outer var must still appear
    in the enclosing loop's carry)."""
    from ..core.block_walk import written_names
    return written_names(block.program, block.idx)


def _const_producer_value(name, blocks):
    """The fill_constant value that produced ``name`` in any of ``blocks``
    (None when the var is not a build-time constant)."""
    for b in blocks:
        for o in b.ops:
            if name in o.output("Out") and o.type == "fill_constant":
                return float(o.attrs.get("value", 0.0))
    return None


def _derive_while_bound(op):
    """Static trip-count bound for a While without explicit max_iters —
    the analog of the reference's unbounded while_grad (while_op.cc:35),
    which can interpret its backward block for however many steps ran; a
    reverse scan needs a static length, so derive one from the canonical
    counter loop the reference's own decoders build
    (layers/control_flow.py:607 While + increment + less_than):

        i = fill_constant(C0);  limit = fill_constant(V)
        while less_than(i, limit):  ...;  i = increment(i, S)

    Returns ceil((V - C0)/S) (+1 for less_equal) — over-estimating is
    harmless because the scan body is masked once the condition goes false
    (_while_scan). Returns None when the pattern doesn't match (dynamic
    limit), in which case the caller raises the explicit-bound error."""
    block = op.block
    program = block.program
    sub = program.blocks[op.attrs["sub_block"]]
    cond_name = op.input("Condition")[0]

    cmp_op = None
    for o in list(sub.ops) + list(block.ops):
        if cond_name in o.output("Out") and o.type in ("less_than",
                                                       "less_equal"):
            cmp_op = o
    if cmp_op is None:
        return None
    counter = cmp_op.input("X")[0]
    limit = cmp_op.input("Y")[0]

    v = _const_producer_value(limit, [block])
    c0 = _const_producer_value(counter, [block])
    if v is None or c0 is None:
        return None
    step = None
    for o in sub.ops:
        if o.type == "increment" and counter in o.output("Out"):
            step = float(o.attrs.get("step", 1.0))
    if not step or step <= 0:
        return None
    import math
    bound = int(math.ceil((v - c0) / step))
    if cmp_op.type == "less_equal":
        bound += 1
    return max(bound, 1)


def _while_grad_maker(op):
    """while_grad consumes the pre-loop state snapshots + post-loop output
    grads and produces (a) grads for the free weights read by the body and
    (b) grads w.r.t. the PRE-loop carried state, which OVERWRITE the carried
    names' post-loop cotangents — ops before the loop that produced the
    inits must see d/d(pre-loop value), not d/d(post-loop value). Requires a
    max_iters bound so the loop is a reverse-differentiable masked lax.scan
    (the reference's WhileGrad, while_op.cc:35, interprets a generated
    backward block instead); when absent, a bound is derived from the
    counter/limit pattern (_derive_while_bound)."""
    attrs = dict(op.attrs)
    if attrs.get("max_iters") is None:
        attrs["max_iters"] = _derive_while_bound(op)
    if attrs.get("max_iters") is None:
        raise RuntimeError(
            "while op lies on a gradient path, has no max_iters bound, and "
            "no static bound could be derived from its condition (expected "
            "the counter pattern: fill_constant init, less_than/less_equal "
            "against a fill_constant limit, increment in the body); build "
            "it as fluid.layers.While(cond, max_iters=N) to train through "
            "it (lax.while_loop itself is not reverse-differentiable)")
    diff = op.attrs.get("diff_vars", [])
    carried = op.attrs.get("carried", [])
    return [OpSpec(
        "while_grad",
        {"Condition": op.input("Condition"), "Carried": op.input("Carried"),
         "FreeVars": op.input("FreeVars"), "PreLoop": op.output("PreLoop"),
         "OutGrads": G(op.output("Out"))},
        {"DiffGrads": G(diff), "CarriedGrads": G(carried)},
        attrs,
        overwrite_slots=frozenset({"CarriedGrads"}))]


def _while_scan(exec_state, sub, env_base, pre, carried, cond_name,
                max_iters):
    """The bounded-loop functional core: max_iters masked steps (state holds
    once the condition goes false). Used by BOTH the bounded forward and
    while_grad, so the gradient differentiates exactly the function that ran
    — a max_iters bound is a visible semantic of the loop, never a silent
    grad-only truncation."""
    from ..core.executor import _run_ops

    def body(carry, _):
        cond = data_of(carry[cond_name]).reshape(()).astype(jnp.bool_)
        local = dict(env_base)
        local.update(carry)
        _run_ops(sub, local, exec_state)
        new = {}
        for n in carried:
            new[n] = jax.tree_util.tree_map(
                lambda a, b: jnp.where(cond, a, b), local[n], carry[n])
        return new, None

    final, _ = jax.lax.scan(body, pre, None, length=max_iters)
    return final


@register_op("while", is_control_flow=True, grad=_while_grad_maker)
def while_op(ctx):
    """Loop over the sub-block (vs. the reference's interpreted scope-loop,
    while_op.cc). Carry = condition + every block-written var that already
    exists in the enclosing env (loop state); everything else the block
    writes is a per-iteration temporary. With a max_iters bound the loop is
    a masked lax.scan of exactly that many steps (differentiable; identical
    to the unbounded form whenever the trip count fits the bound); without
    one it is a lax.while_loop (forward-only). Pre-loop carried values are
    snapshotted into the declared PreLoop outputs for while_grad."""
    sub = ctx.sub_block("sub_block")
    cond_name = ctx.op.input("Condition")[0]
    env = ctx.env
    max_iters = ctx.attr("max_iters", None)

    written = _block_written(sub)
    carry_names = [n for n in written if n in env]
    if cond_name not in carry_names:
        carry_names.append(cond_name)

    init = {n: env[n] for n in carry_names}
    # snapshot pre-loop state under this op's unique PreLoop names
    for n, pname in zip(ctx.attr("carried", []), ctx.op.output("PreLoop")):
        if n in init:
            env[pname] = init[n]

    if max_iters is not None:
        final = _while_scan(ctx._exec, sub, env, init, carry_names,
                            cond_name, int(max_iters))
        env.update(final)
        return

    from ..core.executor import _run_ops

    def cond_fn(carry):
        return data_of(carry[cond_name]).reshape(()).astype(jnp.bool_)

    def body_fn(carry):
        local = dict(env)
        local.update(carry)
        _run_ops(sub, local, ctx._exec)
        return {n: local[n] for n in carry_names}

    final = jax.lax.while_loop(cond_fn, body_fn, init)
    env.update(final)


@register_op("while_grad", is_control_flow=True)
def while_grad(ctx):
    """Reverse-mode through the bounded loop: jax.vjp over the SAME masked
    scan the forward ran, w.r.t. both the free weights and the pre-loop
    carried state. CarriedGrads overwrite the carried names' grads (in-place
    loop-state contract, see _while_grad_maker)."""
    env = ctx.env
    attr = ctx.attr
    sub = ctx.sub_block("sub_block")
    cond_name = ctx.op.input("Condition")[0]
    carried = list(attr("carried", []))
    max_iters = int(attr("max_iters"))
    all_diff = list(attr("diff_vars", []))
    diff_names = [n for n in all_diff if _has_float_leaf(env[n])]

    from ..fluid.framework import grad_var_name

    preloop_names = dict(zip(carried, ctx.op.input("PreLoop")))
    pre = {n: env[preloop_names[n]] for n in carried
           if preloop_names[n] in env}
    carried = [n for n in carried if n in pre]
    # differentiable pre-loop state: float-leaf carried values
    pre_float = {n: v for n, v in pre.items()
                 if all(jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
                        for l in jax.tree_util.tree_leaves(v))}
    prim_w = {n: data_of(env[n]) for n in diff_names}

    def fwd(weights, pre_diff):
        base = dict(env)
        for n, v in weights.items():
            old = env[n]
            base[n] = LoDArray(v, old.lens) if isinstance(old, LoDArray) \
                else v
        start = dict(pre)
        start.update(pre_diff)
        return _while_scan(ctx._exec, sub, base, start, carried, cond_name,
                           max_iters)

    final, vjp = jax.vjp(fwd, prim_w, pre_float)

    import numpy as _np

    def ct_leaf(out_leaf, grad_leaf):
        if not jnp.issubdtype(out_leaf.dtype, jnp.floating):
            return _np.zeros(out_leaf.shape, jax.dtypes.float0)
        if grad_leaf is None:
            return jnp.zeros_like(out_leaf)
        return jnp.asarray(grad_leaf).astype(out_leaf.dtype).reshape(
            out_leaf.shape)

    cts = {}
    for n in carried:
        g = env.get(grad_var_name(n))
        out_v = final[n]
        out_leaves, treedef = jax.tree_util.tree_flatten(out_v)
        if g is None or len(out_leaves) != len(
                jax.tree_util.tree_leaves(g)):
            g_leaves = [None] * len(out_leaves)
        else:
            g_leaves = jax.tree_util.tree_leaves(g)
        cts[n] = jax.tree_util.tree_unflatten(
            treedef, [ct_leaf(o, gl) for o, gl in zip(out_leaves, g_leaves)])

    (w_grads, pre_grads) = vjp(cts)
    _emit_diff_grads(ctx, env, all_diff, w_grads)

    carried_grad_vals = []
    for n in attr("carried", []):
        if n in pre_grads:
            carried_grad_vals.append(_zero_float0(pre_grads[n], pre[n]))
        elif n in pre:
            carried_grad_vals.append(
                jax.tree_util.tree_map(jnp.zeros_like, pre[n]))
        else:
            carried_grad_vals.append(jnp.zeros(()))
    ctx.set_outputs("CarriedGrads", carried_grad_vals)


@register_op("conditional_block", is_control_flow=True)
def conditional_block(ctx):
    """Scalar-guarded conditional lowered to ``lax.cond``: the block's ops
    are TRACED unconditionally (XLA needs both branch computations), but at
    RUNTIME only the taken branch executes — the lazy cost model of the
    reference's conditional_block_op.cc, unlike a both-sides select. The
    false branch keeps the previous bindings (zeros when unbound, with
    shapes discovered via jax.eval_shape of the block)."""
    sub = ctx.sub_block("sub_block")
    cond = data_of(ctx.inputs("Cond")[0]).reshape(()).astype(jnp.bool_)
    env = ctx.env
    exec_state = ctx._exec
    from ..core.executor import _run_ops

    written = _block_written(sub)

    def then_fn(_):
        local = dict(env)
        _run_ops(sub, local, exec_state)
        return tuple(local[n] for n in written)

    prev_tracing = getattr(exec_state, "_tracing", False)
    if exec_state is not None:
        exec_state._tracing = True  # branches (and eval_shape) only trace
    try:
        if all(n in env for n in written):
            shapes = None  # every write pre-bound: no extra trace needed
        else:
            # shapes of the block's writes to synthesize zero defaults for
            # names unbound before the block
            shapes = jax.eval_shape(then_fn, 0)

        def else_fn(_):
            out = []
            for i, n in enumerate(written):
                old = env.get(n)
                if old is None:
                    old = jax.tree_util.tree_map(
                        lambda l: jnp.zeros(l.shape, l.dtype), shapes[i])
                out.append(old)
            return tuple(out)

        results = jax.lax.cond(cond, then_fn, else_fn, 0)
    finally:
        if exec_state is not None:
            exec_state._tracing = prev_tracing
    for n, v in zip(written, results):
        env[n] = v
    from ..core.flags import get_flag
    if get_flag("check_nan_inf") and not prev_tracing:
        # eager mode: the block's ops only traced (lax.cond), so the per-op
        # sweep couldn't see them — check the block's OUTPUTS here (block-
        # level attribution instead of op-level; jit mode gets op-level via
        # debug_nans)
        import numpy as _np
        for n, v in zip(written, results):
            for leaf in jax.tree_util.tree_leaves(v):
                if isinstance(leaf, jax.core.Tracer):
                    continue
                arr = _np.asarray(leaf)
                if _np.issubdtype(arr.dtype, _np.floating) and \
                        not _np.isfinite(arr).all():
                    raise FloatingPointError(
                        f"NaN/Inf in conditional_block output {n!r} "
                        "(check_nan_inf flag; rerun under jit with the "
                        "flag for per-op attribution)")


# ---------------------------------------------------------------------------
# recurrent (StaticRNN) and dynamic_recurrent (DynamicRNN)
# ---------------------------------------------------------------------------

def _run_recurrent(exec_state, sub, attr, env, lens):
    """Shared pure lowering: lax.scan over time with memory carries.

    attrs: sub_block, step_inputs [outer names], step_vars [block-local
    per-step names], memories [(mem_name, new_name)], outputs [block names].
    ``lens`` is None for StaticRNN (all rows run full length) or [b] int32
    for DynamicRNN aliveness masking. Returns ({out_name: stacked [b,T,...]},
    {mem_name: final [b, ...]}) WITHOUT touching env — the functional core
    both the forward op and jax.vjp (the grad op) trace through.
    """
    step_inputs = attr("step_inputs", [])
    step_vars = attr("step_vars", [])
    memories = [tuple(m) for m in attr("memories", [])]
    mem_inits = attr("mem_inits", {})
    out_names = attr("outputs", [])

    from ..core.executor import _run_ops

    xs = {}
    T = None
    for outer, inner in zip(step_inputs, step_vars):
        v = env[outer]
        d = v.data if isinstance(v, LoDArray) else data_of(v)
        xs[inner] = jnp.swapaxes(d, 0, 1)      # time-major [T, b, ...]
        T = xs[inner].shape[0]

    init_mems = {mem: data_of(env[mem_inits[mem]]) for mem, _ in memories}

    def body(carry, step):
        t, slices = step
        local = dict(env)
        local.update({mem: val for mem, val in carry.items()})
        local.update(slices)
        _run_ops(sub, local, exec_state)
        new_carry = {}
        for mem, new in memories:
            new_val = data_of(local[new])
            if lens is not None:
                alive = (t < lens).reshape(
                    (-1,) + (1,) * (new_val.ndim - 1)).astype(new_val.dtype)
                new_val = alive * new_val + (1 - alive) * carry[mem]
            new_carry[mem] = new_val
        outs = {}
        for o in out_names:
            ov = data_of(local[o])
            if lens is not None:
                alive = (t < lens).reshape(
                    (-1,) + (1,) * (ov.ndim - 1)).astype(ov.dtype)
                ov = ov * alive
            outs[o] = ov
        return new_carry, outs

    steps = (jnp.arange(T), xs)
    final_mems, stacked = jax.lax.scan(body, init_mems, steps)
    stacked_out = {o: jnp.swapaxes(stacked[o], 0, 1) for o in out_names}
    return stacked_out, {mem: final_mems[mem] for mem, _ in memories}


def _recurrent_fwd(ctx, lens):
    stacked, finals = _run_recurrent(ctx._exec, ctx.sub_block("sub_block"),
                                     ctx.attr, ctx.env, lens)
    for o, v in stacked.items():
        ctx.env[o + "@STACKED"] = LoDArray(v, lens) if lens is not None else v
    for m, v in finals.items():
        ctx.env[m + "@FINAL"] = v


def _recurrent_grad_maker(op):
    """Grad op consumes the forward's inputs + output grads and produces
    grads for every recorded differentiable outer var."""
    diff = op.attrs.get("diff_vars", [])
    spec = OpSpec(
        op.type + "_grad",
        {"Inputs": op.input("Inputs"), "MemInits": op.input("MemInits"),
         "FreeVars": op.input("FreeVars"),
         "StackedGrad": G(op.output("Stacked")),
         "FinalGrad": G(op.output("FinalMems"))},
        {"DiffGrads": G(diff)},
        dict(op.attrs))
    return [spec]


@register_op("recurrent", is_control_flow=True,
             grad=_recurrent_grad_maker)
def recurrent(ctx):
    _recurrent_fwd(ctx, lens=None)


def _dyn_lens(ctx):
    first = ctx.env[ctx.attr("step_inputs")[0]]
    if not isinstance(first, LoDArray):
        raise TypeError("dynamic_recurrent expects LoD step inputs")
    return first.lens


@register_op("dynamic_recurrent", is_control_flow=True,
             grad=_recurrent_grad_maker)
def dynamic_recurrent(ctx):
    _recurrent_fwd(ctx, lens=_dyn_lens(ctx))


def _has_float_leaf(v):
    return any(jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
               for l in jax.tree_util.tree_leaves(v))


def _zero_float0(g, like_v):
    """Replace float0 leaves (cotangents of integer leaves, e.g. a
    TensorArrayVal's length) with typed zeros so downstream consumers see
    well-formed values."""
    return jax.tree_util.tree_map(
        lambda gl, ol: jnp.zeros_like(ol)
        if getattr(gl, "dtype", None) == jax.dtypes.float0 else gl,
        g, like_v)


def _emit_diff_grads(ctx, env, all_diff, grads):
    """Write grads to the DECLARED DiffGrads output names in diff_vars order
    (backward.py may have renamed an output for rename-and-sum
    accumulation); missing/non-float entries get zeros."""
    out_vals = []
    for n in all_diff:
        old = env[n]
        if n in grads:
            g = _zero_float0(grads[n], data_of(old))
            if isinstance(old, LoDArray):
                g = LoDArray(g, old.lens)
        else:
            g = jax.tree_util.tree_map(jnp.zeros_like, old)
        out_vals.append(g)
    ctx.set_outputs("DiffGrads", out_vals)


def _recurrent_grad(ctx, lens):
    """Gradient THROUGH the scan: jax.vjp over the functionalized forward
    with respect to every differentiable outer input — step inputs, memory
    inits, and the free variables (weights) the sub-block reads. The
    reference interprets a generated backward sub-block step-by-step
    (operators/recurrent_op.cc RecurrentGradOp, while_op.cc:35 WhileGrad,
    python backward.py:273 sub-block recursion); here reverse-mode AD of the
    scan gives the same result with XLA managing the saved activations."""
    env = ctx.env
    attr = ctx.attr
    sub = ctx.sub_block("sub_block")
    out_names = attr("outputs", [])
    memories = [tuple(m) for m in attr("memories", [])]

    # differentiable outer vars (recorded float-typed at build time);
    # non-float runtime values (defensive) get zero grads
    all_diff = list(attr("diff_vars", []))
    diff_names = [n for n in all_diff if _has_float_leaf(env[n])]

    prim = {n: data_of(env[n]) for n in diff_names}

    def fwd(vals):
        local = dict(env)
        for n, v in vals.items():
            old = env[n]
            local[n] = LoDArray(v, old.lens) if isinstance(old, LoDArray) \
                else v
        return _run_recurrent(ctx._exec, sub, attr, local, lens)

    (stacked, finals), vjp = jax.vjp(fwd, prim)

    def cotangent(name, like_val):
        g = env.get(name)
        if g is None:
            return jnp.zeros_like(like_val)
        return data_of(g).astype(like_val.dtype).reshape(like_val.shape)

    ct_stacked = {o: cotangent(o + "@STACKED@GRAD", stacked[o])
                  for o in out_names}
    ct_finals = {m: cotangent(m + "@FINAL@GRAD", finals[m])
                 for m, _ in memories}
    (grads,) = vjp((ct_stacked, ct_finals))
    _emit_diff_grads(ctx, env, all_diff, grads)


@register_op("recurrent_grad", is_control_flow=True)
def recurrent_grad(ctx):
    _recurrent_grad(ctx, lens=None)


@register_op("dynamic_recurrent_grad", is_control_flow=True)
def dynamic_recurrent_grad(ctx):
    _recurrent_grad(ctx, lens=_dyn_lens(ctx))


@register_op("batch_gather")
def batch_gather(ctx):
    """Out[i, j] = X[i, Index[i, j]] over the second axis — the beam-state
    reordering primitive (the reference encodes beam provenance in LoD and
    re-gathers via sequence_expand; dense beams gather by parent_idx)."""
    x = data_of(ctx.input("X"))
    idx = data_of(ctx.input("Index")).astype(jnp.int32)
    bidx = jnp.arange(x.shape[0])[:, None]
    ctx.set_output("Out", x[bidx, idx])


# ---------------------------------------------------------------------------
# beam search (dense [batch, beam] layout)
# ---------------------------------------------------------------------------

def _beam_search_lod(ctx):
    """The reference's variable-width LoD beam step (beam_search_op.cc):
    ids/scores arrive as a 2-level LoD tensor — level 0 groups PREFIXES per
    source sentence, each prefix row holding K candidate (id, score) pairs —
    plus flat pre_ids [n_prefix]. Per source: take the top beam_size
    candidates across all its prefixes (descending score), regroup them by
    prefix, drop every candidate of a finished prefix (pre_id == end_id —
    finished hypotheses leave the beam), and emit per-prefix groups sorted
    by ascending id. Output widths SHRINK as hypotheses finish: level 1 of
    the output LoD has one (possibly empty) entry per input prefix.

    Host-side op (dynamic output widths cannot jit); the dense [b, beam]
    branch below is the jit-able fast path the book decoder uses."""
    import numpy as onp

    ids_v = ctx.input("ids")
    scores_v = ctx.input("scores")
    pre_ids = onp.asarray(data_of(ctx.input("pre_ids"))).reshape(-1)
    beam = int(ctx.attr("beam_size"))
    end_id = int(ctx.attr("end_id"))

    cand_ids = onp.asarray(ids_v.data)          # [n_prefix, K, ...]
    cand_scores = onp.asarray(scores_v.data)
    lens = onp.asarray(ids_v.lens).reshape(-1)  # per-prefix candidate count
    outer = onp.asarray(ids_v.outer_lens).reshape(-1)  # prefixes per source
    n_prefix = cand_ids.shape[0]
    cand_ids = cand_ids.reshape(n_prefix, -1)
    cand_scores = cand_scores.reshape(n_prefix, -1)

    # SelectTopBeamSizeItems: per source, top beam_size across prefixes
    per_prefix = [[] for _ in range(n_prefix)]
    start = 0
    for width in outer:
        items = []
        for p in range(start, start + int(width)):
            for c in range(int(lens[p])):
                items.append((p, int(cand_ids[p, c]),
                              float(cand_scores[p, c])))
        items.sort(key=lambda it: -it[2])
        for p, i, s in items[:beam]:
            per_prefix[p].append((i, s))
        start += int(width)

    # PruneEndidCandidates: finished prefixes contribute nothing
    for p in range(n_prefix):
        if pre_ids[p] == end_id:
            per_prefix[p] = []

    widths = onp.asarray([len(v) for v in per_prefix], onp.int32)
    max_w = max(int(widths.max()) if n_prefix else 0, 1)
    out_ids = onp.zeros((n_prefix, max_w, 1), onp.int64)
    out_scores = onp.zeros((n_prefix, max_w, 1), onp.float32)
    for p, items in enumerate(per_prefix):
        items.sort(key=lambda it: it[0])        # ascending id (reference)
        for j, (i, s) in enumerate(items):
            out_ids[p, j, 0] = i
            out_scores[p, j, 0] = s

    ctx.set_output("selected_ids",
                   LoDArray(jnp.asarray(out_ids), jnp.asarray(widths),
                            ids_v.outer_lens))
    ctx.set_output("selected_scores",
                   LoDArray(jnp.asarray(out_scores), jnp.asarray(widths),
                            ids_v.outer_lens))


@register_op("beam_search")
def beam_search(ctx):
    """One beam step. LoD-input form: the reference's variable-width
    semantics (see _beam_search_lod). Dense form — inputs: pre_ids [b, beam]
    int, pre_scores [b, beam]
    (accumulated log-probs), ids [b, beam, k] candidate tokens, scores
    [b, beam, k] candidate log-probs. Finished beams (pre_id == end_id) emit
    only end_id with unchanged score. Outputs selected_ids/selected_scores
    [b, beam] and parent_idx [b, beam] (which source beam each came from).
    Dense re-design of beam_search_op.h:96-193."""
    if isinstance(ctx.input("ids"), LoDArray):
        _beam_search_lod(ctx)
        return
    pre_ids = data_of(ctx.input("pre_ids")).astype(jnp.int32)
    pre_scores = data_of(ctx.input("pre_scores"))
    cand_ids = data_of(ctx.input("ids")).astype(jnp.int32)
    cand_scores = data_of(ctx.input("scores"))
    beam = int(ctx.attr("beam_size"))
    end_id = int(ctx.attr("end_id"))

    b, bm, k = cand_scores.shape
    finished = pre_ids == end_id                        # [b, beam]
    # finished beams: single continuation (end_id, score unchanged)
    total = pre_scores[:, :, None] + cand_scores        # [b, beam, k]
    neg_inf = jnp.asarray(-1e9, total.dtype)
    # mask all but candidate 0 of finished beams; candidate 0 keeps score
    keep_first = jnp.arange(k)[None, None, :] == 0
    total = jnp.where(finished[:, :, None],
                      jnp.where(keep_first, pre_scores[:, :, None], neg_inf),
                      total)
    ids_eff = jnp.where(finished[:, :, None], end_id, cand_ids)

    flat_scores = total.reshape(b, bm * k)
    top_scores, top_idx = jax.lax.top_k(flat_scores, beam)  # [b, beam]
    parent = (top_idx // k).astype(jnp.int32)
    sel_ids = jnp.take_along_axis(ids_eff.reshape(b, bm * k), top_idx, axis=1)
    ctx.set_output("selected_ids", sel_ids)
    ctx.set_output("selected_scores", top_scores)
    ctx.set_output("parent_idx", parent)


@register_op("beam_search_decode")
def beam_search_decode(ctx):
    """Backtrack beams: Ids/Parents are TensorArrays of [b, beam] per step
    (Ids[0] is the init token), Scores the accumulated scores at the last
    step. Emits SentenceIds as a LoDArray of batch*beam ragged sequences
    (eos-trimmed) and SentenceScores [b*beam] — the dense equivalent of
    beam_search_decode_op.cc's 2-level-LoD backtrack."""
    ids_arr = ctx.input("Ids")
    parents_arr = ctx.input("Parents")
    scores = data_of(ctx.input("Scores"))
    end_id = int(ctx.attr("end_id"))

    ids = ids_arr.data                # [cap, b, beam]
    parents = parents_arr.data
    T = ids.shape[0]
    b, beam = ids.shape[1], ids.shape[2]

    def back(carry, t):
        beam_idx = carry              # [b, beam] which beam at step t+1
        tok = jnp.take_along_axis(ids[t], beam_idx, axis=1)
        prev = jnp.take_along_axis(parents[t], beam_idx, axis=1)
        return prev, tok

    last = jnp.broadcast_to(jnp.arange(beam, dtype=jnp.int32)[None, :],
                            (b, beam))
    length = ids_arr.length
    # walk from the last written step back to step 0
    ts = jnp.arange(T - 1, -1, -1)
    valid_t = ts < length

    def masked_back(carry, inp):
        t, ok = inp
        new_carry, tok = back(carry, t)
        new_carry = jnp.where(ok, new_carry, carry)
        return new_carry, (tok, ok)

    _, (toks_rev, oks) = jax.lax.scan(masked_back, last, (ts, valid_t))
    toks = jnp.flip(toks_rev, axis=0)             # [T, b, beam] time order
    oks = jnp.flip(oks, axis=0)
    seqs = jnp.transpose(toks, (1, 2, 0)).reshape(b * beam, T)
    written = jnp.transpose(
        jnp.broadcast_to(oks[:, None, None], (T, b, beam)),
        (1, 2, 0)).reshape(b * beam, T)

    # per-sequence length: first end_id (inclusive) within written steps
    is_end = (seqs == end_id) & written
    any_end = is_end.any(axis=1)
    first_end = jnp.argmax(is_end, axis=1)
    total = written.sum(axis=1).astype(jnp.int32)
    lens = jnp.where(any_end, first_end + 1, total).astype(jnp.int32)
    # 2-level LoD mirroring the reference's output (beam_search_decode_op.cc
    # emits [source][beam] nested offsets): outer level groups the beam
    # sentence rows of each source sentence
    outer = jnp.full((b,), beam, jnp.int32)
    ctx.set_output("SentenceIds", LoDArray(seqs[..., None], lens, outer))
    ctx.set_output("SentenceScores", scores.reshape(b * beam))


@register_op("ifelse_merge", grad=lambda op: [OpSpec(
    "ifelse_merge_grad",
    {"Cond": op.input("Cond"), "Out@GRAD": G(op.output("Out"))},
    {"TrueVal@GRAD": G(op.input("TrueVal")),
     "FalseVal@GRAD": G(op.input("FalseVal"))})])
def ifelse_merge(ctx):
    """Row-wise select merging IfElse branches (the merge_lod_tensor
    equivalent, reference merge_lod_tensor_op.cc, under select semantics)."""
    cond = data_of(ctx.input("Cond"))
    t = data_of(ctx.input("TrueVal"))
    f = data_of(ctx.input("FalseVal"))
    c = cond.reshape((cond.shape[0],) + (1,) * (t.ndim - 1)) > 0.5
    ctx.set_output("Out", jnp.where(c, t, f))


@register_op("ifelse_merge_grad")
def ifelse_merge_grad(ctx):
    cond = data_of(ctx.input("Cond"))
    d = data_of(ctx.input("Out@GRAD"))
    c = cond.reshape((cond.shape[0],) + (1,) * (d.ndim - 1)) > 0.5
    zero = jnp.zeros_like(d)
    ctx.set_output("TrueVal@GRAD", jnp.where(c, d, zero))
    ctx.set_output("FalseVal@GRAD", jnp.where(c, zero, d))
