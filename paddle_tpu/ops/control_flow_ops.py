"""Control-flow ops: while, recurrent (StaticRNN/DynamicRNN), TensorArray,
conditional_block, beam_search, beam_search_decode.

Reference: /root/reference/paddle/fluid/operators/while_op.cc (scope-mutating
loop over a sub-block), recurrent_op.cc:39-103 (StepScopes per timestep),
tensor_array_read_write ops, conditional_block_op.cc, beam_search_op.h:96-193,
beam_search_decode_op.cc, and the lod_rank_table/shrink_rnn_memory DynamicRNN
machinery (lod_rank_table_op.cc, shrink_rnn_memory_op.cc).

TPU-native re-design (SURVEY.md §7 hard part b): the reference mutates step
scopes imperatively; under XLA everything must functionalize:

* TensorArray (the reference's LoDTensorArray) becomes ``TensorArrayVal`` — a
  PRE-ALLOCATED [cap, ...] device buffer plus a length counter, a pytree that
  crosses jit/scan/while_loop. Writes are dynamic_update_slice at a traced
  index. Arrays carried through a while loop must receive one write before
  the loop so their shape is known (the reference's decoders all do this).
* ``while`` lowers to ONE ``lax.while_loop`` whose carry is exactly the set
  of block-written variables that pre-exist outside, plus the condition.
* ``recurrent``/``dynamic_recurrent`` (StaticRNN/DynamicRNN) lower to ONE
  ``lax.scan`` over the time axis. DynamicRNN replaces the reference's
  lod_rank_table + shrink_rnn_memory batch-shrinking (a GPU-efficiency
  reordering) with per-row aliveness masking over the padded LoD batch — the
  TPU equivalent with identical semantics on the valid region.
* ``conditional_block`` runs its block and select()s outputs against the
  previous bindings — XLA computes both sides, cond picks (scalar guards
  like LR schedules and Switch cases).
* ``beam_search`` works on DENSE [batch, beam] state (scores accumulated in
  log space, finished beams frozen at end_id) instead of the reference's
  2-level-LoD layout; ``beam_search_decode`` backtracks stored parent
  pointers into a LoDArray of [batch*beam] ragged token sequences.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op
from .common import data_of


@jax.tree_util.register_pytree_node_class
class TensorArrayVal:
    """Pre-allocated tensor array: data [cap, ...], length scalar int32."""

    __slots__ = ("data", "length")

    def __init__(self, data, length):
        self.data = data
        self.length = length

    def tree_flatten(self):
        return (self.data, self.length), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def cap(self):
        return self.data.shape[0]

    def __repr__(self):
        return (f"TensorArrayVal(cap={getattr(self.data, 'shape', None)}, "
                f"length={self.length})")


class EmptyTensorArray:
    """Build-time placeholder until the first write fixes the element shape."""

    def __init__(self, cap):
        self.cap = cap


def _as_scalar_i32(v):
    return data_of(v).reshape(()).astype(jnp.int32)


@register_op("write_to_array")
def write_to_array(ctx):
    x = ctx.input("X")
    xd = x.data if isinstance(x, LoDArray) else data_of(x)
    i = _as_scalar_i32(ctx.input("I"))
    # read-modify-write: the array var is both input "Array" and output "Out"
    # (the reference write_to_array aliases them); first write allocates the
    # [cap, ...] buffer from the element's shape
    arr = ctx.input("Array") if ctx.has_input("Array") else None
    if arr is None or isinstance(arr, EmptyTensorArray):
        cap = arr.cap if arr is not None else ctx.attr("cap", 64)
        data = jnp.zeros((cap,) + xd.shape, xd.dtype)
        length = jnp.zeros((), jnp.int32)
        arr = TensorArrayVal(data, length)
    new_data = jax.lax.dynamic_update_index_in_dim(arr.data, xd.astype(
        arr.data.dtype), i, axis=0)
    new_len = jnp.maximum(arr.length, i + 1)
    ctx.set_output("Out", TensorArrayVal(new_data, new_len))


@register_op("read_from_array")
def read_from_array(ctx):
    arr = ctx.input("X")
    i = _as_scalar_i32(ctx.input("I"))
    ctx.set_output("Out", jax.lax.dynamic_index_in_dim(arr.data, i, axis=0,
                                                       keepdims=False))


@register_op("array_length")
def array_length(ctx):
    arr = ctx.input("X")
    ctx.set_output("Out", arr.length.reshape(1).astype(jnp.int64)
                   if hasattr(arr.length, "reshape")
                   else jnp.asarray([arr.length], jnp.int64))


@register_op("max_sequence_len")
def max_sequence_len(ctx):
    """Max length of a LoD input (max_sequence_len over the rank table in the
    reference; here directly over lens)."""
    x = ctx.input("RankTable")
    lens = x.lens if isinstance(x, LoDArray) else data_of(x)
    ctx.set_output("Out", jnp.max(lens).reshape(1).astype(jnp.int64))


# ---------------------------------------------------------------------------
# while
# ---------------------------------------------------------------------------

def _block_written(block):
    """Names written by the block, recursing into nested control-flow
    sub-blocks (a nested While/Switch writing an outer var must still appear
    in the enclosing loop's carry)."""
    seen, out = set(), []

    def walk(blk):
        for op in blk.ops:
            for n in op.output_arg_names():
                if n not in seen:
                    seen.add(n)
                    out.append(n)
            for attr in ("sub_block", "sub_block_false"):
                if op.has_attr(attr):
                    walk(blk.program.blocks[op.attr(attr)])

    walk(block)
    return out


@register_op("while", is_control_flow=True)
def while_op(ctx):
    """ONE lax.while_loop over the sub-block (vs. the reference's interpreted
    scope-loop, while_op.cc). Carry = condition + every block-written var
    that already exists in the enclosing env (loop state); everything else
    the block writes is a per-iteration temporary."""
    sub = ctx.sub_block("sub_block")
    cond_name = ctx.op.input("Condition")[0]
    env = ctx.env

    written = _block_written(sub)
    carry_names = [n for n in written if n in env]
    if cond_name not in carry_names:
        carry_names.append(cond_name)

    from ..core.executor import _run_ops

    def cond_fn(carry):
        return data_of(carry[cond_name]).reshape(()).astype(jnp.bool_)

    def body_fn(carry):
        local = dict(env)
        local.update(carry)
        _run_ops(sub, local, ctx._exec)
        return {n: local[n] for n in carry_names}

    init = {n: env[n] for n in carry_names}
    final = jax.lax.while_loop(cond_fn, body_fn, init)
    env.update(final)


@register_op("conditional_block", is_control_flow=True)
def conditional_block(ctx):
    """Select-semantics conditional (scalar guard): run the block, keep its
    writes where cond else the previous binding (zeros when unbound). XLA
    evaluates both sides; cond picks — the jit-compatible lowering of
    conditional_block_op.cc for scalar conditions (Switch/LR schedules)."""
    sub = ctx.sub_block("sub_block")
    cond = data_of(ctx.inputs("Cond")[0]).reshape(()).astype(jnp.bool_)
    env = ctx.env
    from ..core.executor import _run_ops

    local = dict(env)
    _run_ops(sub, local, ctx._exec)
    for n in _block_written(sub):
        new = local[n]
        old = env.get(n)
        if old is None:
            old = jax.tree_util.tree_map(jnp.zeros_like, new)
        env[n] = jax.tree_util.tree_map(
            lambda a, b: jnp.where(cond, a, b), new, old)


# ---------------------------------------------------------------------------
# recurrent (StaticRNN) and dynamic_recurrent (DynamicRNN)
# ---------------------------------------------------------------------------

def _scan_recurrent(ctx, lens):
    """Shared lowering: lax.scan over time with memory carries.

    attrs: sub_block, step_inputs [outer names], step_vars [block-local
    per-step names], memories [(mem_name, new_name)], outputs [block names].
    ``lens`` is None for StaticRNN (all rows run full length) or [b] int32
    for DynamicRNN aliveness masking.
    """
    sub = ctx.sub_block("sub_block")
    env = ctx.env
    step_inputs = ctx.attr("step_inputs", [])
    step_vars = ctx.attr("step_vars", [])
    memories = [tuple(m) for m in ctx.attr("memories", [])]
    mem_inits = ctx.attr("mem_inits", {})
    out_names = ctx.attr("outputs", [])

    from ..core.executor import _run_ops

    xs = {}
    T = None
    for outer, inner in zip(step_inputs, step_vars):
        v = env[outer]
        d = v.data if isinstance(v, LoDArray) else data_of(v)
        xs[inner] = jnp.swapaxes(d, 0, 1)      # time-major [T, b, ...]
        T = xs[inner].shape[0]

    init_mems = {mem: data_of(env[mem_inits[mem]]) for mem, _ in memories}

    def body(carry, step):
        t, slices = step
        local = dict(env)
        local.update({mem: val for mem, val in carry.items()})
        local.update(slices)
        _run_ops(sub, local, ctx._exec)
        new_carry = {}
        for mem, new in memories:
            new_val = data_of(local[new])
            if lens is not None:
                alive = (t < lens).reshape(
                    (-1,) + (1,) * (new_val.ndim - 1)).astype(new_val.dtype)
                new_val = alive * new_val + (1 - alive) * carry[mem]
            new_carry[mem] = new_val
        outs = {}
        for o in out_names:
            ov = data_of(local[o])
            if lens is not None:
                alive = (t < lens).reshape(
                    (-1,) + (1,) * (ov.ndim - 1)).astype(ov.dtype)
                ov = ov * alive
            outs[o] = ov
        return new_carry, outs

    steps = (jnp.arange(T), xs)
    final_mems, stacked = jax.lax.scan(body, init_mems, steps)
    for o in out_names:
        out = jnp.swapaxes(stacked[o], 0, 1)   # back to [b, T, ...]
        ctx.env[o + "@STACKED"] = LoDArray(out, lens) if lens is not None \
            else out
    for mem, _ in memories:
        ctx.env[mem + "@FINAL"] = final_mems[mem]


@register_op("recurrent", is_control_flow=True)
def recurrent(ctx):
    _scan_recurrent(ctx, lens=None)


@register_op("dynamic_recurrent", is_control_flow=True)
def dynamic_recurrent(ctx):
    first = ctx.env[ctx.attr("step_inputs")[0]]
    if not isinstance(first, LoDArray):
        raise TypeError("dynamic_recurrent expects LoD step inputs")
    _scan_recurrent(ctx, lens=first.lens)


@register_op("batch_gather")
def batch_gather(ctx):
    """Out[i, j] = X[i, Index[i, j]] over the second axis — the beam-state
    reordering primitive (the reference encodes beam provenance in LoD and
    re-gathers via sequence_expand; dense beams gather by parent_idx)."""
    x = data_of(ctx.input("X"))
    idx = data_of(ctx.input("Index")).astype(jnp.int32)
    bidx = jnp.arange(x.shape[0])[:, None]
    ctx.set_output("Out", x[bidx, idx])


# ---------------------------------------------------------------------------
# beam search (dense [batch, beam] layout)
# ---------------------------------------------------------------------------

@register_op("beam_search")
def beam_search(ctx):
    """One beam step. Inputs: pre_ids [b, beam] int, pre_scores [b, beam]
    (accumulated log-probs), ids [b, beam, k] candidate tokens, scores
    [b, beam, k] candidate log-probs. Finished beams (pre_id == end_id) emit
    only end_id with unchanged score. Outputs selected_ids/selected_scores
    [b, beam] and parent_idx [b, beam] (which source beam each came from).
    Dense re-design of beam_search_op.h:96-193."""
    pre_ids = data_of(ctx.input("pre_ids")).astype(jnp.int32)
    pre_scores = data_of(ctx.input("pre_scores"))
    cand_ids = data_of(ctx.input("ids")).astype(jnp.int32)
    cand_scores = data_of(ctx.input("scores"))
    beam = int(ctx.attr("beam_size"))
    end_id = int(ctx.attr("end_id"))

    b, bm, k = cand_scores.shape
    finished = pre_ids == end_id                        # [b, beam]
    # finished beams: single continuation (end_id, score unchanged)
    total = pre_scores[:, :, None] + cand_scores        # [b, beam, k]
    neg_inf = jnp.asarray(-1e9, total.dtype)
    # mask all but candidate 0 of finished beams; candidate 0 keeps score
    keep_first = jnp.arange(k)[None, None, :] == 0
    total = jnp.where(finished[:, :, None],
                      jnp.where(keep_first, pre_scores[:, :, None], neg_inf),
                      total)
    ids_eff = jnp.where(finished[:, :, None], end_id, cand_ids)

    flat_scores = total.reshape(b, bm * k)
    top_scores, top_idx = jax.lax.top_k(flat_scores, beam)  # [b, beam]
    parent = (top_idx // k).astype(jnp.int32)
    sel_ids = jnp.take_along_axis(ids_eff.reshape(b, bm * k), top_idx, axis=1)
    ctx.set_output("selected_ids", sel_ids)
    ctx.set_output("selected_scores", top_scores)
    ctx.set_output("parent_idx", parent)


@register_op("beam_search_decode")
def beam_search_decode(ctx):
    """Backtrack beams: Ids/Parents are TensorArrays of [b, beam] per step
    (Ids[0] is the init token), Scores the accumulated scores at the last
    step. Emits SentenceIds as a LoDArray of batch*beam ragged sequences
    (eos-trimmed) and SentenceScores [b*beam] — the dense equivalent of
    beam_search_decode_op.cc's 2-level-LoD backtrack."""
    ids_arr = ctx.input("Ids")
    parents_arr = ctx.input("Parents")
    scores = data_of(ctx.input("Scores"))
    end_id = int(ctx.attr("end_id"))

    ids = ids_arr.data                # [cap, b, beam]
    parents = parents_arr.data
    T = ids.shape[0]
    b, beam = ids.shape[1], ids.shape[2]

    def back(carry, t):
        beam_idx = carry              # [b, beam] which beam at step t+1
        tok = jnp.take_along_axis(ids[t], beam_idx, axis=1)
        prev = jnp.take_along_axis(parents[t], beam_idx, axis=1)
        return prev, tok

    last = jnp.broadcast_to(jnp.arange(beam, dtype=jnp.int32)[None, :],
                            (b, beam))
    length = ids_arr.length
    # walk from the last written step back to step 0
    ts = jnp.arange(T - 1, -1, -1)
    valid_t = ts < length

    def masked_back(carry, inp):
        t, ok = inp
        new_carry, tok = back(carry, t)
        new_carry = jnp.where(ok, new_carry, carry)
        return new_carry, (tok, ok)

    _, (toks_rev, oks) = jax.lax.scan(masked_back, last, (ts, valid_t))
    toks = jnp.flip(toks_rev, axis=0)             # [T, b, beam] time order
    oks = jnp.flip(oks, axis=0)
    seqs = jnp.transpose(toks, (1, 2, 0)).reshape(b * beam, T)
    written = jnp.transpose(
        jnp.broadcast_to(oks[:, None, None], (T, b, beam)),
        (1, 2, 0)).reshape(b * beam, T)

    # per-sequence length: first end_id (inclusive) within written steps
    is_end = (seqs == end_id) & written
    any_end = is_end.any(axis=1)
    first_end = jnp.argmax(is_end, axis=1)
    total = written.sum(axis=1).astype(jnp.int32)
    lens = jnp.where(any_end, first_end + 1, total).astype(jnp.int32)
    ctx.set_output("SentenceIds", LoDArray(seqs[..., None], lens))
    ctx.set_output("SentenceScores", scores.reshape(b * beam))
