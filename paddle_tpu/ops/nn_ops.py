"""NN ops that aren't conv/pool/norm: dropout, lookup_table (embedding).

Reference: dropout_op.cc, lookup_table_op.cc
(/root/reference/paddle/fluid/operators/). lookup_table's grad produces a
dense scatter-add by default, or — with is_sparse — a SparseRows gradient
(core/sparse.py), the SelectedRows equivalent the reference emits from
lookup_table_op.cc's sparse W@GRAD path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op, same_shape, OpSpec
from ..core.lod import LoDArray
from ..core.sparse import sparse_rows_from_grad
from .common import G, data_of, like


@register_op("dropout", infer_shape=same_shape("X", "Out"), grad=lambda op: [OpSpec(
    "dropout_grad",
    {"Mask": op.output("Mask"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def dropout(ctx):
    x = ctx.input("X")
    xd = data_of(x)
    prob = ctx.attr("dropout_prob", 0.5)
    if ctx.attr("is_test", False):
        # reference dropout_op.h: test mode scales by (1 - p)
        ctx.set_output("Out", like(x, xd * (1.0 - prob)))
        ctx.set_output("Mask", like(x, jnp.ones_like(xd)))
        return
    keep = jax.random.bernoulli(ctx.next_rng(), 1.0 - prob, xd.shape)
    mask = keep.astype(xd.dtype)
    ctx.set_output("Out", like(x, xd * mask))
    ctx.set_output("Mask", like(x, mask))


@register_op("dropout_grad")
def dropout_grad(ctx):
    d = ctx.input("Out@GRAD")
    mask = data_of(ctx.input("Mask"))
    ctx.set_output("X@GRAD", like(d, data_of(d) * mask))


@register_op("lookup_table", grad=lambda op: [OpSpec(
    "lookup_table_grad",
    {"W": op.input("W"), "Ids": op.input("Ids"),
     "Out@GRAD": G(op.output("Out"))},
    {"W@GRAD": G(op.input("W"))}, dict(op.attrs))])
def lookup_table(ctx):
    w = data_of(ctx.input("W"))
    ids_v = ctx.input("Ids")
    ids = data_of(ids_v).astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    out = jnp.take(w, ids, axis=0)
    padding_idx = ctx.attr("padding_idx", None)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    ctx.set_output("Out", like(ids_v, out))


@register_op("lookup_table_grad")
def lookup_table_grad(ctx):
    """W@GRAD: dense scatter-add by default; with is_sparse a SparseRows
    (the reference's SelectedRows output, lookup_table_op.cc
    LookupTableGradKernel sparse path) that optimizer sparse branches
    consume without ever materializing the [vocab, dim] dense gradient."""
    w = data_of(ctx.input("W"))
    ids = data_of(ctx.input("Ids")).astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    d_v = ctx.input("Out@GRAD")
    d = data_of(d_v)
    if isinstance(d_v, LoDArray):
        # padded positions carry garbage grads — mask them out
        d = d * d_v.mask(d.dtype).reshape(d.shape[:2] + (1,) * (d.ndim - 2))
    flat_ids = ids.reshape(-1)
    flat_d = d.reshape(-1, w.shape[-1])
    if ctx.attr("is_sparse", False):
        if isinstance(d_v, LoDArray):
            # padded positions: send their (zeroed) grads to the sentinel
            # row so merge/scatter drop them entirely
            valid = d_v.mask(jnp.int32).reshape(-1)
            flat_ids = jnp.where(valid > 0, flat_ids, w.shape[0])
        ctx.set_output("W@GRAD",
                       sparse_rows_from_grad(flat_ids, flat_d, w.shape[0]))
        return
    dw = jnp.zeros_like(w).at[flat_ids].add(flat_d)
    ctx.set_output("W@GRAD", dw)


@register_op("split_ids")
def split_ids(ctx):
    """Route ids to N shard outputs by id % N (reference split_ids_op.cc —
    the trainer-side prep for a sharded lookup table). Output sizes are
    data-dependent, so this is a HOST-side op (eager mode; the reference's
    kernel is CPU-only for the same reason): the jit-compatible sharded-
    table path is the GSPMD-sharded embedding (tests/test_sparse.py)."""
    import numpy as np

    ids_v = ctx.input("Ids")
    import jax as _jax
    if isinstance(data_of(ids_v), _jax.core.Tracer):
        raise RuntimeError(
            "split_ids produces data-dependent output sizes and only runs "
            "host-side: use Executor(mode='eager') for this program, or "
            "the GSPMD-sharded embedding path for in-graph sharded tables")
    ids = np.asarray(data_of(ids_v))
    outs = ctx.op.output("Out")
    n = len(outs)
    flat = ids.reshape(-1)
    pieces = [ids.reshape(-1, 1)[flat % n == i] for i in range(n)]
    ctx.set_outputs("Out", [jnp.asarray(p) for p in pieces])


@register_op("split_selected_rows")
def split_selected_rows(ctx):
    """Split a SparseRows by row ranges (reference split_selected_rows_op.cc
    height_sections: rows [0,h0) to shard 0 as-is, [h0,h0+h1) to shard 1
    rebased, ...). Static shapes: every output keeps the input's entry
    count; out-of-range entries become sentinels that scatters drop."""
    from ..core.sparse import SparseRows

    x = ctx.input("X")
    sections = [int(s) for s in ctx.attr("height_sections")]
    outs = []
    start = 0
    for h in sections:
        in_range = (x.rows >= start) & (x.rows < start + h)
        rows = jnp.where(in_range, x.rows - start, h)   # h = sentinel
        vals = jnp.where(in_range[:, None], x.values, 0)
        outs.append(SparseRows(rows.astype(jnp.int32), vals, h))
        start += h
    ctx.set_outputs("Out", outs)
