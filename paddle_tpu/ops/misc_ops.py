"""Remaining op breadth: cumsum, prelu, maxout, spp, unpool, norm,
im2sequence, rank_loss, margin_rank_loss, bilinear_tensor_product, is_empty,
nce, conv3d, pool3d.

Reference: /root/reference/paddle/fluid/operators/{cum_op.h (cumsum),
prelu_op.cc (scalar alpha), maxout_op.cc + math/maxouting.cc, spp_op.h
(pyramid of pools + concat), unpool_op.cc + math/unpooling.cc (max-indices
scatter), norm_op.h (cross-channel L2 normalize, per-channel scale),
im2sequence_op.h (im2col patches as sequences), rank_loss_op.h
(log(1+e^{l-r}) - label (l-r)), margin_rank_loss_op.h, bilinear_tensor_
product_op.h (x W_k yᵀ per output k), is_empty_op.cc, nce_op.h (sampled
sigmoid logits, -log(o/(o+b)) / -log(b/(o+b)) with b = S/C), conv_op.cc +
pool_op.cc 3-D variants}.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.lod import LoDArray
from ..core.registry import register_op, OpSpec, same_shape, infer_output
from .common import G, data_of, like


# ---------------------------------------------------------------------------
# cumsum
# ---------------------------------------------------------------------------

@register_op("cumsum", infer_shape=same_shape("X", "Out"), grad=lambda op: [
    OpSpec("cumsum", {"X": G(op.output("Out"))}, {"Out": G(op.input("X"))},
           {**dict(op.attrs), "reverse": not op.attr("reverse", False)})])
def cumsum(ctx):
    """cum_op.h: running sum along ``axis`` with exclusive/reverse modes;
    the gradient is cumsum with reverse flipped."""
    x = data_of(ctx.input("X"))
    axis = int(ctx.attr("axis", -1))
    exclusive = bool(ctx.attr("exclusive", False))
    reverse = bool(ctx.attr("reverse", False))
    v = jnp.flip(x, axis) if reverse else x
    out = jnp.cumsum(v, axis=axis)
    if exclusive:
        out = out - v
    if reverse:
        out = jnp.flip(out, axis)
    ctx.set_output("Out", like(ctx.input("X"), out))


# ---------------------------------------------------------------------------
# prelu (scalar alpha, the reference's product(alpha)==1 contract)
# ---------------------------------------------------------------------------

@register_op("prelu", infer_shape=same_shape("X", "Out"), grad=lambda op: [
    OpSpec("prelu_grad",
           {"X": op.input("X"), "Alpha": op.input("Alpha"),
            "Out@GRAD": G(op.output("Out"))},
           {"X@GRAD": G(op.input("X")), "Alpha@GRAD": G(op.input("Alpha"))})])
def prelu(ctx):
    x = data_of(ctx.input("X"))
    alpha = data_of(ctx.input("Alpha")).reshape(())
    ctx.set_output("Out", like(ctx.input("X"),
                               jnp.where(x > 0, x, alpha * x)))


@register_op("prelu_grad")
def prelu_grad(ctx):
    x = data_of(ctx.input("X"))
    alpha = data_of(ctx.input("Alpha")).reshape(())
    d = data_of(ctx.input("Out@GRAD"))
    ctx.set_output("X@GRAD", jnp.where(x > 0, d, alpha * d))
    ctx.set_output("Alpha@GRAD",
                   jnp.sum(jnp.where(x > 0, 0.0, d * x)).reshape(
                       data_of(ctx.input("Alpha")).shape))


# ---------------------------------------------------------------------------
# maxout
# ---------------------------------------------------------------------------

def _maxout_infer(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        return
    g = int(op.attrs["groups"])
    n, c, h, w = x.shape
    infer_output(op, block, "Out", (n, c // g, h, w), dtype=x.dtype)


def _maxout_compute(x, groups):
    n, c, h, w = x.shape
    return x.reshape(n, c // groups, groups, h, w).max(axis=2)


@register_op("maxout", infer_shape=_maxout_infer, grad=lambda op: [OpSpec(
    "maxout_grad",
    {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def maxout(ctx):
    """maxout_op.cc: channels split into groups of ``groups``, max over the
    group (NCHW)."""
    x = data_of(ctx.input("X"))
    ctx.set_output("Out", _maxout_compute(x, int(ctx.attr("groups"))))


@register_op("maxout_grad")
def maxout_grad(ctx):
    x = data_of(ctx.input("X"))
    dy = data_of(ctx.input("Out@GRAD"))
    g = int(ctx.attr("groups"))
    _, vjp = jax.vjp(lambda a: _maxout_compute(a, g), x)
    ctx.set_output("X@GRAD", vjp(dy.astype(x.dtype))[0])


# ---------------------------------------------------------------------------
# spp (spatial pyramid pooling)
# ---------------------------------------------------------------------------

@register_op("spp", grad=lambda op: [OpSpec(
    "spp_grad",
    {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def spp(ctx):
    """spp_op.h: for level l in [0, pyramid_height): pool into 2^l x 2^l
    bins (max or avg), flatten, concat -> [N, C * Σ 4^l]."""
    x = data_of(ctx.input("X"))
    ctx.set_output("Out", _spp_compute(
        x, int(ctx.attr("pyramid_height")),
        ctx.attr("pooling_type", "max")))


def _spp_compute(x, height, ptype):
    """Reference spp_op.h geometry: kernel = ceil(size/bins), stride =
    kernel, padding = (kernel*bins - size + 1) / 2 — EXACTLY bins x bins
    outputs per level regardless of divisibility."""
    from .conv_ops import _pool2d_compute
    n, c, h, w = x.shape
    outs = []
    for lvl in range(height):
        bins = 2 ** lvl
        kh, kw = -(-h // bins), -(-w // bins)
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        pooled = _pool2d_compute(x, (kh, kw), (kh, kw), (ph, pw), ptype,
                                 False, False)
        assert pooled.shape[2] == bins and pooled.shape[3] == bins, \
            (pooled.shape, bins)
        outs.append(pooled.reshape(n, -1))
    return jnp.concatenate(outs, axis=1)


@register_op("spp_grad")
def spp_grad(ctx):
    x = data_of(ctx.input("X"))
    dy = data_of(ctx.input("Out@GRAD"))
    h = int(ctx.attr("pyramid_height"))
    ptype = ctx.attr("pooling_type", "max")
    _, vjp = jax.vjp(lambda a: _spp_compute(a, h, ptype), x)
    ctx.set_output("X@GRAD", vjp(dy.astype(x.dtype))[0])


# ---------------------------------------------------------------------------
# max_pool2d_with_index + unpool
# ---------------------------------------------------------------------------

@register_op("max_pool2d_with_index")
def max_pool2d_with_index(ctx):
    """Pooling that also emits flat argmax indices within each image
    (math/pooling.cc MaxPool2dWithIndexFunctor) — the producer unpool
    consumes."""
    x = data_of(ctx.input("X"))
    ks = tuple(ctx.attr("ksize"))
    st = tuple(ctx.attr("strides", ks))
    n, c, h, w = x.shape
    oh, ow = (h - ks[0]) // st[0] + 1, (w - ks[1]) // st[1] + 1
    # windows -> [N, C, oh, ow, kh*kw]
    patches = jnp.stack([
        x[:, :, i:i + st[0] * oh:st[0], j:j + st[1] * ow:st[1]]
        for i in range(ks[0]) for j in range(ks[1])], axis=-1)
    arg = jnp.argmax(patches, axis=-1)
    out = jnp.max(patches, axis=-1)
    # flat index within the [h, w] plane (reference index convention)
    ki, kj = arg // ks[1], arg % ks[1]
    rows = jnp.arange(oh)[None, None, :, None] * st[0] + ki
    cols = jnp.arange(ow)[None, None, None, :] * st[1] + kj
    ctx.set_output("Out", out)
    ctx.set_output("Mask", (rows * w + cols).astype(jnp.int32))


@register_op("unpool", grad=lambda op: [OpSpec(
    "unpool_grad",
    {"Indices": op.input("Indices"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def unpool(ctx):
    """unpool_op.cc (max unpooling): scatter each pooled value back to its
    argmax position in the [unpooled_h, unpooled_w] plane."""
    x = data_of(ctx.input("X"))
    idx = data_of(ctx.input("Indices")).astype(jnp.int32)
    uh, uw = tuple(ctx.attr("unpooled_size"))
    n, c, oh, ow = x.shape
    flat = jnp.zeros((n, c, uh * uw), x.dtype)
    out = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1)].add(x.reshape(n, c, -1))
    ctx.set_output("Out", out.reshape(n, c, uh, uw))


@register_op("unpool_grad")
def unpool_grad(ctx):
    dy = data_of(ctx.input("Out@GRAD"))
    idx = data_of(ctx.input("Indices")).astype(jnp.int32)
    n, c = idx.shape[:2]
    flat = dy.reshape(n, c, -1)
    ctx.set_output("X@GRAD", jnp.take_along_axis(
        flat, idx.reshape(n, c, -1), axis=2).reshape(idx.shape))


# ---------------------------------------------------------------------------
# norm (cross-channel L2 normalization with per-channel scale)
# ---------------------------------------------------------------------------

def _norm_compute(x, scale, eps):
    denom = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + eps)
    return x / denom * scale.reshape(1, -1, 1, 1)


@register_op("norm", infer_shape=same_shape("X", "Out"), grad=lambda op: [
    OpSpec("norm_grad",
           {"X": op.input("X"), "Scale": op.input("Scale"),
            "Out@GRAD": G(op.output("Out"))},
           {"X@GRAD": G(op.input("X")), "Scale@GRAD": G(op.input("Scale"))},
           dict(op.attrs))])
def norm(ctx):
    """norm_op.h: out[n,c,h,w] = scale[c] * x / ||x[n,:,h,w]||₂ (+eps)."""
    x = data_of(ctx.input("X"))
    scale = data_of(ctx.input("Scale")).reshape(-1)
    ctx.set_output("Out", _norm_compute(x, scale,
                                        float(ctx.attr("epsilon", 1e-10))))


@register_op("norm_grad")
def norm_grad(ctx):
    x = data_of(ctx.input("X"))
    scale = data_of(ctx.input("Scale")).reshape(-1)
    dy = data_of(ctx.input("Out@GRAD"))
    eps = float(ctx.attr("epsilon", 1e-10))
    _, vjp = jax.vjp(lambda a, s: _norm_compute(a, s, eps), x, scale)
    dx, ds = vjp(dy.astype(x.dtype))
    ctx.set_output("X@GRAD", dx)
    ctx.set_output("Scale@GRAD", ds.reshape(
        data_of(ctx.input("Scale")).shape))


# ---------------------------------------------------------------------------
# im2sequence — im2col patches as an LoD sequence per image
# ---------------------------------------------------------------------------

def _im2seq_compute(x, kernels, strides, paddings):
    n, c, h, w = x.shape
    kh, kw = kernels
    sh, sw = strides
    pu, pl, pd, pr = paddings          # up, left, down, right
    oh = (h + pu + pd - kh) // sh + 1
    ow = (w + pl + pr - kw) // sw + 1
    xp = jnp.pad(x, ((0, 0), (0, 0), (pu, pd), (pl, pr)))
    patches = jnp.stack([
        xp[:, :, i:i + sh * oh:sh, j:j + sw * ow:sw]
        for i in range(kh) for j in range(kw)], axis=2)  # [N,C,kh*kw,oh,ow]
    # reference layout per step: [c, kh, kw] flattened; steps row-major
    seq = jnp.transpose(patches.reshape(n, c, kh, kw, oh, ow),
                        (0, 4, 5, 1, 2, 3))
    return seq.reshape(n, oh * ow, c * kh * kw), oh * ow


@register_op("im2sequence", grad=lambda op: [OpSpec(
    "im2sequence_grad",
    {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def im2sequence(ctx):
    """im2sequence_op.h: each image becomes one sequence of oh*ow steps,
    each step a flattened [c, kh, kw] patch (the OCR-CTC front end)."""
    x = data_of(ctx.input("X"))
    seq, steps = _im2seq_compute(
        x, tuple(ctx.attr("kernels")), tuple(ctx.attr("strides", [1, 1])),
        tuple(ctx.attr("paddings", [0, 0, 0, 0])))
    lens = jnp.full((x.shape[0],), steps, jnp.int32)
    ctx.set_output("Out", LoDArray(seq, lens))


@register_op("im2sequence_grad")
def im2sequence_grad(ctx):
    x = data_of(ctx.input("X"))
    dy = data_of(ctx.input("Out@GRAD"))
    args = (tuple(ctx.attr("kernels")), tuple(ctx.attr("strides", [1, 1])),
            tuple(ctx.attr("paddings", [0, 0, 0, 0])))
    _, vjp = jax.vjp(lambda a: _im2seq_compute(a, *args)[0], x)
    ctx.set_output("X@GRAD", vjp(dy.astype(x.dtype))[0])


# ---------------------------------------------------------------------------
# rank_loss / margin_rank_loss
# ---------------------------------------------------------------------------

def _rank_loss_compute(label, left, right):
    return jnp.log1p(jnp.exp(left - right)) - label * (left - right)


@register_op("rank_loss", infer_shape=same_shape("Left", "Out"),
             grad=lambda op: [OpSpec(
                 "rank_loss_grad",
                 {"Label": op.input("Label"), "Left": op.input("Left"),
                  "Right": op.input("Right"),
                  "Out@GRAD": G(op.output("Out"))},
                 {"Left@GRAD": G(op.input("Left")),
                  "Right@GRAD": G(op.input("Right"))})])
def rank_loss(ctx):
    """rank_loss_op.h: RankNet pairwise loss
    log(1 + e^{l-r}) - label·(l-r)."""
    ctx.set_output("Out", _rank_loss_compute(
        data_of(ctx.input("Label")), data_of(ctx.input("Left")),
        data_of(ctx.input("Right"))))


@register_op("rank_loss_grad")
def rank_loss_grad(ctx):
    label = data_of(ctx.input("Label"))
    left = data_of(ctx.input("Left"))
    right = data_of(ctx.input("Right"))
    d = data_of(ctx.input("Out@GRAD"))
    sig = jax.nn.sigmoid(left - right)
    ctx.set_output("Left@GRAD", d * (sig - label))
    ctx.set_output("Right@GRAD", d * (label - sig))


@register_op("margin_rank_loss", infer_shape=same_shape("X1", "Out"),
             grad=lambda op: [OpSpec(
                 "margin_rank_loss_grad",
                 {"Label": op.input("Label"), "X1": op.input("X1"),
                  "X2": op.input("X2"), "Out@GRAD": G(op.output("Out"))},
                 {"X1@GRAD": G(op.input("X1")),
                  "X2@GRAD": G(op.input("X2"))}, dict(op.attrs))])
def margin_rank_loss(ctx):
    """margin_rank_loss_op.h: max(0, -label·(x1-x2) + margin)."""
    label = data_of(ctx.input("Label"))
    x1 = data_of(ctx.input("X1"))
    x2 = data_of(ctx.input("X2"))
    margin = float(ctx.attr("margin", 0.0))
    ctx.set_output("Out", jnp.maximum(0.0, -label * (x1 - x2) + margin))


@register_op("margin_rank_loss_grad")
def margin_rank_loss_grad(ctx):
    label = data_of(ctx.input("Label"))
    x1 = data_of(ctx.input("X1"))
    x2 = data_of(ctx.input("X2"))
    margin = float(ctx.attr("margin", 0.0))
    d = data_of(ctx.input("Out@GRAD"))
    act = (-label * (x1 - x2) + margin) > 0
    ctx.set_output("X1@GRAD", jnp.where(act, -label * d, 0.0))
    ctx.set_output("X2@GRAD", jnp.where(act, label * d, 0.0))


# ---------------------------------------------------------------------------
# bilinear_tensor_product
# ---------------------------------------------------------------------------

def _btp_compute(x, y, w, bias):
    # out[b, k] = x[b] @ W[k] @ y[b] (+ bias[k])
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if bias is not None:
        out = out + bias.reshape(1, -1)
    return out


@register_op("bilinear_tensor_product", grad=lambda op: [OpSpec(
    "bilinear_tensor_product_grad",
    {"X": op.input("X"), "Y": op.input("Y"), "Weight": op.input("Weight"),
     **({"Bias": op.input("Bias")} if op.input("Bias") else {}),
     "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X")), "Y@GRAD": G(op.input("Y")),
     "Weight@GRAD": G(op.input("Weight")),
     **({"Bias@GRAD": G(op.input("Bias"))} if op.input("Bias") else {})})])
def bilinear_tensor_product(ctx):
    """bilinear_tensor_product_op.h: out_k = x W_k yᵀ + b_k."""
    x = data_of(ctx.input("X"))
    y = data_of(ctx.input("Y"))
    w = data_of(ctx.input("Weight"))
    bias = data_of(ctx.input("Bias")) if ctx.has_input("Bias") else None
    ctx.set_output("Out", _btp_compute(x, y, w, bias))


@register_op("bilinear_tensor_product_grad")
def bilinear_tensor_product_grad(ctx):
    x = data_of(ctx.input("X"))
    y = data_of(ctx.input("Y"))
    w = data_of(ctx.input("Weight"))
    has_bias = ctx.has_input("Bias")
    bias = data_of(ctx.input("Bias")) if has_bias else None
    d = data_of(ctx.input("Out@GRAD"))
    args = (x, y, w) + ((bias,) if has_bias else ())

    def f(*a):
        return _btp_compute(a[0], a[1], a[2], a[3] if has_bias else None)

    _, vjp = jax.vjp(f, *args)
    grads = vjp(d.astype(x.dtype))
    ctx.set_output("X@GRAD", grads[0])
    ctx.set_output("Y@GRAD", grads[1])
    ctx.set_output("Weight@GRAD", grads[2])
    if has_bias:
        ctx.set_output("Bias@GRAD", grads[3])


# ---------------------------------------------------------------------------
# is_empty
# ---------------------------------------------------------------------------

@register_op("is_empty")
def is_empty(ctx):
    """is_empty_op.cc: scalar bool, true iff X has zero elements (a static
    property under XLA, computed at trace time)."""
    x = data_of(ctx.input("X"))
    ctx.set_output("Out", jnp.asarray([x.size == 0]))


# ---------------------------------------------------------------------------
# nce (noise-contrastive estimation)
# ---------------------------------------------------------------------------

@register_op("nce", grad=lambda op: [OpSpec(
    "nce_grad",
    {"Input": op.input("Input"), "Weight": op.input("Weight"),
     "SampleLabels": op.output("SampleLabels"),
     **({"Bias": op.input("Bias")} if op.input("Bias") else {}),
     **({"SampleWeight": op.input("SampleWeight")}
        if op.input("SampleWeight") else {}),
     "Cost@GRAD": G(op.output("Cost"))},
    {"Input@GRAD": G(op.input("Input")),
     "Weight@GRAD": G(op.input("Weight")),
     **({"Bias@GRAD": G(op.input("Bias"))} if op.input("Bias") else {})},
    dict(op.attrs))])
def nce(ctx):
    """nce_op.h: per sample, logits σ(x·w_c + b_c) over [true classes |
    sampled negatives]; cost = Σ_true -log(o/(o+b)) + Σ_neg -log(b/(o+b)),
    b = num_neg/num_classes, each row scaled by SampleWeight when given.
    Negatives: custom_neg_classes when given (the reference's unit-test
    hook) else uniform draws from the executor PRNG. The drawn samples are
    EMITTED as SampleLabels (the reference op's output) and consumed by
    nce_grad, so forward cost and gradient always describe the same
    sampled loss."""
    label = data_of(ctx.input("Label"))
    if label.ndim == 1:
        label = label[:, None]
    num_neg = int(ctx.attr("num_neg_samples"))
    num_classes = int(ctx.attr("num_total_classes"))
    custom = ctx.attr("custom_neg_classes", []) or []
    b = label.shape[0]
    if custom:
        neg = jnp.broadcast_to(jnp.asarray(custom, jnp.int32)[None, :],
                               (b, len(custom)))
    else:
        neg = jax.random.randint(ctx.next_rng(), (b, num_neg), 0,
                                 num_classes).astype(jnp.int32)
    samples = jnp.concatenate([label.astype(jnp.int32), neg], axis=1)
    ctx.set_output("SampleLabels", samples)
    ctx.set_output("Cost", _nce_cost(ctx, samples, label.shape[1]))


def _nce_cost(ctx, samples, num_true, x=None, w=None, bias=None):
    x = data_of(ctx.input("Input")) if x is None else x
    w = data_of(ctx.input("Weight")) if w is None else w
    if bias is None and ctx.has_input("Bias"):
        bias = data_of(ctx.input("Bias"))
    num_neg = int(ctx.attr("num_neg_samples"))
    num_classes = int(ctx.attr("num_total_classes"))
    logits = jnp.einsum("bd,bsd->bs", x, w[samples])
    if bias is not None:
        logits = logits + bias.reshape(-1)[samples]
    o = jax.nn.sigmoid(logits)
    bconst = num_neg / num_classes
    true_cost = -jnp.log(o[:, :num_true] / (o[:, :num_true] + bconst))
    neg_cost = -jnp.log(bconst / (o[:, num_true:] + bconst))
    cost = true_cost.sum(axis=1) + neg_cost.sum(axis=1)
    if ctx.has_input("SampleWeight"):
        cost = cost * data_of(ctx.input("SampleWeight")).reshape(-1)
    return cost.reshape(-1, 1)


@register_op("nce_grad")
def nce_grad(ctx):
    """jax.vjp over _nce_cost with the forward's OWN SampleLabels."""
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Weight"))
    has_bias = ctx.has_input("Bias")
    bias = data_of(ctx.input("Bias")) if has_bias else None
    samples = data_of(ctx.input("SampleLabels")).astype(jnp.int32)
    num_neg = int(ctx.attr("num_neg_samples"))
    custom = ctx.attr("custom_neg_classes", []) or []
    num_true = samples.shape[1] - (len(custom) or num_neg)
    d = data_of(ctx.input("Cost@GRAD")).reshape(-1, 1)

    args = (x, w) + ((bias,) if has_bias else ())

    def f(*a):
        return _nce_cost(ctx, samples, num_true, a[0], a[1],
                         a[2] if has_bias else None)

    _, vjp = jax.vjp(f, *args)
    grads = vjp(d.astype(x.dtype))
    ctx.set_output("Input@GRAD", grads[0])
    ctx.set_output("Weight@GRAD", grads[1])
    if has_bias:
        ctx.set_output("Bias@GRAD", grads[2])


# ---------------------------------------------------------------------------
# conv3d / pool3d
# ---------------------------------------------------------------------------

def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(a) for a in (list(v) + [v[-1]] * 3)[:3])
    return (int(v),) * 3


@register_op("conv3d", grad=lambda op: [OpSpec(
    "conv3d_grad",
    {"Input": op.input("Input"), "Filter": op.input("Filter"),
     "Output@GRAD": G(op.output("Output"))},
    {"Input@GRAD": G(op.input("Input")),
     "Filter@GRAD": G(op.input("Filter"))}, dict(op.attrs))])
def conv3d(ctx):
    """conv_op.cc 3-D registration: NCDHW input, OIDHW filter — one
    lax.conv_general_dilated on the MXU, like conv2d."""
    ctx.set_output("Output", _conv3d_compute(ctx))


def _conv3d_compute(ctx, x=None, w=None):
    from ..core.amp import cast_compute
    x = data_of(ctx.input("Input")) if x is None else x
    w = data_of(ctx.input("Filter")) if w is None else w
    s = _triple(ctx.attr("strides", [1, 1, 1]))
    p = _triple(ctx.attr("paddings", [0, 0, 0]))
    d = _triple(ctx.attr("dilations", [1, 1, 1]))
    g = int(ctx.attr("groups", 1) or 1)
    x, w = cast_compute(x, w)
    return lax.conv_general_dilated(
        x, w, window_strides=s, padding=[(pp, pp) for pp in p],
        rhs_dilation=d, feature_group_count=g,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))


@register_op("conv3d_grad")
def conv3d_grad(ctx):
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Filter"))
    dy = data_of(ctx.input("Output@GRAD"))
    out, vjp = jax.vjp(lambda a, b: _conv3d_compute(ctx, a, b), x, w)
    dx, dw = vjp(dy.astype(out.dtype))
    ctx.set_output("Input@GRAD", dx)
    ctx.set_output("Filter@GRAD", dw)


def _pool3d_compute(x, ksize, strides, paddings, ptype, global_pooling):
    n, c, d, h, w = x.shape
    if global_pooling:
        ksize = (d, h, w)
        paddings = (0, 0, 0)
    kd, kh, kw = ksize
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in paddings)
    dims = (1, 1, kd, kh, kw)
    strides5 = (1, 1) + tuple(strides)
    if ptype == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides5, pads)
    sums = lax.reduce_window(x, 0.0, lax.add, dims, strides5, pads)
    if any(paddings):
        ones = jnp.ones((1, 1, d, h, w), x.dtype)
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides5, pads)
        return sums / counts
    return sums / (kd * kh * kw)


def _pool3d_args(attr):
    return (_triple(attr("ksize", [2, 2, 2])),
            _triple(attr("strides", [1, 1, 1])),
            _triple(attr("paddings", [0, 0, 0])),
            attr("pooling_type", "max"),
            bool(attr("global_pooling", False)))


@register_op("pool3d", grad=lambda op: [OpSpec(
    "pool3d_grad",
    {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def pool3d(ctx):
    x = data_of(ctx.input("X"))
    ctx.set_output("Out", _pool3d_compute(x, *_pool3d_args(ctx.attr)))


@register_op("pool3d_grad")
def pool3d_grad(ctx):
    x = data_of(ctx.input("X"))
    dy = data_of(ctx.input("Out@GRAD"))
    args = _pool3d_args(ctx.attr)
    out, vjp = jax.vjp(lambda a: _pool3d_compute(a, *args), x)
    ctx.set_output("X@GRAD", vjp(dy.astype(out.dtype))[0])