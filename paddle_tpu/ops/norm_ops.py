"""Normalization ops: batch_norm, layer_norm, lrn.

Reference: /root/reference/paddle/fluid/operators/batch_norm_op.cc (NCHW,
inputs X/Scale/Bias/Mean/Variance, outputs Y/MeanOut/VarianceOut/SavedMean/
SavedVariance, running stats out = momentum*running + (1-momentum)*batch),
layer_norm_op.cc (begin_norm_axis flattening, outputs Y/Mean/Variance),
lrn_op.cc (cross-channel local response normalization, MidOut auxiliary).

The reference dispatches cuDNN batch-norm kernels; here each op is a few
jnp reductions that XLA fuses into neighbouring convs. batch_norm's grad uses
the standard closed form over SavedMean/SavedVariance (batch_norm_op.cc
BatchNormGradKernel); layer_norm/lrn grads come from jax.vjp of the forward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op, OpSpec, infer_output, same_shape
from .common import G, data_of


# ---------------------------------------------------------------------------
# batch_norm
# ---------------------------------------------------------------------------

def _bn_infer(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        return
    layout = op.attrs.get("data_layout", "NCHW")
    c = x.shape[-1] if layout == "NHWC" else x.shape[1]
    infer_output(op, block, "Y", x.shape, dtype=x.dtype)
    for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        if op.output(slot):
            infer_output(op, block, slot, (c,), dtype=x.dtype)


def _bn_grad_maker(op):
    return [OpSpec("batch_norm_grad",
                   {"X": op.input("X"), "Scale": op.input("Scale"),
                    "SavedMean": op.output("SavedMean"),
                    "SavedVariance": op.output("SavedVariance"),
                    "Y@GRAD": G(op.output("Y"))},
                   {"X@GRAD": G(op.input("X")),
                    "Scale@GRAD": G(op.input("Scale")),
                    "Bias@GRAD": G(op.input("Bias"))},
                   dict(op.attrs))]


def _bn_channel_axis(x, layout):
    if layout == "NHWC":
        return x.ndim - 1
    if layout in (None, "NCHW", "AnyLayout"):
        # 2-D [N, C] inputs (batch_norm after fc) also take axis 1
        return 1
    raise ValueError(f"batch_norm: unsupported data_layout {layout!r}")


def _bn_axes(x, layout):
    c = _bn_channel_axis(x, layout)
    return tuple(i for i in range(x.ndim) if i != c)


def _bn_bshape(x, layout):
    c = _bn_channel_axis(x, layout)
    return tuple(x.shape[c] if i == c else 1 for i in range(x.ndim))


def bn_forward_math(x, scale, bias, running_mean, running_var, eps,
                    momentum, layout, is_test):
    """The batch_norm op's forward math, shared with the fused
    conv2d+bn op's jnp twin (ops/fused_ops.py) so the fused program and
    the unfused chain are BITWISE identical under kernel_tier=jnp.
    Returns (y, new_mean, new_var, saved_mean, saved_var)."""
    from ..core.flags import get_flag

    axes = _bn_axes(x, layout)
    bshape = _bn_bshape(x, layout)

    # stability island: statistics accumulate in float32 straight out of the
    # (possibly bf16) activations — single pass via E[x²]-E[x]², reductions
    # carry an fp32 accumulator (dtype=) so no upcast copy of x is ever
    # materialized; the normalize is one fused elementwise kernel emitting
    # the activation dtype.
    out_dtype = x.dtype

    stat_dtype = jnp.bfloat16 if get_flag("bn_bf16_stats") else jnp.float32
    if is_test:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var
    else:
        mean = jnp.mean(x, axis=axes, dtype=stat_dtype).astype(jnp.float32)
        if x.dtype == jnp.bfloat16 or stat_dtype == jnp.bfloat16:
            # AMP fast path: single-pass E[x²]-E[x]² with fp32 accumulators
            # (the flax recipe). Two separate jnp reductions beat a variadic
            # lax.reduce here: XLA's specialized column-reduce emitter only
            # kicks in for plain monoid reduces (a variadic (Σx, Σx²) reduce
            # measured 2185 vs 2463 img/s on the flagship bench).
            # Cancellation only bites when |mean|/std exceeds ~3e3, beyond
            # bf16 training regimes.
            mean_sq = jnp.mean(jnp.square(x), axis=axes,
                               dtype=stat_dtype).astype(jnp.float32)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
        else:
            # fp32 path keeps the numerically robust centered two-pass form
            var = jnp.var(x, axis=axes)
        new_mean = momentum * running_mean + (1.0 - momentum) * mean
        new_var = momentum * running_var + (1.0 - momentum) * var

    inv_std = jax.lax.rsqrt(var + eps)
    y = (x.astype(jnp.float32) * (scale * inv_std).reshape(bshape)
         + (bias - mean * scale * inv_std).reshape(bshape)).astype(out_dtype)
    return y, new_mean, new_var, mean, var


@register_op("batch_norm", infer_shape=_bn_infer, grad=_bn_grad_maker)
def batch_norm(ctx):
    x = data_of(ctx.input("X"))
    scale = data_of(ctx.input("Scale"))
    bias = data_of(ctx.input("Bias"))
    running_mean = data_of(ctx.input("Mean"))
    running_var = data_of(ctx.input("Variance"))
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    layout = ctx.attr("data_layout", "NCHW")

    from ..core.flags import get_flag
    if get_flag("bn_fusion_barrier") or get_flag("bn_fusion_barrier_fwd"):
        # sever the producer conv from the stat reduces (see flags.py)
        x = jax.lax.optimization_barrier(x)

    y, new_mean, new_var, mean, var = bn_forward_math(
        x, scale, bias, running_mean, running_var, eps, momentum, layout,
        bool(ctx.attr("is_test", False)))
    ctx.set_output("Y", y)
    ctx.set_output("MeanOut", new_mean)
    ctx.set_output("VarianceOut", new_var)
    ctx.set_output("SavedMean", mean)
    ctx.set_output("SavedVariance", var)


def bn_backward_math(x, scale, mean, var, dy, eps, layout, is_test):
    """The batch_norm_grad closed form over the saved statistics, shared
    with the fused conv2d+bn grad's jnp twin. Returns (dx, dscale, dbias);
    dx comes back in the activation dtype."""
    axes = _bn_axes(x, layout)
    bshape = _bn_bshape(x, layout)
    m = x.size // x.shape[_bn_channel_axis(x, layout)]

    # float32 stability island mirroring the forward; dX returns in the
    # activation dtype so the bf16 backward chain stays bf16
    out_dtype = x.dtype
    x = x.astype(jnp.float32)
    dy = dy.astype(jnp.float32)
    inv_std = jax.lax.rsqrt(var + eps).reshape(bshape)
    xhat = (x - mean.reshape(bshape)) * inv_std
    dbias = jnp.sum(dy, axis=axes)
    dscale = jnp.sum(dy * xhat, axis=axes)
    if is_test:
        dx = dy * scale.reshape(bshape) * inv_std
    else:
        dx = (scale.reshape(bshape) * inv_std / m) * (
            m * dy - dbias.reshape(bshape) - xhat * dscale.reshape(bshape))
    return dx.astype(out_dtype), dscale, dbias


@register_op("batch_norm_grad")
def batch_norm_grad(ctx):
    x = data_of(ctx.input("X"))
    scale = data_of(ctx.input("Scale"))
    mean = data_of(ctx.input("SavedMean"))
    var = data_of(ctx.input("SavedVariance"))
    dy = data_of(ctx.input("Y@GRAD"))
    eps = ctx.attr("epsilon", 1e-5)
    layout = ctx.attr("data_layout", "NCHW")
    from ..core.flags import get_flag
    if get_flag("bn_fusion_barrier") or get_flag("bn_fusion_barrier_bwd"):
        x, dy = jax.lax.optimization_barrier((x, dy))
    dx, dscale, dbias = bn_backward_math(
        x, scale, mean, var, dy, eps, layout,
        bool(ctx.attr("is_test", False)))
    ctx.set_output("X@GRAD", dx)
    ctx.set_output("Scale@GRAD", dscale)
    ctx.set_output("Bias@GRAD", dbias)


# ---------------------------------------------------------------------------
# layer_norm
# ---------------------------------------------------------------------------

def _ln_compute(x, scale, bias, begin_norm_axis, eps):
    shape = x.shape
    lead = 1
    for s in shape[:begin_norm_axis]:
        lead *= s
    flat = x.reshape(lead, -1)
    mean = jnp.mean(flat, axis=1, keepdims=True)
    var = jnp.var(flat, axis=1, keepdims=True)
    y = (flat - mean) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale.reshape(1, -1)
    if bias is not None:
        y = y + bias.reshape(1, -1)
    return y.reshape(shape), mean.reshape(lead), var.reshape(lead)


def _ln_infer(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        return
    bna = op.attrs.get("begin_norm_axis", 1)
    lead = 1
    for s in x.shape[:bna]:
        lead *= s
    infer_output(op, block, "Y", x.shape, dtype=x.dtype)
    for slot in ("Mean", "Variance"):
        if op.output(slot):
            infer_output(op, block, slot, (lead,), dtype=x.dtype)


def _ln_grad_maker(op):
    inputs = {"X": op.input("X"), "Y@GRAD": G(op.output("Y"))}
    outputs = {"X@GRAD": G(op.input("X"))}
    if op.input("Scale"):
        inputs["Scale"] = op.input("Scale")
        outputs["Scale@GRAD"] = G(op.input("Scale"))
    if op.input("Bias"):
        inputs["Bias"] = op.input("Bias")
        outputs["Bias@GRAD"] = G(op.input("Bias"))
    return [OpSpec("layer_norm_grad", inputs, outputs, dict(op.attrs))]


@register_op("layer_norm", infer_shape=_ln_infer, grad=_ln_grad_maker)
def layer_norm(ctx):
    x = data_of(ctx.input("X"))
    scale = data_of(ctx.input("Scale")) if ctx.has_input("Scale") else None
    bias = data_of(ctx.input("Bias")) if ctx.has_input("Bias") else None
    y, mean, var = _ln_compute(x, scale, bias,
                               ctx.attr("begin_norm_axis", 1),
                               ctx.attr("epsilon", 1e-5))
    ctx.set_output("Y", y)
    ctx.set_output("Mean", mean)
    ctx.set_output("Variance", var)


@register_op("layer_norm_grad")
def layer_norm_grad(ctx):
    x = data_of(ctx.input("X"))
    scale = data_of(ctx.input("Scale")) if ctx.has_input("Scale") else None
    bias = data_of(ctx.input("Bias")) if ctx.has_input("Bias") else None
    dy = data_of(ctx.input("Y@GRAD"))
    bna = ctx.attr("begin_norm_axis", 1)
    eps = ctx.attr("epsilon", 1e-5)

    args = [x] + ([scale] if scale is not None else []) \
        + ([bias] if bias is not None else [])

    def f(*a):
        s = a[1] if scale is not None else None
        b = a[-1] if bias is not None else None
        return _ln_compute(a[0], s, b, bna, eps)[0]

    _, vjp = jax.vjp(f, *args)
    grads = vjp(dy)
    ctx.set_output("X@GRAD", grads[0])
    if scale is not None:
        ctx.set_output("Scale@GRAD", grads[1])
    if bias is not None:
        ctx.set_output("Bias@GRAD", grads[-1])


# ---------------------------------------------------------------------------
# lrn (cross-channel local response normalization)
# ---------------------------------------------------------------------------

def _lrn_compute(x, n, k, alpha, beta):
    # mid = k + alpha * sum_{c window n} x^2  (lrn_op.cc MidOut)
    sq = x * x
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, n - 1 - half), (0, 0), (0, 0)))
    windows = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * windows
    return x * mid ** (-beta), mid


def _lrn_grad_maker(op):
    return [OpSpec("lrn_grad",
                   {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
                   {"X@GRAD": G(op.input("X"))}, dict(op.attrs))]


@register_op("lrn", infer_shape=same_shape("X", "Out"), grad=_lrn_grad_maker)
def lrn(ctx):
    x = data_of(ctx.input("X"))
    out, mid = _lrn_compute(x, int(ctx.attr("n", 5)), ctx.attr("k", 2.0),
                            ctx.attr("alpha", 1e-4), ctx.attr("beta", 0.75))
    ctx.set_output("Out", out)
    ctx.set_output("MidOut", mid)


@register_op("lrn_grad")
def lrn_grad(ctx):
    x = data_of(ctx.input("X"))
    dy = data_of(ctx.input("Out@GRAD"))
    n, k = int(ctx.attr("n", 5)), ctx.attr("k", 2.0)
    alpha, beta = ctx.attr("alpha", 1e-4), ctx.attr("beta", 0.75)
    _, vjp = jax.vjp(lambda a: _lrn_compute(a, n, k, alpha, beta)[0], x)
    ctx.set_output("X@GRAD", vjp(dy)[0])
