"""Detection (SSD) op family: prior_box, iou_similarity, box_coder,
bipartite_match, target_assign, mine_hard_examples, multiclass_nms, roi_pool.

Reference: /root/reference/paddle/fluid/operators/prior_box_op.h (cell-
centered anchor generation), iou_similarity_op.h, box_coder_op.h
(encode/decode center-size), bipartite_match_op.cc:55-135 (greedy global-max
matching + per-prediction argmax), target_assign_op.h, mine_hard_examples_
op.cc (max_negative mining), multiclass_nms_op.cc:100-250 (per-class NMSFast
with adaptive eta threshold + cross-class keep_top_k), roi_pool_op.cc.

TPU-native design: the reference runs all of these CPU-only (no CUDA
kernels for the SSD set) in loops; here the vectorizable ones (iou,
box_coder, prior_box, target_assign, roi_pool) are pure jnp broadcasting,
and the inherently sequential ones (bipartite matching, NMS) are bounded
``lax.fori_loop``s with masking over STATIC box counts — the standard
compiled-NMS formulation — batched by jax.vmap. Ragged outputs
(multiclass_nms's variable detection count) use the framework's padded
LoDArray convention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.lod import LoDArray
from ..core.registry import register_op
from .common import data_of


# ---------------------------------------------------------------------------
# iou_similarity
# ---------------------------------------------------------------------------

def _iou_matrix(x, y, normalized=True):
    """x [N,4], y [M,4] -> [N,M] Jaccard overlap
    (multiclass_nms_op.cc:112-129 JaccardOverlap)."""
    area = lambda b: jnp.maximum(b[..., 2] - b[..., 0], 0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0) if normalized else \
        (b[..., 2] - b[..., 0] + 1) * (b[..., 3] - b[..., 1] + 1)
    xi = x[:, None, :]
    yi = y[None, :, :]
    ix_min = jnp.maximum(xi[..., 0], yi[..., 0])
    iy_min = jnp.maximum(xi[..., 1], yi[..., 1])
    ix_max = jnp.minimum(xi[..., 2], yi[..., 2])
    iy_max = jnp.minimum(xi[..., 3], yi[..., 3])
    iw = jnp.maximum(ix_max - ix_min, 0.0)
    ih = jnp.maximum(iy_max - iy_min, 0.0)
    inter = iw * ih
    union = area(xi) + area(yi) - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity")
def iou_similarity(ctx):
    xv = ctx.input("X")
    x = data_of(xv)
    y = data_of(ctx.input("Y"))
    if x.ndim == 3:   # padded LoD batch [b, n, 4]
        out = jax.vmap(lambda a: _iou_matrix(a, y))(x)
        ctx.set_output("Out", LoDArray(out, xv.lens)
                       if isinstance(xv, LoDArray) else out)
        return
    ctx.set_output("Out", _iou_matrix(x, y))


# ---------------------------------------------------------------------------
# prior_box
# ---------------------------------------------------------------------------

def _expand_aspect_ratios(aspect_ratios, flip):
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(ar)
        if flip:
            out.append(1.0 / ar)
    return out


@register_op("prior_box")
def prior_box(ctx):
    """Anchor boxes per feature-map cell (prior_box_op.h:88-165): per
    min_size — [min, sqrt(min·max) if max, min·√ar for ar≠1...] — centered
    at (w+offset)·step, normalized by image size, optionally clipped."""
    feat = data_of(ctx.input("Input"))
    img = data_of(ctx.input("Image"))
    min_sizes = [float(s) for s in ctx.attr("min_sizes")]
    max_sizes = [float(s) for s in ctx.attr("max_sizes", []) or []]
    ars = _expand_aspect_ratios(
        [float(a) for a in ctx.attr("aspect_ratios", [1.0])],
        bool(ctx.attr("flip", False)))
    variances = [float(v) for v in ctx.attr("variances",
                                            [0.1, 0.1, 0.2, 0.2])]
    clip = bool(ctx.attr("clip", False))
    offset = float(ctx.attr("offset", 0.5))
    ih, iw = img.shape[2], img.shape[3]
    fh, fw = feat.shape[2], feat.shape[3]
    step_w = float(ctx.attr("step_w", 0.0)) or iw / fw
    step_h = float(ctx.attr("step_h", 0.0)) or ih / fh

    # per-cell half-extents, in the reference's prior order
    half = []
    for s, mn in enumerate(min_sizes):
        half.append((mn / 2.0, mn / 2.0))
        if max_sizes:
            mx = (mn * max_sizes[s]) ** 0.5
            half.append((mx / 2.0, mx / 2.0))
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            half.append((mn * ar ** 0.5 / 2.0, mn / ar ** 0.5 / 2.0))
    half = jnp.asarray(half, jnp.float32)              # [P, 2] (w, h)
    num_priors = half.shape[0]

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, num_priors))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, num_priors))
    bw = half[None, None, :, 0]
    bh = half[None, None, :, 1]
    boxes = jnp.stack([(cxg - bw) / iw, (cyg - bh) / ih,
                       (cxg + bw) / iw, (cyg + bh) / ih], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (fh, fw, num_priors, 4))
    ctx.set_output("Boxes", boxes)
    ctx.set_output("Variances", var)


# ---------------------------------------------------------------------------
# box_coder
# ---------------------------------------------------------------------------

def _center_size(b):
    w = b[..., 2] - b[..., 0]
    h = b[..., 3] - b[..., 1]
    cx = (b[..., 2] + b[..., 0]) / 2
    cy = (b[..., 3] + b[..., 1]) / 2
    return cx, cy, w, h


@register_op("box_coder")
def box_coder(ctx):
    """encode_center_size / decode_center_size (box_coder_op.h:33-125).
    encode: T [N,4] targets x P [M,4] priors -> [N,M,4] offsets;
    decode: T [N,M,4] offsets + priors -> [N,M,4] corner boxes."""
    prior = data_of(ctx.input("PriorBox"))
    pvar = data_of(ctx.input("PriorBoxVar")) \
        if ctx.has_input("PriorBoxVar") \
        else jnp.ones((prior.shape[0], 4), jnp.float32)
    tv = ctx.input("TargetBox")
    target = data_of(tv)
    code_type = ctx.attr("code_type", "encode_center_size")
    pcx, pcy, pw, ph = _center_size(prior)            # [M]

    if code_type == "encode_center_size":
        if target.ndim == 3:
            # aligned encode (ssd_loss): target [b, M, 4] already gathered
            # per prior -> elementwise offsets [b, M, 4] (the later
            # reference's axis=0 box_coder semantics)
            tcx, tcy, tw, th = _center_size(target)   # [b, M]
            out = jnp.stack([
                (tcx - pcx[None, :]) / pw[None, :] / pvar[None, :, 0],
                (tcy - pcy[None, :]) / ph[None, :] / pvar[None, :, 1],
                jnp.log(jnp.maximum(jnp.abs(tw / pw[None, :]), 1e-10))
                / pvar[None, :, 2],
                jnp.log(jnp.maximum(jnp.abs(th / ph[None, :]), 1e-10))
                / pvar[None, :, 3],
            ], axis=-1)
            ctx.set_output("OutputBox", out)
            return
        tcx, tcy, tw, th = _center_size(target)       # [N]
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :] / pvar[None, :, 0],
            (tcy[:, None] - pcy[None, :]) / ph[None, :] / pvar[None, :, 1],
            jnp.log(jnp.abs(tw[:, None] / pw[None, :])) / pvar[None, :, 2],
            jnp.log(jnp.abs(th[:, None] / ph[None, :])) / pvar[None, :, 3],
        ], axis=-1)
    else:
        t = target if target.ndim == 3 else target[:, None, :]
        cx = pvar[None, :, 0] * t[..., 0] * pw[None, :] + pcx[None, :]
        cy = pvar[None, :, 1] * t[..., 1] * ph[None, :] + pcy[None, :]
        w = jnp.exp(pvar[None, :, 2] * t[..., 2]) * pw[None, :]
        h = jnp.exp(pvar[None, :, 3] * t[..., 3]) * ph[None, :]
        out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                        axis=-1)
    ctx.set_output("OutputBox", LoDArray(out, tv.lens)
                   if isinstance(tv, LoDArray) else out)


# ---------------------------------------------------------------------------
# bipartite_match
# ---------------------------------------------------------------------------

def _bipartite_match_single(dist, valid_rows):
    """Greedy global-max matching (bipartite_match_op.cc:59-103): repeat
    min(row,col) times — pick the max entry among unused rows/cols (>eps),
    bind them. valid_rows masks padded LoD rows."""
    row, col = dist.shape
    eps = 1e-6
    neg = jnp.asarray(-1.0, dist.dtype)

    def body(_, carry):
        match_idx, match_dist, row_used, col_used = carry
        masked = jnp.where(row_used[:, None] | col_used[None, :]
                           | (dist < eps), neg, dist)
        flat = jnp.argmax(masked)
        r, c = flat // col, flat % col
        best = masked[r, c]
        ok = best > 0
        match_idx = jnp.where(ok, match_idx.at[c].set(r.astype(jnp.int32)),
                              match_idx)
        match_dist = jnp.where(ok, match_dist.at[c].set(best), match_dist)
        row_used = jnp.where(ok, row_used.at[r].set(True), row_used)
        col_used = jnp.where(ok, col_used.at[c].set(True), col_used)
        return match_idx, match_dist, row_used, col_used

    init = (jnp.full((col,), -1, jnp.int32), jnp.zeros((col,), dist.dtype),
            ~valid_rows, jnp.zeros((col,), jnp.bool_))
    match_idx, match_dist, _, _ = lax.fori_loop(
        0, min(row, col), body, init)
    return match_idx, match_dist


def _argmax_match_extend(dist, match_idx, match_dist, valid_rows, thresh):
    """ArgMaxMatch (bipartite_match_op.cc:105-135): unmatched columns take
    their argmax row when overlap >= threshold."""
    eps = 1e-6
    masked = jnp.where(valid_rows[:, None], dist, -1.0)
    best_row = jnp.argmax(masked, axis=0).astype(jnp.int32)
    best = jnp.max(masked, axis=0)
    take = (match_idx == -1) & (best >= thresh) & (best >= eps)
    return (jnp.where(take, best_row, match_idx),
            jnp.where(take, best, match_dist))


@register_op("bipartite_match")
def bipartite_match(ctx):
    dv = ctx.input("DistMat")
    dist = data_of(dv)
    match_type = ctx.attr("match_type", "bipartite")
    thresh = float(ctx.attr("dist_threshold", 0.5))
    if dist.ndim == 2:
        dist = dist[None]
        lens = None
    else:
        lens = dv.lens if isinstance(dv, LoDArray) else None
    b, row, col = dist.shape
    valid = (jnp.arange(row)[None, :] < lens[:, None]) if lens is not None \
        else jnp.ones((b, row), jnp.bool_)

    def one(d, v):
        mi, md = _bipartite_match_single(d, v)
        if match_type == "per_prediction":
            mi, md = _argmax_match_extend(d, mi, md, v, thresh)
        return mi, md

    mi, md = jax.vmap(one)(dist, valid)
    ctx.set_output("ColToRowMatchIndices", mi)
    ctx.set_output("ColToRowMatchDist", md)


# ---------------------------------------------------------------------------
# target_assign
# ---------------------------------------------------------------------------

@register_op("target_assign")
def target_assign(ctx):
    """out[i,j] = X[i, match[i,j]] where match >= 0 else mismatch_value;
    weight 1 for matched, 0 otherwise (target_assign_op.h). X is the
    (padded-LoD) per-image gt rows [b, n, K]."""
    xv = ctx.input("X")
    x = data_of(xv)
    match = data_of(ctx.input("MatchIndices")).astype(jnp.int32)
    mismatch = ctx.attr("mismatch_value", 0)
    matched = match >= 0
    safe = jnp.maximum(match, 0)
    bidx = jnp.arange(x.shape[0])[:, None]
    gathered = x[bidx, safe]                       # [b, col, K]
    out = jnp.where(matched[..., None], gathered,
                    jnp.asarray(mismatch, x.dtype))
    ctx.set_output("Out", out)
    ctx.set_output("OutWeight", matched[..., None].astype(jnp.float32))


# ---------------------------------------------------------------------------
# mine_hard_examples
# ---------------------------------------------------------------------------

@register_op("mine_hard_examples")
def mine_hard_examples(ctx):
    """max_negative mining (mine_hard_examples_op.cc): per image, negatives
    (match == -1) ranked by classification loss desc; keep
    neg_pos_ratio * num_pos of them. Outputs a padded 0/1 NegMask [b, P]
    (the dense equivalent of the reference's LoD NegIndices) and
    UpdatedMatchIndices where unselected negatives stay -1."""
    cls_loss = data_of(ctx.input("ClsLoss"))        # [b, P]
    match = data_of(ctx.input("MatchIndices")).astype(jnp.int32)
    neg_pos_ratio = float(ctx.attr("neg_pos_ratio", 3.0))
    neg_overlap = float(ctx.attr("neg_dist_threshold", 0.5))
    dist = data_of(ctx.input("MatchDist")) if ctx.has_input("MatchDist") \
        else None

    is_neg = match == -1
    if dist is not None:
        is_neg = is_neg & (dist < neg_overlap)
    num_pos = jnp.sum(match >= 0, axis=1)
    num_neg = jnp.minimum((num_pos * neg_pos_ratio).astype(jnp.int32),
                          jnp.sum(is_neg, axis=1).astype(jnp.int32))
    masked_loss = jnp.where(is_neg, cls_loss, -jnp.inf)
    order = jnp.argsort(-masked_loss, axis=1)
    rank = jnp.argsort(order, axis=1)               # rank of each prior
    selected = (rank < num_neg[:, None]) & is_neg
    ctx.set_output("NegMask", selected.astype(jnp.int32))
    ctx.set_output("UpdatedMatchIndices",
                   jnp.where(selected, -1, match).astype(jnp.int32))


# ---------------------------------------------------------------------------
# multiclass_nms
# ---------------------------------------------------------------------------

def _nms_class(iou_all, scores, score_threshold, nms_threshold, eta, top_k):
    """Compiled NMSFast (multiclass_nms_op.cc:134-172): sort desc, walk the
    order keeping boxes whose IoU with every kept box <= the (eta-adaptive)
    threshold. Returns a keep mask aligned with the boxes. ``iou_all`` is
    the pairwise IoU computed ONCE per image (classes only differ in sort
    order, so each class just permutes it)."""
    n = scores.shape[0]
    k = n if top_k < 0 else min(int(top_k), n)
    order = jnp.argsort(-scores)
    sscores = scores[order]
    iou = iou_all[order][:, order]

    def body(i, carry):
        keep, thresh = carry
        cand_ok = (sscores[i] > score_threshold)
        sup = jnp.any(keep & (iou[i] > thresh) &
                      (jnp.arange(n) < i))
        ok = cand_ok & (~sup) & (i < k)
        keep = keep.at[i].set(ok)
        thresh = jnp.where(ok & (eta < 1.0) & (thresh > 0.5), thresh * eta,
                           thresh)
        return keep, thresh

    keep, _ = lax.fori_loop(0, n, body,
                            (jnp.zeros((n,), jnp.bool_),
                             jnp.asarray(nms_threshold, jnp.float32)))
    # unsort back to original indexing
    inv = jnp.argsort(order)
    return keep[inv]


@register_op("multiclass_nms")
def multiclass_nms(ctx):
    """Per-class NMS + cross-class keep_top_k (multiclass_nms_op.cc:174-
    250). Inputs BBoxes [b, P, 4], Scores [b, C, P]; output a LoDArray of
    [b, keep_top_k, 6] rows (label, score, x1, y1, x2, y2), padded with
    label -1 past each image's detection count (the reference emits
    [num_kept, 6] with LoD; lens carries the counts here)."""
    boxes = data_of(ctx.input("BBoxes"))
    scores = data_of(ctx.input("Scores"))
    bg = int(ctx.attr("background_label", 0))
    score_threshold = float(ctx.attr("score_threshold"))
    nms_top_k = int(ctx.attr("nms_top_k"))
    keep_top_k = int(ctx.attr("keep_top_k"))
    nms_threshold = float(ctx.attr("nms_threshold", 0.3))
    eta = float(ctx.attr("nms_eta", 1.0))

    b, C, P = scores.shape
    K = keep_top_k if keep_top_k > 0 else C * P
    # background never enters NMS (the reference skips it before NMSFast)
    fg_classes = jnp.asarray([c for c in range(C) if c != bg]
                             if 0 <= bg < C else list(range(C)), jnp.int32)

    def one(bx, sc):
        iou_all = _iou_matrix(bx, bx)               # once per image
        fg_scores = sc[fg_classes]                  # [C', P]

        def per_class(c_scores):
            return _nms_class(iou_all, c_scores, score_threshold,
                              nms_threshold, eta, nms_top_k)
        keep = jax.vmap(per_class)(fg_scores)       # [C', P]
        flat_scores = jnp.where(keep, fg_scores, -jnp.inf).reshape(-1)
        k = min(K, int(fg_classes.shape[0]) * P)
        top_scores, top_idx = lax.top_k(flat_scores, k)
        label = fg_classes[top_idx // P].astype(jnp.float32)
        pbox = bx[top_idx % P]
        valid = top_scores > -jnp.inf
        count = jnp.sum(valid).astype(jnp.int32)
        rows = jnp.concatenate([
            jnp.where(valid, label, -1.0)[:, None],
            jnp.where(valid, top_scores, 0.0)[:, None],
            jnp.where(valid[:, None], pbox, 0.0)], axis=1)
        return rows, count

    rows, counts = jax.vmap(one)(boxes, scores)
    ctx.set_output("Out", LoDArray(rows, counts))


# ---------------------------------------------------------------------------
# roi_pool
# ---------------------------------------------------------------------------

@register_op("roi_pool")
def roi_pool(ctx):
    """Max-pool each ROI to [pooled_h, pooled_w] (roi_pool_op.cc; Fast
    R-CNN). ROIs [R, 5] (batch_idx, x1, y1, x2, y2) at spatial_scale of the
    NCHW input."""
    x = data_of(ctx.input("X"))                     # [N, C, H, W]
    rois = data_of(ctx.input("ROIs"))               # [R, 5]
    ph = int(ctx.attr("pooled_height"))
    pw = int(ctx.attr("pooled_width"))
    scale = float(ctx.attr("spatial_scale", 1.0))
    N, C, H, W = x.shape

    def one(roi):
        bi = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = x[bi]                                 # [C, H, W]
        hh = jnp.arange(H)
        ww = jnp.arange(W)

        def cell(i, j):
            hs = y1 + (i * rh) // ph
            he = y1 + ((i + 1) * rh + ph - 1) // ph
            ws = x1 + (j * rw) // pw
            we = x1 + ((j + 1) * rw + pw - 1) // pw
            hs, he = jnp.clip(hs, 0, H), jnp.clip(he, 0, H)
            ws, we = jnp.clip(ws, 0, W), jnp.clip(we, 0, W)
            m = ((hh[:, None] >= hs) & (hh[:, None] < he)
                 & (ww[None, :] >= ws) & (ww[None, :] < we))
            empty = ~(m.any())
            vals = jnp.where(m[None], img, -jnp.inf)
            mx = jnp.max(vals, axis=(1, 2))
            return jnp.where(empty, 0.0, mx)        # [C]

        ii = jnp.arange(ph)
        jj = jnp.arange(pw)
        grid = jax.vmap(lambda i: jax.vmap(lambda j: cell(i, j))(jj))(ii)
        return jnp.transpose(grid, (2, 0, 1))       # [C, ph, pw]

    ctx.set_output("Out", jax.vmap(one)(rois))