"""IO / runtime op forms: fill, delete_var, save, load, save_combine,
load_combine, get_places, lod_array_length, read, channel ops, go.

Reference: /root/reference/paddle/fluid/operators/{fill_op.cc (dtype + flat
"data" attr reshaped to "shape"), save_op.cc / load_op.cc (file_path attr,
overwrite check), save_combine_op.cc / load_combine_op.cc (many vars, one
file, order-preserving), delete_var_op.cc, get_places_op.cc (device_count /
device_type), lod_array_length_op.cc, read_op.cc (pops a batch from a READER
var), channel_create/close/send/recv_op.cc (ChannelHolder var),
go_op.cc (spawns the sub-block on the ThreadPool)}.

TPU-native notes: checkpoint persistence is owned by fluid/io.py's
manifest-based save/load (atomic renames); these op forms expose the same
serialization through the reference's op-driven contract, so programs that
embed save/load/fill ops (the reference's io.py builds exactly such tiny
programs) run unchanged. They are HOST ops: they run in the eager
interpreter or at trace time on concrete values — a jit-compiled training
step never embeds them (the reference likewise runs save/load in separate
tiny programs, python/paddle/fluid/io.py:145,234). Channels/Go wrap the
host-side CSP objects of fluid/concurrency.py, keeping channel state in the
scope exactly like the reference's ChannelHolder variables.
"""

from __future__ import annotations

import os
import threading

import numpy as np
import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op
from ..core.types import np_dtype

import weakref

# promoted-iterator cache for reader creators without a settable __dict__
# (see the read op)
_PROMOTED: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _require_concrete(op_type, *values):
    for v in values:
        for leaf in jax.tree_util.tree_leaves(v):
            if isinstance(leaf, jax.core.Tracer):
                raise RuntimeError(
                    f"op {op_type!r} is a host op (IO/CSP) and cannot be "
                    "traced into a jit-compiled step; run its program with "
                    "Executor(mode='eager') like the reference's save/load "
                    "programs")


# ---------------------------------------------------------------------------
# fill / delete_var / get_places
# ---------------------------------------------------------------------------

@register_op("fill")
def fill(ctx):
    """fill_op.cc: flat "data" attr values reshaped to "shape"."""
    dtype = np_dtype(ctx.attr("dtype", "float32"))
    shape = tuple(ctx.attr("shape"))
    data = np.asarray(ctx.attr("data"), dtype=dtype).reshape(shape)
    ctx.set_output("Out", jnp.asarray(data))


@register_op("delete_var")
def delete_var(ctx):
    """delete_var_op.cc: drop variables from the runtime environment."""
    for name in ctx.op.input("X"):
        ctx.env.pop(name, None)


@register_op("get_places")
def get_places(ctx):
    """get_places_op.cc: emit the device list (device_count=0 -> all)."""
    kind = ctx.attr("device_type", "AUTO")
    count = int(ctx.attr("device_count", 0) or 0)
    if kind in ("CPU",):
        devs = jax.devices("cpu")
    else:
        devs = jax.devices()
    if count:
        devs = devs[:count]
    ctx.set_output("Out", list(devs))


# ---------------------------------------------------------------------------
# save / load (single var)  +  save_combine / load_combine
# ---------------------------------------------------------------------------

def _to_numpy(v):
    if isinstance(v, LoDArray):
        return {"data": np.asarray(v.data), "lens": np.asarray(v.lens),
                "outer": [np.asarray(o) for o in v.outer_levels]}
    return np.asarray(v)


def _from_numpy(v):
    if isinstance(v, dict):
        return LoDArray(jnp.asarray(v["data"]), jnp.asarray(v["lens"]),
                        tuple(jnp.asarray(o) for o in v["outer"]) or None)
    return jnp.asarray(v)


@register_op("save")
def save(ctx):
    v = ctx.input("X")
    _require_concrete("save", v)
    path = ctx.attr("file_path")
    if not ctx.attr("overwrite", True) and os.path.exists(path):
        raise FileExistsError(f"save: {path} exists and overwrite=False "
                              "(save_op.cc overwrite check)")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.save(f, _to_numpy(v), allow_pickle=True)
    os.replace(tmp, path)


@register_op("load")
def load(ctx):
    path = ctx.attr("file_path")
    with open(path, "rb") as f:
        v = np.load(f, allow_pickle=True)
    if v.dtype == object:
        v = v.item()
    ctx.set_output("Out", _from_numpy(v))


@register_op("save_combine")
def save_combine(ctx):
    vs = ctx.inputs("X")
    _require_concrete("save_combine", *vs)
    path = ctx.attr("file_path")
    if not ctx.attr("overwrite", True) and os.path.exists(path):
        raise FileExistsError(f"save_combine: {path} exists")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    # order-preserving container (load_combine restores by position,
    # save_combine_op.cc serializes sequentially). Build the object vector
    # explicitly: np.asarray(list, dtype=object) would collapse same-shaped
    # tensors into one deep (N, *shape) array and break the round-trip.
    container = np.empty(len(vs), dtype=object)
    container[:] = [_to_numpy(v) for v in vs]
    with open(tmp, "wb") as f:
        np.save(f, container, allow_pickle=True)
    os.replace(tmp, path)


@register_op("load_combine")
def load_combine(ctx):
    path = ctx.attr("file_path")
    with open(path, "rb") as f:
        vs = np.load(f, allow_pickle=True)
    ctx.set_outputs("Out", [_from_numpy(v) for v in vs])


# ---------------------------------------------------------------------------
# lod_array_length / read
# ---------------------------------------------------------------------------

@register_op("lod_array_length")
def lod_array_length(ctx):
    """lod_array_length_op.cc: scalar int64 length of a tensor array."""
    arr = ctx.input("X")
    ctx.set_output("Out", arr.length.astype(jnp.int64).reshape((1,)))


# ---------------------------------------------------------------------------
# reader creation/decoration ops (reference operators/reader/: the startup
# program builds the reader chain into a persistable READER var; runtime
# values are reader-creator CALLABLES from paddle_tpu.reader, promoted to
# live iterators by the read op at first pop)
# ---------------------------------------------------------------------------

@register_op("create_recordio_file_reader")
def create_recordio_file_reader(ctx):
    """create_recordio_file_reader_op.cc / open_files: a creator over one or
    more recordio files; dict records (fluid.recordio_writer batches) become
    slot tuples in insertion (feed) order, tuple records pass through.

    ``thread_num > 1`` (the open_files form) shards the file list into one
    raw-bytes reader per file, interleaved, with record decode running on a
    thread_num-wide WorkerPool (reader/pool.py) — the host-parallel decode
    the reference got from its C++ prefetch pool. The pool lives for one
    pass: created at iterator start, shut down when the pass ends or the
    iterator is abandoned."""
    from ..reader import creator as reader_creator

    paths = list(ctx.attr("filenames"))
    thread_num = int(ctx.attr("thread_num", 1) or 1)

    def _as_tuple(rec):
        if isinstance(rec, dict):
            return tuple(rec.values())
        return rec

    def make():
        base = reader_creator.recordio_sharded(paths, thread_num)
        return (_as_tuple(r) for r in base())

    ctx.set_output("Out", make)


@register_op("create_shuffle_reader")
def create_shuffle_reader_op(ctx):
    from ..reader.decorator import shuffle
    ctx.set_output("Out", shuffle(ctx.input("UnderlyingReader"),
                                  int(ctx.attr("buffer_size", 1024))))


@register_op("create_double_buffer_reader")
def create_double_buffer_reader_op(ctx):
    """create_double_buffer_reader_op.cc: a background thread keeps the
    next batches DEVICE-STAGED while the consumer computes (the shared
    background_buffer helper; the feed-dict flavor in reader/prefetch.py
    uses the same one). The layer's ``place`` attr picks the staging
    device."""
    from ..reader.prefetch import background_buffer

    underlying = ctx.input("UnderlyingReader")
    capacity = int(ctx.attr("capacity", 2) or 2)
    place = str(ctx.attr("place", "") or "")
    device = jax.devices("cpu")[0] if "CPU" in place.upper() \
        else jax.devices()[0]

    def stage(item):
        # ONE device_put per batch (the slot tuple is a pytree): one
        # transfer submission instead of a round trip per slot — on remote
        # TPU attachments each host->device call costs a full round trip
        if isinstance(item, (tuple, list)):
            return jax.device_put(tuple(np.asarray(v) for v in item),
                                  device)
        return jax.device_put(np.asarray(item), device)

    ctx.set_output("Out", background_buffer(underlying, capacity, stage))


@register_op("create_multi_pass_reader")
def create_multi_pass_reader_op(ctx):
    underlying = ctx.input("UnderlyingReader")
    pass_num = int(ctx.attr("pass_num", 1))

    def make():
        for _ in range(pass_num):
            yield from underlying()

    ctx.set_output("Out", make)


@register_op("read")
def read(ctx):
    """read_op.cc: pop the next sample batch from a READER variable (here a
    host iterator placed in the scope by the reader framework) into the
    output vars; raises StopIteration at end-of-data like the reference
    (executor catches it to end the pass)."""
    reader = ctx.input("Reader")
    if callable(reader) and not hasattr(reader, "__next__"):
        # a reader creator: promote to a live iterator ONCE and cache it ON
        # the creator object — the creator is what persists in the scope
        # (the read op only READS the reader var, so env rebinds don't
        # survive state write-back), exactly the reference's
        # ReaderHolder-in-scope contract (framework/reader.h:68). Creators
        # without __dict__ (e.g. functools.partial) cache via weakref.
        it = getattr(reader, "__promoted_iter__", None) \
            or _PROMOTED.get(reader)
        if it is None:
            it = iter(reader())
            try:
                reader.__promoted_iter__ = it
            except AttributeError:
                _PROMOTED[reader] = it   # TypeError here = unweakrefable
                # creator: a loud error beats silently re-reading batch 0
        creator, reader = reader, it
    else:
        creator = None
    try:
        batch = next(reader)
    except StopIteration:
        # end of pass: clear the cached iterator so the next run starts a
        # fresh pass (the reference's reader reset semantics)
        if creator is not None:
            if hasattr(creator, "__dict__"):
                creator.__dict__.pop("__promoted_iter__", None)
            _PROMOTED.pop(creator, None)
        raise
    outs = ctx.op.output("Out")
    if len(outs) == 1 and not isinstance(batch, (tuple, list)):
        batch = (batch,)
    ctx.set_outputs("Out", [jnp.asarray(np.asarray(b)) for b in batch])


# ---------------------------------------------------------------------------
# CSP channel ops + go (host concurrency through the scope)
# ---------------------------------------------------------------------------

@register_op("channel_create")
def channel_create(ctx):
    from ..fluid.concurrency import Channel
    ctx.set_output("Out", Channel(dtype=ctx.attr("data_type", "float32"),
                                  capacity=int(ctx.attr("capacity", 0))))


@register_op("channel_send")
def channel_send(ctx):
    ch = ctx.input("Channel")
    v = ctx.input("X")
    _require_concrete("channel_send", v)
    ch.send(v)


@register_op("channel_recv")
def channel_recv(ctx):
    ch = ctx.input("Channel")
    v, ok = ch.recv()
    ctx.set_output("Out", v)
    ctx.set_output("Status", jnp.asarray(ok))


@register_op("channel_close")
def channel_close(ctx):
    ctx.input("Channel").close()


@register_op("go", is_control_flow=True)
def go(ctx):
    """go_op.cc: run the sub-block concurrently on a daemon thread over a
    snapshot of the environment (channels inside it are shared objects — the
    communication medium, like the reference's captured scope)."""
    sub = ctx.sub_block()
    env_snapshot = dict(ctx.env)
    _require_concrete("go", *[v for v in env_snapshot.values()
                              if isinstance(v, jax.Array)])
    exec_state = ctx._exec
    from ..core.executor import _run_ops

    t = threading.Thread(target=_run_ops, args=(sub, env_snapshot, exec_state),
                         daemon=True)
    t.start()
    ctx.set_output("Out", t)
