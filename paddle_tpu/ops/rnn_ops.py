"""Recurrent ops: dynamic_lstm, dynamic_gru, lstm_unit, gru_unit.

Reference: /root/reference/paddle/fluid/operators/lstm_op.cc (dynamic LSTM
over a ragged batch reordered by math/sequence2batch.h, fused gate kernels in
math/detail/lstm_kernel.h), gru_op.cc, lstm_unit_op.cc, gru_unit_op.cc.

TPU-native design: the reference reorders the ragged batch time-major and
launches one fused CUDA kernel per step (hl_cuda_lstm.cu hand-scheduled
kernels); here each RNN is ONE ``lax.scan`` over the padded LoDArray with a
length mask — XLA fuses the gate math, and the scanned matmul hits the MXU.
Gate layouts (documented contract of this framework):

* LSTM projected input / recurrent weight column order: [i, f, c, o]
  (input, forget, candidate, output), weight shape [H, 4H]. NOTE: the
  reference stores [c, i, f, o] (lstm_op.cc:125) — reference-trained
  weights must be permuted via
  ``paddle_tpu.utils.convert_reference_lstm_weight`` on import.
* GRU projected input order: [u, r, c] (update, reset, candidate);
  weight [H, 3H] = [W_u | W_r | W_c] like the reference gru_op
  ("the first 2H columns are update/reset, the last H candidate").
  h_t = u * c_t + (1 - u) * h_{t-1}, matching the reference kernel
  ``h = u * (c - h_prev) + h_prev`` (gru_unit_op.h; math/detail/gru_kernel.h).

Gradients flow through ``jax.vjp`` over the scan (XLA reverse-scan), the
functional analog of the reference's hand-written LstmGradKernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op, OpSpec, same_shape
from .common import G, data_of


def _act(name):
    return {
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "relu": jax.nn.relu,
        "identity": lambda x: x,
    }[name or "identity"]


def _reverse_padded(data, lens):
    """Reverse each row's valid prefix in place (padding stays at the end):
    the is_reverse attr of lstm/gru ops."""
    L = data.shape[1]
    idx = lens[:, None] - 1 - jnp.arange(L)[None, :]
    valid = idx >= 0
    idx = jnp.where(valid, idx, jnp.arange(L)[None, :])
    idx = jnp.broadcast_to(
        idx.reshape(idx.shape + (1,) * (data.ndim - 2)),
        idx.shape + data.shape[2:]).astype(jnp.int32)
    return jnp.take_along_axis(data, idx, axis=1)


def _lstm_scan(x, lens, w, h0, c0, gate_act, cell_act, cand_act,
               peepholes=None):
    """x: [b, L, 4H] projected inputs (+bias already added); w: [H, 4H].
    ``peepholes``: optional (w_ic, w_fc, w_oc) each [H] — the reference's
    diagonal cell->gate connections (math/detail/lstm_kernel.h:37-40:
    i/f see the PREVIOUS cell state, o sees the NEW one). Returns
    hidden [b, L, H], cell [b, L, H]."""
    from .autotune import dispatch_variant, make_key
    from .pallas import kernel_span

    b, L, H4 = x.shape
    H = H4 // 4
    ga, ca, cda = _act(gate_act), _act(cell_act), _act(cand_act)
    # the Pallas fused cell implements the standard activation set (the
    # reference's hand-scheduled hl_cuda_lstm.cu does the same); other
    # activations / peepholes fall back to the scan with a counter bump
    supported = (peepholes is None
                 and (gate_act, cell_act, cand_act)
                 == ("sigmoid", "tanh", "tanh"))
    choice = dispatch_variant(
        "rnn",
        make_key(cell="lstm", x=tuple(x.shape), dtype=str(x.dtype)),
        {"jnp": True, "pallas": supported}, tier_kernel="lstm")

    if choice == "pallas":
        # whole-recurrence kernel: ONE launch for the full sequence with
        # the recurrent weight VMEM-resident across steps (see
        # ops/pallas/rnn.lstm_seq_pallas)
        from .pallas.rnn import lstm_seq_pallas
        with kernel_span("pallas", "lstm"):
            xt = jnp.swapaxes(x, 0, 1)               # [L, b, 4H]
            alive = (jnp.arange(L)[:, None] < lens[None, :]) \
                .astype(x.dtype)[..., None]          # [L, b, 1]
            hs, cs = lstm_seq_pallas(xt, alive, w, h0, c0)
            hs = hs * alive
            cs = cs * alive
            return jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)

    def step(carry, inp):
        h_prev, c_prev, t = carry
        xt = inp                                     # [b, 4H]
        gates = xt + h_prev @ w                      # MXU matmul
        alive = (t < lens)[:, None].astype(x.dtype)
        gi = gates[:, :H]
        gf = gates[:, H:2 * H]
        go = gates[:, 3 * H:]
        if peepholes is not None:
            w_ic, w_fc, w_oc = peepholes
            gi = gi + c_prev * w_ic[None, :]
            gf = gf + c_prev * w_fc[None, :]
        i = ga(gi)
        f = ga(gf)
        cand = cda(gates[:, 2 * H:3 * H])
        c = f * c_prev + i * cand
        if peepholes is not None:
            go = go + c * w_oc[None, :]
        o = ga(go)
        h = o * ca(c)
        h = alive * h + (1 - alive) * h_prev
        c = alive * c + (1 - alive) * c_prev
        return (h, c, t + 1), (h * alive, c * alive)

    xt = jnp.swapaxes(x, 0, 1)                       # [L, b, 4H]
    (_, _, _), (hs, cs) = jax.lax.scan(
        step, (h0, c0, jnp.zeros((), jnp.int32)), xt)
    return jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)


def _lstm_compute(x, lens, w, bias, h0, c0, attrs):
    b, L, H4 = x.shape
    H = H4 // 4
    peepholes = None
    if bias is not None:
        x = x + bias[None, None, :H4]
        if bias.shape[-1] == 7 * H:
            # reference bias layout with use_peepholes (lstm_op.cc:74):
            # [4H gate bias | W_ic | W_fc | W_oc]
            peepholes = (bias[4 * H:5 * H], bias[5 * H:6 * H],
                         bias[6 * H:7 * H])
    if h0 is None:
        h0 = jnp.zeros((b, H), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((b, H), x.dtype)
    rev = attrs.get("is_reverse", False)
    if rev:
        x = _reverse_padded(x, lens)
    hs, cs = _lstm_scan(x, lens, w,
                        h0, c0,
                        attrs.get("gate_activation", "sigmoid"),
                        attrs.get("cell_activation", "tanh"),
                        attrs.get("candidate_activation", "tanh"),
                        peepholes=peepholes)
    if rev:
        hs = _reverse_padded(hs, lens)
        cs = _reverse_padded(cs, lens)
    return hs, cs


def _lstm_grad_maker(op):
    inputs = {"Input": op.input("Input"), "Weight": op.input("Weight"),
              "Hidden@GRAD": G(op.output("Hidden")),
              "Cell@GRAD": G(op.output("Cell"))}
    outputs = {"Input@GRAD": G(op.input("Input")),
               "Weight@GRAD": G(op.input("Weight"))}
    for slot in ("Bias", "H0", "C0"):
        if op.input(slot):
            inputs[slot] = op.input(slot)
            outputs[slot + "@GRAD"] = G(op.input(slot))
    return [OpSpec("lstm_grad", inputs, outputs, dict(op.attrs))]


def _rnn_infer(out_slots):
    def infer(op, block):
        x = block.var(op.input("Input")[0])
        w = block.var(op.input("Weight")[0])
        if x.shape is None or w.shape is None:
            return
        H = w.shape[0]
        for slot in out_slots:
            for name in op.output(slot):
                v = block.var(name)
                v.shape = tuple(x.shape[:-1]) + (H,)
                v.dtype = x.dtype
                v.lod_level = x.lod_level
    return infer


@register_op("lstm", infer_shape=_rnn_infer(("Hidden", "Cell")),
             grad=_lstm_grad_maker)
def lstm(ctx):
    xv = ctx.input("Input")
    x = xv.data if isinstance(xv, LoDArray) else data_of(xv)
    lens = xv.lens if isinstance(xv, LoDArray) else \
        jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    w = data_of(ctx.input("Weight"))
    bias = data_of(ctx.input("Bias")) if ctx.has_input("Bias") else None
    if bias is not None:
        bias = bias.reshape(-1)
    h0 = data_of(ctx.input("H0")) if ctx.has_input("H0") else None
    c0 = data_of(ctx.input("C0")) if ctx.has_input("C0") else None
    hs, cs = _lstm_compute(x, lens, w, bias, h0, c0, ctx.op.attrs)
    ctx.set_output("Hidden", LoDArray(hs, lens))
    ctx.set_output("Cell", LoDArray(cs, lens))


@register_op("lstm_grad")
def lstm_grad(ctx):
    xv = ctx.input("Input")
    x = xv.data if isinstance(xv, LoDArray) else data_of(xv)
    lens = xv.lens if isinstance(xv, LoDArray) else \
        jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    w = data_of(ctx.input("Weight"))
    attrs = dict(ctx.op.attrs)

    def gd(slot):
        v = ctx.input(slot)
        return v.data if isinstance(v, LoDArray) else data_of(v)

    # differentiate wrt every forward input the op actually consumed
    operands = {"Input": x, "Weight": w}
    if ctx.has_input("Bias"):
        operands["Bias"] = data_of(ctx.input("Bias")).reshape(-1)
    if ctx.has_input("H0"):
        operands["H0"] = data_of(ctx.input("H0"))
    if ctx.has_input("C0"):
        operands["C0"] = data_of(ctx.input("C0"))
    names = list(operands)

    def f(*args):
        kw = dict(zip(names, args))
        return _lstm_compute(kw["Input"], lens, kw["Weight"], kw.get("Bias"),
                             kw.get("H0"), kw.get("C0"), attrs)

    _, vjp = jax.vjp(f, *operands.values())
    grads = dict(zip(names, vjp((gd("Hidden@GRAD"), gd("Cell@GRAD")))))
    dx = grads["Input"]
    ctx.set_output("Input@GRAD",
                   LoDArray(dx, lens) if isinstance(xv, LoDArray) else dx)
    ctx.set_output("Weight@GRAD", grads["Weight"])
    if "Bias" in grads:
        ctx.set_output("Bias@GRAD", grads["Bias"].reshape(1, -1))
    if "H0" in grads:
        ctx.set_output("H0@GRAD", grads["H0"])
    if "C0" in grads:
        ctx.set_output("C0@GRAD", grads["C0"])


# ---------------------------------------------------------------------------
# dynamic GRU
# ---------------------------------------------------------------------------

def _gru_compute(x, lens, w, bias, h0, attrs):
    b, L, H3 = x.shape
    H = H3 // 3
    if bias is not None:
        x = x + bias[None, None, :]
    if h0 is None:
        h0 = jnp.zeros((b, H), x.dtype)
    ga = _act(attrs.get("gate_activation", "sigmoid"))
    ca = _act(attrs.get("activation", "tanh"))
    wu, wr, wc = w[:, :H], w[:, H:2 * H], w[:, 2 * H:]
    rev = attrs.get("is_reverse", False)
    if rev:
        x = _reverse_padded(x, lens)

    from .autotune import dispatch_variant, make_key
    from .pallas import kernel_span
    supported = (attrs.get("gate_activation", "sigmoid") == "sigmoid"
                 and attrs.get("activation", "tanh") == "tanh")
    choice = dispatch_variant(
        "rnn",
        make_key(cell="gru", x=tuple(x.shape), dtype=str(x.dtype)),
        {"jnp": True, "pallas": supported}, tier_kernel="gru")

    if choice == "pallas":
        # whole-recurrence kernel (see ops/pallas/rnn.gru_seq_pallas)
        from .pallas.rnn import gru_seq_pallas
        with kernel_span("pallas", "gru"):
            xs = jnp.swapaxes(x, 0, 1)               # [L, b, 3H]
            alive = (jnp.arange(L)[:, None] < lens[None, :]) \
                .astype(x.dtype)[..., None]          # [L, b, 1]
            hs = gru_seq_pallas(xs, alive, w, h0) * alive
            hs = jnp.swapaxes(hs, 0, 1)
        if rev:
            hs = _reverse_padded(hs, lens)
        return hs

    def step(carry, inp):
        h_prev, t = carry
        xt = inp
        alive = (t < lens)[:, None].astype(x.dtype)
        r = ga(xt[:, H:2 * H] + h_prev @ wr)
        rc = (r * h_prev) @ wc                       # MXU matmul
        u = ga(xt[:, :H] + h_prev @ wu)
        c = ca(xt[:, 2 * H:] + rc)
        h = u * c + (1.0 - u) * h_prev
        h = alive * h + (1 - alive) * h_prev
        return (h, t + 1), h * alive

    xt = jnp.swapaxes(x, 0, 1)
    _, hs = jax.lax.scan(step, (h0, jnp.zeros((), jnp.int32)), xt)
    hs = jnp.swapaxes(hs, 0, 1)
    if rev:
        hs = _reverse_padded(hs, lens)
    return hs


def _gru_grad_maker(op):
    inputs = {"Input": op.input("Input"), "Weight": op.input("Weight"),
              "Hidden@GRAD": G(op.output("Hidden"))}
    outputs = {"Input@GRAD": G(op.input("Input")),
               "Weight@GRAD": G(op.input("Weight"))}
    for slot in ("Bias", "H0"):
        if op.input(slot):
            inputs[slot] = op.input(slot)
            outputs[slot + "@GRAD"] = G(op.input(slot))
    return [OpSpec("gru_grad", inputs, outputs, dict(op.attrs))]


@register_op("gru", infer_shape=_rnn_infer(("Hidden",)), grad=_gru_grad_maker)
def gru(ctx):
    xv = ctx.input("Input")
    x = xv.data if isinstance(xv, LoDArray) else data_of(xv)
    lens = xv.lens if isinstance(xv, LoDArray) else \
        jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    w = data_of(ctx.input("Weight"))
    bias = data_of(ctx.input("Bias")).reshape(-1) \
        if ctx.has_input("Bias") else None
    h0 = data_of(ctx.input("H0")) if ctx.has_input("H0") else None
    hs = _gru_compute(x, lens, w, bias, h0, ctx.op.attrs)
    ctx.set_output("Hidden", LoDArray(hs, lens))


@register_op("gru_grad")
def gru_grad(ctx):
    xv = ctx.input("Input")
    x = xv.data if isinstance(xv, LoDArray) else data_of(xv)
    lens = xv.lens if isinstance(xv, LoDArray) else \
        jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    w = data_of(ctx.input("Weight"))
    dh = ctx.input("Hidden@GRAD")
    dh_data = dh.data if isinstance(dh, LoDArray) else data_of(dh)
    attrs = dict(ctx.op.attrs)

    operands = {"Input": x, "Weight": w}
    if ctx.has_input("Bias"):
        operands["Bias"] = data_of(ctx.input("Bias")).reshape(-1)
    if ctx.has_input("H0"):
        operands["H0"] = data_of(ctx.input("H0"))
    names = list(operands)

    def f(*args):
        kw = dict(zip(names, args))
        return _gru_compute(kw["Input"], lens, kw["Weight"], kw.get("Bias"),
                            kw.get("H0"), attrs)

    _, vjp = jax.vjp(f, *operands.values())
    grads = dict(zip(names, vjp(dh_data)))
    dx = grads["Input"]
    ctx.set_output("Input@GRAD",
                   LoDArray(dx, lens) if isinstance(xv, LoDArray) else dx)
    ctx.set_output("Weight@GRAD", grads["Weight"])
    if "Bias" in grads:
        ctx.set_output("Bias@GRAD", grads["Bias"].reshape(1, -1))
    if "H0" in grads:
        ctx.set_output("H0@GRAD", grads["H0"])


# ---------------------------------------------------------------------------
# single-step units (StaticRNN building blocks)
# ---------------------------------------------------------------------------

@register_op("lstm_unit", grad=lambda op: [OpSpec(
    "lstm_unit_grad",
    {"X": op.input("X"), "C_prev": op.input("C_prev"),
     "C@GRAD": G(op.output("C")), "H@GRAD": G(op.output("H"))},
    {"X@GRAD": G(op.input("X")), "C_prev@GRAD": G(op.input("C_prev"))},
    dict(op.attrs))])
def lstm_unit(ctx):
    """One fused LSTM cell step: X=[b,4H] pre-activations, C_prev=[b,H]
    (lstm_unit_op.cc; forget_bias attr added into the forget gate)."""
    x = data_of(ctx.input("X"))
    c_prev = data_of(ctx.input("C_prev"))
    H = c_prev.shape[-1]
    fb = ctx.attr("forget_bias", 0.0)
    i = jax.nn.sigmoid(x[:, :H])
    f = jax.nn.sigmoid(x[:, H:2 * H] + fb)
    cand = jnp.tanh(x[:, 2 * H:3 * H])
    o = jax.nn.sigmoid(x[:, 3 * H:])
    c = f * c_prev + i * cand
    h = o * jnp.tanh(c)
    ctx.set_output("C", c)
    ctx.set_output("H", h)


def _lstm_unit_fwd(x, c_prev, fb):
    H = c_prev.shape[-1]
    i = jax.nn.sigmoid(x[:, :H])
    f = jax.nn.sigmoid(x[:, H:2 * H] + fb)
    cand = jnp.tanh(x[:, 2 * H:3 * H])
    o = jax.nn.sigmoid(x[:, 3 * H:])
    c = f * c_prev + i * cand
    return c, o * jnp.tanh(c)


@register_op("lstm_unit_grad")
def lstm_unit_grad(ctx):
    x = data_of(ctx.input("X"))
    c_prev = data_of(ctx.input("C_prev"))
    fb = ctx.attr("forget_bias", 0.0)
    dc = data_of(ctx.input("C@GRAD"))
    dh = data_of(ctx.input("H@GRAD"))
    _, vjp = jax.vjp(lambda a, b: _lstm_unit_fwd(a, b, fb), x, c_prev)
    dx, dcp = vjp((dc, dh))
    ctx.set_output("X@GRAD", dx)
    ctx.set_output("C_prev@GRAD", dcp)


def _gru_unit_fwd(x, h_prev, w, bias, gate_act, cand_act):
    H = h_prev.shape[-1]
    if bias is not None:
        x = x + bias.reshape(1, -1)
    u = gate_act(x[:, :H] + h_prev @ w[:, :H])
    r = gate_act(x[:, H:2 * H] + h_prev @ w[:, H:2 * H])
    c = cand_act(x[:, 2 * H:] + (r * h_prev) @ w[:, 2 * H:])
    h = u * c + (1.0 - u) * h_prev
    return u, r, c, h


def _gru_unit_grad_maker(op):
    inputs = {"Input": op.input("Input"), "HiddenPrev": op.input("HiddenPrev"),
              "Weight": op.input("Weight"),
              "Hidden@GRAD": G(op.output("Hidden"))}
    outputs = {"Input@GRAD": G(op.input("Input")),
               "HiddenPrev@GRAD": G(op.input("HiddenPrev")),
               "Weight@GRAD": G(op.input("Weight"))}
    if op.input("Bias"):
        inputs["Bias"] = op.input("Bias")
        outputs["Bias@GRAD"] = G(op.input("Bias"))
    return [OpSpec("gru_unit_grad", inputs, outputs, dict(op.attrs))]


def _gru_unit_acts(ctx):
    """Resolve the gate/candidate activations; the reference gru_unit_op
    encodes them as enum ints (0 identity, 1 sigmoid, 2 tanh, 3 relu) while
    the layer API passes strings — accept both."""
    codes = {0: "identity", 1: "sigmoid", 2: "tanh", 3: "relu"}

    def resolve(attr, default):
        v = ctx.attr(attr, default)
        return _act(codes[v] if isinstance(v, int) else v)

    return resolve("gate_activation", "sigmoid"), resolve("activation", "tanh")


@register_op("gru_unit", grad=_gru_unit_grad_maker)
def gru_unit(ctx):
    x = data_of(ctx.input("Input"))
    h_prev = data_of(ctx.input("HiddenPrev"))
    w = data_of(ctx.input("Weight"))
    bias = data_of(ctx.input("Bias")) if ctx.has_input("Bias") else None
    ga, ca = _gru_unit_acts(ctx)
    u, r, c, h = _gru_unit_fwd(x, h_prev, w, bias, ga, ca)
    ctx.set_output("Gate", jnp.concatenate([u, r, c], axis=-1))
    ctx.set_output("ResetHiddenPrev", r * h_prev)
    ctx.set_output("Hidden", h)


@register_op("gru_unit_grad")
def gru_unit_grad(ctx):
    x = data_of(ctx.input("Input"))
    h_prev = data_of(ctx.input("HiddenPrev"))
    w = data_of(ctx.input("Weight"))
    has_bias = ctx.has_input("Bias")
    bias = data_of(ctx.input("Bias")) if has_bias else None
    dh = data_of(ctx.input("Hidden@GRAD"))
    ga, ca = _gru_unit_acts(ctx)

    if has_bias:
        _, vjp = jax.vjp(
            lambda a, b, ww, bb: _gru_unit_fwd(a, b, ww, bb, ga, ca)[3],
            x, h_prev, w, bias)
        dx, dhp, dw, db = vjp(dh)
        ctx.set_output("Bias@GRAD", db)
    else:
        _, vjp = jax.vjp(
            lambda a, b, ww: _gru_unit_fwd(a, b, ww, None, ga, ca)[3],
            x, h_prev, w)
        dx, dhp, dw = vjp(dh)
    ctx.set_output("Input@GRAD", dx)
    ctx.set_output("HiddenPrev@GRAD", dhp)
    ctx.set_output("Weight@GRAD", dw)


# ---------------------------------------------------------------------------
# lstmp — LSTM with recurrent projection (reference lstmp_op.{cc,h}:
# r_t = proj_act(P^T h_t); the recurrence runs over the PROJECTED state,
# Weight [P, 4H], ProjWeight [H, P]; outputs Projection + Cell)
# ---------------------------------------------------------------------------

def _lstmp_compute(x, lens, w, proj_w, bias, h0, c0, attrs):
    b, L, H4 = x.shape
    H = H4 // 4
    P = proj_w.shape[1]
    if bias is not None:
        x = x + bias[None, None, :H4]
    ga = _act(attrs.get("gate_activation", "sigmoid"))
    ca = _act(attrs.get("cell_activation", "tanh"))
    cda = _act(attrs.get("candidate_activation", "tanh"))
    pa = _act(attrs.get("proj_activation", "tanh"))
    r0 = jnp.zeros((b, P), x.dtype) if h0 is None else h0 @ proj_w
    c0 = jnp.zeros((b, H), x.dtype) if c0 is None else c0
    rev = attrs.get("is_reverse", False)
    if rev:
        x = _reverse_padded(x, lens)

    def step(carry, inp):
        r_prev, c_prev, t = carry
        gates = inp + r_prev @ w                    # w: [P, 4H]
        i = ga(gates[:, :H])
        f = ga(gates[:, H:2 * H])
        cand = cda(gates[:, 2 * H:3 * H])
        o = ga(gates[:, 3 * H:])
        c = f * c_prev + i * cand
        h = o * ca(c)
        r = pa(h @ proj_w)                          # [b, P]
        alive = (t < lens)[:, None].astype(x.dtype)
        r = alive * r + (1 - alive) * r_prev
        c = alive * c + (1 - alive) * c_prev
        return (r, c, t + 1), (r * alive, c * alive)

    xt = jnp.swapaxes(x, 0, 1)
    _, (rs, cs) = jax.lax.scan(step, (r0, c0, jnp.zeros((), jnp.int32)), xt)
    rs = jnp.swapaxes(rs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if rev:
        rs = _reverse_padded(rs, lens)
        cs = _reverse_padded(cs, lens)
    return rs, cs


def _lstmp_grad_maker(op):
    inputs = {"Input": op.input("Input"), "Weight": op.input("Weight"),
              "ProjWeight": op.input("ProjWeight"),
              "Projection@GRAD": G(op.output("Projection")),
              "Cell@GRAD": G(op.output("Cell"))}
    outputs = {"Input@GRAD": G(op.input("Input")),
               "Weight@GRAD": G(op.input("Weight")),
               "ProjWeight@GRAD": G(op.input("ProjWeight"))}
    for slot in ("Bias", "H0", "C0"):
        if op.input(slot):
            inputs[slot] = op.input(slot)
            outputs[slot + "@GRAD"] = G(op.input(slot))
    return [OpSpec("lstmp_grad", inputs, outputs, dict(op.attrs))]


def _lstmp_infer(op, block):
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Weight")[0])
    pw = block.var(op.input("ProjWeight")[0])
    if x.shape is None or w.shape is None or pw.shape is None:
        return
    H, P = pw.shape
    for slot, width in (("Projection", P), ("Cell", H)):
        for name in op.output(slot):
            v = block.var(name)
            v.shape = tuple(x.shape[:-1]) + (width,)
            v.dtype = x.dtype
            v.lod_level = x.lod_level


@register_op("lstmp", infer_shape=_lstmp_infer, grad=_lstmp_grad_maker)
def lstmp(ctx):
    xv = ctx.input("Input")
    x = xv.data if isinstance(xv, LoDArray) else data_of(xv)
    lens = xv.lens if isinstance(xv, LoDArray) else \
        jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    w = data_of(ctx.input("Weight"))
    proj_w = data_of(ctx.input("ProjWeight"))
    bias = data_of(ctx.input("Bias")).reshape(-1) \
        if ctx.has_input("Bias") else None
    h0 = data_of(ctx.input("H0")) if ctx.has_input("H0") else None
    c0 = data_of(ctx.input("C0")) if ctx.has_input("C0") else None
    rs, cs = _lstmp_compute(x, lens, w, proj_w, bias, h0, c0, ctx.op.attrs)
    ctx.set_output("Projection", LoDArray(rs, lens))
    ctx.set_output("Cell", LoDArray(cs, lens))


@register_op("lstmp_grad")
def lstmp_grad(ctx):
    xv = ctx.input("Input")
    x = xv.data if isinstance(xv, LoDArray) else data_of(xv)
    lens = xv.lens if isinstance(xv, LoDArray) else \
        jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    attrs = dict(ctx.op.attrs)
    operands = {"Input": x, "Weight": data_of(ctx.input("Weight")),
                "ProjWeight": data_of(ctx.input("ProjWeight"))}
    if ctx.has_input("Bias"):
        operands["Bias"] = data_of(ctx.input("Bias")).reshape(-1)
    if ctx.has_input("H0"):
        operands["H0"] = data_of(ctx.input("H0"))
    if ctx.has_input("C0"):
        operands["C0"] = data_of(ctx.input("C0"))
    names = list(operands)

    def f(*args):
        kw = dict(zip(names, args))
        return _lstmp_compute(kw["Input"], lens, kw["Weight"],
                              kw["ProjWeight"], kw.get("Bias"),
                              kw.get("H0"), kw.get("C0"), attrs)

    def gd(slot):
        v = ctx.input(slot)
        return v.data if isinstance(v, LoDArray) else data_of(v)

    outs, vjp = jax.vjp(f, *[operands[n] for n in names])
    d_rs = gd("Projection@GRAD").astype(outs[0].dtype)
    d_cs = gd("Cell@GRAD").astype(outs[1].dtype)
    grads = vjp((d_rs.reshape(outs[0].shape), d_cs.reshape(outs[1].shape)))
    for n, g in zip(names, grads):
        if n == "Input":
            ctx.set_output("Input@GRAD",
                           LoDArray(g, lens) if isinstance(xv, LoDArray)
                           else g)
        elif n == "Bias":
            # restore the (1, 4H) parameter shape (lstm_grad does the same)
            ctx.set_output("Bias@GRAD", g.reshape(1, -1))
        else:
            ctx.set_output(n + "@GRAD", g)


# ---------------------------------------------------------------------------
# simple_rnn — the vanilla recurrence of the legacy recurrent_layer
# (reference gserver/layers/RecurrentLayer.cpp: h_t = act(x_t + h_{t-1} W
# + b); there is no standalone fluid op for it — the fluid generation
# reached it through StaticRNN blocks — so this TPU-native op gives the
# v2 DSL's recurrent_layer a direct scan lowering)
# ---------------------------------------------------------------------------

def _simple_rnn_compute(x, lens, w, bias, h0, attrs):
    b, L, H = x.shape
    act = _act(attrs.get("activation", "tanh"))
    rev = bool(attrs.get("is_reverse", False))
    if bias is not None:
        x = x + bias[None, None, :]
    if h0 is None:
        h0 = jnp.zeros((b, H), x.dtype)
    if rev:
        # reversed recurrence over ragged rows: flip the VALID prefix of
        # each row (the reference runs the layer backwards per sequence)
        x = _reverse_padded(x, lens)
    xt = jnp.swapaxes(x, 0, 1)                        # [L, b, H]

    def step(carry, inp):
        h_prev, t = carry
        h = act(inp + h_prev @ w)
        alive = (t < lens)[:, None].astype(x.dtype)
        h = alive * h + (1 - alive) * h_prev
        return (h, t + 1), h * alive

    (_, _), hs = jax.lax.scan(step, (h0, jnp.zeros((), jnp.int32)), xt)
    hs = jnp.swapaxes(hs, 0, 1)
    if rev:
        hs = _reverse_padded(hs, lens)
    return hs


def _simple_rnn_grad_maker(op):
    inputs = {"Input": op.input("Input"), "Weight": op.input("Weight"),
              "Out@GRAD": G(op.output("Out"))}
    outputs = {"Input@GRAD": G(op.input("Input")),
               "Weight@GRAD": G(op.input("Weight"))}
    if op.input("Bias"):
        inputs["Bias"] = op.input("Bias")
        outputs["Bias@GRAD"] = G(op.input("Bias"))
    return [OpSpec("simple_rnn_grad", inputs, outputs, dict(op.attrs))]


@register_op("simple_rnn", infer_shape=_rnn_infer(("Out",)),
             grad=_simple_rnn_grad_maker)
def simple_rnn(ctx):
    xv = ctx.input("Input")
    x = xv.data if isinstance(xv, LoDArray) else data_of(xv)
    lens = xv.lens if isinstance(xv, LoDArray) else \
        jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    w = data_of(ctx.input("Weight"))
    bias = data_of(ctx.input("Bias")).reshape(-1) \
        if ctx.has_input("Bias") else None
    hs = _simple_rnn_compute(x, lens, w, bias, None, ctx.op.attrs)
    ctx.set_output("Out", LoDArray(hs, lens))


@register_op("simple_rnn_grad")
def simple_rnn_grad(ctx):
    xv = ctx.input("Input")
    x = xv.data if isinstance(xv, LoDArray) else data_of(xv)
    lens = xv.lens if isinstance(xv, LoDArray) else \
        jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    attrs = dict(ctx.op.attrs)
    operands = {"Input": x, "Weight": data_of(ctx.input("Weight"))}
    if ctx.has_input("Bias"):
        operands["Bias"] = data_of(ctx.input("Bias")).reshape(-1)
    names = list(operands)

    def f(*args):
        kw = dict(zip(names, args))
        return _simple_rnn_compute(kw["Input"], lens, kw["Weight"],
                                   kw.get("Bias"), None, attrs)

    dyv = ctx.input("Out@GRAD")
    dy = dyv.data if isinstance(dyv, LoDArray) else data_of(dyv)
    _, vjp = jax.vjp(f, *operands.values())
    grads = dict(zip(names, vjp(dy)))
    ctx.set_output("Input@GRAD", LoDArray(grads["Input"], lens))
    ctx.set_output("Weight@GRAD", grads["Weight"])
    if "Bias" in grads:
        # restore the (1, H) parameter shape
        ctx.set_output("Bias@GRAD", grads["Bias"].reshape(1, -1))
