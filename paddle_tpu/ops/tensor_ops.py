"""Tensor creation / manipulation ops.

Reference counterparts: fill_constant_op.cc, uniform_random_op.cc,
gaussian_random_op.cc, cast_op.cc, scale_op.cc, assign_op.cc,
fill_zeros_like_op.cc, reshape_op.cc, transpose_op.cc, concat_op.cc,
split_op.cc, sum_op.cc, sign_op.cc, clip_op.cc, clip_by_norm_op.cc,
squared_l2_norm_op.cc, increment_op.cc, top_k_op.cc, one_hot_op.cc,
gather_op.cc, scatter_op.cc, slice-style ops — all under
/root/reference/paddle/fluid/operators/.

Random ops: the reference seeds a per-op std::mt19937 from an attr
(uniform_random_op.cc). TPU-native: random ops draw from the executor's
threaded jax PRNG key (ctx.next_rng()), so randomness is reproducible from
Program.random_seed and splits deterministically inside one compiled step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.lod import LoDArray
from ..core.registry import register_op, same_shape, OpSpec
from ..core.sparse import SparseRows, is_sparse
from ..core.types import np_dtype
from .common import G, data_of, like, G_slot


# ---------- creation ----------

@register_op("fill_constant")
def fill_constant(ctx):
    dtype = np_dtype(ctx.attr("dtype", "float32"))
    shape = tuple(ctx.attr("shape", []))
    ctx.set_output("Out", jnp.full(shape, ctx.attr("value", 0.0), dtype=dtype))


@register_op("fill_constant_batch_size_like")
def fill_constant_batch_size_like(ctx):
    """Shape copied from Input's batch dim (reference
    fill_constant_batch_size_like_op.cc)."""
    ref = data_of(ctx.input("Input"))
    shape = list(ctx.attr("shape"))
    in_idx = ctx.attr("input_dim_idx", 0)
    out_idx = ctx.attr("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = np_dtype(ctx.attr("dtype", "float32"))
    ctx.set_output("Out", jnp.full(tuple(shape), ctx.attr("value", 0.0), dtype))


@register_op("fill_zeros_like", infer_shape=same_shape("X", "Out"))
def fill_zeros_like(ctx):
    x = ctx.input("X")
    if isinstance(x, LoDArray):
        ctx.set_output("Out", like(x, jnp.zeros_like(data_of(x))))
        return
    # generic pytrees too (TensorArrayVal and other control-flow state get
    # zero-filled grads when backward reaches a while/recurrent op)
    ctx.set_output("Out", jax.tree_util.tree_map(jnp.zeros_like, x))


@register_op("uniform_random")
def uniform_random(ctx):
    dtype = np_dtype(ctx.attr("dtype", "float32"))
    shape = tuple(ctx.attr("shape"))
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    out = jax.random.uniform(ctx.next_rng(), shape, jnp.float32, lo, hi)
    ctx.set_output("Out", out.astype(dtype))


@register_op("gaussian_random")
def gaussian_random(ctx):
    dtype = np_dtype(ctx.attr("dtype", "float32"))
    shape = tuple(ctx.attr("shape"))
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    out = mean + std * jax.random.normal(ctx.next_rng(), shape, jnp.float32)
    ctx.set_output("Out", out.astype(dtype))


@register_op("assign_value")
def assign_value(ctx):
    values = np.asarray(ctx.attr("values"))
    shape = tuple(ctx.attr("shape", values.shape))
    ctx.set_output("Out", jnp.asarray(values).reshape(shape))


# ---------- unary-ish ----------

def _unary_grad(op_type, extra=()):
    def maker(op):
        inputs = {"Out@GRAD": G(op.output("Out"))}
        for s in extra:
            inputs[s] = op.input(s)
        return [OpSpec(op_type + "_grad", inputs,
                       {"X@GRAD": G(op.input("X"))}, dict(op.attrs))]
    return maker


@register_op("cast", grad=lambda op: [OpSpec(
    "cast", {"X": G(op.output("Out"))}, {"Out": G(op.input("X"))},
    {"dtype": op.attr("in_dtype", "float32"), "in_dtype": op.attr("dtype")})])
def cast(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", like(x, data_of(x).astype(np_dtype(ctx.attr("dtype")))))


@register_op("scale", infer_shape=same_shape("X", "Out"), grad=lambda op: [OpSpec(
    "scale", {"X": G(op.output("Out"))}, {"Out": G(op.input("X"))},
    {"scale": op.attr("scale", 1.0)})])
def scale(ctx):
    x = ctx.input("X")
    s = ctx.attr("scale", 1.0)
    b = ctx.attr("bias", 0.0)
    ctx.set_output("Out", like(x, data_of(x) * s + b))


@register_op("assign", infer_shape=same_shape("X", "Out"), grad=lambda op: [OpSpec(
    "assign", {"X": G(op.output("Out"))}, {"Out": G(op.input("X"))})])
def assign(ctx):
    ctx.set_output("Out", ctx.input("X"))


@register_op("sign", infer_shape=same_shape("X", "Out"))
def sign(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", like(x, jnp.sign(data_of(x))))


@register_op("clip", infer_shape=same_shape("X", "Out"),
             grad=_unary_grad("clip", extra=("X",)))
def clip(ctx):
    x = ctx.input("X")
    if is_sparse(x):
        # SelectedRows input (sparse grad clipping): merge duplicates first —
        # clip(v1+v2) != clip(v1)+clip(v2) — then clip the value block
        from ..core.sparse import merge_rows
        m = merge_rows(x)
        ctx.set_output("Out", SparseRows(
            m.rows, jnp.clip(m.values, ctx.attr("min"), ctx.attr("max")),
            m.nrows, merged=True))
        return
    ctx.set_output("Out", like(x, jnp.clip(data_of(x), ctx.attr("min"),
                                           ctx.attr("max"))))


@register_op("clip_grad")
def clip_grad(ctx):
    x = data_of(ctx.input("X"))
    d = ctx.input("Out@GRAD")
    mask = (x >= ctx.attr("min")) & (x <= ctx.attr("max"))
    ctx.set_output("X@GRAD", like(d, data_of(d) * mask))


@register_op("clip_by_norm", infer_shape=same_shape("X", "Out"))
def clip_by_norm(ctx):
    xv = ctx.input("X")
    max_norm = ctx.attr("max_norm")
    if is_sparse(xv):
        # reference clip_by_norm_op.cc SelectedRows path: MergeAdd, then
        # clip by the norm of the merged value block
        from ..core.sparse import merge_rows
        m = merge_rows(xv)
        norm = jnp.sqrt(jnp.sum(jnp.square(m.values)))
        scale_f = jnp.where(norm > max_norm,
                            max_norm / jnp.maximum(norm, 1e-12), 1.0)
        ctx.set_output("Out", SparseRows(m.rows, m.values * scale_f,
                                         m.nrows, merged=True))
        return
    x = data_of(xv)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale_f = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    ctx.set_output("Out", like(xv, x * scale_f))


@register_op("squared_l2_norm", grad=lambda op: [OpSpec(
    "squared_l2_norm_grad",
    {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))})])
def squared_l2_norm(ctx):
    xv = ctx.input("X")
    if is_sparse(xv):
        # merged value block's norm == the dense gradient's norm (duplicate
        # rows must be summed before squaring; sentinel segments sum to the
        # zeroed padding grads, contributing 0)
        from ..core.sparse import merge_rows
        ctx.set_output("Out", jnp.sum(
            jnp.square(merge_rows(xv).values)).reshape((1,)))
        return
    x = data_of(xv)
    ctx.set_output("Out", jnp.sum(jnp.square(x)).reshape((1,)))


@register_op("squared_l2_norm_grad")
def squared_l2_norm_grad(ctx):
    x = data_of(ctx.input("X"))
    d = data_of(ctx.input("Out@GRAD")).reshape(())
    ctx.set_output("X@GRAD", 2.0 * d * x)


@register_op("increment")
def increment(ctx):
    x = data_of(ctx.input("X"))
    ctx.set_output("Out", x + jnp.asarray(ctx.attr("step", 1.0), x.dtype))


@register_op("shape")
def shape_op(ctx):
    x = data_of(ctx.input("Input"))
    ctx.set_output("Out", jnp.asarray(np.array(x.shape, dtype=np.int64)))


# ---------- shape manipulation ----------

@register_op("reshape", grad=lambda op: [OpSpec(
    "reshape_grad", {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))})])
def reshape(ctx):
    x = data_of(ctx.input("X"))
    # reference reshape_op.cc: 0 means copy input dim, -1 infers
    shape = [x.shape[i] if s == 0 else s
             for i, s in enumerate(ctx.attr("shape"))]
    ctx.set_output("Out", jnp.reshape(x, shape))


@register_op("reshape_grad")
def reshape_grad(ctx):
    x = data_of(ctx.input("X"))
    d = data_of(ctx.input("Out@GRAD"))
    ctx.set_output("X@GRAD", jnp.reshape(d, x.shape))


@register_op("transpose", grad=lambda op: [OpSpec(
    "transpose_grad", {"Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def transpose(ctx):
    x = data_of(ctx.input("X"))
    ctx.set_output("Out", jnp.transpose(x, ctx.attr("axis")))


@register_op("transpose_grad")
def transpose_grad(ctx):
    d = data_of(ctx.input("Out@GRAD"))
    axis = ctx.attr("axis")
    inv = np.argsort(axis)
    ctx.set_output("X@GRAD", jnp.transpose(d, inv))


def _concat_axis(ctx, vs):
    """LoD inputs see the reference's flat [rows, feat] axis numbering; the
    padded [b, T, feat] layout shifts positive axes by one (the same
    convention as mul's x_num_col_dims, ops/matmul.py)."""
    axis = ctx.attr("axis", 0)
    if any(isinstance(v, LoDArray) for v in vs) and axis >= 0:
        if axis == 0:
            raise ValueError("concat along the LoD rows axis is not "
                             "supported; use sequence_concat")
        axis += 1
    return axis


@register_op("concat", grad=lambda op: [OpSpec(
    "concat_grad",
    {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def concat(ctx):
    vs = ctx.inputs("X")
    xs = [data_of(v) for v in vs]
    out = jnp.concatenate(xs, axis=_concat_axis(ctx, vs))
    ctx.set_output("Out", like(vs[0], out))


@register_op("concat_grad")
def concat_grad(ctx):
    vs = ctx.inputs("X")
    xs = [data_of(v) for v in vs]
    d = data_of(ctx.input("Out@GRAD"))
    axis = _concat_axis(ctx, vs)
    sizes = np.cumsum([x.shape[axis] for x in xs])[:-1]
    parts = jnp.split(d, sizes, axis=axis)
    ctx.set_outputs("X@GRAD", [like(v, p) for v, p in zip(vs, parts)])


@register_op("split", grad=lambda op: [OpSpec(
    "concat", {"X": G(op.output("Out"))}, {"Out": G(op.input("X"))},
    {"axis": op.attr("axis", 0)})])
def split(ctx):
    x = data_of(ctx.input("X"))
    axis = ctx.attr("axis", 0)
    if ctx.attr("sections"):
        idx = np.cumsum(ctx.attr("sections"))[:-1]
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, ctx.attr("num", len(ctx.op.output("Out"))), axis=axis)
    ctx.set_outputs("Out", parts)


@register_op("sum", grad=lambda op: [OpSpec(
    "assign", {"X": G(op.output("Out"))}, {"Out": [g]})
    for g in G(op.input("X"))])
def sum_op(ctx):
    """Variadic sum (reference sum_op.cc — also handles SelectedRows).

    All-SparseRows inputs concatenate entries (the reference's
    sum_op over SelectedRows appends rows); mixed dense+sparse densifies
    the sparse terms (sum_op.cc LoDTensor+SelectedRows mix)."""
    vs = ctx.inputs("X")
    if vs and all(hasattr(v, "tree_flatten") and not isinstance(v, LoDArray)
                  and not is_sparse(v) for v in vs):
        # generic pytree values (TensorArrayVal grads accumulated across
        # multiple array reads): leafwise sum, aux from the first
        out = vs[0]
        for v in vs[1:]:
            out = jax.tree_util.tree_map(
                lambda a, b: a + b if jnp.issubdtype(
                    jnp.asarray(a).dtype, jnp.floating) else a, out, v)
        ctx.set_output("Out", out)
        return
    if any(is_sparse(v) for v in vs):
        if all(is_sparse(v) for v in vs):
            rows = jnp.concatenate([v.rows for v in vs])
            vals = jnp.concatenate([v.values for v in vs])
            ctx.set_output("Out", SparseRows(rows, vals, vs[0].nrows))
            return
        xs = [v.to_dense() if is_sparse(v) else data_of(v) for v in vs]
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        ctx.set_output("Out", out)
        return
    xs = [data_of(v) for v in vs]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    ctx.set_output("Out", like(ctx.inputs("X")[0], out))


# ---------- gather / scatter / indexing ----------

@register_op("gather", grad=lambda op: [OpSpec(
    "gather_grad",
    {"X": op.input("X"), "Index": op.input("Index"),
     "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))})])
def gather(ctx):
    x = data_of(ctx.input("X"))
    idx = data_of(ctx.input("Index")).astype(jnp.int32)
    ctx.set_output("Out", jnp.take(x, idx, axis=0))


@register_op("gather_grad")
def gather_grad(ctx):
    x = data_of(ctx.input("X"))
    idx = data_of(ctx.input("Index")).astype(jnp.int32)
    d = data_of(ctx.input("Out@GRAD"))
    ctx.set_output("X@GRAD", jnp.zeros_like(x).at[idx].add(d))


@register_op("scatter")
def scatter(ctx):
    """Reference scatter_op.cc: overwrite rows of X at Ids with Updates."""
    x = data_of(ctx.input("X"))
    ids = data_of(ctx.input("Ids")).astype(jnp.int32)
    upd = data_of(ctx.input("Updates"))
    ctx.set_output("Out", x.at[ids].set(upd))


# ---------- comparison / logical (reference compare_op.cc, logical_op.cc) ----

def _cmp(name, fn):
    @register_op(name)
    def op(ctx, _fn=fn):
        x, y = data_of(ctx.input("X")), data_of(ctx.input("Y"))
        ctx.set_output("Out", _fn(x, y))


_cmp("less_than", lambda x, y: x < y)
_cmp("less_equal", lambda x, y: x <= y)
_cmp("greater_than", lambda x, y: x > y)
_cmp("greater_equal", lambda x, y: x >= y)
_cmp("equal", lambda x, y: x == y)
_cmp("not_equal", lambda x, y: x != y)
_cmp("logical_and", lambda x, y: x & y)
_cmp("logical_or", lambda x, y: x | y)
_cmp("logical_xor", lambda x, y: x ^ y)


@register_op("logical_not")
def logical_not(ctx):
    ctx.set_output("Out", ~data_of(ctx.input("X")))


# ---------- top_k / one_hot / argmax ----------

@register_op("top_k")
def top_k(ctx):
    xin = ctx.input("X")
    x = data_of(xin)
    k = ctx.attr("k", 1)
    vals, idx = jax.lax.top_k(x, k)
    # LoD propagates (reference top_k_op.cc: Out/Indices share X's lod —
    # the ctc_greedy_decoder path argmaxes ragged logits)
    ctx.set_output("Out", like(xin, vals))
    ctx.set_output("Indices", like(xin, idx.astype(jnp.int64)))


@register_op("one_hot")
def one_hot(ctx):
    x = data_of(ctx.input("X"))
    depth = ctx.attr("depth")
    flat = x.reshape(x.shape[:-1]) if x.shape and x.shape[-1] == 1 else x
    ctx.set_output("Out", jax.nn.one_hot(flat.astype(jnp.int32), depth,
                                         dtype=jnp.float32))


@register_op("argmax")
def argmax(ctx):
    x = data_of(ctx.input("X"))
    ctx.set_output("Out", jnp.argmax(x, axis=ctx.attr("axis", -1)).astype(jnp.int64))


# ---------- multiplex / is_empty ----------

@register_op("multiplex")
def multiplex(ctx):
    """Row-wise select among candidate tensors by Ids
    (reference multiplex_op.cc)."""
    ids = data_of(ctx.input("Ids")).astype(jnp.int32).reshape(-1)
    xs = jnp.stack([data_of(v) for v in ctx.inputs("X")], axis=0)
    rows = jnp.arange(ids.shape[0])
    ctx.set_output("Out", xs[ids, rows])


# ---------- print (debug) ----------

_PRINT_COUNTS: dict = {}


@register_op("print", infer_shape=same_shape("In", "Out"),
             grad=lambda op: [OpSpec(
                 "print",
                 {"In": G(op.output("Out"))}, {"Out": G(op.input("In"))},
                 {**dict(op.attrs),
                  "message": (op.attr("message", "") or "") + " @GRAD",
                  "print_phase": "forward",
                  "is_backward_print": True})
                 if op.attr("print_phase", "both") in ("backward", "both")
                 else OpSpec("assign", {"X": G(op.output("Out"))},
                             {"Out": G(op.input("In"))})])
def print_op(ctx):
    """Debug print (reference print_op.cc): logs message, tensor metadata
    and a bounded data summary for the first ``first_n`` executions, then
    passes the value through unchanged. Works under jit via debug callbacks
    (fires per execution, like the reference's per-run kernel print)."""
    xv = ctx.input("In")
    x = data_of(xv)
    first_n = int(ctx.attr("first_n", -1))
    message = ctx.attr("message", "") or ""
    summarize = int(ctx.attr("summarize", 20))
    name = ctx.op.input("In")[0]
    show_name = ctx.attr("print_tensor_name", True)
    show_type = ctx.attr("print_tensor_type", True)
    show_shape = ctx.attr("print_tensor_shape", True)
    key = id(ctx.op)
    phase = ctx.attr("print_phase", "both")

    if phase in ("forward", "both") or ctx.attr("is_backward_print", False):
        shape, dtype = x.shape, x.dtype

        def emit(arr):
            count = _PRINT_COUNTS.get(key, 0)
            if first_n >= 0 and count >= first_n:
                return
            _PRINT_COUNTS[key] = count + 1
            parts = [message] if message else []
            if show_name:
                parts.append(f"name={name}")
            if show_type:
                parts.append(f"dtype={dtype}")
            if show_shape:
                parts.append(f"shape={tuple(shape)}")
            flat = np.asarray(arr).reshape(-1)
            k = flat.size if summarize < 0 else min(summarize, flat.size)
            parts.append(f"data={flat[:k].tolist()}")
            print("[print op] " + "  ".join(parts), flush=True)

        jax.debug.callback(emit, x)
    ctx.set_output("Out", like(xv, x))
