"""Op-inventory breadth: expand, pad, crop, label_smooth, minus, l1_norm,
conv_shift, modified_huber_loss, *_random_batch_size_like, conv3d_transpose,
max_pool3d_with_index, positive_negative_pair, average_accumulates,
detection_map.

Reference semantics: /root/reference/paddle/fluid/operators/{expand_op.cc
(tile by expand_times, grad sums over tiles), pad_op.cc (paddings =
[before0, after0, ...] + pad_value, grad slices), crop_op.h (offset slice via
StridedMemcpy, shape from attr or the Y reference input), label_smooth_op.h
(out = (1-eps)·x + eps·prior-or-uniform), minus_op.cc, l1_norm_op.cc,
conv_shift_op.cu (per-row circular correlation), modified_huber_loss_op.h,
batch_size_like.h + {uniform,gaussian}_random_batch_size_like_op.cc,
conv_transpose_op.cc (3-D variant), pool_with_index_op.cc (3-D variant),
positive_negative_pair_op.h (per-query concordant/discordant pair counts),
average_accumulates_op.h (Polyak-style parameter-average windows),
detection_map_op.cc}.

TPU-native notes: every lowering here is a handful of jnp/lax calls that XLA
fuses; the reference's hand-written CUDA kernels (e.g. conv_shift_op.cu's
shared-memory circular loads) become gather/one-hot matmul forms. The
stateful metric ops (positive_negative_pair, average_accumulates,
detection_map) keep the reference's accumulate-through-inputs contract so
they thread through scopes exactly like the originals.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.lod import LoDArray
from ..core.registry import register_op, OpSpec, same_shape, infer_output
from ..core.types import np_dtype
from .common import G, data_of, like


# ---------------------------------------------------------------------------
# expand
# ---------------------------------------------------------------------------

def _expand_infer(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        return
    times = op.attrs.get("expand_times", [1] * len(x.shape))
    infer_output(op, block, "Out",
                 tuple(int(s * t) for s, t in zip(x.shape, times)),
                 dtype=x.dtype)


@register_op("expand", infer_shape=_expand_infer, grad=lambda op: [OpSpec(
    "expand_grad", {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def expand(ctx):
    """expand_op.h: Eigen broadcast by expand_times per dimension."""
    x = data_of(ctx.input("X"))
    times = tuple(int(t) for t in ctx.attr("expand_times"))
    ctx.set_output("Out", jnp.tile(x, times))


@register_op("expand_grad")
def expand_grad(ctx):
    x = data_of(ctx.input("X"))
    dy = data_of(ctx.input("Out@GRAD"))
    times = tuple(int(t) for t in ctx.attr("expand_times"))
    # fold each tiled axis into (times, size) and sum the tile axis
    split = []
    for t, s in zip(times, x.shape):
        split += [t, s]
    dx = dy.reshape(split).sum(axis=tuple(range(0, 2 * len(times), 2)))
    ctx.set_output("X@GRAD", dx)


# ---------------------------------------------------------------------------
# pad
# ---------------------------------------------------------------------------

def _pad_pairs(ctx_attr, ndim):
    flat = [int(p) for p in ctx_attr("paddings")]
    return [(flat[2 * i], flat[2 * i + 1]) for i in range(ndim)]


def _pad_infer(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        return
    flat = op.attrs.get("paddings", [])
    shape = tuple(int(s + flat[2 * i] + flat[2 * i + 1])
                  for i, s in enumerate(x.shape))
    infer_output(op, block, "Out", shape, dtype=x.dtype)


@register_op("pad", infer_shape=_pad_infer, grad=lambda op: [OpSpec(
    "pad_grad", {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def pad(ctx):
    x = data_of(ctx.input("X"))
    pairs = _pad_pairs(ctx.attr, x.ndim)
    ctx.set_output("Out", jnp.pad(x, pairs, constant_values=jnp.asarray(
        ctx.attr("pad_value", 0.0), x.dtype)))


@register_op("pad_grad")
def pad_grad(ctx):
    x = data_of(ctx.input("X"))
    dy = data_of(ctx.input("Out@GRAD"))
    pairs = _pad_pairs(ctx.attr, x.ndim)
    sl = tuple(slice(b, b + s) for (b, _), s in zip(pairs, x.shape))
    ctx.set_output("X@GRAD", dy[sl])


# ---------------------------------------------------------------------------
# crop
# ---------------------------------------------------------------------------

def _crop_shape(ctx):
    if ctx.has_input("Y"):
        return data_of(ctx.input("Y")).shape
    return tuple(int(s) for s in ctx.attr("shape"))


def _crop_infer(op, block):
    if op.attrs.get("shape"):
        x = block.var(op.input("X")[0])
        infer_output(op, block, "Out", tuple(op.attrs["shape"]), dtype=x.dtype)


@register_op("crop", infer_shape=_crop_infer, grad=lambda op: [OpSpec(
    "crop_grad", {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def crop(ctx):
    """crop_op.h: slice ``shape`` out of X at ``offsets`` (shape optionally
    borrowed from reference input Y, crop_op.cc:60-64). A -1 shape entry
    (the layer-level dynamic batch dim) resolves to the rest of that dim
    past its offset."""
    x = data_of(ctx.input("X"))
    shape = _crop_shape(ctx)
    offsets = [int(o) for o in ctx.attr("offsets", [0] * x.ndim)]
    shape = [xs - o if s == -1 else s
             for s, xs, o in zip(shape, x.shape, offsets)]
    ctx.set_output("Out", lax.slice(
        x, offsets, [o + s for o, s in zip(offsets, shape)]))


@register_op("crop_grad")
def crop_grad(ctx):
    x = data_of(ctx.input("X"))
    dy = data_of(ctx.input("Out@GRAD"))
    offsets = [int(o) for o in ctx.attr("offsets", [0] * x.ndim)]
    pairs = [(o, xs - o - ds)
             for o, xs, ds in zip(offsets, x.shape, dy.shape)]
    ctx.set_output("X@GRAD", jnp.pad(dy, pairs))


# ---------------------------------------------------------------------------
# label_smooth
# ---------------------------------------------------------------------------

@register_op("label_smooth", infer_shape=same_shape("X", "Out"),
             grad=lambda op: [OpSpec(
                 "label_smooth_grad", {"Out@GRAD": G(op.output("Out"))},
                 {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def label_smooth(ctx):
    """label_smooth_op.h: (1-ε)·x + ε·prior (uniform 1/num_classes when no
    PriorDist input)."""
    x = data_of(ctx.input("X"))
    eps = ctx.attr("epsilon", 0.0)
    if ctx.has_input("PriorDist"):
        prior = data_of(ctx.input("PriorDist")).reshape(-1)
        out = (1.0 - eps) * x + eps * prior
    else:
        out = (1.0 - eps) * x + eps / x.shape[-1]
    ctx.set_output("Out", out.astype(x.dtype))


@register_op("label_smooth_grad")
def label_smooth_grad(ctx):
    dy = data_of(ctx.input("Out@GRAD"))
    ctx.set_output("X@GRAD", (1.0 - ctx.attr("epsilon", 0.0)) * dy)


# ---------------------------------------------------------------------------
# minus / l1_norm
# ---------------------------------------------------------------------------

@register_op("minus", infer_shape=same_shape("X", "Out"), grad=lambda op: [
    OpSpec("scale", {"X": G(op.output("Out"))}, {"Out": G(op.input("X"))},
           {"scale": 1.0}),
    OpSpec("scale", {"X": G(op.output("Out"))}, {"Out": G(op.input("Y"))},
           {"scale": -1.0})])
def minus(ctx):
    """minus_op.cc: Out = X - Y (same shape; grads are ±identity scales,
    exactly the reference's MinusGradMaker pair of scale ops)."""
    x, y = data_of(ctx.input("X")), data_of(ctx.input("Y"))
    ctx.set_output("Out", like(ctx.input("X"), x - y))


@register_op("l1_norm", grad=lambda op: [OpSpec(
    "l1_norm_grad", {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))})])
def l1_norm(ctx):
    """l1_norm_op.h: scalar Σ|x|; grad is sign(x)·dout."""
    x = data_of(ctx.input("X"))
    ctx.set_output("Out", jnp.sum(jnp.abs(x)).reshape(()))


@register_op("l1_norm_grad")
def l1_norm_grad(ctx):
    x = data_of(ctx.input("X"))
    dy = data_of(ctx.input("Out@GRAD")).reshape(())
    ctx.set_output("X@GRAD", jnp.sign(x) * dy)


# ---------------------------------------------------------------------------
# conv_shift (circular correlation)
# ---------------------------------------------------------------------------

def _conv_shift_compute(x, y):
    # out[b, i] = Σ_j x[b, (i + j - M//2) mod W] · y[b, j]
    # (conv_shift_op.cu:84-95 index arithmetic). Gather-free lowering: roll x
    # once per tap — M is small and odd (InferShape enforces M ≤ W).
    w = x.shape[1]
    m = y.shape[1]
    half = m // 2
    taps = [jnp.roll(x, shift=half - j, axis=1) * y[:, j:j + 1]
            for j in range(m)]
    del w
    return sum(taps)


@register_op("conv_shift", infer_shape=same_shape("X", "Out"),
             grad=lambda op: [OpSpec(
                 "conv_shift_grad",
                 {"X": op.input("X"), "Y": op.input("Y"),
                  "Out@GRAD": G(op.output("Out"))},
                 {"X@GRAD": G(op.input("X")), "Y@GRAD": G(op.input("Y"))})])
def conv_shift(ctx):
    x, y = data_of(ctx.input("X")), data_of(ctx.input("Y"))
    ctx.set_output("Out", _conv_shift_compute(x, y))


@register_op("conv_shift_grad")
def conv_shift_grad(ctx):
    x, y = data_of(ctx.input("X")), data_of(ctx.input("Y"))
    dy = data_of(ctx.input("Out@GRAD"))
    _, vjp = jax.vjp(_conv_shift_compute, x, y)
    dx, dyy = vjp(dy)
    ctx.set_output("X@GRAD", dx)
    ctx.set_output("Y@GRAD", dyy)


# ---------------------------------------------------------------------------
# modified_huber_loss
# ---------------------------------------------------------------------------

@register_op("modified_huber_loss", grad=lambda op: [OpSpec(
    "modified_huber_loss_grad",
    {"Y": op.input("Y"), "IntermediateVal": op.output("IntermediateVal"),
     "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))})])
def modified_huber_loss(ctx):
    """modified_huber_loss_op.h: inter = x·(2y-1) with y ∈ {0,1};
    loss = -4·inter if inter < -1, (1-inter)² if inter < 1, else 0."""
    x = data_of(ctx.input("X")).reshape(-1)
    y = data_of(ctx.input("Y")).reshape(-1)
    inter = x * (2.0 * y - 1.0)
    loss = jnp.where(inter < -1.0, -4.0 * inter,
                     jnp.where(inter < 1.0, jnp.square(1.0 - inter), 0.0))
    shape = data_of(ctx.input("X")).shape
    ctx.set_output("IntermediateVal", inter.reshape(shape))
    ctx.set_output("Out", loss.reshape(shape))


@register_op("modified_huber_loss_grad")
def modified_huber_loss_grad(ctx):
    y = data_of(ctx.input("Y")).reshape(-1)
    inter = data_of(ctx.input("IntermediateVal")).reshape(-1)
    dy = data_of(ctx.input("Out@GRAD")).reshape(-1)
    sign = 2.0 * y - 1.0
    dx = jnp.where(inter < -1.0, -4.0 * sign * dy,
                   jnp.where(inter < 1.0, -2.0 * (1.0 - inter) * sign * dy,
                             0.0))
    ctx.set_output("X@GRAD", dx.reshape(data_of(ctx.input("Y")).shape))


# ---------------------------------------------------------------------------
# uniform/gaussian_random_batch_size_like (batch_size_like.h)
# ---------------------------------------------------------------------------

def _batch_size_like_shape(ctx):
    ref = data_of(ctx.input("Input"))
    shape = [int(s) for s in ctx.attr("shape")]
    shape[int(ctx.attr("output_dim_idx", 0))] = \
        ref.shape[int(ctx.attr("input_dim_idx", 0))]
    return tuple(shape)


@register_op("uniform_random_batch_size_like")
def uniform_random_batch_size_like(ctx):
    shape = _batch_size_like_shape(ctx)
    dtype = np_dtype(ctx.attr("dtype", "float32"))
    out = jax.random.uniform(ctx.next_rng(), shape, jnp.float32,
                             ctx.attr("min", -1.0), ctx.attr("max", 1.0))
    ctx.set_output("Out", out.astype(dtype))


@register_op("gaussian_random_batch_size_like")
def gaussian_random_batch_size_like(ctx):
    shape = _batch_size_like_shape(ctx)
    dtype = np_dtype(ctx.attr("dtype", "float32"))
    out = ctx.attr("mean", 0.0) + ctx.attr("std", 1.0) * jax.random.normal(
        ctx.next_rng(), shape, jnp.float32)
    ctx.set_output("Out", out.astype(dtype))


# ---------------------------------------------------------------------------
# conv3d_transpose
# ---------------------------------------------------------------------------

def _triple(v):
    if isinstance(v, (list, tuple)):
        v = list(v) + [v[-1]] * (3 - len(v))
        return tuple(int(i) for i in v[:3])
    return (int(v),) * 3


def _conv3d_transpose_compute(x, w, strides, paddings, dilations):
    """Same lhs-dilation trick as conv2d_transpose (conv_ops.py): the
    reference's filter layout is [C_in, C_out, kd, kh, kw]
    (conv_transpose_op.cc Conv3DTransposeOpMaker)."""
    from ..core.amp import cast_compute
    ks = w.shape[2:]
    ke = [dilations[i] * (ks[i] - 1) + 1 for i in range(3)]
    x, w = cast_compute(x, w)
    w_t = jnp.flip(w.transpose(1, 0, 2, 3, 4), axis=(2, 3, 4))
    return lax.conv_general_dilated(
        x, w_t,
        window_strides=(1, 1, 1),
        padding=[(ke[i] - 1 - paddings[i],) * 2 for i in range(3)],
        lhs_dilation=strides,
        rhs_dilation=dilations,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))


def _conv3d_transpose_infer(op, block):
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Filter")[0])
    if x.shape is None or w.shape is None:
        return
    s = _triple(op.attrs.get("strides", [1, 1, 1]))
    p = _triple(op.attrs.get("paddings", [0, 0, 0]))
    d = _triple(op.attrs.get("dilations", [1, 1, 1]))
    n = x.shape[0]
    m = w.shape[1]
    spatial = tuple(
        (x.shape[2 + i] - 1) * s[i] - 2 * p[i] + (d[i] * (w.shape[2 + i] - 1)
                                                  + 1)
        for i in range(3))
    infer_output(op, block, "Output", (n, m) + spatial, dtype=x.dtype)


@register_op("conv3d_transpose", infer_shape=_conv3d_transpose_infer,
             grad=lambda op: [OpSpec(
                 "conv3d_transpose_grad",
                 {"Input": op.input("Input"), "Filter": op.input("Filter"),
                  "Output@GRAD": G(op.output("Output"))},
                 {"Input@GRAD": G(op.input("Input")),
                  "Filter@GRAD": G(op.input("Filter"))},
                 dict(op.attrs))])
def conv3d_transpose(ctx):
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Filter"))
    s = _triple(ctx.attr("strides", [1, 1, 1]))
    p = _triple(ctx.attr("paddings", [0, 0, 0]))
    d = _triple(ctx.attr("dilations", [1, 1, 1]))
    ctx.set_output("Output", _conv3d_transpose_compute(x, w, s, p, d))


@register_op("conv3d_transpose_grad")
def conv3d_transpose_grad(ctx):
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Filter"))
    dy = data_of(ctx.input("Output@GRAD"))
    s = _triple(ctx.attr("strides", [1, 1, 1]))
    p = _triple(ctx.attr("paddings", [0, 0, 0]))
    d = _triple(ctx.attr("dilations", [1, 1, 1]))
    out, vjp = jax.vjp(
        lambda a, b: _conv3d_transpose_compute(a, b, s, p, d), x, w)
    dx, dw = vjp(dy.astype(out.dtype))
    ctx.set_output("Input@GRAD", dx)
    ctx.set_output("Filter@GRAD", dw)


# ---------------------------------------------------------------------------
# max_pool3d_with_index
# ---------------------------------------------------------------------------

@register_op("max_pool3d_with_index")
def max_pool3d_with_index(ctx):
    """pool_with_index_op.cc 3-D form (math/pooling.cc
    MaxPool3dWithIndexFunctor): mask holds the flat argmax offset within the
    UNPADDED [D, H, W] volume. paddings pad with -inf (the max can never land
    on padding) and global_pooling swallows ksize/paddings, both per the
    reference op."""
    x = data_of(ctx.input("X"))
    n, c, dd, h, w = x.shape
    ks = _triple(ctx.attr("ksize"))
    pd = _triple(ctx.attr("paddings", [0, 0, 0]))
    if ctx.attr("global_pooling", False):
        ks, pd = (dd, h, w), (0, 0, 0)
    st = _triple(ctx.attr("strides", ks))
    neg = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    xp = jnp.pad(x, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1]),
                     (pd[2], pd[2])), constant_values=neg)
    od = (dd + 2 * pd[0] - ks[0]) // st[0] + 1
    oh = (h + 2 * pd[1] - ks[1]) // st[1] + 1
    ow = (w + 2 * pd[2] - ks[2]) // st[2] + 1
    patches = jnp.stack([
        xp[:, :,
           a:a + st[0] * od:st[0],
           b:b + st[1] * oh:st[1],
           e:e + st[2] * ow:st[2]]
        for a in range(ks[0]) for b in range(ks[1]) for e in range(ks[2])],
        axis=-1)
    arg = jnp.argmax(patches, axis=-1)
    out = jnp.max(patches, axis=-1)
    ka = arg // (ks[1] * ks[2])
    kb = (arg // ks[2]) % ks[1]
    ke = arg % ks[2]
    # argmax coordinates back in UNPADDED input space (mask contract)
    ds = jnp.arange(od)[None, None, :, None, None] * st[0] + ka - pd[0]
    hs = jnp.arange(oh)[None, None, None, :, None] * st[1] + kb - pd[1]
    ws = jnp.arange(ow)[None, None, None, None, :] * st[2] + ke - pd[2]
    ctx.set_output("Out", out)
    ctx.set_output("Mask", ((ds * h + hs) * w + ws).astype(jnp.int32))


# ---------------------------------------------------------------------------
# positive_negative_pair
# ---------------------------------------------------------------------------

@register_op("positive_negative_pair")
def positive_negative_pair(ctx):
    """positive_negative_pair_op.h: over all in-batch pairs sharing a QueryID
    with different labels, count score-order-concordant (positive),
    discordant (negative) and tied (neutral) pairs, weighted by the mean of
    the two instance weights; accumulate onto the Accumulate* inputs."""
    score = data_of(ctx.input("Score"))
    label = data_of(ctx.input("Label")).reshape(-1)
    query = data_of(ctx.input("QueryID")).reshape(-1)
    col = int(ctx.attr("column", -1))
    s = score[:, col].reshape(-1)
    n = s.shape[0]
    w = data_of(ctx.input("Weight")).reshape(-1) if ctx.has_input("Weight") \
        else jnp.ones((n,), jnp.float32)

    same_query = query[:, None] == query[None, :]
    diff_label = label[:, None] != label[None, :]
    upper = jnp.triu(jnp.ones((n, n), bool), k=1)
    eligible = same_query & diff_label & upper
    pw = (w[:, None] + w[None, :]) * 0.5
    concord = (s[:, None] - s[None, :]) * (label[:, None] - label[None, :]) > 0
    tied = s[:, None] == s[None, :]

    pos = jnp.sum(jnp.where(eligible & ~tied & concord, pw, 0.0))
    neg = jnp.sum(jnp.where(eligible & ~tied & ~concord, pw, 0.0))
    neu = jnp.sum(jnp.where(eligible & tied, pw, 0.0))
    # NOTE reference quirk (positive_negative_pair_op.h:96-103): tied pairs
    # add to neutral AND to pos/neg via the unguarded ternary; we follow the
    # documented semantics (tied -> neutral only), matching the evaluator's
    # use and the v2 PnpairEvaluator.
    for slot, val in (("PositivePair", pos), ("NegativePair", neg),
                      ("NeutralPair", neu)):
        acc = "Accumulate" + slot
        if ctx.has_input(acc):
            val = val + data_of(ctx.input(acc)).reshape(())
        ctx.set_output(slot, val.reshape((1,)))


# ---------------------------------------------------------------------------
# average_accumulates (ParamAverage windows)
# ---------------------------------------------------------------------------

@register_op("average_accumulates")
def average_accumulates(ctx):
    """average_accumulates_op.h: maintain Polyak-average sums of a parameter
    over a sliding window. sum_1 accumulates every step; every 16384 updates
    it folds into sum_2 (precision); when the window outgrows
    max(min_average_window, min(max_average_window, num_updates ·
    average_window)) everything folds into sum_3 and restarts. All branch
    decisions lower to jnp.where so the op stays jit-compilable."""
    param = data_of(ctx.input("param"))
    s1 = data_of(ctx.input("in_sum_1"))
    s2 = data_of(ctx.input("in_sum_2"))
    s3 = data_of(ctx.input("in_sum_3"))
    num_updates = data_of(ctx.input("in_num_updates")).reshape(()).astype(
        jnp.int64)
    num_acc = data_of(ctx.input("in_num_accumulates")).reshape(()).astype(
        jnp.int64)
    old_num_acc = data_of(
        ctx.input("in_old_num_accumulates")).reshape(()).astype(jnp.int64)

    avg_window = ctx.attr("average_window", 0.0)
    # clamp the huge C++ default below int32 max: jnp.int64 silently becomes
    # int32 without jax_enable_x64 (the repo default) and a 2**62 literal
    # would overflow at conversion
    int_max = np.iinfo(np.int32).max
    max_w = min(int(ctx.attr("max_average_window", int_max)), int_max)
    min_w = min(int(ctx.attr("min_average_window", 10000)), max_w)
    k_max_num = 16384  # kMaxNumAccumulates

    num_updates = num_updates + 1
    num_acc = num_acc + 1
    in_s1, in_s2 = s1, s2
    s1 = s1 + param

    # both folds use the PRE-UPDATE in_sum_1/in_sum_2 and zero out_sum_1,
    # exactly like the reference (average_accumulates_op.h: out_sum_2 =
    # in_sum_2 + in_sum_1; out_sum_3 = in_sum_1 + in_sum_2) — meaning the
    # fold step's own param never enters an accumulator (reference quirk,
    # kept for parity)
    fold2 = (num_updates % k_max_num) == 0
    s2 = jnp.where(fold2, in_s2 + in_s1, s2)
    s1 = jnp.where(fold2, jnp.zeros_like(s1), s1)

    window_full = (num_acc >= min_w) & (
        num_acc >= jnp.minimum(
            jnp.asarray(max_w, jnp.int64),
            (num_updates.astype(jnp.float32) * avg_window).astype(jnp.int64)))
    s3 = jnp.where(window_full, in_s1 + in_s2, s3)
    s1 = jnp.where(window_full, jnp.zeros_like(s1), s1)
    s2 = jnp.where(window_full, jnp.zeros_like(s2), s2)
    old_num_acc = jnp.where(window_full, num_acc, old_num_acc)
    num_acc = jnp.where(window_full, jnp.zeros_like(num_acc), num_acc)

    ctx.set_output("out_sum_1", s1)
    ctx.set_output("out_sum_2", s2)
    ctx.set_output("out_sum_3", s3)
    ctx.set_output("out_num_updates", num_updates.reshape((1,)))
    ctx.set_output("out_num_accumulates", num_acc.reshape((1,)))
    ctx.set_output("out_old_num_accumulates", old_num_acc.reshape((1,)))


# ---------------------------------------------------------------------------
# detection_map (op form of the mAP evaluator)
# ---------------------------------------------------------------------------

def _ap_from_tp_fp(tp_sorted_desc_scores, tps, fps, n_pos, ap_type):
    """11-point or integral AP given per-detection (score-desc) tp/fp flags
    and the positive count (detection_map_op.h GetMAP)."""
    import numpy as onp
    acc_tp = onp.cumsum(tps)
    acc_fp = onp.cumsum(fps)
    if n_pos == 0 or len(tps) == 0:
        return 0.0
    precision = acc_tp / onp.maximum(acc_tp + acc_fp, 1e-12)
    recall = acc_tp / n_pos
    if ap_type == "11point":
        max_precisions = onp.zeros(11)
        start_idx = len(tps) - 1
        for j in range(10, -1, -1):
            for i in range(start_idx, -1, -1):
                if recall[i] < j / 10.0:
                    start_idx = i
                    if j > 0:
                        max_precisions[j - 1] = max_precisions[j]
                    break
                if max_precisions[j] < precision[i]:
                    max_precisions[j] = precision[i]
        return float(max_precisions.sum() / 11.0)
    # integral
    ap = 0.0
    prev_recall = 0.0
    for i in range(len(tps)):
        ap += precision[i] * (recall[i] - prev_recall)
        prev_recall = recall[i]
    return float(ap)


@register_op("detection_map")
def detection_map(ctx):
    """detection_map_op.cc as an eager/host op: DetectRes is a LoD tensor of
    [label, score, xmin, ymin, xmax, ymax] rows per image, Label a LoD tensor
    of [label, xmin, ymin, xmax, ymax] (or with a difficult flag at column 1,
    detection_map_op.cc:90-97); emits MAP plus accumulated state. Runs on
    host numpy — it is an evaluation metric, not a training-path op (the
    reference's kernel is likewise pure CPU)."""
    import numpy as onp

    det = ctx.input("DetectRes")
    gt = ctx.input("Label")
    overlap_t = float(ctx.attr("overlap_threshold", 0.5))
    evaluate_difficult = bool(ctx.attr("evaluate_difficult", True))
    ap_type = ctx.attr("ap_type", "integral")
    class_num = int(ctx.attr("class_num"))

    def rows_per_seq(v):
        data = onp.asarray(data_of(v))
        if isinstance(v, LoDArray):
            out = []
            lens = onp.asarray(v.lens).reshape(-1)
            for i, ln in enumerate(lens):
                out.append(data[i][:int(ln)])
            return out
        return [data.reshape(-1, data.shape[-1])]

    det_seqs = rows_per_seq(det)
    gt_seqs = rows_per_seq(gt)

    # state: per-class positive count, and (score, tp/fp flag) lists
    pos_count = onp.zeros(class_num, onp.int64)
    true_pos = {c: [] for c in range(class_num)}
    false_pos = {c: [] for c in range(class_num)}

    for dets, gts in zip(det_seqs, gt_seqs):
        has_difficult = gts.shape[1] == 6
        if has_difficult:
            g_label = gts[:, 0].astype(int)
            g_diff = gts[:, 1].astype(bool)
            g_box = gts[:, 2:6]
        else:
            g_label = gts[:, 0].astype(int)
            g_diff = onp.zeros(len(gts), bool)
            g_box = gts[:, 1:5]
        for c in onp.unique(g_label):
            n = int(onp.sum((g_label == c) & (evaluate_difficult |
                                              ~g_diff)))
            pos_count[int(c)] += n
        matched = onp.zeros(len(gts), bool)
        order = onp.argsort(-dets[:, 1])
        for i in order:
            c = int(dets[i, 0])
            box = dets[i, 2:6]
            cand = onp.where(g_label == c)[0]
            best_iou, best_j = 0.0, -1
            for j in cand:
                gb = g_box[j]
                ix1, iy1 = max(box[0], gb[0]), max(box[1], gb[1])
                ix2, iy2 = min(box[2], gb[2]), min(box[3], gb[3])
                iw, ih = max(0.0, ix2 - ix1), max(0.0, iy2 - iy1)
                inter = iw * ih
                ua = ((box[2] - box[0]) * (box[3] - box[1])
                      + (gb[2] - gb[0]) * (gb[3] - gb[1]) - inter)
                iou = inter / ua if ua > 0 else 0.0
                if iou > best_iou:
                    best_iou, best_j = iou, j
            if best_iou > overlap_t:
                if (not evaluate_difficult) and g_diff[best_j]:
                    continue
                if not matched[best_j]:
                    matched[best_j] = True
                    true_pos[c].append((float(dets[i, 1]), 1))
                    false_pos[c].append((float(dets[i, 1]), 0))
                else:
                    true_pos[c].append((float(dets[i, 1]), 0))
                    false_pos[c].append((float(dets[i, 1]), 1))
            else:
                true_pos[c].append((float(dets[i, 1]), 0))
                false_pos[c].append((float(dets[i, 1]), 1))

    # merge accumulated state from inputs (PosCount/TruePos/FalsePos) ONLY
    # when HasState is wired and nonzero — the reference starts fresh
    # otherwise (detection_map_op.h:91-98: `int state = 0; if (has_state)
    # ...; if (in_pos_count != nullptr && state)`)
    has_state = (ctx.has_input("HasState") and int(onp.asarray(
        data_of(ctx.input("HasState"))).reshape(-1)[0]) != 0)
    if ctx.has_input("PosCount") and has_state:
        prev_pos = onp.asarray(data_of(ctx.input("PosCount"))).reshape(-1)
        pos_count[:len(prev_pos)] += prev_pos.astype(onp.int64)
        for name, store in (("TruePos", true_pos), ("FalsePos", false_pos)):
            v = ctx.input(name)
            rows = onp.asarray(data_of(v))
            if isinstance(v, LoDArray):
                lens = onp.asarray(v.lens).reshape(-1)
            else:
                # plain-tensor state: every per-class row is full width
                lens = onp.full(rows.shape[0], rows.shape[1], onp.int64)
            for c, ln in enumerate(lens):
                seq = rows[c][:int(ln)]
                store.setdefault(c, [])
                store[c].extend((float(s), int(f)) for s, f in seq)

    m_ap, count = 0.0, 0
    for c in range(class_num):
        if pos_count[c] == 0 or not true_pos[c]:
            continue
        entries = sorted(true_pos[c], key=lambda e: -e[0])
        f_entries = sorted(false_pos[c], key=lambda e: -e[0])
        tps = onp.asarray([e[1] for e in entries])
        fps = onp.asarray([e[1] for e in f_entries])
        m_ap += _ap_from_tp_fp(None, tps, fps, int(pos_count[c]), ap_type)
        count += 1
    m_ap = m_ap / count if count else 0.0

    ctx.set_output("MAP", jnp.asarray(m_ap, jnp.float32).reshape((1,)))
    ctx.set_output("AccumPosCount",
                   jnp.asarray(pos_count, jnp.int32).reshape(-1, 1))

    def pack(store):
        max_len = max((len(v) for v in store.values()), default=0)
        arr = onp.zeros((class_num, max(max_len, 1), 2), onp.float32)
        lens = onp.zeros(class_num, onp.int32)
        for c, v in store.items():
            lens[c] = len(v)
            for i, (s, f) in enumerate(sorted(v, key=lambda e: -e[0])):
                arr[c, i] = (s, f)
        return LoDArray(jnp.asarray(arr), jnp.asarray(lens))

    ctx.set_output("AccumTruePos", pack(true_pos))
    ctx.set_output("AccumFalsePos", pack(false_pos))
