"""Activation ops — full parity with the reference's activation zoo.

Reference: /root/reference/paddle/fluid/operators/activation_op.cc — 28 kinds
(sigmoid, logsigmoid, exp, relu, tanh, tanh_shrink, softshrink, sqrt, abs,
ceil, floor, round, reciprocal, log, square, softplus, softsign, brelu,
leaky_relu, soft_relu, elu, relu6, pow, stanh, hard_shrink, thresholded_relu,
hard_sigmoid, swish), each a CPU functor + CUDA kernel pair with a grad functor
declaring whether it needs X or Out. Here: one jnp expression each; XLA fuses
them into producers/consumers so they are free on TPU. Grad makers mirror the
reference's X-or-Out dependency choice so the autodiff graph matches.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op, same_shape, OpSpec
from .common import G, data_of, like


def _register_act(name, fwd, grad_fn, use="out"):
    """fwd(x, ctx) -> out; grad_fn(ref, dout, ctx) -> dx where ref is Out or X
    per ``use`` (mirrors the reference functors' GradFunctor dependencies)."""

    def maker(op, _name=name, _use=use):
        inputs = {"Out@GRAD": G(op.output("Out"))}
        if _use in ("out", "both"):
            inputs["Out"] = op.output("Out")
        if _use in ("x", "both"):
            inputs["X"] = op.input("X")
        return [OpSpec(_name + "_grad", inputs,
                       {"X@GRAD": G(op.input("X"))}, dict(op.attrs))]

    @register_op(name, infer_shape=same_shape("X", "Out"), grad=maker)
    def forward(ctx, _fwd=fwd):
        x = ctx.input("X")
        ctx.set_output("Out", like(x, _fwd(data_of(x), ctx)))

    @register_op(name + "_grad")
    def backward(ctx, _g=grad_fn, _use=use):
        dout_v = ctx.input("Out@GRAD")
        dout = data_of(dout_v)
        if _use == "out":
            ref = (data_of(ctx.input("Out")),)
        elif _use == "x":
            ref = (data_of(ctx.input("X")),)
        else:
            ref = (data_of(ctx.input("X")), data_of(ctx.input("Out")))
        ctx.set_output("X@GRAD", like(dout_v, _g(*ref, dout, ctx)))


_A = _register_act

_A("sigmoid", lambda x, c: jax.nn.sigmoid(x),
   lambda o, d, c: d * o * (1 - o), "out")
_A("logsigmoid", lambda x, c: -jnp.logaddexp(0.0, -x),
   lambda x, d, c: d * (1.0 / (1.0 + jnp.exp(x))), "x")
_A("exp", lambda x, c: jnp.exp(x), lambda o, d, c: d * o, "out")
_A("relu", lambda x, c: jnp.maximum(x, 0), lambda o, d, c: d * (o > 0), "out")
_A("tanh", lambda x, c: jnp.tanh(x), lambda o, d, c: d * (1 - o * o), "out")
_A("tanh_shrink", lambda x, c: x - jnp.tanh(x),
   lambda x, d, c: d * jnp.square(jnp.tanh(x)), "x")
_A("softshrink",
   lambda x, c: jnp.where(x > c.attr("lambda", 0.5), x - c.attr("lambda", 0.5),
                          jnp.where(x < -c.attr("lambda", 0.5),
                                    x + c.attr("lambda", 0.5), 0.0)),
   lambda x, d, c: d * ((x > c.attr("lambda", 0.5)) | (x < -c.attr("lambda", 0.5))),
   "x")
_A("sqrt", lambda x, c: jnp.sqrt(x), lambda o, d, c: d * 0.5 / o, "out")
_A("abs", lambda x, c: jnp.abs(x), lambda x, d, c: d * jnp.sign(x), "x")
_A("ceil", lambda x, c: jnp.ceil(x), lambda x, d, c: jnp.zeros_like(d), "x")
_A("floor", lambda x, c: jnp.floor(x), lambda x, d, c: jnp.zeros_like(d), "x")
_A("round", lambda x, c: jnp.round(x), lambda x, d, c: jnp.zeros_like(d), "x")
_A("reciprocal", lambda x, c: 1.0 / x, lambda o, d, c: -d * o * o, "out")
_A("log", lambda x, c: jnp.log(x), lambda x, d, c: d / x, "x")
_A("square", lambda x, c: jnp.square(x), lambda x, d, c: 2.0 * d * x, "x")
_A("softplus", lambda x, c: jnp.logaddexp(0.0, x),
   lambda x, d, c: d * (1.0 / (1.0 + jnp.exp(-x))), "x")
_A("softsign", lambda x, c: x / (1 + jnp.abs(x)),
   lambda x, d, c: d / jnp.square(1 + jnp.abs(x)), "x")
_A("brelu",
   lambda x, c: jnp.clip(x, c.attr("t_min", 0.0), c.attr("t_max", 24.0)),
   lambda x, d, c: d * ((x > c.attr("t_min", 0.0)) & (x < c.attr("t_max", 24.0))),
   "x")
_A("leaky_relu",
   lambda x, c: jnp.where(x >= 0, x, c.attr("alpha", 0.02) * x),
   lambda x, d, c: d * jnp.where(x >= 0, 1.0, c.attr("alpha", 0.02)), "x")
_A("soft_relu",
   lambda x, c: jnp.log1p(jnp.exp(jnp.clip(x, -c.attr("threshold", 40.0),
                                           c.attr("threshold", 40.0)))),
   lambda o, d, c: d * (1 - jnp.exp(-o)), "out")
_A("elu",
   lambda x, c: jnp.where(x >= 0, x, c.attr("alpha", 1.0) * (jnp.exp(x) - 1)),
   lambda x, d, c: d * jnp.where(x >= 0, 1.0,
                                 c.attr("alpha", 1.0) * jnp.exp(x)), "x")
_A("relu6", lambda x, c: jnp.clip(x, 0.0, c.attr("threshold", 6.0)),
   lambda x, d, c: d * ((x > 0) & (x < c.attr("threshold", 6.0))), "x")
_A("pow", lambda x, c: jnp.power(x, c.attr("factor", 1.0)),
   lambda x, d, c: d * c.attr("factor", 1.0)
   * jnp.power(x, c.attr("factor", 1.0) - 1), "x")
_A("stanh",
   lambda x, c: c.attr("scale_b", 1.7159) * jnp.tanh(c.attr("scale_a", 0.67) * x),
   lambda x, d, c: d * c.attr("scale_a", 0.67) * c.attr("scale_b", 1.7159)
   * (1 - jnp.square(jnp.tanh(c.attr("scale_a", 0.67) * x))), "x")
_A("hard_shrink",
   lambda x, c: jnp.where((x > c.attr("threshold", 0.5))
                          | (x < -c.attr("threshold", 0.5)), x, 0.0),
   lambda x, d, c: d * ((x > c.attr("threshold", 0.5))
                        | (x < -c.attr("threshold", 0.5))), "x")
_A("thresholded_relu",
   lambda x, c: jnp.where(x > c.attr("threshold", 1.0), x, 0.0),
   lambda x, d, c: d * (x > c.attr("threshold", 1.0)), "x")
_A("hard_sigmoid",
   lambda x, c: jnp.clip(c.attr("slope", 0.2) * x + c.attr("offset", 0.5), 0.0, 1.0),
   lambda x, d, c: d * jnp.where(
       (c.attr("slope", 0.2) * x + c.attr("offset", 0.5) > 0)
       & (c.attr("slope", 0.2) * x + c.attr("offset", 0.5) < 1),
       c.attr("slope", 0.2), 0.0), "x")
_A("swish",
   lambda x, c: x / (1 + jnp.exp(-c.attr("beta", 1.0) * x)),
   lambda x, d, c: d * ((1 + jnp.exp(-c.attr("beta", 1.0) * x)
                         + c.attr("beta", 1.0) * x * jnp.exp(-c.attr("beta", 1.0) * x))
                        / jnp.square(1 + jnp.exp(-c.attr("beta", 1.0) * x))), "x")

