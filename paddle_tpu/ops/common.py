"""Shared helpers for op lowerings and grad makers."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import OpSpec
from ..fluid.framework import grad_var_name


def G(names):
    """Names -> their gradient-variable names (the @GRAD convention used by the
    reference's backward pass, python/paddle/fluid/backward.py)."""
    if isinstance(names, str):
        return grad_var_name(names)
    return [grad_var_name(n) for n in names]


def data_of(v):
    """Unwrap a LoDArray to its padded dense data (LoD-transparent ops)."""
    return v.data if isinstance(v, LoDArray) else v


def like(ref, value):
    """Re-wrap ``value`` as a LoDArray if ``ref`` carried LoD (both
    levels)."""
    if isinstance(ref, LoDArray):
        return LoDArray(value, ref.lens, ref.outer_lens)
    return value


def collapse_to(v, target_shape, lead_axis):
    """Sum ``v`` down to ``target_shape`` which was broadcast into it starting
    at ``lead_axis`` — the gradient of the reference's elementwise broadcast
    rule (operators/elementwise_op_function.h)."""
    nd = v.ndim
    ynd = len(target_shape)
    axes = tuple(range(lead_axis)) + tuple(range(lead_axis + ynd, nd))
    if axes:
        v = jnp.sum(v, axis=axes)
    # handle size-1 dims inside target_shape broadcast
    inner = tuple(i for i, s in enumerate(target_shape) if s == 1 and v.shape[i] != 1)
    if inner:
        v = jnp.sum(v, axis=inner, keepdims=True)
    return v.reshape(target_shape)


def simple_grad(op_type, in_slots, out_slots, grad_of_outs, grad_to_ins,
                extra_inputs=None):
    """Build a standard grad maker: grad op consumes listed forward slots +
    output grads, produces input grads. Mirrors DefaultGradOpDescMaker
    (/root/reference/paddle/fluid/framework/grad_op_desc_maker.h:133)."""
    def maker(op):
        inputs = {}
        for s in in_slots:
            inputs[s] = op.input(s)
        for s in out_slots:
            inputs[s] = op.output(s)
        for s in grad_of_outs:
            inputs[G_slot(s)] = G(op.output(s))
        for s in (extra_inputs or []):
            inputs[s] = op.input(s)
        outputs = {G_slot(s): G(op.input(s)) for s in grad_to_ins}
        return [OpSpec(op_type, inputs, outputs, dict(op.attrs))]
    return maker


def G_slot(slot):
    return slot + "@GRAD"
