"""Matrix-multiply ops — the MXU workhorses.

Reference: mul_op.cc (flatten-to-2D semantics via x_num_col_dims /
y_num_col_dims), matmul_op.cc (batched, with transpose flags). The reference
dispatches to cuBLAS GEMM (operators/math/math_function.cu); here a single
jnp.dot / einsum lowers straight onto the TPU MXU. ``mul`` accumulates in
float32 via preferred_element_type when inputs are bfloat16 — the TPU-native
mixed-precision recipe.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.amp import cast_compute
from ..core.lod import LoDArray
from ..core.registry import register_op, OpSpec
from .common import G, data_of, like


def _flat2d(x, num_col_dims):
    lead = 1
    for s in x.shape[:num_col_dims]:
        lead *= s
    rest = 1
    for s in x.shape[num_col_dims:]:
        rest *= s
    return x.reshape(lead, rest)


def _mul_grad_maker(op):
    return [OpSpec(
        "mul_grad",
        {"X": op.input("X"), "Y": op.input("Y"),
         "Out@GRAD": G(op.output("Out"))},
        {"X@GRAD": G(op.input("X")), "Y@GRAD": G(op.input("Y"))},
        dict(op.attrs))]


@register_op("mul", grad=_mul_grad_maker)
def mul(ctx):
    xv = ctx.input("X")
    x, y = data_of(xv), data_of(ctx.input("Y"))
    xnc = ctx.attr("x_num_col_dims", 1)
    ync = ctx.attr("y_num_col_dims", 1)
    if isinstance(xv, LoDArray):
        # the reference sees a LoDTensor as its flat [total_rows, *feat] form
        # (mul_op.cc flattens from there); our padded [b, L, *feat] layout has
        # one extra leading dim, so the split point shifts by one
        xnc = xnc + 1
    x, y = cast_compute(x, y)
    x2, y2 = _flat2d(x, xnc), _flat2d(y, ync)
    out = jnp.dot(x2, y2, preferred_element_type=jnp.float32).astype(x.dtype)
    out_shape = x.shape[:xnc] + y.shape[ync:]
    ctx.set_output("Out", like(xv, out.reshape(out_shape)))


@register_op("mul_grad")
def mul_grad(ctx):
    xv = ctx.input("X")
    x, y = data_of(xv), data_of(ctx.input("Y"))
    d = data_of(ctx.input("Out@GRAD"))
    xnc = ctx.attr("x_num_col_dims", 1)
    ync = ctx.attr("y_num_col_dims", 1)
    if isinstance(xv, LoDArray):
        xnc = xnc + 1
    x, y, d = cast_compute(x, y, d)
    x2, y2 = _flat2d(x, xnc), _flat2d(y, ync)
    d2 = d.reshape(x2.shape[0], y2.shape[1])
    dx = jnp.dot(d2, y2.T, preferred_element_type=jnp.float32)
    dy = jnp.dot(x2.T, d2, preferred_element_type=jnp.float32)
    ctx.set_output("X@GRAD", like(ctx.input("X"), dx.reshape(x.shape).astype(x.dtype)))
    ctx.set_output("Y@GRAD", dy.reshape(y.shape).astype(y.dtype))


def _cos_sim_compute(x, y):
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    return jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)


@register_op("cos_sim", grad=lambda op: [OpSpec(
    "cos_sim_grad",
    {"X": op.input("X"), "Y": op.input("Y"),
     "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X")), "Y@GRAD": G(op.input("Y"))})])
def cos_sim(ctx):
    """Row-wise cosine similarity (cos_sim_op.cc); Y may have one row that
    broadcasts over X's batch."""
    x, y = data_of(ctx.input("X")), data_of(ctx.input("Y"))
    ctx.set_output("Out", _cos_sim_compute(x, y))


@register_op("cos_sim_grad")
def cos_sim_grad(ctx):
    import jax
    x, y = data_of(ctx.input("X")), data_of(ctx.input("Y"))
    d = data_of(ctx.input("Out@GRAD"))
    _, vjp = jax.vjp(_cos_sim_compute, x, y)
    dx, dy = vjp(d)
    ctx.set_output("X@GRAD", dx)
    ctx.set_output("Y@GRAD", dy)


def _matmul_grad_maker(op):
    return [OpSpec(
        "matmul_grad",
        {"X": op.input("X"), "Y": op.input("Y"),
         "Out@GRAD": G(op.output("Out"))},
        {"X@GRAD": G(op.input("X")), "Y@GRAD": G(op.input("Y"))},
        dict(op.attrs))]


def _mm(x, y, tx, ty):
    if x.ndim == 1:
        x = x[None, :]
    if y.ndim == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y, preferred_element_type=jnp.float32)


@register_op("matmul", grad=_matmul_grad_maker)
def matmul(ctx):
    xv = ctx.input("X")
    x, y = cast_compute(data_of(xv), data_of(ctx.input("Y")))
    out = _mm(x, y, ctx.attr("transpose_X", False), ctx.attr("transpose_Y", False))
    if x.ndim == 1 and y.ndim == 1:
        out = out.reshape(())
    ctx.set_output("Out", like(xv, out.astype(x.dtype)))


@register_op("matmul_grad")
def matmul_grad(ctx):
    x, y = data_of(ctx.input("X")), data_of(ctx.input("Y"))
    d = data_of(ctx.input("Out@GRAD"))
    x, y, d = cast_compute(x, y, d)
    tx, ty = ctx.attr("transpose_X", False), ctx.attr("transpose_Y", False)
    if x.ndim == 1 and y.ndim == 1:
        d = d.reshape(1, 1)
    # standard matmul VJP with transpose flags
    if not tx and not ty:
        dx = _mm(d, y, False, True)
        dy = _mm(x, d, True, False)
    elif tx and not ty:
        dx = _mm(y, d, False, True)
        dy = _mm(x, d, False, False)
    elif not tx and ty:
        dx = _mm(d, y, False, False)
        dy = _mm(d, x, True, False)
    else:
        dx = _mm(y, d, True, True)
        dy = _mm(d, x, True, True)
    # collapse broadcasting in batch dims
    def fit(g, ref):
        while g.ndim > ref.ndim:
            g = jnp.sum(g, axis=0)
        return g.reshape(ref.shape).astype(ref.dtype)
    ctx.set_output("X@GRAD", like(ctx.input("X"), fit(dx, x)))
    ctx.set_output("Y@GRAD", fit(dy, y))
