"""Sequence (LoD) ops over the padded LoDArray representation.

Reference: /root/reference/paddle/fluid/operators/sequence_*op.cc,
row_conv_op.cc, lod_reset_op.cc. There every op walks the level-1 LoD offset
table over a concatenated ragged tensor; here sequences live padded as
``LoDArray(data=[batch, max_len, *feat], lens=[batch])`` (core/lod.py — the
ragged→padded packing of operators/math/sequence_padding.h promoted to the
XLA boundary), and every op is a masked dense computation, so the whole
sequence pipeline fuses into one XLA program with static shapes.

Gradients come from ``jax.vjp`` over the same lowering unless a closed form
is cheaper.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.lod import LoDArray
from ..core.registry import register_op, OpSpec, same_shape
from .common import G, data_of


def _seq(v):
    if not isinstance(v, LoDArray):
        raise TypeError(f"sequence op expects a LoDArray input, got {type(v)}")
    return v


def _rows_to_level0(y):
    """[batch_rows] int32: the LEVEL-0 (outermost) group index of each data
    row, composing the parent maps through every outer level — ref_level=0
    addresses the outermost LoD level regardless of nesting depth
    (reference sequence_expand_op.cc ref_level semantics over N-level LoD,
    lod_tensor.h:55)."""
    idx = y.row_to_outer()                    # rows -> innermost outer groups
    for level in range(len(y.outer_levels) - 2, -1, -1):
        idx = y.row_to_outer(level)[idx]      # groups -> parents, composed
    return idx


def _mask(data, lens, dtype=None):
    m = jnp.arange(data.shape[1])[None, :] < lens[:, None]
    if dtype is not None:
        m = m.astype(dtype)
    return m


def _feat_mask(data, lens):
    """Mask broadcastable over the feature dims of [b, L, *feat]."""
    m = _mask(data, lens, data.dtype)
    return m.reshape(m.shape + (1,) * (data.ndim - 2))


def _vjp_grad(op_type, in_slots=("X",), out_slot="Out", extra_outputs=()):
    """Grad maker: "<op>_grad" consumes the forward inputs + dOut and emits
    input grads (the DefaultGradOpDescMaker pattern)."""
    def maker(op):
        inputs = {s: op.input(s) for s in in_slots if op.input(s)}
        inputs[out_slot + "@GRAD"] = G(op.output(out_slot))
        outputs = {s + "@GRAD": G(op.input(s))
                   for s in in_slots if op.input(s)}
        return [OpSpec(op_type + "_grad", inputs, outputs, dict(op.attrs))]
    return maker


# ---------------------------------------------------------------------------
# sequence_pool — AVERAGE / SUM / SQRT / MAX / LAST / FIRST  → dense [b, feat]
# (reference sequence_pool_op.cc + math/sequence_pooling.cc)
# ---------------------------------------------------------------------------

def _sequence_pool_compute(data, lens, pooltype):
    fm = _feat_mask(data, lens)
    masked = data * fm
    n = jnp.maximum(lens, 1).astype(data.dtype)
    n = n.reshape((-1,) + (1,) * (data.ndim - 2))
    if pooltype == "SUM":
        return masked.sum(axis=1)
    if pooltype == "AVERAGE":
        return masked.sum(axis=1) / n
    if pooltype == "SQRT":
        return masked.sum(axis=1) / jnp.sqrt(n)
    if pooltype == "MAX":
        neg = jnp.where(fm > 0, data, -jnp.inf)
        return neg.max(axis=1)
    if pooltype == "LAST":
        idx = jnp.maximum(lens - 1, 0)
        return jnp.take_along_axis(
            data, idx.reshape((-1, 1) + (1,) * (data.ndim - 2))
            .astype(jnp.int32) * jnp.ones((1,) + data.shape[1:], jnp.int32)[:, :1],
            axis=1).squeeze(1)
    if pooltype == "FIRST":
        return data[:, 0]
    raise ValueError(f"unknown pooltype {pooltype!r}")


def _sp_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    if x.shape is not None:
        out.shape = tuple(x.shape[:1]) + tuple(x.shape[2:]) \
            if len(x.shape) > 2 else x.shape
    out.dtype = x.dtype
    out.lod_level = 0


def _windowed_pool(data, lens, k, pooltype):
    """Strided sequence pooling (reference seq pooling with stride: one
    result PER WINDOW of k steps, so the output is itself a sequence of
    ceil(len/k) entries)."""
    b, L = data.shape[:2]
    feat = data.shape[2:]
    nw = -(-L // k)
    pad = nw * k - L
    dp = jnp.pad(data, ((0, 0), (0, pad)) + ((0, 0),) * len(feat))
    w = dp.reshape((b, nw, k) + feat)
    tok = (jnp.arange(nw * k).reshape(nw, k))[None]          # [1, nw, k]
    valid = tok < lens[:, None, None]                        # [b, nw, k]
    vm = valid.reshape(valid.shape + (1,) * len(feat)).astype(data.dtype)
    counts = valid.sum(axis=2)                               # [b, nw]
    cm = jnp.maximum(counts, 1).reshape(
        (b, nw) + (1,) * len(feat)).astype(data.dtype)
    if pooltype == "SUM":
        out = (w * vm).sum(axis=2)
    elif pooltype == "AVERAGE":
        out = (w * vm).sum(axis=2) / cm
    elif pooltype == "SQRT":
        out = (w * vm).sum(axis=2) / jnp.sqrt(cm)
    elif pooltype == "MAX":
        out = jnp.where(vm > 0, w, -jnp.inf).max(axis=2)
        out = jnp.where(counts.reshape(cm.shape) > 0, out, 0.0)
    elif pooltype == "FIRST":
        out = w[:, :, 0]
    elif pooltype == "LAST":
        last = jnp.clip(counts - 1, 0, k - 1)                # [b, nw]
        idx = last.reshape((b, nw, 1) + (1,) * len(feat)).astype(jnp.int32)
        idx = jnp.broadcast_to(idx, (b, nw, 1) + feat)
        out = jnp.take_along_axis(w, idx, axis=2)[:, :, 0]
    else:
        raise ValueError(f"unknown pooltype {pooltype!r}")
    out_lens = -(-lens // k)
    out = out * _feat_mask(out, out_lens)
    return LoDArray(out, out_lens)


def _regroup_rows(rows, outer_lens):
    """[n_inner, *feat] rows -> padded LoDArray [n_outer, max_inner, *feat]
    grouped by outer_lens (the TO_SEQUENCE pooling output form)."""
    n = rows.shape[0]
    starts = jnp.cumsum(outer_lens) - outer_lens
    owner = jnp.searchsorted(jnp.cumsum(outer_lens), jnp.arange(n),
                             side="right").astype(jnp.int32)
    pos = jnp.arange(n) - starts[owner]
    # static padded bound: at most n_inner rows can land in one group
    out = jnp.zeros((outer_lens.shape[0], rows.shape[0]) + rows.shape[1:],
                    rows.dtype)
    out = out.at[owner, pos].set(rows)
    return LoDArray(out, outer_lens.astype(jnp.int32))


@register_op("sequence_pool", infer_shape=_sp_infer,
             grad=_vjp_grad("sequence_pool"))
def sequence_pool(ctx):
    x = _seq(ctx.input("X"))
    pooltype = ctx.attr("pooltype", "AVERAGE")
    stride = int(ctx.attr("stride", 0) or 0)
    if stride > 0:
        ctx.set_output("Out", _windowed_pool(x.data, x.lens, stride,
                                             pooltype))
        return
    pooled = _sequence_pool_compute(x.data, x.lens, pooltype)
    if x.outer_levels and ctx.attr("agg_level", "non-seq") == "seq":
        # nested input, pool INNER sequences -> a level-1 sequence of
        # per-inner results grouped by the outer level (reference
        # AggregateLevel.TO_SEQUENCE)
        ctx.set_output("Out", _regroup_rows(pooled, x.outer_levels[-1]))
        return
    ctx.set_output("Out", pooled)


@register_op("sequence_pool_grad")
def sequence_pool_grad(ctx):
    x = _seq(ctx.input("X"))
    dy = data_of(ctx.input("Out@GRAD"))
    pooltype = ctx.attr("pooltype", "AVERAGE")
    _, vjp = jax.vjp(
        lambda d: _sequence_pool_compute(d, x.lens, pooltype), x.data)
    ctx.set_output("X@GRAD", LoDArray(vjp(dy)[0], x.lens))


# ---------------------------------------------------------------------------
# sequence_softmax — softmax within each sequence (feature dim of size 1)
# ---------------------------------------------------------------------------

def _sequence_softmax_compute(data, lens):
    squeeze = data.ndim == 3 and data.shape[-1] == 1
    d = data[..., 0] if squeeze else data
    m = _mask(d, lens)
    z = jnp.where(m, d, -jnp.inf)
    z = z - z.max(axis=1, keepdims=True)
    e = jnp.exp(z) * m.astype(d.dtype)
    out = e / jnp.maximum(e.sum(axis=1, keepdims=True), 1e-30)
    return out[..., None] if squeeze else out


@register_op("sequence_softmax", infer_shape=same_shape("X", "Out"),
             grad=_vjp_grad("sequence_softmax"))
def sequence_softmax(ctx):
    x = _seq(ctx.input("X"))
    ctx.set_output("Out",
                   LoDArray(_sequence_softmax_compute(x.data, x.lens), x.lens))


@register_op("sequence_softmax_grad")
def sequence_softmax_grad(ctx):
    x = _seq(ctx.input("X"))
    dy = _seq(ctx.input("Out@GRAD"))
    _, vjp = jax.vjp(lambda d: _sequence_softmax_compute(d, x.lens), x.data)
    ctx.set_output("X@GRAD", LoDArray(vjp(dy.data)[0], x.lens))


# ---------------------------------------------------------------------------
# sequence_expand — tile x's i-th row along y's i-th sequence
# (reference sequence_expand_op.cc; the NMT-attention "broadcast encoder
# state over decoder steps" primitive)
# ---------------------------------------------------------------------------

def _se_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape, out.dtype = x.shape, x.dtype
    # ref_level=0 emits dense per-inner-sequence rows (lod 0); the default
    # innermost expansion emits one sequence per x row (lod 1)
    out.lod_level = 0 if op.attrs.get("ref_level", -1) == 0 else 1


@register_op("sequence_expand", infer_shape=_se_infer, grad=lambda op: [OpSpec(
    "sequence_expand_grad",
    {"X": op.input("X"), "Y": op.input("Y"),
     "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def sequence_expand(ctx):
    """ref_level=-1/1 (default): tile x's i-th row along y's i-th sequence.
    ref_level=0 over a 2-level y: repeat x's i-th row once per INNER
    sequence of y's i-th OUTER sequence (reference sequence_expand_op.cc's
    nested-LoD expansion) — the NMT 'broadcast encoder state over beam
    rows' primitive."""
    xv = ctx.input("X")
    y = _seq(ctx.input("Y"))
    ref_level = int(ctx.attr("ref_level", -1))
    if ref_level == 0 and y.outer_lens is not None:
        if isinstance(xv, LoDArray):
            # sequence_expand_op.cc nested case: x's i-th SEQUENCE repeated
            # once per inner sequence of y's i-th outer group, sub-lod
            # preserved — a row gather in the padded representation. Output
            # sequence count == y's inner-sequence count (static).
            x = _seq(xv)
            n_outer = y.outer_levels[0].shape[0]
            if x.data.shape[0] != n_outer:
                raise ValueError(
                    f"sequence_expand ref_level=0: x has {x.data.shape[0]} "
                    f"sequences but y has {n_outer} outer groups")
            idx = _rows_to_level0(y)          # [y_batch] -> outer group
            ctx.set_output("Out", LoDArray(x.data[idx], x.lens[idx]))
            return
        x = data_of(xv)                       # [n_level0, *feat]
        out = x[_rows_to_level0(y)]           # [batch_rows, *feat]
        ctx.set_output("Out", out)
        return
    if isinstance(xv, LoDArray):
        # innermost-level reference with ragged X (sequence_expand_op.cc
        # "Case 2": x.lod=[[0,2,4]], y.lod=[...,[0,3,6,7,8]] -> x's i-th
        # sequence repeated y_lens[i] times). Output sequence count is
        # sum(y_lens) — data-dependent — so the padded form emits the static
        # bound n_y*max_len rows with jnp.repeat(total_repeat_length=...);
        # rows past the true total carry length 0 (empty trailing sequences
        # at the fetch boundary when y is ragged under jit; exact when
        # sum(y_lens) == bound or when running eagerly with concrete lens).
        x = _seq(xv)
        if x.data.shape[0] != y.lens.shape[0]:
            raise ValueError(
                f"sequence_expand: x has {x.data.shape[0]} sequences but y "
                f"has {y.lens.shape[0]} reference segments")
        total = int(y.lens.shape[0]) * int(y.max_len)
        concrete = not isinstance(y.lens, jax.core.Tracer)
        if concrete:
            total = int(jnp.sum(y.lens))
        idx = jnp.repeat(jnp.arange(y.lens.shape[0]), y.lens,
                         total_repeat_length=total)
        n_valid = jnp.sum(y.lens)
        valid = jnp.arange(total) < n_valid
        ctx.set_output("Out", LoDArray(
            x.data[idx], jnp.where(valid, x.lens[idx], 0)))
        return
    x = data_of(xv)  # [batch, feat]
    tiled = jnp.broadcast_to(x[:, None], (x.shape[0], y.max_len) + x.shape[1:])
    fm = _feat_mask(tiled, y.lens)
    ctx.set_output("Out", LoDArray(tiled * fm, y.lens))


@register_op("sequence_expand_grad")
def sequence_expand_grad(ctx):
    xv = ctx.input("X")
    y = _seq(ctx.input("Y"))
    dy_v = ctx.input("Out@GRAD")
    ref_level = int(ctx.attr("ref_level", -1))
    if ref_level == 0 and y.outer_lens is not None:
        idx = _rows_to_level0(y)
        n_outer = y.outer_levels[0].shape[0]
        if isinstance(xv, LoDArray):
            # ragged-X expansion was a row gather; grad is the segment-sum
            # of the repeated padded rows back onto x's sequences
            dy = _seq(dy_v)
            x = _seq(xv)
            d = dy.data * _feat_mask(dy.data, x.lens[idx])
            ctx.set_output("X@GRAD", LoDArray(
                jax.ops.segment_sum(d, idx, num_segments=n_outer), x.lens))
            return
        d = data_of(dy_v)                     # [batch_rows, *feat]
        ctx.set_output("X@GRAD", jax.ops.segment_sum(
            d, idx, num_segments=n_outer))
        return
    if isinstance(xv, LoDArray):
        x = _seq(xv)
        dy = _seq(dy_v)
        total = dy.data.shape[0]
        idx = jnp.repeat(jnp.arange(y.lens.shape[0]), y.lens,
                         total_repeat_length=total)
        valid = (jnp.arange(total) < jnp.sum(y.lens)).reshape(
            (total,) + (1,) * (dy.data.ndim - 1))
        d = dy.data * _feat_mask(dy.data, dy.lens) * valid.astype(dy.data.dtype)
        ctx.set_output("X@GRAD", LoDArray(
            jax.ops.segment_sum(d, idx, num_segments=x.data.shape[0]),
            x.lens))
        return
    dy = _seq(dy_v)
    d = dy.data * _feat_mask(dy.data, y.lens)
    ctx.set_output("X@GRAD", d.sum(axis=1))


# ---------------------------------------------------------------------------
# sequence_concat — concatenate along time per row
# ---------------------------------------------------------------------------

def _seq_concat2(a, al, b, bl):
    out_max = a.shape[1] + b.shape[1]
    pos = jnp.arange(out_max)[None, :]              # [1, Lo]
    from_b = pos >= al[:, None]                      # past a's valid prefix
    ia = jnp.minimum(pos, a.shape[1] - 1)
    ib = jnp.clip(pos - al[:, None], 0, b.shape[1] - 1)
    ga = _gather_time(a, jnp.broadcast_to(ia, (a.shape[0], out_max)))
    gb = _gather_time(b, jnp.broadcast_to(ib, (b.shape[0], out_max)))
    sel = from_b.reshape(from_b.shape + (1,) * (a.ndim - 2))
    out = jnp.where(sel, gb, ga)
    lens = al + bl
    return out * _feat_mask(out, lens), lens


def _gather_time(x, idx):
    """x: [b, L, *feat], idx: [b, Lo] -> [b, Lo, *feat]."""
    idx = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    idx = jnp.broadcast_to(idx, idx.shape[:2] + x.shape[2:])
    return jnp.take_along_axis(x, idx.astype(jnp.int32), axis=1)


def _sequence_concat_compute(datas, lenss):
    out, lens = datas[0], lenss[0]
    for d, l in zip(datas[1:], lenss[1:]):
        out, lens = _seq_concat2(out, lens, d, l)
    return out, lens


@register_op("sequence_concat", grad=lambda op: [OpSpec(
    "sequence_concat_grad",
    {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def sequence_concat(ctx):
    xs = [_seq(v) for v in ctx.inputs("X")]
    out, lens = _sequence_concat_compute([x.data for x in xs],
                                         [x.lens for x in xs])
    ctx.set_output("Out", LoDArray(out, lens))


@register_op("sequence_concat_grad")
def sequence_concat_grad(ctx):
    xs = [_seq(v) for v in ctx.inputs("X")]
    dy = _seq(ctx.input("Out@GRAD"))
    _, vjp = jax.vjp(
        lambda *ds: _sequence_concat_compute(ds, [x.lens for x in xs])[0],
        *[x.data for x in xs])
    grads = vjp(dy.data)
    ctx.set_outputs("X@GRAD", [LoDArray(g, x.lens)
                               for g, x in zip(grads, xs)])


# ---------------------------------------------------------------------------
# sequence_reshape — change feature width, lengths rescale
# ---------------------------------------------------------------------------

@register_op("sequence_reshape", grad=_vjp_grad("sequence_reshape"))
def sequence_reshape(ctx):
    x = _seq(ctx.input("X"))
    new_dim = int(ctx.attr("new_dim"))
    b, L, D = x.data.shape
    assert (L * D) % new_dim == 0, "sequence_reshape: indivisible new_dim"
    out = x.data.reshape(b, L * D // new_dim, new_dim)
    lens = (x.lens * D) // new_dim
    ctx.set_output("Out", LoDArray(out, lens))


@register_op("sequence_reshape_grad")
def sequence_reshape_grad(ctx):
    x = _seq(ctx.input("X"))
    dy = _seq(ctx.input("Out@GRAD"))
    ctx.set_output("X@GRAD", LoDArray(dy.data.reshape(x.data.shape), x.lens))


# ---------------------------------------------------------------------------
# sequence_slice / sequence_erase / lod_reset
# ---------------------------------------------------------------------------

@register_op("sequence_slice", grad=lambda op: [OpSpec(
    "sequence_slice_grad",
    {"X": op.input("X"), "Offset": op.input("Offset"),
     "Length": op.input("Length"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def sequence_slice(ctx):
    """Slice [offset, offset+length) out of every sequence
    (sequence_slice_op.cc; Offset/Length arrive as [b] or [b,1] tensors)."""
    x = _seq(ctx.input("X"))
    off = data_of(ctx.input("Offset")).reshape(-1).astype(jnp.int32)
    length = data_of(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    idx = off[:, None] + jnp.arange(x.max_len)[None, :]
    idx = jnp.minimum(idx, x.max_len - 1)
    out = _gather_time(x.data, idx)
    lens = jnp.minimum(length, jnp.maximum(x.lens - off, 0))
    ctx.set_output("Out", LoDArray(out * _feat_mask(out, lens), lens))


@register_op("sequence_slice_grad")
def sequence_slice_grad(ctx):
    x = _seq(ctx.input("X"))
    off = data_of(ctx.input("Offset")).reshape(-1).astype(jnp.int32)
    dy = _seq(ctx.input("Out@GRAD"))
    d = dy.data * _feat_mask(dy.data, dy.lens)
    # scatter rows back to their offset positions
    pos = jnp.arange(x.max_len)[None, :] - off[:, None]
    valid = (pos >= 0) & (pos < dy.max_len)
    gather_idx = jnp.clip(pos, 0, dy.max_len - 1)
    dx = _gather_time(d, gather_idx)
    dx = dx * valid.reshape(valid.shape + (1,) * (dx.ndim - 2)).astype(dx.dtype)
    ctx.set_output("X@GRAD", LoDArray(dx, x.lens))


@register_op("sequence_erase")
def sequence_erase(ctx):
    """Remove tokens matching attr 'tokens' and compact each row
    (sequence_erase_op.cc — the CTC-decoding blank/dup stripper)."""
    x = _seq(ctx.input("X"))
    tokens = jnp.asarray(ctx.attr("tokens", []), dtype=x.data.dtype)
    d = x.data
    flatd = d if d.ndim == 2 else d[..., 0]
    valid = _mask(flatd, x.lens, jnp.bool_)
    keep = valid & ~jnp.isin(flatd, tokens)
    # stable partition: kept elements first, order preserved
    order = jnp.argsort(~keep, axis=1, stable=True)
    comp = jnp.take_along_axis(flatd, order, axis=1)
    lens = keep.sum(axis=1).astype(jnp.int32)
    comp = comp * _mask(comp, lens, comp.dtype)
    ctx.set_output("Out", LoDArray(comp if d.ndim == 2 else comp[..., None],
                                   lens))


def _lod_repack(data, old_lens, new_lens, new_max):
    """Re-segment the flat rows of a padded LoD tensor under new lengths
    (the whole point of lod_reset_op.cc: same rows, new offsets — including
    a different number of sequences). Traced-safe: only ``new_max`` (the new
    padded bound) must be static; row/col lookups are gathers."""
    b, L = data.shape[0], data.shape[1]
    old_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(old_lens.astype(jnp.int32))])
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(new_lens.astype(jnp.int32))])
    pos = jnp.arange(new_max, dtype=jnp.int32)
    flat_idx = new_off[:-1, None] + pos[None, :]          # [n_new, new_max]
    valid = pos[None, :] < new_lens[:, None]
    flat_idx = jnp.clip(flat_idx, 0, b * L - 1)
    row = jnp.clip(jnp.searchsorted(old_off[1:], flat_idx, side="right"),
                   0, b - 1)
    col = jnp.clip(flat_idx - old_off[row], 0, L - 1)
    gathered = data[row, col]
    mask = valid.reshape(valid.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, gathered, 0)


@register_op("lod_reset")
def lod_reset(ctx):
    xv = ctx.input("X")
    x = _seq(xv) if isinstance(xv, LoDArray) else None
    data = x.data if x is not None else data_of(xv)
    if ctx.has_input("Y"):
        y = ctx.input("Y")
        # fallback static bound on any one new sequence's length: the total
        # flat element count (rows for a plain tensor, rows*padded-len for a
        # LoD input) — a new segment can never exceed it
        cap = data.shape[0] if x is None else data.shape[0] * data.shape[1]
        if isinstance(y, LoDArray):
            lens = y.lens
            # Y's own padded bound caps its max length (static)
            new_max = y.data.shape[1] if y.data.ndim >= 2 else cap
        else:
            lens = jnp.diff(data_of(y).astype(jnp.int32))
            concrete = not isinstance(lens, jax.core.Tracer)
            new_max = int(jnp.max(lens)) if concrete and lens.size else cap
    else:
        target = np.asarray(ctx.attr("target_lod"), np.int64)
        lens = jnp.asarray(np.diff(target), jnp.int32)
        new_max = int(np.diff(target).max()) if target.size > 1 else 0
    old_lens = jnp.ones((data.shape[0],), jnp.int32) if x is None else x.lens
    # malformed target lod (covers a different element count than X holds)
    # corrupts the repack; reject when both sides are concrete (the eager /
    # attr path — traced lens can't be validated at trace time)
    if not isinstance(lens, jax.core.Tracer) \
            and not isinstance(old_lens, jax.core.Tracer):
        n_new, n_old = int(jnp.sum(lens)), int(jnp.sum(old_lens))
        if n_new != n_old:
            raise ValueError(
                f"lod_reset: target lod covers {n_new} elements but X "
                f"holds {n_old}")
    if x is None:
        # plain tensor input (lod_reset_op.cc accepts a bare tensor): each
        # row is one element; segment rows by the new lengths
        packed = _lod_repack(data[:, None], old_lens, lens, new_max)
        ctx.set_output("Out", LoDArray(packed, lens))
        return
    packed = _lod_repack(data, x.lens, lens, new_max)
    ctx.set_output("Out", LoDArray(packed, lens))


# ---------------------------------------------------------------------------
# sequence_conv — context-window convolution over time
# (sequence_conv_op.cc + math/context_project.h)
# ---------------------------------------------------------------------------

def _sequence_conv_compute(data, lens, filt, context_length, context_start):
    b, L, D = data.shape
    fm = _feat_mask(data, lens)
    d = data * fm
    cols = []
    for j in range(context_length):
        shift = context_start + j
        if shift < 0:
            shifted = jnp.pad(d, ((0, 0), (-shift, 0), (0, 0)))[:, :L]
        elif shift > 0:
            shifted = jnp.pad(d, ((0, 0), (0, shift), (0, 0)))[:, shift:]
        else:
            shifted = d
        # rows beyond each sequence's length contribute zeros (the reference
        # pads per-sequence, not per-batch — masking achieves the same)
        pos = jnp.arange(L)[None, :] + shift
        ok = (pos >= 0) & (pos < lens[:, None])
        cols.append(shifted * ok[..., None].astype(d.dtype))
    col = jnp.concatenate(cols, axis=-1)          # [b, L, ctx*D]
    out = jnp.einsum("bld,df->blf", col, filt)    # MXU matmul
    return out * fm[..., :1] if fm.shape[-1] != 1 else out * fm


def _sc_grad_maker(op):
    return [OpSpec("sequence_conv_grad",
                   {"X": op.input("X"), "Filter": op.input("Filter"),
                    "Out@GRAD": G(op.output("Out"))},
                   {"X@GRAD": G(op.input("X")),
                    "Filter@GRAD": G(op.input("Filter"))}, dict(op.attrs))]


def _sc_infer(op, block):
    x = block.var(op.input("X")[0])
    f = block.var(op.input("Filter")[0])
    out = block.var(op.output("Out")[0])
    if x.shape is not None and f.shape is not None:
        out.shape = tuple(x.shape[:-1]) + (f.shape[1],)
    out.dtype = x.dtype
    out.lod_level = x.lod_level


@register_op("sequence_conv", infer_shape=_sc_infer, grad=_sc_grad_maker)
def sequence_conv(ctx):
    x = _seq(ctx.input("X"))
    filt = data_of(ctx.input("Filter"))
    cl = int(ctx.attr("contextLength"))
    cs = int(ctx.attr("contextStart", -((cl - 1) // 2)))
    out = _sequence_conv_compute(x.data, x.lens, filt, cl, cs)
    ctx.set_output("Out", LoDArray(out, x.lens))


@register_op("sequence_conv_grad")
def sequence_conv_grad(ctx):
    x = _seq(ctx.input("X"))
    filt = data_of(ctx.input("Filter"))
    dy = _seq(ctx.input("Out@GRAD"))
    cl = int(ctx.attr("contextLength"))
    cs = int(ctx.attr("contextStart", -((cl - 1) // 2)))
    _, vjp = jax.vjp(
        lambda d, f: _sequence_conv_compute(d, x.lens, f, cl, cs),
        x.data, filt)
    dmasked = dy.data * _feat_mask(dy.data, x.lens)
    dx, df = vjp(dmasked)
    ctx.set_output("X@GRAD", LoDArray(dx, x.lens))
    ctx.set_output("Filter@GRAD", df)


# ---------------------------------------------------------------------------
# row_conv — lookahead convolution (row_conv_op.cc, DeepSpeech2)
# ---------------------------------------------------------------------------

def _row_conv_compute(data, lens, filt):
    k, D = filt.shape            # future_context + 1
    b, L, _ = data.shape
    d = data * _feat_mask(data, lens)
    out = jnp.zeros_like(d)
    for j in range(k):
        shifted = jnp.pad(d, ((0, 0), (0, j), (0, 0)))[:, j:] if j else d
        pos = jnp.arange(L)[None, :] + j
        ok = (pos < lens[:, None])[..., None].astype(d.dtype)
        out = out + shifted * ok * filt[j][None, None, :]
    return out


@register_op("row_conv", grad=lambda op: [OpSpec(
    "row_conv_grad",
    {"X": op.input("X"), "Filter": op.input("Filter"),
     "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X")), "Filter@GRAD": G(op.input("Filter"))},
    dict(op.attrs))])
def row_conv(ctx):
    x = _seq(ctx.input("X"))
    filt = data_of(ctx.input("Filter"))
    ctx.set_output("Out", LoDArray(_row_conv_compute(x.data, x.lens, filt),
                                   x.lens))


@register_op("row_conv_grad")
def row_conv_grad(ctx):
    x = _seq(ctx.input("X"))
    filt = data_of(ctx.input("Filter"))
    dy = _seq(ctx.input("Out@GRAD"))
    _, vjp = jax.vjp(lambda d, f: _row_conv_compute(d, x.lens, f),
                     x.data, filt)
    dx, df = vjp(dy.data * _feat_mask(dy.data, x.lens))
    ctx.set_output("X@GRAD", LoDArray(dx, x.lens))
    ctx.set_output("Filter@GRAD", df)
