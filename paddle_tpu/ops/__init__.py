"""Op library: importing this package registers every op lowering.

The registry split (core/registry.py) mirrors the reference's
REGISTER_OPERATOR/REGISTER_OP_*_KERNEL machinery
(/root/reference/paddle/fluid/framework/op_registry.h); modules here correspond
to the op families in SURVEY.md §2.2.
"""

from . import (  # noqa: F401
    elementwise,
    activation,
    tensor_ops,
    matmul,
    reduce,
    loss,
    nn_ops,
    conv_ops,
    norm_ops,
    sequence_ops,
    rnn_ops,
    attention_ops,
    control_flow_ops,
    crf_ops,
    ctc_ops,
    fused_ops,
    optimizer_ops,
    metrics,
    detection_ops,
    misc_ops,
    breadth_ops,
    io_ops,
)
