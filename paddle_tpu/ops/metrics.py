"""Metric ops: accuracy, auc — reference accuracy_op.cu, auc_op.cc
(/root/reference/paddle/fluid/operators/)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op
from .common import data_of


@register_op("accuracy")
def accuracy(ctx):
    """Inputs follow the reference (accuracy_op.cc): Out = top-k indices' match
    rate vs Label; also emits Correct and Total counters."""
    indices = data_of(ctx.input("Indices"))
    label = data_of(ctx.input("Label")).reshape(-1, 1)
    correct_per_row = jnp.any(indices == label, axis=1)
    num_correct = jnp.sum(correct_per_row.astype(jnp.int32))
    total = indices.shape[0]
    ctx.set_output("Accuracy",
                   (num_correct.astype(jnp.float32) / total).reshape(()))
    ctx.set_output("Correct", num_correct.reshape(()))
    ctx.set_output("Total", jnp.asarray(total, dtype=jnp.int32))
