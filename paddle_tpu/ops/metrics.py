"""Metric ops: accuracy, auc, precision_recall, chunk_eval.

Reference: /root/reference/paddle/fluid/operators/accuracy_op.cc,
auc_op.{cc,h} (thresholded TP/FN/TN/FP sweep over prediction column 0, ROC
trapezoid or PR), precision_recall_op.{cc,h} (per-class TP/FP/TN/FN with
macro/micro averaging and running state), chunk_eval_op.{cc,h} (chunk
extraction from IOB/IOE/IOBES tag sequences, F1 over matched chunks).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op
from .common import data_of


@register_op("accuracy")
def accuracy(ctx):
    """Inputs follow the reference (accuracy_op.cc): Out = top-k indices' match
    rate vs Label; also emits Correct and Total counters."""
    indices = data_of(ctx.input("Indices"))
    label = data_of(ctx.input("Label")).reshape(-1, 1)
    correct_per_row = jnp.any(indices == label, axis=1)
    num_correct = jnp.sum(correct_per_row.astype(jnp.int32))
    total = indices.shape[0]
    ctx.set_output("Accuracy",
                   (num_correct.astype(jnp.float32) / total).reshape(()))
    ctx.set_output("Correct", num_correct.reshape(()))
    ctx.set_output("Total", jnp.asarray(total, dtype=jnp.int32))


def auc_from_stats(tp, fn, tn, fp, curve="ROC"):
    """Trapezoidal area under the thresholded curve (auc_op.h:91-120)."""
    eps = 1e-12
    if curve == "PR":
        x = tp / jnp.maximum(tp + fn, eps)          # recall
        y = tp / jnp.maximum(tp + fp, eps)          # precision
    else:
        x = fp / jnp.maximum(fp + tn, eps)          # fpr
        y = tp / jnp.maximum(tp + fn, eps)          # tpr
    dx = x[:-1] - x[1:]           # thresholds ascending -> x descending
    return jnp.sum(dx * (y[:-1] + y[1:]) / 2.0)


@register_op("auc")
def auc(ctx):
    """Batch AUC over prediction column 0 vs binary labels at
    ``num_thresholds`` thresholds (auc_op.h:29-120): curve="ROC" integrates
    TPR over FPR by trapezoid; "PR" integrates precision over recall.
    Emits TP/FN/TN/FP stat vectors so a stateful Evaluator can accumulate
    across batches (the reference's Python evaluator pattern)."""
    pred = data_of(ctx.input("Out"))
    label = data_of(ctx.input("Label")).reshape(-1)
    num_thresholds = int(ctx.attr("num_thresholds", 200))
    curve = ctx.attr("curve", "ROC")

    eps = 1e-7
    inner = jnp.arange(1, num_thresholds - 1,
                       dtype=jnp.float32) / (num_thresholds - 1)
    thresholds = jnp.concatenate([jnp.asarray([-eps], jnp.float32), inner,
                                  jnp.asarray([1.0 + eps], jnp.float32)])
    score = pred[:, 0] if pred.ndim == 2 else pred.reshape(-1)
    pos = label > 0
    above = score[None, :] >= thresholds[:, None]       # [T, N]
    tp = jnp.sum(above & pos[None, :], axis=1).astype(jnp.float32)
    fn = jnp.sum((~above) & pos[None, :], axis=1).astype(jnp.float32)
    fp = jnp.sum(above & (~pos[None, :]), axis=1).astype(jnp.float32)
    tn = jnp.sum((~above) & (~pos[None, :]), axis=1).astype(jnp.float32)
    ctx.set_output("TPOut", tp)
    ctx.set_output("FNOut", fn)
    ctx.set_output("TNOut", tn)
    ctx.set_output("FPOut", fp)
    ctx.set_output("AUC", auc_from_stats(tp, fn, tn, fp, curve).reshape(()))


@register_op("precision_recall")
def precision_recall(ctx):
    """Per-class TP/FP/TN/FN + macro/micro precision/recall/F1
    (precision_recall_op.h). Inputs: Indices [N,1] (predicted class),
    Labels [N,1]; optional Weights [N] and StatesInfo [C,4] running state.
    Outputs BatchMetrics [6] (macro P/R/F1, micro P/R/F1), AccumMetrics
    [6], AccumStatesInfo [C,4] with columns (TP, FP, TN, FN)."""
    idx = data_of(ctx.input("Indices")).reshape(-1)
    labels = data_of(ctx.input("Labels")).reshape(-1)
    C = int(ctx.attr("class_number"))
    w = data_of(ctx.input("Weights")).reshape(-1).astype(jnp.float32) \
        if ctx.has_input("Weights") \
        else jnp.ones((idx.shape[0],), jnp.float32)
    states = data_of(ctx.input("StatesInfo")) \
        if ctx.has_input("StatesInfo") else jnp.zeros((C, 4), jnp.float32)

    cls = jnp.arange(C)
    pred_is = idx[None, :] == cls[:, None]          # [C, N]
    lab_is = labels[None, :] == cls[:, None]
    wf = w[None, :]
    tp = jnp.sum((pred_is & lab_is) * wf, axis=1)
    fp = jnp.sum((pred_is & ~lab_is) * wf, axis=1)
    fn = jnp.sum((~pred_is & lab_is) * wf, axis=1)
    tn = jnp.sum((~pred_is & ~lab_is) * wf, axis=1)
    batch = jnp.stack([tp, fp, tn, fn], axis=1)      # [C, 4]
    accum = states + batch

    def metrics6(st):
        tp_, fp_, fn_ = st[:, 0], st[:, 1], st[:, 3]
        eps = 1e-12
        prec = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, eps),
                         1.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, eps),
                        1.0)
        f1 = jnp.where(prec + rec > 0,
                       2 * prec * rec / jnp.maximum(prec + rec, eps), 0.0)
        macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
        tps, fps, fns = tp_.sum(), fp_.sum(), fn_.sum()
        mprec = jnp.where(tps + fps > 0, tps / jnp.maximum(tps + fps, eps),
                          1.0)
        mrec = jnp.where(tps + fns > 0, tps / jnp.maximum(tps + fns, eps),
                         1.0)
        mf1 = jnp.where(mprec + mrec > 0,
                        2 * mprec * mrec / jnp.maximum(mprec + mrec, eps),
                        0.0)
        return jnp.concatenate([macro, jnp.stack([mprec, mrec, mf1])])

    ctx.set_output("BatchMetrics", metrics6(batch))
    ctx.set_output("AccumMetrics", metrics6(accum))
    ctx.set_output("AccumStatesInfo", accum)


def extract_chunks(tags, scheme, num_chunk_types, excluded=()):
    """Chunk extraction (mirrors chunk_eval_op.h GetSegments): returns a set
    of (begin, end, type). Schemes: IOB (tag = type*2 + {0:B, 1:I}), IOE
    (…{0:I, 1:E}), IOBES (type*4 + {B,I,E,S}), plain (tag == type).
    Out-of-range tags are Outside."""
    chunks = []
    state = {"start": None, "type": None}
    tags = [int(t) for t in tags]

    def close(end):
        if state["start"] is not None and state["type"] not in excluded:
            chunks.append((state["start"], end, state["type"]))
        state["start"] = state["type"] = None

    for i, t in enumerate(tags):
        if scheme == "plain":
            ttype, pos = t, "S"
            is_tag = 0 <= t < num_chunk_types
        elif scheme == "IOB":
            ttype, pos = t // 2, ("B" if t % 2 == 0 else "I")
            is_tag = 0 <= t < num_chunk_types * 2
        elif scheme == "IOE":
            ttype, pos = t // 2, ("I" if t % 2 == 0 else "E")
            is_tag = 0 <= t < num_chunk_types * 2
        elif scheme == "IOBES":
            ttype, pos = t // 4, "BIES"[t % 4]
            is_tag = 0 <= t < num_chunk_types * 4
        else:
            raise ValueError(f"unknown chunk scheme {scheme!r}")
        if not is_tag:
            close(i - 1)
            continue
        if scheme == "plain":
            if state["start"] is not None and ttype != state["type"]:
                close(i - 1)
            if state["start"] is None:
                state["start"], state["type"] = i, ttype
            continue
        if pos in ("B", "S") or (state["start"] is not None
                                 and ttype != state["type"]):
            close(i - 1)
        if state["start"] is None:
            state["start"], state["type"] = i, ttype
        if pos in ("E", "S"):
            close(i)
    close(len(tags) - 1)
    return set(chunks)


@register_op("chunk_eval")
def chunk_eval(ctx):
    """Chunking F1 over tagged sequences (chunk_eval_op.cc): precision =
    |inference ∩ label chunks| / |inference chunks|, etc. LoD inputs; runs
    host-side per sequence (the reference is CPU-only too)."""
    import numpy as np

    inf_v = ctx.input("Inference")
    lab_v = ctx.input("Label")
    scheme = ctx.attr("chunk_scheme", "IOB")
    num_types = int(ctx.attr("num_chunk_types"))
    excluded = tuple(ctx.attr("excluded_chunk_types", []) or [])

    def seqs(v):
        if isinstance(v, LoDArray):
            data = np.asarray(v.data).reshape(v.data.shape[0], -1)
            lens = np.asarray(v.lens)
            return [data[i, :lens[i]] for i in range(len(lens))]
        return [np.asarray(data_of(v)).reshape(-1)]

    n_inf = n_lab = n_correct = 0
    for inf, lab in zip(seqs(inf_v), seqs(lab_v)):
        ic = extract_chunks(inf, scheme, num_types, excluded)
        lc = extract_chunks(lab, scheme, num_types, excluded)
        n_inf += len(ic)
        n_lab += len(lc)
        n_correct += len(ic & lc)
    p = n_correct / n_inf if n_inf else 0.0
    r = n_correct / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    ctx.set_output("Precision", jnp.asarray([p], jnp.float32))
    ctx.set_output("Recall", jnp.asarray([r], jnp.float32))
    ctx.set_output("F1-Score", jnp.asarray([f1], jnp.float32))
    ctx.set_output("NumInferChunks", jnp.asarray([n_inf], jnp.int64))
    ctx.set_output("NumLabelChunks", jnp.asarray([n_lab], jnp.int64))
    ctx.set_output("NumCorrectChunks", jnp.asarray([n_correct], jnp.int64))