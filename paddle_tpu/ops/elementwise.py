"""Elementwise binary ops with the reference's axis-broadcast semantics.

Reference: /root/reference/paddle/fluid/operators/elementwise_op_function.h and
elementwise_{add,sub,mul,div,max,min,pow}_op.cc. Semantics: Y (smaller rank) is
broadcast into X starting at attr ``axis`` (axis == -1 means align trailing
dims). The CUDA kernels there are replaced by jnp broadcasting, which XLA fuses
into neighbors — elementwise ops should never be standalone HBM round-trips on
TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op, same_shape, OpSpec
from ..core.sparse import SparseRows, is_sparse
from .common import G, data_of, like, collapse_to


def _align(x, y, axis, x_is_lod=False, y_is_lod=False):
    """Reshape y so it broadcasts into x per the reference's axis rule.

    The reference axis indexes the LoDTensor's flat [total_rows, *feat]
    layout; our padded LoD layout [batch, max_len, *feat] has one extra
    leading dim, so a positive axis against a non-LoD y shifts by one."""
    if x.shape == y.shape:
        return y, 0
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    elif x_is_lod and not y_is_lod and axis >= 1:
        axis += 1
    new_shape = (1,) * axis + tuple(y.shape) + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape), axis


_FWD = {
    "elementwise_add": lambda x, y: x + y,
    "elementwise_sub": lambda x, y: x - y,
    "elementwise_mul": lambda x, y: x * y,
    "elementwise_div": lambda x, y: x / y,
    "elementwise_max": lambda x, y: jnp.maximum(x, y),
    "elementwise_min": lambda x, y: jnp.minimum(x, y),
    "elementwise_pow": lambda x, y: jnp.power(x, y),
}

# (dx_fn, dy_fn): each takes (x, y_broadcast, out, dout)
_GRADS = {
    "elementwise_add": (lambda x, yb, o, d: d,
                        lambda x, yb, o, d: d),
    "elementwise_sub": (lambda x, yb, o, d: d,
                        lambda x, yb, o, d: -d),
    "elementwise_mul": (lambda x, yb, o, d: d * yb,
                        lambda x, yb, o, d: d * x),
    "elementwise_div": (lambda x, yb, o, d: d / yb,
                        lambda x, yb, o, d: -d * x / (yb * yb)),
    "elementwise_max": (lambda x, yb, o, d: d * (x >= yb),
                        lambda x, yb, o, d: d * (x < yb)),
    "elementwise_min": (lambda x, yb, o, d: d * (x <= yb),
                        lambda x, yb, o, d: d * (x > yb)),
    "elementwise_pow": (lambda x, yb, o, d: d * yb * jnp.power(x, yb - 1),
                        lambda x, yb, o, d: d * o * jnp.log(jnp.where(x > 0, x, 1.0))),
}


def _make_grad_maker(op_type):
    def maker(op):
        return [OpSpec(
            op_type + "_grad",
            inputs={"X": op.input("X"), "Y": op.input("Y"),
                    "Out": op.output("Out"), "Out@GRAD": G(op.output("Out"))},
            outputs={"X@GRAD": G(op.input("X")), "Y@GRAD": G(op.input("Y"))},
            attrs=dict(op.attrs))]
    return maker


def _register(op_type):
    fwd = _FWD[op_type]
    dx_fn, dy_fn = _GRADS[op_type]

    @register_op(op_type, infer_shape=same_shape("X", "Out"),
                 grad=_make_grad_maker(op_type))
    def forward(ctx, _fwd=fwd, _t=op_type):
        xv, yv = ctx.input("X"), ctx.input("Y")
        if is_sparse(xv) and _t in ("elementwise_mul", "elementwise_div"):
            # sparse grad × scalar (gradient-clip scale factor): these ops
            # are linear per-element in X, so they apply to the value block
            # (reference selected_rows_functor scale path)
            y = data_of(yv)
            if getattr(y, "size", None) == 1:
                ctx.set_output("Out", SparseRows(
                    xv.rows, _fwd(xv.values, y.reshape(())), xv.nrows,
                    xv.merged))
                return
        x, y = data_of(xv), data_of(yv)
        yb, _ = _align(x, y, ctx.attr("axis", -1),
                       isinstance(xv, LoDArray), isinstance(yv, LoDArray))
        ctx.set_output("Out", like(xv, _fwd(x, yb)))

    @register_op(op_type + "_grad")
    def backward(ctx, _dx=dx_fn, _dy=dy_fn):
        xv, yv = ctx.input("X"), ctx.input("Y")
        x = data_of(xv)
        y = data_of(yv)
        out = data_of(ctx.input("Out"))
        dout = data_of(ctx.input("Out@GRAD"))
        yb, axis = _align(x, y, ctx.attr("axis", -1),
                          isinstance(xv, LoDArray), isinstance(yv, LoDArray))
        dx = _dx(x, yb, out, dout).astype(x.dtype)
        dy_full = _dy(x, yb, out, dout)
        dy = (collapse_to(dy_full, y.shape, axis)
              if y.shape != x.shape else dy_full).astype(y.dtype)
        ctx.set_output("X@GRAD", like(ctx.input("X"), dx))
        ctx.set_output("Y@GRAD", like(ctx.input("Y"), dy))


for _t in _FWD:
    _register(_t)
