"""Fused embedding rowwise-SGD Pallas kernel (the sparse-path hot update).

The lookup_table backward produces a ``SparseRows`` gradient and the sgd
op's sparse branch scatter-subtracts it into the [vocab, dim] table —
XLA lowers that to a gather/scatter pair over the whole table layout.
Here the update is ONE kernel walking the touched rows: the row index
rides scalar prefetch (it computes each grid step's block mapping), every
program reads its table row into VMEM, applies ``row -= lr * grad_row``
and writes it back through an input/output alias — O(touched rows) HBM
traffic with no dense-table intermediate, feeding the same SelectedRows
machinery the pserver wire path (PR 3) speaks.

Contract: rows must be MERGED (duplicate-free, core.sparse.merge_rows) —
the caller pre-merges like every reference sparse optimizer kernel does.
Sentinel rows (>= nrows) are clamped to row 0 with their update zeroed
and REORDERED TO THE FRONT of the grid: a sequential grid only
guarantees coherent read-modify-write for CONSECUTIVE same-block steps,
so the sentinels' no-op rewrites of row 0 must run before (and
contiguous with) any real row-0 update — at the tail they would race
the refetch and stomp it with the pre-update row. Numerics pinned
against the jnp scatter twin in tests/test_fused_embedding_sgd.py
(interpret on CPU).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

from . import on_cpu as _on_cpu


def _row_sgd_kernel(rows_ref, sc_ref, vals_ref, w_ref, w_out):
    del rows_ref  # consumed by the index maps (scalar prefetch)
    w_out[...] = w_ref[...] - sc_ref[0] * vals_ref[...]


def embedding_sgd_pallas(w, rows, vals, lr):
    """w[rows] -= lr * vals, one touched row per grid step.

    w [V, D]; rows [R] int32 MERGED (unique or sentinel); vals [R, D] in
    w's dtype. Returns the updated table (w is donated through an
    input/output alias when jit allows)."""
    from jax.experimental.pallas import tpu as pltpu

    v_rows = w.shape[0]
    r = rows.shape[0]
    d = w.shape[1]
    # sentinels first (argsort key -1), real rows ascending after — see
    # the module docstring for why tail sentinels would be a write race
    order = jnp.argsort(jnp.where(rows >= v_rows, -1, rows))
    rows_s = rows[order]
    sentinel = rows_s >= v_rows
    rows_c = jnp.where(sentinel, 0, rows_s).astype(jnp.int32)
    vals_c = jnp.where(sentinel[:, None], 0, vals[order]).astype(w.dtype)
    lr_arr = jnp.asarray(lr, jnp.float32).reshape(1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, rows_ref: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, d), lambda i, rows_ref: (i, 0)),
            pl.BlockSpec((1, d), lambda i, rows_ref: (rows_ref[i], 0)),
        ],
        out_specs=pl.BlockSpec((1, d), lambda i, rows_ref: (rows_ref[i], 0)),
    )
    return pl.pallas_call(
        _row_sgd_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(w.shape, w.dtype),
        input_output_aliases={3: 0},
        interpret=_on_cpu(),
    )(rows_c, lr_arr.reshape(1, 1), vals_c, w)


def embedding_sgd_jnp(w, rows, vals, lr):
    """The scatter twin: exactly the sgd op's sparse branch expression."""
    return w.at[rows].add(-lr * vals.astype(w.dtype), mode="drop")
