"""Whole-recurrence LSTM/GRU Pallas kernels (the hand-tuned RNN hot spots).

Migrated unchanged from the seed ``ops/pallas_kernels.py`` into the kernel
tier (the old module remains as a deprecation shim). The reference
hand-schedules fused CUDA kernels for exactly these spots
(/root/reference/paddle/cuda/src/hl_cuda_lstm.cu, hl_gpu_lstm.cuh); the
Pallas analogs go further than per-cell fusion: the LSTM/GRU run their
WHOLE sequence as one kernel — grid over time, recurrent weight
VMEM-resident across steps (lax.scan re-reads it from HBM every
iteration), h/c carries in VMEM scratch, bf16 MXU gate matmuls with f32
accumulation. Measured 1.22x vs the scan path on the v5e LSTM training
lane (round 5); GRU 0.98-1.08x across sessions (kept out of the tier's
AUTO_PALLAS set for that reason).

Numerics incl. all gradients are pinned against jnp twins
(tests/test_pallas_kernels.py, interpret mode on CPU, native on TPU).
Gradients use jax.custom_vjp: a reverse lax.scan of per-step vjps over the
saved carries, recomputing gates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

from . import on_cpu as _on_cpu


def _lstm_cell_jnp(gates, c_prev, h_prev, alive):
    hdim = gates.shape[-1] // 4
    i = jax.nn.sigmoid(gates[:, :hdim])
    f = jax.nn.sigmoid(gates[:, hdim:2 * hdim])
    cand = jnp.tanh(gates[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(gates[:, 3 * hdim:])
    c = f * c_prev + i * cand
    h = o * jnp.tanh(c)
    return (alive * h + (1 - alive) * h_prev,
            alive * c + (1 - alive) * c_prev)


# ---------------------------------------------------------------------------
# Whole-recurrence LSTM: one kernel for the ENTIRE sequence
# ---------------------------------------------------------------------------

def _lstm_seq_kernel(x_ref, alive_ref, w_ref, h0_ref, c0_ref,
                     hs_ref, cs_ref, h_s, c_s):
    """Grid over time. The recurrent weight w stays VMEM-resident across
    every grid step (XLA's lax.scan body re-reads it from HBM each
    iteration — for hid 512 that is ~4 MB x seq_len per layer) and the h/c
    carries live in VMEM scratch, so the whole recurrence is ONE kernel
    launch instead of seq_len (matmul + fusion) pairs. The per-step matmul
    runs on the MXU in bf16 with f32 accumulation (the lane's
    default_matmul_precision contract)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_s[...] = h0_ref[...]
        c_s[...] = c0_ref[...]

    h_prev = h_s[...]
    c_prev = c_s[...]
    gates = x_ref[0] + jax.lax.dot(
        h_prev.astype(w_ref.dtype), w_ref[...],
        preferred_element_type=jnp.float32).astype(h_prev.dtype)
    hdim = h_prev.shape[-1]
    alive = alive_ref[0]
    i = jax.nn.sigmoid(gates[:, :hdim])
    f = jax.nn.sigmoid(gates[:, hdim:2 * hdim])
    cand = jnp.tanh(gates[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(gates[:, 3 * hdim:])
    c = f * c_prev + i * cand
    h = o * jnp.tanh(c)
    h = alive * h + (1 - alive) * h_prev
    c = alive * c + (1 - alive) * c_prev
    h_s[...] = h
    c_s[...] = c
    hs_ref[0] = h
    cs_ref[0] = c


def _lstm_seq_fwd_pallas(x, alive, w, h0, c0):
    """x [L, b, 4H] (projected inputs + bias), alive [L, b, 1] float,
    w [H, 4H]; returns CARRY sequences hs/cs [L, b, H] (unmasked — the
    caller applies the output mask)."""
    from jax.experimental.pallas import tpu as pltpu

    L, b, H4 = x.shape
    H = H4 // 4
    wb = w.astype(jnp.bfloat16)   # MXU operand; bf16 halves its VMEM stay
    return pl.pallas_call(
        _lstm_seq_kernel,
        grid=(L,),
        in_specs=[
            pl.BlockSpec((1, b, H4), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, b, 1), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
            pl.BlockSpec((b, H), lambda t: (0, 0)),
            pl.BlockSpec((b, H), lambda t: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((1, b, H), lambda t: (t, 0, 0)),
                   pl.BlockSpec((1, b, H), lambda t: (t, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((L, b, H), x.dtype),
                   jax.ShapeDtypeStruct((L, b, H), x.dtype)],
        scratch_shapes=[pltpu.VMEM((b, H), x.dtype),
                        pltpu.VMEM((b, H), x.dtype)],
        interpret=_on_cpu(),
    )(x, alive, wb, h0, c0)


def _lstm_step_jnp(xt, h_prev, c_prev, w, alive):
    """One reference step on CARRIES (the jnp twin the backward
    differentiates): the bf16-MXU gate matmul + the shared cell math.
    Returns (h_carry, c_carry)."""
    gates = xt + jax.lax.dot(
        h_prev.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32).astype(h_prev.dtype)
    return _lstm_cell_jnp(gates, c_prev, h_prev, alive)


@jax.custom_vjp
def lstm_seq_pallas(x, alive, w, h0, c0):
    return _lstm_seq_fwd_pallas(x, alive, w, h0, c0)


def _lstm_seq_fwd(x, alive, w, h0, c0):
    hs, cs = _lstm_seq_fwd_pallas(x, alive, w, h0, c0)
    return (hs, cs), (x, alive, w, h0, c0, hs, cs)


def _lstm_seq_bwd(res, cts):
    """Reverse scan of per-step jax.vjp over the SAVED carries: gates are
    recomputed from x[t] + h[t-1] @ w (one extra matmul per step — the
    trade XLA's scan makes by saving gates instead; recompute keeps the
    saved-residual HBM footprint at 2 arrays)."""
    x, alive, w, h0, c0, hs, cs = res
    dhs, dcs = cts
    h_prevs = jnp.concatenate([h0[None], hs[:-1]], axis=0)
    c_prevs = jnp.concatenate([c0[None], cs[:-1]], axis=0)

    def bstep(carry, inp):
        dh_next, dc_next, dw = carry
        xt, at, hp, cp, dh_out, dc_out = inp
        _, vjp = jax.vjp(
            lambda xv, hv, cv, wv: _lstm_step_jnp(xv, hv, cv, wv, at),
            xt, hp, cp, w)
        dxt, dhp, dcp, dwt = vjp((dh_next + dh_out, dc_next + dc_out))
        return (dhp, dcp, dw + dwt), dxt

    zero = jnp.zeros_like(h0)
    (dh0, dc0, dw), dx = jax.lax.scan(
        bstep, (zero, jnp.zeros_like(c0), jnp.zeros_like(w)),
        (x, alive, h_prevs, c_prevs, dhs, dcs), reverse=True)
    return dx, None, dw, dh0, dc0


lstm_seq_pallas.defvjp(_lstm_seq_fwd, _lstm_seq_bwd)


# ---------------------------------------------------------------------------
# Whole-recurrence GRU (same pattern as lstm_seq_pallas)
# ---------------------------------------------------------------------------

def _gru_seq_kernel(x_ref, alive_ref, w_ref, h0_ref, hs_ref, h_s):
    """Grid over time; w [H, 3H] = [W_u | W_r | W_c] VMEM-resident, h carry
    in VMEM scratch. Gate math matches _gru_cell_jnp / the scan path
    (gru_unit_op.h: h = u*c + (1-u)*h_prev)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_s[...] = h0_ref[...]

    h_prev = h_s[...]
    xt = x_ref[0]
    alive = alive_ref[0]
    hdim = h_prev.shape[-1]
    w = w_ref[...]
    hb = h_prev.astype(w.dtype)
    ur = jax.lax.dot(hb, w[:, :2 * hdim],
                     preferred_element_type=jnp.float32).astype(h_prev.dtype)
    u = jax.nn.sigmoid(xt[:, :hdim] + ur[:, :hdim])
    r = jax.nn.sigmoid(xt[:, hdim:2 * hdim] + ur[:, hdim:])
    rc = jax.lax.dot((r * h_prev).astype(w.dtype), w[:, 2 * hdim:],
                     preferred_element_type=jnp.float32).astype(h_prev.dtype)
    c = jnp.tanh(xt[:, 2 * hdim:] + rc)
    h = u * c + (1.0 - u) * h_prev
    h = alive * h + (1 - alive) * h_prev
    h_s[...] = h
    hs_ref[0] = h


def _gru_seq_fwd_pallas(x, alive, w, h0):
    from jax.experimental.pallas import tpu as pltpu

    L, b, H3 = x.shape
    H = H3 // 3
    wb = w.astype(jnp.bfloat16)
    return pl.pallas_call(
        _gru_seq_kernel,
        grid=(L,),
        in_specs=[
            pl.BlockSpec((1, b, H3), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, b, 1), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H3), lambda t: (0, 0)),
            pl.BlockSpec((b, H), lambda t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, b, H), lambda t: (t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((L, b, H), x.dtype),
        scratch_shapes=[pltpu.VMEM((b, H), x.dtype)],
        interpret=_on_cpu(),
    )(x, alive, wb, h0)


def _gru_step_jnp(xt, h_prev, w, alive):
    """jnp twin of one kernel step on CARRIES (bf16 matmul recipe)."""
    hdim = h_prev.shape[-1]
    wb = w.astype(jnp.bfloat16)
    ur = jax.lax.dot(h_prev.astype(jnp.bfloat16), wb[:, :2 * hdim],
                     preferred_element_type=jnp.float32).astype(h_prev.dtype)
    u = jax.nn.sigmoid(xt[:, :hdim] + ur[:, :hdim])
    r = jax.nn.sigmoid(xt[:, hdim:2 * hdim] + ur[:, hdim:])
    rc = jax.lax.dot((r * h_prev).astype(jnp.bfloat16), wb[:, 2 * hdim:],
                     preferred_element_type=jnp.float32).astype(h_prev.dtype)
    c = jnp.tanh(xt[:, 2 * hdim:] + rc)
    h = u * c + (1.0 - u) * h_prev
    return alive * h + (1 - alive) * h_prev


@jax.custom_vjp
def gru_seq_pallas(x, alive, w, h0):
    return _gru_seq_fwd_pallas(x, alive, w, h0)


def _gru_seq_fwd(x, alive, w, h0):
    hs = _gru_seq_fwd_pallas(x, alive, w, h0)
    return hs, (x, alive, w, h0, hs)


def _gru_seq_bwd(res, dhs):
    x, alive, w, h0, hs = res
    h_prevs = jnp.concatenate([h0[None], hs[:-1]], axis=0)

    def bstep(carry, inp):
        dh_next, dw = carry
        xt, at, hp, dh_out = inp
        _, vjp = jax.vjp(
            lambda xv, hv, wv: _gru_step_jnp(xv, hv, wv, at), xt, hp, w)
        dxt, dhp, dwt = vjp(dh_next + dh_out)
        return (dhp, dw + dwt), dxt

    (dh0, dw), dx = jax.lax.scan(
        bstep, (jnp.zeros_like(h0), jnp.zeros_like(w)),
        (x, alive, h_prevs, dhs), reverse=True)
    return dx, None, dw, dh0


gru_seq_pallas.defvjp(_gru_seq_fwd, _gru_seq_bwd)
