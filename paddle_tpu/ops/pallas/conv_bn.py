"""Fused conv+bn(+relu) Pallas kernels for the ResNet block shapes.

The flagship profile (bench.py roofline notes) shows the step HBM-bound
through the conv→batch_norm→relu chains: XLA materializes the conv output
to HBM, re-reads it for the statistics reduce, and re-reads the normalized
activation for the elementwise tail. These kernels keep the activation
VMEM-resident through the whole epilogue instead:

* **forward (training)** — ONE kernel, grid ``(2, N)`` over a sequential
  TPU grid: pass 0 computes each image's conv block in VMEM and
  accumulates the batch Σy/Σy² in scratch (the conv output never touches
  HBM); at the pass boundary the batch mean/var and folded scale/shift
  land in scratch; pass 1 recomputes the conv and writes only the final
  normalized+activated y. The conv runs twice (trading MXU flops for HBM
  round trips — the right trade for the HBM-bound 1x1/small-C shapes, see
  ``supported()``), but the [N,H,W,C] intermediate never round-trips.
* **forward (inference)** — single pass: conv + precomputed scale/shift
  (+relu), the classic folded-BN serving epilogue.
* **backward (training)** — same two-pass shape: pass 0 recomputes the
  conv (and the relu mask from it) and accumulates dbias/dscale; pass 1
  forms the BN input-gradient dz in VMEM and emits dx (transposed conv as
  shifted taps against the rotated weights) and the dw tap dots, with dw
  accumulated across images in scratch. Neither dz nor the relu-masked dy
  ever materializes in HBM.

Convs are expressed as unrolled per-tap MXU dots over the padded input
block ("grouped by the conv_1x1_grad_as_dot analysis": a 1x1 conv IS a
channel matmul; a 3x3 conv is nine shifted ones), so only k∈{1,3},
stride 1 (stride-2 supported for 1x1 via pre-subsampling), NHWC, ungrouped,
undilated shapes are fused — everything else routes to the jnp twin via
the tier's fallback counter. Numerics are pinned against the unfused
conv2d+batch_norm(+relu) op chain in tests/test_fused_conv_bn.py
(interpret mode on CPU, native on TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

from . import on_cpu as _on_cpu


# conservative per-core VMEM budget for one program's working set (the
# hardware has ~16 MB; pallas double-buffers the streamed blocks)
_VMEM_BUDGET = 10 * 1024 * 1024


def _itemsize(dtype):
    return jnp.dtype(dtype).itemsize


def supported(x_shape, w_shape, strides, paddings, dilations, groups,
              data_format, x_dtype, backward=False, block_n=1):
    """Is this conv+bn shape fused-kernel eligible? (The op layer passes
    the verdict to ``use_pallas`` so ineligible shapes fall back to the
    jnp twin with a counter bump.) ``block_n > 1`` asks about the
    double-buffered forward variant — ``block_n`` images stream per grid
    step, so the VMEM working set scales and N must tile evenly."""
    if data_format != "NHWC" or groups != 1:
        return False
    if tuple(dilations) != (1, 1):
        return False
    if len(x_shape) != 4 or any(d is None for d in x_shape):
        return False
    kh, kw = int(w_shape[2]), int(w_shape[3])
    if (kh, kw) not in ((1, 1), (3, 3)):
        return False
    s = tuple(int(v) for v in strides)
    if s == (2, 2):
        # stride 2 is fused only as the subsampled 1x1 form
        if (kh, kw) != (1, 1) or tuple(paddings) != (0, 0):
            return False
    elif s != (1, 1):
        return False
    if jnp.dtype(x_dtype) not in (jnp.dtype(jnp.float32),
                                  jnp.dtype(jnp.bfloat16)):
        return False
    n, h, w, cin = (int(d) for d in x_shape)
    cout = int(w_shape[0])
    if s == (2, 2):
        h, w = -(-h // 2), -(-w // 2)
    ph, pw = (int(p) for p in paddings)
    hp, wp = h + 2 * ph, w + 2 * pw
    ho, wo = hp - kh + 1, wp - kw + 1
    if ho <= 0 or wo <= 0:
        return False
    bn = int(block_n)
    if bn < 1 or (bn > 1 and (backward or n % bn != 0)):
        return False
    it = _itemsize(x_dtype)
    x_b = hp * wp * cin * it * bn
    wt_b = kh * kw * cin * cout * it
    z_b = ho * wo * cout * 4 * bn
    if backward:
        dy_b = ho * wo * cout * it
        dzp_b = hp * wp * cout * it
        dw_b = kh * kw * cin * cout * 4
        need = 2 * x_b + 2 * dy_b + 2 * wt_b + dzp_b + dw_b + 2 * z_b
    else:
        need = 2 * x_b + wt_b + 2 * z_b
    return need <= _VMEM_BUDGET


def _prep(x, w, strides, paddings):
    """Shared input prep: subsample stride-2 1x1, spatially pad, and lay
    the OIHW filter out as per-tap [kh*kw, Cin, Cout] matmul operands."""
    kh, kw = w.shape[2], w.shape[3]
    if tuple(strides) == (2, 2):
        x = x[:, ::2, ::2, :]
    ph, pw = paddings
    if ph or pw:
        x = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    wt = w.transpose(2, 3, 1, 0).reshape(kh * kw, w.shape[1], w.shape[0])
    return x, wt.astype(x.dtype), kh, kw


def _conv_taps(x, wt_ref, kh, kw, ho, wo):
    """f32 conv accumulator for one image: Σ_taps shifted-slice matmuls.
    ``x`` is the padded [Hp, Wp, Cin] block; taps are unrolled python
    loops (static), each an MXU dot with f32 accumulation."""
    cin = x.shape[-1]
    acc = None
    for a in range(kh):
        for b in range(kw):
            xs = x[a:a + ho, b:b + wo, :].reshape(ho * wo, cin)
            part = jax.lax.dot(xs, wt_ref[a * kw + b],
                               preferred_element_type=jnp.float32)
            acc = part if acc is None else acc + part
    return acc


# ---------------------------------------------------------------------------
# forward, training mode: conv + batch stats + normalize + act, one kernel
# ---------------------------------------------------------------------------

def _conv_bn_train_kernel(x_ref, wt_ref, sb_ref, y_ref, sm_ref, sv_ref,
                          sum_s, sq_s, ab_s, *, kh, kw, ho, wo, count, eps,
                          act, out_dtype, block_n=1):
    t = pl.program_id(0)
    i = pl.program_id(1)
    n = pl.num_programs(1)

    @pl.when(jnp.logical_and(t == 0, i == 0))
    def _():
        sum_s[...] = jnp.zeros_like(sum_s)
        sq_s[...] = jnp.zeros_like(sq_s)

    # block_n > 1 is the double-buffered variant: each grid step streams
    # a block of images so pallas's block double-buffering overlaps the
    # next block's HBM→VMEM copy with this block's taps. The per-image
    # loop is unrolled in-image-order, so the Σy/Σy² adds land in the
    # SAME sequence as block_n=1 — bitwise-identical f32 statistics
    for j in range(block_n):
        # conv block in the COMPUTE dtype (bf16 under AMP): the jnp
        # twin's lax.conv emits the input dtype, and the BN statistics
        # accumulate in f32 FROM that — rounding here keeps the two
        # paths aligned
        z = _conv_taps(x_ref[j], wt_ref, kh, kw, ho, wo) \
            .astype(x_ref.dtype)
        zf = z.astype(jnp.float32)

        @pl.when(t == 0)
        def _(zf=zf):
            sum_s[0, :] += jnp.sum(zf, axis=0)
            sq_s[0, :] += jnp.sum(zf * zf, axis=0)

        @pl.when(t == 1)
        def _(zf=zf, j=j):
            y = zf * ab_s[0, :][None, :] + ab_s[1, :][None, :]
            if act == "relu":
                y = jnp.maximum(y, 0.0)
            y_ref[j] = y.reshape(ho, wo, -1).astype(out_dtype)

    @pl.when(jnp.logical_and(t == 0, i == n - 1))
    def _():
        m = sum_s[0, :] / count
        v = jnp.maximum(sq_s[0, :] / count - m * m, 0.0)
        inv = jax.lax.rsqrt(v + eps)
        a = sb_ref[0, :] * inv
        ab_s[0, :] = a
        ab_s[1, :] = sb_ref[1, :] - m * a
        sm_ref[0, :] = m
        sv_ref[0, :] = v


def conv_bn_train_pallas(x, w, scale, bias, eps, strides, paddings, act,
                         block_n=1):
    """Fused training-mode conv+bn(+act) forward.

    x [N,H,W,Cin] NHWC, w [Cout,Cin,kh,kw] OIHW (stride 1, or stride 2
    for 1x1), scale/bias [C]. Returns (y, batch_mean, batch_var) — the
    momentum blend into the running stats is [C]-cheap and stays in jnp
    at the op layer. ``block_n`` streams that many images per grid step
    (the autotuner's ``pallas_db`` variant; N must tile evenly)."""
    from jax.experimental.pallas import tpu as pltpu

    out_dtype = x.dtype
    x, wt, kh, kw = _prep(x, w, strides, paddings)
    n, hp, wp, cin = x.shape
    cout = w.shape[0]
    ho, wo = hp - kh + 1, wp - kw + 1
    count = float(n * ho * wo)
    bn = int(block_n)
    if n % bn != 0:
        raise ValueError(f"block_n={bn} does not tile batch {n}")
    sb = jnp.stack([scale.astype(jnp.float32).reshape(-1),
                    bias.astype(jnp.float32).reshape(-1)])

    kernel = functools.partial(
        _conv_bn_train_kernel, kh=kh, kw=kw, ho=ho, wo=wo, count=count,
        eps=float(eps), act=act, out_dtype=out_dtype, block_n=bn)
    y, sm, sv = pl.pallas_call(
        kernel,
        grid=(2, n // bn),
        in_specs=[
            pl.BlockSpec((bn, hp, wp, cin), lambda t, i: (i, 0, 0, 0)),
            pl.BlockSpec((kh * kw, cin, cout), lambda t, i: (0, 0, 0)),
            pl.BlockSpec((2, cout), lambda t, i: (0, 0)),
        ],
        out_specs=[
            # t*i: every pass-0 step parks on block 0 (same block ⇒ the
            # write-back defers), pass 1 walks the real blocks — so the
            # unwritten stats pass never flushes garbage rows to HBM
            pl.BlockSpec((bn, ho, wo, cout), lambda t, i: (t * i, 0, 0, 0)),
            pl.BlockSpec((1, cout), lambda t, i: (0, 0)),
            pl.BlockSpec((1, cout), lambda t, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, ho, wo, cout), out_dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, cout), jnp.float32),
                        pltpu.VMEM((1, cout), jnp.float32),
                        pltpu.VMEM((2, cout), jnp.float32)],
        interpret=_on_cpu(),
    )(x, wt, sb)
    return y, sm[0], sv[0]


# ---------------------------------------------------------------------------
# forward, inference mode: conv + folded scale/shift (+act), single pass
# ---------------------------------------------------------------------------

def _conv_affine_kernel(x_ref, wt_ref, ab_ref, y_ref, *, kh, kw, ho, wo,
                        act, out_dtype, block_n=1):
    for j in range(block_n):
        z = _conv_taps(x_ref[j], wt_ref, kh, kw, ho, wo).astype(x_ref.dtype)
        y = z.astype(jnp.float32) * ab_ref[0, :][None, :] \
            + ab_ref[1, :][None, :]
        if act == "relu":
            y = jnp.maximum(y, 0.0)
        y_ref[j] = y.reshape(ho, wo, -1).astype(out_dtype)


def conv_affine_pallas(x, w, a, b, strides, paddings, act, block_n=1):
    """Fused inference conv + y = conv*a + b (+act): the folded-BN serving
    epilogue (a = scale·rsqrt(var+eps), b = bias − mean·a, precomputed).
    ``block_n`` streams that many images per grid step (the autotuner's
    ``pallas_db`` variant; N must tile evenly)."""
    out_dtype = x.dtype
    x, wt, kh, kw = _prep(x, w, strides, paddings)
    n, hp, wp, cin = x.shape
    cout = w.shape[0]
    ho, wo = hp - kh + 1, wp - kw + 1
    bn = int(block_n)
    if n % bn != 0:
        raise ValueError(f"block_n={bn} does not tile batch {n}")
    ab = jnp.stack([a.astype(jnp.float32).reshape(-1),
                    b.astype(jnp.float32).reshape(-1)])
    kernel = functools.partial(_conv_affine_kernel, kh=kh, kw=kw, ho=ho,
                               wo=wo, act=act, out_dtype=out_dtype,
                               block_n=bn)
    return pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, hp, wp, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((kh * kw, cin, cout), lambda i: (0, 0, 0)),
            pl.BlockSpec((2, cout), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, ho, wo, cout), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, ho, wo, cout), out_dtype),
        interpret=_on_cpu(),
    )(x, wt, ab)


# ---------------------------------------------------------------------------
# backward, training mode: relu-mask + BN grad + both conv grads, one kernel
# ---------------------------------------------------------------------------

def _conv_bn_bwd_kernel(x_ref, wt_ref, wtr_ref, dy_ref, aux_ref,
                        dx_ref, dw_ref, db_ref, ds_ref,
                        db_s, ds_s, dw_s, dzp_s, *, kh, kw, ho, wo, h, wd,
                        ph, pw, count, act):
    t = pl.program_id(0)
    i = pl.program_id(1)
    n = pl.num_programs(1)
    cin = x_ref.shape[-1]
    cout = dy_ref.shape[-1]
    x = x_ref[0]
    # recompute the conv block (the fused forward never materialized it)
    z = _conv_taps(x, wt_ref, kh, kw, ho, wo).astype(x_ref.dtype)
    zf = z.astype(jnp.float32)
    a_row = aux_ref[0, :][None, :]
    b_row = aux_ref[1, :][None, :]
    mean = aux_ref[2, :][None, :]
    inv = aux_ref[3, :][None, :]
    scale = aux_ref[4, :][None, :]
    dyf = dy_ref[0].reshape(ho * wo, cout).astype(jnp.float32)
    if act == "relu":
        pre = zf * a_row + b_row
        dyf = dyf * (pre > 0)
    xhat = (zf - mean) * inv

    @pl.when(jnp.logical_and(t == 0, i == 0))
    def _():
        db_s[...] = jnp.zeros_like(db_s)
        ds_s[...] = jnp.zeros_like(ds_s)

    @pl.when(t == 0)
    def _():
        db_s[0, :] += jnp.sum(dyf, axis=0)
        ds_s[0, :] += jnp.sum(dyf * xhat, axis=0)

    @pl.when(jnp.logical_and(t == 0, i == n - 1))
    def _():
        db_ref[0, :] = db_s[0, :]
        ds_ref[0, :] = ds_s[0, :]

    @pl.when(jnp.logical_and(t == 1, i == 0))
    def _():
        dw_s[...] = jnp.zeros_like(dw_s)
        dzp_s[...] = jnp.zeros_like(dzp_s)

    @pl.when(t == 1)
    def _():
        db = db_s[0, :][None, :]
        ds = ds_s[0, :][None, :]
        # batch_norm_grad closed form (norm_ops bn_backward_math): dz in
        # f32, then cast to the conv compute dtype exactly like the twin's
        # vjp cotangent cast
        dz = (scale * inv / count) * (count * dyf - db - xhat * ds)
        dzc = dz.astype(x_ref.dtype)
        # filter grad taps: dw[a,b] += x_slice^T · dz (f32 accumulation)
        for a in range(kh):
            for b in range(kw):
                xs = x[a:a + ho, b:b + wo, :].reshape(ho * wo, cin)
                dw_s[a * kw + b] += jax.lax.dot_general(
                    xs, dzc, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
        # input grad: full correlation of dz against the rotated weights.
        # dzp is dz embedded in a zero border of kh-1/kw-1 (the border was
        # zeroed once at (1,0) and interior rows are overwritten per image)
        dzp_s[kh - 1:kh - 1 + ho, kw - 1:kw - 1 + wo, :] = \
            dzc.reshape(ho, wo, cout)
        hp = ho + kh - 1
        wp = wo + kw - 1
        dxp = None
        for a in range(kh):
            for b in range(kw):
                dzs = dzp_s[a:a + hp, b:b + wp, :].reshape(hp * wp, cout)
                part = jax.lax.dot(dzs, wtr_ref[a * kw + b],
                                   preferred_element_type=jnp.float32)
                dxp = part if dxp is None else dxp + part
        dxp = dxp.reshape(hp, wp, cin)
        dx_ref[0] = dxp[ph:ph + h, pw:pw + wd, :].astype(dx_ref.dtype)

    @pl.when(jnp.logical_and(t == 1, i == n - 1))
    def _():
        dw_ref[...] = dw_s[...]


def conv_bn_bwd_pallas(x, w, dy, scale, bias, mean, var, eps, strides,
                       paddings, act):
    """Fused training-mode backward: (dx, dw OIHW, dscale, dbias) from the
    upstream dy of the fused forward. Stride-2 1x1 is handled by running
    the stride-1 kernel on the subsampled input and scattering dx back
    into the even positions (the subsample trick's exact transpose)."""
    from jax.experimental.pallas import tpu as pltpu

    stride2 = tuple(strides) == (2, 2)
    x_orig_shape = x.shape
    x_dtype = x.dtype
    xp, wt, kh, kw = _prep(x, w, strides, paddings)
    wtr_src = wt.reshape(kh, kw, w.shape[1], w.shape[0])
    # rotate 180° and transpose per tap: dx tap j reads w[kh-1-a, kw-1-b]^T
    wtr = jnp.flip(wtr_src, axis=(0, 1)).transpose(0, 1, 3, 2) \
        .reshape(kh * kw, w.shape[0], w.shape[1])
    n, hp, wp, cin = xp.shape
    cout = w.shape[0]
    ho, wo = hp - kh + 1, wp - kw + 1
    ph, pw = (int(p) for p in paddings)
    h, wd = hp - 2 * ph, wp - 2 * pw
    count = float(n * ho * wo)
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + float(eps))
    a_fold = scale.astype(jnp.float32) * inv
    aux = jnp.stack([
        a_fold.reshape(-1),
        bias.astype(jnp.float32).reshape(-1)
        - mean.astype(jnp.float32).reshape(-1) * a_fold.reshape(-1),
        mean.astype(jnp.float32).reshape(-1),
        inv.reshape(-1),
        scale.astype(jnp.float32).reshape(-1),
    ])

    kernel = functools.partial(_conv_bn_bwd_kernel, kh=kh, kw=kw, ho=ho,
                               wo=wo, h=h, wd=wd, ph=ph, pw=pw, count=count,
                               act=act)
    dx, dw, db, ds = pl.pallas_call(
        kernel,
        grid=(2, n),
        in_specs=[
            pl.BlockSpec((1, hp, wp, cin), lambda t, i: (i, 0, 0, 0)),
            pl.BlockSpec((kh * kw, cin, cout), lambda t, i: (0, 0, 0)),
            pl.BlockSpec((kh * kw, cout, cin), lambda t, i: (0, 0, 0)),
            pl.BlockSpec((1, ho, wo, cout), lambda t, i: (i, 0, 0, 0)),
            pl.BlockSpec((5, cout), lambda t, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, wd, cin), lambda t, i: (t * i, 0, 0, 0)),
            pl.BlockSpec((kh * kw, cin, cout), lambda t, i: (0, 0, 0)),
            pl.BlockSpec((1, cout), lambda t, i: (0, 0)),
            pl.BlockSpec((1, cout), lambda t, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wd, cin), x_dtype),
            jax.ShapeDtypeStruct((kh * kw, cin, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((kh * kw, cin, cout), jnp.float32),
            # dz embedded in a kh-1/kw-1 zero border ON EACH SIDE (the
            # full-correlation operand for the dx taps)
            pltpu.VMEM((ho + 2 * (kh - 1), wo + 2 * (kw - 1), cout),
                       x_dtype),
        ],
        interpret=_on_cpu(),
    )(xp, wt, wtr, dy, aux)
    dw_oihw = dw.reshape(kh, kw, cin, cout).transpose(3, 2, 0, 1)
    if stride2:
        dx_full = jnp.zeros(x_orig_shape, dx.dtype)
        dx = dx_full.at[:, ::2, ::2, :].set(dx)
    return dx, dw_oihw, ds[0], db[0]
