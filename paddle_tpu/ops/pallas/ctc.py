"""CTC alpha-recurrence Pallas kernel (the warp-ctc replacement's hot loop).

Migrated unchanged from the seed ``ops/pallas_kernels.py`` into the kernel
tier. One program per batch row keeps the whole alpha vector VMEM-resident
across all T steps — the reference's warp-ctc keeps it in shared memory per
block (ctc_helper kernels). Dispatched by ``ops/ctc_ops.py`` under the
tier; numerics pinned against the lax.scan path incl. gradients.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

from . import on_cpu as _on_cpu


_NEG = -1e30


def _ctc_alpha_kernel(e_ref, alpha0_ref, final0_ref, can_skip_ref,
                      s_valid_ref, xlen_ref, ylen_ref, loss_ref):
    """Whole-sequence CTC forward for ONE batch element: alpha stays
    VMEM-resident across all T steps (the reference's warp-ctc keeps it in
    shared memory per block, ctc_helper kernels). e [T, Sp] are the emit
    log-probs at the blank-interleaved labels; masks are f32 0/1."""
    e = e_ref[0]                          # [T, Sp]
    can_skip = can_skip_ref[0]            # [Sp]
    s_valid = s_valid_ref[0]
    xlen = xlen_ref[0, 0]
    ylen = ylen_ref[0, 0]
    T = e.shape[0]
    sp = e.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (sp,), 0)

    last = 2 * ylen                       # index of the final blank
    onehot_last = (iota == last).astype(e.dtype)
    onehot_lab = (iota == jnp.maximum(last - 1, 0)).astype(e.dtype)

    def final_of(alpha):
        a_last = jnp.sum(jnp.where(onehot_last > 0, alpha, 0.0))
        a_lab = jnp.sum(jnp.where(onehot_lab > 0, alpha, 0.0))
        a_lab = jnp.where(ylen > 0, a_lab, _NEG)
        return jnp.logaddexp(a_last, a_lab)

    def body(t, carry):
        alpha, final = carry
        a1 = jnp.where(iota >= 1, jnp.roll(alpha, 1), _NEG)
        a2 = jnp.where((iota >= 2) & (can_skip > 0),
                       jnp.roll(alpha, 2), _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
        lp = jax.lax.dynamic_slice_in_dim(e, t, 1, axis=0)[0]
        nxt = jnp.where(s_valid > 0, merged + lp, _NEG)
        alpha = jnp.where(t < xlen, nxt, alpha)
        final = jnp.where(t == xlen - 1, final_of(alpha), final)
        return alpha, final

    alpha0 = alpha0_ref[0]
    _, final = jax.lax.fori_loop(1, T, body,
                                 (alpha0, final0_ref[0, 0]))
    loss_ref[0, 0] = -final


def ctc_alpha_pallas(e, alpha0, final0, can_skip, s_valid, x_lens, y_lens):
    """[b, T, Sp] emit matrix -> [b, 1] loss; one program per batch row."""
    b, T, sp = e.shape
    f32 = e.dtype
    return pl.pallas_call(
        _ctc_alpha_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, T, sp), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, sp), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, sp), lambda i: (i, 0)),
            pl.BlockSpec((1, sp), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 1), f32),
        interpret=_on_cpu(),
    )(e, alpha0, final0, can_skip, s_valid, x_lens, y_lens)
