"""Fused optimizer megakernels: one Pallas launch updates ALL dense params.

The per-param optimizer ops (ops/optimizer_ops.py) trace into the step
computation, but XLA still emits one small fused kernel per parameter —
the ResNet-50 step dispatches ~160 of them (the profile's
multiply_subtract_fusion tail). Here the optimizer state lives in flat
f32 arenas (params / grads / accumulators concatenated and padded to a
lane-aligned tile grid) and ONE kernel walks the arena tiles applying the
update — SGD, momentum and Adam, each elementwise over its tile, scalars
(learning rate, bias-correction) prefetched into SMEM.

The jnp twins are the exact per-param update expressions shared with the
per-param ops (optimizer_ops._sgd_dense & co.), so ``kernel_tier=jnp``
reproduces the per-param program bitwise; the Pallas arena path is pinned
against the twins in tests/test_fused_optimizer.py (interpret on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

from . import on_cpu as _on_cpu


# arena tile: one grid step processes TILE elements as an [8, 128] f32
# block (the f32 register tile), so any param mix packs without padding
# waste beyond the final tile
_TILE = 8 * 128


def flatten_arena(arrays):
    """Concat raveled f32 arrays into a [n_tiles, 1024]-shaped arena (zero
    padded tail). Returns (arena2d, total_elems)."""
    flat = jnp.concatenate([a.ravel() for a in arrays])
    total = flat.shape[0]
    pad = (-total) % _TILE
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(-1, 128), total


def split_arena(arena2d, shapes, dtype=None):
    """Invert :func:`flatten_arena`: slice each param back out."""
    flat = arena2d.reshape(-1)
    out, off = [], 0
    for s in shapes:
        n = 1
        for d in s:
            n *= int(d)
        a = flat[off:off + n].reshape(s)
        out.append(a.astype(dtype) if dtype is not None else a)
        off += n
    return out


def _rows(arena2d):
    return arena2d.shape[0]


def _arena_call(kernel, outs, scalars, *arenas):
    """Shared pallas_call wiring: grid over row-tiles of the arena(s),
    scalars ride a (1, k) SMEM block."""
    from jax.experimental.pallas import tpu as pltpu

    rows = _rows(arenas[0])
    tile_rows = _TILE // 128
    grid = (rows // tile_rows,)
    sc = jnp.stack([jnp.asarray(s, jnp.float32).reshape(())
                    for s in scalars]).reshape(1, -1)
    block = pl.BlockSpec((tile_rows, 128), lambda i: (i, 0))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((1, sc.shape[1]), lambda i: (0, 0),
                               memory_space=pltpu.SMEM)]
        + [block] * len(arenas),
        out_specs=[block] * outs,
        out_shape=[jax.ShapeDtypeStruct(arenas[0].shape, jnp.float32)] * outs,
        interpret=_on_cpu(),
    )(sc, *arenas)


def _sgd_kernel(sc_ref, p_ref, g_ref, p_out):
    p_out[...] = p_ref[...] - sc_ref[0, 0] * g_ref[...]


def sgd_arena_pallas(p, g, lr):
    """p_new = p - lr*g over [rows, 128] f32 arenas."""
    (out,) = _arena_call(_sgd_kernel, 1, [lr], p, g)
    return out


def _momentum_kernel(sc_ref, p_ref, g_ref, v_ref, p_out, v_out, *,
                     nesterov):
    lr = sc_ref[0, 0]
    mu = sc_ref[0, 1]
    g = g_ref[...]
    v_new = mu * v_ref[...] + g
    if nesterov:
        p_out[...] = p_ref[...] - (g + mu * v_new) * lr
    else:
        p_out[...] = p_ref[...] - lr * v_new
    v_out[...] = v_new


def momentum_arena_pallas(p, g, v, lr, mu, nesterov=False):
    """(p_new, v_new): the momentum op's dense update over arenas."""
    kernel = functools.partial(_momentum_kernel, nesterov=bool(nesterov))
    p_out, v_out = _arena_call(kernel, 2, [lr, mu], p, g, v)
    return p_out, v_out


def _adam_kernel(sc_ref, p_ref, g_ref, m1_ref, m2_ref,
                 p_out, m1_out, m2_out, *, b1, b2, eps):
    lr = sc_ref[0, 0]   # already bias-corrected (the adam op's lr_eff)
    g = g_ref[...]
    m1n = b1 * m1_ref[...] + (1 - b1) * g
    m2n = b2 * m2_ref[...] + (1 - b2) * g * g
    p_out[...] = p_ref[...] - lr * m1n / (jnp.sqrt(m2n) + eps)
    m1_out[...] = m1n
    m2_out[...] = m2n


def adam_arena_pallas(p, g, m1, m2, lr_eff, b1, b2, eps):
    """(p_new, m1_new, m2_new); lr_eff carries the sqrt(1-b2^t)/(1-b1^t)
    bias correction (a traced scalar — it rides the SMEM block)."""
    kernel = functools.partial(_adam_kernel, b1=float(b1), b2=float(b2),
                               eps=float(eps))
    return _arena_call(kernel, 3, [lr_eff], p, g, m1, m2)
