"""The Pallas kernel tier: a small library of fused TPU primitives.

Design template: *Tensor Processing Primitives* (PAPERS.md) — the op layer
targets a SMALL set of fused kernels (conv+bn+relu epilogues, one-kernel
optimizer steps, rowwise embedding updates, whole-recurrence RNN/CTC)
instead of growing one-off kernels per call site. Every kernel here has a
jnp twin with pinned numerics (tests run the kernels in interpret mode on
CPU), and every dispatch site routes through :func:`use_pallas` so tier
selection, per-kernel fallback, and profiler attribution live in ONE place.

Tier selection (the ``kernel_tier`` flag):

* ``auto`` (default) — Pallas on TPU for the kernels measured to win
  (:data:`AUTO_PALLAS`), jnp everywhere else (CPU suites never pay
  interpret-mode kernels unless they opt in).
* ``pallas`` — Pallas for every kernel with a lowering (interpret mode on
  CPU: this is what the parity tests run).
* ``jnp`` — the plain jax.numpy lowerings, bitwise-identical to the
  pre-tier behavior.

The legacy ``use_pallas_rnn`` / ``use_pallas_ctc`` flags are deprecated but
still honored: set to True they force the Pallas path for their kernels
(with a one-time DeprecationWarning) regardless of ``kernel_tier``.

Fallback contract: when the tier resolves to Pallas but a dispatch site
reports the shape/config unsupported (``supported=False``), the call
SILENTLY routes to the jnp twin and bumps a per-kernel counter
(:func:`fallback_counts`) — an unsupported shape is a routing decision,
never an error. Profiler spans (``pallas/<kernel>`` vs ``jnp/<kernel>``,
kind="kernel") land in chrome traces so the two paths are distinguishable
per op.
"""

from __future__ import annotations

import warnings
from contextlib import contextmanager

from ...core.flags import get_flag
from ...core.profiler import record_event
from ...obs.metrics import REGISTRY as _METRICS

# kernels that default to Pallas under kernel_tier=auto on TPU — the
# measured-to-win set (lstm 1.22x on v5e; gru measured 0.98-1.08x across
# sessions so it stays opt-in via kernel_tier=pallas)
AUTO_PALLAS = frozenset({
    "lstm", "ctc", "conv_bn", "optimizer", "embedding_sgd",
})

# kernel family -> the deprecated flag that used to gate it
_LEGACY_FLAGS = {
    "lstm": "use_pallas_rnn",
    "gru": "use_pallas_rnn",
    "ctc": "use_pallas_ctc",
}

_warned_legacy: set = set()

# pallas->jnp silent-fallback counter, in the obs.metrics registry
# (fallback_counts() derives its historical dict from this family)
_M_FALLBACKS = _METRICS.counter(
    "paddle_tpu_pallas_fallbacks",
    "unsupported shapes routed pallas->jnp silently, per kernel family",
    labels=("kernel",))


def _legacy_forced(kernel):
    """True when the kernel's deprecated flag is set (warn once per flag)."""
    name = _LEGACY_FLAGS.get(kernel)
    if name is None or not get_flag(name):
        return False
    if name not in _warned_legacy:
        _warned_legacy.add(name)
        warnings.warn(
            f"flag {name!r} is deprecated: use kernel_tier='pallas' (or "
            "'auto', which picks Pallas on TPU) instead; the old flag is "
            "still honored and forces the Pallas path for its kernels",
            DeprecationWarning, stacklevel=3)
    return True


def on_cpu():
    """Shared interpret-mode predicate: every kernel module passes
    ``interpret=on_cpu()`` to pallas_call so CPU (tests, smoke benches)
    runs the same kernel bodies through the interpreter."""
    import jax
    return jax.default_backend() == "cpu"


def resolve_tier():
    """The tier the ``kernel_tier`` flag resolves to: 'pallas' or 'jnp'
    ('auto' = pallas on TPU, jnp elsewhere — per-kernel AUTO_PALLAS
    membership is applied in :func:`use_pallas`, not here)."""
    t = get_flag("kernel_tier")
    if t == "auto":
        import jax
        return "pallas" if jax.default_backend() == "tpu" else "jnp"
    if t not in ("pallas", "jnp"):
        raise ValueError(
            f"kernel_tier must be auto|pallas|jnp, got {t!r}")
    return t


def use_pallas(kernel, supported=True):
    """Should this dispatch take the Pallas path?

    ``kernel`` names the kernel family ("conv_bn", "optimizer",
    "embedding_sgd", "lstm", "gru", "ctc"); ``supported`` is the call
    site's shape/config predicate. Unsupported shapes under a Pallas tier
    fall back to the jnp twin with a counter bump (never an error).
    """
    t = get_flag("kernel_tier")
    if t not in ("auto", "pallas", "jnp"):
        raise ValueError(
            f"kernel_tier must be auto|pallas|jnp, got {t!r}")
    want = _legacy_forced(kernel)
    if not want:
        if t == "pallas":
            want = True
        elif t == "auto" and kernel in AUTO_PALLAS:
            import jax
            want = jax.default_backend() == "tpu"
    if want and not supported:
        record_fallback(kernel)
        return False
    return want


def record_fallback(kernel):
    _M_FALLBACKS.labels(kernel=kernel).inc()
    # flight recorder: a silent tier downgrade is exactly the kind of
    # decision an incident bundle must surface (a fleet quietly running
    # jnp twins explains a perf regression)
    from ...obs.recorder import record as _flight_record
    _flight_record("pallas_fallback", component="ops.pallas",
                   kernel=kernel)


def fallback_counts():
    """{kernel: times an unsupported shape routed pallas->jnp} — derived
    from the ``paddle_tpu_pallas_fallbacks`` registry counter; kernels
    with zero fallbacks are omitted (the historical dict shape)."""
    out = {}
    for key, child in _M_FALLBACKS.children().items():
        n = int(child.value)
        if n:
            out[key[0]] = n
    return out


def reset_fallback_counts():
    """TEST hygiene: zero the fallback counters (scrape consumers treat
    counters as monotonic — do not call outside tests)."""
    _M_FALLBACKS.reset()


@contextmanager
def kernel_span(tier, kernel):
    """Profiler span around one kernel dispatch: chrome traces show
    ``pallas/<kernel>`` vs ``jnp/<kernel>`` (kind="kernel") so tier time is
    attributable per op. Host spans: real time in eager mode, trace-time
    under jit (the repo's standard record_event semantics)."""
    with record_event(f"{tier}/{kernel}", kind="kernel"):
        yield


# kernel modules (conv_bn, optimizer, embedding, rnn, ctc) are imported
# lazily by their dispatch sites: the tier layer itself must stay cheap to
# import (it is pulled in at ops-package import time)

__all__ = [
    "AUTO_PALLAS", "resolve_tier", "use_pallas", "record_fallback",
    "fallback_counts", "reset_fallback_counts", "kernel_span",
]
