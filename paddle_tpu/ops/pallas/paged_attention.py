"""Ragged paged-attention Pallas kernel (the decode step's hot gather).

The jnp lowering of the ``paged_attention`` op materializes every row's
gathered ``[max_seqs, P*block_size, H, D]`` context before one big
softmax — HBM traffic proportional to the POSSIBLE context, not the
actual ragged lengths. This kernel is the *Ragged Paged Attention*
shape: grid ``(max_seqs, P)``, the block table and per-sequence context
lengths ride SCALAR PREFETCH so each grid step's index map points the
K/V BlockSpec straight at the arena block the table names — the kernel
streams one block at a time through VMEM and accumulates an online
(flash-style) softmax in scratch, so no gathered context ever
materializes. Table entries past a sequence's length are skipped
(``pl.when``), making per-step work proportional to the sequence's REAL
block count.

Numerics: online softmax re-associates the reduction, so kernel-vs-twin
parity is the OpTest tolerance contract (like conv_bn), not bitwise —
bitwise guarantees (continuous-vs-sequential, cached-vs-cold) hold
WITHIN a tier because both sides of those pins run the same lowering.
Inactive rows (ctx_len == 0) never enter the accumulation and emit
zeros, matching the twin's explicit mask.

The twin (:func:`paged_attention_jnp`) is verbatim the pre-tier op body,
so ``kernel_tier=jnp`` stays bitwise the pre-tier behavior.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

from . import on_cpu as _on_cpu

# conservative VMEM budget for one grid step's resident blocks: K + V
# arena block, Q row, accumulator — well under the ~16 MiB/core v5e VMEM
_VMEM_BUDGET_BYTES = 4 * 1024 * 1024


def paged_attention_supported(qh, kc, bt):
    """Shape/dtype predicate for the kernel: f32 everywhere (the arena
    dtype the engine allocates) and one block's K+V resident in VMEM."""
    if qh.dtype != jnp.float32 or kc.dtype != jnp.float32:
        return False
    nb, bs, h, d = kc.shape
    per_step = 4 * (2 * bs * h * d + 2 * h * d + h * d)
    return bt.shape[1] >= 1 and per_step <= _VMEM_BUDGET_BYTES


def _paged_attn_kernel(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
                       m_ref, l_ref, acc_ref, *, block_size, n_tables):
    s = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref[...], -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref[...])
        acc_ref[...] = jnp.zeros_like(acc_ref[...])

    ctx = cl_ref[s]
    base = p * block_size

    @pl.when(base < ctx)
    def _attend():
        q = q_ref[0]                                  # [H, D]
        k = k_ref[0]                                  # [bs, H, D]
        v = v_ref[0]
        scale = q.shape[-1] ** -0.5
        scores = jnp.einsum("hd,bhd->hb", q, k,
                            preferred_element_type=jnp.float32) * scale
        pos = base + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)               # [H, bs]
        scores = jnp.where(pos < ctx, scores, -jnp.inf)
        m_prev = m_ref[...]                           # [H, 1]
        m_new = jnp.maximum(m_prev,
                            jnp.max(scores, axis=1, keepdims=True))
        # base < ctx guarantees >= 1 unmasked slot, so m_new is finite:
        # exp(-inf - m_new) == 0.0 for masked slots, and the first
        # contributing block's correction exp(-inf - m_new) zeroes the
        # (all-zero) initial accumulator exactly
        w = jnp.exp(scores - m_new)                   # [H, bs]
        corr = jnp.exp(m_prev - m_new)                # [H, 1]
        m_ref[...] = m_new
        l_ref[...] = corr * l_ref[...] + jnp.sum(w, axis=1, keepdims=True)
        acc_ref[...] = corr * acc_ref[...] + jnp.einsum(
            "hb,bhd->hd", w, v, preferred_element_type=jnp.float32)

    @pl.when(p == n_tables - 1)
    def _finalize():
        l = l_ref[...]
        # ctx == 0 rows never attended: l == 0, acc == 0 -> emit zeros
        o_ref[0] = (acc_ref[...]
                    / jnp.where(l > 0.0, l, 1.0)).astype(o_ref.dtype)


def paged_attention_pallas(qh, kc, vc, bt, ctx_lens):
    """One decode step's attention for every slot: qh [S, H, D] against
    the arena kc/vc [nb, bs, H, D] through block tables bt [S, P] and
    per-sequence ctx_lens [S]. Returns [S, H, D] (zeros for inactive
    rows). Interpret mode on CPU, like every kernel in the tier."""
    from jax.experimental.pallas import tpu as pltpu

    s, h, d = qh.shape
    nb, bs = kc.shape[0], kc.shape[1]
    p = bt.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, p),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i, j, bt, cl: (i, 0, 0)),
            pl.BlockSpec((1, bs, h, d),
                         lambda i, j, bt, cl: (bt[i, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, h, d),
                         lambda i, j, bt, cl: (bt[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i, j, bt, cl: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),      # running max
            pltpu.VMEM((h, 1), jnp.float32),      # running normalizer
            pltpu.VMEM((h, d), jnp.float32),      # running weighted values
        ],
    )
    kernel = functools.partial(_paged_attn_kernel, block_size=bs,
                               n_tables=p)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, h, d), qh.dtype),
        interpret=_on_cpu(),
    )(bt.astype(jnp.int32), ctx_lens.astype(jnp.int32), qh, kc, vc)


def paged_attention_jnp(qh, kc, vc, bt, ctx_lens):
    """The gather-then-attend twin: verbatim the pre-tier op body
    (materializes the [S, P*bs, H, D] context, one masked softmax)."""
    nb, bs = kc.shape[0], kc.shape[1]
    b = bt.shape[0]
    idx = (bt[:, :, None] * bs
           + jnp.arange(bs, dtype=jnp.int32)[None, None, :]).reshape(b, -1)
    kf = kc.reshape((nb * bs,) + kc.shape[2:])
    vf = vc.reshape((nb * bs,) + vc.shape[2:])
    kctx = kf[idx]                                             # [b, C, H, D]
    vctx = vf[idx]
    d = qh.shape[-1]
    scores = jnp.einsum("bhd,bchd->bhc", qh, kctx) * (d ** -0.5)
    live = jnp.arange(idx.shape[1], dtype=jnp.int32)[None, :] \
        < ctx_lens[:, None]                                    # [b, C]
    scores = jnp.where(live[:, None, :], scores, -1e9)
    # a fully-masked (inactive) row softmaxes to uniform weights over
    # garbage — finite, never NaN — and is zeroed by the active mask below
    pw = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhc,bchd->bhd", pw, vctx)
    active = (ctx_lens > 0)[:, None, None]
    return jnp.where(active, out, 0.0)
