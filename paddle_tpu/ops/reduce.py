"""Reduction ops: mean, reduce_{sum,mean,max,min,prod}.

Reference: mean_op.cc, reduce_op.cc (/root/reference/paddle/fluid/operators/).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op, OpSpec
from .common import G, data_of


@register_op("mean", grad=lambda op: [OpSpec(
    "mean_grad", {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))})])
def mean(ctx):
    x = data_of(ctx.input("X"))
    ctx.set_output("Out", jnp.mean(x).reshape(()).astype(x.dtype))


@register_op("mean_grad")
def mean_grad(ctx):
    x = data_of(ctx.input("X"))
    d = data_of(ctx.input("Out@GRAD")).reshape(())
    ctx.set_output("X@GRAD", jnp.full(x.shape, d / x.size).astype(x.dtype))


def _axes(ctx, x):
    dim = ctx.attr("dim", 0)
    if ctx.attr("reduce_all", False):
        return tuple(range(x.ndim))
    if isinstance(dim, (list, tuple)):
        return tuple(d % x.ndim for d in dim)
    return (dim % x.ndim,)


def _reg_reduce(name, fn, grad_fwd):
    def maker(op):
        return [OpSpec(name + "_grad",
                       {"X": op.input("X"), "Out": op.output("Out"),
                        "Out@GRAD": G(op.output("Out"))},
                       {"X@GRAD": G(op.input("X"))}, dict(op.attrs))]

    @register_op(name, grad=maker)
    def forward(ctx, _fn=fn):
        x = data_of(ctx.input("X"))
        out = _fn(x, axis=_axes(ctx, x), keepdims=ctx.attr("keep_dim", False))
        ctx.set_output("Out", out)

    @register_op(name + "_grad")
    def backward(ctx, _g=grad_fwd):
        x = data_of(ctx.input("X"))
        out = data_of(ctx.input("Out"))
        d = data_of(ctx.input("Out@GRAD"))
        axes = _axes(ctx, x)
        if not ctx.attr("keep_dim", False):
            shape = list(x.shape)
            for a in axes:
                shape[a] = 1
            d = d.reshape(shape)
            out = out.reshape(shape)
        ctx.set_output("X@GRAD", _g(x, out, jnp.broadcast_to(d, x.shape), axes))


_reg_reduce("reduce_sum", jnp.sum, lambda x, o, d, ax: d)
_reg_reduce("reduce_mean", jnp.mean,
            lambda x, o, d, ax: d / jnp.prod(jnp.asarray([x.shape[a] for a in ax])))
_reg_reduce("reduce_max", jnp.max,
            lambda x, o, d, ax: d * (x == jnp.broadcast_to(o, x.shape)))
_reg_reduce("reduce_min", jnp.min,
            lambda x, o, d, ax: d * (x == jnp.broadcast_to(o, x.shape)))
_reg_reduce("reduce_prod", jnp.prod,
            lambda x, o, d, ax: d * jnp.broadcast_to(o, x.shape) / x)
