"""Reduction ops: mean, reduce_{sum,mean,max,min,prod}.

Reference: mean_op.cc, reduce_op.cc (/root/reference/paddle/fluid/operators/).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op, OpSpec
from .common import G, data_of


@register_op("mean", grad=lambda op: [OpSpec(
    "mean_grad", {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))})])
def mean(ctx):
    """Mean over all elements. For a LoDArray this is the mean over the VALID
    (unpadded) elements, matching the reference mean over a ragged LoDTensor's
    real rows (mean_op.cc sees only the concatenated data)."""
    xv = ctx.input("X")
    if isinstance(xv, LoDArray):
        feat = int(np.prod(xv.data.shape[2:])) or 1
        m = xv.mask(xv.data.dtype).reshape(
            xv.data.shape[:2] + (1,) * (xv.data.ndim - 2))
        count = jnp.sum(xv.lens).astype(xv.data.dtype) * feat
        ctx.set_output("Out", (jnp.sum(xv.data * m) / count).reshape(()))
        return
    x = data_of(xv)
    ctx.set_output("Out", jnp.mean(x).reshape(()).astype(x.dtype))


@register_op("mean_grad")
def mean_grad(ctx):
    xv = ctx.input("X")
    d = data_of(ctx.input("Out@GRAD")).reshape(())
    if isinstance(xv, LoDArray):
        feat = int(np.prod(xv.data.shape[2:])) or 1
        m = xv.mask(xv.data.dtype).reshape(
            xv.data.shape[:2] + (1,) * (xv.data.ndim - 2))
        count = jnp.sum(xv.lens).astype(xv.data.dtype) * feat
        g = jnp.broadcast_to(m * (d / count), xv.data.shape)
        ctx.set_output("X@GRAD", LoDArray(g, xv.lens))
        return
    x = data_of(xv)
    ctx.set_output("X@GRAD", jnp.full(x.shape, d / x.size).astype(x.dtype))


def _axes(ctx, x):
    dim = ctx.attr("dim", 0)
    if ctx.attr("reduce_all", False):
        return tuple(range(x.ndim))
    if isinstance(dim, (list, tuple)):
        return tuple(d % x.ndim for d in dim)
    return (dim % x.ndim,)


def _reg_reduce(name, fn, grad_fwd):
    def maker(op):
        return [OpSpec(name + "_grad",
                       {"X": op.input("X"), "Out": op.output("Out"),
                        "Out@GRAD": G(op.output("Out"))},
                       {"X@GRAD": G(op.input("X"))}, dict(op.attrs))]

    @register_op(name, grad=maker)
    def forward(ctx, _fn=fn):
        x = data_of(ctx.input("X"))
        out = _fn(x, axis=_axes(ctx, x), keepdims=ctx.attr("keep_dim", False))
        ctx.set_output("Out", out)

    @register_op(name + "_grad")
    def backward(ctx, _g=grad_fwd):
        x = data_of(ctx.input("X"))
        out = data_of(ctx.input("Out"))
        d = data_of(ctx.input("Out@GRAD"))
        axes = _axes(ctx, x)
        if not ctx.attr("keep_dim", False):
            shape = list(x.shape)
            for a in axes:
                shape[a] = 1
            d = d.reshape(shape)
            out = out.reshape(shape)
        ctx.set_output("X@GRAD", _g(x, out, jnp.broadcast_to(d, x.shape), axes))


_reg_reduce("reduce_sum", jnp.sum, lambda x, o, d, ax: d)
_reg_reduce("reduce_mean", jnp.mean,
            lambda x, o, d, ax: d / jnp.prod(jnp.asarray([x.shape[a] for a in ax])))
_reg_reduce("reduce_max", jnp.max,
            lambda x, o, d, ax: d * (x == jnp.broadcast_to(o, x.shape)))
_reg_reduce("reduce_min", jnp.min,
            lambda x, o, d, ax: d * (x == jnp.broadcast_to(o, x.shape)))
_reg_reduce("reduce_prod", jnp.prod,
            lambda x, o, d, ax: d * jnp.broadcast_to(o, x.shape) / x)
