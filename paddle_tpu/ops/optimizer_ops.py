"""Optimizer update ops.

Reference: sgd_op.cc, momentum_op.cc, adam_op.h, adagrad_op.cc, rmsprop_op.cc,
adamax_op.cc, adadelta_op.cc, decayed_adagrad_op.cc, ftrl_op.cc
(/root/reference/paddle/fluid/operators/). In the reference these are ops
*inside the training program* that update parameters in place
(ParamOut == Param); the functional lowering rebinds the name, and because the
whole block is one jitted computation, XLA fuses the update into the backward
pass — no separate "optimizer step" launch ever exists on TPU.

Each op's ``*Out`` aliases follow the reference exactly so that
optimizer.py-built programs are structurally identical to the reference's.

Sparse (SelectedRows) branches: every reference optimizer kernel has a
SelectedRows path that merges duplicate gradient rows then updates ONLY the
touched rows of the parameter/accumulators ("lazy" updates —
operators/adam_op.h SparseAdamFunctor, operators/sgd_op.cu sparse branch,
operators/adagrad_op.cc). Here sgd/momentum/adagrad/adam consume a
``SparseRows`` gradient the same way via core.sparse.apply_rowwise (gather
touched rows → per-row update → scatter back); the remaining optimizers
densify the gradient first (correct, just not lazy).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op
from ..core.sparse import SparseRows, merge_rows, apply_rowwise, is_sparse
from .common import data_of


def _lr(ctx):
    return data_of(ctx.input("LearningRate")).reshape(())


def _param_grad(ctx):
    """Param + Grad with the gradient cast up to the parameter dtype: under
    AMP the backward produces bf16 grads while master weights and optimizer
    state stay float32 (the mixed-precision contract). A SparseRows grad
    reaching an optimizer without a sparse branch is densified here."""
    p = data_of(ctx.input("Param"))
    g = ctx.input("Grad")
    if is_sparse(g):
        g = g.to_dense()
    g = data_of(g).astype(p.dtype)
    return p, g


def _sparse_grad(ctx, p):
    """The Grad input as a merged SparseRows in the param dtype, or None."""
    g = ctx.input("Grad")
    if not is_sparse(g):
        return None
    return merge_rows(g.astype(p.dtype))


# ---- dense update expressions, shared verbatim by the per-param ops and
# the fused megakernel's jnp twin (so kernel_tier=jnp keeps the fused
# program bitwise-identical to the per-param one) ----

def _sgd_dense(p, g, lr):
    return p - lr * g


def _momentum_dense(p, g, v, lr, mu, nesterov):
    v_new = mu * v + g
    if nesterov:
        return p - (g + mu * v_new) * lr, v_new
    return p - lr * v_new, v_new


def _adam_dense(p, g, m1, m2, lr_eff, b1, b2, eps):
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * g * g
    return p - lr_eff * m1n / (jnp.sqrt(m2n) + eps), m1n, m2n


def _sgd_apply(p_v, g_v, lr):
    """One param's SGD step: dense expression, or the sparse branch
    (sgd_op.cu): scatter-subtract the touched rows. The sparse branch
    dispatches to the fused embedding-lookup+sgd Pallas kernel under the
    tier — gather + rowwise update in ONE kernel, O(touched rows) HBM
    traffic (rows pre-merged like every reference sparse optimizer
    kernel; the jnp scatter needs no merge — the update is linear, so
    duplicate rows accumulate correctly)."""
    p = data_of(p_v)
    if is_sparse(g_v):
        from .autotune import dispatch_variant, make_key
        from .pallas import kernel_span
        supported = p.ndim == 2 and g_v.values.ndim == 2
        key = make_key(rows=int(p.shape[0]),
                       dim=int(p.shape[1]) if p.ndim == 2 else 0,
                       nnz=int(g_v.values.shape[0]), dtype=str(p.dtype))
        choice = dispatch_variant("embedding", key,
                                  {"jnp": True, "pallas": supported},
                                  tier_kernel="embedding_sgd")
        if choice == "pallas":
            from .pallas.embedding import embedding_sgd_pallas
            m = merge_rows(g_v.astype(p.dtype))
            with kernel_span("pallas", "embedding_sgd"):
                return embedding_sgd_pallas(p, m.rows, m.values, lr)
        vals = g_v.values.astype(p.dtype)
        return p.at[g_v.rows].add(-lr * vals, mode="drop")
    return _sgd_dense(p, data_of(g_v).astype(p.dtype), lr)


@register_op("sgd", in_place=True)
def sgd(ctx):
    ctx.set_output("ParamOut",
                   _sgd_apply(ctx.input("Param"), ctx.input("Grad"),
                              _lr(ctx)))


@register_op("momentum", in_place=True)
def momentum(ctx):
    p = data_of(ctx.input("Param"))
    v = data_of(ctx.input("Velocity"))
    mu = ctx.attr("mu")
    lr = _lr(ctx)
    nesterov = ctx.attr("use_nesterov", False)
    sg = _sparse_grad(ctx, p)
    if sg is not None:
        def upd(g, p_r, v_r):
            v_new = mu * v_r + g
            if nesterov:
                return p_r - (g + mu * v_new) * lr, v_new
            return p_r - lr * v_new, v_new
        p_new, v_new = apply_rowwise(sg, [p, v], upd)
        ctx.set_output("ParamOut", p_new)
        ctx.set_output("VelocityOut", v_new)
        return
    p, g = _param_grad(ctx)
    p_new, v_new = _momentum_dense(p, g, v, lr, mu, nesterov)
    ctx.set_output("ParamOut", p_new)
    ctx.set_output("VelocityOut", v_new)


@register_op("adam", in_place=True)
def adam(ctx):
    p = data_of(ctx.input("Param"))
    m1 = data_of(ctx.input("Moment1"))
    m2 = data_of(ctx.input("Moment2"))
    b1p = data_of(ctx.input("Beta1Pow")).reshape(())
    b2p = data_of(ctx.input("Beta2Pow")).reshape(())
    b1, b2 = ctx.attr("beta1", 0.9), ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(ctx) * jnp.sqrt(1 - b2p) / (1 - b1p)
    sg = _sparse_grad(ctx, p)
    if sg is not None:
        # adam_op.h SparseAdamFunctor: lazy per-row moment/param update
        def upd(g, p_r, m1_r, m2_r):
            m1n = b1 * m1_r + (1 - b1) * g
            m2n = b2 * m2_r + (1 - b2) * g * g
            return p_r - lr * m1n / (jnp.sqrt(m2n) + eps), m1n, m2n
        p_new, m1_new, m2_new = apply_rowwise(sg, [p, m1, m2], upd)
        ctx.set_output("ParamOut", p_new)
        ctx.set_output("Moment1Out", m1_new)
        ctx.set_output("Moment2Out", m2_new)
        return
    p, g = _param_grad(ctx)
    p_new, m1n, m2n = _adam_dense(p, g, m1, m2, lr, b1, b2, eps)
    ctx.set_output("ParamOut", p_new)
    ctx.set_output("Moment1Out", m1n)
    ctx.set_output("Moment2Out", m2n)


@register_op("adagrad", in_place=True)
def adagrad(ctx):
    p = data_of(ctx.input("Param"))
    m = data_of(ctx.input("Moment"))
    eps = ctx.attr("epsilon", 1e-6)
    lr = _lr(ctx)
    sg = _sparse_grad(ctx, p)
    if sg is not None:
        def upd(g, p_r, m_r):
            m_new = m_r + g * g
            return p_r - lr * g / (jnp.sqrt(m_new) + eps), m_new
        p_new, m_new = apply_rowwise(sg, [p, m], upd)
        ctx.set_output("ParamOut", p_new)
        ctx.set_output("MomentOut", m_new)
        return
    p, g = _param_grad(ctx)
    m_new = m + g * g
    ctx.set_output("ParamOut", p - lr * g / (jnp.sqrt(m_new) + eps))
    ctx.set_output("MomentOut", m_new)


@register_op("decayed_adagrad", in_place=True)
def decayed_adagrad(ctx):
    p, g = _param_grad(ctx)
    m = data_of(ctx.input("Moment"))
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    m_new = decay * m + (1 - decay) * g * g
    ctx.set_output("ParamOut", p - _lr(ctx) * g / (jnp.sqrt(m_new) + eps))
    ctx.set_output("MomentOut", m_new)


@register_op("adadelta", in_place=True)
def adadelta(ctx):
    p, g = _param_grad(ctx)
    avg_sq_grad = data_of(ctx.input("AvgSquaredGrad"))
    avg_sq_upd = data_of(ctx.input("AvgSquaredUpdate"))
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    asg = rho * avg_sq_grad + (1 - rho) * g * g
    upd = -jnp.sqrt((avg_sq_upd + eps) / (asg + eps)) * g
    asu = rho * avg_sq_upd + (1 - rho) * upd * upd
    ctx.set_output("ParamOut", p + upd)
    ctx.set_output("AvgSquaredGradOut", asg)
    ctx.set_output("AvgSquaredUpdateOut", asu)


@register_op("rmsprop", in_place=True)
def rmsprop(ctx):
    p, g = _param_grad(ctx)
    ms = data_of(ctx.input("MeanSquare"))
    mom = data_of(ctx.input("Moment"))
    rho = ctx.attr("decay", 0.9)
    eps = ctx.attr("epsilon", 1e-10)
    momentum_c = ctx.attr("momentum", 0.0)
    ms_new = rho * ms + (1 - rho) * g * g
    mom_new = momentum_c * mom + _lr(ctx) * g / jnp.sqrt(ms_new + eps)
    ctx.set_output("ParamOut", p - mom_new)
    ctx.set_output("MeanSquareOut", ms_new)
    ctx.set_output("MomentOut", mom_new)


@register_op("adamax", in_place=True)
def adamax(ctx):
    p, g = _param_grad(ctx)
    m = data_of(ctx.input("Moment"))
    inf_norm = data_of(ctx.input("InfNorm"))
    b1p = data_of(ctx.input("Beta1Pow")).reshape(())
    b1, b2 = ctx.attr("beta1", 0.9), ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf_norm, jnp.abs(g) + eps)
    lr = _lr(ctx) / (1 - b1p)
    ctx.set_output("ParamOut", p - lr * m_new / inf_new)
    ctx.set_output("MomentOut", m_new)
    ctx.set_output("InfNormOut", inf_new)


@register_op("ftrl", in_place=True)
def ftrl(ctx):
    p, g = _param_grad(ctx)
    sq = data_of(ctx.input("SquaredAccumulator"))
    lin = data_of(ctx.input("LinearAccumulator"))
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr_power = ctx.attr("lr_power", -0.5)
    lr = _lr(ctx)
    new_sq = sq + g * g
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    x = jnp.clip(new_lin, -l1, l1) - new_lin
    if lr_power == -0.5:
        y = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        y = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    ctx.set_output("ParamOut", x / y)
    ctx.set_output("SquaredAccumOut", new_sq)
    ctx.set_output("LinearAccumOut", new_lin)


@register_op("proximal_gd", in_place=True)
def proximal_gd(ctx):
    p, g = _param_grad(ctx)
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr = _lr(ctx)
    prox = p - lr * g
    ctx.set_output("ParamOut",
                   jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
                   / (1.0 + lr * l2))


@register_op("proximal_adagrad", in_place=True)
def proximal_adagrad(ctx):
    p, g = _param_grad(ctx)
    m = data_of(ctx.input("Moment"))
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    m_new = m + g * g
    lr = _lr(ctx) / jnp.sqrt(m_new)
    prox = p - lr * g
    ctx.set_output("ParamOut",
                   jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
                   / (1.0 + lr * l2))
    ctx.set_output("MomentOut", m_new)


# ---------------------------------------------------------------------------
# fused dense-optimizer megakernel ops (no reference analog)
# ---------------------------------------------------------------------------
#
# The per-param ops above trace one small update per parameter — XLA emits
# one fused kernel per param, so a ResNet-50 step dispatches ~160 tiny
# launches for the momentum tail alone (bench.py profile). These variadic
# ops take ALL dense params in one op; under a Pallas tier the update runs
# as ONE arena megakernel (ops/pallas/optimizer.py: params/grads/state
# concatenated into flat f32 arenas, one launch walks the tiles), and the
# jnp twin applies the per-param dense expressions above in a python loop
# — bitwise the per-param program. A SparseRows grad (or a non-f32 param)
# keeps its param on the per-param path inside the same op. Emitted by
# fluid.optimizer.{SGD,Momentum,Adam}(fused=True).

def _fused_apply(ctx, state_slots, out_slots, dense_fn, sparse_fn,
                 arena_fn):
    """Shared driver for the fused ops: split the param list into
    arena-fusable entries (dense f32 grads) and per-param entries
    (SparseRows / non-f32), run the per-param branch with ``sparse_fn``/
    ``dense_fn``, and the fusable set through ONE arena megakernel
    (``arena_fn``) under a Pallas tier — or the same ``dense_fn``
    expressions per param under jnp (bitwise the per-param program).

    dense_fn(p, g, *states) and sparse_fn(p_var, g_sparse, *states) both
    return a (p_new, *state_news) tuple; arena_fn(*arenas) returns the
    updated arenas in the same order.
    """
    from .autotune import dispatch_variant, make_key
    from .pallas import kernel_span

    slots = ("Params", "Grads") + tuple(state_slots)
    entries = list(zip(*[ctx.inputs(s) for s in slots]))
    k = 1 + len(state_slots)
    outs = [[None] * len(entries) for _ in range(k)]
    fusable = []
    for i, e in enumerate(entries):
        p = data_of(e[0])
        if (not is_sparse(e[1])) and p.dtype == jnp.float32:
            fusable.append(i)
            continue
        if is_sparse(e[1]):
            res = sparse_fn(e[0], e[1], *[data_of(v) for v in e[2:]])
        else:
            res = dense_fn(p, data_of(e[1]).astype(p.dtype),
                           *[data_of(v) for v in e[2:]])
        for j, v in enumerate(res):
            outs[j][i] = v
    # the dispatch runs even with no fusable params so an all-sparse op
    # under a Pallas tier is a counted fallback, not a silent miss
    kind = {0: "sgd", 1: "momentum"}.get(len(state_slots), "adam")
    elems = sum(int(data_of(entries[i][0]).size) for i in fusable)
    choice = dispatch_variant(
        "optimizer",
        make_key(kind=kind, tensors=len(fusable), elems=elems),
        {"jnp": True, "pallas": bool(fusable)})
    if choice == "pallas":
        from .pallas import optimizer as opk
        ps = [data_of(entries[i][0]) for i in fusable]
        gs = [data_of(entries[i][1]).astype(jnp.float32) for i in fusable]
        states = [[data_of(entries[i][2 + j]) for i in fusable]
                  for j in range(len(state_slots))]
        shapes = [p.shape for p in ps]
        with kernel_span("pallas", "optimizer"):
            arenas = [opk.flatten_arena(xs)[0]
                      for xs in (ps, gs, *states)]
            results = arena_fn(*arenas)
            split = [opk.split_arena(r, shapes) for r in results]
        for j in range(k):
            for i, v in zip(fusable, split[j]):
                outs[j][i] = v
    else:
        if fusable:
            # the jnp twin: the per-param dense expressions verbatim
            # (bitwise the per-param program)
            with kernel_span("jnp", "optimizer"):
                for i in fusable:
                    p = data_of(entries[i][0])
                    res = dense_fn(
                        p, data_of(entries[i][1]).astype(p.dtype),
                        *[data_of(v) for v in entries[i][2:]])
                    for j, v in enumerate(res):
                        outs[j][i] = v
    for slot, vals in zip(out_slots, outs):
        ctx.set_outputs(slot, vals)


@register_op("fused_sgd", in_place=True)
def fused_sgd(ctx):
    lr = _lr(ctx)

    def arena(pa, ga):
        from .pallas import optimizer as opk
        return (opk.sgd_arena_pallas(pa, ga, lr),)

    _fused_apply(ctx, (), ("ParamsOut",),
                 dense_fn=lambda p, g: (_sgd_dense(p, g, lr),),
                 sparse_fn=lambda p_v, g_v: (_sgd_apply(p_v, g_v, lr),),
                 arena_fn=arena)


@register_op("fused_momentum", in_place=True)
def fused_momentum(ctx):
    lr = _lr(ctx)
    mu = ctx.attr("mu")
    nesterov = bool(ctx.attr("use_nesterov", False))

    def dense(p, g, v):
        return _momentum_dense(p, g, v, lr, mu, nesterov)

    def sparse(p_v, g_v, v):
        p = data_of(p_v)
        sg = merge_rows(g_v.astype(p.dtype))

        def upd(g, p_r, v_r):
            v_new = mu * v_r + g
            if nesterov:
                return p_r - (g + mu * v_new) * lr, v_new
            return p_r - lr * v_new, v_new
        return tuple(apply_rowwise(sg, [p, v], upd))

    def arena(pa, ga, va):
        from .pallas import optimizer as opk
        return opk.momentum_arena_pallas(pa, ga, va, lr, mu, nesterov)

    _fused_apply(ctx, ("Velocities",), ("ParamsOut", "VelocitiesOut"),
                 dense, sparse, arena)


@register_op("fused_adam", in_place=True)
def fused_adam(ctx):
    b1, b2 = ctx.attr("beta1", 0.9), ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    b1p = data_of(ctx.input("Beta1Pow")).reshape(())
    b2p = data_of(ctx.input("Beta2Pow")).reshape(())
    # ONE shared beta-power pair (every param shares the step count), so
    # lr_eff is one scalar for the whole arena
    lr_eff = _lr(ctx) * jnp.sqrt(1 - b2p) / (1 - b1p)

    def dense(p, g, m1, m2):
        return _adam_dense(p, g, m1, m2, lr_eff, b1, b2, eps)

    def sparse(p_v, g_v, m1, m2):
        p = data_of(p_v)
        sg = merge_rows(g_v.astype(p.dtype))

        def upd(g, p_r, m1_r, m2_r):
            m1n = b1 * m1_r + (1 - b1) * g
            m2n = b2 * m2_r + (1 - b2) * g * g
            return (p_r - lr_eff * m1n / (jnp.sqrt(m2n) + eps), m1n, m2n)
        return tuple(apply_rowwise(sg, [p, m1, m2], upd))

    def arena(pa, ga, m1a, m2a):
        from .pallas import optimizer as opk
        return opk.adam_arena_pallas(pa, ga, m1a, m2a, lr_eff, b1, b2, eps)

    _fused_apply(ctx, ("Moment1s", "Moment2s"),
                 ("ParamsOut", "Moment1sOut", "Moment2sOut"),
                 dense, sparse, arena)
