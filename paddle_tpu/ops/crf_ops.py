"""Linear-chain CRF ops: linear_chain_crf, crf_decoding.

Reference: /root/reference/paddle/fluid/operators/linear_chain_crf_op.{h,cc}
(forward algorithm per ragged sequence; Transition layout [D+2, D] with row 0
start scores, row 1 end scores, rows 2.. the [D, D] tag-transition matrix;
LogLikelihood output is the negative log likelihood used directly as a cost)
and crf_decoding_op.h (Viterbi; with a Label input it emits per-token 0/1
correctness instead of the path).

TPU lowering: one masked lax.scan per batch computes all sequences' forward
recursions in parallel over the padded LoD layout (the reference loops
sequences serially on CPU — linear_chain_crf_op.h ForwardOneSequence).
Gradients via jax.vjp through the scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op, OpSpec
from .common import G, data_of


def _crf_nll(emission, lens, labels, w):
    """Negative log-likelihood per sequence.

    emission: [b, L, D]; lens: [b]; labels: [b, L] int; w: [D+2, D].
    """
    b, L, D = emission.shape
    start, end, trans = w[0], w[1], w[2:]

    x = jnp.swapaxes(emission, 0, 1)          # [L, b, D]
    y = jnp.swapaxes(labels, 0, 1)            # [L, b]

    alpha0 = start[None, :] + x[0]            # [b, D]
    gold0 = start[y[0]] + jnp.take_along_axis(x[0], y[0][:, None],
                                              axis=1)[:, 0]

    init = dict(
        alpha=alpha0,
        gold=gold0,
        logz=jnp.where(lens == 1,
                       jax.scipy.special.logsumexp(alpha0 + end[None, :],
                                                   axis=1),
                       jnp.zeros((b,), emission.dtype)),
        gold_end=jnp.where(lens == 1, end[y[0]],
                           jnp.zeros((b,), emission.dtype)),
        prev_y=y[0],
    )

    def step(c, inp):
        t, xt, yt = inp
        # alpha[t, j] = logsumexp_i(alpha[t-1, i] + trans[i, j]) + x[t, j]
        nxt = jax.scipy.special.logsumexp(
            c["alpha"][:, :, None] + trans[None, :, :], axis=1) + xt
        alive = (t < lens)[:, None]
        alpha = jnp.where(alive, nxt, c["alpha"])
        gold_step = (jnp.take_along_axis(xt, yt[:, None], axis=1)[:, 0]
                     + trans[c["prev_y"], yt])
        gold = c["gold"] + jnp.where(t < lens, gold_step, 0.0)
        last = t == lens - 1
        logz = jnp.where(
            last, jax.scipy.special.logsumexp(alpha + end[None, :], axis=1),
            c["logz"])
        gold_end = jnp.where(last, end[yt], c["gold_end"])
        prev_y = jnp.where(t < lens, yt, c["prev_y"])
        return dict(alpha=alpha, gold=gold, logz=logz, gold_end=gold_end,
                    prev_y=prev_y), None

    if L > 1:
        ts = jnp.arange(1, L)
        final, _ = jax.lax.scan(step, init, (ts, x[1:], y[1:]))
    else:
        final = init
    return (final["logz"] - (final["gold"] + final["gold_end"]))[:, None]


def _crf_grad_maker(op):
    return [OpSpec(
        "linear_chain_crf_grad",
        {"Emission": op.input("Emission"),
         "Transition": op.input("Transition"), "Label": op.input("Label"),
         "LogLikelihood@GRAD": G(op.output("LogLikelihood"))},
        {"Emission@GRAD": G(op.input("Emission")),
         "Transition@GRAD": G(op.input("Transition"))}, dict(op.attrs))]


def _emission_parts(ctx):
    ev = ctx.input("Emission")
    if not isinstance(ev, LoDArray):
        raise TypeError("linear_chain_crf expects a LoD emission input")
    lab = ctx.input("Label")
    labels = (lab.data if isinstance(lab, LoDArray) else data_of(lab))
    if labels.ndim == 3:
        labels = labels[..., 0]
    return ev, labels.astype(jnp.int32)


@register_op("linear_chain_crf", grad=_crf_grad_maker)
def linear_chain_crf(ctx):
    ev, labels = _emission_parts(ctx)
    w = data_of(ctx.input("Transition"))
    nll = _crf_nll(ev.data, ev.lens, labels, w)
    ctx.set_output("LogLikelihood", nll)


@register_op("linear_chain_crf_grad")
def linear_chain_crf_grad(ctx):
    ev, labels = _emission_parts(ctx)
    w = data_of(ctx.input("Transition"))
    d = data_of(ctx.input("LogLikelihood@GRAD"))
    _, vjp = jax.vjp(lambda e, t: _crf_nll(e, ev.lens, labels, t),
                     ev.data, w)
    de, dw = vjp(d)
    ctx.set_output("Emission@GRAD", LoDArray(de, ev.lens))
    ctx.set_output("Transition@GRAD", dw)


@register_op("crf_decoding")
def crf_decoding(ctx):
    """Viterbi decode (crf_decoding_op.h). Output ViterbiPath: the best tag
    path as a LoDArray; when Label is given, 0/1 per-token correctness
    (the reference's evaluation mode)."""
    ev = ctx.input("Emission")
    if not isinstance(ev, LoDArray):
        raise TypeError("crf_decoding expects a LoD emission input")
    w = data_of(ctx.input("Transition"))
    start, end, trans = w[0], w[1], w[2:]
    x = jnp.swapaxes(ev.data, 0, 1)       # [L, b, D]
    lens = ev.lens
    b = x.shape[1]
    L = x.shape[0]

    def fwd(c, inp):
        t, xt = inp
        scores = c[:, :, None] + trans[None, :, :]     # [b, i, j]
        best_prev = jnp.argmax(scores, axis=1)          # [b, j]
        nxt = jnp.max(scores, axis=1) + xt
        alive = (t < lens)[:, None]
        out = jnp.where(alive, nxt, c)
        return out, (best_prev, alive)

    init = start[None, :] + x[0]
    ts = jnp.arange(1, L)
    final, (ptrs, alives) = jax.lax.scan(fwd, init, (ts, x[1:])) \
        if L > 1 else (init, (jnp.zeros((0, b, x.shape[2]), jnp.int32),
                              jnp.zeros((0, b, 1), bool)))

    # add end scores at each sequence's true last position: recompute final
    # per row by scanning once more is avoided — decode from the alpha at the
    # final state (we kept alpha frozen past each row's end, so `final` holds
    # alpha[len-1]); add end scores there.
    last_tag = jnp.argmax(final + end[None, :], axis=1)    # [b]

    def back(carry, inp):
        ptr_t, alive_t = inp
        tag = carry
        prev = jnp.take_along_axis(ptr_t, tag[:, None], axis=1)[:, 0]
        new = jnp.where(alive_t[:, 0], prev, tag)
        return new, tag

    # walk pointers back from the end: emits tags for t = L-1 .. 1, and the
    # final carry is the tag at t = 0
    if L > 1:
        tag0, tags_rev = jax.lax.scan(back, last_tag,
                                      (ptrs[::-1], alives[::-1]))
        path = jnp.concatenate([tag0[None, :], tags_rev[::-1]], axis=0)
    else:
        path = last_tag[None, :]
    # positions beyond each row's length hold junk from frozen pointers; the
    # true path occupies positions [0, len) because pointers froze past len
    path = jnp.swapaxes(path, 0, 1)[..., None].astype(jnp.int64)  # [b, L, 1]

    if ctx.has_input("Label"):
        lab = ctx.input("Label")
        labels = lab.data if isinstance(lab, LoDArray) else data_of(lab)
        if labels.ndim == 2:
            labels = labels[..., None]
        correct = (path == labels.astype(jnp.int64)).astype(jnp.int64)
        ctx.set_output("ViterbiPath", LoDArray(correct, lens))
    else:
        ctx.set_output("ViterbiPath", LoDArray(path, lens))
