"""Deprecation shim: the seed's ad-hoc kernel module became the kernel tier.

The two hand-tuned kernel families that lived here (whole-recurrence
LSTM/GRU, CTC alpha) are now ``ops/pallas/rnn.py`` and ``ops/pallas/ctc.py``
inside the first-class Pallas kernel tier (``paddle_tpu/ops/pallas/`` — see
its package docstring for the selection/fallback contract). This module
re-exports the old public names so existing imports keep working.
"""

from __future__ import annotations

from .pallas.rnn import (  # noqa: F401
    lstm_seq_pallas,
    gru_seq_pallas,
    _lstm_cell_jnp,
    _lstm_step_jnp,
    _gru_step_jnp,
    _lstm_seq_fwd_pallas,
    _gru_seq_fwd_pallas,
)
from .pallas.ctc import ctc_alpha_pallas, _NEG  # noqa: F401

__all__ = ["lstm_seq_pallas", "gru_seq_pallas", "ctc_alpha_pallas"]
