"""Pallas TPU kernels for the hand-tuned hot spots.

The reference hand-schedules fused CUDA kernels for exactly these spots —
the LSTM/GRU cell update (/root/reference/paddle/cuda/src/hl_cuda_lstm.cu,
hl_gpu_lstm.cuh: one kernel applies all four gate activations + the cell
recurrence in registers instead of separate elementwise launches). The
Pallas analogs keep the big matmul on the MXU (outside the kernel, where
XLA tiles it) and fuse the post-matmul gate math + aliveness masking into
one VMEM-resident pass.

Default OFF (flag ``use_pallas_rnn``): XLA's own elementwise fusion already
fuses this chain well, so the kernels are an opt-in tuning surface and the
demonstration of the custom-kernel escape hatch; numerics are pinned
against the jnp path (tests/test_pallas_kernels.py, interpret mode on CPU,
native on TPU). Gradients use jax.custom_vjp with a jnp backward — the
backward chain is elementwise and XLA-fused regardless.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl


def _on_cpu():
    return jax.default_backend() == "cpu"


def _lstm_cell_jnp(gates, c_prev, h_prev, alive):
    hdim = gates.shape[-1] // 4
    i = jax.nn.sigmoid(gates[:, :hdim])
    f = jax.nn.sigmoid(gates[:, hdim:2 * hdim])
    cand = jnp.tanh(gates[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(gates[:, 3 * hdim:])
    c = f * c_prev + i * cand
    h = o * jnp.tanh(c)
    return (alive * h + (1 - alive) * h_prev,
            alive * c + (1 - alive) * c_prev)


def _gru_cell_kernel(u_in_ref, c_in_ref, h_prev_ref, w_c_ref, alive_ref,
                     h_ref):
    """Fused GRU cell: u_in [b, H] is the update-gate preactivation, c_in
    [b, H] the candidate's input projection; the candidate still needs
    (r*h_prev) @ W_c which arrives via w_c (that matmul stays outside on
    the MXU, with the reset gate applied before it). One pass computes the
    update gate, the candidate epilogue, and the masked recurrence."""
    h_prev = h_prev_ref[...]
    rc = w_c_ref[...]
    alive = alive_ref[...]
    u = jax.nn.sigmoid(u_in_ref[...])
    cand = jnp.tanh(c_in_ref[...] + rc)
    h = u * cand + (1 - u) * h_prev
    h_ref[...] = alive * h + (1 - alive) * h_prev


def _gru_cell_jnp(u_in, c_in, h_prev, rc, alive):
    u = jax.nn.sigmoid(u_in)
    cand = jnp.tanh(c_in + rc)
    h = u * cand + (1 - u) * h_prev
    return alive * h + (1 - alive) * h_prev


@jax.custom_vjp
def fused_gru_cell(u_in, c_in, h_prev, rc, alive):
    b, hdim = u_in.shape
    return pl.pallas_call(
        _gru_cell_kernel,
        out_shape=jax.ShapeDtypeStruct((b, hdim), u_in.dtype),
        interpret=_on_cpu(),
    )(u_in, c_in, h_prev, rc, alive)


def _gru_fwd(u_in, c_in, h_prev, rc, alive):
    return fused_gru_cell(u_in, c_in, h_prev, rc, alive), \
        (u_in, c_in, h_prev, rc, alive)


def _gru_bwd(res, ct):
    u_in, c_in, h_prev, rc, alive = res
    _, vjp = jax.vjp(_gru_cell_jnp, u_in, c_in, h_prev, rc, alive)
    return vjp(ct)


fused_gru_cell.defvjp(_gru_fwd, _gru_bwd)


# ---------------------------------------------------------------------------
# CTC alpha recurrence (the warp-ctc replacement's hot loop)
# ---------------------------------------------------------------------------

_NEG = -1e30


def _ctc_alpha_kernel(e_ref, alpha0_ref, final0_ref, can_skip_ref,
                      s_valid_ref, xlen_ref, ylen_ref, loss_ref):
    """Whole-sequence CTC forward for ONE batch element: alpha stays
    VMEM-resident across all T steps (the reference's warp-ctc keeps it in
    shared memory per block, ctc_helper kernels). e [T, Sp] are the emit
    log-probs at the blank-interleaved labels; masks are f32 0/1."""
    e = e_ref[0]                          # [T, Sp]
    can_skip = can_skip_ref[0]            # [Sp]
    s_valid = s_valid_ref[0]
    xlen = xlen_ref[0, 0]
    ylen = ylen_ref[0, 0]
    T = e.shape[0]
    sp = e.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (sp,), 0)

    last = 2 * ylen                       # index of the final blank
    onehot_last = (iota == last).astype(e.dtype)
    onehot_lab = (iota == jnp.maximum(last - 1, 0)).astype(e.dtype)

    def final_of(alpha):
        a_last = jnp.sum(jnp.where(onehot_last > 0, alpha, 0.0))
        a_lab = jnp.sum(jnp.where(onehot_lab > 0, alpha, 0.0))
        a_lab = jnp.where(ylen > 0, a_lab, _NEG)
        return jnp.logaddexp(a_last, a_lab)

    def body(t, carry):
        alpha, final = carry
        a1 = jnp.where(iota >= 1, jnp.roll(alpha, 1), _NEG)
        a2 = jnp.where((iota >= 2) & (can_skip > 0),
                       jnp.roll(alpha, 2), _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
        lp = jax.lax.dynamic_slice_in_dim(e, t, 1, axis=0)[0]
        nxt = jnp.where(s_valid > 0, merged + lp, _NEG)
        alpha = jnp.where(t < xlen, nxt, alpha)
        final = jnp.where(t == xlen - 1, final_of(alpha), final)
        return alpha, final

    alpha0 = alpha0_ref[0]
    _, final = jax.lax.fori_loop(1, T, body,
                                 (alpha0, final0_ref[0, 0]))
    loss_ref[0, 0] = -final


def ctc_alpha_pallas(e, alpha0, final0, can_skip, s_valid, x_lens, y_lens):
    """[b, T, Sp] emit matrix -> [b, 1] loss; one program per batch row."""
    b, T, sp = e.shape
    f32 = e.dtype
    return pl.pallas_call(
        _ctc_alpha_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, T, sp), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, sp), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, sp), lambda i: (i, 0)),
            pl.BlockSpec((1, sp), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 1), f32),
        interpret=_on_cpu(),
    )(e, alpha0, final0, can_skip, s_valid, x_lens, y_lens)


# ---------------------------------------------------------------------------
# Whole-recurrence LSTM: one kernel for the ENTIRE sequence
# ---------------------------------------------------------------------------

def _lstm_seq_kernel(x_ref, alive_ref, w_ref, h0_ref, c0_ref,
                     hs_ref, cs_ref, h_s, c_s):
    """Grid over time. The recurrent weight w stays VMEM-resident across
    every grid step (XLA's lax.scan body re-reads it from HBM each
    iteration — for hid 512 that is ~4 MB x seq_len per layer) and the h/c
    carries live in VMEM scratch, so the whole recurrence is ONE kernel
    launch instead of seq_len (matmul + fusion) pairs. The per-step matmul
    runs on the MXU in bf16 with f32 accumulation (the lane's
    default_matmul_precision contract)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_s[...] = h0_ref[...]
        c_s[...] = c0_ref[...]

    h_prev = h_s[...]
    c_prev = c_s[...]
    gates = x_ref[0] + jax.lax.dot(
        h_prev.astype(w_ref.dtype), w_ref[...],
        preferred_element_type=jnp.float32).astype(h_prev.dtype)
    hdim = h_prev.shape[-1]
    alive = alive_ref[0]
    i = jax.nn.sigmoid(gates[:, :hdim])
    f = jax.nn.sigmoid(gates[:, hdim:2 * hdim])
    cand = jnp.tanh(gates[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(gates[:, 3 * hdim:])
    c = f * c_prev + i * cand
    h = o * jnp.tanh(c)
    h = alive * h + (1 - alive) * h_prev
    c = alive * c + (1 - alive) * c_prev
    h_s[...] = h
    c_s[...] = c
    hs_ref[0] = h
    cs_ref[0] = c


def _lstm_seq_fwd_pallas(x, alive, w, h0, c0):
    """x [L, b, 4H] (projected inputs + bias), alive [L, b, 1] float,
    w [H, 4H]; returns CARRY sequences hs/cs [L, b, H] (unmasked — the
    caller applies the output mask)."""
    from jax.experimental.pallas import tpu as pltpu

    L, b, H4 = x.shape
    H = H4 // 4
    wb = w.astype(jnp.bfloat16)   # MXU operand; bf16 halves its VMEM stay
    return pl.pallas_call(
        _lstm_seq_kernel,
        grid=(L,),
        in_specs=[
            pl.BlockSpec((1, b, H4), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, b, 1), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
            pl.BlockSpec((b, H), lambda t: (0, 0)),
            pl.BlockSpec((b, H), lambda t: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((1, b, H), lambda t: (t, 0, 0)),
                   pl.BlockSpec((1, b, H), lambda t: (t, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((L, b, H), x.dtype),
                   jax.ShapeDtypeStruct((L, b, H), x.dtype)],
        scratch_shapes=[pltpu.VMEM((b, H), x.dtype),
                        pltpu.VMEM((b, H), x.dtype)],
        interpret=_on_cpu(),
    )(x, alive, wb, h0, c0)


def _lstm_step_jnp(xt, h_prev, c_prev, w, alive):
    """One reference step on CARRIES (the jnp twin the backward
    differentiates): the bf16-MXU gate matmul + the shared cell math.
    Returns (h_carry, c_carry)."""
    gates = xt + jax.lax.dot(
        h_prev.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32).astype(h_prev.dtype)
    return _lstm_cell_jnp(gates, c_prev, h_prev, alive)


@jax.custom_vjp
def lstm_seq_pallas(x, alive, w, h0, c0):
    return _lstm_seq_fwd_pallas(x, alive, w, h0, c0)


def _lstm_seq_fwd(x, alive, w, h0, c0):
    hs, cs = _lstm_seq_fwd_pallas(x, alive, w, h0, c0)
    return (hs, cs), (x, alive, w, h0, c0, hs, cs)


def _lstm_seq_bwd(res, cts):
    """Reverse scan of per-step jax.vjp over the SAVED carries: gates are
    recomputed from x[t] + h[t-1] @ w (one extra matmul per step — the
    trade XLA's scan makes by saving gates instead; recompute keeps the
    saved-residual HBM footprint at 2 arrays)."""
    x, alive, w, h0, c0, hs, cs = res
    dhs, dcs = cts
    h_prevs = jnp.concatenate([h0[None], hs[:-1]], axis=0)
    c_prevs = jnp.concatenate([c0[None], cs[:-1]], axis=0)

    def bstep(carry, inp):
        dh_next, dc_next, dw = carry
        xt, at, hp, cp, dh_out, dc_out = inp
        _, vjp = jax.vjp(
            lambda xv, hv, cv, wv: _lstm_step_jnp(xv, hv, cv, wv, at),
            xt, hp, cp, w)
        dxt, dhp, dcp, dwt = vjp((dh_next + dh_out, dc_next + dc_out))
        return (dhp, dcp, dw + dwt), dxt

    zero = jnp.zeros_like(h0)
    (dh0, dc0, dw), dx = jax.lax.scan(
        bstep, (zero, jnp.zeros_like(c0), jnp.zeros_like(w)),
        (x, alive, h_prevs, c_prevs, dhs, dcs), reverse=True)
    return dx, None, dw, dh0, dc0


lstm_seq_pallas.defvjp(_lstm_seq_fwd, _lstm_seq_bwd)
