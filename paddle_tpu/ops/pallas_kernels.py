"""Pallas TPU kernels for the hand-tuned hot spots.

The reference hand-schedules fused CUDA kernels for exactly these spots —
the LSTM/GRU cell update (/root/reference/paddle/cuda/src/hl_cuda_lstm.cu,
hl_gpu_lstm.cuh: one kernel applies all four gate activations + the cell
recurrence in registers instead of separate elementwise launches). The
Pallas analogs keep the big matmul on the MXU (outside the kernel, where
XLA tiles it) and fuse the post-matmul gate math + aliveness masking into
one VMEM-resident pass.

Default OFF (flag ``use_pallas_rnn``): XLA's own elementwise fusion already
fuses this chain well, so the kernels are an opt-in tuning surface and the
demonstration of the custom-kernel escape hatch; numerics are pinned
against the jnp path (tests/test_pallas_kernels.py, interpret mode on CPU,
native on TPU). Gradients use jax.custom_vjp with a jnp backward — the
backward chain is elementwise and XLA-fused regardless.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl


def _on_cpu():
    return jax.default_backend() == "cpu"


def _lstm_cell_kernel(gates_ref, c_prev_ref, h_prev_ref, alive_ref,
                      h_ref, c_ref):
    """One fused pass: gates [b, 4H] -> (h, c) [b, H], masked by alive.
    Gate column order [i, f, c, o] (this framework's documented layout)."""
    gates = gates_ref[...]
    h4 = gates.shape[-1]
    hdim = h4 // 4
    c_prev = c_prev_ref[...]
    h_prev = h_prev_ref[...]
    alive = alive_ref[...]
    i = jax.nn.sigmoid(gates[:, :hdim])
    f = jax.nn.sigmoid(gates[:, hdim:2 * hdim])
    cand = jnp.tanh(gates[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(gates[:, 3 * hdim:])
    c = f * c_prev + i * cand
    h = o * jnp.tanh(c)
    h_ref[...] = alive * h + (1 - alive) * h_prev
    c_ref[...] = alive * c + (1 - alive) * c_prev


def _lstm_cell_jnp(gates, c_prev, h_prev, alive):
    hdim = gates.shape[-1] // 4
    i = jax.nn.sigmoid(gates[:, :hdim])
    f = jax.nn.sigmoid(gates[:, hdim:2 * hdim])
    cand = jnp.tanh(gates[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(gates[:, 3 * hdim:])
    c = f * c_prev + i * cand
    h = o * jnp.tanh(c)
    return (alive * h + (1 - alive) * h_prev,
            alive * c + (1 - alive) * c_prev)


@jax.custom_vjp
def fused_lstm_cell(gates, c_prev, h_prev, alive):
    """Fused LSTM cell (standard sigmoid/tanh activations): pallas forward,
    jnp custom-vjp backward. All operands [b, ·]; alive [b, 1]."""
    b, h4 = gates.shape
    hdim = h4 // 4
    return pl.pallas_call(
        _lstm_cell_kernel,
        out_shape=(jax.ShapeDtypeStruct((b, hdim), gates.dtype),
                   jax.ShapeDtypeStruct((b, hdim), gates.dtype)),
        interpret=_on_cpu(),
    )(gates, c_prev, h_prev, alive)


def _fused_fwd(gates, c_prev, h_prev, alive):
    out = fused_lstm_cell(gates, c_prev, h_prev, alive)
    return out, (gates, c_prev, h_prev, alive)


def _fused_bwd(res, cts):
    gates, c_prev, h_prev, alive = res
    _, vjp = jax.vjp(_lstm_cell_jnp, gates, c_prev, h_prev, alive)
    return vjp(cts)


fused_lstm_cell.defvjp(_fused_fwd, _fused_bwd)


def _gru_cell_kernel(u_in_ref, c_in_ref, h_prev_ref, w_c_ref, alive_ref,
                     h_ref):
    """Fused GRU cell: u_in [b, H] is the update-gate preactivation, c_in
    [b, H] the candidate's input projection; the candidate still needs
    (r*h_prev) @ W_c which arrives via w_c (that matmul stays outside on
    the MXU, with the reset gate applied before it). One pass computes the
    update gate, the candidate epilogue, and the masked recurrence."""
    h_prev = h_prev_ref[...]
    rc = w_c_ref[...]
    alive = alive_ref[...]
    u = jax.nn.sigmoid(u_in_ref[...])
    cand = jnp.tanh(c_in_ref[...] + rc)
    h = u * cand + (1 - u) * h_prev
    h_ref[...] = alive * h + (1 - alive) * h_prev


def _gru_cell_jnp(u_in, c_in, h_prev, rc, alive):
    u = jax.nn.sigmoid(u_in)
    cand = jnp.tanh(c_in + rc)
    h = u * cand + (1 - u) * h_prev
    return alive * h + (1 - alive) * h_prev


@jax.custom_vjp
def fused_gru_cell(u_in, c_in, h_prev, rc, alive):
    b, hdim = u_in.shape
    return pl.pallas_call(
        _gru_cell_kernel,
        out_shape=jax.ShapeDtypeStruct((b, hdim), u_in.dtype),
        interpret=_on_cpu(),
    )(u_in, c_in, h_prev, rc, alive)


def _gru_fwd(u_in, c_in, h_prev, rc, alive):
    return fused_gru_cell(u_in, c_in, h_prev, rc, alive), \
        (u_in, c_in, h_prev, rc, alive)


def _gru_bwd(res, ct):
    u_in, c_in, h_prev, rc, alive = res
    _, vjp = jax.vjp(_gru_cell_jnp, u_in, c_in, h_prev, rc, alive)
    return vjp(ct)


fused_gru_cell.defvjp(_gru_fwd, _gru_bwd)


# ---------------------------------------------------------------------------
# CTC alpha recurrence (the warp-ctc replacement's hot loop)
# ---------------------------------------------------------------------------

_NEG = -1e30


def _ctc_alpha_kernel(e_ref, alpha0_ref, final0_ref, can_skip_ref,
                      s_valid_ref, xlen_ref, ylen_ref, loss_ref):
    """Whole-sequence CTC forward for ONE batch element: alpha stays
    VMEM-resident across all T steps (the reference's warp-ctc keeps it in
    shared memory per block, ctc_helper kernels). e [T, Sp] are the emit
    log-probs at the blank-interleaved labels; masks are f32 0/1."""
    e = e_ref[0]                          # [T, Sp]
    can_skip = can_skip_ref[0]            # [Sp]
    s_valid = s_valid_ref[0]
    xlen = xlen_ref[0, 0]
    ylen = ylen_ref[0, 0]
    T = e.shape[0]
    sp = e.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (sp,), 0)

    last = 2 * ylen                       # index of the final blank
    onehot_last = (iota == last).astype(e.dtype)
    onehot_lab = (iota == jnp.maximum(last - 1, 0)).astype(e.dtype)

    def final_of(alpha):
        a_last = jnp.sum(jnp.where(onehot_last > 0, alpha, 0.0))
        a_lab = jnp.sum(jnp.where(onehot_lab > 0, alpha, 0.0))
        a_lab = jnp.where(ylen > 0, a_lab, _NEG)
        return jnp.logaddexp(a_last, a_lab)

    def body(t, carry):
        alpha, final = carry
        a1 = jnp.where(iota >= 1, jnp.roll(alpha, 1), _NEG)
        a2 = jnp.where((iota >= 2) & (can_skip > 0),
                       jnp.roll(alpha, 2), _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
        lp = jax.lax.dynamic_slice_in_dim(e, t, 1, axis=0)[0]
        nxt = jnp.where(s_valid > 0, merged + lp, _NEG)
        alpha = jnp.where(t < xlen, nxt, alpha)
        final = jnp.where(t == xlen - 1, final_of(alpha), final)
        return alpha, final

    alpha0 = alpha0_ref[0]
    _, final = jax.lax.fori_loop(1, T, body,
                                 (alpha0, final0_ref[0, 0]))
    loss_ref[0, 0] = -final


def ctc_alpha_pallas(e, alpha0, final0, can_skip, s_valid, x_lens, y_lens):
    """[b, T, Sp] emit matrix -> [b, 1] loss; one program per batch row."""
    b, T, sp = e.shape
    f32 = e.dtype
    return pl.pallas_call(
        _ctc_alpha_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, T, sp), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, sp), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, sp), lambda i: (i, 0)),
            pl.BlockSpec((1, sp), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 1), f32),
        interpret=_on_cpu(),
    )(e, alpha0, final0, can_skip, s_valid, x_lens, y_lens)
