"""Pallas TPU kernels for the hand-tuned hot spots.

The reference hand-schedules fused CUDA kernels for exactly these spots —
the LSTM/GRU cell update (/root/reference/paddle/cuda/src/hl_cuda_lstm.cu,
hl_gpu_lstm.cuh: one kernel applies all four gate activations + the cell
recurrence in registers instead of separate elementwise launches). The
Pallas analogs keep the big matmul on the MXU (outside the kernel, where
XLA tiles it) and fuse the post-matmul gate math + aliveness masking into
one VMEM-resident pass.

Default OFF (flag ``use_pallas_rnn``): XLA's own elementwise fusion already
fuses this chain well, so the kernels are an opt-in tuning surface and the
demonstration of the custom-kernel escape hatch; numerics are pinned
against the jnp path (tests/test_pallas_kernels.py, interpret mode on CPU,
native on TPU). Gradients use jax.custom_vjp with a jnp backward — the
backward chain is elementwise and XLA-fused regardless.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl


def _on_cpu():
    return jax.default_backend() == "cpu"


def _lstm_cell_kernel(gates_ref, c_prev_ref, h_prev_ref, alive_ref,
                      h_ref, c_ref):
    """One fused pass: gates [b, 4H] -> (h, c) [b, H], masked by alive.
    Gate column order [i, f, c, o] (this framework's documented layout)."""
    gates = gates_ref[...]
    h4 = gates.shape[-1]
    hdim = h4 // 4
    c_prev = c_prev_ref[...]
    h_prev = h_prev_ref[...]
    alive = alive_ref[...]
    i = jax.nn.sigmoid(gates[:, :hdim])
    f = jax.nn.sigmoid(gates[:, hdim:2 * hdim])
    cand = jnp.tanh(gates[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(gates[:, 3 * hdim:])
    c = f * c_prev + i * cand
    h = o * jnp.tanh(c)
    h_ref[...] = alive * h + (1 - alive) * h_prev
    c_ref[...] = alive * c + (1 - alive) * c_prev


def _lstm_cell_jnp(gates, c_prev, h_prev, alive):
    hdim = gates.shape[-1] // 4
    i = jax.nn.sigmoid(gates[:, :hdim])
    f = jax.nn.sigmoid(gates[:, hdim:2 * hdim])
    cand = jnp.tanh(gates[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(gates[:, 3 * hdim:])
    c = f * c_prev + i * cand
    h = o * jnp.tanh(c)
    return (alive * h + (1 - alive) * h_prev,
            alive * c + (1 - alive) * c_prev)


@jax.custom_vjp
def fused_lstm_cell(gates, c_prev, h_prev, alive):
    """Fused LSTM cell (standard sigmoid/tanh activations): pallas forward,
    jnp custom-vjp backward. All operands [b, ·]; alive [b, 1]."""
    b, h4 = gates.shape
    hdim = h4 // 4
    return pl.pallas_call(
        _lstm_cell_kernel,
        out_shape=(jax.ShapeDtypeStruct((b, hdim), gates.dtype),
                   jax.ShapeDtypeStruct((b, hdim), gates.dtype)),
        interpret=_on_cpu(),
    )(gates, c_prev, h_prev, alive)


def _fused_fwd(gates, c_prev, h_prev, alive):
    out = fused_lstm_cell(gates, c_prev, h_prev, alive)
    return out, (gates, c_prev, h_prev, alive)


def _fused_bwd(res, cts):
    gates, c_prev, h_prev, alive = res
    _, vjp = jax.vjp(_lstm_cell_jnp, gates, c_prev, h_prev, alive)
    return vjp(cts)


fused_lstm_cell.defvjp(_fused_fwd, _fused_bwd)


def _gru_cell_kernel(u_in_ref, c_in_ref, h_prev_ref, w_c_ref, alive_ref,
                     h_ref):
    """Fused GRU cell: u_in [b, H] is the update-gate preactivation, c_in
    [b, H] the candidate's input projection; the candidate still needs
    (r*h_prev) @ W_c which arrives via w_c (that matmul stays outside on
    the MXU, with the reset gate applied before it). One pass computes the
    update gate, the candidate epilogue, and the masked recurrence."""
    h_prev = h_prev_ref[...]
    rc = w_c_ref[...]
    alive = alive_ref[...]
    u = jax.nn.sigmoid(u_in_ref[...])
    cand = jnp.tanh(c_in_ref[...] + rc)
    h = u * cand + (1 - u) * h_prev
    h_ref[...] = alive * h + (1 - alive) * h_prev


def _gru_cell_jnp(u_in, c_in, h_prev, rc, alive):
    u = jax.nn.sigmoid(u_in)
    cand = jnp.tanh(c_in + rc)
    h = u * cand + (1 - u) * h_prev
    return alive * h + (1 - alive) * h_prev


@jax.custom_vjp
def fused_gru_cell(u_in, c_in, h_prev, rc, alive):
    b, hdim = u_in.shape
    return pl.pallas_call(
        _gru_cell_kernel,
        out_shape=jax.ShapeDtypeStruct((b, hdim), u_in.dtype),
        interpret=_on_cpu(),
    )(u_in, c_in, h_prev, rc, alive)


def _gru_fwd(u_in, c_in, h_prev, rc, alive):
    return fused_gru_cell(u_in, c_in, h_prev, rc, alive), \
        (u_in, c_in, h_prev, rc, alive)


def _gru_bwd(res, ct):
    u_in, c_in, h_prev, rc, alive = res
    _, vjp = jax.vjp(_gru_cell_jnp, u_in, c_in, h_prev, rc, alive)
    return vjp(ct)


fused_gru_cell.defvjp(_gru_fwd, _gru_bwd)
