"""Attention ops: causal self-attention and its serving-time split.

``causal_self_attention`` is the model-authoring op (the dense analog of
the reference's scaled_dot_product_attention composition): one op per
transformer layer, Q/K/V already projected by ``fc`` layers. At serving
time the generation engine (serving/generate/decode_engine.py) clones the
saved program and rewrites every causal_self_attention site into one of
two phase ops over a PAGED KV arena (the layer *Ragged Paged Attention*
assumes exists above the kernel):

* ``prefill_attention`` — the same causal attention over the prompt
  window, plus a scatter of every position's K/V rows into the arena at
  ``SlotMapping`` (flat ``block*block_size+offset`` slots; out-of-range
  sentinel slots — padding positions — are dropped by the scatter).
* ``paged_attention`` — the fixed-shape ``[max_seqs, 1]`` decode step:
  write the new token's K/V row, then attend its Q against the sequence's
  context gathered THROUGH its block table. Ragged in-flight sequences
  share the one executable: each row sees only its own ``ContextLens``
  prefix, and rows with ``ContextLens == 0`` (inactive slots) write
  nothing (sentinel slot) and emit zeros.

Both phase ops are row-independent (no cross-row reductions), which is
what makes continuous batching BITWISE equal to one-sequence-at-a-time
decode: a sequence's logits depend only on its own tokens, block table
and the arena rows it wrote, never on which other rows share the batch.

The arena update is functional (the ops output the updated KCache/VCache
under the SAME variable names, the optimizer-op in-place convention); the
engine feeds the arena arrays in and fetches them back as device arrays,
so no host round trip occurs. On TPU the natural next step is donating
the arena buffers; at current arena sizes the copy is noise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import data_of
from .sequence_ops import _vjp_grad


def _split_heads(x, num_heads):
    b, t, e = x.shape
    if e % num_heads:
        raise ValueError(
            f"attention hidden size {e} is not divisible by num_heads "
            f"{num_heads}")
    return x.reshape(b, t, num_heads, e // num_heads)


def _causal_mha(q, k, v, num_heads):
    """Plain causal multi-head attention: [b, T, E] x3 -> [b, T, E]."""
    qh = _split_heads(q, num_heads)
    kh = _split_heads(k, num_heads)
    vh = _split_heads(v, num_heads)
    d = qh.shape[-1]
    scores = jnp.einsum("bthd,bshd->bhts", qh, kh) * (d ** -0.5)
    t = q.shape[1]
    causal = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(causal[None, None], scores, -1e9)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", p, vh)
    return out.reshape(q.shape)


@register_op("causal_self_attention",
             grad=_vjp_grad("causal_self_attention", in_slots=("Q", "K", "V")))
def causal_self_attention(ctx):
    """Causal MHA over a [b, T, E] window — the training/export form the
    generation engine's program split rewrites per phase."""
    q = data_of(ctx.input("Q"))
    k = data_of(ctx.input("K"))
    v = data_of(ctx.input("V"))
    ctx.set_output("Out", _causal_mha(q, k, v, int(ctx.attr("num_heads"))))


@register_op("causal_self_attention_grad")
def causal_self_attention_grad(ctx):
    q = data_of(ctx.input("Q"))
    k = data_of(ctx.input("K"))
    v = data_of(ctx.input("V"))
    h = int(ctx.attr("num_heads"))
    d = data_of(ctx.input("Out@GRAD"))
    _, vjp = jax.vjp(lambda a, b, c: _causal_mha(a, b, c, h), q, k, v)
    dq, dk, dv = vjp(d)
    ctx.set_output("Q@GRAD", dq)
    ctx.set_output("K@GRAD", dk)
    ctx.set_output("V@GRAD", dv)


def _scatter_rows(cache, slots, rows):
    """Write ``rows`` [n, H, D] into the arena [nb, bs, H, D] at flat slots
    [n] (block*block_size + offset). Out-of-range slots (the padding / idle
    sentinel, ``num_blocks * block_size``) are DROPPED — never a wrapped or
    clamped write into some victim sequence's block."""
    nb, bs = cache.shape[0], cache.shape[1]
    flat = cache.reshape((nb * bs,) + cache.shape[2:])
    flat = flat.at[slots].set(rows, mode="drop")
    return flat.reshape(cache.shape)


@register_op("prefill_attention")
def prefill_attention(ctx):
    """Phase 1 of the serving split: causal attention over the (padded)
    prompt window + K/V scatter into the paged arena. Padding positions map
    to the out-of-range sentinel slot and write nothing; because padding
    sits AFTER the real prompt and the mask is causal, every real position's
    output is independent of the padding, so only slot mapping — not an
    extra length mask — is needed."""
    q = data_of(ctx.input("Q"))
    k = data_of(ctx.input("K"))
    v = data_of(ctx.input("V"))
    h = int(ctx.attr("num_heads"))
    kc = data_of(ctx.input("KCache"))
    vc = data_of(ctx.input("VCache"))
    slots = data_of(ctx.input("SlotMapping")).astype(jnp.int32).reshape(-1)
    kh = _split_heads(k, h).reshape((-1,) + kc.shape[2:])
    vh = _split_heads(v, h).reshape((-1,) + vc.shape[2:])
    ctx.set_output("KCacheOut", _scatter_rows(kc, slots, kh))
    ctx.set_output("VCacheOut", _scatter_rows(vc, slots, vh))
    ctx.set_output("Out", _causal_mha(q, k, v, h))


@register_op("paged_attention")
def paged_attention(ctx):
    """Phase 2 of the serving split: one decode step for every slot of the
    fixed-shape batch. Q/K/V are [max_seqs, 1, E]; the new K/V row is
    written at ``SlotMapping`` [max_seqs] first (sentinel = no write), then
    each row's Q attends over the UPDATED arena gathered through its
    ``BlockTables`` row, masked to its ``ContextLens`` prefix (which counts
    the just-written token). Inactive rows (ContextLens == 0) output
    zeros."""
    q = data_of(ctx.input("Q"))
    k = data_of(ctx.input("K"))
    v = data_of(ctx.input("V"))
    h = int(ctx.attr("num_heads"))
    kc = data_of(ctx.input("KCache"))
    vc = data_of(ctx.input("VCache"))
    bt = data_of(ctx.input("BlockTables")).astype(jnp.int32)   # [b, P]
    ctx_lens = data_of(ctx.input("ContextLens")).astype(jnp.int32)  # [b]
    slots = data_of(ctx.input("SlotMapping")).astype(jnp.int32).reshape(-1)

    nb, bs = kc.shape[0], kc.shape[1]
    kh = _split_heads(k, h).reshape((-1,) + kc.shape[2:])      # [b, H, D]
    vh = _split_heads(v, h).reshape((-1,) + vc.shape[2:])
    kc = _scatter_rows(kc, slots, kh)
    vc = _scatter_rows(vc, slots, vh)
    ctx.set_output("KCacheOut", kc)
    ctx.set_output("VCacheOut", vc)

    b, p = bt.shape
    # flat arena indices of every context position this row may see:
    # [b, P, bs] -> [b, C]; unused table entries gather garbage that the
    # ContextLens mask below excludes from the softmax
    idx = (bt[:, :, None] * bs
           + jnp.arange(bs, dtype=jnp.int32)[None, None, :]).reshape(b, -1)
    kf = kc.reshape((nb * bs,) + kc.shape[2:])
    vf = vc.reshape((nb * bs,) + vc.shape[2:])
    kctx = kf[idx]                                             # [b, C, H, D]
    vctx = vf[idx]
    qh = _split_heads(q, h)[:, 0]                              # [b, H, D]
    d = qh.shape[-1]
    scores = jnp.einsum("bhd,bchd->bhc", qh, kctx) * (d ** -0.5)
    live = jnp.arange(idx.shape[1], dtype=jnp.int32)[None, :] \
        < ctx_lens[:, None]                                    # [b, C]
    scores = jnp.where(live[:, None, :], scores, -1e9)
    # a fully-masked (inactive) row softmaxes to uniform weights over
    # garbage — finite, never NaN — and is zeroed by the active mask below
    pw = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhc,bchd->bhd", pw, vctx).reshape(b, 1, -1)
    active = (ctx_lens > 0)[:, None, None]
    ctx.set_output("Out", jnp.where(active, out, 0.0))
