"""Attention ops: causal self-attention and its serving-time split.

``causal_self_attention`` is the model-authoring op (the dense analog of
the reference's scaled_dot_product_attention composition): one op per
transformer layer, Q/K/V already projected by ``fc`` layers. At serving
time the generation engine (serving/generate/decode_engine.py) clones the
saved program and rewrites every causal_self_attention site into one of
two phase ops over a PAGED KV arena (the layer *Ragged Paged Attention*
assumes exists above the kernel):

* ``prefill_attention`` — the same causal attention over the prompt
  window, plus a scatter of every position's K/V rows into the arena at
  ``SlotMapping`` (flat ``block*block_size+offset`` slots; out-of-range
  sentinel slots — padding positions — are dropped by the scatter).
* ``chunked_prefill_attention`` — the PARTIAL prefill: a chunk of the
  prompt whose earlier positions already live in the arena (a cached
  shared prefix, or this prompt's previous chunks). The chunk's K/V rows
  scatter in first, then every chunk query attends over the arena
  context gathered through the sequence's block table, masked causally
  at its ABSOLUTE position (``ChunkStart`` + window index) — so the
  math a tail position sees is element-for-element the full-window
  causal attention, which is what makes cached-prefix token streams
  bitwise equal to cold ones.
* ``paged_attention`` — the fixed-shape ``[max_seqs, 1]`` decode step:
  write the new token's K/V row, then attend its Q against the sequence's
  context gathered THROUGH its block table. Ragged in-flight sequences
  share the one executable: each row sees only its own ``ContextLens``
  prefix, and rows with ``ContextLens == 0`` (inactive slots) write
  nothing (sentinel slot) and emit zeros. The gather-then-attend form
  is the jnp twin of the Pallas ragged paged-attention kernel
  (ops/pallas/paged_attention.py): under a Pallas ``kernel_tier`` the
  decode step attends straight through the arena with scalar-prefetched
  block tables instead of materializing the gathered
  ``[max_seqs, max_ctx]`` context (silent jnp fallback on unsupported
  shapes, like every kernel in the tier).

Both phase ops are row-independent (no cross-row reductions), which is
what makes continuous batching BITWISE equal to one-sequence-at-a-time
decode: a sequence's logits depend only on its own tokens, block table
and the arena rows it wrote, never on which other rows share the batch.

The arena update is functional (the ops output the updated KCache/VCache
under the SAME variable names, the optimizer-op in-place convention); the
engine feeds the arena arrays in and fetches them back as device arrays,
so no host round trip occurs. On TPU the natural next step is donating
the arena buffers; at current arena sizes the copy is noise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import data_of
from .sequence_ops import _vjp_grad


def _split_heads(x, num_heads):
    b, t, e = x.shape
    if e % num_heads:
        raise ValueError(
            f"attention hidden size {e} is not divisible by num_heads "
            f"{num_heads}")
    return x.reshape(b, t, num_heads, e // num_heads)


def _causal_mha(q, k, v, num_heads):
    """Plain causal multi-head attention: [b, T, E] x3 -> [b, T, E]."""
    qh = _split_heads(q, num_heads)
    kh = _split_heads(k, num_heads)
    vh = _split_heads(v, num_heads)
    d = qh.shape[-1]
    scores = jnp.einsum("bthd,bshd->bhts", qh, kh) * (d ** -0.5)
    t = q.shape[1]
    causal = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(causal[None, None], scores, -1e9)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", p, vh)
    return out.reshape(q.shape)


@register_op("causal_self_attention",
             grad=_vjp_grad("causal_self_attention", in_slots=("Q", "K", "V")))
def causal_self_attention(ctx):
    """Causal MHA over a [b, T, E] window — the training/export form the
    generation engine's program split rewrites per phase."""
    q = data_of(ctx.input("Q"))
    k = data_of(ctx.input("K"))
    v = data_of(ctx.input("V"))
    ctx.set_output("Out", _causal_mha(q, k, v, int(ctx.attr("num_heads"))))


@register_op("causal_self_attention_grad")
def causal_self_attention_grad(ctx):
    q = data_of(ctx.input("Q"))
    k = data_of(ctx.input("K"))
    v = data_of(ctx.input("V"))
    h = int(ctx.attr("num_heads"))
    d = data_of(ctx.input("Out@GRAD"))
    _, vjp = jax.vjp(lambda a, b, c: _causal_mha(a, b, c, h), q, k, v)
    dq, dk, dv = vjp(d)
    ctx.set_output("Q@GRAD", dq)
    ctx.set_output("K@GRAD", dk)
    ctx.set_output("V@GRAD", dv)


def _scatter_rows(cache, slots, rows):
    """Write ``rows`` [n, H, D] into the arena [nb, bs, H, D] at flat slots
    [n] (block*block_size + offset). Out-of-range slots (the padding / idle
    sentinel, ``num_blocks * block_size``) are DROPPED — never a wrapped or
    clamped write into some victim sequence's block."""
    nb, bs = cache.shape[0], cache.shape[1]
    flat = cache.reshape((nb * bs,) + cache.shape[2:])
    flat = flat.at[slots].set(rows, mode="drop")
    return flat.reshape(cache.shape)


@register_op("prefill_attention")
def prefill_attention(ctx):
    """Phase 1 of the serving split: causal attention over the (padded)
    prompt window + K/V scatter into the paged arena. Padding positions map
    to the out-of-range sentinel slot and write nothing; because padding
    sits AFTER the real prompt and the mask is causal, every real position's
    output is independent of the padding, so only slot mapping — not an
    extra length mask — is needed."""
    q = data_of(ctx.input("Q"))
    k = data_of(ctx.input("K"))
    v = data_of(ctx.input("V"))
    h = int(ctx.attr("num_heads"))
    kc = data_of(ctx.input("KCache"))
    vc = data_of(ctx.input("VCache"))
    slots = data_of(ctx.input("SlotMapping")).astype(jnp.int32).reshape(-1)
    kh = _split_heads(k, h).reshape((-1,) + kc.shape[2:])
    vh = _split_heads(v, h).reshape((-1,) + vc.shape[2:])
    ctx.set_output("KCacheOut", _scatter_rows(kc, slots, kh))
    ctx.set_output("VCacheOut", _scatter_rows(vc, slots, vh))
    ctx.set_output("Out", _causal_mha(q, k, v, h))


def _gather_context(cache, bt):
    """Arena rows of every context position a block-table row may see:
    cache [nb, bs, H, D], bt [b, P] -> [b, P*bs, H, D] ordered by
    position (table order x in-block offset). Unused table entries
    gather garbage the caller's mask excludes."""
    nb, bs = cache.shape[0], cache.shape[1]
    idx = (bt[:, :, None] * bs
           + jnp.arange(bs, dtype=jnp.int32)[None, None, :]) \
        .reshape(bt.shape[0], -1)
    flat = cache.reshape((nb * bs,) + cache.shape[2:])
    return flat[idx]


@register_op("chunked_prefill_attention")
def chunked_prefill_attention(ctx):
    """Partial prefill over a prompt CHUNK whose earlier positions are
    already in the arena (cached shared prefix and/or previous chunks).
    Q/K/V are the [b, T, E] chunk window; the chunk's K/V rows scatter in
    at ``SlotMapping`` first (sentinel = padding, no write), then every
    window position i attends over the arena context gathered through
    ``BlockTables``, masked causally at its absolute position
    ``ChunkStart + i``. ChunkStart == 0 and an empty arena reduce this
    to full-window causal prefill (the parity anchor)."""
    q = data_of(ctx.input("Q"))
    k = data_of(ctx.input("K"))
    v = data_of(ctx.input("V"))
    h = int(ctx.attr("num_heads"))
    kc = data_of(ctx.input("KCache"))
    vc = data_of(ctx.input("VCache"))
    bt = data_of(ctx.input("BlockTables")).astype(jnp.int32)   # [b, P]
    start = data_of(ctx.input("ChunkStart")).astype(jnp.int32) \
        .reshape(-1)                                           # [b]
    slots = data_of(ctx.input("SlotMapping")).astype(jnp.int32).reshape(-1)

    kh = _split_heads(k, h).reshape((-1,) + kc.shape[2:])
    vh = _split_heads(v, h).reshape((-1,) + vc.shape[2:])
    kc = _scatter_rows(kc, slots, kh)
    vc = _scatter_rows(vc, slots, vh)
    ctx.set_output("KCacheOut", kc)
    ctx.set_output("VCacheOut", vc)

    # one lowering today, but the dispatch still registers the shape key
    # (and capture records it), so a future pallas chunked-prefill
    # variant tunes in with no dispatch-site change
    from .autotune import dispatch_variant, make_key
    dispatch_variant(
        "chunked_prefill_attention",
        make_key(q=tuple(q.shape), kc=tuple(kc.shape),
                 tables=int(bt.shape[1]), heads=h, dtype=str(q.dtype)),
        {"jnp": True})

    kctx = _gather_context(kc, bt)                             # [b, C, H, D]
    vctx = _gather_context(vc, bt)
    qh = _split_heads(q, h)                                    # [b, T, H, D]
    d = qh.shape[-1]
    t = q.shape[1]
    scores = jnp.einsum("bthd,bchd->bhtc", qh, kctx) * (d ** -0.5)
    qpos = start[:, None] + jnp.arange(t, dtype=jnp.int32)[None]  # [b, T]
    cpos = jnp.arange(kctx.shape[1], dtype=jnp.int32)
    # same mask value (-1e9) and softmax form as _causal_mha: a masked
    # slot contributes exp(-1e9 - max) == 0.0 exactly, so the extra
    # never-visible arena slots change no real position's output bits
    visible = cpos[None, None] <= qpos[:, :, None]             # [b, T, C]
    scores = jnp.where(visible[:, None], scores, -1e9)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhtc,bchd->bthd", p, vctx)
    ctx.set_output("Out", out.reshape(q.shape))


@register_op("paged_attention")
def paged_attention(ctx):
    """Phase 2 of the serving split: one decode step for every slot of the
    fixed-shape batch. Q/K/V are [max_seqs, 1, E]; the new K/V row is
    written at ``SlotMapping`` [max_seqs] first (sentinel = no write), then
    each row's Q attends over the UPDATED arena gathered through its
    ``BlockTables`` row, masked to its ``ContextLens`` prefix (which counts
    the just-written token). Inactive rows (ContextLens == 0) output
    zeros. Under a Pallas ``kernel_tier`` the attend rides the ragged
    paged-attention kernel (scalar-prefetched block tables, no gathered
    context materialized); unsupported shapes fall back to the jnp twin
    silently with a ``fallback_counts()`` bump."""
    q = data_of(ctx.input("Q"))
    k = data_of(ctx.input("K"))
    v = data_of(ctx.input("V"))
    h = int(ctx.attr("num_heads"))
    kc = data_of(ctx.input("KCache"))
    vc = data_of(ctx.input("VCache"))
    bt = data_of(ctx.input("BlockTables")).astype(jnp.int32)   # [b, P]
    ctx_lens = data_of(ctx.input("ContextLens")).astype(jnp.int32)  # [b]
    slots = data_of(ctx.input("SlotMapping")).astype(jnp.int32).reshape(-1)

    kh = _split_heads(k, h).reshape((-1,) + kc.shape[2:])      # [b, H, D]
    vh = _split_heads(v, h).reshape((-1,) + vc.shape[2:])
    kc = _scatter_rows(kc, slots, kh)
    vc = _scatter_rows(vc, slots, vh)
    ctx.set_output("KCacheOut", kc)
    ctx.set_output("VCacheOut", vc)

    from .autotune import dispatch_variant, make_key
    from .pallas import kernel_span
    from .pallas import paged_attention as pa

    qh = _split_heads(q, h)[:, 0]                              # [b, H, D]
    b = bt.shape[0]
    key = make_key(q=tuple(qh.shape), kc=tuple(kc.shape),
                   tables=int(bt.shape[1]), dtype=str(qh.dtype))
    choice = dispatch_variant("paged_attention", key, {
        "jnp": True,
        "pallas": pa.paged_attention_supported(qh, kc, bt),
    })
    if choice == "pallas":
        with kernel_span("pallas", "paged_attention"):
            out = pa.paged_attention_pallas(qh, kc, vc, bt, ctx_lens)
    else:
        with kernel_span("jnp", "paged_attention"):
            out = pa.paged_attention_jnp(qh, kc, vc, bt, ctx_lens)
    ctx.set_output("Out", out.reshape(b, 1, -1))
