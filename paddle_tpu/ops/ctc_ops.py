"""CTC ops: warpctc (CTC loss), ctc_align, edit_distance.

Reference: /root/reference/paddle/fluid/operators/warpctc_op.{h,cc} (dynloads
the warp-ctc CUDA library, ragged logits + ragged labels → per-sequence loss;
operators/math/sequence_padding.h converts ragged↔padded for it),
ctc_align_op.h (merge repeated tokens then drop blanks), edit_distance_op.h
(Levenshtein between hypothesis and reference sequences).

TPU-native: the warp-ctc library is replaced by a log-space forward algorithm
(alpha recurrence over the 2U+1 blank-interleaved label sequence) expressed as
ONE masked lax.scan over time for the whole padded batch — XLA fuses it; the
gradient falls out of jax.vjp over the same scan, replacing warp-ctc's
hand-written backward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op, OpSpec
from .common import G, data_of

_NEG = -1e30


def _ctc_loss(logits, x_lens, labels, y_lens, blank):
    """logits [b, T, C] unnormalized; labels [b, U] int; returns [b, 1].
    Dispatches to the Pallas whole-recurrence kernel under the kernel
    tier (legacy use_pallas_ctc still honored; backward always runs the
    scan path via custom_vjp, like the RNN cells). T==1 sequences have no
    recurrence to fuse and route to the scan path (counted fallback)."""
    from .pallas import use_pallas, kernel_span
    if use_pallas("ctc", logits.shape[1] > 1):
        with kernel_span("pallas", "ctc"):
            return _ctc_loss_pallas(logits, x_lens, labels, y_lens, blank)
    return _ctc_loss_scan(logits, x_lens, labels, y_lens, blank)


def _ctc_loss_scan(logits, x_lens, labels, y_lens, blank):
    b, T, C = logits.shape
    U = labels.shape[1]
    S = 2 * U + 1
    logp = jax.nn.log_softmax(logits, axis=-1)
    logp = jnp.swapaxes(logp, 0, 1)                       # [T, b, C]

    # blank-interleaved extended labels z: [b, S]
    z = jnp.full((b, S), blank, dtype=jnp.int32)
    z = z.at[:, 1::2].set(labels.astype(jnp.int32))
    s_valid = jnp.arange(S)[None, :] < (2 * y_lens[:, None] + 1)

    # can we skip from s-2 (different label and not blank)?
    z_prev2 = jnp.pad(z, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (jnp.arange(S)[None, :] % 2 == 1) & (z != z_prev2)

    def emit(t_logp, zz):
        return jnp.take_along_axis(t_logp, zz, axis=1)    # [b, S]

    alpha0 = jnp.full((b, S), _NEG)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    first_lab = emit(logp[0], z)[:, 1]
    alpha0 = alpha0.at[:, 1].set(jnp.where(y_lens > 0, first_lab, _NEG))
    alpha0 = jnp.where(s_valid, alpha0, _NEG)

    def final_of(alpha, ylen):
        last = 2 * ylen            # index of final blank
        a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
        a_lab = jnp.take_along_axis(alpha,
                                    jnp.maximum(last - 1, 0)[:, None],
                                    axis=1)[:, 0]
        a_lab = jnp.where(ylen > 0, a_lab, _NEG)
        return jnp.logaddexp(a_last, a_lab)

    init = dict(alpha=alpha0,
                final=jnp.where(x_lens == 1, final_of(alpha0, y_lens), _NEG))

    def step(c, inp):
        t, lp = inp
        a = c["alpha"]
        a1 = jnp.pad(a, ((0, 0), (1, 0)), constant_values=_NEG)[:, :S]
        a2 = jnp.pad(a, ((0, 0), (2, 0)), constant_values=_NEG)[:, :S]
        a2 = jnp.where(can_skip, a2, _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(a, a1), a2)
        nxt = merged + emit(lp, z)
        nxt = jnp.where(s_valid, nxt, _NEG)
        alive = (t < x_lens)[:, None]
        alpha = jnp.where(alive, nxt, a)
        final = jnp.where(t == x_lens - 1, final_of(alpha, y_lens),
                          c["final"])
        return dict(alpha=alpha, final=final), None

    if T > 1:
        c, _ = jax.lax.scan(step, init, (jnp.arange(1, T), logp[1:]))
    else:
        c = init
    return (-c["final"])[:, None]


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _ctc_loss_pallas(logits, x_lens, labels, y_lens, blank):
    """Pallas whole-recurrence CTC forward (alpha VMEM-resident across T,
    the warp-ctc shared-memory pattern, ops/pallas/ctc.ctc_alpha_pallas);
    the emit gather, masks and t=0 init are precomputed here where XLA owns
    them. Backward = jax.vjp of the scan path (custom_vjp)."""
    from .pallas.ctc import ctc_alpha_pallas

    b, T, C = logits.shape
    U = labels.shape[1]
    S = 2 * U + 1
    logp = jax.nn.log_softmax(logits, axis=-1)
    logp = jnp.swapaxes(logp, 0, 1)                       # [T, b, C]

    z = jnp.full((b, S), blank, dtype=jnp.int32)
    z = z.at[:, 1::2].set(labels.astype(jnp.int32))
    s_valid = jnp.arange(S)[None, :] < (2 * y_lens[:, None] + 1)
    z_prev2 = jnp.pad(z, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (jnp.arange(S)[None, :] % 2 == 1) & (z != z_prev2)

    alpha0 = jnp.full((b, S), _NEG)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    first_lab = jnp.take_along_axis(logp[0], z, axis=1)[:, 1]
    alpha0 = alpha0.at[:, 1].set(jnp.where(y_lens > 0, first_lab, _NEG))
    alpha0 = jnp.where(s_valid, alpha0, _NEG)

    last = 2 * y_lens
    a_last = jnp.take_along_axis(alpha0, last[:, None], axis=1)[:, 0]
    a_lab = jnp.take_along_axis(alpha0, jnp.maximum(last - 1, 0)[:, None],
                                axis=1)[:, 0]
    a_lab = jnp.where(y_lens > 0, a_lab, _NEG)
    final0 = jnp.where(x_lens == 1, jnp.logaddexp(a_last, a_lab), _NEG)

    sp = max(8, -(-S // 8) * 8)              # pad S to a sublane multiple
    pad = sp - S
    e = jnp.swapaxes(jnp.take_along_axis(
        logp, jnp.broadcast_to(z[None], (T, b, S)), axis=2), 0, 1)
    e = jnp.pad(e, ((0, 0), (0, 0), (0, pad)), constant_values=_NEG)
    a0 = jnp.pad(alpha0, ((0, 0), (0, pad)), constant_values=_NEG)
    cs = jnp.pad(can_skip.astype(logp.dtype), ((0, 0), (0, pad)))
    sv = jnp.pad(s_valid.astype(logp.dtype), ((0, 0), (0, pad)))
    return ctc_alpha_pallas(
        e, a0, final0[:, None].astype(logp.dtype), cs, sv,
        x_lens.astype(jnp.int32).reshape(b, 1),
        y_lens.astype(jnp.int32).reshape(b, 1))


def _ctc_pallas_fwd(logits, x_lens, labels, y_lens, blank):
    return (_ctc_loss_pallas(logits, x_lens, labels, y_lens, blank),
            (logits, x_lens, labels, y_lens))


def _ctc_pallas_bwd(blank, res, ct):
    logits, x_lens, labels, y_lens = res
    _, vjp = jax.vjp(
        lambda lg: _ctc_loss_scan(lg, x_lens, labels, y_lens, blank), logits)
    return (vjp(ct)[0], None, None, None)


_ctc_loss_pallas.defvjp(_ctc_pallas_fwd, _ctc_pallas_bwd)


def _warpctc_grad_maker(op):
    return [OpSpec(
        "warpctc_grad",
        {"Logits": op.input("Logits"), "Label": op.input("Label"),
         "Loss@GRAD": G(op.output("Loss"))},
        {"Logits@GRAD": G(op.input("Logits"))}, dict(op.attrs))]


def _ctc_inputs(ctx):
    lv = ctx.input("Logits")
    if not isinstance(lv, LoDArray):
        raise TypeError("warpctc expects LoD logits")
    lab = ctx.input("Label")
    if not isinstance(lab, LoDArray):
        raise TypeError("warpctc expects a LoD label")
    labels = lab.data
    if labels.ndim == 3:
        labels = labels[..., 0]
    return lv, labels.astype(jnp.int32), lab.lens


@register_op("warpctc", grad=_warpctc_grad_maker)
def warpctc(ctx):
    lv, labels, y_lens = _ctc_inputs(ctx)
    blank = int(ctx.attr("blank", 0))
    # norm_by_times does NOT scale the forward Loss — the reference scales
    # only the logits gradient in the backward kernel (warpctc_op.h:217-223,
    # ScaleLoDTensorFunctor) and returns the unscaled loss.
    loss = _ctc_loss(lv.data, lv.lens, labels, y_lens, blank)
    ctx.set_output("Loss", loss)


@register_op("warpctc_grad")
def warpctc_grad(ctx):
    lv, labels, y_lens = _ctc_inputs(ctx)
    blank = int(ctx.attr("blank", 0))
    d = data_of(ctx.input("Loss@GRAD"))

    def f(lg):
        return _ctc_loss(lg, lv.lens, labels, y_lens, blank)

    _, vjp = jax.vjp(f, lv.data)
    dlogits = vjp(d)[0]
    if ctx.attr("norm_by_times", False):
        # 1/T scaling applied to the logits gradient only (warpctc_op.h:217)
        dlogits = dlogits / jnp.maximum(
            lv.lens[:, None, None], 1).astype(dlogits.dtype)
    ctx.set_output("Logits@GRAD", LoDArray(dlogits, lv.lens))


@register_op("ctc_align")
def ctc_align(ctx):
    """Merge repeated tokens, drop blanks, compact (ctc_align_op.h)."""
    x = ctx.input("Input")
    if not isinstance(x, LoDArray):
        raise TypeError("ctc_align expects LoD input")
    blank = int(ctx.attr("blank", 0))
    merge = bool(ctx.attr("merge_repeated", True))
    d = x.data
    flat = d if d.ndim == 2 else d[..., 0]
    valid = jnp.arange(flat.shape[1])[None, :] < x.lens[:, None]
    keep = valid & (flat != blank)
    if merge:
        prev = jnp.pad(flat, ((0, 0), (1, 0)), constant_values=-1)[:, :-1]
        keep = keep & (flat != prev)
    order = jnp.argsort(~keep, axis=1, stable=True)
    comp = jnp.take_along_axis(flat, order, axis=1)
    lens = keep.sum(axis=1).astype(jnp.int32)
    comp = comp * (jnp.arange(comp.shape[1])[None, :]
                   < lens[:, None]).astype(comp.dtype)
    ctx.set_output("Output", LoDArray(comp if d.ndim == 2 else comp[..., None],
                                      lens))


@register_op("edit_distance")
def edit_distance(ctx):
    """Levenshtein distance per (hypothesis, reference) sequence pair
    (edit_distance_op.h). normalized attr divides by reference length."""
    hyp = ctx.input("Hyps")
    ref = ctx.input("Refs")
    if not isinstance(hyp, LoDArray) or not isinstance(ref, LoDArray):
        raise TypeError("edit_distance expects LoD inputs")
    h = hyp.data if hyp.data.ndim == 2 else hyp.data[..., 0]
    r = ref.data if ref.data.ndim == 2 else ref.data[..., 0]
    hl, rl = hyp.lens, ref.lens
    b, H = h.shape
    R = r.shape[1]

    # DP over hypothesis tokens; row j = distance of hyp prefix vs ref
    # prefix of length j
    row0 = jnp.broadcast_to(jnp.arange(R + 1, dtype=jnp.float32)[None, :],
                            (b, R + 1))

    def step(row, i):
        tok = h[:, i]                                   # [b]
        sub_or_match = row[:, :-1] + (r != tok[:, None]).astype(jnp.float32)
        deletion = row[:, 1:] + 1.0
        new_tail = jnp.minimum(sub_or_match, deletion)
        first = row[:, 0] + 1.0

        def inner(carry, j):
            left = carry
            val = jnp.minimum(new_tail[:, j], left + 1.0)
            return val, val

        _, cols = jax.lax.scan(inner, first, jnp.arange(R))
        new_row = jnp.concatenate([first[:, None],
                                   jnp.swapaxes(cols, 0, 1)], axis=1)
        # rows beyond this hypothesis's length keep the previous row
        alive = (i < hl)[:, None]
        return jnp.where(alive, new_row, row), None

    final_row, _ = jax.lax.scan(step, row0, jnp.arange(H))
    dist = jnp.take_along_axis(final_row, rl[:, None], axis=1)[:, 0]
    if ctx.attr("normalized", False):
        dist = dist / jnp.maximum(rl, 1).astype(dist.dtype)
    ctx.set_output("Out", dist[:, None])
    ctx.set_output("SequenceNum", jnp.asarray([b], jnp.int32))
