"""Convolution / pooling ops.

Reference semantics: /root/reference/paddle/fluid/operators/conv_op.cc
(conv2d, depthwise_conv2d; NCHW input, MCHW filter, strides/paddings/
dilations/groups attrs), conv_transpose_op.cc (filter layout [C_in, C_out,
kh, kw], output size (H-1)*stride - 2*pad + kh), pool_op.cc (max/avg,
global_pooling, ceil_mode; avg divides by the window clipped to the input —
see paddle/fluid/operators/math/pooling.cc Compute loops).

TPU-native design: a conv is ONE ``lax.conv_general_dilated`` — the MXU path —
instead of the reference's im2col+gemm CPU kernel (operators/math/im2col.cc)
and cuDNN dispatch (conv_cudnn_op.cu.cc). Gradients are obtained by
``jax.vjp`` over the same lowering: XLA synthesizes the transposed-conv
backward kernels the reference hand-registered as conv2d_grad, and fuses them
into the step computation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.amp import cast_compute
from ..core.registry import register_op, OpSpec, infer_output
from .common import G, data_of


def _pair(v):
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1] if len(v) > 1 else v[0]))
    return (int(v), int(v))


def _s2d_stem_eligible(x, w, strides, paddings, dilations, groups, df):
    """True when the space-to-depth stem rewrite applies exactly: an NHWC
    stride-2 ungrouped undilated conv over few input channels (the ResNet/VGG
    stem: 7x7/s2 over HxWx3) whose spatial dims are even. At C_in=3 the MXU
    contraction tile is nearly empty; folding the 2x2 pixel blocks into
    channels (C=12, kernel 4x4, stride 1) quadruples lane occupancy for the
    same FLOPs — the standard TPU ResNet stem transform."""
    return (df == "NHWC" and strides == (2, 2) and dilations == (1, 1)
            and groups == 1 and x.ndim == 4 and x.shape[3] <= 4
            and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0
            and w.shape[2] > 1 and w.shape[3] > 1)


def _s2d_stem_conv(x, w, paddings):
    """Exact rewrite of conv2d(k, stride=2, pad) over NHWC x as a stride-1
    conv over the space-to-depth transform of x.

    Derivation: y[i,j] = sum_{p,q,c} x[2i+p-ph, 2j+q-pw, c] * W[o,c,p,q].
    Writing each input row as u = 2(i+m) + a (block row i+m, parity a) gives
    p = 2m + a + ph with m in [-(ph+1)//2, (kh-1-ph)//2]; the filter embeds
    into a zero-padded (2Kh, 2Kw) grid whose (parity, block) regrouping is
    the rearranged stride-1 kernel over the (a,b,c)-packed channels.
    """
    n, h, wd, c = x.shape
    o, _, kh, kw = w.shape
    ph, pw = paddings

    def geom(k, p, size):
        m_min = -((p + 1) // 2)
        m_max = (k - 1 - p) // 2
        kk = m_max - m_min + 1
        out = (size + 2 * p - k) // 2 + 1
        pad_l = -m_min
        pad_r = out - 1 + m_max - (size // 2 - 1)
        off = 2 * (-m_min) - p  # 1 when p is odd, 0 when even
        return kk, pad_l, pad_r, off, out

    kh2, pl_h, pr_h, off_h, _ = geom(kh, ph, h)
    kw2, pl_w, pr_w, off_w, _ = geom(kw, pw, wd)
    if min(pl_h, pr_h, pl_w, pr_w) < 0:
        return None
    # x: [N,H,W,C] -> blocks [N,H/2,2,W/2,2,C] -> [N,H/2,W/2, a*2C+b*C+c]
    x2 = x.reshape(n, h // 2, 2, wd // 2, 2, c)
    x2 = x2.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, wd // 2, 4 * c)
    # filter: embed at (off_h, off_w) inside the (2Kh, 2Kw) grid, regroup
    # [o,c, m,a, n,b] -> [o, (a,b,c), m, n]
    wp = jnp.zeros((o, c, 2 * kh2, 2 * kw2), w.dtype)
    wp = wp.at[:, :, off_h:off_h + kh, off_w:off_w + kw].set(w)
    w2 = wp.reshape(o, c, kh2, 2, kw2, 2)
    w2 = w2.transpose(0, 3, 5, 1, 2, 4).reshape(o, 4 * c, kh2, kw2)
    return lax.conv_general_dilated(
        x2, w2,
        window_strides=(1, 1),
        padding=[(pl_h, pr_h), (pl_w, pr_w)],
        dimension_numbers=("NHWC", "OIHW", "NHWC"))


def _conv2d_compute(x, w, strides, paddings, dilations, groups, df="NCHW"):
    # under AMP both operands become bf16; the TPU MXU still accumulates in
    # float32 internally, so no explicit preferred_element_type is needed
    # (and conv's transpose rule can't differentiate through one).
    # data_format="NHWC" is the TPU-native layout (channels in the lane
    # dimension — BN reductions and elementwise tiles align); the filter
    # stays OIHW for reference checkpoint parity and XLA relayouts it once.
    x, w = cast_compute(x, w)
    from ..core.flags import get_flag
    if (get_flag("conv_space_to_depth")
            and _s2d_stem_eligible(x, w, strides, paddings, dilations, groups,
                                   df)):
        y = _s2d_stem_conv(x, w, paddings)
        if y is not None:
            return y
    return lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(paddings[0], paddings[0]), (paddings[1], paddings[1])],
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=(df, "OIHW", df))


def _channel_dim(df):
    return 3 if df == "NHWC" else 1


def _conv_attrs(ctx_or_op, attr):
    strides = _pair(attr("strides", [1, 1]))
    paddings = _pair(attr("paddings", [0, 0]))
    dilations = _pair(attr("dilations", [1, 1]))
    groups = int(attr("groups", 1) or 1)
    return strides, paddings, dilations, groups


def _conv_df(attr):
    return attr("data_format", "NCHW") or "NCHW"


def _conv_out_size(h, k, pad, stride, dilation=1):
    return (h + 2 * pad - (dilation * (k - 1) + 1)) // stride + 1


def _conv2d_infer(op, block):
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Filter")[0])
    if x.shape is None or w.shape is None:
        return
    s = _pair(op.attrs.get("strides", [1, 1]))
    p = _pair(op.attrs.get("paddings", [0, 0]))
    d = _pair(op.attrs.get("dilations", [1, 1]))
    df = op.attrs.get("data_format", "NCHW") or "NCHW"
    if df == "NHWC":
        n, h, wd, _ = x.shape
    else:
        n, _, h, wd = x.shape
    m, _, kh, kw = w.shape
    oh = _conv_out_size(h, kh, p[0], s[0], d[0])
    ow = _conv_out_size(wd, kw, p[1], s[1], d[1])
    shape = (n, oh, ow, m) if df == "NHWC" else (n, m, oh, ow)
    infer_output(op, block, "Output", shape, dtype=x.dtype)


def _conv2d_grad_maker(op):
    return [OpSpec("conv2d_grad",
                   {"Input": op.input("Input"), "Filter": op.input("Filter"),
                    "Output@GRAD": G(op.output("Output"))},
                   {"Input@GRAD": G(op.input("Input")),
                    "Filter@GRAD": G(op.input("Filter"))},
                   dict(op.attrs))]


@register_op("conv2d", infer_shape=_conv2d_infer, grad=_conv2d_grad_maker)
def conv2d(ctx):
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Filter"))
    strides, paddings, dilations, groups = _conv_attrs(ctx, ctx.attr)
    ctx.set_output("Output",
                   _conv2d_compute(x, w, strides, paddings, dilations, groups,
                                   _conv_df(ctx.attr)))


@register_op("conv2d_grad")
def conv2d_grad(ctx):
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Filter"))
    dy = data_of(ctx.input("Output@GRAD"))
    strides, paddings, dilations, groups = _conv_attrs(ctx, ctx.attr)
    df = _conv_df(ctx.attr)
    from ..core.flags import get_flag
    if (get_flag("conv_1x1_grad_as_dot") and df == "NHWC"
            and w.shape[2:] == (1, 1) and strides == (1, 1)
            and paddings == (0, 0) and dilations == (1, 1) and groups == 1):
        # A/B probe: a 1x1 conv IS a channel matmul, so emit its grads as
        # dot_general instead of jax's transposed convs — the standalone
        # filter-grad dot measured at HBM peak while the in-graph conv
        # emitter ran at ~55% (round-5 profile). Whether XLA's layout
        # assignment cooperates in-graph is what the flag measures.
        xc, wc = cast_compute(x, w)
        dyc = dy.astype(xc.dtype)
        w2 = wc.reshape(wc.shape[0], wc.shape[1])          # [O, I]
        dx = jax.lax.dot_general(dyc, w2, (((3,), (0,)), ((), ())))
        dw = jax.lax.dot_general(dyc, xc, (((0, 1, 2), (0, 1, 2)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ctx.set_output("Input@GRAD", cast_compute(dx))
        ctx.set_output("Filter@GRAD",
                       dw.reshape(w.shape).astype(jnp.float32))
        return
    out, vjp = jax.vjp(
        lambda a, b: _conv2d_compute(a, b, strides, paddings, dilations,
                                     groups, df), x, w)
    # upstream grads may arrive fp32 (loss islands) while the forward ran
    # bf16 under AMP — align the cotangent dtype with the primal output
    dx, dw = vjp(dy.astype(out.dtype))
    # activation grads stay in the compute dtype (the vjp cast boundary
    # upcasts them to fp32 — wasted HBM writes under AMP); the filter grad
    # keeps fp32 as the optimizer's master-gradient
    ctx.set_output("Input@GRAD", cast_compute(dx))
    ctx.set_output("Filter@GRAD", dw)


def _depthwise_grad_maker(op):
    spec = _conv2d_grad_maker(op)[0]
    spec.type = "depthwise_conv2d_grad"
    return [spec]


@register_op("depthwise_conv2d", infer_shape=_conv2d_infer,
             grad=_depthwise_grad_maker)
def depthwise_conv2d(ctx):
    """Reference conv_op.cc registers depthwise_conv2d as conv2d with
    groups == channels (depthwise_conv_op.cu special kernel); here the same
    lax conv with feature_group_count covers it."""
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Filter"))
    strides, paddings, dilations, _ = _conv_attrs(ctx, ctx.attr)
    df = _conv_df(ctx.attr)
    ctx.set_output("Output",
                   _conv2d_compute(x, w, strides, paddings, dilations,
                                   groups=x.shape[_channel_dim(df)], df=df))


@register_op("depthwise_conv2d_grad")
def depthwise_conv2d_grad(ctx):
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Filter"))
    dy = data_of(ctx.input("Output@GRAD"))
    strides, paddings, dilations, _ = _conv_attrs(ctx, ctx.attr)
    df = _conv_df(ctx.attr)
    out, vjp = jax.vjp(
        lambda a, b: _conv2d_compute(a, b, strides, paddings, dilations,
                                     groups=x.shape[_channel_dim(df)], df=df),
        x, w)
    dx, dw = vjp(dy.astype(out.dtype))
    ctx.set_output("Input@GRAD", cast_compute(dx))
    ctx.set_output("Filter@GRAD", dw)


# ---------------------------------------------------------------------------
# conv2d_transpose
# ---------------------------------------------------------------------------

def _conv2d_transpose_compute(x, w, strides, paddings, dilations):
    # Exactly the gradient-of-conv2d wrt its input, which is
    # conv_transpose_op.cc's definition (output = (H-1)*stride - 2*pad +
    # dilated_kernel_extent): dilate the input by stride, swap the paddle
    # [C_in, C_out, kh, kw] filter to OIHW and rotate it 180°, and pad by
    # (kernel_extent - 1 - pad) so XLA sees a plain forward conv.
    kh, kw = w.shape[2], w.shape[3]
    ke_h = dilations[0] * (kh - 1) + 1
    ke_w = dilations[1] * (kw - 1) + 1
    x, w = cast_compute(x, w)
    w_t = jnp.flip(w.transpose(1, 0, 2, 3), axis=(2, 3))
    return lax.conv_general_dilated(
        x, w_t,
        window_strides=(1, 1),
        padding=[(ke_h - 1 - paddings[0],) * 2, (ke_w - 1 - paddings[1],) * 2],
        lhs_dilation=strides,
        rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def _conv2d_transpose_infer(op, block):
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Filter")[0])
    if x.shape is None or w.shape is None:
        return
    s = _pair(op.attrs.get("strides", [1, 1]))
    p = _pair(op.attrs.get("paddings", [0, 0]))
    d = _pair(op.attrs.get("dilations", [1, 1]))
    n, _, h, wd = x.shape
    _, m, kh, kw = w.shape
    ho = (h - 1) * s[0] - 2 * p[0] + (d[0] * (kh - 1) + 1)
    wo = (wd - 1) * s[1] - 2 * p[1] + (d[1] * (kw - 1) + 1)
    infer_output(op, block, "Output", (n, m, ho, wo), dtype=x.dtype)


@register_op("conv2d_transpose", infer_shape=_conv2d_transpose_infer,
             grad=lambda op: [OpSpec(
                 "conv2d_transpose_grad",
                 {"Input": op.input("Input"), "Filter": op.input("Filter"),
                  "Output@GRAD": G(op.output("Output"))},
                 {"Input@GRAD": G(op.input("Input")),
                  "Filter@GRAD": G(op.input("Filter"))},
                 dict(op.attrs))])
def conv2d_transpose(ctx):
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Filter"))
    strides, paddings, dilations, _ = _conv_attrs(ctx, ctx.attr)
    ctx.set_output("Output",
                   _conv2d_transpose_compute(x, w, strides, paddings,
                                             dilations))


@register_op("conv2d_transpose_grad")
def conv2d_transpose_grad(ctx):
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Filter"))
    dy = data_of(ctx.input("Output@GRAD"))
    strides, paddings, dilations, _ = _conv_attrs(ctx, ctx.attr)
    out, vjp = jax.vjp(
        lambda a, b: _conv2d_transpose_compute(a, b, strides, paddings,
                                               dilations), x, w)
    dx, dw = vjp(dy.astype(out.dtype))
    ctx.set_output("Input@GRAD", cast_compute(dx))
    ctx.set_output("Filter@GRAD", dw)


# ---------------------------------------------------------------------------
# pool2d
# ---------------------------------------------------------------------------

def _pool_geometry(h, w, ksize, strides, paddings, global_pooling,
                   ceil_mode):
    """Shared window geometry for pool2d forward and the maxpool grad:
    effective ksize/paddings, output dims, and the extra bottom/right padding
    that makes the window grid cover a ceil-mode output."""
    if global_pooling:
        ksize = (h, w)
        paddings = (0, 0)
    kh, kw = ksize
    ph, pw = paddings
    sh, sw = strides

    def out_dim(size, k, p, s):
        if ceil_mode:
            return -((size - k + 2 * p) // -s) + 1
        return (size - k + 2 * p) // s + 1

    oh, ow = out_dim(h, kh, ph, sh), out_dim(w, kw, pw, sw)
    eh = max(0, (oh - 1) * sh + kh - h - 2 * ph)
    ew = max(0, (ow - 1) * sw + kw - w - 2 * pw)
    return (kh, kw), (ph, pw), (sh, sw), (oh, ow), (eh, ew)


def _pool_pad_value(x):
    return -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
        else int(jnp.iinfo(x.dtype).min)


def _pool2d_compute(x, ksize, strides, paddings, pooling_type, global_pooling,
                    ceil_mode, exclusive=True, df="NCHW"):
    if df == "NHWC":
        n, h, w, c = x.shape
    else:
        n, c, h, w = x.shape
    (kh, kw), (ph, pw), (sh, sw), (oh, ow), (eh, ew) = _pool_geometry(
        h, w, ksize, strides, paddings, global_pooling, ceil_mode)
    if df == "NHWC":
        pads = ((0, 0), (ph, ph + eh), (pw, pw + ew), (0, 0))
        dims = (1, kh, kw, 1)
        strides4 = (1, sh, sw, 1)
        ones_shape = (1, h, w, 1)
    else:
        pads = ((0, 0), (0, 0), (ph, ph + eh), (pw, pw + ew))
        dims = (1, 1, kh, kw)
        strides4 = (1, 1, sh, sw)
        ones_shape = (1, 1, h, w)

    # init values must be python scalars: jax only recognizes the
    # differentiable reduce_window_sum/max special cases for literal inits
    if pooling_type == "max":
        return lax.reduce_window(x, _pool_pad_value(x), lax.max, dims,
                                 strides4, pads)

    sums = lax.reduce_window(x, 0.0, lax.add, dims, strides4, pads)
    if exclusive and (ph or pw or eh or ew):
        ones = jnp.ones(ones_shape, x.dtype)
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides4, pads)
        return sums / counts
    return sums / (kh * kw)


def _pool2d_attrs(attr):
    ksize = _pair(attr("ksize", [2, 2]))
    strides = _pair(attr("strides", [1, 1]))
    paddings = _pair(attr("paddings", [0, 0]))
    return (ksize, strides, paddings, attr("pooling_type", "max"),
            bool(attr("global_pooling", False)), bool(attr("ceil_mode", False)),
            bool(attr("exclusive", True)), _conv_df(attr))


def _pool2d_infer(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        return
    k = _pair(op.attrs.get("ksize", [2, 2]))
    s = _pair(op.attrs.get("strides", [1, 1]))
    p = _pair(op.attrs.get("paddings", [0, 0]))
    ceil = bool(op.attrs.get("ceil_mode", False))
    df = op.attrs.get("data_format", "NCHW") or "NCHW"
    if df == "NHWC":
        n, h, w, c = x.shape
    else:
        n, c, h, w = x.shape
    if op.attrs.get("global_pooling", False):
        oh = ow = 1
    else:
        def od(size, kk, pp, ss):
            return (-((size - kk + 2 * pp) // -ss) + 1) if ceil else \
                ((size - kk + 2 * pp) // ss + 1)
        oh, ow = od(h, k[0], p[0], s[0]), od(w, k[1], p[1], s[1])
    shape = (n, oh, ow, c) if df == "NHWC" else (n, c, oh, ow)
    infer_output(op, block, "Out", shape, dtype=x.dtype)


@register_op("pool2d", infer_shape=_pool2d_infer, grad=lambda op: [OpSpec(
    "pool2d_grad",
    {"X": op.input("X"), "Out@GRAD": G(op.output("Out"))},
    {"X@GRAD": G(op.input("X"))}, dict(op.attrs))])
def pool2d(ctx):
    x = data_of(ctx.input("X"))
    ctx.set_output("Out", _pool2d_compute(x, *_pool2d_attrs(ctx.attr)))


def _maxpool2d_grad(x, dy, ksize, strides, paddings, global_pooling,
                    ceil_mode, df):
    """Max-pool gradient with the reference's semantics: EVERY input position
    equal to its window max receives the window's dy
    (operators/math/pooling.cc MaxPool2dGradFunctor: `if (input == output)
    input_grad += output_grad`). jax's reduce_window vjp lowers to
    select_and_scatter, which routes each window's gradient to the FIRST
    maximum only — a semantic difference that shows with tied values (common
    for quantized/int inputs). This exact-reference mode is opt-in via
    PDTPU_MAXPOOL_COMPARE_GRAD: on TPU the kh*kw strided scatter passes
    measured ~12 ms slower than select_and_scatter on the flagship bench, so
    the default keeps the fast first-match lowering (ties are measure-zero
    for float activations)."""
    if df == "NHWC":
        n, h, w, c = x.shape
    else:
        n, c, h, w = x.shape
    (kh, kw), (ph, pw), (sh, sw), (oh, ow), (eh, ew) = _pool_geometry(
        h, w, ksize, strides, paddings, global_pooling, ceil_mode)
    neg = _pool_pad_value(x)
    # window maxima (recomputed; cheaper than saving the fwd output across
    # the bwd region) and padded input on the window grid
    y = _pool2d_compute(x, (kh, kw), (sh, sw), (ph, pw), "max", False,
                        ceil_mode, df=df)
    if df == "NHWC":
        pads = ((0, 0), (ph, ph + eh), (pw, pw + ew), (0, 0))
        hax, wax = 1, 2
    else:
        pads = ((0, 0), (0, 0), (ph, ph + eh), (pw, pw + ew))
        hax, wax = 2, 3
    xp = jnp.pad(x, pads, constant_values=neg)
    dxp = jnp.zeros(xp.shape, dy.dtype)
    idx = [slice(None)] * 4
    for i in range(kh):
        for j in range(kw):
            idx[hax] = slice(i, i + sh * (oh - 1) + 1, sh)
            idx[wax] = slice(j, j + sw * (ow - 1) + 1, sw)
            sl = tuple(idx)
            contrib = jnp.where(xp[sl] == y, dy, 0)
            dxp = dxp.at[sl].add(contrib)
    idx[hax] = slice(ph, ph + h)
    idx[wax] = slice(pw, pw + w)
    return dxp[tuple(idx)]


@register_op("pool2d_grad")
def pool2d_grad(ctx):
    x = data_of(ctx.input("X"))
    dy = data_of(ctx.input("Out@GRAD"))
    args = _pool2d_attrs(ctx.attr)
    (ksize, strides, paddings, pooling_type, global_pooling, ceil_mode,
     _exclusive, df) = args
    import os
    if pooling_type == "max" and os.environ.get("PDTPU_MAXPOOL_COMPARE_GRAD"):
        ctx.set_output("X@GRAD",
                       _maxpool2d_grad(x, dy.astype(x.dtype), ksize, strides,
                                       paddings, global_pooling, ceil_mode,
                                       df))
        return
    out, vjp = jax.vjp(lambda a: _pool2d_compute(a, *args), x)
    # upstream grads can arrive in a different float dtype than the forward
    # output under AMP (e.g. bf16 grad meeting an fp32-promoted forward)
    ctx.set_output("X@GRAD", vjp(dy.astype(out.dtype))[0])
