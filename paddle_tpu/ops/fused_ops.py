"""fused_conv2d_bn: the conv+batch_norm(+act) chain as ONE op.

No reference analog — the reference executes conv2d, batch_norm and the
activation as three kernels (cuDNN + BatchNormKernel + relu). Here the
``fluid.fuse_conv_bn`` transpiler pass (fluid/fusion.py) rewrites eligible
conv2d→batch_norm(→relu) chains into this op at build time, and its
lowering picks the execution tier per dispatch:

* **pallas** (kernel_tier resolves to Pallas and the shape is eligible) —
  the fused Pallas kernels (ops/pallas/conv_bn.py): the conv block stays
  VMEM-resident through the statistics, normalize and activation instead
  of three HBM round trips; training backward likewise fuses the relu
  mask, BN grad and both conv gradients into one kernel.
* **jnp twin** (everything else, incl. per-shape fallback with a
  ``fallback_counts`` bump) — literally `_conv2d_compute` +
  `bn_forward_math` + the relu expression, i.e. the SAME jaxprs the
  unfused op chain traces, so ``kernel_tier=jnp`` reproduces the unfused
  program bitwise.

The op carries batch_norm's full output contract (MeanOut/VarianceOut
write back in place, SavedMean/SavedVariance feed the grad) so a fused
program checkpoints and resumes exactly like an unfused one.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.amp import cast_compute
from ..core.registry import register_op, OpSpec, infer_output
from .common import G, data_of
from .conv_ops import _conv_attrs, _conv_df, _conv2d_infer, _conv2d_compute
from .norm_ops import bn_forward_math, bn_backward_math
from .pallas import use_pallas, kernel_span


def _fused_supported(x, w, strides, paddings, dilations, groups, df,
                     backward=False, block_n=1, dtype=None):
    from .pallas import conv_bn as cbk
    return cbk.supported(tuple(x.shape), tuple(w.shape), strides, paddings,
                         dilations, groups, df, dtype or x.dtype,
                         backward=backward, block_n=block_n)


def _fused_conv_bn_infer(op, block):
    _conv2d_infer(op, block)
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Filter")[0])
    if x is None or w is None or w.shape is None:
        return
    c = int(w.shape[0])
    for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        if op.output(slot):
            infer_output(op, block, slot, (c,), dtype=x.dtype)


def _fused_conv_bn_grad_maker(op):
    return [OpSpec(
        "fused_conv2d_bn_grad",
        {"Input": op.input("Input"), "Filter": op.input("Filter"),
         "Scale": op.input("Scale"), "Bias": op.input("Bias"),
         "SavedMean": op.output("SavedMean"),
         "SavedVariance": op.output("SavedVariance"),
         "Output": op.output("Output"),
         "Output@GRAD": G(op.output("Output"))},
        {"Input@GRAD": G(op.input("Input")),
         "Filter@GRAD": G(op.input("Filter")),
         "Scale@GRAD": G(op.input("Scale")),
         "Bias@GRAD": G(op.input("Bias"))},
        dict(op.attrs))]


@register_op("fused_conv2d_bn", infer_shape=_fused_conv_bn_infer,
             grad=_fused_conv_bn_grad_maker)
def fused_conv2d_bn(ctx):
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Filter"))
    scale = data_of(ctx.input("Scale"))
    bias = data_of(ctx.input("Bias"))
    rm = data_of(ctx.input("Mean"))
    rv = data_of(ctx.input("Variance"))
    strides, paddings, dilations, groups = _conv_attrs(ctx, ctx.attr)
    df = _conv_df(ctx.attr)
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    act = ctx.attr("act", "") or ""
    is_test = bool(ctx.attr("is_test", False))
    x, w = cast_compute(x, w)

    # NOTE conv_space_to_depth and the fused kernels are disjoint by
    # construction: s2d needs k>1 at stride 2, the fused path takes
    # stride 2 only at k=1 — s2d-eligible convs always land on the jnp
    # twin, whose _conv2d_compute applies the rewrite itself
    sup = _fused_supported(x, w, strides, paddings, dilations, groups, df)
    from .autotune import dispatch_variant, make_key
    key = make_key(x=tuple(x.shape), w=tuple(w.shape), dtype=str(x.dtype),
                   strides=tuple(strides), paddings=tuple(paddings),
                   dilations=tuple(dilations), groups=groups, df=df,
                   act=act, is_test=is_test)
    choice = dispatch_variant("conv_bn", key, {
        "jnp": True,
        "pallas": sup,
        "pallas_db": _fused_supported(x, w, strides, paddings, dilations,
                                      groups, df, block_n=2),
        # bf16 activations (value-changing, tuner opt-in): only a cast
        # AWAY from f32 is a distinct variant
        "pallas_bf16": (x.dtype == jnp.float32
                        and _fused_supported(x, w, strides, paddings,
                                             dilations, groups, df,
                                             dtype=jnp.bfloat16)),
    })
    if choice != "jnp":
        from .pallas import conv_bn as cbk
        out_dtype = x.dtype
        block_n = 2 if choice == "pallas_db" else 1
        if choice == "pallas_bf16":
            x, w = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
        if is_test:
            inv = jax.lax.rsqrt(rv.astype(jnp.float32) + eps)
            a = scale.astype(jnp.float32) * inv
            b = bias.astype(jnp.float32) - rm.astype(jnp.float32) * a
            with kernel_span(choice, "conv_bn"):
                y = cbk.conv_affine_pallas(x, w, a, b, strides, paddings,
                                           act, block_n=block_n)
            new_mean, new_var, sm, sv = rm, rv, rm, rv
        else:
            with kernel_span(choice, "conv_bn"):
                y, sm, sv = cbk.conv_bn_train_pallas(
                    x, w, scale, bias, eps, strides, paddings, act,
                    block_n=block_n)
            new_mean = momentum * rm + (1.0 - momentum) * sm
            new_var = momentum * rv + (1.0 - momentum) * sv
        if choice == "pallas_bf16":
            y = y.astype(out_dtype)
    else:
        with kernel_span("jnp", "conv_bn"):
            z = _conv2d_compute(x, w, strides, paddings, dilations, groups,
                                df)
            y, new_mean, new_var, sm, sv = bn_forward_math(
                z, scale, bias, rm, rv, eps, momentum, df, is_test)
            if act == "relu":
                y = jnp.maximum(y, 0)
    ctx.set_output("Output", y)
    ctx.set_output("MeanOut", new_mean)
    ctx.set_output("VarianceOut", new_var)
    ctx.set_output("SavedMean", sm)
    ctx.set_output("SavedVariance", sv)


@register_op("fused_conv2d_bn_grad")
def fused_conv2d_bn_grad(ctx):
    x = data_of(ctx.input("Input"))
    w = data_of(ctx.input("Filter"))
    scale = data_of(ctx.input("Scale"))
    bias = data_of(ctx.input("Bias"))
    sm = data_of(ctx.input("SavedMean"))
    sv = data_of(ctx.input("SavedVariance"))
    y = data_of(ctx.input("Output"))
    dy = data_of(ctx.input("Output@GRAD"))
    strides, paddings, dilations, groups = _conv_attrs(ctx, ctx.attr)
    df = _conv_df(ctx.attr)
    eps = ctx.attr("epsilon", 1e-5)
    act = ctx.attr("act", "") or ""
    is_test = bool(ctx.attr("is_test", False))
    x, w = cast_compute(x, w)

    sup = (not is_test
           and _fused_supported(x, w, strides, paddings, dilations, groups,
                                df, backward=True))
    if use_pallas("conv_bn", sup):
        from .pallas import conv_bn as cbk
        with kernel_span("pallas", "conv_bn"):
            dx, dw, dscale, dbias = cbk.conv_bn_bwd_pallas(
                x, w, dy.astype(x.dtype), scale, bias, sm, sv, eps, strides,
                paddings, act)
        ctx.set_output("Input@GRAD", dx)
        ctx.set_output("Filter@GRAD", dw)
        ctx.set_output("Scale@GRAD", dscale)
        ctx.set_output("Bias@GRAD", dbias)
        return
    with kernel_span("jnp", "conv_bn"):
        # the unfused chain's exact backward: relu_grad (d·(out>0)) →
        # batch_norm_grad closed form → conv vjp (conv2d_grad's path)
        dy2 = dy * (y > 0) if act == "relu" else dy
        z = _conv2d_compute(x, w, strides, paddings, dilations, groups, df)
        dz, dscale, dbias = bn_backward_math(z, scale, sm, sv, dy2, eps, df,
                                             is_test)
        out, vjp = jax.vjp(
            lambda a, b: _conv2d_compute(a, b, strides, paddings, dilations,
                                         groups, df), x, w)
        dx, dw = vjp(dz.astype(out.dtype))
    ctx.set_output("Input@GRAD", cast_compute(dx))
    ctx.set_output("Filter@GRAD", dw)
    ctx.set_output("Scale@GRAD", dscale)
    ctx.set_output("Bias@GRAD", dbias)
