"""Shared bounded worker pool for host-side record decode/transform.

The reference keeps the accelerator fed with a C++ multi-threaded prefetch
pool (/root/reference/paddle/fluid/operators/reader/
create_double_buffer_reader_op.cc and open_files' ``thread_num``). Here the
same role is a pool of Python threads running GIL-releasing decode work
(zlib inflate, numpy bulk ops, file I/O): ``WorkerPool.imap`` maps a
per-record function over a stream across ``thread_num`` workers, in
order-preserving or unordered mode, and several streams can share one pool
— ``open_files`` runs the decode of all its file shards through a single
pool.

Discipline (shared with reader/prefetch.background_buffer):

* BaseException-safe error propagation — a worker or feeder error travels
  to the consumer and re-raises there; nothing can hang waiting for a
  result that will never come.
* Clean shutdown — abandoning a consumer iterator mid-stream (``close()``
  / ``GeneratorExit`` / an exception in the consuming loop) cancels the
  stream, unblocking its feeder and releasing its workers back to the
  pool; :meth:`WorkerPool.shutdown` then joins every thread, so tests can
  assert no threads leak.
* Bounded buffering — at most ``capacity`` records are in flight per
  stream (submitted but not yet yielded), so a fast producer can never
  balloon host memory.
"""

from __future__ import annotations

import queue as _queue
import threading

__all__ = ["WorkerPool", "pool_map", "interleave"]

# polling granularity for interruptible queue waits; every blocking wait in
# this module re-checks its stream's stop flag at this period, which is what
# makes shutdown deadlock-free without a wake-up token per waiter
_TICK = 0.05


class _Stream:
    """Per-imap bookkeeping shared between feeder, workers and consumer."""

    __slots__ = ("out", "slots", "stop", "error", "total", "done_feeding")

    def __init__(self, capacity):
        # out is unbounded: in-flight items are already bounded by ``slots``
        self.out = _queue.Queue()
        self.slots = threading.BoundedSemaphore(capacity)
        self.stop = threading.Event()
        self.error = []
        self.total = None            # set by the feeder when input ends
        self.done_feeding = threading.Event()


class WorkerPool:
    """``thread_num`` daemon workers pulling tasks off one shared queue.

    Tasks come from :meth:`imap` (parallel per-record map) and
    :meth:`background` (stage a whole reader through a bounded queue).
    Multiple streams interleave on the same workers, so one pool serves a
    whole reader chain (decode + shuffle staging + batch staging).
    """

    def __init__(self, thread_num, capacity=None):
        self.thread_num = max(1, int(thread_num))
        # default per-stream in-flight bound: enough to keep every worker
        # busy plus a reorder margin for ordered mode
        self.capacity = max(self.thread_num,
                            int(capacity or 2 * self.thread_num))
        self._tasks = _queue.Queue()
        self._closed = False
        self._streams = []           # live imap streams, cancelled on shutdown
        self._aux_threads = []       # background() stagers, joined on shutdown
        self._workers = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"reader-pool-{i}")
            for i in range(self.thread_num)]
        for w in self._workers:
            w.start()

    # ------------------------------------------------------------------
    def _work(self):
        while True:
            task = self._tasks.get()
            if task is None:         # poison pill from shutdown()
                return
            task()

    # ------------------------------------------------------------------
    def imap(self, fn, iterable, ordered=True, capacity=None):
        """Iterator of ``fn(item)`` computed across the pool's workers.

        ``ordered=True`` preserves input order (results buffer until their
        predecessors arrive); ``ordered=False`` yields completion order.
        Either way every input item is mapped exactly once. Errors raised
        by ``fn`` (or by iterating ``iterable``) re-raise here; a shutdown()
        racing an active stream cancels it with a loud RuntimeError rather
        than hanging or silently truncating.
        """
        if self._closed:
            raise RuntimeError("imap on a shut-down WorkerPool")
        stream = _Stream(max(1, int(capacity or self.capacity)))
        self._streams = [s for s in self._streams if not s.stop.is_set()]
        self._streams.append(stream)

        def submit(i, item):
            def task():
                if stream.stop.is_set():
                    return
                try:
                    stream.out.put((i, fn(item)))
                except BaseException as e:
                    stream.error.append(e)
                    stream.stop.set()
            self._tasks.put(task)

        def feed():
            n = 0
            try:
                for item in iterable:
                    while not stream.slots.acquire(timeout=_TICK):
                        if stream.stop.is_set():
                            return
                    if stream.stop.is_set():
                        return
                    submit(n, item)
                    n += 1
            except BaseException as e:
                stream.error.append(e)
                stream.stop.set()
            finally:
                stream.total = n
                stream.done_feeding.set()

        feeder = threading.Thread(target=feed, daemon=True,
                                  name="reader-pool-feeder")
        feeder.start()

        def consume():
            received = 0
            pending, next_idx = {}, 0
            try:
                while True:
                    if stream.error:
                        raise stream.error[0]
                    if stream.stop.is_set():
                        # externally cancelled (pool shutdown mid-stream).
                        # Checked BEFORE the completion test: a cancelled
                        # feeder stops submitting and still sets
                        # done_feeding, so completion could otherwise look
                        # normal and silently truncate — fail loudly
                        # instead. (Normal completion never sets stop: only
                        # errors, shutdown, and this generator's own exit
                        # do.)
                        raise RuntimeError(
                            "WorkerPool shut down during iteration")
                    if stream.done_feeding.is_set() \
                            and received >= stream.total:
                        return
                    try:
                        i, res = stream.out.get(timeout=_TICK)
                    except _queue.Empty:
                        continue
                    received += 1
                    if not ordered:
                        stream.slots.release()
                        yield res
                        continue
                    pending[i] = res
                    while next_idx in pending:
                        stream.slots.release()
                        yield pending.pop(next_idx)
                        next_idx += 1
            finally:
                stream.stop.set()
                feeder.join()

        return consume()

    # ------------------------------------------------------------------
    def _register_stage_thread(self, t, stop):
        t.name = "reader-pool-stage"
        # prune finished stagers so a long-lived pool driving many epochs
        # doesn't accumulate dead Thread objects
        self._aux_threads = [(a, s) for a, s in self._aux_threads
                             if a.is_alive()]
        self._aux_threads.append((t, stop))

    def background(self, reader, capacity=2):
        """Decorate ``reader`` so its items are produced by a staging
        thread bookkept by this pool (joined at :meth:`shutdown`), with a
        bounded hand-off queue — prefetch.background_buffer with pool
        bookkeeping. The stager is a dedicated thread rather than a pool
        task on purpose: a stream-lifetime task would pin a worker, and a
        chain like ``imap(decode) -> background(batch)`` on a 1-thread
        pool would deadlock.
        """
        from .prefetch import background_buffer
        return background_buffer(reader, capacity,
                                 register=self._register_stage_thread)

    # ------------------------------------------------------------------
    def shutdown(self, timeout=5.0):
        """Stop every worker and join all pool threads. Idempotent; safe
        while streams are mid-flight: their stop flags are set, so feeders
        unblock and consumers raise RuntimeError instead of hanging on
        tasks that will never run."""
        if not self._closed:
            self._closed = True
            for s in self._streams:
                s.stop.set()
            self._streams = []
            for _, stop in self._aux_threads:
                stop.set()
            for _ in self._workers:
                self._tasks.put(None)
        for t in self._workers + [a for a, _ in self._aux_threads]:
            t.join(timeout)

    def live_threads(self):
        """Names of pool-owned threads still alive (test hook)."""
        return [t.name for t in
                self._workers + [a for a, _ in self._aux_threads]
                if t.is_alive()]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


def pool_map(mapper, reader, thread_num, ordered=True, capacity=None,
             pool=None):
    """Reader decorator: ``mapper`` over samples across ``thread_num``
    threads — the pooled successor of ``decorator.xmap_readers`` (same
    contract, shared-pool execution, loud error propagation). With
    ``pool`` given, its workers are used (and it stays open); otherwise a
    transient pool lives for exactly one iteration.
    """

    def data_reader():
        own = pool or WorkerPool(thread_num, capacity)
        try:
            yield from own.imap(mapper, reader(), ordered=ordered,
                                capacity=capacity)
        finally:
            if own is not pool:
                own.shutdown()

    return data_reader


def interleave(readers, max_open=None):
    """One reader round-robining over ``readers`` (one per file shard) —
    the host-side form of the reference open_files' multi-file interleave.
    Every record of every shard is yielded exactly once. ``max_open``
    bounds how many shard iterators are live at once (an exhausted shard's
    slot goes to the next pending one), so a thousand-file open_files
    holds ``max_open`` file descriptors, not a thousand; default: all."""
    readers = list(readers)
    cap = len(readers) if max_open is None else max(1, int(max_open))

    def data_reader():
        pending = iter(readers)
        active = [iter(r()) for _, r in zip(range(cap), pending)]
        while active:
            alive = []
            for it in active:
                try:
                    item = next(it)
                except StopIteration:
                    nxt = next(pending, None)
                    if nxt is not None:
                        alive.append(iter(nxt()))  # joins next round
                    continue
                alive.append(it)
                yield item
            active = alive

    return data_reader
