from .decorator import (map_readers, buffered, compose, chain, shuffle,
                        ComposeNotAligned, firstn, xmap_readers, cache,
                        bucket_by_length, bucket_bound_for)
from .minibatch import batch
from .pool import WorkerPool, pool_map, interleave
from .prefetch import DeviceFeedIterator, double_buffer
from . import creator
from .creator import convert_reader_to_recordio_file

__all__ = [
    "map_readers", "buffered", "compose", "chain", "shuffle",
    "ComposeNotAligned", "firstn", "xmap_readers", "cache", "batch",
    "bucket_by_length", "bucket_bound_for",
    "WorkerPool", "pool_map", "interleave",
    "DeviceFeedIterator", "double_buffer", "creator",
    "convert_reader_to_recordio_file",
]
