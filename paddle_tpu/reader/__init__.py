from .decorator import (map_readers, buffered, compose, chain, shuffle,
                        ComposeNotAligned, firstn, xmap_readers, cache)
from .minibatch import batch
from .prefetch import DeviceFeedIterator, double_buffer

__all__ = [
    "map_readers", "buffered", "compose", "chain", "shuffle",
    "ComposeNotAligned", "firstn", "xmap_readers", "cache", "batch",
    "DeviceFeedIterator", "double_buffer",
]
