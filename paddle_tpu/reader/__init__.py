from .decorator import (map_readers, buffered, compose, chain, shuffle,
                        ComposeNotAligned, firstn, xmap_readers, cache,
                        bucket_by_length, bucket_bound_for)
from .minibatch import batch
from .prefetch import DeviceFeedIterator, double_buffer
from . import creator
from .creator import convert_reader_to_recordio_file

__all__ = [
    "map_readers", "buffered", "compose", "chain", "shuffle",
    "ComposeNotAligned", "firstn", "xmap_readers", "cache", "batch",
    "bucket_by_length", "bucket_bound_for",
    "DeviceFeedIterator", "double_buffer", "creator",
    "convert_reader_to_recordio_file",
]
