"""Reader creators (reference python/paddle/v2/reader/creator.py):
np_array, text_file, recordio — plus the fluid-side
convert_reader_to_recordio_file (reference python/paddle/fluid/
recordio_writer.py) so any sample reader round-trips through recordio files.

Samples serialize as pickled tuples of numpy arrays/scalars — framework-
independent, like the reference's LoDTensor wire form but without the
protobuf dependency.
"""

from __future__ import annotations

import pickle

__all__ = ["np_array", "text_file", "recordio", "recordio_sharded",
           "convert_reader_to_recordio_file"]


def np_array(x):
    """Reader yielding rows of a numpy array (reference creator.np_array)."""
    import numpy as np

    arr = np.asarray(x)

    def reader():
        for row in arr:
            yield row

    return reader


def text_file(path):
    """Reader yielding stripped lines (reference creator.text_file)."""

    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, decoder=pickle.loads):
    """Reader over one or more recordio files (reference creator.recordio /
    recordio(paths) with the cloud variant elided). ``decoder`` maps raw
    record bytes to a sample."""
    if isinstance(paths, str):
        paths = paths.split(",")

    def reader():
        from ..recordio import Scanner
        for p in paths:
            for rec in Scanner(p):
                yield decoder(rec)

    return reader


def recordio_sharded(paths, thread_num, decoder=pickle.loads, pool=None,
                     ordered=True):
    """Reader over many recordio files with the decode parallelized: one
    raw-bytes scanner per file, interleaved round-robin, record bytes
    decoded across a ``thread_num``-wide WorkerPool — the runtime form of
    ``fluid.layers.open_files(thread_num=N)``. Every record of every shard
    is delivered exactly once; ``ordered=True`` keeps the deterministic
    interleaved order, ``ordered=False`` yields in decode-completion order.
    ``thread_num<=1`` degrades to the serial :func:`recordio` path (no
    threads spawned)."""
    if isinstance(paths, str):
        paths = paths.split(",")
    if int(thread_num) <= 1 and pool is None:
        return recordio(paths, decoder=decoder)

    from .pool import interleave, pool_map

    def raw_shard(path):
        def reader():
            from ..recordio import Scanner
            for rec in Scanner(path):
                yield rec

        return reader

    # max_open=thread_num: concurrent open shards track the decode width
    # (the reference prefetch pool reads thread_num files at once), so a
    # thousand-file open_files never holds a thousand descriptors
    width = pool.thread_num if pool is not None else int(thread_num)
    raw = interleave([raw_shard(p) for p in paths], max_open=max(2, width))
    return pool_map(decoder, raw, thread_num, ordered=ordered, pool=pool)


def convert_reader_to_recordio_file(path, reader, compressor="deflate",
                                    max_records=1000,
                                    encoder=pickle.dumps):
    """Serialize every sample of ``reader`` into one recordio file; returns
    the record count (reference recordio_writer.py
    convert_reader_to_recordio_file)."""
    from ..recordio import Writer

    n = 0
    with Writer(path, compressor=compressor, max_records=max_records) as w:
        for sample in reader():
            w.write(encoder(sample))
            n += 1
    return n
