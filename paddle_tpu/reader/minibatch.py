"""``batch`` — group samples into mini-batch lists.

Reference: /root/reference/python/paddle/v2/minibatch.py:18. Same contract:
the batched reader yields lists of samples; the trailing partial batch is
emitted (drop it with ``drop_last=True``, an extension the reference's
fluid-era batch gained later — static-shape XLA steps want it).
"""

from __future__ import annotations


def batch(reader, batch_size, drop_last=False):
    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
