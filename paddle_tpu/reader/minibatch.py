"""``batch`` — group samples into mini-batch lists.

Reference: /root/reference/python/paddle/v2/minibatch.py:18. Same contract:
the batched reader yields lists of samples; the trailing partial batch is
emitted (drop it with ``drop_last=True``, an extension the reference's
fluid-era batch gained later — static-shape XLA steps want it).
"""

from __future__ import annotations


def batch(reader, batch_size, drop_last=False, pool=None):
    """With ``pool`` (a reader.pool.WorkerPool) batch assembly runs on a
    pool-bookkept staging thread, so the consumer pops ready batches off a
    bounded queue while the next ones assemble."""

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    if pool is not None:
        return pool.background(batch_reader, capacity=2)
    return batch_reader
