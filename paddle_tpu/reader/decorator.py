"""Reader decorators — composable ``() -> iterator`` transforms.

Mirrors the API surface of the reference's
/root/reference/python/paddle/v2/reader/decorator.py:29-337 (map_readers,
shuffle, chain, compose, buffered, firstn, xmap_readers) with the same
contract: a *reader* is a zero-arg callable returning a fresh iterator over
samples; a *reader creator/decorator* builds readers from readers. This
composability is what lets datasets, augmentation, shuffling and batching
stack without touching the training loop.

Implementation is original (py3 threads/queues; the reference is py2
Queue/itertools.imap); ``cache`` is an extension used by benchmarks to
freeze a finite reader's output in memory.
"""

from __future__ import annotations

import itertools
import random
import threading
import queue as _queue


def map_readers(func, *readers):
    """Reader yielding ``func(*samples)`` drawn in lockstep from ``readers``
    (decorator.py:29)."""

    def reader():
        its = [r() for r in readers]
        for args in zip(*its):
            yield func(*args)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffling (decorator.py:52): fill a ``buf_size`` buffer,
    shuffle it, emit, repeat. The classic streaming-shuffle compromise."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers' outputs in sequence (decorator.py:82)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into tuple samples (decorator.py:110): outputs
    (r1_sample, *r2_sample...) flattened one level. check_alignment=True
    (default) raises ComposeNotAligned when readers end at different
    lengths."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        its = [r() for r in readers]
        if check_alignment:
            for outputs in itertools.zip_longest(*its):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            for outputs in zip(*its):
                yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Decouple producer and consumer with a bounded queue filled by a
    background thread (decorator.py:160) — host-side pipelining."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)

        def feed():
            try:
                for d in r:
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return data_reader


def firstn(reader, n):
    """First ``n`` samples only (decorator.py:191)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with ``process_num`` worker threads
    (decorator.py:211 XmapEndSignal machinery). ``order=True`` preserves
    input order via sequence numbers."""

    class _End:
        pass

    def data_reader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(_End)

        def work():
            while True:
                item = in_q.get()
                if item is _End:
                    out_q.put(_End)
                    break
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        if order:
            pending = {}
            next_idx = 0
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                i, mapped = item
                pending[i] = mapped
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                yield item[1]

    return data_reader


def cache(reader):
    """Materialize a finite reader once and replay from memory afterwards
    (TPU extension — used to amortize host decode in benchmarks)."""
    memo = []
    filled = [False]

    def cached_reader():
        if filled[0]:
            yield from memo
            return
        for s in reader():
            memo.append(s)
            yield s
        filled[0] = True

    return cached_reader
