"""Reader decorators — composable ``() -> iterator`` transforms.

Mirrors the API surface of the reference's
/root/reference/python/paddle/v2/reader/decorator.py:29-337 (map_readers,
shuffle, chain, compose, buffered, firstn, xmap_readers) with the same
contract: a *reader* is a zero-arg callable returning a fresh iterator over
samples; a *reader creator/decorator* builds readers from readers. This
composability is what lets datasets, augmentation, shuffling and batching
stack without touching the training loop.

Implementation is original (py3 threads/queues; the reference is py2
Queue/itertools.imap); ``cache`` is an extension used by benchmarks to
freeze a finite reader's output in memory.
"""

from __future__ import annotations

import itertools
import random
import threading
import queue as _queue


def map_readers(func, *readers):
    """Reader yielding ``func(*samples)`` drawn in lockstep from ``readers``
    (decorator.py:29)."""

    def reader():
        its = [r() for r in readers]
        for args in zip(*its):
            yield func(*args)

    return reader


def shuffle(reader, buf_size, pool=None):
    """Buffered shuffling (decorator.py:52): fill a ``buf_size`` buffer,
    shuffle it, emit, repeat. The classic streaming-shuffle compromise.
    With ``pool`` (a reader.pool.WorkerPool) the buffer fill+shuffle runs
    on a pool-bookkept staging thread, decoupled from the consumer."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    if pool is not None:
        return pool.background(data_reader, capacity=2)
    return data_reader


def chain(*readers):
    """Concatenate readers' outputs in sequence (decorator.py:82)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into tuple samples (decorator.py:110): outputs
    (r1_sample, *r2_sample...) flattened one level. check_alignment=True
    (default) raises ComposeNotAligned when readers end at different
    lengths."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        its = [r() for r in readers]
        if check_alignment:
            for outputs in itertools.zip_longest(*its):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            for outputs in zip(*its):
                yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Decouple producer and consumer with a bounded queue filled by a
    background thread (decorator.py:160) — host-side pipelining."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)

        def feed():
            try:
                for d in r:
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return data_reader


def firstn(reader, n):
    """First ``n`` samples only (decorator.py:191)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with ``process_num`` worker threads
    (decorator.py:211 XmapEndSignal machinery). ``order=True`` preserves
    input order via sequence numbers. A spelling of ``pool.pool_map``,
    which replaces the reference's hang-on-error queue machinery with loud
    worker-error propagation and leak-free shutdown."""
    from .pool import pool_map
    return pool_map(mapper, reader, process_num, ordered=order,
                    capacity=buffer_size)


def cache(reader):
    """Materialize a finite reader once and replay from memory afterwards
    (TPU extension — used to amortize host decode in benchmarks)."""
    memo = []
    filled = [False]

    def cached_reader():
        if filled[0]:
            yield from memo
            return
        for s in reader():
            memo.append(s)
            yield s
        filled[0] = True

    return cached_reader


def bucket_by_length(reader, key, bucket_bounds, batch_size, drop_last=False):
    """Group samples into length buckets and emit per-bucket batches — the
    TPU-native answer to the reference's batch-shrinking RNN machinery
    (operators/lod_rank_table_op.cc + shrink_rnn_memory_op.cc: sort by
    length, retire finished sequences each step). Under XLA's static shapes
    we cannot shrink a live batch, so the win is moved to the feed side:
    batching sequences of similar length means each padded batch runs
    scan steps ~equal to ITS OWN max length, not the corpus max — and the
    bucket bounds cap the set of distinct compiled shapes (pad each batch to
    its bucket's bound and every bucket compiles exactly once).

    Args:
        reader: sample reader.
        key: sample -> int length (e.g. ``lambda s: len(s[0])``).
        bucket_bounds: ascending upper bounds; a final unbounded bucket
            catches the tail (longer sequences).
        batch_size: samples per emitted batch.
        drop_last: drop per-bucket remainders at exhaustion.

    Returns a reader over plain batches (lists of samples), like
    paddle.batch; the pad target for a batch is
    ``bucket_bound_for(bucket_bounds, max(key(s) for s in batch))``.
    """
    bounds = sorted(int(b) for b in bucket_bounds)

    def which(n):
        for i, b in enumerate(bounds):
            if n <= b:
                return i
        return len(bounds)

    def bucketed_reader():
        buckets = [[] for _ in range(len(bounds) + 1)]
        for sample in reader():
            b = buckets[which(key(sample))]
            b.append(sample)
            if len(b) == batch_size:
                yield list(b)
                del b[:]
        if not drop_last:
            for b in buckets:
                if b:
                    yield list(b)

    return bucketed_reader


def bucket_bound_for(bucket_bounds, length):
    """The padded length a batch of max sample length ``length`` compiles at
    (the companion of bucket_by_length: feed-side pad target)."""
    for b in sorted(int(x) for x in bucket_bounds):
        if length <= b:
            return b
    return length
