"""Host→device double-buffer prefetch.

Reference: /root/reference/paddle/fluid/operators/reader/
create_double_buffer_reader_op.cc:25-68 — a background thread pulls batches
from the decorated reader and stages them into a small pool of device-side
buffers ahead of the consumer.

TPU-native form: a ``DeviceFeedIterator`` wraps a batched feed-dict reader;
a daemon thread converts each batch with the DataFeeder (or a user convert
fn), ``jax.device_put``s it (optionally pre-cast, e.g. images to bf16 for
AMP), and parks it in a bounded queue. The training loop's ``next()`` then
hands back an already-device-resident feed, so the host transfer overlaps
device compute — the same pipelining the reference gets from its
double-buffer thread.
"""

from __future__ import annotations

import queue as _queue
import queue as _queue2
import threading

import jax


def background_buffer(reader, capacity=2, stage=None):
    """Record-agnostic bounded background prefetch: returns a creator whose
    iterator is fed by a daemon thread (``stage`` runs per item IN the
    feeder, e.g. jax.device_put). BaseException-safe: the end sentinel is
    enqueued in a finally so the consumer can never hang, and feeder errors
    re-raise consumer-side. One implementation for both the feed-dict
    (DeviceFeedIterator) and slot-tuple (reader-graph op) flavors."""

    def make():
        q = _queue2.Queue(maxsize=max(1, int(capacity)))
        end, err = object(), []

        def feed():
            try:
                for item in reader():
                    q.put(stage(item) if stage is not None else item)
            except BaseException as e:   # surface in consumer
                err.append(e)
            finally:
                q.put(end)

        threading.Thread(target=feed, daemon=True).start()
        while True:
            item = q.get()
            if item is end:
                if err:
                    raise err[0]
                return
            yield item

    return make


def double_buffer(reader, place=None, capacity=2, convert=None):
    """Decorate a feed-dict reader so its batches arrive device-resident.
    Returns a reader (zero-arg callable) like every other decorator."""

    def data_reader():
        return iter(DeviceFeedIterator(reader, place=place,
                                       capacity=capacity, convert=convert))

    return data_reader


class DeviceFeedIterator:
    """Iterates device-staged feed dicts produced by a background thread."""

    class _End:
        pass

    def __init__(self, reader, place=None, capacity=2, convert=None,
                 cast=None):
        self._reader = reader
        self._capacity = max(1, int(capacity))
        self._convert = convert
        self._cast = dict(cast or {})
        if place is None:
            self._device = jax.devices()[0]
        else:
            from ..core.executor import _resolve_device
            self._device = _resolve_device(place)

    def _stage(self, batch):
        if self._convert is not None:
            batch = self._convert(batch)
        staged = {}
        for k, v in batch.items():
            arr = jax.device_put(v, self._device)
            if k in self._cast:
                arr = arr.astype(self._cast[k])
            staged[k] = arr
        return staged

    def __iter__(self):
        return background_buffer(self._reader, self._capacity,
                                 self._stage)()
