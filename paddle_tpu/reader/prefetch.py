"""Host→device double-buffer prefetch.

Reference: /root/reference/paddle/fluid/operators/reader/
create_double_buffer_reader_op.cc:25-68 — a background thread pulls batches
from the decorated reader and stages them into a small pool of device-side
buffers ahead of the consumer.

TPU-native form: a ``DeviceFeedIterator`` wraps a batched feed-dict reader;
a daemon thread converts each batch with the DataFeeder (or a user convert
fn), ``jax.device_put``s it (optionally pre-cast, e.g. images to bf16 for
AMP), and parks it in a bounded queue. The training loop's ``next()`` then
hands back an already-device-resident feed, so the host transfer overlaps
device compute — the same pipelining the reference gets from its
double-buffer thread.
"""

from __future__ import annotations

import queue as _queue
import threading

import jax


def background_buffer(reader, capacity=2, stage=None, register=None):
    """Record-agnostic bounded background prefetch: returns a creator whose
    iterator is fed by a daemon thread (``stage`` runs per item IN the
    feeder, e.g. jax.device_put). BaseException-safe: the end sentinel is
    enqueued in a finally so the consumer can never hang, feeder errors
    re-raise consumer-side, and abandoning the iterator mid-pass releases
    the feeder (stop flag polled on every bounded put). ``register`` is
    called with ``(thread, stop_event)`` before each feeder starts
    (WorkerPool.background uses it to bookkeep stagers and cancel/join
    them at shutdown). One implementation for the feed-dict
    (DeviceFeedIterator), slot-tuple (reader-graph op), and pool-staging
    flavors."""

    def make():
        q = _queue.Queue(maxsize=max(1, int(capacity)))
        end, err = object(), []
        stop = threading.Event()

        def put(item):
            # bounded put that notices an abandoned consumer: without the
            # stop check a `break` out of the consuming loop would leave the
            # feeder blocked forever on the full queue, pinning its staged
            # (device-resident) batches and the open readers
            while True:
                try:
                    q.put(item, timeout=0.05)
                    return True
                except _queue.Full:
                    if stop.is_set():
                        return False

        def feed():
            try:
                for item in reader():
                    if not put(stage(item) if stage is not None else item) \
                            or stop.is_set():
                        return
            except BaseException as e:   # surface in consumer
                err.append(e)
            finally:
                put(end)

        t = threading.Thread(target=feed, daemon=True)
        if register is not None:
            register(t, stop)
        t.start()
        try:
            while True:
                try:
                    item = q.get(timeout=0.05)
                except _queue.Empty:
                    if stop.is_set():
                        # cancelled externally (pool shutdown): the feeder
                        # is gone and may not have managed to enqueue the
                        # end sentinel — fail loudly instead of hanging
                        raise RuntimeError(
                            "background reader cancelled mid-stream")
                    continue
                if item is end:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()

    return make


def double_buffer(reader, place=None, capacity=2, convert=None):
    """Decorate a feed-dict reader so its batches arrive device-resident.
    Returns a reader (zero-arg callable) like every other decorator."""

    def data_reader():
        return iter(DeviceFeedIterator(reader, place=place,
                                       capacity=capacity, convert=convert))

    return data_reader


class DeviceFeedIterator:
    """Iterates device-staged feed dicts produced by a background thread."""

    class _End:
        pass

    def __init__(self, reader, place=None, capacity=2, convert=None,
                 cast=None):
        self._reader = reader
        self._capacity = max(1, int(capacity))
        self._convert = convert
        self._cast = dict(cast or {})
        if place is None:
            self._device = jax.devices()[0]
        else:
            from ..core.executor import _resolve_device
            self._device = _resolve_device(place)

    def _stage(self, batch):
        if self._convert is not None:
            batch = self._convert(batch)
        # ONE device_put per batch: the feed dict transfers as a single
        # pytree submission instead of a host->device round trip per key
        staged = dict(jax.device_put(dict(batch), self._device))
        for k, dt in self._cast.items():
            if k in staged:
                staged[k] = staged[k].astype(dt)
        return staged

    def __iter__(self):
        return background_buffer(self._reader, self._capacity,
                                 self._stage)()
