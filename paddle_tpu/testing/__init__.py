"""Shared tiny model builders for tests and the driver dryrun.

The reference keeps its model zoo for tests in the book chapters
(/root/reference/python/paddle/fluid/tests/book/); these are the cut-down
op-mix slices of those models used wherever a full program is needed at
toy shapes (sharding tests, the multi-chip dryrun, convergence smoke tests).
"""

from .models import (build_mlp, build_convnet_slice, build_seq_slice,
                     mlp_feed, convnet_feed, seq_feed)

__all__ = ["build_mlp", "build_convnet_slice", "build_seq_slice",
           "mlp_feed", "convnet_feed", "seq_feed"]
