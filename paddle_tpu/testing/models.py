"""Tiny parameterized training programs (model + optimizer) + matching feeds.

Each builder returns (main_program, startup_program, avg_loss_var). They are
the op-mix slices of the flagship benchmark / book models at toy shapes:

* build_mlp           — fc stack + softmax CE (recognize_digits MLP path)
* build_convnet_slice — conv+BN (NHWC) bottleneck with residual add, pooling,
                        fc head, momentum (bench.py resnet50 cut down)
* build_seq_slice     — ragged LoD tokens -> embedding -> fc -> dynamic GRU ->
                        per-token CE, Adam (machine_translation encoder mix)
"""

from __future__ import annotations

import numpy as np


def build_mlp(dim=16, classes=4, hidden=32, opt="momentum", lr=0.1, seed=7,
              depth=1, return_logits=False):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[dim])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = img
        for _ in range(depth):
            h = fluid.layers.fc(h, size=hidden, act="relu")
        logits = fluid.layers.fc(h, size=classes, act=None)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        if opt == "momentum":
            fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9).minimize(
                loss, startup)
        else:
            fluid.optimizer.Adam(learning_rate=min(lr, 1e-2)).minimize(
                loss, startup)
    if return_logits:
        return main, startup, loss, logits
    return main, startup, loss


def mlp_feed(batch, dim=16, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "img": rng.normal(0, 1, (batch, dim)).astype("float32"),
        "label": rng.randint(0, classes, (batch, 1)).astype("int64"),
    }


def build_convnet_slice(size=8, classes=4, nf=8, lr=0.05, seed=7,
                        bottleneck=False):
    """conv+BN NHWC + residual + pools + fc + momentum. With ``bottleneck``,
    adds the stem/1x1-3x3-1x1/projection structure of bench.py's ResNet."""
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed

    def conv_bn(x, filters, k, stride=1, act="relu"):
        c = fluid.layers.conv2d(x, num_filters=filters, filter_size=k,
                                stride=stride, padding=(k - 1) // 2,
                                bias_attr=False, data_format="NHWC")
        return fluid.layers.batch_norm(c, act=act, data_layout="NHWC")

    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[size, size, 3])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        if bottleneck:
            stem = conv_bn(img, nf, 3, stride=2)
            pool = fluid.layers.pool2d(stem, pool_size=3, pool_stride=2,
                                       pool_padding=1, pool_type="max",
                                       data_format="NHWC")
            b = conv_bn(pool, nf // 2, 1)
            b = conv_bn(b, nf // 2, 3)
            b = conv_bn(b, nf * 2, 1, act=None)
            short = conv_bn(pool, nf * 2, 1, act=None)
            x = fluid.layers.elementwise_add(x=b, y=short, act="relu")
        else:
            c = conv_bn(img, nf, 3)
            c2 = conv_bn(c, nf, 3, act=None)
            x = fluid.layers.elementwise_add(x=c2, y=c, act="relu")
            x = fluid.layers.pool2d(x, pool_size=2, pool_stride=2,
                                    pool_type="avg", data_format="NHWC")
        x = fluid.layers.pool2d(x, pool_size=2, global_pooling=True,
                                pool_type="avg", data_format="NHWC")
        logits = fluid.layers.fc(x, size=classes, act=None)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9).minimize(
            loss, startup)
    return main, startup, loss


def convnet_feed(batch, size=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "img": rng.normal(0, 1, (batch, size, size, 3)).astype("float32"),
        "label": rng.randint(0, classes, (batch, 1)).astype("int64"),
    }


def build_seq_slice(vocab=12, emb=8, hid=8, lr=1e-2, seed=7):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[1], dtype="int64", lod_level=1)
        tgt = fluid.layers.data("tgt", shape=[1], dtype="int64", lod_level=1)
        e = fluid.layers.embedding(src, size=[vocab, emb])
        h = fluid.layers.fc(e, size=hid * 3)
        h = fluid.layers.dynamic_gru(h, size=hid)
        logits = fluid.layers.fc(h, size=vocab, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=logits, label=tgt))
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss, startup)
    return main, startup, loss


def seq_feed(batch, vocab=12, min_len=2, max_len=7, seed=0):
    rng = np.random.RandomState(seed)
    lens = [int(rng.randint(min_len, max_len)) for _ in range(batch)]
    seqs = [rng.randint(0, vocab, (ln, 1)).astype("int64") for ln in lens]
    return {"src": list(seqs), "tgt": list(seqs)}


def build_tiny_lm(vocab=32, emb=16, heads=2, n_layers=2, max_pos=256,
                  seed=7):
    """Decoder-only LM at toy scale — the generative-serving test/bench
    model: token + learned position embeddings, ``n_layers`` pre-LN-free
    transformer blocks (fc q/k/v -> causal_self_attention -> residual +
    layer_norm -> 2x fc MLP -> residual + layer_norm), vocab logits head.
    Feeds ``tokens``/``positions`` [b, seq, 1] int64, fetches logits
    [b, seq, vocab] — exactly the generative-bundle convention
    serving/generate documents. Returns (main, startup, logits_var)."""
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        tokens = fluid.layers.data("tokens", shape=[-1, 1], dtype="int64")
        positions = fluid.layers.data("positions", shape=[-1, 1],
                                      dtype="int64")
        x = fluid.layers.elementwise_add(
            fluid.layers.embedding(tokens, size=[vocab, emb]),
            fluid.layers.embedding(positions, size=[max_pos, emb]))
        for _ in range(n_layers):
            q = fluid.layers.fc(x, size=emb, num_flatten_dims=2)
            k = fluid.layers.fc(x, size=emb, num_flatten_dims=2)
            v = fluid.layers.fc(x, size=emb, num_flatten_dims=2)
            a = fluid.layers.causal_self_attention(q, k, v, num_heads=heads)
            x = fluid.layers.layer_norm(
                fluid.layers.elementwise_add(x, a), begin_norm_axis=2)
            h = fluid.layers.fc(x, size=emb * 2, num_flatten_dims=2,
                                act="relu")
            h = fluid.layers.fc(h, size=emb, num_flatten_dims=2)
            x = fluid.layers.layer_norm(
                fluid.layers.elementwise_add(x, h), begin_norm_axis=2)
        logits = fluid.layers.fc(x, size=vocab, num_flatten_dims=2)
    return main, startup, logits


def export_tiny_lm(dirname, scope=None, **kw):
    """Build + init + save_inference_model a tiny LM bundle at
    ``dirname``; returns the scope holding its parameters (for reference
    full-window runs in parity tests)."""
    import paddle_tpu.fluid as fluid

    main, startup, logits = build_tiny_lm(**kw)
    exe = fluid.Executor()
    scope = scope or fluid.Scope()
    exe.run(startup, scope=scope)
    fluid.io.save_inference_model(dirname, ["tokens", "positions"],
                                  [logits], exe, main, scope=scope)
    return main, scope, logits
