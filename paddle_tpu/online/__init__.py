"""Online learning: continuous training that publishes into a live
serving fleet without dropping a request.

The missing subsystem between this repo's training plane and serving
plane — the end-to-end loop the original Paddle v2 Go/etcd pserver
cluster was famous for (PAPER.md): a model trains on an unbounded
stream, periodically freezes into a versioned inference bundle, and
rolls onto a replica fleet that keeps answering throughout.

* :class:`StreamingTrainer` (trainer.py) — pull/step/push forever over
  a ``reader``-package stream; publish triggers fire at step boundaries
  (``online_publish_every_steps`` / ``online_publish_every_s``) without
  stalling the hot path; pserver restarts are ridden through.
* :class:`CheckpointFreezer` (freezer.py) — barrier-consistent cuts of
  the sharded pserver state (every shard at the same sync round — never
  a torn mix), stitched through ``save_inference_model`` and published
  with lineage metadata (global step, parent version, freeze round).
* :class:`RolloutController` (rollout.py) — registry watcher driving
  canary-gated ``rolling_reload`` with min-serve-time hysteresis,
  permanent quarantine of canary-rejected versions, and optional
  registry gc.
* :class:`TrainerPool` / :class:`BacklogAutoscaler` /
  :func:`master_task_reader` (pool.py) — the elastic trainer fleet: N
  workers lease data chunks from a ``Master`` queue and hold sync-round
  barrier membership via pserver leases only while they possess work;
  the pool hot-joins replacements for crashed workers and the
  autoscaler sizes it from the Master's backlog.
* :class:`OnlineLearningLoop` (loop.py) — the whole supervised process
  tree under one start/stats/stop, chaos-tolerant by construction: a
  pserver shard and a serving replica can be SIGKILLed mid-loop with
  zero failed infer requests and a monotonically advancing served
  version; pass ``chunks=``/``chunk_feeds=`` for the elastic
  Master-fed pool instead of a single reader.
"""

from .freezer import CheckpointFreezer, FreezeError
from .loop import OnlineLearningLoop
from .pool import BacklogAutoscaler, TrainerPool, master_task_reader
from .rollout import RolloutController
from .trainer import StreamingTrainer

__all__ = ["StreamingTrainer", "CheckpointFreezer", "FreezeError",
           "RolloutController", "OnlineLearningLoop", "TrainerPool",
           "BacklogAutoscaler", "master_task_reader"]
