"""StreamingTrainer: train forever on an unbounded reader, publish on a
cadence, survive pserver restarts.

The trainer half of the original Paddle v2 online-learning story (the Go
pserver cluster that trains on an unbounded stream while the same model
serves): one thread looping pull -> forward/backward -> push against the
transpiled trainer program, with the optimizer living server-side. Three
properties matter beyond the plain loop in ``test_fluid_trainer``:

* **unbounded input** — the reader never ends; ``prefetch`` wraps it in
  ``reader.prefetch.background_buffer`` (the reader/pool.py staging
  machinery) so host-side batch prep overlaps the device step.
* **publish triggers that don't stall the hot path** — every
  ``online_publish_every_steps`` steps (and/or ``online_publish_every_s``
  seconds), checked AT A STEP BOUNDARY: the push has acked on every
  shard, no update is in flight, so ``CheckpointFreezer.request_freeze``
  can take a barrier-consistent cut with one cheap prepare RPC per
  shard; the heavy stitch/publish runs on the freezer's worker. A failed
  or skipped freeze does NOT reset the cadence — the trainer retries at
  the next boundary.
* **crash tolerance, in two phases** — a failed pull/forward/backward
  (pserver shard restarting; the ParamClient's RetryPolicy exhausted)
  is COUNTED and its batch dropped, not fatal: nothing remote was
  mutated yet, online learning tolerates a lost batch, and a dead
  training loop loses the whole stream. A failed PUSH is different:
  some shard may already have applied it (advancing its sync round),
  so the push retries WITH THE SAME SEQUENCE NUMBER until every shard
  acks — applied shards answer from the dedup table, the restarted
  shard applies, and the rounds stay in lockstep (dropping a partially
  applied push would desynchronize the rounds forever and every later
  freeze cut would be rejected as torn). A reader failure ends the
  stream but lands loudly in ``stats()`` (``reader_failed`` +
  ``last_error``), never as a silently dead thread.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..core.flags import get_flag
from ..obs.metrics import (REGISTRY as _METRICS, json_safe,
                           next_instance)
from ..obs.recorder import record as _flight_record

_M_STEPS = _METRICS.counter(
    "paddle_tpu_online_trainer_steps",
    "global steps completed by a StreamingTrainer (push acked on every "
    "shard), per instance", labels=("instance",))
_M_STEP_FAILURES = _METRICS.counter(
    "paddle_tpu_online_trainer_step_failures",
    "dropped batches (pull/run failures; push retries are separate), "
    "per instance", labels=("instance",))
_M_PUSH_RETRIES = _METRICS.counter(
    "paddle_tpu_online_trainer_push_retries",
    "same-seq push retries riding out shard restarts, per instance",
    labels=("instance",))
_M_STEP_SECONDS = _METRICS.histogram(
    "paddle_tpu_online_train_step_seconds",
    "StreamingTrainer full-step latency window, per instance",
    labels=("instance",), span_name="online/train_step",
    span_kind="online")


class _Stopped(Exception):
    """Internal: the trainer was stopped while retrying a push."""


class StreamingTrainer:
    """Continuous trainer over a transpiled program.

        t = fluid.DistributeTranspiler()
        t.transpile(0, program=main, pservers=..., trainers=1)
        client = t.trainer_client(retry=RetryPolicy(), endpoints=sup.addresses)
        trainer = StreamingTrainer(exe, scope, t.get_trainer_program(),
                                   t.params_grads, client, reader,
                                   freezer=freezer)
        trainer.start()
        ... trainer.stats() ...
        trainer.stop()

    ``reader`` is a paddle-style creator: a callable returning an
    iterator of FEED DICTS (name -> batch ndarray). ``params_grads`` is
    the transpiler's ``[(param, grad)]`` list — the grads are fetched
    each step and pushed under their param names. ``extra_fetch`` names
    (e.g. the loss) are fetched alongside and surfaced through
    ``stats()["last_extra"]``.
    """

    def __init__(self, executor, scope, program, params_grads, client,
                 reader, freezer=None, publish_every_steps=None,
                 publish_every_s=None, extra_fetch=(), prefetch=2):
        self._exe = executor
        self._scope = scope
        self._program = program
        self._pg = [(p, g) for p, g in params_grads]
        self._client = client
        self._reader = reader
        self._freezer = freezer
        if publish_every_steps is None:
            publish_every_steps = int(get_flag("online_publish_every_steps"))
        if publish_every_s is None:
            publish_every_s = float(get_flag("online_publish_every_s"))
        self._pub_steps = int(publish_every_steps)
        self._pub_s = float(publish_every_s)
        self._extra = [e if isinstance(e, str) else e.name
                       for e in extra_fetch]
        self._fetch = [g for _p, g in self._pg] + self._extra
        self._prefetch = int(prefetch)
        self._step = 0
        self._reader_failed = False
        self._publish_requests = 0
        self._publish_accepted = 0
        self._pending_job = None     # last ACCEPTED cut, until resolved
        self._last_error = None
        self._last_extra = {}
        # step/failure/retry counters + step latency live in the
        # obs.metrics registry under this trainer's instance label
        # (stats() derives from them; _step stays local — it is loop
        # control state, mirrored into the counter at each boundary)
        self.obs_instance = next_instance("trainer")
        self._m_steps = _M_STEPS.labels(instance=self.obs_instance)
        self._m_step_failures = _M_STEP_FAILURES.labels(
            instance=self.obs_instance)
        self._m_push_retries = _M_PUSH_RETRIES.labels(
            instance=self.obs_instance)
        self.step_latency = _M_STEP_SECONDS.labels(
            instance=self.obs_instance)
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------------
    @property
    def global_step(self):
        return self._step

    def running(self):
        return self._thread is not None and self._thread.is_alive()

    def start(self):
        if self.running():
            raise RuntimeError("trainer already running")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="streaming-trainer")
        self._thread.start()
        return self

    def stop(self, timeout=30.0):
        """Stop at the next step boundary; returns True once joined."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            return not self._thread.is_alive()
        return True

    # ------------------------------------------------------------------
    def _push_with_retry(self, grads):
        """Push until every shard acks, re-sending the SAME sequence
        number across attempts (see ``ParamClient.allocate_seq``): a
        sync-mode trainer cannot make progress past a dead shard anyway,
        and retrying is the only path that keeps the shards' rounds
        consistent. Gives up only when the trainer is stopped."""
        seq = self._client.allocate_seq()
        while True:
            try:
                return self._client.push(grads, seq=seq)
            except Exception as e:
                self._m_push_retries.inc()
                # the retry DECISION: a partially-applied push is being
                # re-sent with the SAME seq through a shard restart —
                # exactly what an incident bundle needs to explain a
                # training stall
                _flight_record("push_retry", component=self.obs_instance,
                               seq=seq, error=type(e).__name__)
                self._last_error = f"push(seq={seq}): " \
                                   f"{type(e).__name__}: {e}"
                if self._stop.wait(0.25):
                    raise _Stopped from e

    def _publish_due(self, steps_since, last_t):
        if self._freezer is None:
            return False
        if self._pending_job is not None and self._pending_job.done():
            failed = self._pending_job.failed()
            self._pending_job = None
            if failed:
                # the ACCEPTED cut died in its async stitch (a shard
                # restarted between prepare and fetch, a publish error):
                # the publish it stood for never happened, so it is due
                # NOW, not a full cadence later — the cadence reset at
                # acceptance was provisional
                return True
        if self._pub_steps > 0 and steps_since >= self._pub_steps:
            return True
        if self._pub_s > 0 and time.monotonic() - last_t >= self._pub_s:
            return True
        return False

    def _run(self):
        reader = self._reader
        if self._prefetch > 0:
            from ..reader.prefetch import background_buffer
            reader = background_buffer(reader, self._prefetch)
        steps_since_pub = 0
        last_pub_t = time.monotonic()
        it = iter(reader())
        while not self._stop.is_set():
            try:
                feed = next(it)
            except StopIteration:
                break                      # bounded reader (tests) drained
            except Exception as e:
                # a broken data source is not recoverable from here, but
                # it must be LOUD in stats, not a silently dead thread
                self._last_error = f"reader: {type(e).__name__}: {e}"
                self._reader_failed = True
                break
            try:
                # phase 1 — pull + forward/backward: nothing remote
                # mutated yet, so a failure here safely DROPS the batch
                with self.step_latency.span():
                    for n, v in self._client.pull().items():
                        self._scope.set(n, v)
                    fetched = self._exe.run(self._program, feed=feed,
                                            fetch_list=self._fetch,
                                            scope=self._scope)
                    # SparseRows grads (is_sparse embeddings) ship as-is
                    # on the O(touched-rows) wire; dense grads as host
                    # ndarrays
                    grads = {p: f if hasattr(f, "rows") else np.asarray(f)
                             for (p, _g), f in zip(self._pg, fetched)}
                    # phase 2 — push: once sent, SOME shard may have
                    # applied it (advancing its sync round), so a failed
                    # push is RETRIED WITH THE SAME SEQ until every shard
                    # acks — shards that applied answer from the dedup
                    # table, the restarted one applies, and the rounds
                    # stay in lockstep. Dropping a partially-applied push
                    # would desynchronize the rounds FOREVER and every
                    # later freeze cut would be rejected as torn.
                    self._push_with_retry(grads)
                if self._extra:
                    base = len(self._pg)
                    self._last_extra = {
                        n: np.asarray(fetched[base + i]).tolist()
                        for i, n in enumerate(self._extra)}
                self._step += 1
                self._m_steps.inc()
                steps_since_pub += 1
            except _Stopped:
                break
            except Exception as e:
                # pull/run failure (restarting shard): count, drop the
                # batch, back off a beat, continue
                self._m_step_failures.inc()
                self._last_error = f"{type(e).__name__}: {e}"
                if self._stop.wait(0.05):
                    break
                continue
            # the step BOUNDARY: push acked on every shard, nothing in
            # flight — the one instant a barrier-consistent cut is free
            if self._publish_due(steps_since_pub, last_pub_t):
                self._publish_requests += 1
                try:
                    job = self._freezer.request_freeze(self._step)
                except RuntimeError as e:
                    # freezer closed out from under a still-running
                    # trainer: keep training, stop triggering
                    self._last_error = f"{type(e).__name__}: {e}"
                    self._freezer = None
                    continue
                if job is not None:
                    self._publish_accepted += 1
                    self._pending_job = job
                    steps_since_pub = 0
                    last_pub_t = time.monotonic()
                # else: cut failed / stitcher busy — cadence NOT reset,
                # the next boundary retries (freezer.stats has details)

    # ------------------------------------------------------------------
    def stats(self):
        return json_safe(
            {"global_step": self._step,
             "running": self.running(),
             "step_failures": int(self._m_step_failures.value),
             "push_retries": int(self._m_push_retries.value),
             "reader_failed": self._reader_failed,
             "publish_requests": self._publish_requests,
             "publish_accepted": self._publish_accepted,
             "last_error": self._last_error,
             "last_extra": dict(self._last_extra),
             "step_latency": self.step_latency.snapshot()})


__all__ = ["StreamingTrainer"]
