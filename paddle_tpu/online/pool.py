"""TrainerPool: an elastic fleet of StreamingTrainer workers, fed from
the Master task queue and scaled by its backlog.

The trainer-side half of the elastic-training story (the serving half is
the FleetSupervisor): the original Paddle v2 design trains with however
many trainers happen to be alive — trainers lease task chunks from the
Go master, a dead trainer's leases time out and re-dispatch, and the
cluster manager adds or removes trainer pods with traffic. Three pieces
reproduce that here, all over machinery this repo already has:

* :func:`master_task_reader` — the trainer feed path: a reader creator
  whose iterator leases tasks from the :class:`~..distributed.master.
  Master` queue and yields the chunks' feed dicts. ``task_finished``
  fires only when the iterator is asked for the batch AFTER a task's
  last one — with ``prefetch=0`` that is exactly when every batch of
  the task has completed its step (push acked on every shard), so a
  worker crash mid-task never marks the task done: its lease expires
  and the chunks re-dispatch (at-least-once, the Master contract).
* :class:`TrainerPool` — hot-join/retire supervisor over N in-process
  :class:`~.trainer.StreamingTrainer` workers. Each worker gets its own
  ParamClient (unique trainer id), registers a membership lease on
  every pserver shard, and renews it from a per-worker heartbeat thread
  — so a worker blocked at a sync barrier stays a member, while a
  killed worker stops renewing and the shard barriers SHRINK past it at
  lease expiry instead of timing out. A monitor thread reaps crashed
  workers (counted as ``lease_expired``, never as graceful leaves) and
  hot-joins replacements back up to the floor.
* :class:`BacklogAutoscaler` — closes the loop: polls
  ``Master.backlog()``, publishes the pending depth as the
  ``paddle_tpu_online_backlog_tasks`` gauge, judges it with the same
  multi-window :class:`~..obs.slo.SloRule` burn machinery the serving
  SLOs use, and grows the pool one worker per poll while the scale-up
  rule burns (up to ``online_trainers_max``), shrinking back one per
  idle streak once the queue is drained (down to
  ``online_trainers_min``).
"""

from __future__ import annotations

import threading
import time

from ..core.flags import get_flag
from ..obs.metrics import REGISTRY as _METRICS, json_safe, next_instance
from ..obs.recorder import record as _flight_record

_M_JOINS = _METRICS.counter(
    "paddle_tpu_online_trainer_joins",
    "StreamingTrainer workers hot-joined into a TrainerPool (initial "
    "boot, crash replacement, scale-up), per pool instance",
    labels=("instance",))
_M_LEAVES = _METRICS.counter(
    "paddle_tpu_online_trainer_leaves",
    "StreamingTrainer workers retired GRACEFULLY from a TrainerPool "
    "(lease deregistered on every shard), per pool instance",
    labels=("instance",))
_M_LEASE_EXPIRED = _METRICS.counter(
    "paddle_tpu_online_trainer_lease_expired",
    "TrainerPool workers that left WITHOUT deregistering (killed or "
    "crashed — their pserver leases were left to expire and the open "
    "sync barriers shrank past them), per pool instance",
    labels=("instance",))
_M_BACKLOG = _METRICS.gauge(
    "paddle_tpu_online_backlog_tasks",
    "pending (unleased) Master task-queue depth as last polled by the "
    "BacklogAutoscaler — the trainer autoscaler's control signal, per "
    "pool instance", labels=("instance",))


def master_task_reader(address, chunk_feeds, stop=None, follow=True,
                       poll_s=0.1, membership=None):
    """Reader creator leasing task chunks from a Master queue.

    ``address`` is the master RPC endpoint; ``chunk_feeds(chunk)``
    yields the feed dicts one chunk trains on. The returned creator is
    what StreamingTrainer consumes (``prefetch=0`` there — see module
    docstring for why the finish point depends on it). ``stop`` (a
    threading.Event) aborts between batches WITHOUT finishing the
    current task — the crash/retire path; its lease expires and the
    chunks re-dispatch. ``follow=True`` keeps the iterator alive across
    pass boundaries, polling for the next ``set_dataset``; False ends
    the stream when the current pass completes (bounded tests).

    ``membership`` (the worker's ParamClient) ties the pserver
    barrier-membership lease to TASK POSSESSION: register on acquiring
    a task, deregister when going idle. This is the load-bearing rule
    of elastic sync training — a worker polling an empty queue must NOT
    be a barrier member (its peers' rounds would wait the full lease on
    it, or the full barrier timeout if anything kept renewing), while a
    worker mid-task must be one (so killing it shrinks the barrier at
    lease expiry instead of stalling it). Pushes renew the lease while
    the task is being worked, so no heartbeat thread is needed."""
    from ..distributed.master import MasterClient

    def _join():
        if membership is not None:
            try:
                membership.register_trainer()
            except Exception:
                pass     # shard restarting: the push retry re-joins us

    def _leave():
        if membership is not None:
            try:
                membership.deregister_trainer()
            except Exception:
                pass

    def reader():
        mc = MasterClient(tuple(address))
        member = False
        try:
            while stop is None or not stop.is_set():
                t = mc.get_task()
                if t is None or t.get("wait"):
                    # pass complete (None) or all tasks leased: either
                    # way there is nothing to lease right now — leave
                    # the barrier membership so peers don't wait on an
                    # idle worker
                    if member:
                        _leave()
                        member = False
                    if t is None and not follow:
                        return
                    if stop is not None:
                        if stop.wait(poll_s):
                            return
                    else:
                        time.sleep(poll_s)
                    continue
                if not member:
                    _join()
                    member = True
                for chunk in t["chunks"]:
                    for feed in chunk_feeds(chunk):
                        yield feed
                        if stop is not None and stop.is_set():
                            return   # abandoned mid-task: lease expires
                # resumed past the task's last yield: every batch of
                # this task finished its step (push acked) — the one
                # correct instant to mark the lease done
                mc.finished(t["task_id"], t["epoch"])
        finally:
            if member:
                _leave()
            mc.close()

    return reader


class _Worker:
    __slots__ = ("wid", "trainer", "stop_ev", "state")

    def __init__(self, wid, trainer, stop_ev):
        self.wid = wid
        self.trainer = trainer
        self.stop_ev = stop_ev
        self.state = "live"        # live | retiring | crashed


class TrainerPool:
    """Hot-join/retire supervisor over in-process StreamingTrainers.

        pool = TrainerPool(spawn_fn, min_workers=1, max_workers=4)
        pool.start()            # boots min_workers
        pool.add_worker()       # hot-join (scale-up / test chaos)
        pool.kill(wid)          # crash a worker: NO deregister, NO
                                # task_finished — leases expire
        pool.retire_worker(wid) # graceful leave: deregisters everywhere
        pool.stats(); pool.stop()

    ``spawn_fn(worker_id, stop_event)`` returns a STARTABLE (not yet
    started) StreamingTrainer wired with its own ParamClient (unique
    ``trainer_id``) and a reader that honors ``stop_event`` (e.g.
    :func:`master_task_reader`, which also ties the worker's pserver
    barrier-membership lease to task possession — pushes renew it, so
    no heartbeat thread exists to keep a dead worker looking alive).
    The pool supervises: a worker whose thread dies (or is ``kill``ed)
    is counted as ``lease_expired`` and replaced up to ``min_workers``.
    """

    def __init__(self, spawn_fn, min_workers=None, max_workers=None,
                 supervise_s=0.25, stop_timeout_s=30.0):
        if min_workers is None:
            min_workers = int(get_flag("online_trainers_min"))
        if max_workers is None:
            max_workers = int(get_flag("online_trainers_max"))
        if min_workers < 0 or max_workers < max(1, min_workers):
            raise ValueError(
                f"need 0 <= min_workers <= max_workers (and max >= 1), "
                f"got min={min_workers} max={max_workers}")
        self._spawn_fn = spawn_fn
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self._supervise_s = float(supervise_s)
        self._stop_timeout = float(stop_timeout_s)
        self.obs_instance = next_instance("trainer_pool")
        self._m_joins = _M_JOINS.labels(instance=self.obs_instance)
        self._m_leaves = _M_LEAVES.labels(instance=self.obs_instance)
        self._m_lease_expired = _M_LEASE_EXPIRED.labels(
            instance=self.obs_instance)
        self._lock = threading.Lock()
        self._workers = {}            # wid -> _Worker
        # steps banked by departed workers: keeps global_step() (the
        # publish-lineage clock) monotone across churn — a kill must
        # never make the fleet's step counter jump backwards
        self._steps_departed = 0
        self._next_id = 0
        self._stop = threading.Event()
        self._monitor = None
        # incident trigger (IncidentCollector.trigger), fired when the
        # supervisor reaps a crashed worker — same contract as
        # ChildSupervisor.incident_hook
        self.incident_hook = None

    # ------------------------------------------------------------------
    def start(self):
        if self._monitor is not None and self._monitor.is_alive():
            raise RuntimeError("pool already running")
        self._stop.clear()
        for _ in range(self.min_workers):
            self.add_worker()
        self._monitor = threading.Thread(target=self._watch, daemon=True,
                                         name="trainer-pool")
        self._monitor.start()
        return self

    def size(self):
        """Live worker count (crashed-but-unreaped workers excluded)."""
        with self._lock:
            return sum(1 for w in self._workers.values()
                       if w.state == "live" and w.trainer.running())

    def worker_ids(self):
        with self._lock:
            return sorted(w.wid for w in self._workers.values()
                          if w.state == "live")

    # ------------------------------------------------------------------
    def add_worker(self):
        """Hot-join one worker (noop past ``max_workers``); returns the
        worker id, or None when at capacity. The join is visible as a
        ``paddle_tpu_online_trainer_joins`` bump and a ``trainer_join``
        flight event — membership churn must land in incident bundles."""
        with self._lock:
            if self._stop.is_set():
                return None
            live = [w for w in self._workers.values() if w.state == "live"]
            if len(live) >= self.max_workers:
                return None
            wid = self._next_id
            self._next_id += 1
        stop_ev = threading.Event()
        trainer = self._spawn_fn(wid, stop_ev)
        w = _Worker(wid, trainer, stop_ev)
        trainer.start()
        with self._lock:
            self._workers[wid] = w
        self._m_joins.inc()
        _flight_record("trainer_join", component=self.obs_instance,
                       worker=wid, trainer=trainer.obs_instance)
        return wid

    def retire_worker(self, wid, timeout=None):
        """Graceful leave: stop at a step boundary, deregister the
        membership lease on every shard (open barriers shrink NOW, no
        expiry wait), close the client. Returns True when the worker
        existed and stopped."""
        with self._lock:
            w = self._workers.get(wid)
            if w is None or w.state != "live":
                return False
            w.state = "retiring"
        w.stop_ev.set()
        stopped = w.trainer.stop(self._stop_timeout
                                 if timeout is None else timeout)
        try:
            w.trainer._client.deregister_trainer()
        except Exception:
            pass
        try:
            w.trainer._client.close()
        except Exception:
            pass
        with self._lock:
            self._workers.pop(wid, None)
            self._steps_departed += int(w.trainer.global_step)
        self._m_leaves.inc()
        _flight_record("trainer_leave", component=self.obs_instance,
                       worker=wid, reason="retired",
                       trainer=w.trainer.obs_instance)
        return stopped

    def kill(self, wid):
        """Crash a worker (test/chaos hook — the in-process analog of a
        SIGKILL): the heartbeat and reader stop INSTANTLY, nothing is
        deregistered and no in-flight task is finished — its pserver
        leases expire (shrinking any open barrier) and its Master task
        leases time out and re-dispatch. Counted as ``lease_expired``,
        never as a graceful leave."""
        with self._lock:
            w = self._workers.get(wid)
            if w is None or w.state != "live":
                return False
            w.state = "crashed"
        w.stop_ev.set()
        # crash fidelity: a SIGKILLed process never deregisters, so the
        # graceful-leave path is neutralized — otherwise the reader's
        # finalizer would politely leave the barrier, and the lease-
        # EXPIRY shrink (the machinery this hook exists to exercise)
        # would never fire
        try:
            w.trainer._client.deregister_trainer = lambda: False
        except Exception:
            pass
        w.trainer.stop(1.0)   # a wedged push thread is abandoned, daemon
        try:
            w.trainer._client.close()
        except Exception:
            pass
        with self._lock:
            self._workers.pop(wid, None)
            self._steps_departed += int(w.trainer.global_step)
        self._m_lease_expired.inc()
        _flight_record("trainer_leave", component=self.obs_instance,
                       worker=wid, reason="killed",
                       trainer=w.trainer.obs_instance)
        if self.incident_hook is not None:
            try:
                self.incident_hook("child_restart",
                                   detail={"supervisor": self.obs_instance,
                                           "worker": wid,
                                           "reason": "killed"})
            except Exception:
                pass
        return True

    # ------------------------------------------------------------------
    def _watch(self):
        """Reap workers whose trainer thread died on its own (reader
        blew up, stop() raced) and hot-join replacements up to the
        floor — the pool's supervision contract."""
        while not self._stop.wait(self._supervise_s):
            dead = []
            with self._lock:
                for w in list(self._workers.values()):
                    if w.state == "live" and not w.trainer.running():
                        w.state = "crashed"
                        dead.append(w)
                        self._workers.pop(w.wid, None)
                        self._steps_departed += int(w.trainer.global_step)
            for w in dead:
                w.stop_ev.set()
                try:
                    w.trainer._client.close()
                except Exception:
                    pass
                self._m_lease_expired.inc()
                _flight_record("trainer_leave",
                               component=self.obs_instance,
                               worker=w.wid, reason="died",
                               trainer=w.trainer.obs_instance)
                if self.incident_hook is not None:
                    try:
                        self.incident_hook(
                            "child_restart",
                            detail={"supervisor": self.obs_instance,
                                    "worker": w.wid, "reason": "died"})
                    except Exception:
                        pass
            # top up to the floor every tick — covers self-died workers
            # reaped above AND explicitly kill()ed ones (already popped)
            while (self.size() < self.min_workers
                   and not self._stop.is_set()):
                if self.add_worker() is None:
                    break

    # ------------------------------------------------------------------
    def scale_to(self, n):
        """Move the live worker count toward ``n`` (clamped to
        [min_workers, max_workers]): hot-join or retire one worker at a
        time. Returns the resulting live count."""
        n = max(self.min_workers, min(self.max_workers, int(n)))
        while self.size() < n:
            if self.add_worker() is None:
                break
        while self.size() > n:
            ids = self.worker_ids()
            if not ids or not self.retire_worker(ids[-1]):
                break
        return self.size()

    def global_step(self):
        """Total steps the fleet has applied: live workers' counters
        plus the banked counts of every departed worker. MONOTONE under
        churn — this is the publish-lineage clock, and a version
        stamped after a kill must never carry a smaller step than one
        stamped before it."""
        with self._lock:
            return self._steps_departed + sum(
                w.trainer.global_step for w in self._workers.values())

    def stats(self):
        with self._lock:
            workers = {w.wid: {"state": w.state,
                               "trainer": w.trainer.stats()}
                       for w in self._workers.values()}
        return json_safe({
            "size": self.size(),
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "joins": int(self._m_joins.value),
            "leaves": int(self._m_leaves.value),
            "lease_expired": int(self._m_lease_expired.value),
            "workers": workers,
        })

    def stop(self):
        """Retire every worker gracefully and stop supervising.
        Idempotent."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(self._supervise_s * 4 + 1.0)
            self._monitor = None
        for wid in list(self._workers):
            with self._lock:
                w = self._workers.get(wid)
                if w is None:
                    continue
                w.state = "retiring"
            w.stop_ev.set()
            w.trainer.stop(self._stop_timeout)
            try:
                w.trainer._client.deregister_trainer()
            except Exception:
                pass
            try:
                w.trainer._client.close()
            except Exception:
                pass
            with self._lock:
                self._workers.pop(wid, None)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class BacklogAutoscaler:
    """Scale a TrainerPool from the Master's backlog via SloRules.

    ``backlog_fn()`` returns ``{pending, leased, failed}`` (the
    Master/MasterClient ``backlog()`` surface). Every poll publishes
    the pending depth to the ``paddle_tpu_online_backlog_tasks`` gauge,
    evaluates the scale-up rules with the standard multi-window burn
    machinery (:class:`~..obs.slo.SloMonitor`), and then:

    * any rule breached -> hot-join ONE worker (up to the pool max);
    * queue fully drained (pending == leased == 0) for ``idle_polls``
      consecutive polls -> retire ONE worker (down to the pool min).

    One step per poll keeps scaling smooth — the burn windows already
    damp flapping. Default rule: pending depth measured against an
    objective of one task per pool-max worker over a short window."""

    def __init__(self, pool, backlog_fn, rules=None, poll_s=None,
                 idle_polls=3, on_breach=None):
        from ..obs.slo import SloMonitor, SloRule

        self.pool = pool
        self._backlog_fn = backlog_fn
        self._poll_s = float(get_flag("obs_slo_interval_s")
                             if poll_s is None else poll_s)
        self._idle_polls = int(idle_polls)
        if rules is None:
            rules = [SloRule(
                "online_trainer_backlog",
                metric="paddle_tpu_online_backlog_tasks",
                objective=float(max(1, pool.max_workers)),
                reducer="value",
                labels={"instance": pool.obs_instance},
                windows=((max(2.0 * self._poll_s, 1.0), 1.0),),
                description="pending Master tasks per max-pool worker; "
                            "burning means ingest is outrunning the "
                            "current trainer fleet")]
        self._monitor = SloMonitor(rules, interval_s=self._poll_s,
                                   on_breach=on_breach)
        self._m_backlog = _M_BACKLOG.labels(instance=pool.obs_instance)
        self._idle_streak = 0
        self._scale_ups = 0
        self._scale_downs = 0
        self._last_backlog = None
        self._last_error = None
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------------
    def poll_once(self):
        """One control-loop pass (also the test entry): measure, judge,
        maybe scale one step. Returns the per-rule status."""
        b = self._backlog_fn()
        self._last_backlog = dict(b)
        self._m_backlog.set(float(b["pending"]))
        status = self._monitor.evaluate_once()
        burning = any(not s["ok"] for s in status.values())
        if burning:
            self._idle_streak = 0
            if self.pool.size() < self.pool.max_workers:
                if self.pool.add_worker() is not None:
                    self._scale_ups += 1
        elif b["pending"] == 0 and b["leased"] == 0:
            self._idle_streak += 1
            if self._idle_streak >= self._idle_polls:
                self._idle_streak = 0
                if self.pool.size() > self.pool.min_workers:
                    ids = self.pool.worker_ids()
                    if ids and self.pool.retire_worker(ids[-1]):
                        self._scale_downs += 1
        else:
            self._idle_streak = 0
        return status

    def _watch(self):
        while not self._stop.wait(self._poll_s):
            try:
                self.poll_once()
            except Exception as e:   # the control loop must never die
                self._last_error = f"{type(e).__name__}: {e}"

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("autoscaler already running")
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="trainer-autoscaler")
        self._thread.start()
        return self

    def stop(self, timeout=10.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        return True

    def stats(self):
        return json_safe({
            "poll_s": self._poll_s,
            "backlog": self._last_backlog,
            "scale_ups": self._scale_ups,
            "scale_downs": self._scale_downs,
            "idle_streak": self._idle_streak,
            "pool_size": self.pool.size(),
            "rules": self._monitor.status(),
            "last_error": self._last_error,
        })


__all__ = ["TrainerPool", "BacklogAutoscaler", "master_task_reader"]
