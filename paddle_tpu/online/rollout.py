"""RolloutController: registry watcher driving canary-gated fleet
rollouts with hysteresis and automatic bad-version quarantine.

The serving half of the online loop's control plane. A background thread
polls the ModelRegistry for versions newer than what the fleet serves
and drives ``FleetSupervisor.rolling_reload`` — with three safeguards a
naive "always roll latest" watcher lacks:

* **min-serve-time hysteresis** (``online_min_serve_s``): a new rollout
  never starts until the current version has served that long, so a
  flapping trainer publishing every few steps cannot churn the fleet;
  intermediate versions are skipped (the controller always targets the
  NEWEST eligible version, not the next one).
* **bad-version quarantine**: a :class:`~..serving.fleet.CanaryFailed`
  rollout (the canary ANSWERED and rejected the bundle, then was rolled
  back) marks that version bad FOREVER — it is never retried, the fleet
  keeps serving the previous version, and the loop advances only when
  the trainer publishes a newer good version. Transient failures — the
  canary merely unreachable (killed mid-reload; restarting), or a
  replica crash after the canary passed — surface as plain
  RuntimeErrors and condemn nothing: crashed replicas restart onto the
  current version, and an alive-but-stale replica (reload RPC failed,
  replica kept serving the old engine) is reconverged by re-driving
  ``rolling_reload`` at the served version on a later poll.
* **monotonic targets**: the controller only rolls FORWARD (target >
  served). Rollback exists solely as the canary's safety net inside
  ``rolling_reload``; the served version as reported by the supervisor
  never regresses.

Observability: ``stats()`` carries rollout/rollback counters, the
quarantine set, and a publish-to-served lag window (wall-clock from the
manifest's ``published_at`` to rollout completion — the end-to-end
freshness metric of the whole loop). With ``online_registry_keep`` > 0
the controller garbage-collects the registry after each successful
rollout, pinning the version it just served.
"""

from __future__ import annotations

import threading
import time

from ..core.flags import get_flag
from ..obs.metrics import (REGISTRY as _METRICS, json_safe,
                           next_instance)
from ..obs.recorder import record as _flight_record
from ..serving.fleet import CanaryFailed

# rollout outcomes in the obs.metrics registry: ok / canary_failed
# (quarantined) / error (transient) / converge_repair — stats() derives
# its counters from these children
_M_ROLLOUTS = _METRICS.counter(
    "paddle_tpu_online_rollouts",
    "RolloutController outcomes (ok, canary_failed, error, "
    "converge_repair), per instance", labels=("instance", "outcome"))
_M_GC_DELETED = _METRICS.counter(
    "paddle_tpu_online_registry_gc_deleted",
    "registry versions garbage-collected after rollouts, per instance",
    labels=("instance",))
_M_PUBLISH_TO_SERVED = _METRICS.histogram(
    "paddle_tpu_online_publish_to_served_seconds",
    "publish-to-served lag window (manifest published_at -> rollout "
    "complete), per instance", labels=("instance",),
    span_name="online/publish_to_served", span_kind="online")


class RolloutController:
    """Watch ``registry`` and keep ``supervisor`` on the newest good
    version.

        ctl = RolloutController(registry, "ranker", fleet_sup)
        ctl.start()
        ... ctl.stats() ...
        ctl.stop()
    """

    def __init__(self, registry, model, supervisor, poll_interval_s=None,
                 min_serve_s=None, rollout_timeout_s=120.0,
                 registry_keep=None, incident_collector=None,
                 warm_cache=False, warm_kwargs=None):
        self._registry = registry
        self._model = model
        self._sup = supervisor
        # warm_cache: before rolling a target version out, build its
        # persistent compiled-executable artifacts (registry.warm) so
        # every replica's reload warmup LOADS instead of compiles — the
        # controller pays each compile once, the fleet pays none. Best
        # effort: a failed warm never blocks the rollout (replicas just
        # compile as before). The artifacts must be built for the
        # FLEET'S engine geometry or every replica would silently miss:
        # warm_kwargs overrides, else the supervisor's configured
        # buckets are threaded through.
        self._warm_cache = bool(warm_cache)
        self._warm_kwargs = dict(warm_kwargs or {})
        if self._warm_cache and "buckets" not in self._warm_kwargs \
                and "gen_opts" not in self._warm_kwargs:
            buckets = getattr(supervisor, "_cfg", {}).get("buckets")
            if buckets is not None:
                self._warm_kwargs["buckets"] = buckets
        # obs.recorder.IncidentCollector (or any callable-bearing twin):
        # a canary failure triggers a fleet-wide flight-recorder bundle
        self._incidents = incident_collector
        if poll_interval_s is None:
            poll_interval_s = float(get_flag("online_rollout_poll_ms")) / 1e3
        if min_serve_s is None:
            min_serve_s = float(get_flag("online_min_serve_s"))
        if registry_keep is None:
            registry_keep = int(get_flag("online_registry_keep"))
        self._poll_s = float(poll_interval_s)
        self._min_serve_s = float(min_serve_s)
        self._timeout = float(rollout_timeout_s)
        self._keep = int(registry_keep)
        self._bad = set()
        self._lock = threading.Lock()
        self._needs_converge = False
        self._last_error = None
        self._last_rollout_t = None
        # outcome counters + lag window in the obs.metrics registry
        self.obs_instance = next_instance("rollout")
        self._m_ok = _M_ROLLOUTS.labels(instance=self.obs_instance,
                                        outcome="ok")
        self._m_canary = _M_ROLLOUTS.labels(instance=self.obs_instance,
                                            outcome="canary_failed")
        self._m_errors = _M_ROLLOUTS.labels(instance=self.obs_instance,
                                            outcome="error")
        self._m_converge = _M_ROLLOUTS.labels(instance=self.obs_instance,
                                              outcome="converge_repair")
        self._m_gc = _M_GC_DELETED.labels(instance=self.obs_instance)
        self.publish_to_served = _M_PUBLISH_TO_SERVED.labels(
            instance=self.obs_instance)
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("rollout controller already running")
        self._stop.clear()
        # hysteresis measures SERVE time, and the initial version started
        # serving when the fleet came up — so the clock starts now, not
        # at the first rollout
        self._last_rollout_t = time.monotonic()
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="rollout-controller")
        self._thread.start()
        return self

    def stop(self, timeout=None):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self._timeout + 5.0
                              if timeout is None else timeout)
            return not self._thread.is_alive()
        return True

    # ------------------------------------------------------------------
    def _eligible_target(self):
        """Newest published version that is newer than served and not
        quarantined — None when the fleet is already current."""
        try:
            versions = self._registry.versions(self._model)
        except ValueError:
            return None
        served = self._sup.version
        good = [v for v in versions if v > served and v not in self._bad]
        return good[-1] if good else None

    def _maybe_reconverge(self):
        """A transient failure AFTER the canary passed leaves the
        supervisor's version advanced past a replica that is alive but
        stale (a failed reload RPC on a healthy replica leaves it
        serving the old engine — the crash-restart path never touches
        it). The forward-only eligibility filter cannot see this
        (served == target), so after any transient rollout error,
        re-drive ``rolling_reload`` AT the served version until every
        replica reports it; replicas already on it are skipped."""
        if not self._needs_converge:
            return
        served = self._sup.version
        mixed = False
        for i in range(len(self._sup.addresses)):
            h = self._sup.replica_health(i)
            if h is None or h.get("version") != served:
                mixed = True
                break
        if not mixed:
            self._needs_converge = False
            return
        try:
            self._sup.rolling_reload(served, wait_timeout=self._timeout)
            self._m_converge.inc()
            self._needs_converge = False
        except Exception as e:
            self._m_errors.inc()
            with self._lock:
                self._last_error = f"converge: {type(e).__name__}: {e}"

    def _poll(self):
        target = self._eligible_target()
        if target is None:
            self._maybe_reconverge()
            return
        if (time.monotonic() - self._last_rollout_t) < self._min_serve_s:
            return                       # hysteresis: let the fleet serve
        if self._warm_cache:
            try:
                self._registry.warm(self._model, target,
                                    **self._warm_kwargs)
            except Exception as e:
                # the warm is an optimization, not a gate: replicas
                # compile exactly as before when artifacts are absent
                with self._lock:
                    self._last_error = f"warm: {type(e).__name__}: {e}"
        try:
            self._sup.rolling_reload(target, wait_timeout=self._timeout)
        except CanaryFailed as e:
            self._m_canary.inc()
            _flight_record("canary_quarantine",
                           component=self.obs_instance, version=target,
                           rolled_back_to=e.rolled_back_to)
            with self._lock:
                self._bad.add(target)
                self._last_error = f"CanaryFailed: {e}"
            if self._incidents is not None:
                self._incidents.trigger(
                    "canary_failed",
                    detail={"version": target,
                            "rolled_back_to": e.rolled_back_to})
            return
        except Exception as e:
            # transient (canary unreachable; mid-fleet failure after the
            # canary passed; a replica crash-restarting concurrently):
            # crashed replicas restart onto the current version, and
            # _maybe_reconverge re-drives any alive-but-stale replica
            # the restart path would never touch
            self._m_errors.inc()
            with self._lock:
                self._last_error = f"{type(e).__name__}: {e}"
            self._needs_converge = True
            return
        now = time.monotonic()
        lag = None
        try:
            published_at = self._registry.manifest(
                self._model, target).get("published_at")
            if published_at is not None:
                lag = max(0.0, time.time() - float(published_at))
        except ValueError:
            pass
        self._m_ok.inc()
        with self._lock:
            self._last_rollout_t = now
            if lag is not None:
                self.publish_to_served.record(lag)
        if self._keep > 0:
            try:
                deleted = self._registry.gc(self._model,
                                            keep_latest=self._keep,
                                            pinned={target})
                self._m_gc.inc(len(deleted))
            except Exception as e:
                with self._lock:
                    self._last_error = f"gc: {type(e).__name__}: {e}"

    def _watch(self):
        while not self._stop.wait(self._poll_s):
            try:
                self._poll()
            except Exception as e:      # the watcher must never die
                self._m_errors.inc()
                with self._lock:
                    self._last_error = f"{type(e).__name__}: {e}"

    # ------------------------------------------------------------------
    def stats(self):
        with self._lock:
            bad = sorted(self._bad)
            last_error = self._last_error
        # counters derived from this instance's registry children
        return json_safe(
            {"served_version": self._sup.version,
             "rollouts": int(self._m_ok.value),
             "rollbacks": int(self._m_canary.value),
             "bad_versions": bad,
             "errors": int(self._m_errors.value),
             "converge_repairs": int(self._m_converge.value),
             "gc_deleted": int(self._m_gc.value),
             "last_error": last_error,
             "publish_to_served": self.publish_to_served.snapshot()})


__all__ = ["RolloutController"]
