"""CheckpointFreezer: barrier-consistent pserver cuts stitched into
published inference bundles.

The freeze is the hinge of the online-learning loop: the parameters live
sharded across pserver processes that apply updates continuously, and a
published model must be a CONSISTENT cut — every shard at the same sync
round, never a torn mix where shard 0 has step S's gradient and shard 1
does not (two halves of one embedding trained to different instants).

The cut protocol splits cheap from heavy:

1. **prepare** (``ParamClient.snapshot_prepare``) — called from the
   trainer's thread AT A STEP BOUNDARY, i.e. after ``push`` acked on
   every shard and before the next one is sent, so no update is in
   flight. Each shard copies its params under its apply lock (one
   memcpy) and reports its sync round; the freezer verifies all rounds
   agree and otherwise releases the tag and reports a torn cut. This is
   the only work on the training hot path: one small concurrent RPC per
   shard.
2. **stitch** (worker thread, off the hot path) — fetch the frozen
   copies (the heavy transfer), overlay them on a template scope holding
   the non-pserver persistables, prune + export through
   ``save_inference_model``, and ``ModelRegistry.publish`` with lineage
   metadata (``global_step``, ``parent_version``, ``freeze_round``).

Because the frozen copies are immutable server-side, training continues
at full speed while the stitcher pulls and publishes; a freeze requested
while the stitcher is busy is SKIPPED (tag released, counter bumped) and
the trainer simply retries at a later boundary — publishes are periodic,
not queued, so there is nothing to backlog.

Bitwise contract: the published ``.npy`` params are byte-identical to
the shard state at the prepare instant (tests pin this against a pserver
checkpoint taken at the same sync round, dense and sparse rowwise-
optimizer params alike).
"""

from __future__ import annotations

import os
import queue
import shutil
import tempfile
import threading
import time

import numpy as np

from ..obs.metrics import (REGISTRY as _METRICS, json_safe,
                           next_instance)

# freeze outcomes in the obs.metrics registry: published / skipped_busy /
# failed_<phase> (prepare, torn, stitch) — stats() derives its historical
# counters dict from these children
_M_FREEZES = _METRICS.counter(
    "paddle_tpu_online_freezes",
    "CheckpointFreezer cut outcomes (published, skipped_busy, "
    "failed_prepare, failed_torn, failed_stitch), per instance",
    labels=("instance", "outcome"))
_M_FREEZE_SECONDS = _METRICS.histogram(
    "paddle_tpu_online_freeze_seconds",
    "freeze stitch+publish latency window, per instance",
    labels=("instance",), span_name="online/freeze", span_kind="online")


class FreezeError(RuntimeError):
    """A freeze attempt failed (torn cut, unreachable shard, stitch or
    publish error). The loop treats these as retryable: the next trigger
    cuts fresh."""


class _Job:
    """One accepted cut awaiting its stitch; ``wait`` resolves to the
    published version (or raises the stitch error)."""

    def __init__(self, tag, round_, step):
        self.tag = tag
        self.round = round_
        self.step = step
        self.version = None
        self.error = None
        self._done = threading.Event()

    def resolve(self, version=None, error=None):
        self.version = version
        self.error = error
        self._done.set()

    def done(self):
        return self._done.is_set()

    def failed(self):
        """Resolved with a stitch/publish error — the accepted cut never
        became a version (the trainer's cadence treats this as 'publish
        still owed': retry at the next step boundary)."""
        return self._done.is_set() and self.error is not None

    def wait(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"freeze (step {self.step}, round {self.round}) did not "
                f"publish within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.version


class CheckpointFreezer:
    """Freeze pserver state into registry versions.

        freezer = CheckpointFreezer(client, registry, "ranker",
                                    main_program, ["x"], ["softmax_out"],
                                    template_scope=scope)
        v1 = freezer.request_freeze(0, wait=True)     # initial publish
        ...                                           # from the trainer:
        freezer.request_freeze(step)                  # cut now, stitch async

    ``inference_program`` is the model's program (optimizer ops included
    are fine — ``save_inference_model`` prunes to the fetch path);
    ``template_scope`` supplies persistables the pservers do NOT hold
    (copied once at construction, so later trainer mutation never leaks
    into a freeze); pserver-held params always come from the cut.
    """

    def __init__(self, client, registry, model, inference_program,
                 feed_names, target_names, executor=None,
                 template_scope=None):
        self._client = client
        self._registry = registry
        self._model = model
        self._program = inference_program
        self._feed_names = list(feed_names)
        self._target_names = [t if isinstance(t, str) else t.name
                              for t in target_names]
        if executor is None:
            import paddle_tpu.fluid as fluid
            executor = fluid.Executor()
        self._exe = executor
        # non-pserver persistables (e.g. stats a trainer updates in-graph)
        # frozen ONCE: a freeze must not read a scope another thread is
        # mutating. Pserver params overwrite these per cut.
        self._template = {}
        if template_scope is not None:
            for block in inference_program.blocks:
                for name, var in block.vars.items():
                    if getattr(var, "persistable", False):
                        v = template_scope.find_var(name)
                        if v is not None:
                            self._template[name] = np.array(v)
        self._cut_lock = threading.Lock()
        self._cut_seq = 0
        self._jobs = queue.Queue(maxsize=1)
        self._stats_lock = threading.Lock()
        self._last_error = None
        self._last_publish = None    # {"version", "step", "round", "at"}
        # outcome counters + stitch latency in the obs.metrics registry
        self.obs_instance = next_instance("freezer")
        self._m_outcome = {}         # outcome -> counter child (lazy)
        self.latency = _M_FREEZE_SECONDS.labels(instance=self.obs_instance)
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._drain, daemon=True,
                                        name="checkpoint-freezer")
        self._worker.start()

    # ------------------------------------------------------------------
    def _count(self, outcome):
        child = self._m_outcome.get(outcome)
        if child is None:
            child = self._m_outcome[outcome] = _M_FREEZES.labels(
                instance=self.obs_instance, outcome=outcome)
        child.inc()

    def _outcome_value(self, outcome):
        child = self._m_outcome.get(outcome)
        return int(child.value) if child is not None else 0

    def _record_failure(self, phase, err):
        self._count(f"failed_{phase}")
        with self._stats_lock:
            self._last_error = f"{phase}: {type(err).__name__}: {err}"

    def request_freeze(self, global_step, wait=False, timeout=None):
        """Cut NOW (cheap, call at a step boundary) and hand the stitch
        to the worker. Returns the accepted :class:`_Job`, or with
        ``wait=True`` blocks for the published version (raising
        :class:`FreezeError` when the cut or the stitch failed — a
        waiting caller, like the loop's mandatory v1 publish, must never
        get a silent None). Without ``wait``, a failed cut or a busy
        stitcher returns None — the trainer retries at a later boundary;
        details land in :meth:`stats`."""
        if self._stop.is_set():
            raise RuntimeError("freezer is closed")
        with self._cut_lock:
            self._cut_seq += 1
            tag = f"freeze-{os.getpid()}-{self._cut_seq}"
            err = None
            try:
                rounds = self._client.snapshot_prepare(tag)
            except Exception as e:
                self._record_failure("prepare", e)
                # prepare may have landed on SOME shards before the
                # failing one; drop those copies
                self._client.snapshot_release(tag)
                err = FreezeError(f"freeze cut failed at prepare: "
                                  f"{type(e).__name__}: {e}")
                err.__cause__ = e
            if err is None:
                distinct = set(rounds.values())
                if len(distinct) > 1:
                    self._client.snapshot_release(tag)
                    err = FreezeError(
                        f"torn cut: shard rounds disagree {rounds} — "
                        "cut must happen at a step boundary")
                    self._record_failure("torn", err)
            if err is None:
                job = _Job(tag, distinct.pop(), int(global_step))
                try:
                    self._jobs.put_nowait(job)
                except queue.Full:
                    self._client.snapshot_release(tag)
                    self._count("skipped_busy")
                    err = FreezeError("freeze skipped: a previous cut is "
                                      "still stitching")
        if err is not None:
            if wait:
                raise err
            return None
        if wait:
            return job.wait(timeout)
        return job

    # ------------------------------------------------------------------
    def _drain(self):
        while True:
            try:
                # bounded get: when close() could not land its sentinel
                # (a job already occupied the one-slot queue), the worker
                # still notices _stop once the backlog drains
                job = self._jobs.get(timeout=0.25)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if job is None:
                return
            try:
                with self.latency.span():
                    version = self._stitch(job)
                self._count("published")
                with self._stats_lock:
                    self._last_publish = {"version": version,
                                          "step": job.step,
                                          "round": job.round,
                                          "at": time.time()}
                job.resolve(version=version)
            except Exception as e:
                self._record_failure("stitch", e)
                self._client.snapshot_release(job.tag)
                job.resolve(error=FreezeError(
                    f"freeze at step {job.step} failed: "
                    f"{type(e).__name__}: {e}"))

    def _stitch(self, job):
        """Heavy half: fetch the frozen cut, overlay on the template,
        export, publish. Runs on the worker thread only."""
        from ..core.scope import Scope
        from ..fluid.io import save_inference_model

        params, rounds = self._client.snapshot_fetch(job.tag)
        self._client.snapshot_release(job.tag)
        if set(rounds.values()) != {job.round}:
            # a shard restarted between prepare and fetch and re-served
            # the tag (impossible today — restart loses tags — but the
            # invariant is cheap to keep explicit)
            raise FreezeError(
                f"fetched rounds {rounds} do not match the prepared "
                f"round {job.round}")
        scope = Scope()
        for name, value in self._template.items():
            scope.set(name, value)
        for name, value in params.items():
            scope.set(name, value)
        tmp = tempfile.mkdtemp(prefix="pdtpu-freeze-")
        try:
            save_inference_model(tmp, self._feed_names, self._target_names,
                                 self._exe, self._program, scope=scope)
            published = self._registry.versions(self._model)
            parent = published[-1] if published else None
            return self._registry.publish(
                self._model, tmp,
                lineage={"global_step": job.step,
                         "freeze_round": job.round,
                         "parent_version": parent})
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    # ------------------------------------------------------------------
    def stats(self):
        # the historical shape, derived from the registry outcome
        # children (failures keyed by phase, zero-count phases omitted)
        failures = {}
        for outcome in list(self._m_outcome):
            if outcome.startswith("failed_"):
                n = self._outcome_value(outcome)
                if n:
                    failures[outcome[len("failed_"):]] = n
        with self._stats_lock:
            last_error = self._last_error
            last_publish = dict(self._last_publish) \
                if self._last_publish else None
        return json_safe({"published": self._outcome_value("published"),
                          "skipped_busy": self._outcome_value(
                              "skipped_busy"),
                          "failures": failures,
                          "last_error": last_error,
                          "last_publish": last_publish,
                          "freeze_latency": self.latency.snapshot()})

    def close(self, timeout=30.0):
        """Let an in-flight stitch finish, then stop the worker. Never
        blocks past ``timeout`` + the worker's poll beat: the sentinel is
        enqueued without blocking (a queued job may hold the one slot —
        the worker exits via the stop flag once it drains), and a worker
        that cannot finish in time is reported, not waited on forever."""
        if not self._stop.is_set():
            self._stop.set()
            try:
                self._jobs.put_nowait(None)
            except queue.Full:
                pass          # worker exits via _stop after the backlog
        self._worker.join(timeout)
        return not self._worker.is_alive()


__all__ = ["CheckpointFreezer", "FreezeError"]
