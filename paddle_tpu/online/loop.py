"""OnlineLearningLoop: the end-to-end continuous-learning process tree.

The "millions of users" loop the original Paddle v2 etcd/Go stack was
built for, assembled from this repo's production pieces: a supervised
pserver fleet holds the parameters (checkpointed, restart-on-crash), a
StreamingTrainer consumes an unbounded reader and pushes gradients, a
CheckpointFreezer periodically takes a barrier-consistent cut and
publishes it to the ModelRegistry, and a RolloutController drives
canary-gated ``rolling_reload`` onto a supervised serving fleet — which
answers live inference traffic THE WHOLE TIME.

Supervision tree (everything under one object, one ``stop()``):

    OnlineLearningLoop
    ├── PserverSupervisor        n_pservers forked shards, per-shard
    │                            checkpoints, restart-on-crash
    ├── StreamingTrainer         in-process thread; retry-riding client
    ├── CheckpointFreezer        cut + stitch/publish worker thread
    ├── FleetSupervisor          n_replicas spawned ModelServers,
    │                            restart from the registry's current
    │                            version
    └── RolloutController        registry watcher -> rolling_reload

Chaos contract (pinned by the tier-1 e2e test and the bench lane): with
a pserver shard AND a serving replica SIGKILLed mid-loop, zero infer
requests fail (the FleetClient fails over; the supervisors restart the
children), the served version keeps advancing monotonically, and a
published-but-corrupt version is rolled back by the canary gate without
the fleet ever serving it.

Startup publishes version 1 (the freshly initialized params) BEFORE the
serving fleet boots, so replicas always have a version to load — and a
crash-restarting replica loads whatever is current by then.
"""

from __future__ import annotations

import threading
import time

import numpy as np


class _PublishPacer:
    """Publish cadence for the elastic pool: with N churning workers
    there is no single trainer whose step boundary can drive
    ``request_freeze``, so a loop-level thread owns the cadence instead
    — tick, check steps/seconds since the last ACCEPTED cut, freeze.
    A torn cut (workers push continuously; shard rounds can disagree
    for a moment) or a busy stitcher returns None and the pacer simply
    retries next tick — the cadence only resets on acceptance, exactly
    the StreamingTrainer contract."""

    def __init__(self, freezer, step_fn, every_steps, every_s,
                 tick_s=0.1):
        self._freezer = freezer
        self._step_fn = step_fn
        self._every_steps = int(every_steps or 0)
        self._every_s = float(every_s or 0.0)
        self._tick_s = float(tick_s)
        self.requests = 0
        self.accepted = 0
        self._pending = None
        self._stop = threading.Event()
        self._thread = None

    def _due(self, steps_since, last_t):
        if self._pending is not None and self._pending.done():
            failed = self._pending.failed()
            self._pending = None
            if failed:
                return True    # accepted cut died in its stitch: due now
        if self._every_steps > 0 and steps_since >= self._every_steps:
            return True
        if self._every_s > 0 and time.monotonic() - last_t >= self._every_s:
            return True
        return False

    def _run(self):
        last_step = self._step_fn()
        last_t = time.monotonic()
        while not self._stop.wait(self._tick_s):
            step = self._step_fn()
            if not self._due(step - last_step, last_t):
                continue
            self.requests += 1
            try:
                job = self._freezer.request_freeze(step)
            except RuntimeError:
                return         # freezer closed under us: stop pacing
            if job is not None:
                self.accepted += 1
                self._pending = job
                last_step = step
                last_t = time.monotonic()

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="publish-pacer")
        self._thread.start()
        return self

    def stop(self, timeout=10.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def stats(self):
        return {"requests": self.requests, "accepted": self.accepted}


class OnlineLearningLoop:
    """Wire and supervise the full streaming-train -> publish -> rollout
    loop for one model.

        main, startup = build_model()        # optimizer.minimize applied
        loop = OnlineLearningLoop(
            main, startup, reader,
            infer_feed_names=["x"], infer_targets=[y_pred],
            registry_root=root, model="ranker",
            n_pservers=2, n_replicas=2)
        loop.start()
        ... FleetClient(loop.fleet.addresses) serves throughout ...
        loop.stats()
        loop.stop()

    ``main_program`` must carry optimize ops (``optimizer.minimize``) —
    the transpiler lifts the rule server-side and strips them from the
    trainer program; the SAME program exports the inference bundle
    (``save_inference_model`` prunes to the fetch path).
    """

    def __init__(self, main_program, startup_program, reader,
                 infer_feed_names, infer_targets, registry_root,
                 model="model", n_pservers=2, n_replicas=None,
                 sync_mode=True, publish_every_steps=None,
                 publish_every_s=None, min_serve_s=None,
                 rollout_poll_s=None, registry_keep=None,
                 buckets=None, max_delay_ms=None, checkpoint_dir=None,
                 checkpoint_every=1, trainer_retry=None, extra_fetch=(),
                 prefetch=2, fleet_kwargs=None, slo_rules=None,
                 incident_dir=None, chunks=None, chunk_feeds=None,
                 chunks_per_task=1, master_timeout_s=3.0,
                 trainers_min=None, trainers_max=None, autoscale=True,
                 trainer_lease_s=None):
        from ..serving.registry import ModelRegistry

        # elastic mode: ``chunks`` + ``chunk_feeds`` replace ``reader``
        # — a Master task queue feeds a TrainerPool of N workers (leased
        # membership, hot-join/retire, backlog autoscaling) instead of
        # one StreamingTrainer consuming one reader
        if (chunks is None) != (chunk_feeds is None):
            raise ValueError("elastic mode needs BOTH chunks and "
                             "chunk_feeds (or neither)")
        if chunks is not None and reader is not None:
            raise ValueError("pass either reader (single-trainer) or "
                             "chunks+chunk_feeds (elastic pool), not both")

        self._main = main_program
        self._startup = startup_program
        self._reader = reader
        self._feed_names = list(infer_feed_names)
        self._targets = [t if isinstance(t, str) else t.name
                         for t in infer_targets]
        self.registry = registry_root if isinstance(registry_root,
                                                    ModelRegistry) \
            else ModelRegistry(registry_root)
        self.model = model
        self._n_pservers = int(n_pservers)
        self._n_replicas = n_replicas
        self._sync_mode = bool(sync_mode)
        self._pub_steps = publish_every_steps
        self._pub_s = publish_every_s
        self._min_serve_s = min_serve_s
        self._rollout_poll_s = rollout_poll_s
        self._registry_keep = registry_keep
        self._buckets = buckets
        self._max_delay_ms = max_delay_ms
        self._ckpt_dir = checkpoint_dir
        self._ckpt_every = int(checkpoint_every)
        self._retry = trainer_retry
        self._extra_fetch = extra_fetch
        self._prefetch = prefetch
        self._fleet_kwargs = dict(fleet_kwargs or {})
        self._slo_rules = list(slo_rules or [])
        self._incident_dir = incident_dir
        self._chunks = list(chunks) if chunks is not None else None
        self._chunk_feeds = chunk_feeds
        self._chunks_per_task = int(chunks_per_task)
        self._master_timeout_s = float(master_timeout_s)
        self._trainers_min = trainers_min
        self._trainers_max = trainers_max
        self._autoscale = bool(autoscale)
        self._trainer_lease_s = trainer_lease_s
        self.pservers = None
        self.fleet = None
        self.trainer = None
        self.pool = None
        self.master = None
        self.master_rpc = None
        self.autoscaler = None
        self.pacer = None
        self.freezer = None
        self.rollout = None
        self.client = None
        self.slo_monitor = None
        self.incidents = None
        self._exe = None
        self._scope = None
        self._started = False

    # ------------------------------------------------------------------
    def start(self, wait_ready_s=240.0):
        """Boot the tree bottom-up: pservers -> init params -> publish
        v1 -> serving fleet -> rollout watcher -> trainer. Returns the
        initially served version."""
        import paddle_tpu.fluid as fluid
        from ..distributed.launch import PserverSupervisor
        from ..distributed.rpc import RetryPolicy
        from ..obs.recorder import IncidentCollector
        from ..obs.slo import SloMonitor
        from ..serving.fleet import FleetSupervisor
        from .freezer import CheckpointFreezer
        from .rollout import RolloutController
        from .trainer import StreamingTrainer

        if self._started:
            raise RuntimeError("loop already started")
        self._started = True

        # transpile against placeholder endpoints — placement derives
        # from sorted param names + shard COUNT, so the real supervisor
        # addresses substitute at client construction
        t = fluid.DistributeTranspiler()
        t.transpile(0, program=self._main,
                    pservers=",".join(f"127.0.0.1:{i + 1}"
                                      for i in range(self._n_pservers)),
                    trainers=1, startup_program=self._startup,
                    sync_mode=self._sync_mode)
        self._transpiler = t

        self.pservers = PserverSupervisor(
            n_servers=self._n_pservers, checkpoint_dir=self._ckpt_dir,
            optimizer=t.optimizer, opt_kwargs=t.opt_kwargs,
            mode="sync" if self._sync_mode else "async", fan_in=1,
            checkpoint_every=self._ckpt_every,
            # elastic pool workers register membership leases, so the
            # sync barrier sizes itself to the LIVE worker set instead
            # of the static fan_in above (which stays the lease-less
            # fallback)
            trainer_lease_s=self._trainer_lease_s)
        try:
            if not self.pservers.wait_ready(wait_ready_s):
                raise RuntimeError("pserver shards never became ready")

            self._exe = fluid.Executor()
            self._scope = fluid.Scope()
            self._exe.run(self._startup, scope=self._scope)
            retry = self._retry or RetryPolicy(max_retries=8,
                                               backoff_base_s=0.05,
                                               backoff_max_s=1.0)
            self.client = t.trainer_client(retry=retry,
                                           endpoints=self.pservers.addresses)
            self.client.init_params(
                {p: np.asarray(self._scope.find_var(p))
                 for p, _g in t.params_grads})

            self.freezer = CheckpointFreezer(
                self.client, self.registry, self.model, self._main,
                self._feed_names, self._targets, executor=self._exe,
                template_scope=self._scope)
            # v1: the initialized params — the fleet needs something to
            # serve before the first training-driven publish lands
            self.freezer.request_freeze(0, wait=True, timeout=wait_ready_s)

            self.fleet = FleetSupervisor(
                self.registry, self.model, version="latest",
                n_replicas=self._n_replicas, buckets=self._buckets,
                max_delay_ms=self._max_delay_ms,
                # the same declarative rules judge every replica's OWN
                # registry (surfaced via its health()) AND this process
                slo_rules=self._slo_rules or None, **self._fleet_kwargs)
            if not self.fleet.wait_ready(wait_ready_s):
                raise RuntimeError("serving fleet never became ready")

            # the actionable obs layer: one incident collector over the
            # WHOLE tree (pserver shards + serving replicas + this
            # process), triggered by child restarts, canary failures,
            # and SLO breaches — every chaos event leaves a fleet-wide
            # flight-recorder bundle behind
            self.incidents = IncidentCollector(
                addresses_fn=self._all_addresses,
                out_dir=self._incident_dir)
            self.pservers.incident_hook = self.incidents.trigger
            self.fleet.incident_hook = self.incidents.trigger
            if self._slo_rules:
                self.slo_monitor = SloMonitor(
                    self._slo_rules,
                    on_breach=self.incidents.trigger)
                self.slo_monitor.install()
                self.slo_monitor.start()

            self.rollout = RolloutController(
                self.registry, self.model, self.fleet,
                poll_interval_s=self._rollout_poll_s,
                min_serve_s=self._min_serve_s,
                rollout_timeout_s=wait_ready_s,
                registry_keep=self._registry_keep,
                incident_collector=self.incidents)
            self.rollout.start()

            if self._chunks is not None:
                self._start_elastic()
            else:
                self.trainer = StreamingTrainer(
                    self._exe, self._scope, t.get_trainer_program(),
                    t.params_grads, self.client, self._reader,
                    freezer=self.freezer,
                    publish_every_steps=self._pub_steps,
                    publish_every_s=self._pub_s,
                    extra_fetch=self._extra_fetch,
                    prefetch=self._prefetch)
                self.trainer.start()
        except Exception:
            self.stop()               # resets _started: retryable
            raise
        return self.fleet.version

    # ------------------------------------------------------------------
    def _start_elastic(self):
        """Elastic-mode trainer plane: an in-process Master dispatches
        the chunk queue over RPC, a TrainerPool of StreamingTrainer
        workers leases tasks from it (each with its own ParamClient and
        a pserver membership lease), a BacklogAutoscaler grows/shrinks
        the pool from the queue depth, and a publish pacer drives the
        freeze cadence — any worker may die at any point without losing
        a chunk (Master lease re-dispatch) or stalling a barrier
        (pserver lease shrink)."""
        from ..core.flags import get_flag
        from ..distributed.master import Master
        from ..distributed.rpc import RpcServer
        from .pool import BacklogAutoscaler, TrainerPool

        self.master = Master(timeout_s=self._master_timeout_s)
        self.master_rpc = RpcServer(self.master)
        self.master_rpc.serve_in_thread()
        self.master.set_dataset(self._chunks,
                                chunks_per_task=self._chunks_per_task)

        self.pool = TrainerPool(self._spawn_trainer,
                                min_workers=self._trainers_min,
                                max_workers=self._trainers_max)
        self.pool.incident_hook = self.incidents.trigger
        self.pool.start()
        if self._autoscale:
            self.autoscaler = BacklogAutoscaler(self.pool,
                                                self.master.backlog)
            self.autoscaler.start()
        pub_steps = self._pub_steps if self._pub_steps is not None \
            else int(get_flag("online_publish_every_steps"))
        pub_s = self._pub_s if self._pub_s is not None \
            else float(get_flag("online_publish_every_s"))
        self.pacer = _PublishPacer(self.freezer, self.pool.global_step,
                                   pub_steps, pub_s)
        self.pacer.start()

    def _spawn_trainer(self, wid, stop_ev):
        """TrainerPool spawn hook: a startable StreamingTrainer with its
        OWN scope/executor/ParamClient (unique trainer id — the lease
        and dedup identity) over a stop-aware Master task reader.
        ``prefetch=0`` is load-bearing: the reader marks a task finished
        only when asked for the batch AFTER its last one, which without
        read-ahead is exactly when every batch's push has acked."""
        import paddle_tpu.fluid as fluid
        from ..distributed.param_server import ParamClient
        from ..distributed.rpc import RetryPolicy
        from .pool import master_task_reader
        from .trainer import StreamingTrainer

        t = self._transpiler
        scope = fluid.Scope()
        exe = fluid.Executor()
        exe.run(self._startup, scope=scope)   # shapes; pull overwrites
        retry = self._retry or RetryPolicy(max_retries=8,
                                           backoff_base_s=0.05,
                                           backoff_max_s=1.0)
        client = ParamClient(
            [tuple(a) for a in self.pservers.addresses],
            trainer_id=f"elastic-w{wid}",
            param_names=[p for p, _g in t.params_grads],
            sparse_param_names=t.sparse_param_names, retry=retry)
        reader = master_task_reader(self.master_rpc.address,
                                    self._chunk_feeds, stop=stop_ev,
                                    membership=client)
        return StreamingTrainer(exe, scope, t.get_trainer_program(),
                                t.params_grads, client, reader,
                                freezer=None,       # the pacer publishes
                                publish_every_steps=0, publish_every_s=0,
                                extra_fetch=self._extra_fetch, prefetch=0)

    # ------------------------------------------------------------------
    def _all_addresses(self):
        addrs = []
        if self.fleet is not None:
            addrs += [tuple(a) for a in self.fleet.addresses]
        if self.pservers is not None:
            addrs += [tuple(a) for a in self.pservers.addresses]
        return addrs

    # ------------------------------------------------------------------
    def stats(self, fleet_metrics=True, scrape_timeout=1.0):
        """One aggregated observability surface: every component's
        counters plus the supervisors' per-child restart stats — what an
        operator (and the bench lane) watches the loop through.

        ``fleet_metrics=True`` additionally scrapes the built-in
        ``metrics`` RPC of every pserver shard and serving replica and
        merges those registry snapshots with this process's own (the
        trainer/freezer/rollout counters live HERE) into one fleet-wide
        view under ``"metrics"`` — unreachable children (mid-restart)
        are skipped, never waited on past ``scrape_timeout``."""
        from ..obs import metrics as _m

        out = {"model": self.model, "started": self._started}
        if self.trainer is not None:
            out["trainer"] = self.trainer.stats()
        if self.pool is not None:
            out["pool"] = self.pool.stats()
        if self.master is not None:
            out["backlog"] = self.master.backlog()
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.stats()
        if self.pacer is not None:
            out["publish_pacer"] = self.pacer.stats()
        if self.freezer is not None:
            out["freezer"] = self.freezer.stats()
        if self.rollout is not None:
            out["rollout"] = self.rollout.stats()
        if self.fleet is not None:
            out["served_version"] = self.fleet.version
            out["fleet_children"] = self.fleet.child_stats()
        if self.pservers is not None:
            out["pserver_children"] = self.pservers.child_stats()
        try:
            out["published_versions"] = self.registry.versions(self.model)
        except ValueError:
            out["published_versions"] = []
        if self.slo_monitor is not None:
            out["slo"] = self.slo_monitor.health_section()
        if self.incidents is not None:
            out["incidents"] = self.incidents.stats()
        if fleet_metrics:
            addrs = self._all_addresses()
            scraped = _m.scrape(addrs, timeout=scrape_timeout) \
                if addrs else {}
            out["metrics"] = _m.merge_snapshots(
                [_m.REGISTRY.snapshot()] + list(scraped.values()))
        return _m.json_safe(out)

    def stop(self):
        """Tear the tree down top-down (trainer first so nothing pushes
        into stopping shards; fleet before pservers so no component is
        surprised). Idempotent, and resets the started flag: a stopped
        loop can be start()ed again from scratch (every component is
        rebuilt there)."""
        if self.autoscaler is not None:
            self.autoscaler.stop()
            self.autoscaler = None
        if self.pacer is not None:
            self.pacer.stop()
            self.pacer = None
        if self.pool is not None:
            self.pool.stop()
            self.pool = None
        if self.master_rpc is not None:
            self.master_rpc.shutdown()
            self.master_rpc = None
            self.master = None
        if self.trainer is not None:
            self.trainer.stop()
            self.trainer = None
        if self.rollout is not None:
            self.rollout.stop()
            self.rollout = None
        if self.slo_monitor is not None:
            from ..obs import slo as _slo
            self.slo_monitor.stop()
            if _slo.installed() is self.slo_monitor:
                _slo.install(None)
            self.slo_monitor = None
        if self.incidents is not None:
            # detach the hooks first so a child dying during teardown
            # doesn't race a capture into the closing fleet
            if self.pservers is not None:
                self.pservers.incident_hook = None
            if self.fleet is not None:
                self.fleet.incident_hook = None
            self.incidents.wait_idle(timeout=5.0)
            self.incidents = None
        if self.freezer is not None:
            self.freezer.close()
            self.freezer = None
        if self.fleet is not None:
            self.fleet.stop()
            self.fleet = None
        if self.client is not None:
            self.client.close()
            self.client = None
        if self.pservers is not None:
            self.pservers.stop()
            self.pservers = None
        self._started = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


__all__ = ["OnlineLearningLoop"]
