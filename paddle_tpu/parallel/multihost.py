"""Multi-host (DCN) initialization and global meshes.

Reference capability: multi-node data parallelism via
trainer+pserver programs over gRPC (distribute_transpiler.py:134) or the
legacy/Go pservers. TPU-native: every host runs the SAME SPMD program;
jax.distributed wires the hosts into one runtime, ``global_mesh`` lays the
axes out so that the FASTEST-varying axes map to intra-host ICI and the
slowest to cross-host DCN (data parallelism tolerates DCN latency; tensor/
sequence parallel axes must stay on ICI — the scaling-book layout rule).
The driver's multichip dryrun + tests/test_parallel.py validate the
single-host SPMD path; this module is the multi-host entry the same
programs run under unchanged (ShardingPlan and shard_program_step are
process-count agnostic: jax arrays are globally addressed).
"""

from __future__ import annotations

import jax

from .sharding import make_mesh


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None, local_device_ids=None):
    """Join this process into a multi-host JAX runtime (DCN). On TPU pods
    the three None defaults auto-discover from the TPU environment; on
    CPU/GPU clusters pass them explicitly (the reference's trainer_id /
    pserver endpoint flags, distribute_transpiler.py transpile args) or
    launch via ``paddle_tpu.distributed.launch``, whose env vars are read
    here as defaults."""
    import os
    from ..distributed.launch import ENV_COORD, ENV_NPROC, ENV_RANK

    if coordinator_address is None:
        coordinator_address = os.environ.get(ENV_COORD)
    if num_processes is None and os.environ.get(ENV_NPROC):
        num_processes = int(os.environ[ENV_NPROC])
    if process_id is None and os.environ.get(ENV_RANK):
        process_id = int(os.environ[ENV_RANK])
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id,
                               local_device_ids=local_device_ids)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def global_mesh(axes=("dp", "tp"), dcn_axis="dp"):
    """Mesh over ALL hosts' devices: ``dcn_axis`` spans processes (cross-
    host traffic rides DCN), remaining axes stay within a host (ICI). With
    one process this degrades to the single-host mesh."""
    n_proc = jax.process_count()
    devs = jax.devices()
    if n_proc == 1 or len(axes) == 1:
        return make_mesh(len(devs), axes=axes)
    if dcn_axis != axes[0]:
        raise ValueError("dcn_axis must be the first (slowest-varying) "
                         "mesh axis so cross-host traffic stays on the "
                         "data-parallel dimension")
    if len(axes) != 2:
        raise ValueError("provide a custom mesh for >2 axes across hosts")
    # group rows by OWNING PROCESS, not by device-id order (jax.devices()
    # ordering carries no per-process contiguity guarantee): row i must be
    # exactly host i's devices so the fast axis stays on intra-host ICI
    import numpy as np

    by_proc: dict = {}
    for d in devs:
        by_proc.setdefault(d.process_index, []).append(d)
    rows = [by_proc[p] for p in sorted(by_proc)]
    width = len(rows[0])
    if any(len(r) != width for r in rows):
        raise ValueError("uneven device counts across hosts; build a "
                         "custom Mesh")
    from jax.sharding import Mesh
    return Mesh(np.array(rows), (dcn_axis, axes[1]))
