"""Ring attention: sequence/context parallelism over the device mesh.

The reference predates sequence parallelism — its long-sequence story is
ragged efficiency (LoD, SURVEY.md §5); scaling sequence LENGTH across chips
is the TPU-native extension this framework adds as first-class: shard the
sequence axis over a mesh axis ("sp"), keep each device's Q block resident,
and rotate K/V blocks around the ring with ``lax.ppermute`` while
accumulating attention in an online (flash-style) numerically stable
softmax. Communication rides ICI neighbor links (the ppermute ring), so
per-step traffic is one K/V block per hop — the standard ring-attention
recipe (shard_map + collective-permute) rather than an all-gather of the
full sequence.

API: ``ring_attention(q, k, v, mesh, axis="sp", causal=False,
batch_axis=None)`` with [batch, seq, heads, head_dim] inputs sharded on
seq; ``batch_axis`` composes dp×sp (batch rows sharded over a
data-parallel mesh axis while the ring runs over sp). Numerics match full
softmax attention (pinned by tests on the 8-virtual-device mesh and the
dryrun's composed dp×sp training-step equality).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
# this jax ships shard_map under jax.experimental only (the top-level
# jax.shard_map export landed later); same signature modulo the
# replication-check kwarg name (check_rep here, check_vma upstream)
from jax.experimental.shard_map import shard_map


def _block_attention(q, k, v, m_prev, l_prev, acc_prev, mask=None):
    """One K/V block's contribution under online softmax.

    q [b, sq, h, d], k/v [b, sk, h, d]; m/l [b, h, sq] running max and
    normalizer; acc [b, sq, h, d] running weighted values.
    """
    scale = q.shape[-1] ** -0.5
    # [b, h, sq, sk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    m_block = jnp.max(scores, axis=-1)                    # [b, h, sq]
    m_new = jnp.maximum(m_prev, m_block)
    # guard: fully-masked blocks produce -inf maxima; exp(-inf - -inf) traps
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(scores - safe_m[..., None])               # [b, h, sq, sk]
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    correction = jnp.exp(jnp.where(jnp.isneginf(m_prev), -jnp.inf,
                                   m_prev - safe_m))
    correction = jnp.where(jnp.isneginf(m_prev), 0.0, correction)
    l_new = correction * l_prev + jnp.sum(p, axis=-1)
    acc_new = (acc_prev * correction.transpose(0, 2, 1)[..., None]
               + jnp.einsum("bhqk,bkhd->bqhd", p, v))
    return m_new, l_new, acc_new


@functools.lru_cache(maxsize=64)
def _build_ring_fn(mesh, axis, causal, batch_axis=None):
    """Compiled ring step, cached per (mesh, axis, causal, batch_axis) so a
    training loop calling ring_attention every step hits the jit cache
    instead of retracing (jit keys on the function object). ``batch_axis``
    composes sequence parallelism with data parallelism: batch rows shard
    over that mesh axis while the ring runs per-dp-slice over ``axis``."""
    sp = mesh.shape[axis]
    spec = P(batch_axis, axis, None, None)

    def local(qb, kb, vb):
        rank = lax.axis_index(axis)
        b, sq, h, d = qb.shape
        blk = sq  # per-device block length
        m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, sq), jnp.float32)
        acc0 = jnp.zeros(qb.shape, jnp.float32)
        perm = [(i, (i + 1) % sp) for i in range(sp)]  # ring: pass right

        def body(i, carry):
            kb_i, vb_i, m, l, acc = carry
            # the K/V block currently held arrived from rank - i
            src = (rank - i) % sp

            def attend(carry3):
                m, l, acc = carry3
                mask = None
                if causal:
                    q_pos = rank * blk + jnp.arange(sq)[:, None]    # [sq, 1]
                    k_pos = src * blk + jnp.arange(kb_i.shape[1])[None]
                    mask = (q_pos >= k_pos)[None, None]             # 1,1,sq,sk
                return _block_attention(qb.astype(jnp.float32),
                                        kb_i.astype(jnp.float32),
                                        vb_i.astype(jnp.float32),
                                        m, l, acc, mask)

            if causal:
                # blocks entirely in the future (src > rank) contribute
                # nothing: skip their einsums — halves causal FLOPs
                m, l, acc = lax.cond(src > rank,
                                     lambda c: c, attend, (m, l, acc))
            else:
                m, l, acc = attend((m, l, acc))
            kb_i = lax.ppermute(kb_i, axis, perm)
            vb_i = lax.ppermute(vb_i, axis, perm)
            return kb_i, vb_i, m, l, acc

        _, _, m, l, acc = lax.fori_loop(0, sp, body, (kb, vb, m0, l0, acc0))
        l = jnp.maximum(l, 1e-20)
        out = acc / l.transpose(0, 2, 1)[..., None]
        return out.astype(qb.dtype)

    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_rep=False)
    return jax.jit(fn), NamedSharding(mesh, spec)


def ring_attention(q, k, v, mesh, axis="sp", causal=False,
                   batch_axis=None):
    """Multi-head attention with the SEQUENCE axis sharded over
    ``mesh[axis]``. Inputs [batch, seq, heads, head_dim]; seq must divide
    the axis size. ``batch_axis`` additionally shards batch rows over a
    data-parallel mesh axis (dp×sp composition). Returns the attention
    output with the same sharding."""
    sp = mesh.shape[axis]
    seq = q.shape[1]
    assert seq % sp == 0, (seq, sp)
    if batch_axis is not None:
        assert q.shape[0] % mesh.shape[batch_axis] == 0, \
            (q.shape[0], mesh.shape[batch_axis])
    fn, sharding = _build_ring_fn(mesh, axis, bool(causal), batch_axis)
    q = jax.device_put(q, sharding)
    k = jax.device_put(k, sharding)
    v = jax.device_put(v, sharding)
    return fn(q, k, v)


def full_attention(q, k, v, causal=False):
    """Single-device reference: plain softmax attention (for tests)."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
