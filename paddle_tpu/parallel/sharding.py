"""Sharding planner: the TPU-native distribute "transpiler".

The reference's DistributeTranspiler rewrites one ProgramDesc into N trainer
programs + M pserver programs, splitting parameters into blocks and inserting
send/recv ops (/root/reference/python/paddle/fluid/distribute_transpiler.py:
134,258,363). On TPU the same capability — data parallelism with sharded
optimizer state, plus tensor parallelism the reference never had — is a
*compile-time annotation problem*: build a Mesh, assign a PartitionSpec to
every state/feed leaf, and let GSPMD insert all-reduce/all-gather over ICI
(psum replaces ncclAllReduce, operators/nccl/nccl_op.cu.cc:41-160; sharded
params replace pserver param blocks).

The planner is rule-based over variable names/shapes, mirroring how the
transpiler split by param name (distribute_transpiler.py:92
split_dense_variable).
"""

from __future__ import annotations

import re

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices=None, axes=("dp",), shape=None, devices=None):
    """Create a Mesh over the first n devices. axes like ("dp",) or
    ("dp", "tp"); shape optionally fixes the per-axis sizes."""
    devs = list(devices if devices is not None else jax.devices())[: n_devices]
    n = len(devs)
    if shape is None:
        if len(axes) == 1:
            shape = (n,)
        elif len(axes) == 2:
            # balanced dp×tp: largest tp <= sqrt(n) that divides n
            tp = 1
            for cand in (2, 4, 8, 16):
                if n % cand == 0 and cand * cand <= n:
                    tp = cand
            shape = (n // tp, tp)
        else:
            # balanced k-axis mesh (dp×pp×tp composition): greedily feed
            # prime factors (largest first) to the currently-smallest axis;
            # n=8, 3 axes -> (2, 2, 2)
            sizes = [1] * len(axes)
            rem, f, factors = n, 2, []
            while f * f <= rem:
                while rem % f == 0:
                    factors.append(f)
                    rem //= f
                f += 1
            if rem > 1:
                factors.append(rem)
            for fac in sorted(factors, reverse=True):
                sizes[sizes.index(min(sizes))] *= fac
            shape = tuple(sizes)
    mesh_devs = np.array(devs).reshape(shape)
    return Mesh(mesh_devs, axes)


# optimizer-accumulator name suffixes (fluid/optimizer.py _add_accumulator
# names them "{param}_{acc}"), used to make optimizer state follow its param
_ACC_SUFFIX = re.compile(
    r"_(velocity|moment1|moment2|moment|inf_norm|mean_square|momentum_acc"
    r"|avg_squared_grad|avg_squared_update|squared|linear|beta1_pow"
    r"|beta2_pow)(_\d+)?$")


class ShardingPlan:
    """Assigns PartitionSpecs to program variables.

    Default policy (overridable per-name):
      * feed (data) vars: batch dim sharded over the data axis ("dp")
      * 2-D parameters (fc weights, embedding tables): output dim sharded over
        the model axis ("tp") when the mesh has one and the dim divides evenly
        — tensor parallelism. Conv filters (>=3-D, spatial trailing dims) are
        NEVER sharded on spatial dims; with ``shard_conv_filters`` their
        output-channel dim 0 is sharded instead.
      * optimizer accumulators follow their parameter (suffix matching, the
        way the reference pserver keeps optimizer state with the shard,
        SURVEY.md §2.3 "pserver-style sharded optimizer state")
      * with ``shard_opt_state`` (ZeRO-1 analog of the reference's
        pserver-side param-block split, distribute_transpiler.py:92):
        otherwise-replicated optimizer accumulators shard dim 0 over the
        data axis; GSPMD turns the optimizer update into reduce-scatter +
        all-gather style collectives.
      * everything else replicated
    """

    def __init__(self, mesh, data_axis="dp", model_axis="tp", rules=None,
                 shard_params=True, shard_conv_filters=False,
                 shard_opt_state=False):
        self.mesh = mesh
        self.data_axis = data_axis if data_axis in mesh.axis_names else None
        self.model_axis = model_axis if model_axis in mesh.axis_names else None
        self.rules = list(rules or [])  # (regex, PartitionSpec)
        self.shard_params = shard_params
        self.shard_conv_filters = shard_conv_filters
        self.shard_opt_state = shard_opt_state
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self._tp = sizes.get(model_axis, 1)
        self._dp = sizes.get(data_axis, 1)

    def _base_spec(self, name, shape):
        """TP spec for a parameter-shaped array (shared by a param and its
        same-shaped accumulators so state stays aligned with the param)."""
        if not (self.shard_params and self.model_axis and self._tp > 1
                and shape is not None):
            return P()
        if (len(shape) == 2 and shape[-1] % self._tp == 0
                and shape[-1] >= 2 * self._tp):
            return P(None, self.model_axis)
        if (self.shard_conv_filters and len(shape) == 4
                and shape[0] % self._tp == 0 and shape[0] >= 2 * self._tp):
            # OIHW conv filter: shard output channels, never kh/kw
            return P(self.model_axis)
        return P()

    def spec_for_param(self, name, shape, var=None):
        for pat, spec in self.rules:
            if re.search(pat, name):
                return spec
        spec = self._base_spec(name, shape)
        # accumulator detection: the optimizer's registry tags each
        # accumulator Variable with its param (fluid/optimizer.py
        # _add_accumulator) — authoritative, so arbitrary accumulator names
        # shard correctly; the name-suffix regex additionally covers
        # programs rebuilt without build-time metadata (deserialized
        # __model__ files), matching the known optimizer suffixes
        is_acc = (getattr(var, "optimizer_accumulator_for", None) is not None
                  or _ACC_SUFFIX.search(name) is not None)
        if (spec == P() and self.shard_opt_state and self.data_axis
                and self._dp > 1 and shape is not None and len(shape) >= 1
                and is_acc
                and shape[0] % self._dp == 0 and shape[0] >= 2 * self._dp):
            return P(*([self.data_axis] + [None] * (len(shape) - 1)))
        return spec

    def spec_for_feed(self, name, shape):
        for pat, spec in self.rules:
            if re.search(pat, name):
                return spec
        if (self.data_axis and shape is not None and len(shape) >= 1
                and shape[0] % self._dp == 0):
            return P(*([self.data_axis] + [None] * (len(shape) - 1)))
        return P()

    def named(self, spec):
        return NamedSharding(self.mesh, spec)

    # -- serialization (plan persistence: parallel/planner.py artifacts) --

    @staticmethod
    def _spec_to_list(spec):
        """PartitionSpec -> JSON-safe list: each entry None, an axis
        name, or a list of axis names (a multi-axis entry)."""
        return [list(e) if isinstance(e, (tuple, list)) else e
                for e in spec]

    @staticmethod
    def _spec_from_list(entries):
        if not isinstance(entries, (list, tuple)):
            raise ValueError("malformed PartitionSpec entries: "
                             f"{entries!r}")
        out = []
        for e in entries:
            if e is None or isinstance(e, str):
                out.append(e)
            elif isinstance(e, (list, tuple)) \
                    and all(isinstance(a, str) for a in e):
                out.append(tuple(e))
            else:
                raise ValueError(f"malformed PartitionSpec entry: {e!r}")
        return P(*out)

    def to_dict(self):
        """JSON-safe round-trippable description: the mesh as its
        ``make_mesh`` arguments (axes + shape — the device list is a
        property of the LOADING process, not the plan), the axis roles,
        the per-name rules, and the policy switches."""
        return {
            "schema": "pdtpu-sharding-plan-v1",
            "mesh": {"axes": list(self.mesh.axis_names),
                     "shape": list(self.mesh.devices.shape)},
            "data_axis": self.data_axis,
            "model_axis": self.model_axis,
            "rules": [[pat, self._spec_to_list(spec)]
                      for pat, spec in self.rules],
            "shard_params": bool(self.shard_params),
            "shard_conv_filters": bool(self.shard_conv_filters),
            "shard_opt_state": bool(self.shard_opt_state),
        }

    @classmethod
    def from_dict(cls, doc, devices=None):
        """Rebuild a plan from :meth:`to_dict` output over THIS
        process's devices (or ``devices``). Typed errors: any schema or
        shape violation raises ValueError — never a partial plan."""
        if not isinstance(doc, dict) \
                or doc.get("schema") != "pdtpu-sharding-plan-v1":
            raise ValueError("not a pdtpu-sharding-plan-v1 document")
        mesh_doc = doc.get("mesh")
        if not isinstance(mesh_doc, dict) \
                or not isinstance(mesh_doc.get("axes"), (list, tuple)) \
                or not isinstance(mesh_doc.get("shape"), (list, tuple)) \
                or len(mesh_doc["axes"]) != len(mesh_doc["shape"]):
            raise ValueError("malformed sharding-plan mesh (need "
                             "matching axes and shape lists)")
        try:
            shape = tuple(int(d) for d in mesh_doc["shape"])
        except (TypeError, ValueError):
            raise ValueError("malformed sharding-plan mesh shape") \
                from None
        n = 1
        for d in shape:
            n *= d
        rules_doc = doc.get("rules", [])
        if not isinstance(rules_doc, (list, tuple)):
            raise ValueError("malformed sharding-plan rules")
        rules = []
        for entry in rules_doc:
            if not isinstance(entry, (list, tuple)) or len(entry) != 2 \
                    or not isinstance(entry[0], str):
                raise ValueError(f"malformed sharding-plan rule: "
                                 f"{entry!r}")
            rules.append((entry[0], cls._spec_from_list(entry[1])))
        mesh = make_mesh(n, axes=tuple(str(a) for a in mesh_doc["axes"]),
                         shape=shape, devices=devices)
        return cls(mesh,
                   data_axis=doc.get("data_axis") or "dp",
                   model_axis=doc.get("model_axis") or "tp",
                   rules=rules,
                   shard_params=bool(doc.get("shard_params", True)),
                   shard_conv_filters=bool(
                       doc.get("shard_conv_filters", False)),
                   shard_opt_state=bool(doc.get("shard_opt_state",
                                                False)))


def _shape_of(v):
    return getattr(v, "shape", None)


def place_feed(v, plan, name):
    """Place one feed value by the plan. LoDArray (padded ragged feed) shards
    its batch dim on both leaves — data [batch, max_len, ...] and lens
    [batch] — the SplitLoDTensor-across-devices semantics of the reference's
    parallel_do (operators/parallel_do_op.cc:39-69) done by GSPMD."""
    from ..core.lod import LoDArray

    if isinstance(v, LoDArray):
        data_spec = plan.spec_for_feed(name, getattr(v.data, "shape", None))
        # lens is rank-1 [batch]: take only the batch axis of the data spec
        # (a per-name rule spec is written for the data leaf's rank)
        lens_spec = P(data_spec[0]) if len(data_spec) else P()
        return LoDArray(jax.device_put(v.data, plan.named(data_spec)),
                        jax.device_put(v.lens, plan.named(lens_spec)))
    return jax.device_put(v, plan.named(
        plan.spec_for_feed(name, _shape_of(v))))


def shard_program_step(executor, program, feed_example, fetch_list, plan,
                       scope=None, donate=False):
    """Compile one program block into a pjit-ted SPMD step over plan.mesh.

    Returns (fn, state, feeds) where fn(state, feeds) -> (new_state, fetches):
    the multi-chip equivalent of Executor._compiled, with every state/feed
    leaf placed by the ShardingPlan. Run it in a loop, carrying state.
    """
    from ..core.executor import (_analyze_program, _run_ops, _RNG_KEY,
                                 _is_traceable)
    from ..core.scope import global_scope

    scope = scope or global_scope()
    block = program.global_block()
    fetch_names = [f if isinstance(f, str) else f.name for f in fetch_list]

    feeds = executor._prepare_feed(block, dict(feed_example))
    if scope.find_var(_RNG_KEY) is None:
        scope.set(_RNG_KEY, jax.random.PRNGKey(program.random_seed or 0))

    # per-(program, version) cached block walks, shared with Executor.run
    analysis = _analyze_program(program)
    state_in = [n for n in analysis.free
                if n not in feeds and scope.has_var(n)]
    state_out = [n for n in analysis.written
                 if n in analysis.persistable_written or scope.has_var(n)]
    state = {n: scope.find_var(n) for n in state_in}
    state = {k: v for k, v in state.items() if _is_traceable(v)}
    state[_RNG_KEY] = scope.find_var(_RNG_KEY)

    # placement
    state_shardings = {}
    for n, v in state.items():
        if n == _RNG_KEY:
            state_shardings[n] = plan.named(P())
            continue
        block_var = block.var(n) if block.has_var(n) else None
        state_shardings[n] = plan.named(
            plan.spec_for_param(n, _shape_of(v), var=block_var))

    state = {n: jax.device_put(v, state_shardings[n]) for n, v in state.items()}
    feeds = {n: place_feed(v, plan, n) for n, v in feeds.items()}
    # per-leaf shardings (LoDArray feeds carry two leaves of different rank)
    feed_shardings = jax.tree_util.tree_map(lambda x: x.sharding, feeds)

    def step(st, fd):
        env = dict(st)
        env.update(fd)
        executor._tracing = True
        try:
            _run_ops(block, env, executor)
        finally:
            executor._tracing = False
        # carry exactly the input keyset so the step iterates:
        # fn(fn(state)) — read-only state (learning rate) passes through
        new_state = {n: env.get(n, st[n]) for n in st}
        fetches = [env[n] for n in fetch_names]
        return new_state, fetches

    # pin state shardings on both sides so the step iterates; tpu_jit
    # forwards the xla_compiler_options flag to the backend compiler
    from ..core.executor import tpu_jit
    jitted = tpu_jit(
        step,
        in_shardings=(state_shardings, feed_shardings),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,) if donate else (),
    )

    def fn(st, fd):
        from ..core.flags import get_flag
        if get_flag("check_nan_inf"):
            with jax.debug_nans(True), jax.debug_infs(True):
                out = jitted(st, fd)
                jax.block_until_ready(out)
                return out
        return jitted(st, fd)

    return fn, state, feeds
