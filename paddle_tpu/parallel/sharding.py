"""Sharding planner: the TPU-native distribute "transpiler".

The reference's DistributeTranspiler rewrites one ProgramDesc into N trainer
programs + M pserver programs, splitting parameters into blocks and inserting
send/recv ops (/root/reference/python/paddle/fluid/distribute_transpiler.py:
134,258,363). On TPU the same capability — data parallelism with sharded
optimizer state, plus tensor parallelism the reference never had — is a
*compile-time annotation problem*: build a Mesh, assign a PartitionSpec to
every state/feed leaf, and let GSPMD insert all-reduce/all-gather over ICI
(psum replaces ncclAllReduce, operators/nccl/nccl_op.cu.cc:41-160; sharded
params replace pserver param blocks).

The planner is rule-based over variable names/shapes, mirroring how the
transpiler split by param name (distribute_transpiler.py:92
split_dense_variable).
"""

from __future__ import annotations

import re

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices=None, axes=("dp",), shape=None, devices=None):
    """Create a Mesh over the first n devices. axes like ("dp",) or
    ("dp", "tp"); shape optionally fixes the per-axis sizes."""
    devs = list(devices if devices is not None else jax.devices())[: n_devices]
    n = len(devs)
    if shape is None:
        if len(axes) == 1:
            shape = (n,)
        elif len(axes) == 2:
            # balanced dp×tp: largest tp <= sqrt(n) that divides n
            tp = 1
            for cand in (2, 4, 8, 16):
                if n % cand == 0 and cand * cand <= n:
                    tp = cand
            shape = (n // tp, tp)
        else:
            raise ValueError("provide shape for >2 mesh axes")
    mesh_devs = np.array(devs).reshape(shape)
    return Mesh(mesh_devs, axes)


class ShardingPlan:
    """Assigns PartitionSpecs to program variables.

    Default policy (overridable per-name):
      * feed (data) vars: batch dim sharded over the data axis ("dp")
      * 2-D parameters: output dim sharded over the model axis ("tp") when the
        mesh has one and the dim divides evenly — tensor parallelism
      * optimizer accumulators follow their parameter (suffix matching, the
        way the reference pserver keeps optimizer state with the shard,
        SURVEY.md §2.3 "pserver-style sharded optimizer state")
      * everything else replicated
    """

    def __init__(self, mesh, data_axis="dp", model_axis="tp", rules=None,
                 shard_params=True):
        self.mesh = mesh
        self.data_axis = data_axis if data_axis in mesh.axis_names else None
        self.model_axis = model_axis if model_axis in mesh.axis_names else None
        self.rules = list(rules or [])  # (regex, PartitionSpec)
        self.shard_params = shard_params
        self._tp = (dict(zip(mesh.axis_names, mesh.devices.shape))
                    .get(model_axis, 1))

    def spec_for_param(self, name, shape):
        for pat, spec in self.rules:
            if re.search(pat, name):
                return spec
        if (self.shard_params and self.model_axis and shape is not None
                and len(shape) >= 2 and self._tp > 1
                and shape[-1] % self._tp == 0 and shape[-1] >= 2 * self._tp):
            return P(*([None] * (len(shape) - 1) + [self.model_axis]))
        return P()

    def spec_for_feed(self, name, shape):
        for pat, spec in self.rules:
            if re.search(pat, name):
                return spec
        if self.data_axis and shape is not None and len(shape) >= 1:
            return P(*([self.data_axis] + [None] * (len(shape) - 1)))
        return P()

    def named(self, spec):
        return NamedSharding(self.mesh, spec)


def _shape_of(v):
    return getattr(v, "shape", None)


def shard_program_step(executor, program, feed_example, fetch_list, plan,
                       scope=None, donate=False):
    """Compile one program block into a pjit-ted SPMD step over plan.mesh.

    Returns (fn, state, feeds) where fn(state, feeds) -> (new_state, fetches):
    the multi-chip equivalent of Executor._compiled, with every state/feed
    leaf placed by the ShardingPlan. Run it in a loop, carrying state.
    """
    from ..core.executor import (_collect_free_inputs, _written_names,
                                 _run_ops, _RNG_KEY, _is_traceable)
    from ..core.scope import global_scope

    scope = scope or global_scope()
    block = program.global_block()
    fetch_names = [f if isinstance(f, str) else f.name for f in fetch_list]

    feeds = executor._prepare_feed(block, dict(feed_example))
    if scope.find_var(_RNG_KEY) is None:
        scope.set(_RNG_KEY, jax.random.PRNGKey(program.random_seed or 0))

    free = _collect_free_inputs(program, 0)
    state_in = [n for n in free if n not in feeds and scope.has_var(n)]
    written = _written_names(program, 0)
    state_out = [n for n in written
                 if (block.has_var(n) and block.var(n).persistable)
                 or scope.has_var(n)]
    state = {n: scope.find_var(n) for n in state_in}
    state = {k: v for k, v in state.items() if _is_traceable(v)}
    state[_RNG_KEY] = scope.find_var(_RNG_KEY)

    # placement
    state_shardings = {}
    for n, v in state.items():
        if n == _RNG_KEY:
            state_shardings[n] = plan.named(P())
            continue
        state_shardings[n] = plan.named(plan.spec_for_param(n, _shape_of(v)))
    feed_shardings = {n: plan.named(plan.spec_for_feed(n, _shape_of(v)))
                      for n, v in feeds.items()}

    state = {n: jax.device_put(v, state_shardings[n]) for n, v in state.items()}
    feeds = {n: jax.device_put(v, feed_shardings[n]) for n, v in feeds.items()}

    def step(st, fd):
        env = dict(st)
        env.update(fd)
        _run_ops(block, env, executor)
        # carry exactly the input keyset so the step iterates:
        # fn(fn(state)) — read-only state (learning rate) passes through
        new_state = {n: env.get(n, st[n]) for n in st}
        fetches = [env[n] for n in fetch_names]
        return new_state, fetches

    # pin state shardings on both sides so the step iterates
    fn = jax.jit(
        step,
        in_shardings=(state_shardings, feed_shardings),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,) if donate else (),
    )
    return fn, state, feeds
