"""Auto-parallelism placement planner: searched meshes over a measured
cost model, persistently cached plans.

The sharding layer (sharding.py) makes multi-chip placement a
compile-time annotation problem — but WHICH mesh to annotate with has so
far been a hand decision encoded in each test/bench lane
(``make_mesh(8, axes=("dp", "tp"))`` and friends). This module makes
that decision a SEARCH, the placement-level twin of the kernel
autotuner's "measure once, dispatch forever" (ops/autotune.py) and the
shape argued by *Synthesizing Optimal Parallelism Placement and
Reduction Strategies on Hierarchical Systems* (PAPERS.md): enumerate the
legal (dp, pp, tp, sp) factorizations of the device count, cost each one
with measured compute plus an analytic collective model, and emit the
winner through the existing ``shard_program_step`` path — bitwise the
plan a hand would have built.

Four planes:

* **search space** — :func:`enumerate_meshes` yields every legal
  factorization for a Program (or hand-built :class:`ProgramFeatures`)
  and a device count. Legality is derived from the program, not
  asserted: tensor parallelism requires a 2-D parameter whose output
  dim the candidate tp actually shards (the exact
  ``ShardingPlan._base_spec`` rule, so a "legal" candidate is one whose
  emission really shards something); pipeline requires a cuttable layer
  chain at least ``pp`` deep; sequence parallelism requires attention
  ops; expert parallelism only exists when MoE experts are declared.
* **cost model** — :func:`cost_candidate` combines the measured FLOPs /
  bytes from ``obs.perf.attribute()`` (falling back to a static
  parameter-shape estimate when the backend provides no cost analysis)
  with an analytic collective model: ring all-reduce bytes for dp
  gradients and tp activations, ring KV-passing bytes for sp, all-to-all
  bytes for ep, stage-boundary p2p plus a pipeline bubble term for pp —
  into a typed :class:`PlanCost`. Candidates whose per-device memory
  exceeds the budget are PRUNED with a reason, never ranked.
* **plan API + emission** — :func:`plan` returns a
  :class:`PlacementReport` (ranked candidates, chosen mesh, per-
  candidate cost breakdown, why-pruned notes); ``report.apply()`` /
  :func:`apply_candidate` emit the sharded step through
  ``shard_program_step`` with a mesh/plan constructed EXACTLY as the
  hand-built lanes construct theirs — same axes, same shape, same
  ``ShardingPlan`` kwargs, so the compiled step is bitwise equal.
  ``tools/plan_parallel.py`` renders the report for any program or
  published bundle.
* **persistence** — chosen plans serialize under the ops/autotune
  artifact contract: content-addressed envelope (``MAGIC + sha256hex +
  blob``), full identity fingerprint (program content hash x device
  count/kind x planner flags) in the filename, typed bounded rejects
  (:data:`REJECT_REASONS`) each a ``paddle_tpu_plan_rejects`` bump plus
  a flight event followed by a silent fall-back to fresh planning, and
  manifest pinning for published ``<version>/plan/`` dirs
  (``registry.publish/warm(plan=True)`` certifies ``plan_files`` so
  replicas place without re-searching).
"""

from __future__ import annotations

import hashlib
import json
import os
import re

from ..core.flags import get_flag
from ..obs.metrics import REGISTRY as _METRICS
from .sharding import ShardingPlan, make_mesh, shard_program_step

PLAN_DIRNAME = "plan"
ARTIFACT_SUFFIX = ".jplan"
_MAGIC = b"PDTPUPLAN1\n"

# typed bounded reject vocabulary (the ops.autotune shape — a plan is
# only ever read, never executed at load time):
#   format       — bad magic / truncated / bit-flipped payload
#   manifest     — raw bytes not certified by the version manifest
#   fingerprint  — embedded identity != this process's planning identity
#   deserialize  — JSON/schema violations inside a well-formed envelope
REJECT_REASONS = ("format", "manifest", "fingerprint", "deserialize")

_M_SEARCHES = _METRICS.counter(
    "paddle_tpu_plan_searches",
    "placement-plan searches executed (mesh enumeration + cost model "
    "ranking); a cache hit skips the search entirely")
_M_CACHE_HITS = _METRICS.counter(
    "paddle_tpu_plan_cache_hits",
    "placement plans loaded from a persisted artifact instead of "
    "searched (bundle plan/ dir or the plan_cache_dir flag)")
_M_REJECTS = _METRICS.counter(
    "paddle_tpu_plan_rejects",
    "placement-plan artifacts refused at load, by typed reason "
    "(parallel.planner.REJECT_REASONS); every reject falls back to a "
    "fresh search, never a failure",
    labels=("reason",))

# cost-model machine constants: RELATIVE ranking is what matters (every
# full-use candidate divides the same measured FLOPs by the same device
# count), so these are deliberately round numbers — per-device peak
# FLOP/s and per-device interconnect bytes/s. TPU numbers are v5e-class;
# the CPU fallback only needs comm to be expensive relative to compute
# in the same proportion (ICI-class fabric ~ 1e11 B/s vs ~ 1e14 FLOP/s).
PEAK_FLOPS_S = {"tpu": 2.0e14, "cpu": 5.0e10}
COLLECTIVE_BYTES_S = {"tpu": 9.0e10, "cpu": 2.0e7}

# default microbatch count for the pipeline bubble term
# (bubble = (pp-1)/(micro+pp-1), the GPipe fill/drain fraction)
PIPELINE_MICROBATCHES = 8

# the canonical axis order of every emitted mesh — matches how the
# hand-tuned lanes spell composed meshes (("dp","tp"), ("dp","pp","tp"),
# ("dp","sp")); ep composes after dp like the moe lanes' ("ep",)
_AXIS_ORDER = ("dp", "ep", "pp", "tp", "sp")

# ops that constitute one "layer" of a cuttable pipeline chain —
# param-bearing compute stages a pipeline cut can fall between
_LAYER_OPS = frozenset((
    "mul", "conv2d", "depthwise_conv2d", "fused_conv2d_bn",
    "dynamic_gru", "dynamic_lstm", "embedding", "lookup_table",
))

# ops whose presence makes sequence (ring-attention) parallelism
# meaningful: attention over a sequence axis
_ATTENTION_OPS = frozenset((
    "causal_self_attention", "paged_attention", "chunked_prefill_attention",
))


class PlanError(ValueError):
    """Typed planner failure (no legal candidate, malformed plan doc)."""


def _record(kind, **detail):
    from ..obs.recorder import record as _flight_record
    _flight_record(kind, component="parallel.planner", **detail)


# ---------------------------------------------------------------------------
# program features (the legality + cost inputs)
# ---------------------------------------------------------------------------

class ProgramFeatures:
    """Everything the planner knows about one workload: the legality
    inputs (parameter shapes, layer-chain depth, attention presence, MoE
    expert count, batch/seq) and the cost inputs (measured or estimated
    FLOPs, parameter/activation bytes). Built from a Program by
    :func:`extract_features`; the moe/ring lanes — jax-level model
    functions with no fluid Program — construct one directly."""

    def __init__(self, signature="", batch=None, param_shapes=None,
                 layer_chain=0, attention=False, seq_len=None,
                 moe_experts=0, moe_param_bytes=None, flops=None,
                 bytes_accessed=None, dtype_bytes=4):
        self.signature = str(signature)
        self.batch = None if batch is None else int(batch)
        # {name: shape tuple} of persistable parameters
        self.param_shapes = dict(param_shapes or {})
        self.layer_chain = int(layer_chain)
        self.attention = bool(attention)
        self.seq_len = None if seq_len is None else int(seq_len)
        self.moe_experts = int(moe_experts)
        self.dtype_bytes = int(dtype_bytes)
        self.param_bytes = sum(
            self._numel(s) * self.dtype_bytes
            for s in self.param_shapes.values())
        # expert-parallel share of the parameters: the moe lanes' expert
        # stacks; defaults to ALL params when experts are declared but
        # no split is given (a pure-MoE features object)
        self.moe_param_bytes = self.param_bytes if (
            moe_param_bytes is None and self.moe_experts) \
            else int(moe_param_bytes or 0)
        self.flops = None if flops is None else float(flops)
        self.bytes_accessed = None if bytes_accessed is None \
            else float(bytes_accessed)

    @staticmethod
    def _numel(shape):
        n = 1
        for d in shape:
            n *= max(int(d), 1)
        return n

    def tp_shardable_bytes(self, tp):
        """Bytes of 2-D parameters a model axis of size ``tp`` really
        shards — the EXACT ``ShardingPlan._base_spec`` predicate
        (``shape[-1] % tp == 0 and shape[-1] >= 2*tp``), so tp legality
        here means the emitted plan shards something."""
        total = 0
        for s in self.param_shapes.values():
            if (len(s) == 2 and int(s[-1]) % tp == 0
                    and int(s[-1]) >= 2 * tp):
                total += self._numel(s) * self.dtype_bytes
        return total

    def activation_bytes(self):
        """Rough per-step activation footprint: batch x the summed
        input dims of every 2-D parameter (each fc reads one [b, k]
        activation), plus the attention sequence block when present —
        the analytic term the tp/sp collective model scales."""
        b = self.batch or 1
        act = sum(int(s[0]) for s in self.param_shapes.values()
                  if len(s) == 2)
        total = b * act * self.dtype_bytes
        if self.attention and self.seq_len:
            # [b, seq, d_model] with d_model ~ the widest 2-D param out
            d_model = max((int(s[-1])
                           for s in self.param_shapes.values()
                           if len(s) == 2), default=64)
            total += b * self.seq_len * d_model * self.dtype_bytes
        return total

    def flops_estimate(self):
        """Measured FLOPs when attribute() provided them, else the
        static fwd+bwd matmul estimate (6 x batch x param elements)."""
        if self.flops:
            return self.flops
        b = self.batch or 1
        elems = sum(self._numel(s) for s in self.param_shapes.values())
        return 6.0 * b * max(elems, 1)

    def to_doc(self):
        return {
            "signature": self.signature,
            "batch": self.batch,
            "param_shapes": {n: list(s)
                             for n, s in sorted(self.param_shapes.items())},
            "layer_chain": self.layer_chain,
            "attention": self.attention,
            "seq_len": self.seq_len,
            "moe_experts": self.moe_experts,
            "moe_param_bytes": self.moe_param_bytes,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "dtype_bytes": self.dtype_bytes,
        }

    @classmethod
    def from_doc(cls, doc):
        if not isinstance(doc, dict):
            raise ValueError("malformed features document")
        shapes = doc.get("param_shapes", {})
        if not isinstance(shapes, dict):
            raise ValueError("malformed features param_shapes")
        return cls(signature=doc.get("signature", ""),
                   batch=doc.get("batch"),
                   param_shapes={str(n): tuple(int(d) for d in s)
                                 for n, s in shapes.items()},
                   layer_chain=doc.get("layer_chain", 0),
                   attention=doc.get("attention", False),
                   seq_len=doc.get("seq_len"),
                   moe_experts=doc.get("moe_experts", 0),
                   moe_param_bytes=doc.get("moe_param_bytes"),
                   flops=doc.get("flops"),
                   bytes_accessed=doc.get("bytes_accessed"),
                   dtype_bytes=doc.get("dtype_bytes", 4))


def program_signature(program):
    """Stable content hash of one Program: the deterministic IR dump
    (vars sorted, ops in order) — what the plan fingerprint keys on, so
    a structurally different program is a silent filename miss."""
    return hashlib.sha256(
        program.to_debug_string(with_vars=True).encode()).hexdigest()


def extract_features(program, feed_example=None, fetch_list=None,
                     executor=None, scope=None, moe_experts=0,
                     seq_len=None, measure=True):
    """Walk ``program``'s global block into :class:`ProgramFeatures`:
    parameter shapes from the persistable vars, the layer chain from the
    param-bearing op sequence, attention from the op set, the batch from
    ``feed_example``. With ``measure`` and a feed, the measured FLOPs /
    bytes come from ``obs.perf.attribute()`` (AOT lower + backend
    cost_analysis); a backend without cost analysis falls back to the
    static estimate — the planner never fails for lack of a profiler."""
    from ..fluid.framework import Parameter

    block = program.global_block()
    param_shapes = {}
    for name in sorted(block.vars):
        v = block.vars[name]
        if isinstance(v, Parameter) and v.shape:
            param_shapes[name] = tuple(int(d) for d in v.shape)
    layer_chain = sum(1 for op in block.ops if op.type in _LAYER_OPS)
    attention = any(op.type in _ATTENTION_OPS for op in block.ops)

    batch = None
    if feed_example:
        for v in feed_example.values():
            s = getattr(v, "shape", None)
            if s is not None and len(s) >= 1:
                batch = int(s[0])
                break
            if isinstance(v, (list, tuple)) and v:
                batch = len(v)
                break
    if attention and seq_len is None and feed_example:
        for v in feed_example.values():
            s = getattr(v, "shape", None)
            if s is not None and len(s) >= 2:
                seq_len = int(s[1])
                break

    flops = bytes_accessed = None
    if measure and feed_example is not None and fetch_list is not None:
        from ..obs import perf
        try:
            res = perf.attribute(program, feed=dict(feed_example),
                                 fetch_list=fetch_list, executor=executor,
                                 scope=scope, top=0, per_op=True)
            flops = res["cost"].get("flops")
            bytes_accessed = res["cost"].get("bytes_accessed")
        except Exception as e:
            _record("plan_measure_failed",
                    error=f"{type(e).__name__}: {e}")

    return ProgramFeatures(signature=program_signature(program),
                           batch=batch, param_shapes=param_shapes,
                           layer_chain=layer_chain, attention=attention,
                           seq_len=seq_len, moe_experts=moe_experts,
                           flops=flops, bytes_accessed=bytes_accessed)


# ---------------------------------------------------------------------------
# candidates + cost model
# ---------------------------------------------------------------------------

class PlanCost:
    """Typed cost breakdown of one candidate: modeled seconds of
    per-device compute and collective traffic, per-device memory bytes,
    and the pipeline fill/drain bubble fraction."""

    __slots__ = ("compute_s", "comm_s", "memory_bytes", "bubble_frac")

    def __init__(self, compute_s, comm_s, memory_bytes, bubble_frac=0.0):
        self.compute_s = float(compute_s)
        self.comm_s = float(comm_s)
        self.memory_bytes = int(memory_bytes)
        self.bubble_frac = float(bubble_frac)

    def total_s(self):
        """Modeled step seconds: compute + comm, stretched by the
        pipeline bubble (a stage idles bubble_frac of the step)."""
        return (self.compute_s + self.comm_s) / max(
            1.0 - self.bubble_frac, 1e-9)

    def to_doc(self):
        return {"compute_s": self.compute_s, "comm_s": self.comm_s,
                "memory_bytes": self.memory_bytes,
                "bubble_frac": self.bubble_frac,
                "total_s": self.total_s()}

    @classmethod
    def from_doc(cls, doc):
        if not isinstance(doc, dict):
            raise ValueError("malformed plan cost")
        try:
            return cls(doc["compute_s"], doc["comm_s"],
                       doc["memory_bytes"], doc.get("bubble_frac", 0.0))
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"malformed plan cost: {e}") from None

    def __repr__(self):
        return (f"PlanCost(compute={self.compute_s:.3e}s "
                f"comm={self.comm_s:.3e}s mem={self.memory_bytes} "
                f"bubble={self.bubble_frac:.2f})")


class Candidate:
    """One searched placement: a concrete mesh (axes + shape, the exact
    ``make_mesh`` arguments a hand-built lane would pass) plus the
    ``ShardingPlan`` kwargs that materialize it, its cost, and — when
    pruned — why it was never ranked."""

    def __init__(self, sizes, plan_kw=None, cost=None, pruned=None,
                 note=""):
        self.sizes = {a: int(sizes.get(a, 1)) for a in _AXIS_ORDER}
        self.plan_kw = dict(plan_kw or {})
        self.cost = cost
        self.pruned = pruned
        self.note = str(note)

    @property
    def axes(self):
        axes = tuple(a for a in _AXIS_ORDER if self.sizes[a] > 1)
        return axes or ("dp",)

    @property
    def shape(self):
        return tuple(self.sizes[a] for a in self.axes)

    @property
    def n_devices(self):
        n = 1
        for d in self.shape:
            n *= d
        return n

    def describe(self):
        body = "x".join(f"{a}{self.sizes[a]}" for a in self.axes)
        kw = ",".join(f"{k}={v}" for k, v in sorted(self.plan_kw.items()))
        return body + (f" [{kw}]" if kw else "")

    def build(self, devices=None):
        """-> ``(mesh, ShardingPlan)`` constructed exactly as a hand
        lane constructs them (same make_mesh arguments, same plan
        kwargs) — the bitwise-equality contract of ``apply``."""
        mesh = make_mesh(self.n_devices, axes=self.axes, shape=self.shape,
                         devices=devices)
        return mesh, ShardingPlan(mesh, **self.plan_kw)

    def to_doc(self):
        return {"sizes": {a: s for a, s in self.sizes.items() if s > 1},
                "plan_kw": dict(self.plan_kw),
                "cost": None if self.cost is None else self.cost.to_doc(),
                "pruned": self.pruned,
                "note": self.note}

    @classmethod
    def from_doc(cls, doc):
        if not isinstance(doc, dict) \
                or not isinstance(doc.get("sizes"), dict):
            raise ValueError("malformed plan candidate")
        sizes = {}
        for a, s in doc["sizes"].items():
            if a not in _AXIS_ORDER:
                raise ValueError(f"unknown mesh axis {a!r} in candidate")
            sizes[a] = int(s)
        pruned = doc.get("pruned")
        if pruned is not None and not isinstance(pruned, str):
            raise ValueError("malformed candidate pruned reason")
        kw = doc.get("plan_kw", {})
        if not isinstance(kw, dict):
            raise ValueError("malformed candidate plan_kw")
        cost = doc.get("cost")
        return cls(sizes, plan_kw=kw,
                   cost=None if cost is None else PlanCost.from_doc(cost),
                   pruned=pruned, note=doc.get("note", ""))


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_meshes(target, n_devices, moe_experts=None):
    """Every legal full-device-count factorization for ``target`` (a
    Program or :class:`ProgramFeatures`): (dp, pp, tp, sp) products plus
    (dp, ep) products when MoE experts are declared, each as a
    :class:`Candidate` whose ``build()`` materializes the concrete mesh
    + ShardingPlan. Legality is per-axis:

    * dp — the feed batch (when known) splits evenly;
    * tp — some 2-D parameter's output dim really shards at this tp
      (the ``ShardingPlan._base_spec`` predicate);
    * pp — the param-bearing layer chain is at least ``pp`` deep;
    * sp — the program has attention ops and the sequence length (when
      known) splits evenly;
    * ep — declared MoE experts split evenly.

    dp>1 candidates additionally spawn a ZeRO-1 variant
    (``shard_opt_state=True``) — same mesh, optimizer state sharded over
    dp, strictly less memory at equal modeled step cost."""
    f = target if isinstance(target, ProgramFeatures) \
        else extract_features(target, measure=False,
                              moe_experts=moe_experts or 0)
    if moe_experts is not None:
        f.moe_experts = int(moe_experts)
    n = int(n_devices)
    if n < 1:
        raise PlanError(f"n_devices must be >= 1, got {n}")

    def dp_ok(dp):
        return dp == 1 or f.batch is None \
            or (f.batch % dp == 0 and f.batch >= dp)

    out, seen = [], set()

    def add(sizes, plan_kw=None):
        key = (tuple(sorted((a, s) for a, s in sizes.items() if s > 1)),
               tuple(sorted((plan_kw or {}).items())))
        if key in seen:
            return
        seen.add(key)
        out.append(Candidate(sizes, plan_kw=plan_kw))

    for dp in _divisors(n):
        if not dp_ok(dp):
            continue
        rem = n // dp
        for pp in _divisors(rem):
            if pp > 1 and f.layer_chain < pp:
                continue
            rem2 = rem // pp
            for tp in _divisors(rem2):
                if tp > 1 and not f.tp_shardable_bytes(tp):
                    continue
                sp = rem2 // tp
                if sp > 1 and not (f.attention and (
                        f.seq_len is None or f.seq_len % sp == 0)):
                    continue
                sizes = {"dp": dp, "pp": pp, "tp": tp, "sp": sp}
                add(sizes)
                if dp > 1:
                    add(sizes, plan_kw={"shard_opt_state": True})
        # expert parallelism: (dp, ep) products over declared experts
        if f.moe_experts:
            ep = n // dp
            if ep > 1 and f.moe_experts % ep == 0:
                add({"dp": dp, "ep": ep})
    if not out:
        raise PlanError(
            f"no legal mesh for {n} devices (batch={f.batch}): even "
            "pure data parallelism cannot split this feed")
    return f, out


def _machine_rates():
    import jax
    dev = jax.devices()[0]
    platform = str(dev.platform)
    return (PEAK_FLOPS_S.get(platform, PEAK_FLOPS_S["cpu"]),
            COLLECTIVE_BYTES_S.get(platform, COLLECTIVE_BYTES_S["cpu"]))


def cost_candidate(features, cand, microbatches=None, comm_scale=1.0,
                   rates=None):
    """Cost one candidate: measured compute split over every shard,
    analytic collective seconds per parallel axis, per-device memory,
    pipeline bubble. ``comm_scale`` multiplies every modeled collective
    byte (the monotonicity probe: scaling it up must never improve a
    candidate's rank); ``rates`` overrides ``(flops_s, bytes_s)``."""
    f, s = features, cand.sizes
    dp, ep, pp, tp, sp = (s[a] for a in _AXIS_ORDER)
    shards = dp * ep * pp * tp * sp
    flops_s, bytes_s = rates or _machine_rates()

    compute_s = f.flops_estimate() / shards / flops_s

    dtype_b = f.dtype_bytes
    shard_b = f.tp_shardable_bytes(tp) if tp > 1 else 0
    dense_b = f.param_bytes - shard_b
    moe_b = min(f.moe_param_bytes, dense_b) if ep > 1 else 0
    # per-device gradient bytes after the model-axis splits: tp shards
    # the shardable 2-D params, pp splits the layer chain across
    # stages, ep shards the expert stacks
    grad_b = ((dense_b - moe_b) + moe_b / ep + shard_b / tp) / pp
    act_b = f.activation_bytes() / max(dp, 1)

    comm = 0.0
    if dp > 1:
        # ring all-reduce of the per-device gradients over dp
        comm += 2.0 * (dp - 1) / dp * grad_b
    if tp > 1:
        # Megatron-style activation all-reduce per tp-sharded layer pair
        comm += 2.0 * (tp - 1) / tp * act_b
    if sp > 1:
        # ring attention: each device passes its KV block around the ring
        comm += 2.0 * (sp - 1) / sp * act_b
    if ep > 1:
        # token all-to-all into and out of the expert shards
        comm += 2.0 * (ep - 1) / ep * act_b
    bubble = 0.0
    if pp > 1:
        # stage-boundary activations, p2p both directions (fwd + bwd)
        comm += 2.0 * (pp - 1) * act_b / max(tp * sp, 1)
        micro = int(microbatches or PIPELINE_MICROBATCHES)
        bubble = (pp - 1) / float(micro + pp - 1)
    comm_s = comm * float(comm_scale) / bytes_s

    # per-device memory: params + grads + optimizer state (~3x params;
    # ZeRO-1 shards the optimizer copy over dp) + activations (sharded
    # by dp and, for attention blocks, sp)
    params_dev = (dense_b - moe_b) / pp + moe_b / ep + shard_b / (tp * pp)
    opt_copies = 2.0 + (1.0 / dp if cand.plan_kw.get("shard_opt_state")
                        else 1.0)
    mem = params_dev * opt_copies + f.activation_bytes() / (dp * sp)
    # keep dtype_b referenced for subclass overrides of activation math
    del dtype_b
    return PlanCost(compute_s, comm_s, mem, bubble)


# ---------------------------------------------------------------------------
# fingerprint + report
# ---------------------------------------------------------------------------

def plan_fingerprint(signature, n_devices):
    """Identity a plan is valid for: format/schema + toolchain + backend
    + device kind + DEVICE COUNT + the program's content hash + the
    planner flags that shape the search. Anything else different is a
    filename miss; a doctored artifact is a typed ``fingerprint``
    reject."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    return {
        "format": 1,
        "kind": "placement_plan",
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": str(dev.platform),
        "device_kind": str(getattr(dev, "device_kind", dev.platform)),
        "n_devices": int(n_devices),
        "program": str(signature),
        "flags": {
            "plan_memory_budget_bytes":
                int(get_flag("plan_memory_budget_bytes")),
            "plan_max_candidates": int(get_flag("plan_max_candidates")),
        },
    }


def fingerprint_key(fp):
    """Stable digest of a fingerprint dict (the artifact filename key)."""
    return hashlib.sha256(
        json.dumps(fp, sort_keys=True, default=str).encode()).hexdigest()


class PlacementReport:
    """The search result: ranked candidates (cheapest modeled step
    first), the pruned set with why-pruned notes, and the identity
    fingerprint the report was computed under."""

    def __init__(self, fingerprint, candidates, n_devices, dropped=0,
                 from_cache=False):
        self.fingerprint = dict(fingerprint)
        self.candidates = list(candidates)
        self.n_devices = int(n_devices)
        self.dropped = int(dropped)
        self.from_cache = bool(from_cache)

    def ranked(self):
        return [c for c in self.candidates if c.pruned is None]

    def pruned(self):
        return [c for c in self.candidates if c.pruned is not None]

    @property
    def chosen(self):
        r = self.ranked()
        return r[0] if r else None

    def candidate(self, **sizes):
        """The ranked candidate with exactly these axis sizes (axes not
        named must be 1), or None — how a lane finds its naive-all-dp
        baseline row in the report."""
        want = {a: int(sizes.get(a, 1)) for a in _AXIS_ORDER}
        for c in self.ranked():
            if c.sizes == want and not c.plan_kw:
                return c
        return None

    def apply(self, executor, program, feed_example, fetch_list,
              scope=None, donate=False, devices=None):
        """Emit the chosen placement through ``shard_program_step`` —
        bitwise the step a hand-built mesh/ShardingPlan produces."""
        if self.chosen is None:
            raise PlanError(
                "no candidate survived pruning "
                f"({len(self.pruned())} pruned: "
                f"{sorted({c.pruned for c in self.pruned()})}); raise "
                "plan_memory_budget_bytes or shrink the model")
        return apply_candidate(self.chosen, executor, program,
                               feed_example, fetch_list, scope=scope,
                               donate=donate, devices=devices)

    def to_doc(self):
        return {
            "schema": "pdtpu-plan-v1",
            "fingerprint": dict(self.fingerprint),
            "n_devices": self.n_devices,
            "dropped": self.dropped,
            "candidates": [c.to_doc() for c in self.candidates],
        }

    @classmethod
    def from_doc(cls, doc):
        """Strict schema validation — any violation raises ValueError
        (the store's ``deserialize`` reject)."""
        if not isinstance(doc, dict) \
                or doc.get("schema") != "pdtpu-plan-v1":
            raise ValueError("not a pdtpu-plan-v1 document")
        fp = doc.get("fingerprint")
        cands = doc.get("candidates")
        if not isinstance(fp, dict) or not isinstance(cands, list):
            raise ValueError("malformed placement-plan document")
        try:
            n = int(doc["n_devices"])
        except (KeyError, TypeError, ValueError):
            raise ValueError("malformed placement-plan n_devices") \
                from None
        return cls(fp, [Candidate.from_doc(c) for c in cands], n,
                   dropped=int(doc.get("dropped", 0)))

    def digest(self):
        return hashlib.sha256(
            json.dumps(self.to_doc(), sort_keys=True).encode()).hexdigest()

    def render(self):
        """Human-readable ranking table (tools/plan_parallel.py and the
        bench lane's 'report emitted' gate)."""
        lines = [f"placement plan over {self.n_devices} devices "
                 f"({'cache' if self.from_cache else 'searched'}):"]
        for i, c in enumerate(self.ranked()):
            cost = c.cost
            mark = "->" if i == 0 else "  "
            lines.append(
                f" {mark} {c.describe():28s} total={cost.total_s():.3e}s "
                f"compute={cost.compute_s:.3e}s comm={cost.comm_s:.3e}s "
                f"mem={cost.memory_bytes / 1e6:.1f}MB "
                f"bubble={cost.bubble_frac:.2f}")
        for c in self.pruned():
            mem = "" if c.cost is None \
                else f" mem={c.cost.memory_bytes / 1e6:.1f}MB"
            lines.append(f"  x {c.describe():28s} pruned: {c.pruned}"
                         f"{mem} {c.note}".rstrip())
        if self.dropped:
            lines.append(f"  ({self.dropped} further candidates dropped "
                         "past plan_max_candidates)")
        return "\n".join(lines)


def apply_candidate(cand, executor, program, feed_example, fetch_list,
                    scope=None, donate=False, devices=None):
    """Materialize one candidate and compile the sharded step through
    the existing ``shard_program_step`` path. The mesh and ShardingPlan
    are constructed with exactly the arguments a hand-built lane passes
    (``make_mesh(n, axes, shape)`` + ``ShardingPlan(mesh, **kw)``), so
    the compiled step — and every loss it fetches — is bitwise equal to
    the hand-built plan."""
    mesh, sharding_plan = cand.build(devices=devices)
    fn, state, feeds = shard_program_step(
        executor, program, feed_example, fetch_list, sharding_plan,
        scope=scope, donate=donate)
    return fn, state, feeds, sharding_plan


# ---------------------------------------------------------------------------
# persistence (the ops/autotune artifact contract)
# ---------------------------------------------------------------------------

class PlanStore:
    """One directory of placement-plan artifacts under the autotune /
    execcache discipline: content-addressed envelope, identity in the
    filename, typed bounded rejects, optional manifest pinning,
    tmp+replace writes. ``load`` and ``save`` never raise — a broken
    plan must only ever cost the fresh search it failed to replace."""

    def __init__(self, path, readonly=False, expected_digests=None):
        self.path = str(path)
        self.readonly = bool(readonly)
        self._expected = None if expected_digests is None \
            else dict(expected_digests)
        if not self.readonly:
            os.makedirs(self.path, exist_ok=True)
        self._touched = set()

    def artifact_path(self, fp):
        return os.path.join(
            self.path, f"plan-{fingerprint_key(fp)[:40]}{ARTIFACT_SUFFIX}")

    def note_reject(self, reason, error=None):
        if reason not in REJECT_REASONS:
            reason = "deserialize"
        _M_REJECTS.labels(reason=reason).inc()
        _record("plan_reject", dir=self.path, reason=reason,
                error=None if error is None
                else f"{type(error).__name__}: {error}")

    def load(self, fp):
        """The report for this planning identity, or None (miss or
        typed reject — the caller searches fresh). A missing file is a
        silent miss; everything else wrong is a counted reject."""
        path = self.artifact_path(fp)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        stage = "format"
        try:
            if self._expected is not None:
                # manifest pinning: raw bytes must be exactly what the
                # version manifest certifies, BEFORE any parsing
                stage = "manifest"
                want = self._expected.get(os.path.basename(path))
                if want is None:
                    raise ValueError("artifact is not listed in the "
                                     "version manifest's plan_files")
                if hashlib.sha256(raw).hexdigest() != want:
                    raise ValueError("artifact bytes do not match the "
                                     "manifest's plan_files digest")
                stage = "format"
            if not raw.startswith(_MAGIC):
                raise ValueError("bad magic (not a placement-plan "
                                 "artifact)")
            header_end = raw.index(b"\n", len(_MAGIC))
            digest = raw[len(_MAGIC):header_end].decode("ascii")
            blob = raw[header_end + 1:]
            if hashlib.sha256(blob).hexdigest() != digest:
                raise ValueError("payload digest mismatch (truncated or "
                                 "bit-flipped artifact)")
            stage = "deserialize"
            report = PlacementReport.from_doc(
                json.loads(blob.decode("utf-8")))
            stage = "fingerprint"
            if report.fingerprint != fp:
                raise ValueError("plan fingerprint does not match this "
                                 "process's planning identity")
        except Exception as e:
            self.note_reject(stage, error=e)
            return None
        self._touched.add(os.path.basename(path))
        report.from_cache = True
        return report

    def save(self, report):
        """Persist one report (tmp + ``os.replace``); returns the
        artifact path, or None when read-only / unwritable."""
        if self.readonly:
            return None
        try:
            blob = json.dumps(report.to_doc(), sort_keys=True).encode()
            data = (_MAGIC + hashlib.sha256(blob).hexdigest().encode()
                    + b"\n" + blob)
            path = self.artifact_path(report.fingerprint)
            tmp = path + f".{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except Exception as e:
            _record("plan_save_failed", dir=self.path,
                    error=f"{type(e).__name__}: {e}")
            return None
        self._touched.add(os.path.basename(path))
        return path

    def touched(self):
        return sorted(self._touched)


def manifest_plan_digests(model_dir):
    """basename -> sha256 pin set from the version manifest's
    ``plan_files``; manifest without the field pins the empty set; no
    readable manifest returns None (a raw export — the artifact
    self-digest is the only integrity layer)."""
    try:
        with open(os.path.join(model_dir, "VERSION.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    return {os.path.basename(rel): digest
            for rel, digest in manifest.get("plan_files", {}).items()}


def resolve_store(model_dir=None):
    """The store a planning site should consult: the bundle's published
    ``plan/`` dir (read-only, manifest-pinned) when it exists, else the
    ``plan_cache_dir`` flag's local READ-WRITE cache (a fresh search
    persists there so the next process loads), else None."""
    if model_dir:
        pdir = os.path.join(str(model_dir), PLAN_DIRNAME)
        if os.path.isdir(pdir):
            return PlanStore(pdir, readonly=True,
                             expected_digests=manifest_plan_digests(
                                 str(model_dir)))
    local = get_flag("plan_cache_dir")
    if local:
        return PlanStore(local)
    return None


# ---------------------------------------------------------------------------
# the planner entry point
# ---------------------------------------------------------------------------

def plan(program, feed_example=None, n_devices=None, fetch_list=None,
         executor=None, scope=None, features=None, moe_experts=0,
         seq_len=None, memory_budget=None, max_candidates=None,
         microbatches=None, store=None, model_dir=None, measure=True):
    """Search the legal meshes for ``program`` over ``n_devices`` and
    return a ranked :class:`PlacementReport`.

    ``program`` may be a fluid Program (features are extracted, and with
    a ``feed_example`` + ``fetch_list`` the compute term is MEASURED via
    ``obs.perf.attribute``) or a :class:`ProgramFeatures` describing a
    jax-level workload (the moe/ring lanes). ``memory_budget`` /
    ``max_candidates`` default from the ``plan_memory_budget_bytes`` /
    ``plan_max_candidates`` flags; candidates over budget are pruned
    with a note, never ranked. ``store`` (or the store resolved from
    ``model_dir`` / the ``plan_cache_dir`` flag) is consulted first —
    a fingerprint-matching artifact skips the search entirely
    (``paddle_tpu_plan_cache_hits``); any corrupt artifact is a typed
    reject plus a fresh search, never a failure."""
    import jax

    n = int(n_devices) if n_devices else jax.device_count()
    if features is None and isinstance(program, ProgramFeatures):
        features = program
    if features is None:
        features = extract_features(program, feed_example=feed_example,
                                    fetch_list=fetch_list,
                                    executor=executor, scope=scope,
                                    moe_experts=moe_experts,
                                    seq_len=seq_len, measure=measure)
    fp = plan_fingerprint(features.signature, n)

    if store is None:
        store = resolve_store(model_dir)
    if store is not None:
        cached = store.load(fp)
        if cached is not None:
            _M_CACHE_HITS.labels().inc()
            _record("plan_cache_hit", dir=store.path, n_devices=n,
                    chosen=None if cached.chosen is None
                    else cached.chosen.describe())
            return cached

    _M_SEARCHES.labels().inc()
    budget = int(get_flag("plan_memory_budget_bytes")
                 if memory_budget is None else memory_budget)
    cap = int(get_flag("plan_max_candidates")
              if max_candidates is None else max_candidates)

    features, candidates = enumerate_meshes(features, n,
                                            moe_experts=moe_experts
                                            or None)
    for c in candidates:
        c.cost = cost_candidate(features, c, microbatches=microbatches)
        if budget > 0 and c.cost.memory_bytes > budget:
            c.pruned = "memory_budget"
            c.note = (f"per-device {c.cost.memory_bytes} B > budget "
                      f"{budget} B")
    # rank the survivors: cheapest modeled step, then least memory, then
    # the simplest mesh — deterministic across runs
    ranked = sorted((c for c in candidates if c.pruned is None),
                    key=lambda c: (c.cost.total_s(), c.cost.memory_bytes,
                                   len(c.axes), c.describe()))
    pruned = [c for c in candidates if c.pruned is not None]
    dropped = max(0, len(ranked) - cap) if cap > 0 else 0
    if dropped:
        ranked = ranked[:cap]
    report = PlacementReport(fp, ranked + pruned, n, dropped=dropped)
    _record("plan_search", n_devices=n, candidates=len(candidates),
            pruned=len(pruned), dropped=dropped,
            chosen=None if report.chosen is None
            else report.chosen.describe())
    if store is not None and not store.readonly:
        store.save(report)
    return report


__all__ = [
    "ARTIFACT_SUFFIX", "Candidate", "PLAN_DIRNAME", "PlanCost",
    "PlanError", "PlanStore", "PlacementReport", "ProgramFeatures",
    "REJECT_REASONS", "apply_candidate", "cost_candidate",
    "enumerate_meshes", "extract_features", "fingerprint_key",
    "manifest_plan_digests", "plan", "plan_fingerprint",
    "program_signature", "resolve_store",
]
