"""Pipeline parallelism: a GPipe-style microbatch pipeline over a ``pp``
mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.3 lists it as
TPU-native new work); its model-parallel story is per-layer device
placement (legacy parallel_nn). TPU-first construction: a stack of S
identical stages lives stage-sharded as ``params[S, ...]`` with stage s's
slice on device s; microbatches stream through a shift register
of activations that advances via ``ppermute`` over the ICI ring each tick
(the scaling-book pipelining recipe). M microbatches drain in M + S - 1
ticks with the usual (S-1)/M bubble; reverse-mode AD through the shard_map
(ppermute transposes to the reverse ring) gives the backward schedule for
free.

    mesh = make_mesh(4, axes=("pp",))
    y = pipeline_apply(stage_fn, stacked_params, x_microbatches, mesh)

``stage_fn(stage_params, x) -> y`` must keep x/y the same shape (the
inter-stage activation). All devices run every tick (bubble ticks compute
on zeros), exactly like hardware pipelines.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map


def shard_pipeline_params(stacked_params, mesh, axis="pp"):
    """Place a [S, ...] stage-stacked param pytree stage-sharded."""
    ep = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, ep),
                                  stacked_params)


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh, axis="pp",
                   data_spec=None, param_specs=None):
    """Run ``microbatches [M, mb, ...]`` through S pipelined stages.

    stacked_params: pytree of [S, ...] arrays (stage-major, sharded or not);
    returns [M, mb, ...] outputs.

    Composition hooks (dp×pp×tp on one 3-axis mesh): ``data_spec`` shards
    the microbatch dims over other mesh axes (e.g. P(None, "dp") — each dp
    group pipelines its own batch shard; outputs come back with the same
    spec), and ``param_specs`` overrides the per-leaf parameter specs so
    stage weights can ALSO be tensor-sharded (e.g. P("pp", None, "tp") with
    the stage_fn psum-ing its partial matmul over "tp" — the Megatron
    pattern inside each pipeline stage)."""
    n_stages = mesh.shape[axis]
    m = microbatches.shape[0]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stacked_params leading dim {leaf.shape[0]} must equal the "
                f"{axis!r} axis size {n_stages} (one stage per device; "
                "stack-fold larger stacks into the stage_fn)")

    def per_device(params, xs):
        # params: this device's [1, ...] stage slice; xs: full [M, mb, ...]
        local = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = jax.lax.axis_index(axis)
        mb_shape = xs.shape[1:]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            buf, outs = carry
            # stage 0 injects microbatch t (zeros once the stream drains)
            inject = jnp.where(t < m, xs[jnp.minimum(t, m - 1)],
                               jnp.zeros(mb_shape, xs.dtype))
            inp = jnp.where(stage == 0, inject, buf)
            y = stage_fn(local, inp)
            # last stage collects finished microbatch t - (S-1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            take = (stage == n_stages - 1) & (t >= n_stages - 1)
            outs = jax.lax.dynamic_update_slice(
                outs,
                jnp.where(take, y, jax.lax.dynamic_index_in_dim(
                    outs, out_idx, keepdims=False))[None],
                (out_idx,) + (0,) * len(mb_shape))
            buf = jax.lax.ppermute(y, axis, perm)
            return (buf, outs), None

        outs0 = jnp.zeros((m,) + mb_shape, xs.dtype)
        buf0 = jnp.zeros(mb_shape, xs.dtype)
        (_, outs), _ = jax.lax.scan(tick, (buf0, outs0),
                                    jnp.arange(m + n_stages - 1))
        # outputs live on the last stage; broadcast to every device
        keep = (stage == n_stages - 1).astype(xs.dtype)
        return jax.lax.psum(outs * keep, axis)

    spec_params = param_specs if param_specs is not None else \
        jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    dspec = data_spec if data_spec is not None else P()
    if len(dspec) >= 1 and dspec[0] is not None:
        # per_device closes over the GLOBAL microbatch count; sharding the
        # M dim would silently re-feed clamped local microbatches
        raise ValueError(
            f"data_spec {dspec} must not partition the leading microbatch "
            "dim; shard the per-microbatch batch dim (e.g. P(None, 'dp'))")
    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(spec_params, dspec), out_specs=dspec,
                   check_rep=False)
    return fn(stacked_params, microbatches)


def pipeline_stack_reference(stage_fn, stacked_params, microbatches):
    """Sequential (non-pipelined) reference: fold every stage over every
    microbatch — what pipeline_apply must match bit-for-bit modulo
    reduction order."""
    s = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]

    def apply_all(x):
        for i in range(s):
            local = jax.tree_util.tree_map(lambda a: a[i], stacked_params)
            x = stage_fn(local, x)
        return x

    return jax.vmap(apply_all)(microbatches)
