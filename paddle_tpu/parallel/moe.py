"""Expert parallelism: a mixture-of-experts FFN sharded over an ``ep`` mesh
axis.

The reference predates MoE entirely (SURVEY.md §2.3: expert parallelism
listed as TPU-native new work, "megablocks-style EP if desired"); its
closest capability is the sparse distributed lookup table. This module is
the TPU-first construction: top-1 token routing with a fixed per-expert
capacity (static shapes — the GShard/mesh-tensorflow dispatch-einsum
formulation), experts' weights sharded over ``ep``, and the token
shuffle expressed as plain einsums under GSPMD sharding constraints so XLA
inserts the all-to-all collectives over ICI.

    mesh = make_mesh(8, axes=("ep",))
    out, aux_loss = moe_ffn(x, params, mesh)    # x [tokens, d]

Routing uses a softmax gate; ``aux_loss`` is the standard load-balancing
term (mean fraction * mean gate mass per expert, scaled by E) to train
against expert collapse. Dropped tokens (over capacity) pass through the
residual (output 0 for their expert contribution), the GShard policy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def init_moe_params(rng, d_model, d_hidden, n_experts, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(rng, 3)
    scale = 1.0 / jnp.sqrt(d_model)
    return {
        "gate": jax.random.normal(k1, (d_model, n_experts), dtype) * scale,
        "w_in": jax.random.normal(k2, (n_experts, d_model, d_hidden),
                                  dtype) * scale,
        "w_out": jax.random.normal(k3, (n_experts, d_hidden, d_model),
                                   dtype) * (1.0 / jnp.sqrt(d_hidden)),
    }


def shard_moe_params(params, mesh, axis="ep"):
    """Place expert weights expert-sharded over the mesh (gate replicated)."""
    ep = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    return {
        "gate": jax.device_put(params["gate"], rep),
        "w_in": jax.device_put(params["w_in"], ep),
        "w_out": jax.device_put(params["w_out"], ep),
    }


def moe_ffn(x, params, mesh=None, axis="ep", capacity_factor=1.25,
            act=jax.nn.relu):
    """Top-1 routed expert FFN. x [n_tokens, d_model] -> (out, aux_loss).

    The dispatch/combine are one-hot einsums over a [tokens, E, C] mask —
    static shapes; with ``mesh`` given, sharding constraints pin the
    expert-major intermediates to the ep axis so GSPMD materializes the
    token shuffle as all-to-all over ICI."""
    n, d = x.shape
    e = params["w_in"].shape[0]
    cap = max(1, int(capacity_factor * n / e))

    logits = x @ params["gate"]                     # [n, E]
    gates = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)         # [n]
    gate_val = jnp.take_along_axis(gates, expert_idx[:, None], axis=1)[:, 0]

    # position of each token within its expert's capacity buffer
    onehot = jax.nn.one_hot(expert_idx, e, dtype=x.dtype)       # [n, E]
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot           # [n, E]
    keep = pos < cap
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, -1).astype(jnp.int32),
                            cap, dtype=x.dtype)                 # [n, E, C]
    dispatch = onehot[:, :, None] * pos_oh                      # [n, E, C]

    # aux load-balancing loss (GShard eq. 4): E * mean(frac) . mean(gate)
    frac = jnp.mean(onehot, axis=0)
    mean_gate = jnp.mean(gates, axis=0)
    aux_loss = e * jnp.sum(frac * mean_gate)

    expert_in = jnp.einsum("nd,nec->ecd", x, dispatch)          # [E, C, d]
    if mesh is not None:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(axis)))
    h = act(jnp.einsum("ecd,edh->ech", expert_in, params["w_in"]))
    expert_out = jnp.einsum("ech,ehd->ecd", h, params["w_out"])
    if mesh is not None:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(axis)))

    combine = dispatch * gate_val[:, None, None]                # [n, E, C]
    out = jnp.einsum("ecd,nec->nd", expert_out, combine)
    return out, aux_loss
