"""Distributed execution: device meshes + sharding planner + collectives.

This package is the TPU-native replacement for the reference's entire
distribution stack (SURVEY.md §2.3): the DistributeTranspiler
(/root/reference/python/paddle/fluid/distribute_transpiler.py:134), the gRPC
pserver path (operators/detail/), NCCL parallel_do (operators/nccl/), and the
legacy/Go parameter servers. Instead of rewriting programs into trainer+pserver
pairs communicating over RPC, the planner annotates the compiled step function
with jax.sharding shardings over a Mesh and lets GSPMD insert ICI collectives.
"""

from .sharding import (ShardingPlan, make_mesh, shard_program_step,
                       place_feed)
from .ring_attention import ring_attention
from .moe import moe_ffn, init_moe_params, shard_moe_params
from .pipeline import (pipeline_apply, shard_pipeline_params,
                       pipeline_stack_reference)
from .multihost import init_multihost, global_mesh
from .planner import (Candidate, PlacementReport, PlanCost, PlanError,
                      PlanStore, ProgramFeatures, apply_candidate,
                      cost_candidate, enumerate_meshes, extract_features,
                      plan)

__all__ = ["ShardingPlan", "make_mesh", "shard_program_step", "place_feed",
           "ring_attention", "init_multihost", "global_mesh",
           "moe_ffn", "init_moe_params", "shard_moe_params",
           "pipeline_apply", "shard_pipeline_params",
           "pipeline_stack_reference",
           "Candidate", "PlacementReport", "PlanCost", "PlanError",
           "PlanStore", "ProgramFeatures", "apply_candidate",
           "cost_candidate", "enumerate_meshes", "extract_features",
           "plan"]
