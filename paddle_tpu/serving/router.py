"""FleetClient: a client-side load balancer over a replica fleet.

The routing layer the reference delegated to an external LB sits client-
side here (the gRPC "thick client" pattern): pick a replica by
power-of-two-choices over in-flight counts, fail an idempotent ``infer``
over to a DIFFERENT replica on connection failure, spill a typed
``ServerOverloaded`` to the next replica before surfacing it, and keep a
health view of the fleet — a replica that fails is EJECTED from the pick
set and re-admitted only after a probation of consecutive successful
background health probes (flapping replicas don't bounce in and out on a
single lucky probe).

Error taxonomy (what moves where):

* connection failure (EOF mid-call, refused connect — a crashed or
  restarting replica) — eject the replica, fail over to another; when a
  whole sweep of the fleet fails this way, back off under the
  ``rpc.RetryPolicy`` and sweep again (infer is stateless/idempotent, so
  resending is always safe).
* :class:`~.batcher.ServerOverloaded` (structured code over the wire) —
  the replica is alive but saturated: NOT an ejection (health is fine),
  just spill to the next replica; only when every available replica is
  overloaded does the caller see the typed overload (never auto-retried —
  retrying into a saturated fleet spreads collapse).
* response timeout — ambiguous (the request may be executing), surfaced
  to the caller like every other client in this codebase.
* :class:`~.batcher.QuotaExceeded` — the TENANT is over budget, not the
  replica: deterministic everywhere, so surfaced typed with NO failover
  and NO spillover (spilling an over-quota request to the next replica
  would just burn a connection to be rejected identically). Enforced
  router-side first (``quotas=``) — a locally rejected request never
  even picks a replica — and re-raised typed when a server-side bucket
  rejects over the wire.
* remote errors (``rpc.RemoteError``) — deterministic (a bad feed fails
  identically on every replica): surfaced, no failover.
"""

from __future__ import annotations

import random
import threading
import time

from ..core.flags import get_flag
from ..core.profiler import trace_context
from ..distributed.rpc import RetryPolicy, RpcClient
from ..obs import recorder as _flight
from ..obs.metrics import REGISTRY as _METRICS, json_safe, next_instance
from .batcher import QuotaExceeded, ServerOverloaded
from .client import InferClient

_CONN_ERRORS = (EOFError, ConnectionError, BrokenPipeError, OSError)

_M_REQUESTS = _METRICS.counter(
    "paddle_tpu_router_requests",
    "requests routed through a FleetClient, per instance",
    labels=("instance",))
_M_FAILOVERS = _METRICS.counter(
    "paddle_tpu_router_failovers",
    "connection-failure failovers to another replica, per instance",
    labels=("instance",))
_M_SPILLOVERS = _METRICS.counter(
    "paddle_tpu_router_spillovers",
    "ServerOverloaded spillovers to the next replica, per instance",
    labels=("instance",))
_M_EJECTIONS = _METRICS.counter(
    "paddle_tpu_router_ejections",
    "replicas ejected from the routing set, per instance",
    labels=("instance",))
_M_QUOTA_REJECTS = _METRICS.counter(
    "paddle_tpu_router_quota_rejects",
    "requests rejected typed with QuotaExceeded at a FleetClient "
    "(router-local bucket or a replica's over the wire) — never "
    "failovers, never spillovers; per instance",
    labels=("instance",))
_M_FLEET_SECONDS = _METRICS.histogram(
    "paddle_tpu_fleet_request_seconds",
    "FleetClient end-to-end request latency window, per instance",
    labels=("instance",), span_name="fleet/request", span_kind="rpc")


class _Replica:
    """Router-side view of one replica: a CONNECTION POOL (one RpcClient
    serializes its socket, so concurrent requests to the same replica
    each need their own connection — the pool's size tracks peak
    concurrency and idle connections are reused), the in-flight count,
    and the health/probation state (all mutated under the router lock)."""

    __slots__ = ("address", "timeout", "free", "inflight", "healthy",
                 "consec_ok", "ejections")

    def __init__(self, address, timeout):
        self.address = tuple(address)
        self.timeout = timeout
        self.free = []          # idle InferClients, LIFO (warm conn first)
        self.inflight = 0
        self.healthy = True
        self.consec_ok = 0
        self.ejections = 0

    def acquire_locked(self):
        """Check an idle connection out (caller holds the router lock) —
        or a fresh one; retry=None because the ROUTER owns failure
        policy: a per-connection retry would pin a request to a dead
        replica for the whole backoff budget instead of failing over."""
        if self.free:
            return self.free.pop()
        return InferClient(self.address, timeout=self.timeout, retry=None)

    def release_locked(self, client, broken):
        if broken:
            client.close()
        else:
            self.free.append(client)

    def close_all_locked(self):
        while self.free:
            self.free.pop().close()


class FleetClient:
    """``FleetClient(addresses)`` — balance infers over a replica set.

    ``retry`` (default a stock ``RetryPolicy``) bounds the full-fleet
    retry sweeps, NOT per-replica attempts; ``probe_interval_ms`` /
    ``probation_probes`` default from the ``serving_probe_interval_ms`` /
    ``serving_probation_probes`` flags."""

    def __init__(self, addresses, timeout=None, retry=True,
                 probe_interval_ms=None, probation_probes=None,
                 probe_timeout=2.0, quotas=None):
        if not addresses:
            raise ValueError("FleetClient needs at least one replica "
                             "address")
        if retry is True:
            retry = RetryPolicy()
        self._retry = retry or None
        # router-side tenant quotas (a batcher.TenantQuotas): enforced
        # BEFORE a replica is picked, so an over-budget request costs
        # zero fleet work and can never be mistaken for replica trouble
        self._quotas = quotas
        self._timeout = timeout
        self._replicas = [_Replica(a, timeout) for a in addresses]
        self._lock = threading.Lock()
        # router counters + latency window live in the obs.metrics
        # registry under this router's instance label
        self.obs_instance = next_instance("router")
        self.latency = _M_FLEET_SECONDS.labels(instance=self.obs_instance)
        self._m_requests = _M_REQUESTS.labels(instance=self.obs_instance)
        self._m_failovers = _M_FAILOVERS.labels(instance=self.obs_instance)
        self._m_spillovers = _M_SPILLOVERS.labels(
            instance=self.obs_instance)
        self._m_ejections = _M_EJECTIONS.labels(instance=self.obs_instance)
        self._m_quota_rejects = _M_QUOTA_REJECTS.labels(
            instance=self.obs_instance)
        if probe_interval_ms is None:
            probe_interval_ms = get_flag("serving_probe_interval_ms")
        self._probe_interval_s = float(probe_interval_ms) / 1e3
        if probation_probes is None:
            probation_probes = get_flag("serving_probation_probes")
        self._probation = max(1, int(probation_probes))
        self._probe_timeout = float(probe_timeout)
        self._stop = threading.Event()
        self._prober = threading.Thread(target=self._probe_loop,
                                        daemon=True)
        self._prober.start()

    # ------------------------------------------------------------------
    def add_replica(self, address):
        """Join ``address`` to the routing set (the autoscaler's
        scale-out hand-off: a spawned replica serves no traffic until
        some router routes to it). Idempotent — re-adding a member is a
        no-op. Returns True when the set grew."""
        address = (str(address[0]), int(address[1]))
        with self._lock:
            if any(r.address == address for r in self._replicas):
                return False
            self._replicas.append(_Replica(address, self._timeout))
        return True

    def remove_replica(self, address):
        """Drop ``address`` from the routing set (scale-in), closing its
        pooled connections; in-flight requests on it finish normally.
        Refuses to empty the set. Returns True when a member was
        removed."""
        address = (str(address[0]), int(address[1]))
        with self._lock:
            keep = [r for r in self._replicas if r.address != address]
            if len(keep) == len(self._replicas):
                return False
            if not keep:
                raise ValueError("cannot remove the last replica "
                                 f"{address[0]}:{address[1]}")
            for r in self._replicas:
                if r.address == address:
                    r.close_all_locked()
            self._replicas = keep
        return True

    def _pick(self, tried):
        """Power-of-two-choices over in-flight counts, healthy replicas
        first; falls back to ejected ones (a refused connect is cheap and
        beats stalling when the prober lags a restart). None when every
        replica was tried this sweep."""
        with self._lock:
            pool = [r for r in self._replicas
                    if r.healthy and id(r) not in tried]
            if not pool:
                pool = [r for r in self._replicas if id(r) not in tried]
            if not pool:
                return None
            if len(pool) == 1:
                r = pool[0]
            else:
                a, b = random.sample(pool, 2)
                r = a if a.inflight <= b.inflight else b
            r.inflight += 1
            return r

    def _release(self, r, client, broken):
        with self._lock:
            r.inflight -= 1
            r.release_locked(client, broken)

    def _eject(self, r):
        with self._lock:
            self._m_failovers.inc()
            ejected = False
            if r.healthy:
                r.healthy = False
                r.ejections += 1
                self._m_ejections.inc()
                ejected = True
            r.consec_ok = 0
            # pooled idle connections point at the dead incarnation; drop
            # them so a re-admitted replica starts on fresh sockets
            r.close_all_locked()
        # flight recorder: the routing DECISION (called inside the
        # request's trace context, so the event joins its track); one
        # event per failover, the ejection flagged on the first
        _flight.record("failover", component=self.obs_instance,
                       replica=f"{r.address[0]}:{r.address[1]}",
                       ejected=ejected)

    # ------------------------------------------------------------------
    def infer(self, feed, model=None, tenant=None):
        """One request through the fleet. Raises ``ServerOverloaded``
        only when every available replica rejected it, connection errors
        only when the whole fleet stayed unreachable through the retry
        budget, and ``QuotaExceeded`` immediately when ``tenant`` is
        over budget (no failover, no spillover — see module docstring).
        ``model=`` routes to a named hosted model on multi-model
        replicas."""
        self._m_requests.inc()
        if self._quotas is not None and tenant is not None:
            try:
                self._quotas.check(tenant)
            except QuotaExceeded:
                self._m_quota_rejects.inc()
                raise
        # ONE trace id for the whole fleet request: every failover /
        # spillover attempt below reuses it (the per-attempt InferClient
        # calls pick it up from the context), so the merged chrome trace
        # shows the request as one connected track across replicas
        with trace_context(), self.latency.span():
            attempt = 0
            while True:
                overload = None
                conn_err = None
                tried = set()
                while True:
                    r = self._pick(tried)
                    if r is None:
                        break
                    tried.add(id(r))
                    with self._lock:
                        client = r.acquire_locked()
                    broken = True    # returned to the pool only on success
                    try:
                        out = client.infer(feed, model=model,
                                           tenant=tenant)
                        broken = False
                        return out
                    except QuotaExceeded:
                        # a replica-side bucket rejected: deterministic
                        # for this tenant everywhere — surface typed,
                        # conn back to the pool, NO failover/spillover
                        broken = False
                        self._m_quota_rejects.inc()
                        _flight.record(
                            "quota_reject", component=self.obs_instance,
                            tenant=tenant,
                            replica=f"{r.address[0]}:{r.address[1]}")
                        raise
                    except ServerOverloaded as e:
                        self._m_spillovers.inc()
                        _flight.record(
                            "spillover", component=self.obs_instance,
                            replica=f"{r.address[0]}:{r.address[1]}")
                        broken = False   # replica alive; conn still good
                        overload = e
                    except TimeoutError:
                        raise        # ambiguous: may be executing; surface
                    except _CONN_ERRORS as e:
                        self._eject(r)
                        conn_err = e
                    finally:
                        self._release(r, client, broken)
                if overload is not None:
                    # every reachable replica is saturated: typed overload,
                    # never auto-retried (see module docstring)
                    raise overload
                if conn_err is None:
                    raise ConnectionError("fleet has no replicas to try")
                if self._retry is None \
                        or attempt >= self._retry.max_retries:
                    raise conn_err
                attempt += 1
                # the retry DECISION: a whole-fleet sweep failed and the
                # request is backing off for another — recorded so an
                # incident bundle shows how long a request chased a
                # restarting fleet
                _flight.record("retry_sweep", component=self.obs_instance,
                               attempt=attempt,
                               error=type(conn_err).__name__)
                time.sleep(self._retry.delay_s(attempt))

    # ------------------------------------------------------------------
    def _probe_loop(self):
        """Background health probes for EJECTED replicas: ``_probation``
        consecutive successes re-admit (one fluke doesn't); any failure
        resets the streak. Healthy replicas are not probed — real traffic
        is their probe."""
        while not self._stop.wait(self._probe_interval_s):
            for r in self._replicas:
                if r.healthy or self._stop.is_set():
                    continue
                ok = False
                try:
                    c = RpcClient(r.address, timeout=self._probe_timeout)
                    try:
                        h = c.call("health")
                        ok = (h.get("status") == "serving"
                              and bool(h.get("warmed", True)))
                    finally:
                        c.close()
                except Exception:
                    ok = False
                with self._lock:
                    if ok:
                        r.consec_ok += 1
                        if r.consec_ok >= self._probation:
                            r.healthy = True
                    else:
                        r.consec_ok = 0

    # ------------------------------------------------------------------
    def fleet_stats(self, include_server_stats=True):
        """Aggregate view: per-replica health/in-flight/ejections (plus
        each reachable replica's full server stats), router counters, and
        client-observed latency percentiles."""
        with self._lock:
            reps = [{"address": f"{r.address[0]}:{r.address[1]}",
                     "healthy": r.healthy, "inflight": r.inflight,
                     "ejections": r.ejections} for r in self._replicas]
        counters = {"requests": int(self._m_requests.value),
                    "failovers": int(self._m_failovers.value),
                    "spillovers": int(self._m_spillovers.value),
                    "ejections": int(self._m_ejections.value),
                    "quota_rejects": int(self._m_quota_rejects.value)}
        if self._quotas is not None:
            counters["quotas"] = self._quotas.stats()
        engine = {"compiles": 0, "hits": 0, "hot_recompiles": 0}
        versions = set()
        if include_server_stats:
            for entry, r in zip(reps, self._replicas):
                try:
                    c = RpcClient(r.address, timeout=self._probe_timeout)
                    try:
                        st = c.call("stats")
                    finally:
                        c.close()
                except Exception:
                    st = None
                entry["server"] = st
                if st is not None:
                    for k in engine:
                        engine[k] += st.get("engine", {}).get(k, 0)
                    versions.add(st.get("version"))
        lat = self.latency.snapshot()
        out = {"replicas": reps,
               "healthy": sum(1 for e in reps if e["healthy"]),
               "p50_ms": lat["p50_ms"], "p99_ms": lat["p99_ms"]}
        out.update(counters)
        if include_server_stats:
            out["engine"] = engine
            out["versions"] = sorted(versions,
                                     key=lambda v: (v is None, v))
        return json_safe(out)

    def close(self):
        self._stop.set()
        self._prober.join(self._probe_interval_s * 4
                          + self._probe_timeout + 1.0)
        with self._lock:
            for r in self._replicas:
                r.close_all_locked()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


__all__ = ["FleetClient"]
