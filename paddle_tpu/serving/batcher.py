"""DynamicBatcher: coalesce concurrent requests into bucket-sized batches.

The throughput lever of a model server: N concurrent single-row requests
cost N dispatches unbatched, but ONE dispatch coalesced — and on an
accelerator a dispatch has a large fixed cost (host round trip, executable
launch) that row count barely moves. The batcher holds a bounded queue;
a worker thread groups whole requests into a batch up to ``max_batch``
rows, waiting at most ``max_delay_ms`` for stragglers (a full batch
dispatches immediately, so the delay bound is only paid under quiet
traffic), runs the batch through the engine, and splits the fetches back
per caller.

Backpressure is the bounded queue: when ``capacity`` requests are already
waiting, :meth:`submit` rejects FAST with the typed
:class:`ServerOverloaded` — the client backs off and retries — instead of
admitting work the server cannot finish and stretching every caller's
latency without bound (the reference's unbounded-queue collapse mode).
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque

import numpy as np

from ..core.flags import get_flag
from ..obs.metrics import REGISTRY as _METRICS, json_safe, next_instance
from ..obs.recorder import record as _flight_record

_M_REQUESTS = _METRICS.counter(
    "paddle_tpu_batcher_requests",
    "requests submitted to a DynamicBatcher, per instance",
    labels=("instance",))
_M_REJECTED = _METRICS.counter(
    "paddle_tpu_batcher_rejected",
    "requests rejected with ServerOverloaded (queue full), per instance",
    labels=("instance",))
_M_BATCHES = _METRICS.counter(
    "paddle_tpu_batcher_batches",
    "coalesced batches dispatched by a DynamicBatcher, per instance",
    labels=("instance",))
_M_QUEUE_DEPTH = _METRICS.gauge(
    "paddle_tpu_server_queue_depth",
    "requests currently waiting in a serving queue (DynamicBatcher or "
    "ContinuousBatcher), per instance — updated on every enqueue/dequeue "
    "so scrapes and fleet_metrics() read it O(1)",
    labels=("instance",))
_M_TENANT_REQUESTS = _METRICS.counter(
    "paddle_tpu_tenant_requests",
    "requests checked against a TenantQuotas bucket, by quota instance "
    "and (capped, funneled) tenant label",
    labels=("instance", "tenant"))
_M_TENANT_REJECTED = _METRICS.counter(
    "paddle_tpu_tenant_rejected",
    "requests rejected with QuotaExceeded (tenant token bucket empty), "
    "by quota instance and (capped, funneled) tenant label",
    labels=("instance", "tenant"))


class ServerOverloaded(RuntimeError):
    """The serving queue is full: reject-fast backpressure. Clients should
    back off (bounded exponential delay) and retry or shed the request —
    InferClient re-raises this type from the remote error string."""


class QuotaExceeded(RuntimeError):
    """A tenant's token-bucket quota is exhausted: the request is over
    budget EVERYWHERE, so — unlike :class:`ServerOverloaded` — routers
    must surface it without failover or spillover (another replica would
    reject it identically). Carried over the wire as a structured code and
    re-raised typed by the clients (see serving/client.py)."""

    def __init__(self, message, tenant=None, retry_after_s=None):
        super().__init__(message)
        self.tenant = tenant
        self.retry_after_s = retry_after_s


_TENANT_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_\-]*$")


class TenantQuotas:
    """Per-tenant token buckets: each tenant accrues ``rate`` tokens per
    second up to a ``burst`` ceiling; a request spends one token or is
    rejected typed with :class:`QuotaExceeded` carrying the refill ETA.

    ``rate``/``burst`` default from the ``serving_tenant_rate`` /
    ``serving_tenant_burst`` flags (rate <= 0 means UNLIMITED — every
    tenant admits unless it has an explicit override). ``overrides`` maps
    tenant name -> (rate, burst) for per-tenant budgets; an override rate
    <= 0 makes that one tenant unlimited.

    Tenant ids arrive off the WIRE, so the registry mirror funnels them
    exactly like RPC method names: past ``serving_tenant_label_cap``
    distinct tenants (or a non-identifier name) the per-tenant series
    label collapses to ``__other__`` — a misbehaving caller inventing
    tenant ids must never grow scrape-visible cardinality without bound.
    ``stats()`` keeps the exact per-tenant view (it dies with the
    instance)."""

    def __init__(self, rate=None, burst=None, overrides=None,
                 label_cap=None):
        self.rate = float(get_flag("serving_tenant_rate")
                          if rate is None else rate)
        burst = int(get_flag("serving_tenant_burst")
                    if burst is None else burst)
        self.burst = burst if burst > 0 else max(1, int(math.ceil(
            self.rate if self.rate > 0 else 1)))
        self.overrides = {}
        for tenant, spec in (overrides or {}).items():
            r, b = spec
            r = float(r)
            b = int(b) if int(b) > 0 else max(1, int(math.ceil(
                r if r > 0 else 1)))
            self.overrides[str(tenant)] = (r, b)
        self._label_cap = int(get_flag("serving_tenant_label_cap")
                              if label_cap is None else label_cap)
        self._lock = threading.Lock()
        self._buckets = {}    # tenant -> [tokens, last_refill_monotonic]
        self._rejected = {}   # tenant -> exact reject count
        self._admitted = {}   # tenant -> exact admit count
        self.obs_instance = next_instance("quotas")
        self._m_tenant = {}   # tenant -> (requests child, rejected child)

    # ------------------------------------------------------------------
    def _limits(self, tenant):
        return self.overrides.get(tenant, (self.rate, self.burst))

    def _metric_children_locked(self, tenant):
        mc = self._m_tenant.get(tenant)
        if mc is None:
            label = tenant if _TENANT_NAME_RE.match(tenant) \
                and len(self._m_tenant) < self._label_cap else "__other__"
            mc = self._m_tenant[tenant] = (
                _M_TENANT_REQUESTS.labels(instance=self.obs_instance,
                                          tenant=label),
                _M_TENANT_REJECTED.labels(instance=self.obs_instance,
                                          tenant=label))
        return mc

    def try_acquire(self, tenant, now=None):
        """Spend one token from ``tenant``'s bucket. Returns
        ``(admitted, retry_after_s)`` — ``retry_after_s`` is the time
        until one token refills when rejected, else 0.0."""
        tenant = str(tenant)
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            m_req, m_rej = self._metric_children_locked(tenant)
            rate, burst = self._limits(tenant)
            if rate <= 0:
                self._admitted[tenant] = self._admitted.get(tenant, 0) + 1
                admitted, retry = True, 0.0
            else:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = self._buckets[tenant] = [float(burst), now]
                tokens, last = bucket
                tokens = min(float(burst), tokens + (now - last) * rate)
                bucket[1] = now
                if tokens >= 1.0:
                    bucket[0] = tokens - 1.0
                    self._admitted[tenant] = \
                        self._admitted.get(tenant, 0) + 1
                    admitted, retry = True, 0.0
                else:
                    bucket[0] = tokens
                    self._rejected[tenant] = \
                        self._rejected.get(tenant, 0) + 1
                    admitted, retry = False, (1.0 - tokens) / rate
        m_req.inc()
        if not admitted:
            m_rej.inc()
            _flight_record("quota_reject", component=self.obs_instance,
                           tenant=tenant, retry_after_s=round(retry, 6))
        return admitted, retry

    def check(self, tenant):
        """:meth:`try_acquire`, raising typed :class:`QuotaExceeded` on
        rejection (the enforcement form servers and routers call)."""
        admitted, retry = self.try_acquire(tenant)
        if not admitted:
            raise QuotaExceeded(
                f"tenant {tenant!r} is over its request quota; retry "
                f"after {retry:.3f}s", tenant=tenant, retry_after_s=retry)

    def stats(self):
        with self._lock:
            tenants = sorted(set(self._admitted) | set(self._rejected))
            out = {
                "rate": self.rate,
                "burst": self.burst,
                "overrides": {t: {"rate": r, "burst": b}
                              for t, (r, b) in self.overrides.items()},
                "tenants": {t: {"admitted": self._admitted.get(t, 0),
                                "rejected": self._rejected.get(t, 0)}
                            for t in tenants},
            }
        return json_safe(out)


class _Request:
    __slots__ = ("feed", "n", "sig", "done", "result", "error")

    def __init__(self, feed, n):
        self.feed = feed
        self.n = n
        # coalesce-compatibility signature: requests only batch with
        # requests of the same feed names, dtypes and trailing shapes —
        # one malformed request (float64 from numpy's default, a wrong
        # feature dim) must fail ALONE, not upcast/except the whole batch
        self.sig = tuple(sorted(
            (k, np.asarray(v).dtype.str, np.asarray(v).shape[1:])
            for k, v in feed.items()))
        self.done = threading.Event()
        self.result = None
        self.error = None


class DynamicBatcher:
    """``run_batch`` is the batch executor — ``InferenceEngine.infer``'s
    signature: feed dict of [n, ...] arrays in, list of fetch arrays
    (leading dim n) out. ``max_batch`` is the coalesce target (the
    engine's largest bucket); ``max_delay_ms``/``capacity`` default from
    the ``serving_max_delay_ms``/``serving_queue_capacity`` flags."""

    def __init__(self, run_batch, max_batch, max_delay_ms=None,
                 capacity=None):
        self._run_batch = run_batch
        self.max_batch = int(max_batch)
        if max_delay_ms is None:
            max_delay_ms = get_flag("serving_max_delay_ms")
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.capacity = int(get_flag("serving_queue_capacity")
                            if capacity is None else capacity)
        self._pending = deque()
        self._cv = threading.Condition()
        self._closed = False
        # request/reject/batch counters live in the obs.metrics registry
        # under this batcher's instance label (stats() derives from them);
        # the per-batch-size histogram stays local (under _cv)
        self.obs_instance = next_instance("batcher")
        self._m_requests = _M_REQUESTS.labels(instance=self.obs_instance)
        self._m_rejected = _M_REJECTED.labels(instance=self.obs_instance)
        self._m_batches = _M_BATCHES.labels(instance=self.obs_instance)
        self._m_depth = _M_QUEUE_DEPTH.labels(instance=self.obs_instance)
        self._m_depth.set(0)
        self._batch_hist = {}
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------
    def submit(self, feed):
        """Block until this request's rows come back from a coalesced
        batch; raises :class:`ServerOverloaded` immediately when the queue
        is full (never queues past ``capacity``)."""
        if not feed:
            raise ValueError("cannot submit an empty feed")
        ns = {np.asarray(v).shape[0] if np.ndim(v) else 1
              for v in feed.values()}
        if len(ns) != 1:
            # reject the malformed request HERE: coalesced with others it
            # would fail the engine's row-count check for the whole batch
            raise ValueError(
                f"inconsistent batch sizes across feeds: "
                f"{ {k: np.asarray(v).shape for k, v in feed.items()} }")
        n = int(ns.pop())
        if n == 0:
            # alone it would raise the engine's empty-batch error anyway;
            # coalesced it would silently return empty arrays — reject
            # deterministically instead of traffic-dependently
            raise ValueError("cannot submit an empty (0-row) batch")
        req = _Request(feed, n)
        with self._cv:
            if self._closed:
                raise RuntimeError("DynamicBatcher is closed")
            self._m_requests.inc()
            if len(self._pending) >= self.capacity:
                self._m_rejected.inc()
                _flight_record("overload_reject",
                               component=self.obs_instance,
                               queue_depth=len(self._pending),
                               capacity=self.capacity)
                raise ServerOverloaded(
                    f"serving queue full ({self.capacity} requests "
                    "waiting); back off and retry")
            self._pending.append(req)
            self._m_depth.set(len(self._pending))
            self._cv.notify_all()
        req.done.wait()
        if req.error is not None:
            raise req.error
        return req.result

    # ------------------------------------------------------------------
    def _run(self):
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if not self._pending and self._closed:
                    return
                # coalesce: hold the batch open for stragglers until the
                # deadline, but dispatch a full batch (or a closing
                # batcher's flush) immediately
                deadline = time.monotonic() + self.max_delay_s
                while (sum(r.n for r in self._pending) < self.max_batch
                       and not self._closed):
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(left)
                batch = [self._pending.popleft()]
                total = batch[0].n
                while self._pending and \
                        total + self._pending[0].n <= self.max_batch and \
                        self._pending[0].sig == batch[0].sig:
                    # an incompatible head ends the batch and forms its
                    # own on the next loop turn (FIFO preserved)
                    r = self._pending.popleft()
                    batch.append(r)
                    total += r.n
                self._m_depth.set(len(self._pending))
                self._m_batches.inc()
                self._batch_hist[total] = \
                    self._batch_hist.get(total, 0) + 1
            self._dispatch(batch, total)

    def _dispatch(self, batch, total):
        """Run one coalesced batch OUTSIDE the queue lock and route the
        fetch rows back to their callers (an error fans out to every
        caller in the batch)."""
        try:
            if len(batch) == 1:
                feed = batch[0].feed
            else:
                feed = {k: np.concatenate(
                            [np.asarray(r.feed[k]) for r in batch], axis=0)
                        for k in batch[0].feed}
            fetches = self._run_batch(feed)
            for f in fetches:
                if not (isinstance(f, np.ndarray) and f.ndim >= 1
                        and f.shape[0] == total):
                    # a non-per-row fetch cannot be split back per caller
                    # — it was computed over the COALESCED rows of every
                    # request in this batch (the engine enforces the same
                    # contract; this guards foreign run_batch callables)
                    raise ValueError(
                        f"run_batch returned a non-per-row fetch (shape "
                        f"{getattr(f, 'shape', None)}, batch rows {total})"
                        "; dynamic batching requires fetches with a "
                        "leading batch dimension")
            lo = 0
            for r in batch:
                r.result = [f[lo:lo + r.n] for f in fetches]
                lo += r.n
        except Exception as e:
            for r in batch:
                r.error = e
        finally:
            for r in batch:
                r.done.set()

    # ------------------------------------------------------------------
    def stats(self):
        with self._cv:
            depth = len(self._pending)
            hist = dict(sorted(self._batch_hist.items()))
        # counters derived from this instance's obs.metrics children
        return json_safe({
            "queue_depth": depth,
            "capacity": self.capacity,
            "max_batch": self.max_batch,
            "max_delay_ms": self.max_delay_s * 1e3,
            "requests": int(self._m_requests.value),
            "rejected": int(self._m_rejected.value),
            "batches": int(self._m_batches.value),
            "batch_size_hist": hist,
        })

    def close(self, timeout=30.0):
        """Stop admitting requests, FLUSH everything already queued (their
        callers get real results), and join the worker. If the worker is
        WEDGED (a run_batch that never returns) and the join times out,
        requests still waiting in the queue are rejected with a typed
        RuntimeError instead of hanging their callers forever — a queued
        request at close() is always either answered or rejected typed.
        Returns True when the worker exited within ``timeout``."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._worker.join(timeout)
        closed_clean = not self._worker.is_alive()
        if not closed_clean:
            # pop the undispatched queue under the lock so the wedged
            # worker can never race these requests back out of it
            with self._cv:
                stranded, self._pending = list(self._pending), deque()
                self._m_depth.set(0)
            err = RuntimeError(
                "DynamicBatcher is closed: the dispatch worker did not "
                f"exit within {timeout}s (wedged run_batch); this queued "
                "request was rejected without being served")
            for r in stranded:
                r.error = err
                r.done.set()
        return closed_clean


__all__ = ["DynamicBatcher", "QuotaExceeded", "ServerOverloaded",
           "TenantQuotas"]
