"""DynamicBatcher: coalesce concurrent requests into bucket-sized batches.

The throughput lever of a model server: N concurrent single-row requests
cost N dispatches unbatched, but ONE dispatch coalesced — and on an
accelerator a dispatch has a large fixed cost (host round trip, executable
launch) that row count barely moves. The batcher holds a bounded queue;
a worker thread groups whole requests into a batch up to ``max_batch``
rows, waiting at most ``max_delay_ms`` for stragglers (a full batch
dispatches immediately, so the delay bound is only paid under quiet
traffic), runs the batch through the engine, and splits the fetches back
per caller.

Backpressure is the bounded queue: when ``capacity`` requests are already
waiting, :meth:`submit` rejects FAST with the typed
:class:`ServerOverloaded` — the client backs off and retries — instead of
admitting work the server cannot finish and stretching every caller's
latency without bound (the reference's unbounded-queue collapse mode).
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from ..core.flags import get_flag
from ..obs.metrics import REGISTRY as _METRICS, json_safe, next_instance
from ..obs.recorder import record as _flight_record

_M_REQUESTS = _METRICS.counter(
    "paddle_tpu_batcher_requests",
    "requests submitted to a DynamicBatcher, per instance",
    labels=("instance",))
_M_REJECTED = _METRICS.counter(
    "paddle_tpu_batcher_rejected",
    "requests rejected with ServerOverloaded (queue full), per instance",
    labels=("instance",))
_M_BATCHES = _METRICS.counter(
    "paddle_tpu_batcher_batches",
    "coalesced batches dispatched by a DynamicBatcher, per instance",
    labels=("instance",))


class ServerOverloaded(RuntimeError):
    """The serving queue is full: reject-fast backpressure. Clients should
    back off (bounded exponential delay) and retry or shed the request —
    InferClient re-raises this type from the remote error string."""


class _Request:
    __slots__ = ("feed", "n", "sig", "done", "result", "error")

    def __init__(self, feed, n):
        self.feed = feed
        self.n = n
        # coalesce-compatibility signature: requests only batch with
        # requests of the same feed names, dtypes and trailing shapes —
        # one malformed request (float64 from numpy's default, a wrong
        # feature dim) must fail ALONE, not upcast/except the whole batch
        self.sig = tuple(sorted(
            (k, np.asarray(v).dtype.str, np.asarray(v).shape[1:])
            for k, v in feed.items()))
        self.done = threading.Event()
        self.result = None
        self.error = None


class DynamicBatcher:
    """``run_batch`` is the batch executor — ``InferenceEngine.infer``'s
    signature: feed dict of [n, ...] arrays in, list of fetch arrays
    (leading dim n) out. ``max_batch`` is the coalesce target (the
    engine's largest bucket); ``max_delay_ms``/``capacity`` default from
    the ``serving_max_delay_ms``/``serving_queue_capacity`` flags."""

    def __init__(self, run_batch, max_batch, max_delay_ms=None,
                 capacity=None):
        self._run_batch = run_batch
        self.max_batch = int(max_batch)
        if max_delay_ms is None:
            max_delay_ms = get_flag("serving_max_delay_ms")
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.capacity = int(get_flag("serving_queue_capacity")
                            if capacity is None else capacity)
        self._pending = deque()
        self._cv = threading.Condition()
        self._closed = False
        # request/reject/batch counters live in the obs.metrics registry
        # under this batcher's instance label (stats() derives from them);
        # the per-batch-size histogram stays local (under _cv)
        self.obs_instance = next_instance("batcher")
        self._m_requests = _M_REQUESTS.labels(instance=self.obs_instance)
        self._m_rejected = _M_REJECTED.labels(instance=self.obs_instance)
        self._m_batches = _M_BATCHES.labels(instance=self.obs_instance)
        self._batch_hist = {}
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------
    def submit(self, feed):
        """Block until this request's rows come back from a coalesced
        batch; raises :class:`ServerOverloaded` immediately when the queue
        is full (never queues past ``capacity``)."""
        if not feed:
            raise ValueError("cannot submit an empty feed")
        ns = {np.asarray(v).shape[0] if np.ndim(v) else 1
              for v in feed.values()}
        if len(ns) != 1:
            # reject the malformed request HERE: coalesced with others it
            # would fail the engine's row-count check for the whole batch
            raise ValueError(
                f"inconsistent batch sizes across feeds: "
                f"{ {k: np.asarray(v).shape for k, v in feed.items()} }")
        n = int(ns.pop())
        if n == 0:
            # alone it would raise the engine's empty-batch error anyway;
            # coalesced it would silently return empty arrays — reject
            # deterministically instead of traffic-dependently
            raise ValueError("cannot submit an empty (0-row) batch")
        req = _Request(feed, n)
        with self._cv:
            if self._closed:
                raise RuntimeError("DynamicBatcher is closed")
            self._m_requests.inc()
            if len(self._pending) >= self.capacity:
                self._m_rejected.inc()
                _flight_record("overload_reject",
                               component=self.obs_instance,
                               queue_depth=len(self._pending),
                               capacity=self.capacity)
                raise ServerOverloaded(
                    f"serving queue full ({self.capacity} requests "
                    "waiting); back off and retry")
            self._pending.append(req)
            self._cv.notify_all()
        req.done.wait()
        if req.error is not None:
            raise req.error
        return req.result

    # ------------------------------------------------------------------
    def _run(self):
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if not self._pending and self._closed:
                    return
                # coalesce: hold the batch open for stragglers until the
                # deadline, but dispatch a full batch (or a closing
                # batcher's flush) immediately
                deadline = time.monotonic() + self.max_delay_s
                while (sum(r.n for r in self._pending) < self.max_batch
                       and not self._closed):
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(left)
                batch = [self._pending.popleft()]
                total = batch[0].n
                while self._pending and \
                        total + self._pending[0].n <= self.max_batch and \
                        self._pending[0].sig == batch[0].sig:
                    # an incompatible head ends the batch and forms its
                    # own on the next loop turn (FIFO preserved)
                    r = self._pending.popleft()
                    batch.append(r)
                    total += r.n
                self._m_batches.inc()
                self._batch_hist[total] = \
                    self._batch_hist.get(total, 0) + 1
            self._dispatch(batch, total)

    def _dispatch(self, batch, total):
        """Run one coalesced batch OUTSIDE the queue lock and route the
        fetch rows back to their callers (an error fans out to every
        caller in the batch)."""
        try:
            if len(batch) == 1:
                feed = batch[0].feed
            else:
                feed = {k: np.concatenate(
                            [np.asarray(r.feed[k]) for r in batch], axis=0)
                        for k in batch[0].feed}
            fetches = self._run_batch(feed)
            for f in fetches:
                if not (isinstance(f, np.ndarray) and f.ndim >= 1
                        and f.shape[0] == total):
                    # a non-per-row fetch cannot be split back per caller
                    # — it was computed over the COALESCED rows of every
                    # request in this batch (the engine enforces the same
                    # contract; this guards foreign run_batch callables)
                    raise ValueError(
                        f"run_batch returned a non-per-row fetch (shape "
                        f"{getattr(f, 'shape', None)}, batch rows {total})"
                        "; dynamic batching requires fetches with a "
                        "leading batch dimension")
            lo = 0
            for r in batch:
                r.result = [f[lo:lo + r.n] for f in fetches]
                lo += r.n
        except Exception as e:
            for r in batch:
                r.error = e
        finally:
            for r in batch:
                r.done.set()

    # ------------------------------------------------------------------
    def stats(self):
        with self._cv:
            depth = len(self._pending)
            hist = dict(sorted(self._batch_hist.items()))
        # counters derived from this instance's obs.metrics children
        return json_safe({
            "queue_depth": depth,
            "capacity": self.capacity,
            "max_batch": self.max_batch,
            "max_delay_ms": self.max_delay_s * 1e3,
            "requests": int(self._m_requests.value),
            "rejected": int(self._m_rejected.value),
            "batches": int(self._m_batches.value),
            "batch_size_hist": hist,
        })

    def close(self, timeout=30.0):
        """Stop admitting requests, FLUSH everything already queued (their
        callers get real results), and join the worker. If the worker is
        WEDGED (a run_batch that never returns) and the join times out,
        requests still waiting in the queue are rejected with a typed
        RuntimeError instead of hanging their callers forever — a queued
        request at close() is always either answered or rejected typed.
        Returns True when the worker exited within ``timeout``."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._worker.join(timeout)
        closed_clean = not self._worker.is_alive()
        if not closed_clean:
            # pop the undispatched queue under the lock so the wedged
            # worker can never race these requests back out of it
            with self._cv:
                stranded, self._pending = list(self._pending), deque()
            err = RuntimeError(
                "DynamicBatcher is closed: the dispatch worker did not "
                f"exit within {timeout}s (wedged run_batch); this queued "
                "request was rejected without being served")
            for r in stranded:
                r.error = err
                r.done.set()
        return closed_clean


__all__ = ["DynamicBatcher", "ServerOverloaded"]
