"""Model serving: dynamic-batching inference over saved programs.

The reference tree serves trained models through blocking one-shot paths
(``v2.inference`` feeds the whole input as a single batch;
``fluid.io.load_inference_model`` hands back a raw program). This package
assembles the pieces PRs 1-3 built — the framed zero-copy RPC transport,
retry policies, fault injection, profiler spans — into the missing
subsystem: a model server that keeps a TPU fed under concurrent traffic
without ever recompiling on the hot path.

* :class:`InferenceEngine` (engine.py) — wraps a ``load_inference_model``
  bundle with shape-bucketed execution: batches pad up to a small set of
  power-of-two buckets so each bucket's XLA executable compiles once at
  warmup.
* :class:`DynamicBatcher` (batcher.py) — coalesces concurrent single
  requests into one bucket-sized batch under a ``max_delay_ms`` deadline,
  with bounded-queue backpressure (:class:`ServerOverloaded`).
* :class:`ModelServer` / :class:`InferClient` (server.py / client.py) — a
  multi-threaded server over ``distributed/rpc.py``'s framed codec with
  health/stats RPCs, zero-downtime hot reload, graceful drain, and
  retry-surviving clients.

On top of the single server sits the fleet control plane:

* :class:`ModelRegistry` (registry.py) — versioned, content-hashed store
  of ``save_inference_model`` bundles (``publish``/``resolve``; a version
  is visible only once its manifest lands atomically).
* :class:`FleetSupervisor` (fleet.py) — N supervised replica processes on
  fixed addresses (the pserver supervision loop transplanted to the
  inference plane) with ``rolling_reload``: canary-gated, zero-downtime
  version rollouts that roll back a failed canary.
* :class:`FleetClient` (router.py) — client-side balancer: power-of-two-
  choices picks, connection-failure failover, overload spillover, and
  health probes that eject/probation-readmit replicas.
* :class:`ExecCache` (execcache.py) — persistent compiled-executable
  cache: warmup executables are AOT-serialized next to the bundle
  (``registry.warm()`` / ``publish(warm_cache=True)`` →
  ``<version>/warm/``) keyed by a full identity fingerprint, so
  scale-out replicas, crash restarts and rollout reloads LOAD in
  milliseconds instead of recompiling.

The multi-tenant plane turns that stack into a fleet product:

* Multi-model hosting (server.py) — one :class:`ModelServer` hosts N
  engines keyed by model name (feed-forward and generative side by
  side) behind the same RPC endpoint via a ``model=`` field, with a
  refcount-aware LRU evictor bounding the per-replica budget
  (``serving_max_models``).
* :class:`TenantQuotas` / :class:`QuotaExceeded` (batcher.py) —
  per-tenant token-bucket admission, enforced at the router and/or
  server, carried over the wire as a structured code exactly like
  :class:`ServerOverloaded`; quota rejects never trigger failover.
* :class:`FleetAutoscaler` (autoscale.py) — closes the SLO burn-rate →
  replica-count loop: judges fleet metrics with SloMonitor windows,
  scales out one canary-gated warm replica per breach, scales in on
  sustained idle.
"""

from .execcache import ExecCache
from .engine import InferenceEngine
from .batcher import (DynamicBatcher, QuotaExceeded, ServerOverloaded,
                      TenantQuotas)
from .server import ModelServer
from .client import InferClient
from .registry import ModelRegistry
from .fleet import CanaryFailed, FleetSupervisor
from .router import FleetClient
from .autoscale import FleetAutoscaler
from .generate import (PagedKVCache, CacheExhausted, GenerationEngine,
                       NoFreeSlots, ContinuousBatcher, GenClient)

__all__ = ["InferenceEngine", "DynamicBatcher", "ServerOverloaded",
           "QuotaExceeded", "TenantQuotas", "ModelServer", "InferClient",
           "ModelRegistry", "ExecCache", "FleetSupervisor", "CanaryFailed",
           "FleetClient", "FleetAutoscaler",
           "PagedKVCache", "CacheExhausted", "GenerationEngine",
           "NoFreeSlots", "ContinuousBatcher", "GenClient"]
