"""GenerationEngine: stateful autoregressive decode over a saved program.

The :class:`~..engine.InferenceEngine` sibling for generative bundles. A
generative saved program is a decoder-only LM over a token window —
feeds ``tokens`` (``[batch, seq, 1]`` int64, plus optional ``positions``),
one logits fetch ``[batch, seq, vocab]`` — whose attention sites are
``causal_self_attention`` ops (fluid.layers.causal_self_attention). The
engine SPLITS that one program into the two serving phases:

* **prefill** — the program cloned with every attention site rewritten to
  ``prefill_attention``: causal attention over the (bucket-padded) prompt
  window that also scatters each position's K/V into the paged arena
  (kvcache.py). One executable per prompt-length bucket, compiled at
  :meth:`warmup`.
* **chunked prefill** — a third clone rewritten to
  ``chunked_prefill_attention``: a prompt CHUNK attending over arena
  context that is already there (a cached shared prefix, previous
  chunks). Built and warmed only when the prefix cache
  (``serving_prefix_cache_blocks``) or chunking
  (``serving_prefill_chunk``) is enabled, so disabled engines compile
  exactly what they did before. A request whose prompt prefix is cached
  attaches to the cached blocks and prefills only its uncached tail; a
  long cold prompt (with chunking on) admits immediately and prefills
  one bounded chunk per :meth:`step` boundary, so in-flight decode
  streams keep producing tokens while it loads.
* **decode** — the clone rewritten to ``paged_attention``: a fixed-shape
  ``[max_seqs, 1]`` step over the arena. Ragged in-flight sequences share
  this ONE executable through their block tables and context lengths;
  idle slots ride along masked (sentinel slot, context length 0). The hot
  path never retraces — ``stats()`` carries per-phase compile/hit
  counters and the same ``hot_recompiles`` alarm the feed-forward engine
  has.

Sampling is host-side and PER-SEQUENCE — greedy argmax, top-k (own
``numpy.RandomState`` seeded per request), or beam search riding the
dense ``beam_search`` op (ops/control_flow_ops.py) with copy-on-write
block-table forks for hypothesis reordering. Because the phase ops are
row-independent and sampling state is per-sequence, a sequence's token
stream is BITWISE identical whether it decodes alone or joins a running
continuous batch — the parity contract the scheduler and tests pin.
"""

from __future__ import annotations

import threading

import numpy as np

from ...core.flags import get_flag
from ...core.profiler import record_event
from ...core.scope import Scope
from ...obs import perf as _perf
from ...obs.metrics import REGISTRY as _METRICS, json_safe, next_instance
from ...obs.recorder import record as _flight_record
from .. import execcache as _execcache
from ..engine import commit_scope_arrays, parse_buckets
from . import kvstore as _kvstore
from .kvcache import CacheExhausted, PagedKVCache

_M_COMPILES = _METRICS.counter(
    "paddle_tpu_genengine_compiles",
    "GenerationEngine executable compiles, per instance/phase/bucket",
    labels=("instance", "phase", "bucket"))
_M_HITS = _METRICS.counter(
    "paddle_tpu_genengine_hits",
    "GenerationEngine trace-cache hits, per instance/phase/bucket",
    labels=("instance", "phase", "bucket"))
_M_HOT = _METRICS.counter(
    "paddle_tpu_genengine_hot_recompiles",
    "generation compiles observed AFTER warmup (the no-recompile alarm)",
    labels=("instance",))
# per-request serving quantities: TTFT (submit -> first ACTUAL token —
# stamped by the scheduler, which owns the submit clock; a request
# aborted before its first token DISCARDS its probe) and TPOT (mean
# time per output token after the first, recorded once at stream end
# for requests that emitted >= 2 tokens)
_M_TTFT = _METRICS.histogram(
    "paddle_tpu_genengine_ttft_seconds",
    "time to first token per generation request (submit -> first actual "
    "token), per engine instance", labels=("instance",),
    span_name="serving/ttft", span_kind="stage")
_M_TPOT = _METRICS.histogram(
    "paddle_tpu_genengine_tpot_seconds",
    "mean time per output token after the first, recorded once per "
    "finished stream that emitted >= 2 tokens, per engine instance",
    labels=("instance",), span_name="serving/tpot", span_kind="stage")

ATTENTION_OP = "causal_self_attention"
_SLOTS = "__kv_slots__"
_TABLES = "__kv_block_tables__"
_CTXLENS = "__kv_context_lens__"
_CHUNKSTART = "__kv_chunk_start__"


class NoFreeSlots(RuntimeError):
    """All ``max_seqs`` decode slots are occupied: the admission-control
    twin of :class:`CacheExhausted` for the slot dimension. The scheduler
    keeps the request queued until a sequence finishes."""


def _kv_name(kind, layer):
    return f"__kv_{kind}_{layer}__"


def normalize_sampling(sampling):
    """Validate/default a sampling spec (a plain dict so it crosses the
    RPC wire untouched): ``mode`` greedy | topk | beam, with ``top_k``/
    ``temperature``/``seed`` for topk and ``beam_size`` for beam;
    ``eos_id`` (None = run to max_new_tokens) applies to all modes."""
    s = dict(sampling or {})
    mode = s.pop("mode", "greedy")
    out = {"mode": mode,
           "eos_id": s.pop("eos_id", None),
           "top_k": int(s.pop("top_k", 8)),
           "temperature": float(s.pop("temperature", 1.0)),
           "seed": int(s.pop("seed", 0)),
           "beam_size": int(s.pop("beam_size", 4))}
    if s:
        raise ValueError(f"unknown sampling fields {sorted(s)}")
    if mode not in ("greedy", "topk", "beam"):
        raise ValueError(f"sampling mode must be greedy|topk|beam, "
                         f"got {mode!r}")
    if mode == "topk" and out["top_k"] <= 0:
        raise ValueError("top_k must be positive")
    if mode == "topk" and out["temperature"] <= 0:
        raise ValueError("temperature must be positive")
    if mode == "beam" and out["beam_size"] < 2:
        raise ValueError("beam_size must be >= 2")
    if out["eos_id"] is not None:
        out["eos_id"] = int(out["eos_id"])
    return out


def _log_softmax(x):
    x = x - x.max()
    return x - np.log(np.exp(x).sum())


class _Sequence:
    """One decode slot's state (a beam hypothesis is one of these too)."""

    __slots__ = ("seq_id", "slot", "next_token", "emitted", "max_new",
                 "params", "rng", "group", "finished", "user_data",
                 "prompt", "pending", "prefilling")

    def __init__(self, seq_id, slot, params, max_new):
        self.seq_id = seq_id
        self.slot = slot
        self.params = params
        self.max_new = max_new
        self.next_token = 0
        self.emitted = 0
        self.rng = np.random.RandomState(params["seed"] & 0x7FFFFFFF)
        self.group = None          # set for beam hypotheses
        self.finished = False
        self.user_data = None      # scheduler's stream handle
        self.prompt = None         # full prompt (prefix registration)
        self.pending = None        # prompt tail still to chunk-prefill
        self.prefilling = False    # occupies a slot but must not decode


class _BeamGroup:
    """A beam request: ``beam_size`` sequences advancing in lockstep."""

    __slots__ = ("seqs", "pre_ids", "pre_scores", "hist_ids",
                 "hist_parents", "steps", "max_new", "end_id", "finished",
                 "user_data", "prompt", "pending", "prefilling")

    def __init__(self, seqs, max_new, end_id):
        self.seqs = seqs
        self.max_new = max_new
        # -1 never matches a real token: "no EOS" runs to max_new
        self.end_id = -1 if end_id is None else int(end_id)
        self.pre_ids = None
        self.pre_scores = None
        self.hist_ids = []
        self.hist_parents = []
        self.steps = 0
        self.finished = False
        self.user_data = None
        self.prompt = None
        self.pending = None        # lead hypothesis's unprefilled tail
        self.prefilling = False


class GenerationEngine:
    """``GenerationEngine(model_dir)`` loads a generative bundle into a
    private scope and splits it; ``max_seqs``/``block_size``/``num_blocks``
    default from the ``serving_max_seqs`` / ``serving_kv_block_size`` /
    ``serving_kv_num_blocks`` flags; ``max_len`` bounds prompt+generation
    per sequence (it sizes the block-table width); ``prefill_buckets``
    are the prompt-length pads (default: powers of two up to ``max_len``).

    Thread safety: like InferenceEngine, dispatches serialize on a lock;
    the ContinuousBatcher drives the engine from one worker thread."""

    def __init__(self, model_dir=None, program=None, feed_names=None,
                 fetch_vars=None, executor=None, scope=None, max_seqs=None,
                 block_size=None, num_blocks=None, max_len=128,
                 prefill_buckets=None, prefix_cache_blocks=None,
                 prefill_chunk=None, exec_cache=None, kv_store=None,
                 donate_arena=True):
        import paddle_tpu.fluid as fluid

        self._scope = scope or Scope()
        self._exe = executor or fluid.Executor()
        if model_dir is not None:
            program, feed_names, fetch_vars = fluid.io.load_inference_model(
                model_dir, self._exe, scope=self._scope)
        if program is None or feed_names is None or fetch_vars is None:
            raise ValueError(
                "GenerationEngine needs model_dir= or all of program=/"
                "feed_names=/fetch_vars=")
        # persistent compiled-executable cache: each (phase, bucket)
        # executable loads from a fingerprint-matched artifact at warmup
        # instead of compiling (serving/execcache.py). The engine config
        # (max_seqs, max_len, arena geometry, chunking) needs no explicit
        # key — it is fully determined by the warmup feed shapes the
        # fingerprint already covers.
        self._model_dir = str(model_dir) if model_dir is not None else None
        self._tune_digest = None       # set by warmup's attach_for_bundle
        self._bundle_hash = _execcache.bundle_content_hash(model_dir) \
            if model_dir else None
        self._exec_cache = _execcache.resolve_cache(model_dir, exec_cache) \
            if self._bundle_hash is not None else None
        self._warm_execs = {}          # (phase, bucket) -> WarmExecutable
        self._warm_loaded = set()      # keys whose executable was LOADED
        # numpy state's first dispatch would land a second jit cache
        # entry per executable once the run writes jax arrays back —
        # commit up front (see engine.commit_scope_arrays)
        commit_scope_arrays(self._scope)
        self._feed_names = list(feed_names)
        unknown = [n for n in self._feed_names
                   if n not in ("tokens", "positions")]
        if "tokens" not in self._feed_names or unknown:
            raise ValueError(
                "a generative bundle feeds 'tokens' (and optionally "
                f"'positions'); this one feeds {self._feed_names}")
        fetch_names = [v if isinstance(v, str) else v.name
                       for v in fetch_vars]
        if len(fetch_names) != 1:
            raise ValueError(
                f"a generative bundle fetches exactly its logits, "
                f"got {fetch_names}")
        self._logits_name = fetch_names[0]

        self.max_seqs = int(max_seqs if max_seqs is not None
                            else get_flag("serving_max_seqs"))
        self.max_len = int(max_len)
        if self.max_seqs <= 0 or self.max_len <= 0:
            raise ValueError("max_seqs and max_len must be positive")

        layers, heads, head_dim = self._attention_config(program)
        self.num_layers = layers
        self.cache = PagedKVCache(layers, heads, head_dim,
                                  num_blocks=num_blocks,
                                  block_size=block_size,
                                  prefix_cache_blocks=prefix_cache_blocks)
        # persistent KV-prefix spill tier (serving/generate/kvstore.py):
        # a published <version>/kv/ dir (read-only, manifest-pinned) or
        # the serving_kv_spill_dir flag's local tier. Keyed by the same
        # bundle content hash the exec cache uses plus the arena
        # geometry — no bundle bytes, no spill tier.
        self._kv_store = None
        if self._bundle_hash is not None and kv_store is not False:
            kv_fp = _kvstore.kv_fingerprint(
                self._bundle_hash, layers, heads, head_dim,
                self.cache.block_size, self.cache.k[0].dtype)
            self._kv_store = _kvstore.resolve_store(model_dir, kv_store,
                                                    kv_fp)
        self.cache.attach_spill(self._kv_store)
        # decode-arena donation: the phase executables alias the arena
        # feed buffers into the arena fetches (donate_argnums on a
        # dedicated jit argument), so the functional arena update stays
        # on device instead of allocating a fresh arena every dispatch.
        # Token streams are bitwise identical either way (donation is
        # aliasing, never arithmetic); donate_arena=False pins the
        # undonated twin for parity tests.
        self.donate_arena = bool(donate_arena)
        self._donate_feeds = tuple(sorted(self._arena_fetch_names())) \
            if self.donate_arena else ()
        self.prefill_chunk = int(prefill_chunk if prefill_chunk is not None
                                 else get_flag("serving_prefill_chunk"))
        self._table_width = self.cache.blocks_for(self.max_len)
        self._prefill_program = self._rewrite(program, "prefill_attention")
        self._decode_program = self._rewrite(program, "paged_attention")
        # the chunked-prefill executable family exists only when a
        # partial prefill can happen (cached-prefix tails, chunked
        # admission) — disabled engines compile exactly what they always
        # did, and warmup cost doesn't grow for them
        self._partial_enabled = (self.cache.prefix_cache_blocks > 0
                                 or self.prefill_chunk > 0)
        self._chunk_program = (
            self._rewrite(program, "chunked_prefill_attention")
            if self._partial_enabled else None)
        if prefill_buckets is None:
            b, buckets = 8, []
            while b < self.max_len:
                buckets.append(b)
                b *= 2
            buckets.append(b)
            prefill_buckets = buckets
        self.prefill_buckets = parse_buckets(prefill_buckets)

        self._slots = [None] * self.max_seqs
        self._groups = []
        self._prefill_queue = []   # FIFO of handles mid-chunked-prefill
        self._next_seq_id = 0
        self._lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._seen = set()
        # per-(phase, bucket) compile/hit counters live in the
        # obs.metrics registry under this engine's instance label;
        # stats() derives the historical phases dict from them
        self.obs_instance = next_instance("genengine")
        self._phase = {"prefill": {}, "chunk": {}, "decode": {}}
        self._m_hot = _M_HOT.labels(instance=self.obs_instance)
        # per-request TTFT/TPOT windows: the scheduler (which owns the
        # submit clock) records into these; stats() snapshots them
        self.ttft = _M_TTFT.labels(instance=self.obs_instance)
        self.tpot = _M_TPOT.labels(instance=self.obs_instance)
        self._warmed = False
        from ...ops.pallas import resolve_tier
        self._kernel_tier = resolve_tier()

    # ------------------------------------------------------------------
    # program split
    # ------------------------------------------------------------------
    def _attention_config(self, program):
        block = program.global_block()
        sites = [op for op in block.ops if op.type == ATTENTION_OP]
        if not sites:
            raise ValueError(
                "program has no causal_self_attention sites: not a "
                "generative bundle (use InferenceEngine for feed-forward "
                "models)")
        configs = set()
        for op in sites:
            heads = int(op.attr("num_heads"))
            kvar = block.var(op.input("K")[0])
            hidden = int(kvar.shape[-1])
            configs.add((heads, hidden // heads))
        if len(configs) != 1:
            raise ValueError(
                f"attention sites disagree on (heads, head_dim): "
                f"{sorted(configs)}")
        heads, head_dim = configs.pop()
        return len(sites), heads, head_dim

    def _rewrite(self, program, phase_op):
        """Clone the program and rewrite every attention site into the
        phase op, wiring the per-layer arena vars in and out under the
        SAME names (the optimizer-op in-place convention) so the arena
        update stays on device. Arena/slot vars are DECLARED in the clone
        (dtype-annotated, ``is_data`` — they are fed every dispatch), so
        the rewritten program is self-describing and verifiable."""
        from ...fluid.framework import Operator

        p = program.clone(for_test=True)
        block = p.global_block()

        def _declare(name, dtype):
            if not block.has_var(name):
                block.create_var(name=name, dtype=dtype, is_data=True)

        _declare(_SLOTS, "int32")
        if phase_op == "paged_attention":
            _declare(_TABLES, "int32")
            _declare(_CTXLENS, "int32")
        elif phase_op == "chunked_prefill_attention":
            _declare(_TABLES, "int32")
            _declare(_CHUNKSTART, "int32")
        layer = 0
        for i, op in enumerate(block.ops):
            if op.type != ATTENTION_OP:
                continue
            inputs = dict(op.inputs)
            outputs = dict(op.outputs)
            for kind in ("k", "v"):
                _declare(_kv_name(kind, layer), "float32")
            inputs["KCache"] = [_kv_name("k", layer)]
            inputs["VCache"] = [_kv_name("v", layer)]
            inputs["SlotMapping"] = [_SLOTS]
            outputs["KCacheOut"] = [_kv_name("k", layer)]
            outputs["VCacheOut"] = [_kv_name("v", layer)]
            if phase_op == "paged_attention":
                inputs["BlockTables"] = [_TABLES]
                inputs["ContextLens"] = [_CTXLENS]
            elif phase_op == "chunked_prefill_attention":
                inputs["BlockTables"] = [_TABLES]
                inputs["ChunkStart"] = [_CHUNKSTART]
            block.ops[i] = Operator(block, phase_op, inputs, outputs,
                                    dict(op.attrs))
            layer += 1
        # verify_passes: the per-phase clone-rewrite is a transform pass
        # like any other — a mis-wired arena var fails HERE naming the
        # phase, not as an undefined name inside the compiled step
        from ...fluid.analysis import verify_pass_output
        verify_pass_output(
            p, f"GenerationEngine._rewrite({phase_op})",
            feed_names=list(self._feed_names))
        return p

    # ------------------------------------------------------------------
    # dispatch plumbing
    # ------------------------------------------------------------------
    def _arena_feed(self):
        feed = {}
        for l in range(self.num_layers):
            feed[_kv_name("k", l)] = self.cache.k[l]
            feed[_kv_name("v", l)] = self.cache.v[l]
        return feed

    def _arena_fetch_names(self):
        return [_kv_name(k, l) for l in range(self.num_layers)
                for k in ("k", "v")]

    def _gen_fetch(self):
        return [self._logits_name] + self._arena_fetch_names()

    def _warm_phase(self, program, feed, phase, bucket):
        """Register one (phase, bucket) warm executable from the
        persistent cache — or, writable caches only, AOT-compile and
        persist it. Silent on every failure: the phase just compiles
        through the normal jit path at its warmup dispatch."""
        if self._exec_cache is None or (phase, bucket) in self._warm_execs:
            return
        entry = _execcache.acquire(
            self._exec_cache, self._bundle_hash, f"gen_{phase}_b{bucket}",
            program, feed, self._gen_fetch(), self._exe, self._scope,
            identity={"instance": self.obs_instance, "phase": phase,
                      "bucket": bucket},
            donate_feeds=self._donate_feeds)
        if entry is not None:
            self._warm_execs[(phase, bucket)] = entry
            if entry.source == "cache":
                self._warm_loaded.add((phase, bucket))

    def _phase_children(self, phase, bucket):
        per = self._phase[phase].get(bucket)
        if per is None:
            per = self._phase[phase][bucket] = (
                _M_COMPILES.labels(instance=self.obs_instance,
                                   phase=phase, bucket=str(bucket)),
                _M_HITS.labels(instance=self.obs_instance,
                               phase=phase, bucket=str(bucket)))
        return per

    def _dispatch(self, program, feed, phase, bucket):
        fetch = self._gen_fetch()
        key = (phase, bucket)
        warm = self._warm_execs.get(key)
        # accounting BEFORE dispatch (mark-then-dispatch): concurrent
        # first dispatches of one executable count ONE compile; a
        # cache-LOADED first dispatch counts as a hit (nothing
        # compiles — warm warmup() reports 0)
        with self._stats_lock:
            per = self._phase_children(phase, bucket)
            if key in self._seen:
                per[1].inc()
            else:
                self._seen.add(key)
                if warm is not None and key in self._warm_loaded:
                    per[1].inc()
                else:
                    per[0].inc()
                    if self._warmed:
                        self._m_hot.inc()
        outs = None
        if warm is not None:
            # warm path: the persisted executable dispatched directly
            # (same trace, same glue — bitwise the jit path's outputs);
            # a deserialized-but-unrunnable artifact falls through to
            # the jit path with a reject bump, never an engine error
            try:
                with record_event(f"serving/gen_{phase}_b{bucket}",
                                  kind="stage"):
                    outs = warm.run(self._exe, program, feed, self._scope,
                                    return_numpy=False,
                                    donate_feeds=self._donate_feeds)
            except Exception as e:
                self._warm_execs.pop(key, None)
                loaded = key in self._warm_loaded
                self._warm_loaded.discard(key)
                self._exec_cache.note_reject(f"gen_{phase}_b{bucket}",
                                             "run_failed", error=e)
                if loaded:
                    with self._stats_lock:
                        # the jit fallback below really compiles but the
                        # pre-dispatch accounting booked a hit: record
                        # the real compile + hot alarm (compiles never
                        # undercount; the stray hit on this one-off
                        # corruption event is accepted)
                        per[0].inc()
                        if self._warmed:
                            self._m_hot.inc()
        if outs is None:
            # compile-site label for obs.perf: a build under this
            # dispatch (warmup compiles one executable per phase clone x
            # bucket) is attributed with its phase/bucket identity
            site = "genengine_warmup" if not self._warmed \
                else f"genengine_{phase}"
            detail = dict(instance=self.obs_instance, phase=phase,
                          bucket=bucket)
            if self._exec_cache is not None:
                detail["cache_hit"] = False
            with _perf.compile_site(site, **detail):
                with record_event(f"serving/gen_{phase}_b{bucket}",
                                  kind="stage"):
                    outs = self._exe.run(program, feed=feed,
                                         fetch_list=fetch,
                                         scope=self._scope,
                                         return_numpy=False,
                                         donate_feeds=self._donate_feeds)
        for l in range(self.num_layers):
            self.cache.k[l] = outs[1 + 2 * l]
            self.cache.v[l] = outs[2 + 2 * l]
        return np.asarray(outs[0], np.float32)

    def _prefill_bucket(self, n):
        import bisect
        i = bisect.bisect_left(self.prefill_buckets, n)
        if i == len(self.prefill_buckets):
            raise ValueError(
                f"prompt of {n} tokens exceeds the largest prefill "
                f"bucket {self.prefill_buckets[-1]}")
        return self.prefill_buckets[i]

    def _run_prefill(self, seq, prompt):
        bucket = self._prefill_bucket(len(prompt))
        toks = np.zeros((1, bucket, 1), np.int64)
        toks[0, :len(prompt), 0] = prompt
        slots = np.full((1, bucket), self.cache.sentinel_slot, np.int32)
        slots[0, :len(prompt)] = self.cache.append_slots(
            seq.seq_id, len(prompt))
        feed = self._arena_feed()
        feed["tokens"] = toks
        feed[_SLOTS] = slots
        if "positions" in self._feed_names:
            feed["positions"] = np.arange(bucket, dtype=np.int64) \
                .reshape(1, bucket, 1)
        logits = self._dispatch(self._prefill_program, feed, "prefill",
                                bucket)
        return logits[0, len(prompt) - 1]          # [vocab]

    def _chunk_limit(self):
        # tails longer than this defer to the chunked pump; with
        # chunking off nothing defers (a tail never exceeds max_len)
        return self.prefill_chunk if self.prefill_chunk > 0 else self.max_len

    def _run_chunk(self, seq, chunk, start):
        """One partial-prefill dispatch: ``chunk`` prompt tokens whose
        context starts at absolute position ``start`` (everything before
        them — cached prefix, earlier chunks — is already in the arena).
        Returns the chunk's last real position's logits."""
        bucket = self._prefill_bucket(len(chunk))
        toks = np.zeros((1, bucket, 1), np.int64)
        toks[0, :len(chunk), 0] = chunk
        slots = np.full((1, bucket), self.cache.sentinel_slot, np.int32)
        slots[0, :len(chunk)] = self.cache.append_slots(
            seq.seq_id, len(chunk))
        feed = self._arena_feed()
        feed["tokens"] = toks
        feed[_SLOTS] = slots
        feed[_TABLES] = self.cache.block_table(
            seq.seq_id, self._table_width).reshape(1, -1)
        feed[_CHUNKSTART] = np.asarray([start], np.int32)
        if "positions" in self._feed_names:
            feed["positions"] = (start + np.arange(bucket, dtype=np.int64)) \
                .reshape(1, bucket, 1)
        logits = self._dispatch(self._chunk_program, feed, "chunk", bucket)
        return logits[0, len(chunk) - 1]           # [vocab]

    def _run_tail(self, seq, prompt, cached):
        """Single-dispatch prefill of the uncached tail: a cold prompt
        keeps the original full-window prefill path (bitwise the
        pre-cache behavior); a cached prefix prefills only the tail
        through the chunked executable."""
        if cached == 0:
            return self._run_prefill(seq, prompt)
        return self._run_chunk(seq, prompt[cached:], cached)

    def _run_decode(self):
        S, P = self.max_seqs, self._table_width
        toks = np.zeros((S, 1, 1), np.int64)
        pos = np.zeros((S, 1, 1), np.int64)
        tables = np.zeros((S, P), np.int32)
        ctx = np.zeros(S, np.int32)
        slots = np.full(S, self.cache.sentinel_slot, np.int32)
        for s in self._slots:
            if s is None or s.finished or s.prefilling:
                continue
            j = s.slot
            toks[j, 0, 0] = s.next_token
            pos[j, 0, 0] = self.cache.context_len(s.seq_id)
            slots[j] = self.cache.append_slots(s.seq_id, 1)[0]
            tables[j] = self.cache.block_table(s.seq_id, P)
            ctx[j] = self.cache.context_len(s.seq_id)
        feed = self._arena_feed()
        feed["tokens"] = toks
        feed[_SLOTS] = slots
        feed[_TABLES] = tables
        feed[_CTXLENS] = ctx
        if "positions" in self._feed_names:
            feed["positions"] = pos
        logits = self._dispatch(self._decode_program, feed, "decode",
                                self.max_seqs)
        return logits[:, 0]                        # [max_seqs, vocab]

    # ------------------------------------------------------------------
    def warmup(self, sample_feed=None):
        """Compile the decode executable and every prefill bucket with
        inert feeds (sentinel slots: nothing is written to the arena).
        Returns the number of executables compiled."""
        del sample_feed                            # engine derives its own
        with self._lock:
            before = self._compiles()
            from ...ops.pallas import resolve_tier
            self._kernel_tier = resolve_tier()
            # bundle's published tuning table attaches BEFORE any trace:
            # the digest flag keys every retrace and exec fingerprint
            from ...ops.autotune import attach_for_bundle
            self._tune_digest = attach_for_bundle(self._model_dir)
            with record_event("serving/gen_warmup", kind="stage"):
                if self._exec_cache is not None:
                    # inert decode feed, shaped exactly like the
                    # _run_decode below builds it with every slot idle —
                    # the fingerprint must key the aval set the hot path
                    # dispatches
                    S, P = self.max_seqs, self._table_width
                    dfeed = self._arena_feed()
                    dfeed["tokens"] = np.zeros((S, 1, 1), np.int64)
                    dfeed[_SLOTS] = np.full(S, self.cache.sentinel_slot,
                                            np.int32)
                    dfeed[_TABLES] = np.zeros((S, P), np.int32)
                    dfeed[_CTXLENS] = np.zeros(S, np.int32)
                    if "positions" in self._feed_names:
                        dfeed["positions"] = np.zeros((S, 1, 1), np.int64)
                    self._warm_phase(self._decode_program, dfeed,
                                     "decode", self.max_seqs)
                self._run_decode()
                for b in self.prefill_buckets:
                    toks = np.zeros((1, b, 1), np.int64)
                    slots = np.full((1, b), self.cache.sentinel_slot,
                                    np.int32)
                    feed = self._arena_feed()
                    feed["tokens"] = toks
                    feed[_SLOTS] = slots
                    if "positions" in self._feed_names:
                        feed["positions"] = np.arange(b, dtype=np.int64) \
                            .reshape(1, b, 1)
                    self._warm_phase(self._prefill_program, feed,
                                     "prefill", b)
                    self._dispatch(self._prefill_program, feed, "prefill",
                                   b)
                    if self._partial_enabled:
                        # warm the chunked-prefill twin of every bucket
                        # with an inert feed (sentinel slots write
                        # nothing) so a cached-tail or chunked prefill
                        # never compiles on the hot path
                        feed = self._arena_feed()
                        feed["tokens"] = toks
                        feed[_SLOTS] = slots
                        feed[_TABLES] = np.zeros((1, self._table_width),
                                                 np.int32)
                        feed[_CHUNKSTART] = np.zeros(1, np.int32)
                        if "positions" in self._feed_names:
                            feed["positions"] = np.arange(
                                b, dtype=np.int64).reshape(1, b, 1)
                        self._warm_phase(self._chunk_program, feed,
                                         "chunk", b)
                        self._dispatch(self._chunk_program, feed,
                                       "chunk", b)
            self._warmed = True
            return self._compiles() - before

    def _compiles(self):
        with self._stats_lock:
            return int(sum(c.value for per in self._phase.values()
                           for c, _h in per.values()))

    # ------------------------------------------------------------------
    # sampling
    # ------------------------------------------------------------------
    def _sample(self, seq, logits):
        p = seq.params
        if p["mode"] == "greedy":
            return int(np.argmax(logits))
        k = min(p["top_k"], logits.shape[0])
        # deterministic top-k: stable sort on (-logit, index)
        idx = np.lexsort((np.arange(logits.shape[0]), -logits))[:k]
        logp = _log_softmax(logits[idx].astype(np.float64)
                            / p["temperature"])
        probs = np.exp(logp)
        probs /= probs.sum()
        r = seq.rng.random_sample()
        return int(idx[np.searchsorted(np.cumsum(probs), r,
                                       side="right").clip(0, k - 1)])

    # ------------------------------------------------------------------
    # sequence lifecycle
    # ------------------------------------------------------------------
    @property
    def active_sequences(self):
        return sum(1 for s in self._slots if s is not None)

    def _free_slots(self):
        return [i for i, s in enumerate(self._slots) if s is None]

    def _new_seq(self, slot, params, max_new):
        seq = _Sequence(self._next_seq_id, slot, params, max_new)
        self._next_seq_id += 1
        return seq

    def start(self, prompt, max_new_tokens, sampling=None):
        """Admit + prefill one request. Returns ``(handle, first_tokens,
        finished)`` — the first token(s) stream immediately (time to
        first token = admission + prefill + one sample). Raises
        :class:`NoFreeSlots` / :class:`CacheExhausted` typed (and admits
        nothing) when the request cannot join the running batch."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("prompt must have at least one token")
        max_new = int(max_new_tokens)
        if max_new <= 0:
            raise ValueError("max_new_tokens must be positive")
        if len(prompt) + max_new > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceeds the engine's max_len {self.max_len}")
        params = normalize_sampling(sampling)
        # NEVER-satisfiable requests must raise ValueError (a bad request
        # the scheduler pops and fails), not NoFreeSlots/CacheExhausted
        # (transient capacity the strict-FIFO scheduler would wait on
        # forever, wedging the queue behind the head)
        beam = params["beam_size"] if params["mode"] == "beam" else 1
        if beam > self.max_seqs:
            raise ValueError(
                f"beam_size {beam} exceeds the engine's {self.max_seqs} "
                f"decode slots: this request can never be admitted")
        headroom = 1 if params["mode"] == "beam" else 0
        need = beam * (self.cache.blocks_for(len(prompt) + max_new)
                       + headroom)
        if need > self.cache.num_blocks:
            raise ValueError(
                f"request needs {need} KV blocks worst-case but the arena "
                f"only has {self.cache.num_blocks}: it can never be "
                f"admitted (raise serving_kv_num_blocks or lower "
                f"max_new_tokens)")
        with self._lock:
            if params["mode"] == "beam":
                return self._start_beam(prompt, max_new, params)
            free = self._free_slots()
            if not free:
                raise NoFreeSlots(
                    f"all {self.max_seqs} decode slots are busy")
            slot = free[0]
            seq = self._new_seq(slot, params, max_new)
            seq.prompt = prompt
            self.cache.admit(seq.seq_id, len(prompt) + max_new)
            cached = self.cache.attach_prefix(seq.seq_id, prompt) \
                if self.cache.prefix_cache_blocks > 0 else 0
            _flight_record(
                "gen_admit", component=self.obs_instance,
                seq=seq.seq_id, prompt_tokens=len(prompt),
                cached_tokens=cached, max_new=max_new,
                mode=params["mode"],
                chunked=len(prompt) - cached > self._chunk_limit())
            if len(prompt) - cached > self._chunk_limit():
                # long uncached tail under chunking: admit NOW, prefill
                # one bounded chunk per step boundary (the in-flight
                # decode batch keeps stepping in between)
                seq.pending = list(prompt[cached:])
                seq.prefilling = True
                self._slots[slot] = seq
                self._prefill_queue.append(seq)
                return seq, [], False
            try:
                logits = self._run_tail(seq, prompt, cached)
            except Exception:
                self.cache.release(seq.seq_id)
                raise
            self._slots[slot] = seq
            self.cache.register_prefix(seq.seq_id, prompt)
            tok = self._sample(seq, logits)
            toks, finished = self._advance(seq, tok)
            if finished:
                self._retire(seq)
            return seq, toks, finished

    def _advance(self, seq, tok):
        """Apply one sampled token to a greedy/topk sequence; returns
        (tokens_to_emit, finished). EOS is consumed, not emitted."""
        if seq.params["eos_id"] is not None and tok == seq.params["eos_id"]:
            seq.finished = True
            return [], True
        seq.emitted += 1
        seq.next_token = tok
        if seq.emitted >= seq.max_new:
            seq.finished = True
            return [tok], True
        return [tok], False

    def _start_beam(self, prompt, max_new, params):
        B = params["beam_size"]
        free = self._free_slots()
        if len(free) < B:
            raise NoFreeSlots(
                f"beam request needs {B} slots, {len(free)} free of "
                f"{self.max_seqs}")
        seqs, admitted = [], []
        try:
            for slot in free[:B]:
                seq = self._new_seq(slot, params, max_new)
                self.cache.admit(seq.seq_id, len(prompt) + max_new,
                                 cow_headroom=1)
                admitted.append(seq)
                seqs.append(seq)
        except CacheExhausted:
            for s in admitted:
                self.cache.release(s.seq_id)
            raise
        group = _BeamGroup(seqs, max_new, params["eos_id"])
        group.prompt = prompt
        cached = self.cache.attach_prefix(seqs[0].seq_id, prompt) \
            if self.cache.prefix_cache_blocks > 0 else 0
        _flight_record(
            "gen_admit", component=self.obs_instance,
            seq=seqs[0].seq_id, prompt_tokens=len(prompt),
            cached_tokens=cached, max_new=max_new, mode="beam",
            beam_size=B,
            chunked=len(prompt) - cached > self._chunk_limit())
        if len(prompt) - cached > self._chunk_limit():
            # chunked beam prefill: the lead hypothesis loads the prompt
            # chunk-by-chunk; siblings fork COW once it completes
            group.pending = list(prompt[cached:])
            group.prefilling = True
            for s in seqs:
                s.group = group
                s.prefilling = True
                self._slots[s.slot] = s
            self._prefill_queue.append(group)
            return group, [], False
        try:
            logits = self._run_tail(seqs[0], prompt, cached)
        except Exception:
            for s in admitted:
                self.cache.release(s.seq_id)
            raise
        return self._finish_beam_prefill(group, logits)

    def _finish_beam_prefill(self, group, logits):
        """Completion of a beam request's (possibly chunked) prefill:
        register the prefix, fork the sibling hypotheses COW off the
        prefilled lead, and seed the beam from the prompt logits. A beam
        stream emits only on completion (the winning hypothesis is
        unknown until the search ends)."""
        seqs = group.seqs
        B = len(seqs)
        self.cache.register_prefix(seqs[0].seq_id, group.prompt)
        for s in seqs[1:]:
            self.cache.fork(seqs[0].seq_id, s.seq_id)
        logp = _log_softmax(logits.astype(np.float64)).astype(np.float32)
        order = np.lexsort((np.arange(logp.shape[0]), -logp))[:B]
        group.pre_ids = order.astype(np.int64)
        group.pre_scores = logp[order]
        group.hist_ids.append(group.pre_ids.copy())
        group.hist_parents.append(np.arange(B))
        group.steps = 1
        group.prefilling = False
        for s, t in zip(seqs, group.pre_ids):
            s.group = group
            s.prefilling = False
            s.next_token = int(t)
            self._slots[s.slot] = s
        self._groups.append(group)
        if group.steps >= group.max_new or bool(
                np.all(group.pre_ids == group.end_id)):
            toks = self._finish_beam(group)
            return group, toks, True
        return group, [], False

    # ------------------------------------------------------------------
    def step(self):
        """One continuous-batching step: advance the FIFO-head chunked
        prefill by ONE bounded chunk (if any is pending), then one
        fixed-shape decode dispatch over every active slot, then
        per-sequence sampling / one dense ``beam_search`` op call per
        beam group. Returns a list of ``(handle, new_tokens, finished)``
        events (handles are the objects :meth:`start` returned).
        Finished sequences leave the batch immediately — their slots and
        blocks are free before the next step."""
        with self._lock:
            events = []
            if self._prefill_queue:
                events.extend(self._pump_prefill_locked())
            if not any(s is not None and not s.finished
                       and not s.prefilling for s in self._slots):
                return events
            logits = self._run_decode()
            for s in list(self._slots):
                if s is None or s.group is not None or s.prefilling:
                    continue
                tok = self._sample(s, logits[s.slot])
                toks, finished = self._advance(s, tok)
                if finished:
                    self._retire(s)
                if toks or finished:
                    events.append((s, toks, finished))
            for g in list(self._groups):
                events.extend(self._beam_step(g, logits))
            return events

    def _pump_prefill_locked(self):
        """Advance the oldest pending chunked prefill by one chunk; on
        the LAST chunk the request's first sample happens and it joins
        the decode batch — the completion event(s) are returned."""
        handle = self._prefill_queue[0]
        lead = handle.seqs[0] if isinstance(handle, _BeamGroup) else handle
        chunk = handle.pending[:self.prefill_chunk]
        del handle.pending[:len(chunk)]
        start = self.cache.context_len(lead.seq_id)
        _flight_record("gen_prefill_chunk", component=self.obs_instance,
                       seq=lead.seq_id, chunk_tokens=len(chunk),
                       start=start, remaining=len(handle.pending))
        logits = self._run_chunk(lead, chunk, start)
        if handle.pending:
            return []
        self._prefill_queue.pop(0)
        if isinstance(handle, _BeamGroup):
            h, toks, finished = self._finish_beam_prefill(handle, logits)
            return [(h, toks, finished)]
        handle.prefilling = False
        self.cache.register_prefix(handle.seq_id, handle.prompt)
        tok = self._sample(handle, logits)
        toks, finished = self._advance(handle, tok)
        if finished:
            self._retire(handle)
        return [(handle, toks, finished)]

    def _beam_step(self, group, logits):
        B = len(group.seqs)
        logp = np.stack([
            _log_softmax(logits[s.slot].astype(np.float64))
            for s in group.seqs]).astype(np.float32)      # [B, vocab]
        vocab = logp.shape[1]
        k = min(B, vocab)
        cand_idx = np.argsort(-logp, axis=1, kind="stable")[:, :k]
        cand_scores = np.take_along_axis(logp, cand_idx, axis=1)
        sel_ids, sel_scores, parents = self._beam_search_op(
            group.pre_ids.reshape(1, B),
            group.pre_scores.reshape(1, B),
            cand_idx.reshape(1, B, k).astype(np.int64),
            cand_scores.reshape(1, B, k),
            B, group.end_id)
        group.pre_ids = sel_ids.reshape(B).astype(np.int64)
        group.pre_scores = sel_scores.reshape(B)
        parents = parents.reshape(B)
        group.hist_ids.append(group.pre_ids.copy())
        group.hist_parents.append(parents.copy())
        group.steps += 1
        # fork hypothesis state: slot j continues from its parent's
        # context (copy-on-write block sharing), then feeds its token
        self.cache.reorder({
            s.seq_id: group.seqs[int(parents[j])].seq_id
            for j, s in enumerate(group.seqs)})
        for j, s in enumerate(group.seqs):
            s.next_token = int(group.pre_ids[j])
        if group.steps >= group.max_new or bool(
                np.all(group.pre_ids == group.end_id)):
            toks = self._finish_beam(group)
            return [(group, toks, True)]
        # heartbeat: the group advanced but emits only on completion
        return [(group, [], False)]

    _beam_programs = {}

    def _beam_search_op(self, pre_ids, pre_scores, ids, scores, beam,
                        end_id):
        """One step of the dense ``beam_search`` op, run through a tiny
        eager program (reusing the op exactly as the book decoders do)."""
        import paddle_tpu.fluid as fluid

        key = (beam, end_id)
        prog = self._beam_programs.get(key)
        if prog is None:
            prog = fluid.Program()
            b = prog.global_block()
            for n, dt in (("pre_ids", "int64"), ("pre_scores", "float32"),
                          ("ids", "int64"), ("scores", "float32")):
                b.create_var(name=n, dtype=dt, is_data=True)
            b.append_op(
                "beam_search",
                inputs={"pre_ids": ["pre_ids"], "pre_scores": ["pre_scores"],
                        "ids": ["ids"], "scores": ["scores"]},
                outputs={"selected_ids": ["selected_ids"],
                         "selected_scores": ["selected_scores"],
                         "parent_idx": ["parent_idx"]},
                attrs={"beam_size": beam, "end_id": end_id})
            self._beam_programs[key] = prog
        exe = fluid.Executor(mode="eager")
        out = exe.run(prog,
                      feed={"pre_ids": pre_ids, "pre_scores": pre_scores,
                            "ids": ids, "scores": scores},
                      fetch_list=["selected_ids", "selected_scores",
                                  "parent_idx"],
                      scope=Scope())
        return out[0], out[1], out[2]

    def _finish_beam(self, group):
        """Backtrack the best hypothesis and retire the group. Returns
        its tokens (EOS-trimmed) — a beam stream's single emission."""
        j = int(np.argmax(group.pre_scores))
        toks = []
        for t in range(len(group.hist_ids) - 1, -1, -1):
            toks.append(int(group.hist_ids[t][j]))
            j = int(group.hist_parents[t][j])
        toks.reverse()
        if group.end_id in toks:
            toks = toks[:toks.index(group.end_id)]
        group.finished = True
        for s in group.seqs:
            s.finished = True
            self._retire(s)
        self._groups.remove(group)
        return toks

    def _retire(self, seq):
        if self._slots[seq.slot] is seq:
            self._slots[seq.slot] = None
        self.cache.release(seq.seq_id)

    def abort(self, handle):
        """Cancel an in-flight request (client disconnected): frees its
        slot(s) and blocks immediately (mid-chunked-prefill requests
        leave the prefill queue too)."""
        with self._lock:
            if not handle.finished:
                lead = handle.seqs[0] if isinstance(handle, _BeamGroup) \
                    else handle
                _flight_record(
                    "gen_abort", component=self.obs_instance,
                    seq=lead.seq_id, prefilling=bool(handle.prefilling))
            if handle in self._prefill_queue:
                self._prefill_queue.remove(handle)
            if isinstance(handle, _BeamGroup):
                if not handle.finished:
                    handle.finished = True
                    for s in handle.seqs:
                        if not s.finished:
                            s.finished = True
                            self._retire(s)
                    if handle in self._groups:
                        self._groups.remove(handle)
            elif not handle.finished:
                handle.finished = True
                self._retire(handle)

    # ------------------------------------------------------------------
    @property
    def warmed(self):
        """Whether warmup() ran — the cheap liveness bit health() reads
        without paying stats()'s device-memory sample."""
        return self._warmed

    @property
    def hot_recompiles(self):
        """Compiles observed after warmup — derived from this engine's
        registry counter."""
        return int(self._m_hot.value)

    def _memory_section(self):
        """KV-arena accounting reconciliation: the arena's full byte
        footprint (pre-allocated — live regardless of occupancy), the
        share its in-use blocks address, the scope's parameter bytes,
        and the device's live total, so an operator can see what of
        ``paddle_tpu_device_bytes_live`` the serving state explains."""
        arena_bytes = sum(int(a.nbytes)
                          for arrs in (self.cache.k, self.cache.v)
                          for a in arrs)
        cs = self.cache.stats()
        in_use_frac = cs["blocks_in_use"] / max(cs["num_blocks"], 1)
        param_bytes = 0
        for name in self._scope.local_names():
            v = self._scope.find_var(name)
            nb = getattr(v, "nbytes", None)
            if nb is not None:
                param_bytes += int(nb)
        mem = _perf.sample_device_memory()
        accounted = arena_bytes + param_bytes
        return {"arena_bytes": arena_bytes,
                "arena_bytes_in_use": int(arena_bytes * in_use_frac),
                "param_bytes": param_bytes,
                "device_bytes_live": mem["total"],
                "unaccounted_bytes": max(0, mem["total"] - accounted)}

    def stats(self):
        with self._stats_lock:
            phases = {ph: {b: {"compiles": int(c.value),
                               "hits": int(h.value)}
                           for b, (c, h) in per.items()}
                      for ph, per in self._phase.items()}
        return json_safe({
            "phases": phases,
            "compiles": sum(s["compiles"] for per in phases.values()
                            for s in per.values()),
            "hits": sum(s["hits"] for per in phases.values()
                        for s in per.values()),
            "hot_recompiles": self.hot_recompiles,
            "warmed": self._warmed,
            "active_sequences": self.active_sequences,
            "prefilling": len(self._prefill_queue),
            "max_seqs": self.max_seqs,
            "blocks_in_use": self.cache.stats()["blocks_in_use"],
            "cache": self.cache.stats(),
            "prefill_chunk": self.prefill_chunk,
            "kernel_tier": self._kernel_tier,
            "tune_digest": self._tune_digest,
            "exec_cache": self._exec_cache.stats()
            if self._exec_cache is not None else None,
            "kv_store": self._kv_store.stats()
            if self._kv_store is not None else None,
            "donate_arena": self.donate_arena,
            "warm_loaded": len(self._warm_loaded),
            "ttft": self.ttft.snapshot(),
            "tpot": self.tpot.snapshot(),
            "memory": self._memory_section(),
        })


__all__ = ["GenerationEngine", "NoFreeSlots", "normalize_sampling"]
