"""ContinuousBatcher: step-boundary scheduling of generation requests.

The :class:`~..batcher.DynamicBatcher` sibling for stateful decode — and
the part that makes it CONTINUOUS: where the feed-forward batcher
gang-schedules whole requests into one dispatch, here sequences JOIN the
running batch at any step boundary (a queued prompt is prefilled the
moment a slot and enough KV blocks free up) and LEAVE the moment they
hit EOS or their token budget — the batch never waits for its slowest
member, and a finished sequence's slot is refilled before the next
decode step. ``continuous=False`` keeps the gang-scheduled behavior
(admit a full batch, run it to completion, admit the next) as the A/B
baseline the bench lane measures against.

Backpressure keeps the DynamicBatcher's contract: a bounded wait queue
that rejects FAST with the same typed
:class:`~..batcher.ServerOverloaded` when full. Admission is strict
FIFO — a head request that doesn't fit (slots or blocks) blocks the
queue rather than being overtaken, so admission order (and therefore
the parity-pinned token streams) is deterministic. Under chunked
prefill (``serving_prefill_chunk``) a long prompt ADMITS immediately
(reserving its slot and worst-case blocks, keeping the FIFO contract)
and its prefill work interleaves with decode: every worker loop turn is
one ``engine.step()``, which runs at most ONE bounded prefill chunk
before the decode dispatch, so in-flight streams keep emitting tokens
while a cold prompt loads.

``submit`` returns a :class:`TokenStream` — an iterator the caller
drains as the worker emits tokens (the RPC layer turns it into
multi-frame streaming responses). Closing a stream early cancels its
sequence: the worker aborts it at the next step boundary and its
slot/blocks recycle.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque

from ...core.flags import get_flag
from ...obs.metrics import REGISTRY as _METRICS, json_safe, next_instance
from ...obs.recorder import record as _flight_record
from ..batcher import ServerOverloaded, _M_QUEUE_DEPTH
from .decode_engine import CacheExhausted, NoFreeSlots, normalize_sampling

_GEN_REQUESTS = _METRICS.counter(
    "paddle_tpu_genbatcher_requests",
    "generation requests submitted to a ContinuousBatcher, per instance",
    labels=("instance",))
_GEN_REJECTED = _METRICS.counter(
    "paddle_tpu_genbatcher_rejected",
    "generation requests rejected with ServerOverloaded (wait queue "
    "full), per instance", labels=("instance",))


class _Cancelled(Exception):
    pass


class TokenStream:
    """Iterator over one request's generated token ids. ``close()``
    cancels the request (a consumer that stops reading mid-stream);
    worker-side errors re-raise in the consumer."""

    _DONE = object()

    def __init__(self, batcher):
        self._batcher = batcher
        self._q = queue.Queue()
        self._closed = False
        self.first_token_s = None      # set by the worker (TTFT probe)
        self._submit_s = None          # worker stamps TTFT against this
        # per-stream serving-telemetry state (worker-side, under _cv):
        # the TTFT probe resolves exactly once — STAMPED at the first
        # actual token (into the engine's ttft histogram) or DISCARDED
        # when the stream ends first (abort/cancel/error); TPOT records
        # once at stream end for streams that emitted >= 2 tokens
        self._first_emit_t = None
        self._last_emit_t = None
        self._ntokens = 0
        self._resolved = False         # TTFT probe stamped or discarded
        self._tpot_done = False

    # worker side -------------------------------------------------------
    def _emit(self, tokens):
        for t in tokens:
            self._q.put(int(t))

    def _finish(self, error=None):
        self._q.put(error if error is not None else self._DONE)

    # consumer side -----------------------------------------------------
    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def batches(self):
        """Like iteration, but yields LISTS: one blocking wait for the
        next token, then everything else already queued rides the same
        batch — the frame-coalescing form the streaming RPC handler uses
        (a consumer slower than the decode loop gets fewer, fuller
        frames instead of a backlog of one-token messages)."""
        while True:
            item = self._q.get()
            batch = []
            while True:
                if item is self._DONE:
                    if batch:
                        yield batch
                    return
                if isinstance(item, BaseException):
                    if batch:
                        yield batch
                    raise item
                batch.append(item)
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break
            yield batch

    def close(self):
        if not self._closed:
            self._closed = True
            self._batcher._cancel(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class _Pending:
    __slots__ = ("prompt", "max_new", "sampling", "stream", "submit_s")

    def __init__(self, prompt, max_new, sampling, stream, submit_s):
        self.prompt = prompt
        self.max_new = max_new
        self.sampling = sampling
        self.stream = stream
        self.submit_s = submit_s


class ContinuousBatcher:
    """Drives a :class:`~.decode_engine.GenerationEngine` from one worker
    thread: admit (continuous: whenever capacity frees; gang: only when
    the batch drained), one decode step, route events, repeat.
    ``capacity`` bounds the WAIT queue (default
    ``serving_queue_capacity``)."""

    def __init__(self, engine, capacity=None, continuous=True):
        self.engine = engine
        self.continuous = bool(continuous)
        self.capacity = int(get_flag("serving_queue_capacity")
                            if capacity is None else capacity)
        self._pending = deque()
        self._cancels = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._handles = {}            # stream -> engine handle
        # request/overload counters in the obs.metrics registry (stats()
        # derives from them); step/token counts stay local (under _cv)
        self.obs_instance = next_instance("genbatcher")
        self._m_requests = _GEN_REQUESTS.labels(instance=self.obs_instance)
        self._m_rejected = _GEN_REJECTED.labels(instance=self.obs_instance)
        self._m_depth = _M_QUEUE_DEPTH.labels(instance=self.obs_instance)
        self._m_depth.set(0)
        self._n_steps = 0
        self._n_tokens = 0
        self._n_ttft_discarded = 0
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens, sampling=None):
        """Queue one generation request; returns its :class:`TokenStream`.
        Rejects FAST with :class:`ServerOverloaded` when ``capacity``
        requests already wait (in-flight sequences don't count — they
        are bounded by the engine's slots, not the queue)."""
        sampling = normalize_sampling(sampling)   # reject bad specs HERE
        stream = TokenStream(self)
        req = _Pending(list(prompt), int(max_new_tokens), sampling, stream,
                       time.perf_counter())
        with self._cv:
            if self._closed:
                raise RuntimeError("ContinuousBatcher is closed")
            self._m_requests.inc()
            if len(self._pending) >= self.capacity:
                self._m_rejected.inc()
                _flight_record("overload_reject",
                               component=self.obs_instance,
                               queue_depth=len(self._pending),
                               capacity=self.capacity)
                raise ServerOverloaded(
                    f"generation queue full ({self.capacity} requests "
                    "waiting); back off and retry")
            self._pending.append(req)
            self._m_depth.set(len(self._pending))
            self._cv.notify_all()
        return stream

    def _cancel(self, stream):
        with self._cv:
            self._cancels.append(stream)
            self._cv.notify_all()

    # ------------------------------------------------------------------
    # per-request serving telemetry (TTFT / TPOT), worker-side under _cv
    # ------------------------------------------------------------------
    def _note_emit_locked(self, stream, tokens):
        """Account one emission: the FIRST actual token stamps the TTFT
        probe into the engine's ttft histogram; every emission advances
        the TPOT clock."""
        if not tokens:
            return
        now = time.perf_counter()
        if stream.first_token_s is None and stream._submit_s is not None:
            stream.first_token_s = now - stream._submit_s
            stream._first_emit_t = now
            stream._resolved = True
            self.engine.ttft.observe(stream.first_token_s)
        stream._last_emit_t = now
        stream._ntokens += len(tokens)
        self._n_tokens += len(tokens)

    def _finalize_stream_locked(self, stream, reason):
        """Resolve a stream's probes exactly once, however it ends
        (finish / cancel / worker error): an UNSTAMPED TTFT probe is
        DISCARDED (counted — never recorded as a sample, never left
        dangling), and TPOT records once for streams that emitted >= 2
        tokens."""
        if not stream._resolved:
            # aborted/errored before its first token: stamp-or-discard
            # resolves to DISCARD — the histogram must not see a sample
            # for a token that never arrived
            stream._resolved = True
            self._n_ttft_discarded += 1
            _flight_record("gen_finish", component=self.obs_instance,
                           reason=reason, tokens=0, ttft_discarded=True)
            return
        if not stream._tpot_done and stream._ntokens >= 2:
            stream._tpot_done = True
            self.engine.tpot.observe(
                (stream._last_emit_t - stream._first_emit_t)
                / (stream._ntokens - 1))
        _flight_record("gen_finish", component=self.obs_instance,
                       reason=reason, tokens=stream._ntokens,
                       ttft_ms=round(stream.first_token_s * 1e3, 3))

    # ------------------------------------------------------------------
    def _run(self):
        while True:
            with self._cv:
                while (not self._pending and not self._cancels
                       and not self._handles and not self._closed):
                    self._cv.wait()
                if self._closed and not self._handles:
                    self._reject_queued_locked()
                    return
                self._apply_cancels_locked()
                self._admit_locked()
            try:
                events = self.engine.step()
            except Exception as e:
                # a decode-step failure poisons every in-flight sequence
                with self._cv:
                    for stream, handle in list(self._handles.items()):
                        self.engine.abort(handle)
                        self._finalize_stream_locked(stream, "worker_error")
                        stream._finish(e)
                    self._handles.clear()
                continue
            with self._cv:
                self._route_locked(events)

    def _apply_cancels_locked(self):
        while self._cancels:
            stream = self._cancels.popleft()
            handle = self._handles.pop(stream, None)
            if handle is not None:
                self.engine.abort(handle)
                # stamp-or-discard: a stream cancelled before its first
                # token discards its TTFT probe here (a started one was
                # stamped at the token); never a dangling probe
                self._finalize_stream_locked(stream, "cancelled")
            else:
                # not started yet: drop it from the wait queue
                for req in list(self._pending):
                    if req.stream is stream:
                        self._pending.remove(req)
                        self._m_depth.set(len(self._pending))
                        break
            stream._finish(_Cancelled("generation cancelled"))

    def _admit_locked(self):
        """FIFO admission. Continuous mode admits whenever the head fits;
        gang mode opens an admission round only when the batch is empty,
        fills it, then waits for every member to finish."""
        if not self.continuous and self._handles:
            return
        while self._pending and not self._closed:
            req = self._pending[0]
            try:
                handle, first, finished = self.engine.start(
                    req.prompt, req.max_new, req.sampling)
            except (NoFreeSlots, CacheExhausted):
                break                  # head blocks until capacity frees
            except Exception as e:     # bad request (typed ValueError...)
                self._pending.popleft()
                self._m_depth.set(len(self._pending))
                req.stream._finish(e)
                continue
            self._pending.popleft()
            self._m_depth.set(len(self._pending))
            req.stream._submit_s = req.submit_s
            # TTFT is stamped at the FIRST ACTUAL token: a beam or
            # chunked-prefill admission emits nothing yet — its first
            # token lands later via _route_locked
            self._note_emit_locked(req.stream, first)
            req.stream._emit(first)
            if finished:
                self._finalize_stream_locked(req.stream, "finished")
                req.stream._finish()
            else:
                handle.user_data = req.stream
                self._handles[req.stream] = handle

    def _route_locked(self, events):
        if events:
            self._n_steps += 1
        for handle, tokens, finished in events:
            stream = handle.user_data
            if stream is None or stream not in self._handles:
                continue               # cancelled mid-step
            self._note_emit_locked(stream, tokens)
            stream._emit(tokens)
            if finished:
                del self._handles[stream]
                self._finalize_stream_locked(stream, "finished")
                stream._finish()

    # ------------------------------------------------------------------
    def _reject_queued_locked(self):
        err = RuntimeError("ContinuousBatcher is closed; this queued "
                           "request was rejected without being served")
        while self._pending:
            self._pending.popleft().stream._finish(err)
        self._m_depth.set(0)

    def transfer_queued(self, other):
        """Move every still-QUEUED (unadmitted) request to ``other``,
        preserving FIFO order — the reload handoff: the old batcher's
        in-flight sequences finish on the old engine, but its wait
        queue would otherwise be rejected at close even though the new
        engine is ready to serve it. Requests the old worker admits
        concurrently are simply not in the queue anymore and finish
        where they started. Returns the number moved; requests that
        cannot move (``other`` already closed) are rejected typed, the
        close-time behavior they were headed for anyway."""
        with self._cv:
            moved = list(self._pending)
            self._pending.clear()
            self._m_depth.set(0)
        n = 0
        for req in moved:
            with other._cv:
                if not other._closed:
                    # rebind BEFORE the new worker can touch it: a
                    # consumer-side close() must cancel against the
                    # batcher that actually holds the request
                    req.stream._batcher = other
                    other._pending.append(req)
                    other._m_depth.set(len(other._pending))
                    other._cv.notify_all()
                    n += 1
                    continue
            req.stream._finish(RuntimeError(
                "ContinuousBatcher is closed; this queued request was "
                "rejected without being served"))
        return n

    def close(self, timeout=30.0):
        """Stop admitting, let in-flight sequences FINISH (their callers
        get complete streams), reject still-queued requests typed, and
        join the worker. Returns True when the worker exited in time."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._worker.join(timeout)
        closed_clean = not self._worker.is_alive()
        if not closed_clean:
            with self._cv:
                self._reject_queued_locked()
        return closed_clean

    def stats(self):
        with self._cv:
            out = {
                "queue_depth": len(self._pending),
                "capacity": self.capacity,
                "continuous": self.continuous,
                "in_flight": len(self._handles),
                "requests": int(self._m_requests.value),
                "rejected": int(self._m_rejected.value),
                "steps": self._n_steps,
                "tokens_emitted": self._n_tokens,
                "ttft_discarded": self._n_ttft_discarded,
                "ttft": self.engine.ttft.snapshot(),
                "tpot": self.engine.tpot.snapshot(),
            }
        return json_safe(out)


__all__ = ["ContinuousBatcher", "TokenStream"]
