"""GenClient: the streaming twin of InferClient.

``generate()`` is a GENERATOR over the server's multi-frame streaming
response: each frame carries the tokens one continuous-batching step
produced for this request, so the caller sees tokens as they decode
(time-to-first-token = admission + prefill + one sample, not the whole
generation). Remote failures keep the typed contract:

* :class:`~..batcher.ServerOverloaded` — the generation wait queue
  rejected the request; back off (never auto-retried).
* any other handler failure — :class:`~...distributed.rpc.RemoteError`
  with the remote code/traceback, raised mid-stream at the exact frame
  the server failed.

Connection failures are NOT auto-retried: a generation stream is
stateful (a blind resend would decode the prompt twice), so the caller
owns whole-stream retries. One client supports one stream at a time
(the connection is dedicated until the terminal frame); use one
GenClient per concurrent stream. Abandoning the iterator cancels the
request server-side — the scheduler frees its slot and blocks.
"""

from __future__ import annotations

from ...distributed.rpc import RemoteError, RpcClient, WIRE_FRAMED
from ..client import raise_typed


class GenClient:
    def __init__(self, address, timeout=None, wire=WIRE_FRAMED):
        self._rpc = RpcClient(address, timeout=timeout, retry=None,
                              wire=wire)

    def generate(self, prompt, max_new_tokens, sampling=None, model=None,
                 tenant=None):
        """Yield generated token ids for ``prompt`` as the server decodes
        them. ``sampling`` is the ``normalize_sampling`` dict form
        ({"mode": "greedy"|"topk"|"beam", ...}); beam streams emit the
        winning hypothesis once, at completion. ``model=`` targets a
        named hosted model on a multi-model server; ``tenant=`` tags the
        request for quota accounting (:class:`~..batcher.QuotaExceeded`
        re-raises typed). Both are omitted from the wire frame when None,
        so single-model call shapes are unchanged."""
        kwargs = {"prompt": [int(t) for t in prompt],
                  "max_new_tokens": int(max_new_tokens),
                  "sampling": sampling}
        if model is not None:
            kwargs["model"] = str(model)
        if tenant is not None:
            kwargs["tenant"] = str(tenant)
        try:
            for frame in self._rpc.stream("generate", **kwargs):
                for t in frame["tokens"]:
                    yield int(t)
        except RemoteError as e:
            raise_typed(e)

    def _call(self, method, **kwargs):
        try:
            return self._rpc.call(method, **kwargs)
        except RemoteError as e:
            raise_typed(e)

    def health(self):
        return self._call("health")

    def stats(self):
        return self._call("stats")

    def close(self):
        self._rpc.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


__all__ = ["GenClient"]
