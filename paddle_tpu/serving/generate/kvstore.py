"""Persistent KV-prefix store: replicas ATTACH instead of prefill.

PR 11's shared-prefix cache collapses TTFT for the "one system prompt x
a million users" workload, but it lives per-engine and in-arena: every
reload, rollout, and scale-out replica starts cold and re-prefills the
same fleet-famous prefixes independently. This module is the
persistence tier under ``PagedKVCache``: registered refcount-0 prefix
blocks — hash-chain keyed, content-addressed, exactly the chain
granularity the prefix cache pinned — serialize to a host-RAM/disk
directory, LRU eviction DEMOTES to that tier instead of discarding, and
``attach_prefix`` on a spill hit restores blocks into the arena with
zero prefill dispatches, bitwise identical to a hot-cache attach.
"Prefill once, attach forever" — ``execcache.py``'s discipline applied
to KV bytes instead of compiled executables.

The safety contract mirrors ``execcache.py`` exactly:

* **Full identity fingerprint.** An artifact is keyed by everything
  that could change the KV bytes it holds: the bundle's registry
  ``content_hash`` (the exact parameter/program bytes), the arena
  geometry (layers, heads, head_dim, block size, dtype), every
  ``_JIT_KEY_FLAGS`` value (``kernel_tier``!), the jax/jaxlib versions,
  and the backend platform + device kind. ANY mismatch is a silent miss
  followed by a normal prefill — a stale or foreign artifact must never
  attach, because skewed KV bytes silently corrupt every token sampled
  through them.
* **Corruption is a miss, never a failure.** Artifacts carry a sha256
  over their payload; a truncated or bit-flipped file, an unpickle
  raise, a foreign fingerprint, or a payload whose arrays do not match
  the arena geometry all fall back to the prefill path with a
  ``paddle_tpu_kvcache_spill_rejects`` bump and a flight-recorder
  event.
* **Manifest pinning.** A published version's ``kv/`` artifacts are
  listed with per-file sha256 in ``VERSION.json`` (``kv_files``) —
  the RAW bytes must match the manifest BEFORE anything is unpickled,
  ``verify()`` re-hashes them, ``gc()`` deletes them with the version.

Storage layouts: a published registry version holds its artifacts under
``<version>/kv/`` (built by ``ModelRegistry.warm(kv_prompts=...)`` /
``publish(kv_prompts=...)`` — engines open it READ-ONLY); the
``serving_kv_spill_dir`` flag names a per-process read-write spill
directory for unpublished bundles, byte-budgeted by
``serving_kv_spill_bytes`` (oldest artifacts evict first). Empty flag =
no spilling: eviction discards, bitwise the pre-spill behavior.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle

import numpy as np

from ...core.flags import get_flag
from ...obs.metrics import REGISTRY as _METRICS, json_safe, next_instance

KV_DIRNAME = "kv"
ARTIFACT_SUFFIX = ".jkv"
_MAGIC = b"PDTPUKV1\n"

# reject reasons form a bounded enum (they become a metric label):
#   format      — bad magic / truncated / payload digest mismatch
#   manifest    — artifact unlisted in (or mismatching) the version
#                 manifest's kv_files digests — published kv dirs only;
#                 checked over the RAW bytes before unpickling
#   fingerprint — artifact is intact but keyed for a different identity
#   deserialize — unpickle raised, or the payload arrays do not match
#                 the arena geometry the fingerprint promises
REJECT_REASONS = ("format", "manifest", "fingerprint", "deserialize")

_M_WRITES = _METRICS.counter(
    "paddle_tpu_kvcache_spill_writes",
    "prefix-chain KV blocks serialized to the spill tier (eviction "
    "demotions + publish-time precompute), per store instance",
    labels=("instance",))
_M_RESTORES = _METRICS.counter(
    "paddle_tpu_kvcache_spill_restores",
    "prefix-chain KV blocks restored from the spill tier into the arena "
    "instead of being re-prefilled, per store instance",
    labels=("instance",))
_M_SPILL_REJECTS = _METRICS.counter(
    "paddle_tpu_kvcache_spill_rejects",
    "spill artifacts refused at load (corrupt bytes, foreign "
    "fingerprint, manifest mismatch, bad geometry) — prefill fallback, "
    "never an error", labels=("instance", "reason"))
_M_BYTES = _METRICS.gauge(
    "paddle_tpu_kvcache_spill_bytes",
    "bytes currently held by a writable spill directory (the "
    "serving_kv_spill_bytes budget's measured side), per store instance",
    labels=("instance",))


# ---------------------------------------------------------------------------
# identity
# ---------------------------------------------------------------------------

def kv_fingerprint(content_hash, num_layers, num_heads, head_dim,
                   block_size, dtype):
    """The full identity of ONE arena's KV bytes, as a JSON-safe dict:
    the bundle content hash (which parameters produced the bytes), the
    arena geometry (where a block's bytes land and how wide they are),
    the ``_JIT_KEY_FLAGS`` tuple the Executor keys its jit cache on
    (``kernel_tier`` flips must miss — a different attention lowering
    may round differently), jax/jaxlib versions, and the backend
    platform + device kind. ANY mismatch is a silent miss followed by a
    normal prefill."""
    import jax
    import jaxlib

    from ...core.executor import _JIT_KEY_FLAGS

    dev = jax.devices()[0]
    return {
        "format": 1,
        "content_hash": str(content_hash),
        "layers": int(num_layers),
        "heads": int(num_heads),
        "head_dim": int(head_dim),
        "block_size": int(block_size),
        "dtype": str(np.dtype(dtype)),
        "flags": {n: get_flag(n) for n in _JIT_KEY_FLAGS},
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": str(dev.platform),
        "device_kind": str(getattr(dev, "device_kind", dev.platform)),
    }


def fingerprint_key(fp):
    """Stable digest of a fingerprint dict (the artifact filename key):
    a geometry/toolchain flip changes every artifact NAME, so foreign
    configurations miss without even opening a file."""
    return hashlib.sha256(
        json.dumps(fp, sort_keys=True, default=str).encode()).hexdigest()


# ---------------------------------------------------------------------------
# the on-disk store
# ---------------------------------------------------------------------------

class KVStore:
    """Directory of serialized prefix-chain KV blocks, chain-hash keyed.

    One artifact per registered chain hash: because ``h_i`` commits to
    every token in blocks ``0..i``, a per-block artifact IS
    chain-granular — restoring a chain is restoring its blocks in
    order, and a lookup can never attach bytes whose left context
    differs. Artifact format: ``MAGIC + sha256hex(blob) + "\\n" +
    blob`` where ``blob`` pickles ``{"fingerprint", "k", "v"}`` (the
    block's ``[layers, block_size, heads, head_dim]`` K and V numpy
    stacks). The digest detects truncation/bit rot before unpickling;
    the embedded fingerprint must equal the expected one, so a renamed
    or hash-colliding file is refused too. Writes are content-addressed
    and idempotent (an existing artifact is never rewritten) via tmp +
    ``os.replace``.

    ``readonly=True`` is the published ``kv/`` dir contract: replicas
    attach but never mutate a registry version. ``expected_digests``
    (basename -> sha256 of the whole file, from the version manifest's
    ``kv_files``) pins what this store may load BEFORE anything is
    unpickled. ``budget_bytes > 0`` bounds a writable directory: a
    write that would overflow first evicts the OLDEST artifacts (mtime
    order); an artifact bigger than the whole budget is not written."""

    def __init__(self, path, fingerprint, readonly=False,
                 expected_digests=None, budget_bytes=0):
        self.path = str(path)
        self.fingerprint = dict(fingerprint)
        self.readonly = bool(readonly)
        self.budget_bytes = int(budget_bytes or 0)
        self._expected = None if expected_digests is None \
            else dict(expected_digests)
        self._fpkey = fingerprint_key(self.fingerprint)
        if not self.readonly:
            os.makedirs(self.path, exist_ok=True)
        self.obs_instance = next_instance("kvstore")
        self._m_writes = _M_WRITES.labels(instance=self.obs_instance)
        self._m_restores = _M_RESTORES.labels(instance=self.obs_instance)
        self._m_bytes = _M_BYTES.labels(instance=self.obs_instance)
        self._m_rejects = {
            r: _M_SPILL_REJECTS.labels(instance=self.obs_instance,
                                       reason=r)
            for r in REJECT_REASONS}
        # artifact basenames this instance successfully loaded or saved
        # — registry.warm() lists exactly this set in the manifest (a
        # stale artifact from an older geometry/toolchain is unloadable
        # forever and must not be re-certified)
        self._touched = set()
        # writable stores meter their bytes once at open (budget
        # enforcement needs a running total, not a per-write listdir)
        self._bytes = 0 if self.readonly else self._scan_bytes()
        self._m_bytes.set(self._bytes)

    def _scan_bytes(self):
        total = 0
        try:
            for name in os.listdir(self.path):
                if name.endswith(ARTIFACT_SUFFIX):
                    try:
                        total += os.path.getsize(
                            os.path.join(self.path, name))
                    except OSError:
                        pass
        except OSError:
            pass
        return total

    # ------------------------------------------------------------------
    def artifact_path(self, chain_hash):
        return os.path.join(
            self.path, f"{bytes(chain_hash).hex()}-{self._fpkey[:16]}"
                       f"{ARTIFACT_SUFFIX}")

    def note_reject(self, chain_hash, reason, error=None):
        """Count + flight-record one refused artifact."""
        from ...obs.recorder import record as _flight_record

        if reason not in self._m_rejects:
            reason = "deserialize"
        self._m_rejects[reason].inc()
        _flight_record("kv_spill_reject", component=self.obs_instance,
                       chain=bytes(chain_hash).hex()[:16], reason=reason,
                       error=None if error is None
                       else f"{type(error).__name__}: {error}")

    def load(self, chain_hash):
        """The restore path: ``(k, v)`` numpy stacks (``[layers,
        block_size, heads, head_dim]`` each) for the chain, or None
        (miss / reject — the caller prefills). Never raises: corruption
        at ANY depth is a reject + prefill fallback, because a broken
        store must only ever cost the prefill it failed to skip."""
        path = self.artifact_path(chain_hash)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        fp = self.fingerprint
        geom = (fp["layers"], fp["block_size"], fp["heads"],
                fp["head_dim"])
        stage = "format"
        try:
            if self._expected is not None:
                # manifest pinning: the raw bytes must be exactly what
                # the version manifest certifies, checked BEFORE any
                # unpickling — unlisted or mismatching bytes never
                # reach pickle.loads
                stage = "manifest"
                want = self._expected.get(os.path.basename(path))
                if want is None:
                    raise ValueError(
                        "artifact is not listed in the version "
                        "manifest's kv_files")
                if hashlib.sha256(raw).hexdigest() != want:
                    raise ValueError(
                        "artifact bytes do not match the manifest's "
                        "kv_files digest")
                stage = "format"
            if not raw.startswith(_MAGIC):
                raise ValueError("bad magic (not a KV artifact)")
            header_end = raw.index(b"\n", len(_MAGIC))
            digest = raw[len(_MAGIC):header_end].decode("ascii")
            blob = raw[header_end + 1:]
            if hashlib.sha256(blob).hexdigest() != digest:
                raise ValueError("payload digest mismatch (truncated or "
                                 "bit-flipped artifact)")
            stage = "deserialize"
            doc = pickle.loads(blob)
            stage = "fingerprint"
            if doc.get("fingerprint") != fp:
                raise ValueError("artifact fingerprint does not match "
                                 "the arena identity")
            stage = "deserialize"
            k = np.asarray(doc["k"])
            v = np.asarray(doc["v"])
            if k.shape != geom or v.shape != geom \
                    or str(k.dtype) != fp["dtype"] \
                    or str(v.dtype) != fp["dtype"]:
                raise ValueError(
                    f"payload arrays {k.shape}/{k.dtype} do not match "
                    f"the arena geometry {geom}/{fp['dtype']}")
        except Exception as e:
            self.note_reject(chain_hash, stage, error=e)
            return None
        self._m_restores.inc()
        self._touched.add(os.path.basename(path))
        return k, v

    def save(self, chain_hash, k, v):
        """Persist one chain block's KV bytes. Content-addressed and
        idempotent: an artifact already on disk is never rewritten (the
        name commits to chain hash + full fingerprint, so same name
        means same bytes). Returns the artifact path (existing or just
        written), or None when the store is read-only, the write fails,
        or the byte budget cannot fit it — persistence is best-effort,
        the arena keeps working either way."""
        if self.readonly:
            return None
        from ...obs.recorder import record as _flight_record

        path = self.artifact_path(chain_hash)
        if os.path.exists(path):
            self._touched.add(os.path.basename(path))
            return path
        try:
            blob = pickle.dumps(
                {"fingerprint": self.fingerprint,
                 "k": np.asarray(k), "v": np.asarray(v)},
                protocol=pickle.HIGHEST_PROTOCOL)
            data = (_MAGIC + hashlib.sha256(blob).hexdigest().encode()
                    + b"\n" + blob)
            if self.budget_bytes > 0:
                if len(data) > self.budget_bytes:
                    _flight_record(
                        "kv_spill_skip", component=self.obs_instance,
                        chain=bytes(chain_hash).hex()[:16],
                        error=f"artifact ({len(data)} B) exceeds the "
                              f"whole budget ({self.budget_bytes} B)")
                    return None
                self._evict_for(len(data))
            tmp = path + f".{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except Exception as e:
            _flight_record("kv_spill_save_failed",
                           component=self.obs_instance,
                           chain=bytes(chain_hash).hex()[:16],
                           error=f"{type(e).__name__}: {e}")
            return None
        self._bytes += len(data)
        self._m_bytes.set(self._bytes)
        self._m_writes.inc()
        self._touched.add(os.path.basename(path))
        return path

    def _evict_for(self, need):
        """Budget enforcement: delete OLDEST artifacts (mtime order)
        until ``need`` more bytes fit under ``budget_bytes``."""
        if self._bytes + need <= self.budget_bytes:
            return
        entries = []
        try:
            for name in os.listdir(self.path):
                if not name.endswith(ARTIFACT_SUFFIX):
                    continue
                p = os.path.join(self.path, name)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                entries.append((st.st_mtime, p, st.st_size))
        except OSError:
            return
        for _mtime, p, size in sorted(entries):
            if self._bytes + need <= self.budget_bytes:
                break
            try:
                os.remove(p)
            except OSError:
                continue
            self._bytes -= size
            self._touched.discard(os.path.basename(p))
        self._bytes = max(0, self._bytes)
        self._m_bytes.set(self._bytes)

    # ------------------------------------------------------------------
    def touched(self):
        """Artifact basenames this instance loaded or saved (sorted) —
        what a just-run publish-time prefill actually proved usable."""
        return sorted(self._touched)

    def artifacts(self):
        """Artifact filenames currently on disk (sorted)."""
        try:
            return sorted(n for n in os.listdir(self.path)
                          if n.endswith(ARTIFACT_SUFFIX))
        except OSError:
            return []

    def stats(self):
        # no filesystem I/O here: this rides every engine/server stats()
        # scrape — byte inventory is the running total, not a listdir
        return json_safe({
            "dir": self.path,
            "readonly": self.readonly,
            "budget_bytes": self.budget_bytes,
            "bytes": int(self._bytes),
            "touched": len(self._touched),
            "writes": int(self._m_writes.value),
            "restores": int(self._m_restores.value),
            "rejects": {r: int(c.value)
                        for r, c in self._m_rejects.items()},
        })


# ---------------------------------------------------------------------------
# resolution
# ---------------------------------------------------------------------------

def manifest_kv_digests(model_dir):
    """basename -> sha256 pin set for the kv dir at ``model_dir``, from
    the version manifest's ``kv_files``. A manifest WITHOUT the field
    pins the empty set (a kv dir next to a manifest that never
    certified it restores nothing — replicas prefill); no readable
    manifest at all returns None (not a registry version: the artifact
    self-digest is the only integrity layer)."""
    from ..registry import VERSION_MANIFEST

    try:
        with open(os.path.join(model_dir, VERSION_MANIFEST)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    return {os.path.basename(rel): digest
            for rel, digest in manifest.get("kv_files", {}).items()}


def resolve_store(model_dir, kv_store, fingerprint):
    """The spill store an engine's arena should use. An explicit
    ``kv_store`` directory path always wins — that is how
    ``ModelRegistry.warm`` opens a version's ``kv/`` dir writable.
    Otherwise: the bundle's published ``kv/`` dir read-only
    (manifest-pinned) when it exists, else the ``serving_kv_spill_dir``
    flag's local read-write dir (budgeted by ``serving_kv_spill_bytes``),
    else None — no spill tier, bitwise the pre-spill behavior, which is
    also what a ``model_dir``-less engine gets (without bundle bytes
    there is no content identity to key artifacts on).
    ``kv_store=False`` disables the tier for this engine regardless."""
    if kv_store is False:
        return None
    if isinstance(kv_store, KVStore):
        return kv_store
    if kv_store is not None:
        return KVStore(str(kv_store), fingerprint)
    if model_dir is None:
        return None
    kvdir = os.path.join(str(model_dir), KV_DIRNAME)
    if os.path.isdir(kvdir):
        return KVStore(kvdir, fingerprint, readonly=True,
                       expected_digests=manifest_kv_digests(
                           str(model_dir)))
    local = get_flag("serving_kv_spill_dir")
    if local:
        return KVStore(local, fingerprint,
                       budget_bytes=int(get_flag(
                           "serving_kv_spill_bytes")))
    return None


__all__ = ["KVStore", "KV_DIRNAME", "REJECT_REASONS", "kv_fingerprint",
           "fingerprint_key", "manifest_kv_digests", "resolve_store"]
