"""Paged KV-cache arena: fixed-size blocks, per-sequence block tables.

The memory manager under continuous batching (the layer *Ragged Paged
Attention* assumes above the kernel): one pre-allocated
``[num_blocks, block_size, heads, head_dim]`` K and V buffer per layer,
carved into blocks a sequence's context occupies non-contiguously. A
sequence owns a BLOCK TABLE (ordered block ids); position ``p`` of its
context lives at flat arena slot ``table[p // block_size] * block_size +
p % block_size``. Ragged in-flight sequences thereby share ONE
fixed-shape decode executable — the block table, not the tensor shape,
carries each sequence's length.

Admission control is typed: a sequence is admitted only when enough free
blocks exist to cover its WORST-CASE length (prompt + max new tokens),
so decode can never die of allocation mid-flight; when they don't,
:class:`CacheExhausted` rejects fast and the scheduler keeps the request
queued (or the server surfaces backpressure). Blocks recycle to the free
list the moment a sequence finishes.

Beam search forks hypotheses COPY-ON-WRITE: a fork shares the parent's
blocks (refcounted), and only when a hypothesis writes into a SHARED
tail block does it draw a fresh block and copy that one block — the
parent's blocks are never touched, so sibling hypotheses share the whole
prompt prefix at the cost of at most one block copy per fork. Beam slots
are admitted with one block of COW headroom on top of the worst-case
reservation.

SHARED-PREFIX CACHING (the "same system prompt x a million users"
workload): every FULL prompt block is content-hash-chained at prefill —
``h_i = sha1(h_{i-1} || tokens of block i)`` — so a chain hash names a
whole prefix, not one block's tokens. A new request whose prompt starts
with a cached chain ATTACHES to those blocks (refcount bump, the same
sharing the COW fork machinery already protects) and prefills only its
uncached tail; at least the last prompt token always re-prefills so the
first sample has logits. Release no longer recycles registered blocks
eagerly: refcount-0 cached blocks park in an LRU pool (budget =
``serving_prefix_cache_blocks``; 0 disables retention entirely) and are
evicted — oldest first, hash unregistered before the block re-enters
the free list — when the pool overflows or admission needs the block.
Blocks a live sequence holds (refcount > 0) are never candidates.

PERSISTENT SPILL TIER (serving/generate/kvstore.py): when a
:class:`~paddle_tpu.serving.generate.kvstore.KVStore` is attached
(``attach_spill`` — a published ``<version>/kv/`` dir or the
``serving_kv_spill_dir`` flag), LRU eviction DEMOTES a registered
block's bytes to the store before recycling it, and ``attach_prefix``
on an in-arena miss RESTORES the chain's blocks from the store —
arena write + hash re-registration + refcount bump, zero prefill
dispatches, bitwise identical to a hot attach. Every store lookup is
fingerprint-checked (bundle content hash, arena geometry, kernel tier,
jax/jaxlib, backend); corruption at any depth is a typed reject and a
normal prefill, never an engine failure.

The arena arrays themselves (``self.k[l]`` / ``self.v[l]``, jax arrays)
are written by the phase ops (ops/attention_ops.py) — the engine feeds
them into the dispatch and stores the functionally-updated arrays back —
while this class owns all HOST-side accounting (free list, refcounts,
tables, reservations, the prefix-hash index) plus the device block
copies COW requires.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np

from ...core.flags import get_flag
from ...obs.metrics import REGISTRY as _METRICS, json_safe, next_instance
from ...obs.recorder import record as _flight_record

_M_BLOCKS_IN_USE = _METRICS.gauge(
    "paddle_tpu_kvcache_blocks_in_use",
    "KV arena blocks currently allocated, per cache instance",
    labels=("instance",))
_M_REJECTS = _METRICS.counter(
    "paddle_tpu_kvcache_rejects",
    "CacheExhausted rejections (admission, budget, COW overdraw), "
    "per cache instance", labels=("instance",))
_M_COW = _METRICS.counter(
    "paddle_tpu_kvcache_cow_copies",
    "copy-on-write block copies taken by beam forks, per cache instance",
    labels=("instance",))
_M_PREFIX_HITS = _METRICS.counter(
    "paddle_tpu_kvcache_prefix_hits",
    "prompt blocks attached from the shared-prefix cache instead of "
    "being re-prefilled, per cache instance", labels=("instance",))
_M_PREFIX_MISSES = _METRICS.counter(
    "paddle_tpu_kvcache_prefix_misses",
    "admissions whose prompt had cacheable full blocks beyond the "
    "matched chain (the walk stopped on an unregistered hash), per "
    "cache instance", labels=("instance",))
_M_PREFIX_EVICTIONS = _METRICS.counter(
    "paddle_tpu_kvcache_prefix_evictions",
    "cached prefix blocks evicted (LRU: pool over budget or admission "
    "pressure), per cache instance", labels=("instance",))
_M_BLOCKS_CACHED = _METRICS.gauge(
    "paddle_tpu_kvcache_blocks_cached",
    "blocks currently registered in the shared-prefix hash index "
    "(live-referenced + evictable), per cache instance",
    labels=("instance",))


class CacheExhausted(RuntimeError):
    """Not enough free KV blocks to admit (or COW-fork) a sequence: typed
    admission rejection — the scheduler keeps the request queued until
    blocks recycle; a server translates sustained exhaustion into
    queue backpressure (ServerOverloaded), never a crash."""


class PagedKVCache:
    """``PagedKVCache(num_layers, num_heads, head_dim)`` — block size and
    arena block count default from the ``serving_kv_block_size`` /
    ``serving_kv_num_blocks`` flags."""

    def __init__(self, num_layers, num_heads, head_dim, num_blocks=None,
                 block_size=None, dtype=np.float32,
                 prefix_cache_blocks=None):
        import jax.numpy as jnp

        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size if block_size is not None
                              else get_flag("serving_kv_block_size"))
        self.num_blocks = int(num_blocks if num_blocks is not None
                              else get_flag("serving_kv_num_blocks"))
        if self.block_size <= 0 or self.num_blocks <= 0:
            raise ValueError(
                f"KV arena needs positive block_size/num_blocks, got "
                f"{self.block_size}/{self.num_blocks}")
        shape = (self.num_blocks, self.block_size, self.num_heads,
                 self.head_dim)
        self.k = [jnp.zeros(shape, dtype) for _ in range(self.num_layers)]
        self.v = [jnp.zeros(shape, dtype) for _ in range(self.num_layers)]
        # free list popped from the END: initialized descending so blocks
        # allocate 0, 1, 2, ... (deterministic tests, dense arena use)
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._ref = [0] * self.num_blocks
        self._tables = {}        # seq_id -> [block ids]
        self._lens = {}          # seq_id -> tokens written
        self._promised = {}      # seq_id -> admission-time block budget
        self._promised_total = 0
        # ---- shared-prefix cache state ----
        self.prefix_cache_blocks = int(
            prefix_cache_blocks if prefix_cache_blocks is not None
            else get_flag("serving_prefix_cache_blocks"))
        self._hash_to_block = {}   # chain hash -> registered block id
        self._block_hash = {}      # registered block id -> chain hash
        # refcount-0 registered blocks, insertion order = LRU (oldest
        # first); values unused — OrderedDict for O(1) move/pop
        self._evictable = OrderedDict()
        # persistent spill tier (kvstore.KVStore) — None until the
        # engine attaches one; eviction demotes into it, attach_prefix
        # restores from it
        self._spill = None
        # arena accounting in the obs.metrics registry (stats() derives
        # its counters from these children)
        self.obs_instance = next_instance("kvcache")
        self._m_in_use = _M_BLOCKS_IN_USE.labels(instance=self.obs_instance)
        self._m_rejects = _M_REJECTS.labels(instance=self.obs_instance)
        self._m_cow = _M_COW.labels(instance=self.obs_instance)
        self._m_prefix_hits = _M_PREFIX_HITS.labels(
            instance=self.obs_instance)
        self._m_prefix_misses = _M_PREFIX_MISSES.labels(
            instance=self.obs_instance)
        self._m_prefix_evictions = _M_PREFIX_EVICTIONS.labels(
            instance=self.obs_instance)
        self._m_blocks_cached = _M_BLOCKS_CACHED.labels(
            instance=self.obs_instance)

    # ------------------------------------------------------------------
    @property
    def sentinel_slot(self):
        """One-past-the-end flat slot: scatters to it are DROPPED by the
        phase ops — the write-nothing encoding for padding positions and
        inactive decode rows."""
        return self.num_blocks * self.block_size

    def blocks_for(self, n_tokens):
        return -(-int(n_tokens) // self.block_size)

    def available_blocks(self):
        """Free blocks not yet committed to an admitted sequence's worst
        case — what :meth:`admit` has to offer a new sequence. Cached
        refcount-0 blocks count: they evict on demand when a draw needs
        them (a cache entry never blocks an admission)."""
        return (len(self._free) + len(self._evictable)
                - self._promised_unspent())

    # ------------------------------------------------------------------
    def admit(self, seq_id, max_total_len, cow_headroom=0):
        """Reserve worst-case capacity for a new sequence; raises
        :class:`CacheExhausted` (and changes nothing) when the arena
        cannot promise it. ``cow_headroom`` adds blocks for beam slots
        (a fork's copy-on-write draw happens outside table growth)."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already admitted")
        need = self.blocks_for(max_total_len) + int(cow_headroom)
        free_uncommitted = self.available_blocks()
        if need > free_uncommitted:
            self._m_rejects.inc()
            raise CacheExhausted(
                f"KV arena exhausted: sequence needs {need} blocks "
                f"(max_total_len={max_total_len}, block_size="
                f"{self.block_size}) but only {max(0, free_uncommitted)} "
                f"of {self.num_blocks} are uncommitted")
        self._tables[seq_id] = []
        self._lens[seq_id] = 0
        self._promised[seq_id] = need
        self._promised_total += need
        return need

    def _promised_unspent(self):
        # what admitted sequences may still draw: promise minus blocks
        # they currently own (refcount-owned draws, incl. COW copies)
        return sum(max(0, self._promised[s] - self._owned(s))
                   for s in self._tables)

    def _owned(self, seq_id):
        # blocks this sequence is the (co-)holder of; for promise
        # accounting the conservative count is its table length
        return len(self._tables[seq_id])

    def _draw(self, seq_id):
        if not self._free and self._evictable:
            # admission pressure: evict the least-recently-used cached
            # block (refcount 0 by construction — live blocks are never
            # in the pool) to satisfy the draw
            self._evict_lru()
        if not self._free:
            self._m_rejects.inc()
            raise CacheExhausted(
                "KV arena free list empty (copy-on-write overdraw?); "
                "admit beam sequences with cow_headroom >= 1")
        b = self._free.pop()
        self._ref[b] = 1
        self._m_in_use.set(self.num_blocks - len(self._free))
        return b

    # ------------------------------------------------------------------
    # shared-prefix cache
    # ------------------------------------------------------------------
    def _chain_hashes(self, tokens, n_blocks):
        """Content-hash chain over the first ``n_blocks`` FULL blocks of
        ``tokens``: hash i commits to every token in blocks 0..i, so a
        single hash names a whole prefix and a lookup never attaches a
        block whose left context differs."""
        hashes, h = [], b""
        for i in range(n_blocks):
            blk = tokens[i * self.block_size:(i + 1) * self.block_size]
            h = hashlib.sha1(
                h + np.asarray(blk, np.int64).tobytes()).digest()
            hashes.append(h)
        return hashes

    def _cacheable_blocks(self, tokens):
        # full prompt blocks, capped so at least the LAST prompt token
        # always re-prefills (the first sample needs its logits)
        return max(0, (len(tokens) - 1) // self.block_size)

    def attach_prefix(self, seq_id, tokens):
        """Attach the longest cached chain matching ``tokens``'s full
        prompt blocks to freshly-admitted ``seq_id`` (table must be
        empty). Returns the attached length in TOKENS — the prefill may
        skip that many prompt positions. No-op (returns 0) when the
        cache is disabled or nothing matches."""
        if self._tables[seq_id] or self._lens[seq_id]:
            raise ValueError(
                f"attach_prefix on {seq_id!r} after writes (len="
                f"{self._lens[seq_id]})")
        n = self._cacheable_blocks(tokens)
        if self.prefix_cache_blocks <= 0 or n <= 0:
            return 0
        table = self._tables[seq_id]
        matched = 0
        for h in self._chain_hashes(tokens, n):
            b = self._hash_to_block.get(h)
            if b is not None:
                if self._ref[b] == 0:
                    self._evictable.pop(b)
                self._ref[b] += 1
            else:
                # in-arena miss: try the spill tier before giving up —
                # a restored block arrives registered with refcount 1
                # (held by this attach), so the walk continues exactly
                # as if the block had never been evicted
                b = self._try_restore(h)
                if b is None:
                    self._m_prefix_misses.inc()
                    break
            table.append(b)
            matched += 1
            self._m_prefix_hits.inc()
        self._lens[seq_id] = matched * self.block_size
        self._m_in_use.set(self.num_blocks - len(self._free))
        return matched * self.block_size

    def register_prefix(self, seq_id, tokens):
        """Register ``seq_id``'s full prompt blocks in the hash index
        once the whole prompt is written (cold and attached blocks
        alike; already-registered hashes keep their existing block).
        Returns the number of newly registered blocks."""
        if self.prefix_cache_blocks <= 0:
            return 0
        n = min(self._cacheable_blocks(tokens),
                self._lens[seq_id] // self.block_size)
        table = self._tables[seq_id]
        new = 0
        for i, h in enumerate(self._chain_hashes(tokens, n)):
            if h in self._hash_to_block:
                continue
            b = table[i]
            if b in self._block_hash:
                # COW gave this sequence a private copy of a block that
                # is itself registered under an earlier chain — never
                # alias one block to two hashes
                continue
            self._hash_to_block[h] = b
            self._block_hash[b] = h
            new += 1
        if new:
            self._m_blocks_cached.set(len(self._block_hash))
        return new

    def _evict_lru(self):
        b, _ = self._evictable.popitem(last=False)
        h = self._block_hash.pop(b)
        del self._hash_to_block[h]
        if self._spill is not None and not self._spill.readonly:
            # demote instead of discard: persist the block's bytes to
            # the spill tier before the arena slot recycles (content-
            # addressed + idempotent, so re-evicting a chain already
            # spilled writes nothing)
            k_blk, v_blk = self._block_kv(b)
            self._spill.save(h, k_blk, v_blk)
        self._free.append(b)
        self._m_prefix_evictions.inc()
        self._m_blocks_cached.set(len(self._block_hash))
        # flight recorder: an eviction under admission pressure is a
        # capacity decision incident bundles reconstruct cache-thrash
        # from (the bounded ring absorbs bursts)
        _flight_record("kv_evict", component=self.obs_instance, block=b,
                       cached=len(self._block_hash))

    # ------------------------------------------------------------------
    # persistent spill tier
    # ------------------------------------------------------------------
    def attach_spill(self, store):
        """Attach a :class:`~paddle_tpu.serving.generate.kvstore.
        KVStore` (or None to detach): eviction demotes registered
        blocks into it, ``attach_prefix`` restores chains from it."""
        self._spill = store

    @property
    def spill_store(self):
        return self._spill

    def _block_kv(self, b):
        """One block's bytes across every layer, as ``[num_layers,
        block_size, heads, head_dim]`` numpy stacks (K, V) — the spill
        artifact payload."""
        k = np.stack([np.asarray(self.k[l][b])
                      for l in range(self.num_layers)])
        v = np.stack([np.asarray(self.v[l][b])
                      for l in range(self.num_layers)])
        return k, v

    def _try_restore(self, h):
        """Restore chain hash ``h``'s block from the spill tier into a
        fresh arena block: arena write (the COW ``.at[b].set`` idiom),
        hash re-registration, refcount 1 (the attaching sequence holds
        it). Returns the block id, or None (no store / miss / reject /
        no arena capacity) — the caller prefills normally. Never bumps
        the CacheExhausted reject counter: running out of room for a
        restore is not an admission failure."""
        if self._spill is None:
            return None
        if not (self._free or self._evictable):
            return None
        loaded = self._spill.load(h)
        if loaded is None:
            return None
        k_blk, v_blk = loaded
        if not self._free:
            # admission promised this sequence its prompt blocks, so
            # the draw below is within budget; the LRU eviction here
            # can itself demote to the spill tier (a swap, not a loss)
            self._evict_lru()
        if not self._free:
            return None
        b = self._free.pop()
        self._ref[b] = 1
        for l in range(self.num_layers):
            self.k[l] = self.k[l].at[b].set(k_blk[l])
            self.v[l] = self.v[l].at[b].set(v_blk[l])
        self._hash_to_block[h] = b
        self._block_hash[b] = h
        self._m_in_use.set(self.num_blocks - len(self._free))
        self._m_blocks_cached.set(len(self._block_hash))
        return b

    def spill_registered(self):
        """Force-persist EVERY registered prefix block to the spill
        tier (publish-time precompute: ``ModelRegistry.warm`` prefills
        the kv_prompts, then calls this so the chains land under
        ``<version>/kv/`` whether or not eviction ever ran). Returns
        the number of blocks now on disk; 0 with no writable store."""
        if self._spill is None or self._spill.readonly:
            return 0
        n = 0
        for b, h in self._block_hash.items():
            k_blk, v_blk = self._block_kv(b)
            if self._spill.save(h, k_blk, v_blk) is not None:
                n += 1
        return n

    # ------------------------------------------------------------------
    def append_slots(self, seq_id, n=1):
        """Flat arena slots for this sequence's next ``n`` token
        positions (int32 [n]), growing the block table as needed and
        copy-on-writing a shared tail block first. Call BEFORE the
        dispatch that writes them."""
        table = self._tables[seq_id]
        pos = self._lens[seq_id]
        if pos + n > self._promised[seq_id] * self.block_size:
            self._m_rejects.inc()
            raise CacheExhausted(
                f"sequence {seq_id!r} exceeds its admitted budget "
                f"({self._promised[seq_id]} blocks) at position {pos + n}")
        slots = np.empty(n, np.int32)
        for i in range(n):
            p = pos + i
            bi = p // self.block_size
            if bi == len(table):
                table.append(self._draw(seq_id))
            elif self._ref[table[bi]] > 1:
                table[bi] = self._cow(table[bi], seq_id)
            slots[i] = table[bi] * self.block_size + p % self.block_size
        self._lens[seq_id] = pos + n
        return slots

    def _cow(self, block, seq_id):
        """Copy-on-write: draw a fresh block, copy the shared block's
        contents across every layer's K and V arena, drop one reference
        to the shared block. The shared (parent) block's bytes are never
        modified."""
        nb = self._draw(seq_id)
        for l in range(self.num_layers):
            self.k[l] = self.k[l].at[nb].set(self.k[l][block])
            self.v[l] = self.v[l].at[nb].set(self.v[l][block])
        self._ref[block] -= 1
        self._m_cow.inc()
        return nb

    # ------------------------------------------------------------------
    def context_len(self, seq_id):
        return self._lens[seq_id]

    def block_table(self, seq_id, pad_to):
        """The sequence's block table padded with 0 to ``pad_to`` entries
        (padded entries are masked out by ContextLens in the op)."""
        t = self._tables[seq_id]
        if len(t) > pad_to:
            raise ValueError(
                f"sequence {seq_id!r} spans {len(t)} blocks > table "
                f"width {pad_to}")
        out = np.zeros(pad_to, np.int32)
        out[:len(t)] = t
        return out

    # ------------------------------------------------------------------
    def reorder(self, mapping):
        """Atomically rebind destination sequences to COPIES of source
        sequences' block tables (``{dst_seq: src_seq}``) — the beam-step
        fork. All sources are read (and their blocks ref-bumped) BEFORE
        any destination's old table is released, so a permutation (beam
        reorder by parent_idx) never frees a block another binding still
        needs. Shared blocks are copy-on-written only when a destination
        later WRITES into one."""
        new = {d: (list(self._tables[s]), self._lens[s])
               for d, s in mapping.items()}
        for d, (table, _len) in new.items():
            for b in table:
                self._ref[b] += 1
        for d in mapping:
            self._release_blocks(self._tables[d])
        for d, (table, length) in new.items():
            self._tables[d] = table
            self._lens[d] = length

    def fork(self, src_seq, dst_seq):
        """Share ``src``'s context into (already admitted) ``dst``."""
        self.reorder({dst_seq: src_seq})

    # ------------------------------------------------------------------
    def _release_blocks(self, blocks):
        parked = []
        for b in blocks:
            self._ref[b] -= 1
            if self._ref[b] == 0:
                if b in self._block_hash:
                    parked.append(b)
                else:
                    self._free.append(b)
        # registered prefix blocks park in the LRU pool instead of
        # recycling — in REVERSE table order, so within one release the
        # DEEPEST chain block is the eviction-oldest: a chain is only
        # ever trimmed from its tail (evicting a chain's head would
        # strand every deeper block unreachable while still caching it)
        for b in reversed(parked):
            self._evictable[b] = None
            self._evictable.move_to_end(b)
        while len(self._evictable) > self.prefix_cache_blocks:
            self._evict_lru()
        self._m_in_use.set(self.num_blocks - len(self._free))

    def release(self, seq_id):
        """Finish a sequence: recycle its blocks (refcounted) and return
        its reservation. Freed blocks go to the END of the free list, so
        the next allocation reuses the most-recently-freed block;
        registered prefix blocks park in the LRU cache pool instead
        (see the class docstring)."""
        self._release_blocks(self._tables.pop(seq_id))
        del self._lens[seq_id]
        self._promised_total -= self._promised.pop(seq_id)

    # ------------------------------------------------------------------
    @property
    def cow_copies(self):
        """COW copies taken so far — derived from the registry counter."""
        return int(self._m_cow.value)

    @property
    def exhausted_rejects(self):
        """CacheExhausted rejections — derived from the registry counter
        (admission, per-sequence budget, and COW-overdraw alike)."""
        return int(self._m_rejects.value)

    @property
    def prefix_hits(self):
        """Prompt blocks attached from the prefix cache — derived from
        the registry counter."""
        return int(self._m_prefix_hits.value)

    @property
    def prefix_misses(self):
        return int(self._m_prefix_misses.value)

    @property
    def prefix_evictions(self):
        return int(self._m_prefix_evictions.value)

    def stats(self):
        return json_safe({
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "blocks_in_use": self.num_blocks - len(self._free),
            "blocks_free": len(self._free),
            "blocks_promised": self._promised_total,
            "sequences": len(self._tables),
            "cow_copies": self.cow_copies,
            "exhausted_rejects": self.exhausted_rejects,
            "prefix_cache_blocks": self.prefix_cache_blocks,
            "blocks_cached": len(self._block_hash),
            "blocks_evictable": len(self._evictable),
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_evictions": self.prefix_evictions,
            "spill": None if self._spill is None else self._spill.stats(),
        })


__all__ = ["PagedKVCache", "CacheExhausted"]
