"""Generation serving: continuous batching + paged KV cache + streaming.

The stateful-decode subsystem on top of the serving stack (PRs 4-5): a
generative saved program (a decoder-only LM authored with
``fluid.layers.causal_self_attention`` sites — see
``testing/models.build_tiny_lm``) serves autoregressive token streams
with the same no-hot-path-recompiles discipline the feed-forward engine
pins, despite every in-flight sequence having a different length.

* :class:`PagedKVCache` (kvcache.py) — the paged KV arena: fixed-size
  blocks, per-sequence block tables, typed :class:`CacheExhausted`
  admission control, block recycling, copy-on-write beam forks, and
  the SHARED-PREFIX cache (content-hash-chained full prompt blocks,
  LRU retention under ``serving_prefix_cache_blocks``) that collapses
  TTFT for the same-system-prompt-times-a-million-users workload.
* :class:`GenerationEngine` (decode_engine.py) — splits the saved
  program into a per-bucket PREFILL executable, a CHUNKED-prefill
  executable family (cached-prefix tails; ``serving_prefill_chunk``
  bounded admission chunks interleaved with decode) and ONE fixed-shape
  ``[max_seqs, 1]`` DECODE executable over the arena; greedy / top-k /
  beam (the dense ``beam_search`` op) sampling host-side per sequence.
* :class:`ContinuousBatcher` (scheduler.py) — sequences join the running
  batch at any step boundary and leave at EOS/max-len; bounded wait
  queue with the typed ``ServerOverloaded`` fast-reject contract.
* :class:`GenClient` (client.py) — consumes ``ModelServer``'s streaming
  ``generate`` RPC (multi-frame responses on the framed codec), yielding
  tokens as they decode.
"""

from .kvcache import PagedKVCache, CacheExhausted
from .decode_engine import (GenerationEngine, NoFreeSlots,
                            normalize_sampling)
from .scheduler import ContinuousBatcher, TokenStream
from .client import GenClient

__all__ = ["PagedKVCache", "CacheExhausted", "GenerationEngine",
           "NoFreeSlots", "normalize_sampling", "ContinuousBatcher",
           "TokenStream", "GenClient"]
