"""ModelRegistry: a versioned store of ``save_inference_model`` bundles.

The missing link between "a model was exported somewhere in /tmp" and "a
fleet of replicas serves version N and can roll to N+1": versions live
under ``<root>/<model>/<version>/`` as plain copies of the exported
bundle, and a version becomes VISIBLE only when its ``VERSION.json``
manifest (per-file sha256 digests + a combined content hash) lands via
tmp + ``os.replace`` — the same atomic-last-write discipline the pserver
checkpoints and ``fluid.io.save_vars`` use, so a torn publish is an
invisible version, never a corrupt "latest". Versions are immutable once
published; rollback is just resolving the previous version, which is why
the fleet's ``rolling_reload`` can rescue a failed canary without any
undo machinery.

Corruption is detected at two depths: :meth:`verify` re-hashes the files
against the manifest (bit rot, torn copies), and actually LOADING a
resolved bundle reuses ``load_inference_model``'s typed ValueError
(missing/corrupt ``__model__``) — the serving engine raises it before a
bad version can swap in, which is what a rollout's canary gate catches.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time

from ..fluid.io import MODEL_FILENAME

VERSION_MANIFEST = "VERSION.json"


def _sha256_file(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _content_hash(files):
    """Combined hash over the sorted (name, digest) pairs — one value that
    pins the whole bundle's bytes."""
    h = hashlib.sha256()
    for name in sorted(files):
        h.update(f"{name}:{files[name]}\n".encode())
    return h.hexdigest()


class ModelRegistry:
    """``ModelRegistry(root)`` over a directory of
    ``<model>/<version>/`` bundles.

        reg = ModelRegistry(root)
        v = reg.publish("ranker", export_dir)        # auto-increments
        path, v = reg.resolve("ranker", "latest")    # newest published
        reg.verify("ranker", v)                      # re-hash the bytes
    """

    def __init__(self, root):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    # ------------------------------------------------------------------
    def model_dir(self, model):
        if (not model or os.sep in model or (os.altsep or "/") in model
                or model.startswith(".")):
            raise ValueError(
                f"invalid model name {model!r}: one plain path component")
        return os.path.join(self.root, model)

    def version_dir(self, model, version):
        return os.path.join(self.model_dir(model), str(int(version)))

    def models(self):
        return sorted(d for d in os.listdir(self.root)
                      if os.path.isdir(os.path.join(self.root, d)))

    def versions(self, model):
        """PUBLISHED versions (ascending) — a version dir without its
        VERSION.json (a torn publish in progress or abandoned) is
        invisible."""
        d = self.model_dir(model)
        if not os.path.isdir(d):
            return []
        out = []
        for name in os.listdir(d):
            if name.isdigit() and os.path.exists(
                    os.path.join(d, name, VERSION_MANIFEST)):
                out.append(int(name))
        return sorted(out)

    # ------------------------------------------------------------------
    def _all_version_dirs(self, model):
        """EVERY numeric version dir, published or torn — what the
        auto-increment must step over: a freezer that crashed mid-copy
        leaves a manifest-less dir, and handing its number out again
        would wedge every subsequent publish on the immutability check."""
        d = self.model_dir(model)
        if not os.path.isdir(d):
            return []
        return sorted(int(n) for n in os.listdir(d) if n.isdigit())

    def publish(self, model, src_dir, version=None, kernel_tier=None,
                model_kind="feedforward", lineage=None, warm_cache=False,
                warm_kwargs=None, kv_prompts=None, tune=False, plan=False):
        """Copy the bundle at ``src_dir`` in as ``version`` (next integer
        when None) and make it visible by writing the manifest LAST,
        atomically. Returns the published version number. Versions are
        immutable: republishing an existing one raises.

        ``kernel_tier`` is a CAPABILITY field recorded in the manifest:
        which execution tier the publisher validated this bundle with
        ("pallas"|"jnp"; default = the publisher's resolved tier, see
        ops/pallas.resolve_tier). Serving replicas surface their own
        compiled tier through ``InferenceEngine.stats()`` so a rollout
        gate can compare the two.

        ``model_kind`` declares which engine class serves the bundle:
        "feedforward" (InferenceEngine, the default — pre-upgrade
        manifests without the field resolve to it, no migration needed)
        or "generative" (GenerationEngine: stateful decode over the
        bundle's causal_self_attention sites). ModelServer reads it from
        the version dir's VERSION.json and picks the engine class;
        :meth:`model_kind` surfaces it alongside :meth:`resolve`.

        ``lineage`` is an optional dict of provenance the publisher wants
        recorded in the manifest (the online freezer stamps
        ``global_step``/``parent_version``/``freeze_round``); every
        manifest additionally records ``published_at`` (wall-clock), the
        timestamp the rollout controller computes publish-to-served lag
        from. Lineage is metadata only — resolution and verification
        never read it.

        ``warm_cache=True`` runs :meth:`warm` on the just-published
        version (``warm_kwargs`` forwarded): the publisher pays each
        executable's compile ONCE and every replica that serves this
        version loads instead of compiling. The manifest lands FIRST —
        a crash mid-warm leaves a fully published version whose
        replicas simply compile.

        ``kv_prompts`` (generative bundles) additionally runs each
        prompt's prefill ONCE at publish time and stores the resulting
        KV-prefix chains under ``<version>/kv/`` (see
        serving/generate/kvstore.py): replicas that serve this version
        attach those prefixes with ZERO prefill steps. Passing it
        implies a warm pass even without ``warm_cache=True``.

        ``tune=True`` (or a dict of Tuner options, e.g.
        ``{"repeats": 3, "inner": 2}``) additionally runs the kernel
        autotuner at publish time against the engine's REAL warmup
        shapes and ships the winning-variant table under
        ``<version>/tune/`` (ops/autotune.py), manifest-pinned like
        ``warm_files`` — replicas that serve this version route tunable
        kernels by measurement with zero in-band tuning work. Implies a
        warm pass.

        ``plan=True`` additionally runs the auto-parallelism placement
        planner (parallel/planner.py) at publish time and ships the
        searched PlacementReport under ``<version>/plan/``,
        manifest-pinned as ``plan_files`` — replicas that serve this
        version resolve their mesh from the certified artifact
        (``parallel.planner.resolve_store``) without re-searching.
        Implies a warm pass."""
        if not os.path.exists(os.path.join(src_dir, MODEL_FILENAME)):
            raise ValueError(
                f"publish: {src_dir!r} is not a save_inference_model "
                f"bundle (no {MODEL_FILENAME!r} file)")
        # validate BEFORE any filesystem mutation: a raise below the
        # makedirs would leave a torn manifest-less version dir that
        # permanently blocks this version number (immutability check)
        if kernel_tier is None:
            from ..ops.pallas import resolve_tier
            kernel_tier = resolve_tier()
        elif kernel_tier not in ("pallas", "jnp"):
            raise ValueError(
                f"kernel_tier capability must be 'pallas' or 'jnp', "
                f"got {kernel_tier!r}")
        if model_kind not in ("feedforward", "generative"):
            raise ValueError(
                f"model_kind must be 'feedforward' or 'generative', "
                f"got {model_kind!r}")
        if lineage is not None and not isinstance(lineage, dict):
            raise ValueError(
                f"lineage must be a dict of provenance fields, "
                f"got {type(lineage).__name__}")
        auto = version is None
        if not auto:
            version = int(version)
            if version <= 0:
                raise ValueError(f"version must be a positive int, "
                                 f"got {version}")
        # the makedirs IS the claim on the version number: concurrent
        # publishers (a freezer worker racing an operator publish) both
        # computing the same auto-increment cannot both create the dir,
        # so the loser re-derives the next number instead of failing —
        # only an EXPLICIT version collides into the immutability error
        for _attempt in range(64):
            if auto:
                # next number past EVERY existing dir, torn ones included
                # — a crash mid-publish must not permanently wedge
                # auto-increment on its abandoned manifest-less dir
                all_dirs = self._all_version_dirs(model)
                version = all_dirs[-1] + 1 if all_dirs else 1
            dst = self.version_dir(model, version)
            try:
                os.makedirs(dst)
                break
            except FileExistsError:
                if not auto:
                    raise ValueError(
                        f"version {version} of model {model!r} already "
                        "exists (published versions are immutable; "
                        "publish a new one)") from None
        else:
            raise RuntimeError(
                f"publish: could not claim a version number for "
                f"{model!r} after 64 attempts (pathological publish "
                "contention)")
        files = {}
        for name in sorted(os.listdir(src_dir)):
            src = os.path.join(src_dir, name)
            if not os.path.isfile(src) or name == VERSION_MANIFEST \
                    or name.endswith(".tmp"):
                continue
            shutil.copyfile(src, os.path.join(dst, name))
            # hash the DESTINATION bytes: the manifest certifies what the
            # registry holds, not what the source held mid-copy
            files[name] = _sha256_file(os.path.join(dst, name))
        manifest = {"model": model, "version": version, "files": files,
                    "content_hash": _content_hash(files),
                    "kernel_tier": kernel_tier,
                    "model_kind": model_kind,
                    "published_at": time.time()}
        if lineage:
            manifest["lineage"] = dict(lineage)
        tmp = os.path.join(dst, VERSION_MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(dst, VERSION_MANIFEST))
        if warm_cache or kv_prompts or tune or plan:
            wk = dict(warm_kwargs or {})
            if kv_prompts is not None:
                wk.setdefault("kv_prompts", kv_prompts)
            if tune:
                wk.setdefault("tune", tune)
            if plan:
                wk.setdefault("plan", plan)
            self.warm(model, version, **wk)
        return version

    # ------------------------------------------------------------------
    def warm(self, model, version="latest", buckets=None, sample_feed=None,
             gen_opts=None, kv_prompts=None, tune=False, plan=False):
        """Build (or complete) the version's persistent compiled-
        executable artifacts under ``<version>/warm/`` so replicas LOAD
        instead of compile (serving/execcache.py): an engine of the
        manifest's ``model_kind`` is constructed on the version dir with
        a WRITABLE cache and warmed — artifacts that already exist and
        fingerprint-match are loaded (so re-warming is idempotent:
        nothing recompiles, nothing is rewritten), the rest are compiled
        once here and persisted. The manifest then lists every artifact
        under ``warm_files`` with a per-file sha256, exactly like the
        bundle files — :meth:`verify` re-hashes them, :meth:`gc` deletes
        them with the version. The bundle files themselves (and
        ``content_hash``, which KEYS the artifacts) stay immutable; the
        warm dir is an additive sidecar.

        ``buckets``/``sample_feed`` configure a feed-forward warmup;
        ``gen_opts`` are GenerationEngine kwargs for generative bundles
        — they must match what serving replicas use (both default from
        the same flags), or the replica's differently-shaped feeds
        simply miss the cache and compile. The warm dir holds exactly
        the LAST warm run's artifact set: artifacts a previous
        toolchain/flag configuration produced fingerprint-miss forever,
        so they are pruned instead of re-certified into the manifest
        (``warm/`` and ``VERSION.json`` must not grow monotonically
        with every jax upgrade). Returns the sorted artifact relpaths
        recorded in the manifest.

        ``kv_prompts`` (generative bundles only) runs each prompt's
        prefill once HERE and persists the resulting KV-prefix chains
        under ``<version>/kv/`` (serving/generate/kvstore.py), listed
        in the manifest as ``kv_files`` with per-file sha256 — same
        contract as ``warm_files``: :meth:`verify` re-hashes them,
        :meth:`gc` deletes them with the version, and the serving
        engine pins loads to these digests before deserializing
        anything. Re-warming with the same prompts is idempotent
        (every chain loads from its existing artifact with zero
        prefill steps; nothing is rewritten). When ``kv_prompts`` is
        None an existing ``kv/`` dir is left untouched — warm-cache
        refreshes must not prune KV artifacts they didn't rebuild.

        ``tune=True`` (or a Tuner-option dict: ``repeats``/``inner``)
        runs the kernel autotuner FIRST: a throwaway engine (no exec
        cache) is warmed under ``ops.autotune.capture`` to learn the
        real dispatch keys, the tuner measures each key's registered
        variants, and the winning table lands under ``<version>/tune/``
        with ``tune_files`` certified into the manifest BEFORE the warm
        engine is built — so the warm pass attaches the manifest-pinned
        table and every persisted executable's fingerprint already
        carries the table digest (a replica loading warm/ under the
        same table hits; one without the table recompiles instead of
        loading mismatched routing). When ``tune`` is falsy an existing
        ``tune/`` dir is left untouched, like ``kv/``.

        ``plan=True`` runs the publish-time placement search
        (parallel/planner.py): the bundle is loaded into a throwaway
        scope, the planner enumerates and cost-models the legal meshes
        for THIS host's device count, and the ranked PlacementReport
        lands under ``<version>/plan/`` with ``plan_files`` certified
        into the manifest — replicas resolve the certified plan
        (``parallel.planner.resolve_store``) and place without
        re-searching. Re-warming is idempotent (the fingerprint-matching
        artifact is a cache hit, nothing is rewritten); a plan pass that
        fails (e.g. a bundle whose feeds the planner cannot synthesize)
        records a flight event and certifies nothing — plans are an
        additive sidecar, never a publish failure. When ``plan`` is
        falsy an existing ``plan/`` dir is left untouched."""
        path, v = self.resolve(model, version)
        m = self.manifest(model, v)
        from .execcache import ARTIFACT_SUFFIX, ExecCache, WARM_DIRNAME
        from .generate import kvstore as _kvs
        if tune:
            tune_files = self._tune(path, m, buckets=buckets,
                                    sample_feed=sample_feed,
                                    gen_opts=gen_opts,
                                    tune_opts=tune if isinstance(tune, dict)
                                    else None)
            if m.get("tune_files") != tune_files:
                m["tune_files"] = tune_files
                tmp = os.path.join(path, VERSION_MANIFEST + ".tmp")
                with open(tmp, "w") as f:
                    json.dump(m, f, indent=1, sort_keys=True)
                os.replace(tmp, os.path.join(path, VERSION_MANIFEST))
        if plan:
            plan_files = self._plan(path, m)
            if m.get("plan_files") != plan_files:
                m["plan_files"] = plan_files
                tmp = os.path.join(path, VERSION_MANIFEST + ".tmp")
                with open(tmp, "w") as f:
                    json.dump(m, f, indent=1, sort_keys=True)
                os.replace(tmp, os.path.join(path, VERSION_MANIFEST))
        warm_dir = os.path.join(path, WARM_DIRNAME)
        cache = ExecCache(warm_dir)
        kv_files = None
        if m.get("model_kind", "feedforward") == "generative":
            from .generate import GenerationEngine
            gopts = dict(gen_opts or {})
            if kv_prompts:
                # the prefix cache must be ON so prefilled chains
                # register (prefix_cache_blocks is a retention cap, not
                # an allocation), and the engine's KV store must point
                # at the version's kv/ dir, WRITABLE — resolve_store
                # gives an explicit path write access; replicas that
                # later resolve the same dir implicitly get it
                # read-only and manifest-pinned
                gopts.setdefault("prefix_cache_blocks", 4096)
                gopts.setdefault("kv_store",
                                 os.path.join(path, _kvs.KV_DIRNAME))
            engine = GenerationEngine(path, exec_cache=cache, **gopts)
            engine.warmup()
            if kv_prompts:
                kv_files = self._precompute_kv(engine, path, kv_prompts)
        else:
            from .engine import InferenceEngine
            if kv_prompts:
                raise ValueError(
                    "kv_prompts requires a generative bundle; "
                    f"{model!r}/{v} is feedforward")
            engine = InferenceEngine(path, buckets=buckets,
                                     exec_cache=cache)
            engine.warmup(sample_feed)
        touched = set(cache.touched())
        warm_files = {}
        for name in sorted(os.listdir(warm_dir)):
            fpath = os.path.join(warm_dir, name)
            if not os.path.isfile(fpath) or name.endswith(".tmp"):
                continue
            if name in touched:
                warm_files[f"{WARM_DIRNAME}/{name}"] = _sha256_file(fpath)
            elif name.endswith(ARTIFACT_SUFFIX):
                # stale artifact this warmup neither loaded nor wrote:
                # its fingerprint can never match again — prune it
                # (stray non-artifact files are left alone, unlisted)
                try:
                    os.unlink(fpath)
                except OSError:
                    pass
        changed = m.get("warm_files") != warm_files
        m["warm_files"] = warm_files
        if kv_files is not None:
            changed = changed or m.get("kv_files") != kv_files
            m["kv_files"] = kv_files
        if changed:
            tmp = os.path.join(path, VERSION_MANIFEST + ".tmp")
            with open(tmp, "w") as f:
                json.dump(m, f, indent=1, sort_keys=True)
            os.replace(tmp, os.path.join(path, VERSION_MANIFEST))
        return sorted(warm_files) + sorted(kv_files or {}) \
            + sorted(m.get("tune_files", {}) if tune else {}) \
            + sorted(m.get("plan_files", {}) if plan else {})

    def _tune(self, path, m, buckets=None, sample_feed=None, gen_opts=None,
              tune_opts=None):
        """Run the publish-time autotune pass: capture the real warmup's
        dispatch keys on a THROWAWAY engine (no exec cache — loading
        warm artifacts would skip the traced dispatches whose keys this
        pass exists to learn), measure each captured key's registered
        variants, and persist the winning table under ``tune/``. Keys an
        existing valid table already covers are NOT re-measured (re-
        warming is idempotent: same table bytes, same digest, nothing
        downstream recompiles). Returns the ``tune_files`` digest map."""
        from ..ops import autotune as _at
        if m.get("model_kind", "feedforward") == "generative":
            from .generate import GenerationEngine
            engine = GenerationEngine(path, exec_cache=False,
                                      **dict(gen_opts or {}))
            with _at.capture() as keys:
                engine.warmup()
        else:
            from .engine import InferenceEngine
            engine = InferenceEngine(path, buckets=buckets,
                                     exec_cache=False)
            with _at.capture() as keys:
                engine.warmup(sample_feed)
        store = _at.TuneStore(os.path.join(path, _at.TUNE_DIRNAME))
        existing = store.load()
        missing = keys if existing is None else \
            [c for c in keys
             if (c[0], _at.key_str(c[1])) not in existing.entries]
        table = existing
        if missing or existing is None:
            tuner = _at.Tuner(**(tune_opts or {}))
            table = tuner.tune(missing, table=existing)
        store.save(table)
        touched = set(store.touched())
        tune_dir = os.path.join(path, _at.TUNE_DIRNAME)
        tune_files = {}
        for name in sorted(os.listdir(tune_dir)):
            fpath = os.path.join(tune_dir, name)
            if not os.path.isfile(fpath) or name.endswith(".tmp"):
                continue
            if name in touched:
                tune_files[f"{_at.TUNE_DIRNAME}/{name}"] = \
                    _sha256_file(fpath)
            elif name.endswith(_at.ARTIFACT_SUFFIX):
                # a table another toolchain/backend measured: its
                # filename fingerprint can never match here — prune
                try:
                    os.unlink(fpath)
                except OSError:
                    pass
        return tune_files

    def _plan(self, path, m):
        """Run the publish-time placement search: load the bundle into a
        throwaway scope, synthesize a template feed at one row per local
        device (so every data-parallel degree divides), and let
        ``parallel.planner.plan`` search + persist into ``<version>/
        plan/``. A fingerprint-matching existing artifact is a cache hit
        (re-warming is idempotent: same bytes, same digest). The search
        failing — a bundle whose free dims ``template_feed`` cannot
        synthesize, a program the lowering rejects — records a flight
        event and certifies nothing: plans are an additive sidecar.
        Returns the ``plan_files`` digest map."""
        import jax

        import paddle_tpu.fluid as fluid
        from ..core.scope import Scope
        from ..obs import perf as _perf
        from ..parallel import planner as _pl
        plan_dir = os.path.join(path, _pl.PLAN_DIRNAME)
        store = _pl.PlanStore(plan_dir)
        try:
            scope = Scope()
            exe = fluid.Executor()
            program, feed_names, fetch_vars = fluid.io.load_inference_model(
                path, exe, scope=scope)
            feed = _perf.template_feed(program, feed_names,
                                       batch=max(jax.device_count(), 1))
            _pl.plan(program, feed_example=feed, fetch_list=fetch_vars,
                     executor=exe, scope=scope, store=store)
        except Exception as e:
            from ..obs.recorder import record
            record("plan_publish_failed", component="serving.registry",
                   model=m.get("model"), version=m.get("version"),
                   error=f"{type(e).__name__}: {e}")
        plan_files = {}
        touched = set(store.touched())
        for name in sorted(os.listdir(plan_dir)):
            fpath = os.path.join(plan_dir, name)
            if not os.path.isfile(fpath) or name.endswith(".tmp"):
                continue
            if name in touched:
                plan_files[f"{_pl.PLAN_DIRNAME}/{name}"] = \
                    _sha256_file(fpath)
            elif name.endswith(_pl.ARTIFACT_SUFFIX):
                # a plan another toolchain/device-count searched: its
                # filename fingerprint can never match here — prune
                try:
                    os.unlink(fpath)
                except OSError:
                    pass
        return plan_files

    def _precompute_kv(self, engine, path, kv_prompts):
        """Prefill each prompt on the warm engine (chains that already
        have artifacts restore with zero prefill steps), force-spill
        every registered block, then certify exactly the artifacts this
        run touched — stale ``.jkv`` files (earlier prompt sets, older
        toolchains: their filenames embed the fingerprint key, so a
        geometry/toolchain flip strands them forever) are pruned."""
        from .generate import kvstore as _kvs
        for p in kv_prompts:
            toks = [int(t) for t in p]
            handle, _, finished = engine.start(toks, 1, {"mode": "greedy"})
            # chunked admission parks the prompt on the prefill queue;
            # step until the chain is prefilled + registered
            for _ in range(len(toks) + 16):
                if handle.finished or not handle.prefilling:
                    break
                engine.step()
            if not handle.finished:
                engine.abort(handle)
        engine.cache.spill_registered()
        store = engine.cache.spill_store
        touched = set(store.touched()) if store is not None else set()
        kv_dir = os.path.join(path, _kvs.KV_DIRNAME)
        kv_files = {}
        if os.path.isdir(kv_dir):
            for name in sorted(os.listdir(kv_dir)):
                fpath = os.path.join(kv_dir, name)
                if not os.path.isfile(fpath) or name.endswith(".tmp"):
                    continue
                if name in touched:
                    kv_files[f"{_kvs.KV_DIRNAME}/{name}"] = \
                        _sha256_file(fpath)
                elif name.endswith(_kvs.ARTIFACT_SUFFIX):
                    try:
                        os.unlink(fpath)
                    except OSError:
                        pass
        return kv_files

    # ------------------------------------------------------------------
    def resolve(self, model, version="latest"):
        """-> ``(bundle_path, version_int)``. ``"latest"`` (or None) picks
        the newest published version. Unknown models/versions raise a
        ValueError naming what IS available."""
        published = self.versions(model)
        if not published:
            raise ValueError(
                f"model {model!r} has no published versions in registry "
                f"{self.root!r} (known models: {self.models()})")
        if version in (None, "latest"):
            v = published[-1]
        else:
            v = int(version)
            if v not in published:
                raise ValueError(
                    f"model {model!r} has no published version {v}; "
                    f"published: {published}")
        return self.version_dir(model, v), v

    def model_kind(self, model, version="latest"):
        """The resolved version's engine-class declaration; manifests
        published before the field existed default to "feedforward"."""
        return self.manifest(model, version).get("model_kind",
                                                 "feedforward")

    def previous(self, model, version):
        """The newest published version strictly older than ``version``
        (what a failed canary rolls back to), or None."""
        older = [v for v in self.versions(model) if v < int(version)]
        return older[-1] if older else None

    def manifest(self, model, version):
        path, v = self.resolve(model, version)
        mpath = os.path.join(path, VERSION_MANIFEST)
        try:
            with open(mpath) as f:
                return json.load(f)
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise ValueError(
                f"registry version {model!r}/{v} holds a corrupt "
                f"{VERSION_MANIFEST!r} ({type(e).__name__}: {e}); "
                "republish the version") from e

    def gc(self, model, keep_latest=2, pinned=(), torn_ttl_s=3600.0):
        """Retention: delete old published version dirs, keeping the
        newest ``keep_latest`` versions and NEVER deleting

        * the latest published version (what ``resolve("latest")`` and a
          crash-restarting replica load),
        * its :meth:`previous` (the rollback target a failed canary
          needs), or
        * any version in ``pinned`` (the caller's currently-served /
          must-keep set — the registry cannot know what a fleet is
          serving, so the rollout controller passes it).

        Deletion is manifest-first: the VERSION.json is unlinked before
        the dir is removed, so a crash mid-gc leaves a TORN (invisible)
        version, never a corrupt resolvable one. Returns the sorted list
        of deleted version numbers. Typed ValueErrors on bad args;
        pinned versions that no longer exist are ignored (gc must be
        idempotent across restarts).

        Torn (manifest-less) dirs — abandoned by a publisher that
        crashed mid-copy — are swept too once older than ``torn_ttl_s``
        seconds (dir mtime): they hold full-size bundle copies no other
        API can reach, and without the sweep repeated publisher crashes
        grow the registry without bound. The TTL protects an IN-FLIGHT
        publish (a fresh manifest-less dir is a publish in progress,
        not garbage); 0 sweeps every torn dir immediately — only safe
        when no publisher can be running concurrently."""
        try:
            keep_latest = int(keep_latest)
        except (TypeError, ValueError):
            raise ValueError(
                f"keep_latest must be a positive int, "
                f"got {keep_latest!r}") from None
        if keep_latest < 1:
            raise ValueError(
                f"keep_latest must be >= 1 (the latest version is never "
                f"deleted), got {keep_latest}")
        try:
            pinned = {int(v) for v in pinned}
        except (TypeError, ValueError):
            raise ValueError(
                f"pinned must be an iterable of version ints, "
                f"got {pinned!r}") from None
        try:
            torn_ttl_s = float(torn_ttl_s)
        except (TypeError, ValueError):
            raise ValueError(
                f"torn_ttl_s must be a non-negative number of seconds, "
                f"got {torn_ttl_s!r}") from None
        if torn_ttl_s < 0:
            raise ValueError(
                f"torn_ttl_s must be >= 0, got {torn_ttl_s}")
        published = self.versions(model)
        deleted = self._sweep_torn(model, set(published), torn_ttl_s)
        if not published:
            return sorted(deleted)
        latest = published[-1]
        protected = set(published[-keep_latest:]) | {latest} | pinned
        prev = self.previous(model, latest)
        if prev is not None:
            protected.add(prev)
        for v in published:
            if v in protected:
                continue
            vdir = self.version_dir(model, v)
            try:
                os.unlink(os.path.join(vdir, VERSION_MANIFEST))
            except FileNotFoundError:
                pass      # already torn: finish removing the remains
            shutil.rmtree(vdir, ignore_errors=True)
            deleted.append(v)
        return sorted(deleted)

    def _sweep_torn(self, model, published, ttl_s):
        """Delete manifest-less version dirs older than ``ttl_s`` —
        abandoned publishes only; a fresh torn dir is an in-flight
        publish and must survive. Returns the swept version numbers."""
        cutoff = time.time() - ttl_s
        swept = []
        for v in self._all_version_dirs(model):
            if v in published:
                continue
            vdir = self.version_dir(model, v)
            try:
                if os.path.getmtime(vdir) > cutoff:
                    continue
            except OSError:
                continue       # raced a concurrent delete
            shutil.rmtree(vdir, ignore_errors=True)
            swept.append(v)
        return swept

    def verify(self, model, version):
        """Re-hash the stored files against the manifest; raises ValueError
        on a torn (file missing) or corrupt (digest mismatch) version.
        Returns the manifest. Note the deeper check — whether the bundle
        actually LOADS — is ``load_inference_model``'s typed ValueError,
        raised by the engine when a resolved version is served."""
        path, v = self.resolve(model, version)
        m = self.manifest(model, v)
        # warm_files are covered by the same re-hash: a tampered
        # compiled-executable artifact fails verify() exactly like a
        # tampered bundle file. The serving engine independently pins
        # loads to these SAME manifest digests (execcache checks the
        # raw bytes against warm_files BEFORE unpickling anything) —
        # verify is the operator's offline check, the engine's
        # manifest-pinned reject is the runtime safety net.
        listed = dict(m.get("files", {}))
        listed.update(m.get("warm_files", {}))
        # kv_files (publish-time KV-prefix artifacts, kv/) re-hash the
        # same way: verify is the offline check, the engine's
        # manifest-pinned load reject is the runtime one
        listed.update(m.get("kv_files", {}))
        # tune_files (publish-time kernel-tuning tables, tune/) too:
        # ops.autotune.TuneStore pins loads to these digests at runtime
        listed.update(m.get("tune_files", {}))
        # plan_files (publish-time placement plans, plan/) the same:
        # parallel.planner.PlanStore pins loads to these digests
        listed.update(m.get("plan_files", {}))
        for name, want in listed.items():
            fpath = os.path.join(path, name)
            if not os.path.exists(fpath):
                raise ValueError(
                    f"registry version {model!r}/{v} is torn: manifest "
                    f"lists {name!r} but {fpath!r} is missing")
            got = _sha256_file(fpath)
            if got != want:
                raise ValueError(
                    f"registry version {model!r}/{v} is corrupt: "
                    f"{name!r} hashes {got[:12]}… but the manifest "
                    f"records {want[:12]}…")
        if _content_hash(m.get("files", {})) != m.get("content_hash"):
            raise ValueError(
                f"registry version {model!r}/{v} is corrupt: content "
                "hash does not match the manifest's file digests")
        return m


__all__ = ["ModelRegistry", "VERSION_MANIFEST"]
